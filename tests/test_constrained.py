"""Grammar-constrained tool-decision decoding (agent/constrained.py).

The few-shot call formats in prompts/tool_prompt.txt are acceptance cases
(SURVEY §7.3 hard part #5: they become test cases), and an end-to-end run
through the scheduler must ALWAYS yield parsable output even from a
random-weight model — the whole point of constraining.
"""

import asyncio

import jax
import numpy as np
import pytest

from finchat_tpu.agent.constrained import (
    DEAD,
    GrammarVocab,
    TokenConstraint,
    build_tool_grammar,
)
from finchat_tpu.agent.toolcall import parse_tool_decision
from finchat_tpu.models.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def dfa():
    return build_tool_grammar()


def accepts(dfa, text: str) -> bool:
    state = dfa.step_string(dfa.start, text)
    return state != DEAD and dfa.eos_ok[state]


def is_live_prefix(dfa, text: str) -> bool:
    return dfa.step_string(dfa.start, text) != DEAD


@pytest.mark.parametrize(
    "text",
    [
        "No tool call",
        'retrieve_transactions({"search_query": "grocery store purchases", "num_transactions": 20})',
        'retrieve_transactions({"search_query": "all purchases", "time_period_days": 2})',
        "retrieve_transactions({})",
        'retrieve_transactions({"num_transactions": 100})',
        'retrieve_transactions({ "search_query" : "coffee" , "num_transactions" : 5 })',
        '  No tool call',  # leading whitespace tolerated
    ],
)
def test_grammar_accepts_valid_outputs(dfa, text):
    assert accepts(dfa, text)


@pytest.mark.parametrize(
    "text",
    [
        "Hello! I'm here to help",  # prose
        "no tool call",  # wrong case is not the literal contract
        "retrieve_transactions(",  # incomplete: not accepting (but live)
        'retrieve_transactions({"user_id": "u1"})',  # user_id is NOT grammatical
        'retrieve_transactions({"search_query": 5})',  # wrong value type
        'retrieve_transactions({"num_transactions": "many"})',
        "retrieve_transactions({}) extra",  # trailing junk
        'make_coffee({})',  # unknown tool
    ],
)
def test_grammar_rejects_invalid_outputs(dfa, text):
    assert not accepts(dfa, text)


def test_incomplete_prefixes_stay_live(dfa):
    for prefix in ["No to", "retrieve_trans", 'retrieve_transactions({"sea', 'retrieve_transactions({"num_transactions": 1']:
        assert is_live_prefix(dfa, prefix)


def test_every_accepted_output_parses():
    """Grammar ⊆ parser: anything the DFA accepts must produce a well-formed
    decision in toolcall.parse_tool_decision."""
    samples = [
        "No tool call",
        'retrieve_transactions({"search_query": "rent payments", "num_transactions": 3})',
        'retrieve_transactions({"time_period_days": 30})',
        "retrieve_transactions({})",
    ]
    dfa = build_tool_grammar()
    for text in samples:
        assert accepts(dfa, text)
        if text == "No tool call":
            assert parse_tool_decision(text) is None
        else:
            call = parse_tool_decision(text)
            assert call is not None and call.name == "retrieve_transactions"
            assert "user_id" not in call.args


def test_start_mask_byte_vocab():
    tok = ByteTokenizer()
    vocab = GrammarVocab.for_tokenizer(tok)
    allowed, eos_ok, _ = vocab.mask(vocab.dfa.start)
    assert not eos_ok  # empty output is not grammatical
    assert allowed[ord("N")] and allowed[ord("r")] and allowed[ord(" ")]
    assert not allowed[ord("H")] and not allowed[ord("{")]
    # specials carry no text and are never allowed
    assert not allowed[tok.pad_id] and not allowed[tok.bos_id]


def test_constrained_pick_greedy_forces_grammar():
    """Even with adversarial logits (all mass on junk), picks stay in-grammar
    and terminate; the result always parses."""
    tok = ByteTokenizer()
    vocab = GrammarVocab.for_tokenizer(tok)
    c = TokenConstraint(vocab)
    rng = np.random.default_rng(0)
    logits = np.zeros((tok.vocab_size,), np.float32)
    logits[ord("H")] = 100.0  # the model "wants" to say Hello
    out = []
    for _ in range(128):
        t = c.pick(logits, 0.0, rng)
        if t == tok.eos_id:
            break
        out.append(t)
    text = tok.decode(out)
    dfa = build_tool_grammar()
    assert accepts(dfa, text), text


def test_constrained_sampling_terminates_and_parses():
    """Stochastic picks (temperature 1) across many seeds: always grammatical."""
    tok = ByteTokenizer()
    vocab = GrammarVocab.for_tokenizer(tok)
    dfa = vocab.dfa
    budget = 96  # tool_sampling's max_new_tokens: closing mode must land it
    for seed in range(5):
        rng = np.random.default_rng(seed)
        c = TokenConstraint(vocab)
        logits = np.asarray(rng.normal(size=(tok.vocab_size,)) * 3, np.float32)
        out = []
        for step in range(budget):
            t = c.pick(logits, 1.0, rng, remaining=budget - step)
            if t == tok.eos_id:
                break
            out.append(t)
        else:
            pytest.fail("did not terminate within budget")
        text = tok.decode(out)
        assert accepts(dfa, text), text
        parse_tool_decision(text)  # must not raise


async def _run_constrained_engine():
    from finchat_tpu.engine.engine import InferenceEngine
    from finchat_tpu.engine.generator import EngineGenerator
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.llama import PRESETS, init_params
    from finchat_tpu.utils.config import EngineConfig

    tok = ByteTokenizer()
    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=64, max_seq_len=256, prefill_chunk=16
    )
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, engine_cfg)
    scheduler = ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)
    gen = EngineGenerator(scheduler, tok)
    await scheduler.start()
    try:
        text = await gen.generate(
            "User: What did I spend on coffee?",
            SamplingParams(temperature=0.7, max_new_tokens=96, grammar="tool_call"),
        )
    finally:
        await scheduler.stop()
    return text


def test_engine_constrained_generation_end_to_end():
    """A RANDOM-weight model through the real scheduler produces grammatical,
    parsable tool decisions — structure comes from the constraint alone."""
    text = asyncio.run(_run_constrained_engine())
    dfa = build_tool_grammar()
    state = dfa.step_string(dfa.start, text)
    # either completed (accepting) or hit the token budget mid-grammar (live)
    assert state != DEAD, text
    parse_tool_decision(text)  # never raises


def test_token_texts_sentencepiece_style():
    """decode([i]) strips the SentencePiece leading-space marker; token_texts
    must recover the real emitted text ('▁No' -> ' No') or the DFA diverges
    from the stream."""
    from finchat_tpu.agent.constrained import token_texts

    class FakeSPInner:
        all_special_ids = [0]

        def convert_ids_to_tokens(self, ids):
            table = {0: "<s>", 1: "▁No", 2: "▁tool", 3: "call", 4: "<0x7B>", 5: "to"}
            return [table[i] for i in ids]

    class FakeSPTokenizer:
        vocab_size = 6
        eos_id = 0
        _tok = FakeSPInner()

        def decode(self, ids):
            # single-token decode strips the marker — the trap
            return "".join(
                {0: "", 1: "No", 2: "tool", 3: "call", 4: "{", 5: "to"}[i] for i in ids
            )

    texts = token_texts(FakeSPTokenizer())
    assert texts == ["", " No", " tool", "call", "{", "to"]


def test_grammar_vocab_multitoken_literal_with_sp_texts():
    """With correct per-token texts, a multi-token path through the literal
    'No tool call' stays live and lands accepting."""
    from finchat_tpu.agent.constrained import GrammarVocab, build_tool_grammar

    vocab = GrammarVocab(build_tool_grammar(), ["", "No", " tool", " call", "xx"], eos_id=0)
    allowed, _, _ = vocab.mask(vocab.dfa.start)
    assert allowed[1] and not allowed[4] and not allowed[0]
    s = vocab.advance(vocab.dfa.start, 1)  # "No"
    allowed, _, _ = vocab.mask(s)
    assert allowed[2]  # " tool"
    s = vocab.advance(s, 2)
    s = vocab.advance(s, 3)  # " call"
    assert vocab.dfa.eos_ok[s]


def test_string_values_exclude_parser_breaking_chars():
    """Grammar ⊆ parser: '}' and ')' cannot appear inside string values
    (they would truncate toolcall.py's non-greedy extraction regex)."""
    dfa = build_tool_grammar()
    bad = 'retrieve_transactions({"search_query": "food} 2024"})'
    prefix = bad[: bad.index("}") + 1]  # up to and including the in-string '}'
    assert not is_live_prefix(dfa, prefix)


@pytest.mark.parametrize(
    "text",
    [
        'create_financial_plot({"chart_type": "bar", "title": "Spending This Month", "search_query": "all purchases", "time_period_days": 30})',
        'create_financial_plot({"chart_type": "pie"})',
        "create_financial_plot({})",
    ],
)
def test_grammar_accepts_plot_calls(dfa, text):
    assert accepts(dfa, text)


@pytest.mark.parametrize(
    "text",
    [
        'create_financial_plot({"chart_type": "donut"})',  # not in the enum
        'create_financial_plot({"chart_type": bar})',  # unquoted enum
    ],
)
def test_grammar_rejects_bad_plot_calls(dfa, text):
    assert not accepts(dfa, text)


def test_plot_call_parses_with_validation():
    call = parse_tool_decision(
        'create_financial_plot({"chart_type": "pie", "title": "Food", "num_transactions": 50})'
    )
    assert call is not None and call.name == "create_financial_plot"
    assert call.args["chart_type"] == "pie" and call.args["title"] == "Food"
    assert call.args["num_transactions"] == 50
    # bad chart type degrades to the default, never an error
    call = parse_tool_decision('create_financial_plot({"chart_type": "donut"})')
    assert call.args["chart_type"] == "bar"
