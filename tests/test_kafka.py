"""In-memory broker semantics: ordering by key, groups, QoS, faults."""

import json

from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient
from finchat_tpu.utils.config import AI_RESPONSE_TOPIC, USER_MESSAGE_TOPIC, KafkaConfig


def _client(broker):
    return KafkaClient(KafkaConfig(backend="memory"), broker=broker)


def test_produce_consume_roundtrip():
    broker = InMemoryBroker()
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([USER_MESSAGE_TOPIC])
    # offset_reset=latest: records produced AFTER joining are visible
    producer.produce_message(USER_MESSAGE_TOPIC, "conv-1", {"message": "hi", "conversation_id": "conv-1"})
    msg = consumer.poll_message()
    assert msg is not None
    assert json.loads(msg.value().decode()) == {"message": "hi", "conversation_id": "conv-1"}
    assert msg.key() == b"conv-1"  # bytes, matching librdkafka's Message.key()
    assert msg.error() is None
    assert consumer.poll_message() is None


def test_offset_reset_latest_skips_history():
    broker = InMemoryBroker()
    producer = _client(broker)
    producer.produce_message(USER_MESSAGE_TOPIC, "k", {"old": True})
    consumer = _client(broker)
    consumer.setup_consumer([USER_MESSAGE_TOPIC])
    assert consumer.poll_message() is None  # auto.offset.reset=latest (kafka_client.py:18)


def test_same_key_preserves_order():
    broker = InMemoryBroker()
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([AI_RESPONSE_TOPIC])
    for i in range(20):
        producer.produce_message(AI_RESPONSE_TOPIC, "conv-A", {"i": i})
    seen = []
    while (msg := consumer.poll_message()) is not None:
        seen.append(json.loads(msg.value().decode())["i"])
    assert seen == list(range(20))


def test_group_partition_split():
    broker = InMemoryBroker(num_partitions=4)
    producer = _client(broker)
    c1, c2 = _client(broker), _client(broker)
    c1.setup_consumer([USER_MESSAGE_TOPIC])
    c2.setup_consumer([USER_MESSAGE_TOPIC])
    keys = [f"conv-{i}" for i in range(40)]
    for k in keys:
        producer.produce_message(USER_MESSAGE_TOPIC, k, {"k": k})
    got1, got2 = set(), set()
    while (m := c1.poll_message()) is not None:
        got1.add(json.loads(m.value().decode())["k"])
    while (m := c2.poll_message()) is not None:
        got2.add(json.loads(m.value().decode())["k"])
    assert got1 | got2 == set(keys)
    assert got1.isdisjoint(got2)
    assert got1 and got2  # both members got an assignment


def test_default_broker_is_shared_per_process():
    # Two independently constructed clients must see each other (no silent
    # per-client broker isolation).
    producer = KafkaClient(KafkaConfig(backend="memory"))
    consumer = KafkaClient(KafkaConfig(backend="memory"))
    consumer.setup_consumer([AI_RESPONSE_TOPIC])
    producer.produce_message(AI_RESPONSE_TOPIC, "shared", {"ok": 1})
    msg = consumer.poll_message()
    assert msg is not None and json.loads(msg.value().decode()) == {"ok": 1}
    consumer.close()


def test_manual_commit_redelivers_uncommitted_on_rejoin():
    """kafka.commit_after_process (at-least-once): polled-but-uncommitted
    records redeliver when the group re-forms (a crashed worker's
    in-flight message is NOT lost); committed ones do not."""
    broker = InMemoryBroker()
    producer = _client(broker)
    cfg = KafkaConfig(backend="memory", commit_after_process=True)
    c1 = KafkaClient(cfg, broker=broker)
    c1.setup_consumer([USER_MESSAGE_TOPIC])
    producer.produce_message(USER_MESSAGE_TOPIC, "k", {"n": 1})
    producer.produce_message(USER_MESSAGE_TOPIC, "k", {"n": 2})

    m1 = c1.poll_message()
    assert json.loads(m1.value().decode()) == {"n": 1}
    # poll advanced the position, NOT the committed offset
    m2 = c1.poll_message()
    assert json.loads(m2.value().decode()) == {"n": 2}
    # commit only the first message's offset (its handler completed)
    c1.commit_offset(m1.topic(), m1.partition(), m1.offset() + 1)
    c1.close()  # "crash" before n=2 commits

    c2 = KafkaClient(cfg, broker=broker)
    c2.setup_consumer([USER_MESSAGE_TOPIC])
    redelivered = c2.poll_message()
    assert redelivered is not None
    assert json.loads(redelivered.value().decode()) == {"n": 2}
    assert c2.poll_message() is None  # n=1 was committed; only n=2 replays


def test_auto_commit_mode_never_redelivers():
    """Default (commit_after_process off) keeps reference at-most-once
    parity: poll commits, a rejoining consumer sees nothing twice."""
    broker = InMemoryBroker()
    producer = _client(broker)
    c1 = _client(broker)
    c1.setup_consumer([USER_MESSAGE_TOPIC])
    producer.produce_message(USER_MESSAGE_TOPIC, "k", {"n": 1})
    assert c1.poll_message() is not None
    c1.close()
    c2 = _client(broker)
    c2.setup_consumer([USER_MESSAGE_TOPIC])
    assert c2.poll_message() is None


def test_message_timestamp_is_producer_stamped():
    import time

    broker = InMemoryBroker()
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([USER_MESSAGE_TOPIC])
    before = time.time()
    producer.produce_message(USER_MESSAGE_TOPIC, "k", {"n": 1})
    msg = consumer.poll_message()
    ts_type, ts_ms = msg.timestamp()
    assert ts_type == 1  # TIMESTAMP_CREATE_TIME, as librdkafka reports
    assert abs(ts_ms / 1000.0 - before) < 5.0


def test_fault_injection_drop():
    broker = InMemoryBroker()
    broker.faults.drop_produce = lambda topic, value: value.get("drop", False)
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([AI_RESPONSE_TOPIC])
    producer.produce_message(AI_RESPONSE_TOPIC, "k", {"drop": True})
    producer.produce_message(AI_RESPONSE_TOPIC, "k", {"drop": False})
    msg = consumer.poll_message()
    assert json.loads(msg.value().decode()) == {"drop": False}
    assert consumer.poll_message() is None
