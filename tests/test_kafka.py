"""In-memory broker semantics: ordering by key, groups, QoS, faults."""

import json

from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient
from finchat_tpu.utils.config import AI_RESPONSE_TOPIC, USER_MESSAGE_TOPIC, KafkaConfig


def _client(broker):
    return KafkaClient(KafkaConfig(backend="memory"), broker=broker)


def test_produce_consume_roundtrip():
    broker = InMemoryBroker()
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([USER_MESSAGE_TOPIC])
    # offset_reset=latest: records produced AFTER joining are visible
    producer.produce_message(USER_MESSAGE_TOPIC, "conv-1", {"message": "hi", "conversation_id": "conv-1"})
    msg = consumer.poll_message()
    assert msg is not None
    assert json.loads(msg.value().decode()) == {"message": "hi", "conversation_id": "conv-1"}
    assert msg.key() == b"conv-1"  # bytes, matching librdkafka's Message.key()
    assert msg.error() is None
    assert consumer.poll_message() is None


def test_offset_reset_latest_skips_history():
    broker = InMemoryBroker()
    producer = _client(broker)
    producer.produce_message(USER_MESSAGE_TOPIC, "k", {"old": True})
    consumer = _client(broker)
    consumer.setup_consumer([USER_MESSAGE_TOPIC])
    assert consumer.poll_message() is None  # auto.offset.reset=latest (kafka_client.py:18)


def test_same_key_preserves_order():
    broker = InMemoryBroker()
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([AI_RESPONSE_TOPIC])
    for i in range(20):
        producer.produce_message(AI_RESPONSE_TOPIC, "conv-A", {"i": i})
    seen = []
    while (msg := consumer.poll_message()) is not None:
        seen.append(json.loads(msg.value().decode())["i"])
    assert seen == list(range(20))


def test_group_partition_split():
    broker = InMemoryBroker(num_partitions=4)
    producer = _client(broker)
    c1, c2 = _client(broker), _client(broker)
    c1.setup_consumer([USER_MESSAGE_TOPIC])
    c2.setup_consumer([USER_MESSAGE_TOPIC])
    keys = [f"conv-{i}" for i in range(40)]
    for k in keys:
        producer.produce_message(USER_MESSAGE_TOPIC, k, {"k": k})
    got1, got2 = set(), set()
    while (m := c1.poll_message()) is not None:
        got1.add(json.loads(m.value().decode())["k"])
    while (m := c2.poll_message()) is not None:
        got2.add(json.loads(m.value().decode())["k"])
    assert got1 | got2 == set(keys)
    assert got1.isdisjoint(got2)
    assert got1 and got2  # both members got an assignment


def test_default_broker_is_shared_per_process():
    # Two independently constructed clients must see each other (no silent
    # per-client broker isolation).
    producer = KafkaClient(KafkaConfig(backend="memory"))
    consumer = KafkaClient(KafkaConfig(backend="memory"))
    consumer.setup_consumer([AI_RESPONSE_TOPIC])
    producer.produce_message(AI_RESPONSE_TOPIC, "shared", {"ok": 1})
    msg = consumer.poll_message()
    assert msg is not None and json.loads(msg.value().decode()) == {"ok": 1}
    consumer.close()


def test_fault_injection_drop():
    broker = InMemoryBroker()
    broker.faults.drop_produce = lambda topic, value: value.get("drop", False)
    producer = _client(broker)
    consumer = _client(broker)
    consumer.setup_consumer([AI_RESPONSE_TOPIC])
    producer.produce_message(AI_RESPONSE_TOPIC, "k", {"drop": True})
    producer.produce_message(AI_RESPONSE_TOPIC, "k", {"drop": False})
    msg = consumer.poll_message()
    assert json.loads(msg.value().decode()) == {"drop": False}
    assert consumer.poll_message() is None
