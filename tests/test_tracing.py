"""End-to-end request tracing + anomaly flight recorder (ISSUE 12).

Pins the tracing contract:

- TRACE RING: bounded, Chrome-trace-event export per trace id, dispatch
  events correlated by their ``rows`` lists (many requests share one
  ragged dispatch; each still gets its own timeline).
- SPAN IDEMPOTENCE: ``RequestSpan.finish()`` first-call-wins; later calls
  (the preempt-replay / drain-handoff overlap paths exercise them) are
  counted in ``finchat_span_double_finish_total`` and observe nothing.
- PROPAGATION: a trace id submitted through the REAL generator→scheduler
  path yields one timeline containing admitted, prefill dispatches,
  first token, and done — and tracing on vs off never changes the
  greedy streamed output (byte-identity, the satellite contract).
- AGENT MARKS: decide_start / name_commit / tool_launch / tool_adopted /
  response_prefill_hold land on the timeline; streamed output is
  byte-identical with tracing on vs off.
- FLIGHT RECORDER: an anomaly dumps a checksummed file whose events
  include the anomaly and the ring's dispatch spans; corruption is
  detected; per-kind dumps are rate-limited.
- EXEMPLARS: a histogram keeps the last above-p99 trace id and renders
  it after the family.
"""

import asyncio
import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.generator import EngineGenerator, StubGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import get_tokenizer
from finchat_tpu.utils import faults
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS, MetricsRegistry
from finchat_tpu.utils.tracing import (
    ANOMALY_KINDS,
    SPAN_MARKS,
    TRACE_EVENT_NAMES,
    TRACER,
    RequestSpan,
    Tracer,
    load_flight_dump,
)


@pytest.fixture(autouse=True)
def _tracer_reset():
    """Every test starts from an enabled, dump-less, empty ring and the
    process tracer is restored afterwards (it is global like METRICS)."""
    prev_enabled, prev_dir = TRACER.enabled, TRACER.flight_dir
    TRACER.configure(enabled=True, flight_dir="")
    TRACER.clear()
    TRACER._last_dump.clear()
    yield
    TRACER.flush_dumps()
    TRACER.configure(enabled=prev_enabled, flight_dir=prev_dir)
    TRACER.clear()
    faults.disarm_all()


def _events(export):
    return [e["name"] for e in export["traceEvents"]]


# --- ring + export --------------------------------------------------------

def test_ring_is_bounded():
    t = Tracer(ring_events=32)
    for i in range(100):
        t.event("ingress", f"t{i}")
    assert len(t.snapshot()) == 32
    # oldest aged out, newest retained
    assert t.export("t0")["traceEvents"] == []
    assert len(t.export("t99")["traceEvents"]) == 1


def test_export_is_chrome_trace_schema():
    TRACER.event("ingress", "req-1", args={"source": "kafka:user_message"})
    TRACER.event("dispatch", dur=0.002,
                 args={"kind": "ragged", "n": 7,
                       "rows": [[0, "req-1", "prefill"], [1, "other", "decode"]]})
    TRACER.event("first_token", "req-1", track="request")
    export = TRACER.export("req-1")
    # the dispatch correlates through its rows even though the event
    # itself is not stamped with the id (shared-dispatch attribution)
    assert _events(export) == ["ingress", "dispatch", "first_token"]
    assert export["displayTimeUnit"] == "ms"
    for ev in export["traceEvents"]:
        assert ev["ph"] in ("X", "i")
        assert isinstance(ev["ts"], float) and ev["ts"] > 0
        assert isinstance(ev["tid"], str) and "pid" in ev and "cat" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        else:
            assert ev["s"] == "t"
    # json-serializable end to end (what /debug/trace returns)
    json.dumps(export)
    # the sibling request sees the SAME dispatch on its own timeline
    assert "dispatch" in _events(TRACER.export("other"))


def test_disabled_tracer_records_nothing(tmp_path):
    TRACER.configure(enabled=False, flight_dir=str(tmp_path))
    TRACER.event("ingress", "t1")
    TRACER.anomaly("shed", "t1")
    assert TRACER.snapshot() == []
    TRACER.flush_dumps()
    assert list(tmp_path.iterdir()) == []


def test_registry_names_are_consistent():
    # the agent/scheduler marks the PR depends on are all declared — the
    # R5 span-discipline lint keys on these exact sets
    for name in ("admitted", "prefill_done", "first_token", "done",
                 "decide_start", "name_commit", "tool_launch",
                 "tool_adopted", "response_prefill_hold"):
        assert name in SPAN_MARKS
    for kind in ("breaker_trip", "watchdog_timeout", "shed",
                 "replica_give_up", "record_quarantine", "sigterm_drain"):
        assert kind in ANOMALY_KINDS
    assert "dispatch" in TRACE_EVENT_NAMES and "ingress" in TRACE_EVENT_NAMES


# --- span idempotence -----------------------------------------------------

def test_span_finish_first_call_wins():
    reg = MetricsRegistry()
    span = RequestSpan("seq-1", trace_id="t-span")
    span.mark("admitted")
    span.finish(reg)
    done = span.marks["done"]
    n0 = reg.snapshot()["finchat_request_seconds_count"]
    span.finish(reg)
    span.finish(reg)
    assert span.marks["done"] == done  # untouched by later calls
    assert reg.snapshot()["finchat_request_seconds_count"] == n0  # observed once
    assert reg.get("finchat_span_double_finish_total") == 2


# --- real-scheduler propagation + idempotence regressions -----------------

def _make_scheduler(**cfg_overrides):
    config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
    defaults = dict(
        max_seqs=2, page_size=8, num_pages=64, max_seq_len=128,
        prefill_chunk=16, session_cache=False,
    )
    defaults.update(cfg_overrides)
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, EngineConfig(**defaults))
    return ContinuousBatchingScheduler(engine, eos_id=-1)


async def _drain(handle):
    tokens = []
    while True:
        event = await handle.events.get()
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return tokens, None
        else:
            return tokens, event


def _greedy(n):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


def test_trace_threads_to_scheduler_and_is_output_invariant():
    """One traced request through the REAL scheduler: the exported
    timeline carries admitted → prefill dispatch(es) → first_token →
    done → request, the dispatch rows attribute the request's slot, and
    the greedy stream is byte-identical to the same run with tracing
    off (the tracing-never-changes-output satellite)."""

    def run(traced: bool):
        TRACER.configure(enabled=traced)
        TRACER.clear()

        async def go():
            sched = _make_scheduler()
            await sched.start()
            try:
                h = await sched.submit(
                    "s0", list(range(1, 14)), _greedy(8),
                    trace_id="req-42" if traced else None,
                )
                return await asyncio.wait_for(_drain(h), timeout=120)
            finally:
                await sched.stop()

        return asyncio.run(go())

    tokens_on, err_on = run(True)
    export = TRACER.export("req-42")
    names = _events(export)
    for expected in ("admitted", "prefill_done", "first_token", "done",
                     "request", "dispatch"):
        assert expected in names, (expected, names)
    # every dispatch event that carried the request names its row mode
    dispatches = [e for e in export["traceEvents"] if e["name"] == "dispatch"]
    modes = {r[2] for e in dispatches for r in e["args"]["rows"]
             if r[1] == "req-42"}
    assert "prefill" in modes and "decode" in modes, modes
    tokens_off, err_off = run(False)
    assert err_on is None and err_off is None
    assert tokens_on == tokens_off  # byte-identical on vs off


def test_double_finish_counted_on_preempt_and_drain_paths():
    """Regression for the ISSUE 12 satellite: finish() is reached from
    many scheduler sites; on the preempt-replay → shutdown-drain flow a
    stream's span can be finished again by a late cleanup (generator
    finalizer, drain-handoff source failing what the adopter already
    finished). First call wins; extras only count."""
    d0 = METRICS.get("finchat_span_double_finish_total")
    n_before = METRICS.snapshot().get("finchat_request_seconds_count", 0)

    async def go():
        sched = _make_scheduler()
        await sched.start()
        try:
            h = await sched.submit("s0", list(range(1, 14)), _greedy(32),
                                   trace_id="req-drain")
            while h.generated < 2:
                await asyncio.sleep(0.002)
            # preempt-replay: the handle goes back to pending mid-stream
            sched._preempt(h)
            assert h.preempted == 1 and not h.finished
        finally:
            # drain fails the pending replay with a retryable error —
            # the FIRST finish of this span
            await sched.shutdown_drain()
        assert h.finished and h.span.finished
        # late cleanups on the handoff/cancel paths re-finish: counted,
        # not double-observed
        sched._finish(h, "eos")
        h.span.finish()
        return h

    asyncio.run(go())
    assert METRICS.get("finchat_span_double_finish_total") - d0 == 2
    assert METRICS.snapshot()["finchat_request_seconds_count"] - n_before == 1


# --- agent marks + byte identity ------------------------------------------

class _PartialResponseGenerator(StubGenerator):
    """Stub response generator exposing the partial-prefill seam, so the
    name-commit hold (and its response_prefill_hold mark) is exercised
    without an engine."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.holds = []

    async def begin_partial(self, prefix_text, sampling,
                            conversation_id=None, deadline=None,
                            trace_id=None):
        hold = type("Hold", (), {"_partial_claimed": False})()
        self.holds.append(hold)
        return hold

    def release_partial(self, partial):
        pass

    async def stream(self, prompt, sampling, conversation_id=None,
                     deadline=None, trace_id=None, partial=None):
        if partial is not None:
            partial._partial_claimed = True
        async for piece in super().stream(prompt, sampling):
            yield piece


def test_agent_marks_and_streamed_output_identity():
    from finchat_tpu.agent.graph import LLMAgent

    tool_text = ('retrieve_transactions({"search_query": "coffee", '
                 '"num_transactions": 2})')

    async def retriever(args):
        await asyncio.sleep(0.005)
        return ["COFFEE $4", "COFFEE $6"]

    def run_turn(traced: bool):
        TRACER.configure(enabled=True)
        TRACER.clear()
        agent = LLMAgent(
            StubGenerator(default=tool_text, chunk_delay=0.005),
            _PartialResponseGenerator(default="Here is my advice."),
            retriever, "SYSTEM", "TOOL", today=lambda: "2026-08-04",
        )

        async def go():
            chunks = []
            async for update in agent.stream_with_status(
                "coffee spend?", "u1", "CTX", [],
                conversation_id="c1",
                trace_id="req-agent" if traced else None,
            ):
                chunks.append(update)
            return chunks

        return asyncio.run(go())

    traced_chunks = run_turn(True)
    names = _events(TRACER.export("req-agent"))
    for mark in ("decide_start", "name_commit", "tool_launch",
                 "tool_adopted", "response_prefill_hold"):
        assert mark in names, (mark, names)
    # name_commit precedes tool adoption on the timeline
    assert names.index("name_commit") < names.index("tool_adopted")
    untraced_chunks = run_turn(False)
    assert _events(TRACER.export("req-agent")) == []  # no id → no events
    # tracing never changes the streamed event protocol (byte identity)
    assert traced_chunks == untraced_chunks


# --- flight recorder ------------------------------------------------------

def test_flight_dump_checksummed_roundtrip(tmp_path):
    TRACER.configure(flight_dir=str(tmp_path))
    TRACER.event("dispatch", args={"kind": "decode", "n": 3,
                                   "rows": [[0, "req-9", "decode"]]})
    TRACER.anomaly("breaker_trip", args={"plane": "decode", "error": "wedged"})
    TRACER.flush_dumps()
    dumps = sorted(tmp_path.glob("flight-*.json"))
    assert len(dumps) == 1 and "breaker_trip" in dumps[0].name
    rec = load_flight_dump(str(dumps[0]))
    assert rec["reason"] == "breaker_trip"
    names = [e["name"] for e in rec["trace"]["traceEvents"]]
    assert names == ["dispatch", "breaker_trip"]
    assert rec["anomaly_args"]["plane"] == "decode"


def test_flight_dump_corruption_detected(tmp_path):
    TRACER.configure(flight_dir=str(tmp_path))
    TRACER.anomaly("shed")
    TRACER.flush_dumps()
    path = next(tmp_path.glob("flight-*.json"))
    raw = bytearray(path.read_bytes())
    raw[-3] ^= 0xFF  # flip a payload byte under the checksum
    path.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="checksum"):
        load_flight_dump(str(path))
    # truncation is detected too
    path.write_bytes(path.read_bytes()[:-10])
    with pytest.raises(ValueError, match="truncated"):
        load_flight_dump(str(path))


def test_flight_dump_rate_limited_per_kind(tmp_path):
    TRACER.configure(flight_dir=str(tmp_path))
    for _ in range(5):
        TRACER.anomaly("shed")  # a shed wave must not write 5 black boxes
    TRACER.anomaly("watchdog_timeout")  # distinct kind: its own dump
    TRACER.flush_dumps()
    names = [p.name for p in tmp_path.glob("flight-*.json")]
    assert len([n for n in names if "shed" in n]) == 1
    assert len([n for n in names if "watchdog_timeout" in n]) == 1
    # every shed EVENT still landed in the ring (only dumps are limited)
    assert sum(1 for ev in TRACER.snapshot() if ev[2] == "shed") == 5


def test_breaker_trip_dumps_flight_recorder(tmp_path):
    """The ROBUSTNESS breaker drill leaves a black box: the dump contains
    the trip anomaly AND the tripped streams' dispatch spans."""
    TRACER.configure(flight_dir=str(tmp_path))

    async def go():
        sched = _make_scheduler()
        await sched.start()
        try:
            h = await sched.submit("s0", list(range(1, 14)), _greedy(10),
                                   trace_id="req-trip")
            task = asyncio.create_task(_drain(h))
            while h.generated < 2:
                await asyncio.sleep(0.002)
            faults.arm("scheduler.decode",
                       faults.n_shot(sched.breaker_threshold,
                                     RuntimeError("chaos: wedged dispatch")))
            tokens, err = await asyncio.wait_for(task, timeout=120)
            assert err is None  # the stream survived the rebuild
        finally:
            await sched.stop()
            faults.disarm_all()

    asyncio.run(go())
    TRACER.flush_dumps()
    dumps = [p for p in tmp_path.glob("flight-*.json") if "breaker_trip" in p.name]
    assert len(dumps) == 1
    rec = load_flight_dump(str(dumps[0]))
    events = rec["trace"]["traceEvents"]
    assert any(e["name"] == "breaker_trip" for e in events)
    # dispatch spans that carried the tripped request are in the box
    assert any(
        e["name"] == "dispatch"
        and any(r[1] == "req-trip" for r in e["args"]["rows"])
        for e in events
    )
    # ... and the recovery preempt is on the request's own timeline
    assert any(e["name"] == "preempt" for e in events
               if e["args"].get("trace_id") == "req-trip")


# --- exemplars ------------------------------------------------------------

def test_histogram_exemplar_tracks_above_p99():
    reg = MetricsRegistry()
    for i in range(200):
        reg.observe("finchat_lat_seconds", 0.01, trace_id=f"fast-{i}")
    reg.observe("finchat_lat_seconds", 9.0, trace_id="slow-1")
    for i in range(50):
        reg.observe("finchat_lat_seconds", 0.01, trace_id=f"tail-{i}")
    tid, value, ts = reg.exemplar("finchat_lat_seconds")
    assert tid == "slow-1" and value == 9.0
    # rendered after the family as an OpenMetrics-style comment
    text = reg.render_prometheus()
    assert '# exemplar finchat_lat_seconds trace_id="slow-1"' in text


def test_exemplar_through_labeled_view():
    reg = MetricsRegistry()
    view = reg.labeled(replica="3")
    view.observe("finchat_lat_seconds", 4.0, trace_id="r3-slow")
    assert view.exemplar("finchat_lat_seconds")[0] == "r3-slow"
    assert reg.exemplar("finchat_lat_seconds", labels={"replica": "3"})[0] == "r3-slow"


# --- /debug/trace endpoint ------------------------------------------------

async def test_debug_trace_endpoint_prefix_route():
    from finchat_tpu.serve.http import HTTPServer, Request, Response

    TRACER.event("ingress", "req-h", args={"source": "http:/chat"})
    server = HTTPServer("127.0.0.1", 0)

    async def handler(request: Request) -> Response:
        trace_id = request.path.rsplit("/", 1)[-1]
        export = TRACER.export(trace_id)
        if not export["traceEvents"]:
            return Response.json({"detail": "unknown"}, status=404)
        return Response.json(export)

    server.route_prefix("GET", "/debug/trace/", handler)
    await server.start()
    try:
        async def get(path):
            reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
            writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
            await writer.drain()
            raw = await reader.read()
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            return int(head.split()[1]), body

        status, body = await get("/debug/trace/req-h")
        assert status == 200
        assert json.loads(body)["traceEvents"][0]["name"] == "ingress"
        status, _ = await get("/debug/trace/nope")
        assert status == 404
    finally:
        await server.stop()
