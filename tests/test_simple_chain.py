"""Agent-less streaming chain (serve/simple.py — reference llm_service.py
parity): same prompt pieces as the agent path, chunked streaming output,
no tools/RAG/graph involved."""

from finchat_tpu.engine.generator import StubGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.io.schemas import AI_SENDER, USER_SENDER, ChatMessage
from finchat_tpu.serve.simple import LLMService


async def test_streams_chunks_and_renders_full_prompt():
    gen = StubGenerator(default="Hello there friend")
    svc = LLMService(gen, "SYSTEM RULES",
                     sampling=SamplingParams(temperature=0.0, max_new_tokens=16))
    history = [
        ChatMessage(sender=USER_SENDER, message="earlier question"),
        ChatMessage(sender=AI_SENDER, message="earlier answer"),
    ]
    chunks = [c async for c in svc.process_message(
        "what now?", context="name: Pat", chat_history=history,
    )]
    assert "".join(chunks) == "Hello there friend"
    assert len(chunks) > 1  # streamed, not one blob
    # the rendered prompt carries every piece, in the agent's structure
    [prompt] = gen.calls
    for piece in ("SYSTEM RULES", "name: Pat", "earlier question",
                  "earlier answer", "what now?"):
        assert piece in prompt
    assert prompt.index("SYSTEM RULES") < prompt.index("earlier question") < prompt.index("what now?")


async def test_per_call_system_prompt_override():
    gen = StubGenerator(default="ok")
    svc = LLMService(gen, "DEFAULT SYS")
    [_ async for _ in svc.process_message("hi", system_prompt="OVERRIDE SYS")]
    assert "OVERRIDE SYS" in gen.calls[0] and "DEFAULT SYS" not in gen.calls[0]
    [_ async for _ in svc.process_message("hi")]
    assert "DEFAULT SYS" in gen.calls[1]
