"""Parallel layer on the forced 8-device CPU mesh (SURVEY §4.3): mesh
resolution, ring attention vs reference, TP-sharded inference golden match,
sharded train step, and the driver's multichip dryrun."""

import jax
import jax.numpy as jnp
import pytest

from finchat_tpu.models.llama import LlamaConfig, init_params
from finchat_tpu.ops.refs import mha_reference
from finchat_tpu.ops.ring_attention import ring_attention
from finchat_tpu.parallel.mesh import MeshSpec, build_mesh


def test_mesh_spec_resolution():
    assert MeshSpec(data=2, model=-1).resolve(8) == (2, 1, 1, 1, 4)
    assert MeshSpec(data=1, seq=1, expert=1, model=8).resolve(8) == (1, 1, 1, 1, 8)
    assert MeshSpec(data=1, pipe=2, model=-1).resolve(8) == (1, 2, 1, 1, 4)
    with pytest.raises(ValueError):
        MeshSpec(data=3, model=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=2, model=2).resolve(8)  # product mismatch


def test_pipe_axis_tolerated_by_shardings():
    """SURVEY §2.3: the PP axis exists in the mesh and param/state shardings
    (which never name 'pipe') place cleanly on a pipe>1 mesh."""
    from finchat_tpu.engine.engine import create_state
    from finchat_tpu.parallel.sharding import (
        llama_param_shardings, shard_decode_state, shard_params,
    )
    from finchat_tpu.utils.config import EngineConfig

    mesh = build_mesh(MeshSpec(data=1, pipe=2, seq=1, expert=1, model=4))
    assert mesh.shape["pipe"] == 2
    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
        hidden_dim=64, max_seq_len=32,
    )
    params = shard_params(init_params(config, jax.random.key(0)), llama_param_shardings(mesh))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=32, prefill_chunk=8)
    state = shard_decode_state(create_state(config, ecfg, 4), mesh)
    assert state.k_pages.sharding.mesh.shape["pipe"] == 2


def test_ring_attention_matches_reference():
    mesh = build_mesh(MeshSpec(data=1, seq=8, expert=1, model=1))
    B, S, H, Hkv, D = 2, 64, 4, 2, 16
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)
    for causal in (True, False):
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        ref = mha_reference(q, k, v, causal=causal)
        assert float(jnp.abs(out - ref).max()) < 1e-4, f"causal={causal}"


def test_tp_sharded_engine_matches_unsharded():
    """Greedy decode must be bit-identical between 1-device and TP=8."""
    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=64,
    )
    params = init_params(config, jax.random.key(0))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64, prefill_chunk=8)
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 6

    def run(mesh):
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return out

    unsharded = run(None)
    tp_mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    sharded = run(tp_mesh)
    assert unsharded == sharded


def test_train_step_dp_tp_sp():
    from finchat_tpu.parallel.sharding import llama_param_shardings, shard_params
    from finchat_tpu.train.train_step import (
        init_train_state, make_optimizer, make_train_step, shard_batch,
    )

    mesh = build_mesh(MeshSpec(data=2, seq=2, expert=1, model=2))
    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32,
    )
    params = shard_params(init_params(config, jax.random.key(0)), llama_param_shardings(mesh))
    optimizer = make_optimizer(learning_rate=1e-2)
    step = make_train_step(config, optimizer, mesh, use_ring_attention=True)
    state = init_train_state(config, params, optimizer)
    tokens = shard_batch(
        jax.random.randint(jax.random.key(1), (4, 16), 0, 64), mesh, seq_sharded=True
    )
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses)))
    assert losses[-1] < losses[0], losses  # memorizing one tiny batch


def test_dryrun_multichip_entrypoint():
    import importlib.util
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    module.dryrun_multichip(8)

    fn, args = module.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
