"""Parallel layer on the forced 8-device CPU mesh (SURVEY §4.3): mesh
resolution, ring attention vs reference, TP-sharded inference golden match,
sharded train step, and the driver's multichip dryrun."""

import jax
import jax.numpy as jnp
import pytest

from finchat_tpu.models.llama import LlamaConfig, init_params
from finchat_tpu.ops.refs import mha_reference
from finchat_tpu.ops.ring_attention import ring_attention
from finchat_tpu.parallel.mesh import MeshSpec, build_mesh


def test_mesh_spec_resolution():
    assert MeshSpec(data=2, model=-1).resolve(8) == (2, 1, 1, 1, 4)
    assert MeshSpec(data=1, seq=1, expert=1, model=8).resolve(8) == (1, 1, 1, 1, 8)
    assert MeshSpec(data=1, pipe=2, model=-1).resolve(8) == (1, 2, 1, 1, 4)
    with pytest.raises(ValueError):
        MeshSpec(data=3, model=-1).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=2, model=2).resolve(8)  # product mismatch


def test_pipe_axis_tolerated_by_shardings():
    """SURVEY §2.3: the PP axis exists in the mesh and param/state shardings
    (which never name 'pipe') place cleanly on a pipe>1 mesh."""
    from finchat_tpu.engine.engine import create_state
    from finchat_tpu.parallel.sharding import (
        llama_param_shardings, shard_decode_state, shard_params,
    )
    from finchat_tpu.utils.config import EngineConfig

    mesh = build_mesh(MeshSpec(data=1, pipe=2, seq=1, expert=1, model=4))
    assert mesh.shape["pipe"] == 2
    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
        hidden_dim=64, max_seq_len=32,
    )
    params = shard_params(init_params(config, jax.random.key(0)), llama_param_shardings(mesh))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=32, prefill_chunk=8)
    state = shard_decode_state(create_state(config, ecfg, 4), mesh)
    assert state.k_pages.sharding.mesh.shape["pipe"] == 2


def test_ring_attention_matches_reference():
    mesh = build_mesh(MeshSpec(data=1, seq=8, expert=1, model=1))
    B, S, H, Hkv, D = 2, 64, 4, 2, 16
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)
    for causal in (True, False):
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        ref = mha_reference(q, k, v, causal=causal)
        assert float(jnp.abs(out - ref).max()) < 1e-4, f"causal={causal}"


def test_tp_sharded_engine_matches_unsharded():
    """Greedy decode must be bit-identical between 1-device and TP=8."""
    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=64,
    )
    params = init_params(config, jax.random.key(0))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64, prefill_chunk=8)
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 6

    def run(mesh):
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return out

    unsharded = run(None)
    tp_mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    sharded = run(tp_mesh)
    assert unsharded == sharded


def test_train_step_dp_tp_sp():
    from finchat_tpu.parallel.sharding import llama_param_shardings, shard_params
    from finchat_tpu.train.train_step import (
        init_train_state, make_optimizer, make_train_step, shard_batch,
    )

    mesh = build_mesh(MeshSpec(data=2, seq=2, expert=1, model=2))
    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32,
    )
    params = shard_params(init_params(config, jax.random.key(0)), llama_param_shardings(mesh))
    optimizer = make_optimizer(learning_rate=1e-2)
    step = make_train_step(config, optimizer, mesh, use_ring_attention=True)
    state = init_train_state(config, params, optimizer)
    tokens = shard_batch(
        jax.random.randint(jax.random.key(1), (4, 16), 0, 64), mesh, seq_sharded=True
    )
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses)))
    assert losses[-1] < losses[0], losses  # memorizing one tiny batch


@pytest.mark.slow  # ~50 s: the full multichip dryrun matrix on 8 CPU devices
def test_dryrun_multichip_entrypoint():
    import importlib.util
    from pathlib import Path

    path = Path(__file__).resolve().parent.parent / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("graft_entry", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    module.dryrun_multichip(8)

    fn, args = module.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]


def test_ring_prefill_serving_matches_chunked():
    """SURVEY §5.7c: a long prompt prefilled through the seq-sharded ring
    path (TP x SP mesh) must leave the engine in the same state as batched
    chunked prefill — same greedy continuation, same last-token logits."""
    import numpy as np

    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=128, max_seq_len=128,
    )
    params = init_params(config, jax.random.key(0))
    prompt = list(np.random.RandomState(3).randint(1, 128, size=50))
    n_new = 5

    def run(mesh, ring_min):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=32, max_seq_len=128,
            prefill_chunk=16, ring_prefill_min_tokens=ring_min,
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        if ring_min <= len(prompt) and mesh is not None:
            assert eng._use_ring_prefill(len(prompt))
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return np.asarray(logits, np.float32), out

    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))
    ring_logits, ring_tokens = run(mesh, ring_min=16)  # ring path engaged
    mesh_logits, mesh_tokens = run(mesh, ring_min=10_000)  # chunked, same mesh
    _, plain_tokens = run(None, ring_min=10_000)  # chunked, unsharded

    # same mesh, different prefill path: logits agree to bf16-activation
    # numerics (the accumulation orders differ: blockwise ring softmax vs
    # gathered-pages reference)
    np.testing.assert_allclose(ring_logits, mesh_logits, atol=2e-2, rtol=2e-2)
    # the greedy continuation is identical across ring/chunked/unsharded
    assert ring_tokens == mesh_tokens == plain_tokens


@pytest.mark.parametrize("sp_mode,mesh_spec", [
    ("ring", MeshSpec(data=1, seq=2, expert=1, model=4)),
    # ulysses divisibility: per-TP heads (4) and kv (2) divide seq=2
    ("ulysses", MeshSpec(data=2, seq=2, expert=1, model=2)),
])
def test_segmented_ring_prefill_matches_monolithic(sp_mode, mesh_spec):
    """VERDICT r4 weak #8 (chunked SP prefill): prefilling a long prompt
    in segments — each SP-attending (ring or Ulysses) to itself and
    folding the cached earlier segments (engine.prefill_ring_segment) —
    must leave the engine in the same state as the one-shot SP prefill:
    same final-token logits, same greedy continuation."""
    import numpy as np

    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=128, max_seq_len=256,
    )
    params = init_params(config, jax.random.key(0))
    prompt = list(np.random.RandomState(11).randint(1, 128, size=100))
    n_new = 5
    mesh = build_mesh(mesh_spec)

    def run(ring_chunk):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=64, max_seq_len=256,
            prefill_chunk=16, ring_prefill_min_tokens=16,
            ring_prefill_chunk=ring_chunk, sp_mode=sp_mode,
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        assert eng.sp_mode == sp_mode  # no silent fallback in this test
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        if ring_chunk:
            rc = eng.ring_segment_tokens()
            assert rc == ring_chunk  # already a seq multiple here
            logits = None
            for start in range(0, len(prompt), rc):
                logits = eng.prefill_ring_segment(
                    0, prompt[start : start + rc], start
                )
            assert int(np.asarray(eng.state.context_lens)[0]) == len(prompt)
        else:
            logits = eng.prefill_ring(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return np.asarray(logits, np.float32), out

    mono_logits, mono_tokens = run(0)
    seg_logits, seg_tokens = run(32)  # 100 tokens -> 4 segments
    # tolerance is the bf16-activation envelope: the segmented fold
    # accumulates in a different order, and jax 0.4's shard_map lowers
    # the all_to_all/psum chain in yet another order (1/128 elements sat
    # at 0.03 under it), hence 4e-2 rather than 2e-2
    np.testing.assert_allclose(seg_logits, mono_logits, atol=4e-2, rtol=4e-2)
    assert seg_tokens == mono_tokens


def test_scheduler_decode_progress_during_ring_prefill():
    """The 63-streams-stall cliff is dead: with chunked ring prefill on,
    an in-flight decode stream keeps receiving tokens WHILE a long
    ring-eligible prompt prefills, and the ring-prefilled request streams
    the same tokens as it would with a monolithic ring prefill."""
    import asyncio

    import numpy as np

    from finchat_tpu.engine.engine import InferenceEngine
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.tokenizer import ByteTokenizer
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=300, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=128, max_seq_len=256,
    )
    params = init_params(config, jax.random.key(0))
    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))
    tok = ByteTokenizer()
    long_prompt = list(np.random.RandomState(5).randint(5, 250, size=100))

    async def run(ring_chunk):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=64, max_seq_len=256,
            prefill_chunk=16, ring_prefill_min_tokens=64,
            ring_prefill_chunk=ring_chunk,
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        sched = ContinuousBatchingScheduler(eng, eos_id=tok.eos_id)
        await sched.start()
        try:
            stream = await sched.submit(
                "stream", [1, 2, 3, 4, 5],
                SamplingParams(temperature=0.0, max_new_tokens=48),
            )
            seen = []
            while len(seen) < 4:  # steady-state decode first
                event = await asyncio.wait_for(stream.events.get(), timeout=120)
                assert event["type"] == "token", event
                seen.append(event["token_id"])
            ring_handle = await sched.submit(
                "ring", long_prompt,
                SamplingParams(temperature=0.0, max_new_tokens=6),
            )
            during = 0
            ring_tokens = []
            while ring_handle.first_token_at is None and not ring_handle.finished:
                event = await asyncio.wait_for(stream.events.get(), timeout=120)
                if event["type"] != "token":
                    break
                during += 1
            while True:
                event = await asyncio.wait_for(ring_handle.events.get(), timeout=120)
                if event["type"] == "token":
                    ring_tokens.append(event["token_id"])
                elif event["type"] == "done":
                    break
                else:
                    raise AssertionError(event)
            return during, ring_tokens
        finally:
            await sched.stop()

    during_seg, seg_tokens = asyncio.run(run(32))  # 100 tokens -> 4 segments
    # the monolithic run is the token-equality oracle only; its `during`
    # count is timing-dependent (a token can land before/after the single
    # ring round) so the stall contrast is not asserted on it
    _, mono_tokens = asyncio.run(run(0))
    assert seg_tokens == mono_tokens  # same stream either way
    # ≥3 extra prefill rounds ran with a decode step interleaving each;
    # the stream must have advanced while the long prompt prefilled
    assert during_seg >= 2, f"stream starved during segmented ring prefill ({during_seg})"


def test_segmented_ring_composes_with_prefix_cache():
    """With chunked ring prefill, a ring-eligible prompt opening with a
    registered shared head KEEPS the prefix-cache hit (the old monolithic
    path had to skip matching — 'ring assumes position 0'): the first
    segment starts at shared_len with the cached head folded as prefix,
    and the stream equals the uncached ring run token-for-token."""
    import asyncio

    import numpy as np

    from finchat_tpu.engine.engine import InferenceEngine
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.tokenizer import ByteTokenizer
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=300, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=128, max_seq_len=256,
    )
    params = init_params(config, jax.random.key(0))
    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))
    tok = ByteTokenizer()
    rng = np.random.RandomState(9)
    head = list(rng.randint(5, 250, size=48))  # 6 whole pages
    prompt = head + list(rng.randint(5, 250, size=52))  # 100 total, ring-eligible

    async def run(register):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=64, max_seq_len=256,
            prefill_chunk=16, ring_prefill_min_tokens=64,
            ring_prefill_chunk=32,
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        sched = ContinuousBatchingScheduler(eng, eos_id=tok.eos_id)
        if register:
            assert sched.register_prefix(head) == 48
        await sched.start()
        try:
            handle = await sched.submit(
                "s", prompt, SamplingParams(temperature=0.0, max_new_tokens=6)
            )
            tokens = []
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=120)
                if event["type"] == "token":
                    tokens.append(event["token_id"])
                elif event["type"] == "done":
                    break
                else:
                    raise AssertionError(event)
            return handle, tokens
        finally:
            await sched.stop()

    from finchat_tpu.utils.metrics import METRICS

    saved0 = METRICS.get("finchat_prefix_tokens_saved_total")
    cached_handle, cached_tokens = asyncio.run(run(True))
    # the hit engaged (48 head tokens never re-prefilled)...
    assert METRICS.get("finchat_prefix_tokens_saved_total") == saved0 + 48
    assert cached_handle.ring_path  # ...on the ring path
    plain_handle, plain_tokens = asyncio.run(run(False))
    assert plain_handle.ring_path
    assert METRICS.get("finchat_prefix_tokens_saved_total") == saved0 + 48
    assert cached_tokens == plain_tokens


def test_ulysses_serving_prefill_matches_chunked():
    """SURVEY §5.7d: sp_mode='ulysses' must serve the seq-sharded long
    prefill with the same greedy continuation as chunked prefill, and an
    indivisible head count must fall back to ring rather than fail."""
    import numpy as np

    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=128, max_seq_len=128,
    )
    params = init_params(config, jax.random.key(0))
    prompt = list(np.random.RandomState(5).randint(1, 128, size=50))
    n_new = 5
    # seq=2, model=2: per-shard H=4, Hkv=2 — both divisible by seq ✓
    mesh = build_mesh(MeshSpec(data=2, seq=2, expert=1, model=2))

    def run(sp_mode, ring_min):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=32, max_seq_len=128,
            prefill_chunk=16, ring_prefill_min_tokens=ring_min, sp_mode=sp_mode,
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        if sp_mode == "ulysses" and ring_min <= len(prompt):
            assert eng.sp_mode == "ulysses"  # no silent fallback in this shape
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return out

    ulysses_tokens = run("ulysses", ring_min=16)  # seq-sharded path engaged
    chunked_tokens = run("ring", ring_min=10_000)  # chunked on the same mesh
    assert ulysses_tokens == chunked_tokens

    # fallback: seq axis does not divide per-shard KV heads on this mesh
    bad_mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))
    ecfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=128,
        prefill_chunk=16, ring_prefill_min_tokens=16, sp_mode="ulysses",
    )
    eng = InferenceEngine(config, params, ecfg, mesh=bad_mesh)
    assert eng.sp_mode == "ring"  # Hkv/tp = 1 not divisible by seq=2


def test_scheduler_routes_long_prompts_through_ring_prefill():
    """The SERVING path (scheduler), not just the engine API, must engage
    the seq-sharded ring prefill for long prompts on a seq>1 mesh."""
    import asyncio

    import numpy as np

    from finchat_tpu.engine.engine import InferenceEngine
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.tokenizer import ByteTokenizer
    from finchat_tpu.utils.config import EngineConfig

    config = LlamaConfig(
        vocab_size=300, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=128, max_seq_len=128,
    )
    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))
    ecfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=64, max_seq_len=128,
        prefill_chunk=16, ring_prefill_min_tokens=32, warmup_on_start=False,
    )
    engine = InferenceEngine(config, init_params(config, jax.random.key(0)), ecfg, mesh=mesh)
    tok = ByteTokenizer()

    ring_calls: list[int] = []
    real_ring = engine.prefill_ring

    def spy_ring(slot, ids):
        ring_calls.append(len(ids))
        return real_ring(slot, ids)

    engine.prefill_ring = spy_ring

    async def run():
        scheduler = ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)
        await scheduler.start()
        try:
            long_prompt = tok.encode("x" * 60, add_bos=True)  # 61 >= 32
            handle = await scheduler.submit(
                "long", long_prompt, SamplingParams(temperature=0.0, max_new_tokens=4)
            )
            got = 0
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=120)
                if event["type"] == "token":
                    got += 1
                elif event["type"] == "done":
                    break
                else:
                    raise AssertionError(event)
            return got
        finally:
            await scheduler.stop()

    got = asyncio.run(run())
    assert got == 4
    assert ring_calls == [61], ring_calls


def test_ulysses_attention_matches_reference():
    """SURVEY §5.7d: Ulysses all-to-all SP == dense reference, MHA and GQA."""
    from finchat_tpu.ops.ulysses import ulysses_attention

    mesh = build_mesh(MeshSpec(data=2, seq=4, expert=1, model=1))
    B, S, D = 2, 64, 16
    for H, Hkv in ((8, 8), (8, 4)):
        kq, kk, kv = jax.random.split(jax.random.key(H), 3)
        q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)
        for causal in (True, False):
            out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
            ref = mha_reference(q, k, v, causal=causal)
            assert float(jnp.abs(out - ref).max()) < 1e-4, (H, Hkv, causal)


def test_ulysses_rejects_indivisible_heads():
    from finchat_tpu.ops.ulysses import ulysses_attention

    mesh = build_mesh(MeshSpec(data=1, seq=8, expert=1, model=1))
    q = jnp.zeros((1, 16, 4, 8))  # 4 heads, seq axis 8 -> indivisible
    with pytest.raises(ValueError, match="ring attention instead"):
        ulysses_attention(q, q, q, mesh=mesh)


def test_train_step_ulysses_sp():
    """The Ulysses SP mode trains: DP x SP(ulysses) x TP on the CPU mesh."""
    from finchat_tpu.parallel.sharding import llama_param_shardings, shard_params
    from finchat_tpu.train.train_step import (
        init_train_state, make_optimizer, make_train_step, shard_batch,
    )

    mesh = build_mesh(MeshSpec(data=2, seq=2, expert=1, model=2))
    config = LlamaConfig(
        vocab_size=64, dim=64, n_layers=2, n_heads=8, n_kv_heads=4,
        hidden_dim=64, max_seq_len=32,
    )  # per-TP-shard heads 4/2, divisible by seq=2
    params = shard_params(init_params(config, jax.random.key(0)), llama_param_shardings(mesh))
    optimizer = make_optimizer(learning_rate=1e-2)
    step = make_train_step(config, optimizer, mesh, use_ring_attention=True, sp_mode="ulysses")
    state = init_train_state(config, params, optimizer)
    tokens = shard_batch(
        jax.random.randint(jax.random.key(1), (4, 16), 0, 64), mesh, seq_sharded=True
    )
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses)))
    assert losses[-1] < losses[0], losses


def test_pipeline_forward_matches_plain():
    """C4 (SURVEY §2.3): the GPipe-style stage pipeline at pipe=2 computes
    the SAME function as the plain scanned forward, for every microbatch
    count (fill/drain schedule correctness). The data=2 and model=2 axes
    of this mesh partition IN-STAGE (r5): batch shards over data when
    n_micro divides, weights shard Megatron-style over model."""
    import numpy as np

    from finchat_tpu.models.llama import forward, make_causal_attention
    from finchat_tpu.parallel.pipeline import pipeline_forward, shard_params_for_pipeline

    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=2, pipe=2, seq=1, expert=1, model=2))
    params = init_params(config, jax.random.key(0))
    B, S = 4, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, 64)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    ref, _ = forward(params, tokens, positions, config=config,
                     attention=make_causal_attention("ref"))
    from jax.sharding import PartitionSpec as P

    sharded = shard_params_for_pipeline(params, mesh, config)
    # r5: the model=2 axis now actually partitions in-stage (Megatron
    # column/row shards + psum in the stage block), exercised here
    assert sharded["layers"]["attn_q"].sharding.spec == P("pipe", None, "model")
    assert sharded["layers"]["mlp_down"].sharding.spec == P("pipe", "model", None)
    for n_micro in (1, 2, 4):
        got = pipeline_forward(
            sharded, tokens, positions, config=config, mesh=mesh, n_micro=n_micro
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-4, rtol=1e-4,
            err_msg=f"n_micro={n_micro}",
        )

    # PER-ROW position offsets: each stage must use the positions of the
    # microbatch it currently holds, not microbatch 0's
    offsets = jnp.asarray([0, 3, 8, 1])[:, None]
    pos2 = offsets + jnp.arange(S)[None, :]
    ref2, _ = forward(params, tokens, pos2, config=config,
                      attention=make_causal_attention("ref"))
    got2 = pipeline_forward(
        sharded, tokens, pos2, config=config, mesh=mesh, n_micro=4
    )
    np.testing.assert_allclose(
        np.asarray(got2), np.asarray(ref2), atol=1e-4, rtol=1e-4,
        err_msg="per-row positions",
    )


def test_pipeline_sp_forward_matches_plain():
    """PP x SP: with a seq axis in the mesh the stage block ring-attends
    over seq-sharded activations (the ring body runs directly inside the
    all-manual region); the function computed must still equal the plain
    scanned forward — composed with in-stage TP (data=1 on this 8-device
    mesh; the 4-axis composition needs 16 devices and is covered by the
    subprocess run recorded in PERF_r05.md)."""
    import numpy as np

    from finchat_tpu.models.llama import forward, make_causal_attention
    from finchat_tpu.parallel.pipeline import pipeline_forward, shard_params_for_pipeline

    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=1, pipe=2, seq=2, expert=1, model=2))
    params = init_params(config, jax.random.key(0))
    B, S = 4, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, 64)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    ref, _ = forward(params, tokens, positions, config=config,
                     attention=make_causal_attention("ref"))
    sharded = shard_params_for_pipeline(params, mesh, config)
    for n_micro in (1, 2):
        got = pipeline_forward(
            sharded, tokens, positions, config=config, mesh=mesh, n_micro=n_micro
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=1e-4, rtol=1e-4,
            err_msg=f"n_micro={n_micro}",
        )


def test_pipeline_sp_train_step_learns():
    """PP x SP backward: scan + ppermute(pipe) + ring(seq) + psum(model)
    all transpose; loss decreases memorizing one tiny batch."""
    from finchat_tpu.parallel.pipeline import (
        make_pipeline_train_step, shard_params_for_pipeline,
    )
    from finchat_tpu.train.train_step import init_train_state, make_optimizer

    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32,
    )
    mesh = build_mesh(MeshSpec(data=1, pipe=2, seq=2, expert=1, model=2))
    params = shard_params_for_pipeline(init_params(config, jax.random.key(0)), mesh, config)
    optimizer = make_optimizer(learning_rate=1e-2)
    step = make_pipeline_train_step(config, optimizer, mesh, n_micro=2)
    state = init_train_state(config, params, optimizer)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses))), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.slow  # ~20 s: fresh-interpreter subprocess + 4-axis compile
def test_pipeline_four_axis_composition_subprocess():
    """pipe x data x seq x model ALL > 1 needs 16 devices — more than the
    conftest's 8-device mesh — so it runs in a fresh subprocess with its
    own 16-device virtual CPU mesh: forward equality vs the plain scanned
    forward, and a learning train step."""
    import os
    import subprocess
    import sys
    import textwrap

    script = textwrap.dedent("""
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp, numpy as np
        from finchat_tpu.models.llama import (
            LlamaConfig, init_params, forward, make_causal_attention,
        )
        from finchat_tpu.parallel.mesh import MeshSpec, build_mesh
        from finchat_tpu.parallel.pipeline import (
            pipeline_forward, shard_params_for_pipeline, make_pipeline_train_step,
        )
        from finchat_tpu.train.train_step import init_train_state, make_optimizer

        config = LlamaConfig(vocab_size=64, dim=32, n_layers=4, n_heads=4,
                             n_kv_heads=2, hidden_dim=64, max_seq_len=32,
                             dtype=jnp.float32)
        mesh = build_mesh(MeshSpec(data=2, pipe=2, seq=2, expert=1, model=2))
        params = init_params(config, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
        positions = jnp.broadcast_to(jnp.arange(16), (4, 16))
        ref, _ = forward(params, tokens, positions, config=config,
                         attention=make_causal_attention("ref"))
        sharded = shard_params_for_pipeline(params, mesh, config)
        got = pipeline_forward(sharded, tokens, positions, config=config,
                               mesh=mesh, n_micro=2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-4, rtol=1e-4)
        opt = make_optimizer(learning_rate=1e-2)
        step = make_pipeline_train_step(config, opt, mesh, n_micro=2)
        state = init_train_state(config, sharded, opt)
        losses = []
        for _ in range(4):
            state, loss = step(state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses
        print("FOUR_AXIS_OK")
    """)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    proc = subprocess.run(
        [sys.executable, "-c", script],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "FOUR_AXIS_OK" in proc.stdout


def test_pipeline_train_step_learns():
    """The pipelined train step backprops through the fill/drain schedule
    (scan + ppermute transpose): loss decreases memorizing one tiny batch."""
    from finchat_tpu.parallel.pipeline import (
        make_pipeline_train_step, shard_params_for_pipeline,
    )
    from finchat_tpu.train.train_step import init_train_state, make_optimizer

    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32,
    )
    mesh = build_mesh(MeshSpec(data=2, pipe=2, seq=1, expert=1, model=2))
    params = shard_params_for_pipeline(init_params(config, jax.random.key(0)), mesh, config)
    optimizer = make_optimizer(learning_rate=1e-2)
    step = make_pipeline_train_step(config, optimizer, mesh, n_micro=2)
    state = init_train_state(config, params, optimizer)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses))), losses
    assert losses[-1] < losses[0], losses


def test_70b_shardings_fit_v5p16_mesh_shapes():
    """BASELINE config 5 (70B on v5p-16): every llama3-70b param and
    decode-state dim divides the (data=2, model=8) 16-device mesh cleanly —
    no tensor would be forced to replicate (which _fit_sharding refuses
    above 256 MiB). Shape-level check via abstract arrays; no 70B weights
    are materialized."""
    import math

    from finchat_tpu.models.llama import PRESETS
    from finchat_tpu.parallel.mesh import make_abstract_mesh
    from finchat_tpu.parallel.sharding import llama_param_shardings

    config = PRESETS["llama3-70b"]
    # shape-only: an abstract 16-device v5p mesh (no fabricated devices)
    mesh = make_abstract_mesh(
        (2, 1, 1, 1, 8), ("data", "pipe", "seq", "expert", "model")
    )

    c = config
    L, D, H, Hkv, hd, F = (c.n_layers, c.dim, c.n_heads, c.n_kv_heads,
                           c.head_dim, c.hidden_dim)
    shapes = {
        "embed": (c.vocab_size, D),
        "layers": {
            "attn_q": (L, D, H * hd), "attn_k": (L, D, Hkv * hd),
            "attn_v": (L, D, Hkv * hd), "attn_o": (L, H * hd, D),
            "mlp_gate": (L, D, F), "mlp_up": (L, D, F), "mlp_down": (L, F, D),
            "ln_attn": (L, D), "ln_mlp": (L, D),
        },
        "norm": (D,),
        "lm_head": (D, c.vocab_size),
    }
    shardings = llama_param_shardings(mesh)

    def check(path, shape, ns, on_mesh=None):
        on_mesh = on_mesh or mesh
        parts = ns.spec if hasattr(ns, "spec") else ns  # NamedSharding | P
        spec = list(parts) + [None] * (len(shape) - len(parts))
        for dim, axes in zip(shape, spec):
            if axes is None:
                continue
            extent = math.prod(
                on_mesh.shape[a] for a in (axes if isinstance(axes, tuple) else (axes,))
            )
            assert dim % extent == 0, f"{path}: dim {dim} !% mesh {axes}={extent}"

    check("embed", shapes["embed"], shardings["embed"])
    for k, shape in shapes["layers"].items():
        check(f"layers/{k}", shape, shardings["layers"][k])
    check("norm", shapes["norm"], shardings["norm"])
    check("lm_head", shapes["lm_head"], shardings["lm_head"])

    # decode-state KV pages: fused Hkv*hd dim divides the model axis
    assert (Hkv * hd) % mesh.shape["model"] == 0
    # int8-KV scale rows shard head-aligned (Hkv % 8 == 0 at 70B)
    assert Hkv % 8 == 0

    # r5: the PIPELINE route to 70B — pipe=4 x model=4 on the same 16
    # chips, with in-stage Megatron TP. Every stage gets a whole number
    # of layers and every Megatron dim divides the in-stage TP extent.
    from jax.sharding import PartitionSpec as P

    from finchat_tpu.parallel.pipeline import _pipeline_layer_specs, _stage_tp

    pp_mesh = make_abstract_mesh(
        (1, 4, 1, 1, 4), ("data", "pipe", "seq", "expert", "model")
    )
    assert L % pp_mesh.shape["pipe"] == 0  # 80 layers / 4 stages
    tp = _stage_tp(config, pp_mesh)
    assert tp == 4  # in-stage TP actually engages at 70B shapes
    specs = _pipeline_layer_specs(shapes["layers"], tp)
    assert specs["attn_q"] == P("pipe", None, "model")
    assert specs["mlp_down"] == P("pipe", "model", None)
    for k, shape in shapes["layers"].items():
        check(f"pp layers/{k}", shape, specs[k], on_mesh=pp_mesh)


def test_tp_overlap_row_parallel_byte_identity():
    """TP collective-compute overlap (ops/tp_overlap.py): the chunked
    schedule — each output-column chunk's partial-sum psum issued as soon
    as its matmul retires — must be BYTE-identical to the serial
    matmul + one blocking psum at fp32 (each output element keeps the
    same full-K dot and the same single n-way collective reduction), and
    the overlap must be trace-visible (n_chunks psum eqns in the jaxpr —
    the dispatch evidence that the schedule actually engaged, not just a
    knob that fell back to serial)."""
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from finchat_tpu.ops.tp_overlap import row_parallel_dense

    mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    M, K, N, n_chunks = 8, 256, 128, 4
    x = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)

    def make(overlap):
        def local(x_l, w_l):
            return row_parallel_dense(x_l, w_l, "model",
                                      overlap=overlap, n_chunks=n_chunks)
        return shard_map(local, mesh=mesh,
                         in_specs=(P(None, "model"), P("model", None)),
                         out_specs=P(None, None))

    serial = make(False)(x, w)
    overlapped = make(True)(x, w)
    # fp32: byte-identical, not allclose — the contract the manual-TP
    # stage path's bit-identical-to-unsharded guarantee rests on
    np.testing.assert_array_equal(np.asarray(serial), np.asarray(overlapped))

    # bf16: envelope-bounded (chunking still never touches an element's
    # K-reduction, so this holds tight; the pinned contract is fp32)
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    sb = np.asarray(make(False)(xb, wb), np.float32)
    ob = np.asarray(make(True)(xb, wb), np.float32)
    np.testing.assert_allclose(ob, sb, rtol=2e-2, atol=2e-2)

    # trace evidence: the overlapped jaxpr carries n_chunks psum eqns,
    # the serial one exactly 1
    assert str(jax.make_jaxpr(make(True))(x, w)).count("psum") == n_chunks
    assert str(jax.make_jaxpr(make(False))(x, w)).count("psum") == 1


def test_tp_overlap_indivisible_falls_back_serial():
    """An output dim the chunk count does not divide must run the serial
    collective (with a warning), not crash or pad."""
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from finchat_tpu.ops.tp_overlap import row_parallel_dense

    mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    x = jax.random.normal(jax.random.key(2), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(3), (64, 30), jnp.float32)  # 30 % 4 != 0

    f = shard_map(
        lambda x_l, w_l: row_parallel_dense(x_l, w_l, "model",
                                            overlap=True, n_chunks=4),
        mesh=mesh, in_specs=(P(None, "model"), P("model", None)),
        out_specs=P(None, None))
    got = f(x, w)
    ref = shard_map(
        lambda x_l, w_l: row_parallel_dense(x_l, w_l, "model"),
        mesh=mesh, in_specs=(P(None, "model"), P("model", None)),
        out_specs=P(None, None))(x, w)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_pipeline_forward_tp_overlap_matches_serial():
    """The whole manual-TP stage path under the overlap knob: pipeline
    forward with tp_overlap=True is byte-identical at fp32 to the serial
    schedule (engine.tp_overlap / FINCHAT_TP_OVERLAP gate this in
    serving; default off keeps the serial psum as the reference)."""
    import numpy as np

    from finchat_tpu.parallel.pipeline import (
        pipeline_forward,
        shard_params_for_pipeline,
    )

    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32, dtype=jnp.float32,
    )
    mesh = build_mesh(MeshSpec(data=2, pipe=2, seq=1, expert=1, model=2))
    params = init_params(config, jax.random.key(0))
    B, S = 4, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, 64)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    sharded = shard_params_for_pipeline(params, mesh, config)

    serial = pipeline_forward(
        sharded, tokens, positions, config=config, mesh=mesh, n_micro=2)
    overlapped = pipeline_forward(
        sharded, tokens, positions, config=config, mesh=mesh, n_micro=2,
        tp_overlap=True, tp_chunks=4)
    np.testing.assert_array_equal(np.asarray(serial), np.asarray(overlapped))
