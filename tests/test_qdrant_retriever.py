"""External Qdrant backend contract tests (tools/qdrant_retriever.py).

Mirrors test_retrieval.py's security invariants against a FAKED client
(no qdrant-client / no network): the filter the backend receives — not
just the post-hoc re-check — must enforce user isolation, because the
reference treats the server-side must-filter as the security boundary
(qdrant_tool.py:105-112) and the re-check as defense in depth.
"""

from types import SimpleNamespace

import pytest

from finchat_tpu.tools.qdrant_retriever import QdrantRetriever

NOW = 1_700_000_000.0


class FakeEncoder:
    def embed_query(self, text):
        return [0.1, 0.2, 0.3]

    def embed_batch(self, texts):
        return [[0.1 * (i + 1)] * 3 for i in range(len(texts))]


def _hit(user_id, content, **metadata):
    return SimpleNamespace(
        payload={"page_content": content,
                 "metadata": {"user_id": user_id, **metadata}}
    )


class FakeClient:
    """Records calls; serves canned hits, honoring the must-filter the
    way the real service would (so filter bugs fail the test)."""

    def __init__(self, hits=()):
        self.hits = list(hits)
        self.query_calls = []
        self.upsert_calls = []
        self.raise_on_query = None

    def query_points(self, *, collection_name, query, limit, query_filter,
                     search_params, with_payload):
        self.query_calls.append(dict(
            collection_name=collection_name, query=query, limit=limit,
            query_filter=query_filter, search_params=search_params,
            with_payload=with_payload,
        ))
        if self.raise_on_query:
            raise self.raise_on_query
        out = []
        for h in self.hits:
            meta = h.payload["metadata"]
            ok = True
            for cond in query_filter["must"]:
                field = cond["key"].split(".", 1)[1]
                if "match" in cond and meta.get(field) != cond["match"]["value"]:
                    ok = False
                if "range" in cond and not meta.get(field, 0) >= cond["range"]["gte"]:
                    ok = False
            if ok:
                out.append(h)
        return SimpleNamespace(points=out[: int(limit)])

    def upsert(self, *, collection_name, points):
        self.upsert_calls.append(dict(collection_name=collection_name, points=points))


def make(hits=(), **kw):
    client = FakeClient(hits)
    r = QdrantRetriever(FakeEncoder(), client=client, collection="transactions",
                        now=lambda: NOW, **kw)
    return r, client


ALICE_HITS = [
    _hit("alice", "GROCERY OUTLET $54.12", date=NOW - 86400 * 40),
    _hit("alice", "RENT PAYMENT $2000", date=NOW - 86400 * 5),
    _hit("alice", "COFFEE SHOP $4.50", date=NOW - 86400 * 1),
    _hit("bob", "BOB'S SECRET PURCHASE $999", date=NOW - 100),
]


async def test_empty_user_id_returns_empty_without_backend_call():
    r, client = make(ALICE_HITS)
    assert await r({"search_query": "anything"}) == []
    assert await r({"user_id": "", "search_query": "anything"}) == []
    assert client.query_calls == []  # the backend is never even asked


async def test_user_isolation_via_must_filter():
    r, client = make(ALICE_HITS)
    hits = await r({"user_id": "alice", "search_query": "purchases"})
    assert len(hits) == 3
    assert all("BOB" not in h for h in hits)
    [call] = client.query_calls
    assert {"key": "metadata.user_id", "match": {"value": "alice"}} in call["query_filter"]["must"]
    assert call["collection_name"] == "transactions"
    assert call["with_payload"] is True


async def test_time_period_filter_becomes_date_range():
    r, client = make(ALICE_HITS)
    hits = await r({"user_id": "alice", "search_query": "p", "time_period_days": 7})
    assert len(hits) == 2  # 40-day-old grocery txn filtered out
    assert not any("GROCERY" in h for h in hits)
    [call] = client.query_calls
    range_conds = [c for c in call["query_filter"]["must"] if "range" in c]
    assert range_conds == [{"key": "metadata.date",
                            "range": {"gte": int(NOW - 7 * 86_400)}}]


async def test_limits():
    r, client = make(ALICE_HITS)
    assert len(await r({"user_id": "alice", "search_query": "p",
                        "num_transactions": 1})) == 1
    await r({"user_id": "alice", "search_query": "p", "num_transactions": None})
    assert client.query_calls[-1]["limit"] == 10_000  # qdrant_tool.py:145


async def test_posthoc_recheck_skips_mismatched_hits():
    """Even when the service misbehaves (returns another user's rows
    despite the filter), the re-check drops them (qdrant_tool.py:159-170)."""
    r, client = make(ALICE_HITS)
    client.query_points = lambda **kw: SimpleNamespace(points=ALICE_HITS)
    hits = await r({"user_id": "alice", "search_query": "p"})
    assert len(hits) == 3 and all("BOB" not in h for h in hits)


async def test_exception_returns_empty_list():
    r, client = make(ALICE_HITS)
    client.raise_on_query = ConnectionError("qdrant down")
    assert await r({"user_id": "alice", "search_query": "p"}) == []


async def test_structured_rows_carry_metadata():
    r, _ = make(ALICE_HITS)
    rows = await r.structured({"user_id": "alice", "search_query": "p"})
    assert all(row["user_id"] == "alice" and "page_content" in row and "date" in row
               for row in rows)


def test_upsert_payload_shape_and_stable_ids():
    r, client = make()
    r.upsert_transactions("alice", ["A $1", "B $2"], dates=[NOW, NOW],
                          metadatas=[{"amount": -1.0}, {"amount": -2.0}])
    [call] = client.upsert_calls
    assert call["collection_name"] == "transactions"
    p0, p1 = call["points"]
    assert p0["payload"]["page_content"] == "A $1"
    assert p0["payload"]["metadata"] == {"amount": -1.0, "user_id": "alice", "date": NOW}
    assert p0["id"] != p1["id"]
    # stable identity: re-upserting the same row produces the same id
    r.upsert_transactions("alice", ["A $1"], dates=[NOW])
    assert client.upsert_calls[-1]["points"][0]["id"] == p0["id"]


def test_build_app_selects_qdrant_backend(monkeypatch):
    """QDRANT_URL flips the backend; the serve-time warning is gone."""
    import asyncio

    from finchat_tpu.serve.app import build_app
    from finchat_tpu.tools.qdrant_retriever import QdrantRetriever as QR
    import finchat_tpu.tools.qdrant_retriever as qr_mod

    built = {}

    def fake_init(self, encoder, **kw):
        built.update(kw)
        self.client = FakeClient()
        self.encoder = encoder
        self.collection = kw.get("collection", "transactions")
        self.default_limit = kw.get("default_limit", 10_000)
        self.now = __import__("time").time

    monkeypatch.setattr(qr_mod.QdrantRetriever, "__init__", fake_init)
    from finchat_tpu.utils.config import AppConfig

    cfg = AppConfig()
    cfg.model.preset = "stub"
    cfg.vector.url = "http://qdrant.example:6333"
    cfg.vector.api_key = "k"
    app = build_app(cfg)
    assert isinstance(app.retriever, QR)
    assert built["url"] == "http://qdrant.example:6333"
    assert built["collection"] == "transactions"
    # ingestion path works against the external backend (no .index, no
    # snapshot — _persist_index must no-op, not crash)
    n = app._ingest_rows("alice", [{"text": "X $9"}])
    assert n == 1
    assert len(app.retriever.client.upsert_calls) == 1
