"""Durable serving state (ISSUE 7; ROBUSTNESS.md §5).

Contracts pinned here:

- disk spill tier: record-file round trips are BYTE-IDENTICAL to the RAM
  tier (token ids and every snapshot array), a restarted scheduler resumes
  a conversation from disk with the same greedy output and resume depth as
  the RAM tier would give, the tier's own LRU honors its byte budget, and
  the startup sweep deletes write orphans and quarantines bad records;
- fault sites (``disk.spill`` / ``disk.restore`` / ``journal.append``):
  a corrupt, truncated, or fault-injected record is quarantined and the
  conversation cold-starts — never a crash, never stale KV — and a failed
  spill or journal append never fails the serving path;
- answered-message journal: answered ids replay into the dedupe ring at
  restart (redelivered answered message refused), failed ids are never
  journaled (producer retry reprocessed), corrupt/torn records are
  skipped without losing the intact ones;
- memory-broker offset persistence: a fresh broker with the same offsets
  dir rewinds to the committed watermark (only uncommitted records
  redeliver), clamping with a warning when the fresh log is shorter;
- graceful shutdown drain: in-flight streams complete (or stragglers fail
  with a retryable ``shutting_down`` error), session bytes spill to disk,
  and the scheduler exits with zero slot/page leaks.
"""

import asyncio
import dataclasses
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.engine.session_cache import SessionDiskTier
from finchat_tpu.io.journal import AnsweredJournal
from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient, Message
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.utils import faults
from finchat_tpu.utils.config import (
    AI_RESPONSE_TOPIC,
    USER_MESSAGE_TOPIC,
    EngineConfig,
    load_config,
)
from finchat_tpu.utils.metrics import METRICS

CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
PAGE = 8
CHUNK = 16


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.disarm_all()


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _make_scheduler(params, disk_path=None, disk_bytes=64 << 20,
                    session_bytes=32 << 20):
    cfg = EngineConfig(
        max_seqs=4, page_size=PAGE, num_pages=128, max_seq_len=256,
        prefill_chunk=CHUNK, session_cache=True,
        session_cache_bytes=session_bytes,
        session_cache_disk_path=str(disk_path) if disk_path else "",
        session_cache_disk_bytes=disk_bytes,
    )
    return ContinuousBatchingScheduler(
        InferenceEngine(CONFIG, params, cfg), eos_id=-1
    )


async def _collect(scheduler, seq_id, prompt_ids, n_new, conversation_id=None):
    handle = await scheduler.submit(
        seq_id, prompt_ids,
        SamplingParams(temperature=0.0, max_new_tokens=n_new),
        conversation_id=conversation_id,
    )
    tokens = []
    while True:
        event = await asyncio.wait_for(handle.events.get(), timeout=120)
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return handle, tokens
        else:
            return handle, event


# --- disk tier: record format, byte identity, LRU, sweep ------------------

def test_disk_record_roundtrip_byte_identity(tmp_path):
    tier = SessionDiskTier(str(tmp_path), 1 << 20)
    tok = np.arange(24, dtype=np.int32)
    snap = (
        np.arange(96, dtype=np.float32).reshape(2, 3, 16),
        np.full((2, 3, 16), 7.5, np.float32),
        None, None,  # bf16/int8-less cache: no scale planes
    )
    assert tier.spill("c1#resp", tok, 8, snap)
    payload = tier.load("c1#resp")
    assert np.array_equal(payload["token_ids"], tok)
    assert payload["token_ids"].dtype == np.int32
    assert payload["prefix_len"] == 8
    assert payload["snap"][0].tobytes() == snap[0].tobytes()
    assert payload["snap"][1].tobytes() == snap[1].tobytes()
    assert payload["snap"][2] is None and payload["snap"][3] is None
    # a None snap (prefix-only entry) round-trips too
    assert tier.spill("c2#resp", tok[:8], 8, None)
    p2 = tier.load("c2#resp")
    assert p2["snap"] is None and np.array_equal(p2["token_ids"], tok[:8])


def test_disk_tier_lru_budget(tmp_path):
    tier = SessionDiskTier(str(tmp_path), budget_bytes=1 << 20)
    snap = (np.zeros((2, 4, 64), np.float32), np.zeros((2, 4, 64), np.float32),
            None, None)  # ~4 KiB per record
    record_size = len(SessionDiskTier._serialize("k", np.arange(8, dtype=np.int32), 0, snap))
    tier.budget_bytes = int(2.5 * record_size)
    for i in range(4):
        assert tier.spill(f"conv{i}", np.arange(8, dtype=np.int32), 0, snap)
    tier.flush()  # write-behind: evictions land on the writer thread
    # budget holds ~2.5 records: the two oldest evicted
    assert len(tier) == 2
    assert tier.resident_bytes <= tier.budget_bytes
    assert "conv0" not in tier and "conv1" not in tier
    assert tier.load("conv3") is not None
    # a loaded (LRU-refreshed) record survives the next spill's eviction
    tier.load("conv2")
    tier.spill("conv4", np.arange(8, dtype=np.int32), 0, snap)
    tier.flush()
    assert "conv2" in tier


def test_disk_tier_startup_sweep_orphans_and_corruption(tmp_path):
    tier = SessionDiskTier(str(tmp_path), 1 << 20)
    snap = (np.ones((2, 2, 8), np.float32), np.ones((2, 2, 8), np.float32),
            None, None)
    tier.spill("good", np.arange(16, dtype=np.int32), 0, snap)
    tier.spill("truncated", np.arange(16, dtype=np.int32), 0, snap)
    tier.flush()  # both records must be on disk before we tamper/sweep
    # crash leftovers: a partial .tmp write and a truncated record
    (tmp_path / "deadbeef.skv.tmp").write_bytes(b"partial")
    trunc = tmp_path / SessionDiskTier._fname("truncated")
    trunc.write_bytes(trunc.read_bytes()[:-7])
    swept = SessionDiskTier(str(tmp_path), 1 << 20)
    assert "good" in swept and len(swept) == 1
    assert not list(tmp_path.glob("*.tmp"))
    assert list(tmp_path.glob("*.quarantine"))
    assert swept.load("good") is not None
    assert swept.load("truncated") is None


# --- crash-restart resume: byte identity vs the RAM tier ------------------

def test_spill_restore_byte_identity_vs_ram_tier(tmp_path, params):
    """A restarted scheduler (fresh RAM tier, same disk dir) must resume a
    conversation exactly as deep as the RAM tier would have, with
    byte-identical greedy output."""
    t1 = list(range(1, 14))

    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        await sched.start()
        _, toks1 = await _collect(sched, "a-t1", t1, 8, conversation_id="convA")
        t2 = t1 + toks1 + [7, 8, 9]
        h_ram, toks2_ram = await _collect(sched, "a-t2", t2, 8,
                                          conversation_id="convA")
        await sched.stop()
        sched.session_cache.disk.flush()  # a real crash-to-restart gap
        # "crash": new scheduler, same disk dir — the RAM tier is gone
        sched2 = _make_scheduler(params, tmp_path / "disk")
        assert sched2.session_cache.get("convA") is None
        await sched2.start()
        h_disk, toks2_disk = await _collect(sched2, "b-t2", t2, 8,
                                            conversation_id="convA")
        await sched2.stop()
        assert h_disk.resumed_len == h_ram.resumed_len > 0
        assert toks2_disk == toks2_ram
        sched2.allocator.check_invariants()

    asyncio.run(run())


def test_corrupt_record_quarantined_cold_start(tmp_path, params):
    """A bit-flipped record is quarantined at restore time: the stream
    COLD-starts (no stale KV, no crash) and still produces the same greedy
    output."""
    t1 = list(range(1, 14))

    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        await sched.start()
        _, toks1 = await _collect(sched, "a-t1", t1, 8, conversation_id="convB")
        await sched.stop()
        sched.session_cache.disk.flush()
        t2 = t1 + toks1 + [7, 8, 9]
        # corrupt the record's payload
        f = (tmp_path / "disk") / SessionDiskTier._fname("convB")
        raw = bytearray(f.read_bytes())
        raw[-1] ^= 0xFF
        f.write_bytes(bytes(raw))
        q0 = METRICS.get("finchat_durability_quarantines_total")
        sched2 = _make_scheduler(params, tmp_path / "disk")
        await sched2.start()
        h, toks2 = await _collect(sched2, "b-t2", t2, 8, conversation_id="convB")
        await sched2.stop()
        assert h.resumed_len == 0  # cold start, not stale KV
        assert METRICS.get("finchat_durability_quarantines_total") == q0 + 1
        assert list((tmp_path / "disk").glob("*.quarantine"))
        # cold output is the golden output (session cache on/off identity)
        sched3 = _make_scheduler(params, None)
        await sched3.start()
        _, toks_cold = await _collect(sched3, "c-t2", t2, 8)
        await sched3.stop()
        assert toks2 == toks_cold

    asyncio.run(run())


def test_queued_spill_is_visible_before_it_lands(tmp_path):
    """Membership must see QUEUED writes, not only landed records: a
    just-spilled, RAM-evicted entry would otherwise read as absent at the
    restore gate and cold-start — the warm-resume feature silently failing
    exactly in the busy-disk window. And ``load`` must barrier on a queued
    write (or discard) of ITS key — but only its key's, not the whole
    queue."""
    import threading as _threading

    tier = SessionDiskTier(str(tmp_path), 1 << 20)
    gate = _threading.Event()
    tier._writer.submit(gate.wait)  # wedge the writer: writes stay queued
    snap = (np.ones((2, 2, 8), np.float32), np.ones((2, 2, 8), np.float32),
            None, None)
    try:
        tier.spill("convQ", np.arange(16, dtype=np.int32), 0, snap)
        assert "convQ" in tier          # queued, not yet landed
        assert len(tier) == 0           # the index itself only holds landed
        assert not list(tmp_path.glob("*.skv"))
    finally:
        gate.set()
    payload = tier.load("convQ")        # barriers on the pending write
    assert payload is not None and "convQ" in tier and len(tier) == 1
    # a queued discard is pending-visible the same way; load observes it
    wedge = _threading.Event()
    tier._writer.submit(wedge.wait)
    tier.discard("convQ")
    wedge.set()
    assert tier.load("convQ") is None
    assert "convQ" not in tier


def test_over_budget_record_trims_to_partial_warm_resume(tmp_path, params):
    """A disk record bigger than the restarted process's RAM budget is
    TRIMMED to the page-whole prefix that fits — a partial warm resume —
    instead of being refused by ``put`` on every turn (full record read +
    rewrite churn that never warms anything)."""
    t1 = list(range(1, 14))

    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        await sched.start()
        _, toks1 = await _collect(sched, "a-t1", t1, 8, conversation_id="convO")
        await sched.stop()
        sched.session_cache.disk.flush()
        entry = sched.session_cache.get("convO")
        own_pages = (entry.n_tokens - entry.prefix_len) // PAGE
        assert own_pages >= 2
        per_page = entry.nbytes // own_pages
        t2 = t1 + toks1 + [7, 8, 9]
        # restart with a RAM budget that fits only ONE of the record's pages
        sched2 = _make_scheduler(params, tmp_path / "disk",
                                 session_bytes=per_page + per_page // 2)
        await sched2.start()
        h, toks2 = await _collect(sched2, "b-t2", t2, 8,
                                  conversation_id="convO")
        await sched2.stop()
        assert 0 < h.resumed_len <= PAGE  # trimmed: warm, just shallower
        # trimming never changes the output (same identity contract as
        # divergence truncation)
        sched3 = _make_scheduler(params, None)
        await sched3.start()
        _, toks_cold = await _collect(sched3, "c-t2", t2, 8)
        await sched3.stop()
        assert toks2 == toks_cold
        sched2.allocator.check_invariants()

    asyncio.run(run())


def test_restore_skips_redundant_respill(tmp_path, params):
    """A disk restore must not rewrite the record it just read: the bytes
    are already on disk, so a write-through from the restore path would
    double every fall-through's I/O for nothing."""
    t1 = list(range(1, 14))

    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        await sched.start()
        await _collect(sched, "a-t1", t1, 8, conversation_id="convP")
        await sched.stop()
        sched.session_cache.disk.flush()
        sched2 = _make_scheduler(params, tmp_path / "disk")
        s0 = METRICS.get("finchat_durability_spills_total")
        assert sched2._restore_session_from_disk("convP")
        sched2.session_cache.disk.flush()
        assert METRICS.get("finchat_durability_spills_total") == s0
        assert sched2.session_cache.get("convP") is not None

    asyncio.run(run())


@pytest.mark.no_stall_sanitizer  # app construction + start run inline in
# the test body as ONE loop step (cold embed-encoder compile, seconds on
# CPU) — startup path, the same class the R1 STARTUP_ROOTS exclusion
# blesses; nothing here exercises the serving loop the sanitizer guards
async def test_drain_stops_fleet_supervisor_before_scheduler_drain(tmp_path):
    """The graceful drain must take the fleet supervisor down BEFORE the
    per-replica shutdown drains: a respawn's device rebuild racing
    ``shutdown_drain`` on the same engine could corrupt allocator/slot
    state and defeat the zero-leak exit."""
    app, _broker = _stub_app(tmp_path)
    await app.start(serve_http=False)
    order = []

    class FakeSched:
        async def shutdown_drain(self):
            order.append("shutdown_drain")

    class FakeRep:
        scheduler = FakeSched()

    class FakeFleet:
        replicas = [FakeRep()]

        async def stop_supervisor(self):
            order.append("stop_supervisor")

        async def stop(self):
            order.append("fleet_stop")

    app.fleet = FakeFleet()
    await app.drain_and_stop()
    assert order == ["stop_supervisor", "shutdown_drain", "fleet_stop"]


# --- fault sites (ISSUE 7 satellite) --------------------------------------

def test_disk_spill_fault_never_fails_stream(tmp_path, params):
    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        faults.arm("disk.spill", faults.one_shot(RuntimeError("disk full")))
        f0 = METRICS.get("finchat_durability_spill_failures_total")
        await sched.start()
        h, toks = await _collect(sched, "s1", list(range(1, 14)), 8,
                                 conversation_id="convF")
        await sched.stop()
        sched.session_cache.disk.flush()  # the failure lands off-loop
        assert len(toks) == 8  # the stream retired normally
        assert METRICS.get("finchat_durability_spill_failures_total") == f0 + 1
        assert "convF" not in sched.session_cache.disk
        # the RAM entry is still there — only the durability write failed
        assert sched.session_cache.get("convF") is not None

    asyncio.run(run())


def test_disk_restore_fault_quarantines_and_cold_starts(tmp_path, params):
    t1 = list(range(1, 14))

    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        await sched.start()
        _, toks1 = await _collect(sched, "a-t1", t1, 8, conversation_id="convR")
        await sched.stop()
        sched.session_cache.disk.flush()
        sched2 = _make_scheduler(params, tmp_path / "disk")
        assert "convR" in sched2.session_cache.disk
        faults.arm("disk.restore", faults.one_shot(RuntimeError("read error")))
        q0 = METRICS.get("finchat_durability_quarantines_total")
        await sched2.start()
        h, toks2 = await _collect(sched2, "b-t2", t1 + toks1 + [7, 8, 9], 8,
                                  conversation_id="convR")
        await sched2.stop()
        assert len(toks2) == 8 and h.resumed_len == 0  # cold, never stale
        # the unreadable record was quarantined; the cold turn's own
        # retirement then write-through-spilled a FRESH record
        assert METRICS.get("finchat_durability_quarantines_total") == q0 + 1
        assert list((tmp_path / "disk").glob("*.quarantine"))

    asyncio.run(run())


def test_journal_append_fault_logs_and_continues(tmp_path):
    journal = AnsweredJournal(str(tmp_path))
    faults.arm("journal.append", faults.one_shot(RuntimeError("disk full")))
    f0 = METRICS.get("finchat_durability_journal_append_failures_total")
    assert journal.append("m1") is False
    assert METRICS.get("finchat_durability_journal_append_failures_total") == f0 + 1
    assert journal.append("m2") is True
    assert AnsweredJournal(str(tmp_path)).replay() == ["m2"]


# --- answered-message journal ---------------------------------------------

def test_journal_replay_compacts_and_skips_corrupt_records(tmp_path):
    journal = AnsweredJournal(str(tmp_path), keep=3)
    for mid in ("m1", "m2", "m3", "m1", 42):
        journal.append(mid)
    journal.close()
    # torn tail (crash mid-append) + a corrupt middle record
    with open(journal._part_path(0), "r+b") as f:
        raw = f.read()
        lines = raw.split(b"\n")
        lines[1] = b"v1 00000000 " + lines[1].split(b" ", 2)[2]  # bad crc
        f.seek(0)
        f.write(b"\n".join(lines) + b"v1 deadbe")  # torn final line
        f.truncate()
    replayed = AnsweredJournal(str(tmp_path), keep=3).replay()
    # m2 corrupted away; keep=3 most recent distinct of [m1, m3, m1, 42]
    assert replayed == ["m3", "m1", 42]
    # the compacted file replays identically (idempotent)
    assert AnsweredJournal(str(tmp_path), keep=3).replay() == ["m3", "m1", 42]


def _stub_app(tmp_path, broker=None, fail=False):
    from finchat_tpu.engine.generator import StubGenerator
    from finchat_tpu.io.store import InMemoryStore
    from finchat_tpu.serve.app import build_app

    cfg = load_config(overrides={"model.preset": "stub"})
    cfg.kafka.commit_after_process = True
    cfg.journal.path = str(tmp_path / "journal")
    broker = broker or InMemoryBroker()
    store = InMemoryStore()
    store.upsert_context("c1", {"user_id": "u9", "name": "Alex",
                                "income": 5000, "savings_goal": 800})
    store.add_user_message("c1", "How am I doing?", "u9")
    app = build_app(
        cfg, store=store, kafka=KafkaClient(cfg.kafka, broker=broker),
        tool_generator=StubGenerator(default="No tool call"),
        response_generator=StubGenerator(
            default="You are doing fine.",
            fail_with="boom" if fail else None,
        ),
    )
    return app, broker


def _kafka_msg(payload, offset=0):
    return Message(USER_MESSAGE_TOPIC, payload["conversation_id"],
                   json.dumps(payload).encode(), offset=offset, partition=0)


async def test_answered_id_journaled_before_commit_and_replayed(tmp_path):
    """The fsync-before-commit ordering end-to-end: an ANSWERED message's
    id is on disk by the time its offset commits, a restarted app replays
    it into the dedupe ring, and the redelivered message is skipped —
    zero double answers across a crash."""
    app, broker = _stub_app(tmp_path)
    committed = []
    app.kafka.commit_offset = (
        lambda t, p, n: committed.append(
            (tmp_path / "journal" / "answered-p0000.journal").read_bytes()
        )
    )
    payload = {"message": "How am I doing?", "conversation_id": "c1",
               "user_id": "u9", "message_id": "mid-1"}
    msg = _kafka_msg(payload)
    app._note_message_polled(msg)
    app._spawn_message_task(msg)
    await asyncio.gather(*app._inflight)
    await asyncio.sleep(0)  # let the done-callback run
    # the journal bytes the commit observed already contained the id
    assert committed and b"mid-1" in committed[0]
    # restart: fresh ring, same journal — the id replays in
    app2, broker2 = _stub_app(tmp_path, broker=InMemoryBroker())
    assert "mid-1" in app2._seen_ids
    skips0 = METRICS.get("finchat_kafka_dedupe_skips_total")
    app2._spawn_message_task(_kafka_msg(payload))
    assert not app2._inflight  # redelivery refused, not reprocessed
    assert METRICS.get("finchat_kafka_dedupe_skips_total") == skips0 + 1
    assert [json.loads(m.value().decode())
            for m in broker2.drain(AI_RESPONSE_TOPIC)] == []


async def test_failed_id_never_journaled(tmp_path):
    """A FAILED message leaves no journal record: the restarted process
    reprocesses the producer's retry instead of black-holing it."""
    app, _broker = _stub_app(tmp_path, fail=True)
    payload = {"message": "How am I doing?", "conversation_id": "c1",
               "user_id": "u9", "message_id": "mid-f"}
    msg = _kafka_msg(payload)
    app._note_message_polled(msg)
    app._spawn_message_task(msg)
    await asyncio.gather(*app._inflight)
    await asyncio.sleep(0)
    app2, _b2 = _stub_app(tmp_path)
    assert "mid-f" not in app2._seen_ids


# --- memory-broker committed-offset persistence ---------------------------

def test_broker_offsets_persist_and_rewind(tmp_path):
    d = str(tmp_path)
    b1 = InMemoryBroker(offsets_dir=d)
    part = b1._partition_for("k")
    for i in range(3):
        b1.produce("t", "k", b"%d" % i)
    b1.join_group("g", "m1", ["t"], "earliest")
    for _ in range(3):
        assert b1.poll("g", "m1", ["t"], auto_commit=False) is not None
    b1.commit("g", "t", part, 2)  # first two processed; third uncommitted
    # "restart": fresh broker, same records re-produced, same offsets dir —
    # the group rewinds to the committed watermark, redelivering ONLY the
    # uncommitted tail
    b2 = InMemoryBroker(offsets_dir=d)
    for i in range(3):
        b2.produce("t", "k", b"%d" % i)
    b2.join_group("g", "m2", ["t"], "earliest")
    redelivered = []
    while True:
        m = b2.poll("g", "m2", ["t"], auto_commit=False)
        if m is None:
            break
        redelivered.append(m.offset())
    assert redelivered == [2]


def test_broker_persisted_offset_beyond_log_clamps(tmp_path, caplog):
    d = str(tmp_path)
    b1 = InMemoryBroker(offsets_dir=d)
    part = b1._partition_for("k")
    for i in range(3):
        b1.produce("t", "k", b"%d" % i)
    b1.join_group("g", "m1", ["t"], "earliest")
    b1.commit("g", "t", part, 3)
    # fresh broker holds FEWER records than the committed watermark
    b3 = InMemoryBroker(offsets_dir=d)
    b3.produce("t", "k", b"0")
    with caplog.at_level("WARNING"):
        b3.join_group("g", "m3", ["t"], "earliest")
    assert any("beyond the log" in r.message for r in caplog.records)
    assert b3.poll("g", "m3", ["t"], auto_commit=False) is None  # clamped


# --- graceful shutdown drain ----------------------------------------------

def test_shutdown_drain_straggler_zero_leaks_and_spill(tmp_path, params):
    """SIGTERM with a stream mid-decode: the straggler is preempted to
    host with a retryable ``shutting_down`` error, its coherent KV spills
    through the session tier to disk, and the scheduler exits with zero
    slot/page leaks."""

    async def run():
        sched = _make_scheduler(params, tmp_path / "disk")
        await sched.start()
        h = await sched.submit(
            "s1", list(range(1, 14)),
            SamplingParams(temperature=0.0, max_new_tokens=100),
            conversation_id="convS",
        )
        pending = await sched.submit(
            "s2", list(range(30, 44)),
            SamplingParams(temperature=0.0, max_new_tokens=100),
        )
        faults.arm("scheduler.decode", lambda **_: time.sleep(0.01))  # finchat-lint: disable=event-loop-blocking -- deliberate fault payload: simulates a slow device dispatch so the drain deterministically catches a straggler
        while h.generated < 3:
            await asyncio.sleep(0.005)
        await sched.shutdown_drain()
        events = []
        while not h.events.empty():
            events.append(h.events.get_nowait())
        err = [e for e in events if e["type"] == "error"]
        assert err and err[-1]["code"] == "shutting_down"
        assert err[-1]["retryable"] is True
        p_events = []
        while not pending.events.empty():
            p_events.append(pending.events.get_nowait())
        assert any(e.get("code") == "shutting_down" for e in p_events)
        # zero slot/page leaks
        assert sched.allocator.used_count == 0
        assert len(sched.free_slots) == 4
        assert not sched.decoding and not sched.prefilling and not sched.pending
        sched.allocator.check_invariants()
        # the straggler's coherent prompt+generated KV reached the disk tier
        assert "convS" in sched.session_cache.disk
        payload = sched.session_cache.disk.load("convS")
        n_coherent = ((13 + h.generated - 1) // PAGE) * PAGE
        assert payload["token_ids"].shape[0] == n_coherent

    asyncio.run(run())


async def test_app_drain_completes_inflight_within_deadline(tmp_path):
    """App-level graceful drain: an in-flight message COMPLETES (its
    answer and complete marker go out) before drain_and_stop returns, and
    new HTTP admission is refused with a retryable 503."""
    from finchat_tpu.serve.http import Request

    app, broker = _stub_app(tmp_path)
    app.cfg.shutdown.deadline_seconds = 30.0
    app.agent.response_generator.chunk_delay = 0.02
    await app.start(serve_http=False)
    producer = KafkaClient(app.cfg.kafka, broker=broker)
    producer.produce_message(
        USER_MESSAGE_TOPIC, "c1",
        {"message": "How am I doing?", "conversation_id": "c1",
         "user_id": "u9", "message_id": "mid-d"},
    )
    for _ in range(500):
        out = [json.loads(m.value().decode())
               for m in broker.drain(AI_RESPONSE_TOPIC)]
        if out:
            break
        await asyncio.sleep(0.01)
    assert out, "stream never started"
    d0 = METRICS.get("finchat_durability_graceful_drains_total")
    await app.drain_and_stop()
    assert METRICS.get("finchat_durability_graceful_drains_total") == d0 + 1
    out = [json.loads(m.value().decode())
           for m in broker.drain(AI_RESPONSE_TOPIC)]
    assert any(c.get("type") == "complete" for c in out)
    # admission is closed while draining
    app._draining = True
    resp = app._payload_error({"conversation_id": "c1", "message": "x",
                               "user_id": "u9"})
    assert resp is not None and resp.status == 503
