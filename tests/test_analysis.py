"""finchat-lint rule fixtures (ISSUE 8).

Every rule gets positive (flags the bug) and negative (passes the fixed
form) fixtures, including a reproduction of each historical bug the rule
is derived from:

- R1: the inline breaker-trip device rebuild on the event loop (fixed in
  this PR by moving it behind ``asyncio.to_thread``),
- R3: the ``_fail_prefix_job`` slot leak — an unguarded device op on a
  cleanup path ahead of the releases (fixed in PR 6; R3 now pins the
  whole class),
- R5: the fleet counter emitted through a replica's labeled view (caught
  in PR 6 review; the unlabeled-fleet-family convention is now
  mechanical).

Plus the framework itself: suppressions (line + scope + mandatory
justification), the shrink-only baseline, and the runtime sanitizers
(stall + leak).
"""

from __future__ import annotations

import asyncio
import textwrap
import time
from pathlib import Path

import pytest

from finchat_tpu.analysis.core import Finding, load_baseline, run_analysis, write_baseline


def _lint(tmp_path: Path, files: dict[str, str], rules: set[str] | None = None):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_analysis(tmp_path, [tmp_path], rule_filter=rules)


def _messages(result) -> list[str]:
    return [f.message for f in result.findings]


# ---------------------------------------------------------------------------
# R1 event-loop-blocking
# ---------------------------------------------------------------------------

INLINE_REBUILD = """
    import asyncio

    class Sched:
        async def _loop(self):
            try:
                await self._round()
            except Exception as e:
                self._round_failed(str(e))

        async def _round(self):
            pass

        def _round_failed(self, error):
            self._trip_breaker(error)

        def _trip_breaker(self, error):
            self.allocator.reset()
            self.engine.rebuild_device_state()
"""

OFF_LOOP_REBUILD = """
    import asyncio

    class Sched:
        async def _loop(self):
            try:
                await self._round()
            except Exception as e:
                await self._round_failed(str(e))

        async def _round(self):
            pass

        async def _round_failed(self, error):
            await self._trip_breaker(error)

        async def _trip_breaker(self, error):
            self.allocator.reset()
            await asyncio.to_thread(self.engine.rebuild_device_state)
"""


def test_r1_flags_inline_rebuild_reachable_from_async(tmp_path):
    """The historical bug: a breaker trip rebuilt the device state INLINE
    on the event loop every sibling replica shares."""
    res = _lint(tmp_path, {"sched.py": INLINE_REBUILD}, {"event-loop-blocking"})
    assert len(res.findings) == 1
    f = res.findings[0]
    assert "rebuild" in f.message and "_trip_breaker" in f.symbol
    assert "_loop" in f.message  # the chain names the async root


def test_r1_passes_to_thread_rebuild(tmp_path):
    """The fixed form: the rebuild runs in a worker thread (the callable
    is passed by reference — never an on-loop call edge)."""
    res = _lint(tmp_path, {"sched.py": OFF_LOOP_REBUILD}, {"event-loop-blocking"})
    assert res.findings == []


def test_r1_primitives_sleep_fsync_and_executor_join(tmp_path):
    src = """
        import os
        import time

        class W:
            async def handler(self):
                time.sleep(0.5)
                os.fsync(3)
                self.pool.submit(len, "x").result()
    """
    res = _lint(tmp_path, {"w.py": src}, {"event-loop-blocking"})
    msgs = " | ".join(_messages(res))
    assert "time.sleep" in msgs and "os.fsync" in msgs and "executor join" in msgs
    assert len(res.findings) == 3


def test_r1_transitive_chain_through_sync_helpers(tmp_path):
    src = """
        import os

        class Journal:
            def append(self, mid):
                os.fsync(3)

        class App:
            def __init__(self):
                self.journal = Journal()

            async def done(self):
                self.journal.append("m")
    """
    res = _lint(tmp_path, {"app.py": src}, {"event-loop-blocking"})
    assert len(res.findings) == 1
    assert "Journal.append" in res.findings[0].symbol
    assert "App.done" in res.findings[0].message


def test_r1_loop_callback_registration_is_a_root(tmp_path):
    src = """
        import time

        class App:
            async def spawn(self, task):
                def _done(t):
                    time.sleep(1)
                task.add_done_callback(_done)
    """
    res = _lint(tmp_path, {"cb.py": src}, {"event-loop-blocking"})
    assert len(res.findings) == 1
    assert "_done" in res.findings[0].symbol


def test_r1_off_loop_lambda_and_thread_args_are_exempt(tmp_path):
    src = """
        import asyncio
        import time

        class W:
            async def fetch(self):
                return await asyncio.to_thread(lambda: time.sleep(1))
    """
    res = _lint(tmp_path, {"ok.py": src}, {"event-loop-blocking"})
    assert res.findings == []


def test_r1_blocking_socket_liaison_is_flagged(tmp_path):
    """A pod liaison built on raw sockets stalls every in-flight stream
    for a peer's RTT: create_connection / sendall / recv / accept on an
    async path are all primitives."""
    src = """
        import socket

        class Liaison:
            async def call(self, addr, frame):
                conn = socket.create_connection(addr)
                conn.sendall(frame)
                return conn.recv(65536)

            async def serve(self, srv):
                conn, _peer = srv.accept()
                return conn
    """
    res = _lint(tmp_path, {"liaison.py": src}, {"event-loop-blocking"})
    msgs = " | ".join(_messages(res))
    assert "socket.create_connection" in msgs
    assert ".sendall()" in msgs and ".recv()" in msgs and ".accept()" in msgs
    assert len(res.findings) == 4


def test_r1_asyncio_stream_liaison_is_clean(tmp_path):
    """The blessed transport (serve/pod.py): asyncio streams — awaited
    open_connection / readexactly / write+drain never hit the socket
    primitives."""
    src = """
        import asyncio

        class Liaison:
            async def call(self, host, port, frame):
                reader, writer = await asyncio.open_connection(host, port)
                writer.write(frame)
                await writer.drain()
                raw = await reader.readexactly(9)
                writer.close()
                return raw
    """
    res = _lint(tmp_path, {"liaison.py": src}, {"event-loop-blocking"})
    assert res.findings == []


# ---------------------------------------------------------------------------
# R2 hot-path-host-sync
# ---------------------------------------------------------------------------

HOT_ITEM = """
    import jax.numpy as jnp
    import numpy as np

    def dispatch(state, active):  # finchat-lint: hot
        logits = jnp.ones((4, 8))
        token = logits.argmax()
        t = int(np.asarray(token))
        if token:
            pass
        return t
"""

HOT_CLEAN = """
    import asyncio
    import jax.numpy as jnp
    import numpy as np

    async def dispatch(state, active):  # finchat-lint: hot
        logits = jnp.ones((4, 8))
        token = logits.argmax()
        host = await asyncio.to_thread(lambda: np.asarray(token))
        n = logits.shape[0]
        if token is not None:
            pass
        return host, n
"""


def test_r2_flags_host_sync_on_device_values(tmp_path):
    res = _lint(tmp_path, {"hot.py": HOT_ITEM}, {"hot-path-host-sync"})
    msgs = " | ".join(_messages(res))
    assert "D2H" in msgs  # np.asarray on the tainted token
    assert "__bool__" in msgs  # if token:
    assert len(res.findings) == 2


def test_r2_passes_off_loop_fetch_and_host_metadata(tmp_path):
    """The blessed pattern: the fetch rides to_thread; .shape and
    ``is not None`` are host-side and never flagged."""
    res = _lint(tmp_path, {"hot.py": HOT_CLEAN}, {"hot-path-host-sync"})
    assert res.findings == []


def test_r2_item_and_block_until_ready_always_flag(tmp_path):
    src = """
        def kern(x):  # finchat-lint: hot
            a = x.item()
            x.block_until_ready()
            return a
    """
    res = _lint(tmp_path, {"k.py": src}, {"hot-path-host-sync"})
    assert len(res.findings) == 2


def test_r2_host_helpers_do_not_taint(tmp_path):
    """A hot-module function returning a host scalar must not taint its
    callers (the ops/ backend-name helpers were the false-positive class
    the returns-device inference exists for)."""
    src = """
        def backend_name():
            return "ref"

        def kern(x):  # finchat-lint: hot
            b = backend_name()
            if b == "ref":
                return 1
            return 2
    """
    res = _lint(tmp_path, {"k.py": src}, {"hot-path-host-sync"})
    assert res.findings == []


def test_r2_freerun_consume_check(tmp_path):
    """The freerun-consume extension (ISSUE 13): the free-running loop's
    ring-drain functions join the hot set BY NAME in engine/scheduler.py —
    a ``block_until_ready``, ``.item()``, D2H, or implicit ``__bool__`` on
    the ring re-serializes the host against the very capture the loop
    exists to overlap. The blessed off-loop ``to_thread`` fetch stays
    clean."""
    bad = """
        import jax.numpy as jnp
        import numpy as np

        class Sched:
            async def _consume_ring(self, ring):
                ring_tok = jnp.ones((4, 4))
                ring_tok.block_until_ready()
                n = np.asarray(ring_tok)
                if ring_tok:
                    pass
                return n
    """
    res = _lint(tmp_path, {"engine/scheduler.py": bad}, {"hot-path-host-sync"})
    msgs = " | ".join(_messages(res))
    assert "block_until_ready" in msgs
    assert "D2H" in msgs
    assert "__bool__" in msgs
    assert len(res.findings) == 3
    good = """
        import asyncio
        import jax.numpy as jnp
        import numpy as np

        class Sched:
            async def _consume_ring(self, ring):
                ring_tok = jnp.ones((4, 4))
                host = await asyncio.to_thread(lambda: np.asarray(ring_tok))
                return host

            async def _dispatch_freerun(self, rounds):
                ring = jnp.ones((4, 4))
                return ring
    """
    res = _lint(tmp_path, {"engine/scheduler.py": good}, {"hot-path-host-sync"})
    assert res.findings == []


def test_r2_cold_functions_not_hot(tmp_path):
    src = """
        import numpy as np
        import jax.numpy as jnp

        def helper(x):
            v = jnp.ones(3)
            return np.asarray(v)
    """
    res = _lint(tmp_path, {"cold.py": src}, {"hot-path-host-sync"})
    assert res.findings == []


# ---------------------------------------------------------------------------
# R3 resource-pairing
# ---------------------------------------------------------------------------

FAIL_PREFIX_JOB_BUG = """
    class Sched:
        def _fail_prefix_job(self, job):
            self._prefix_jobs.remove(job)
            self.allocator.free(job.owner, job.pages)
            self.engine.reset_slot(job.slot)
            self.free_slots.append(job.slot)
            job.future.set_result(0)
"""

FAIL_PREFIX_JOB_FIXED = """
    class Sched:
        def _fail_prefix_job(self, job):
            self._prefix_jobs.remove(job)
            self.allocator.free(job.owner, job.pages)
            try:
                self.engine.reset_slot(job.slot)
            except Exception:
                pass
            self.free_slots.append(job.slot)
            job.future.set_result(0)
"""


def test_r3_flags_unguarded_device_op_before_releases(tmp_path):
    """The historical ``_fail_prefix_job`` bug: a raising reset_slot
    skipped the slot return and the future resolution, hanging the
    awaiter forever (PR 6 review catch)."""
    res = _lint(tmp_path, {"s.py": FAIL_PREFIX_JOB_BUG}, {"resource-pairing"})
    assert len(res.findings) == 1
    assert "reset_slot" in res.findings[0].message
    assert "_fail_prefix_job" in res.findings[0].symbol


def test_r3_passes_guarded_cleanup(tmp_path):
    res = _lint(tmp_path, {"s.py": FAIL_PREFIX_JOB_FIXED}, {"resource-pairing"})
    assert res.findings == []


def test_r3_flags_device_op_in_finally_before_release(tmp_path):
    src = """
        class Sched:
            def register(self, ids):
                try:
                    self.engine.prefill(0, ids)
                finally:
                    self.engine.reset_slot(0)
                    self.free_slots.append(0)
    """
    res = _lint(tmp_path, {"s.py": src}, {"resource-pairing"})
    assert len(res.findings) == 1
    assert "reset_slot" in res.findings[0].message


def test_r3_flags_acquire_leaked_on_early_raise(tmp_path):
    src = """
        class Sched:
            def admit(self, n):
                pages = self.allocator.allocate("s", n)
                if n > 4:
                    raise RuntimeError("too big")
                self.allocator.free("s", pages)
    """
    res = _lint(tmp_path, {"s.py": src}, {"resource-pairing"})
    assert len(res.findings) == 1
    assert "pages" in res.findings[0].message and "raise" in res.findings[0].message


def test_r3_passes_escaped_or_released_acquires(tmp_path):
    src = """
        class Sched:
            def admit(self, handle, n):
                pages = self.allocator.allocate("s", n)
                handle.page_list = pages  # ownership transferred
                return handle

            def probe(self, n):
                pages = self.allocator.allocate("s", n)
                try:
                    self.check(pages)
                finally:
                    self.allocator.free("s", pages)
    """
    res = _lint(tmp_path, {"s.py": src}, {"resource-pairing"})
    assert res.findings == []


# ---------------------------------------------------------------------------
# R4 knob-consistency
# ---------------------------------------------------------------------------

MINI_CONFIG = """
    from dataclasses import dataclass, field

    def _env(name, default=""):
        return default

    def _env_int(name, default=0):
        return default

    @dataclass
    class EngineConfig:
        max_seqs: int = 64
        secret_knob: int = 3{secret_suppress}

    @dataclass
    class AppConfig:
        engine: EngineConfig = field(default_factory=EngineConfig)

    def load_config():
        cfg = AppConfig()
        cfg.engine.max_seqs = _env_int("FINCHAT_MAX_SEQS", cfg.engine.max_seqs)
        return cfg
"""

MINI_MAIN = """
    overrides = {}
    overrides["engine.max_seqs"] = 1
    overrides["engine.not_a_knob"] = 2
"""


def test_r4_readme_env_and_field_drift(tmp_path):
    files = {
        "utils/config.py": MINI_CONFIG.format(secret_suppress=""),
        "__main__.py": MINI_MAIN,
        "README.md": "docs without the env var",
    }
    res = _lint(tmp_path, files, {"knob-consistency"})
    msgs = " | ".join(_messages(res))
    assert "FINCHAT_MAX_SEQS" in msgs  # wired but not in README
    assert "secret_knob" in msgs  # field without env wiring
    assert "engine.not_a_knob" in msgs  # CLI flag drift
    assert len(res.findings) == 3


def test_r4_clean_when_docs_and_wiring_agree(tmp_path):
    files = {
        "utils/config.py": MINI_CONFIG.format(
            secret_suppress="  # finchat-lint: disable=knob-consistency -- file-only by design"
        ),
        "__main__.py": 'overrides = {}\noverrides["engine.max_seqs"] = 1\n',
        "README.md": "set `FINCHAT_MAX_SEQS` to bound concurrency",
    }
    res = _lint(tmp_path, files, {"knob-consistency"})
    assert res.findings == []
    assert len(res.suppressed) == 1


# ---------------------------------------------------------------------------
# R5 metrics-discipline
# ---------------------------------------------------------------------------

FLEET_LABELED_BUG = """
    from finchat_tpu.utils.metrics import METRICS

    class Sched:
        def __init__(self, replica_id):
            self.metrics = METRICS.labeled(replica=str(replica_id))

        def drain_failed(self):
            self.metrics.inc("finchat_fleet_drain_failures_total")
"""

FLEET_UNLABELED_FIXED = """
    from finchat_tpu.utils.metrics import METRICS

    class Sched:
        def __init__(self, replica_id):
            self.metrics = METRICS.labeled(replica=str(replica_id))

        def drain_failed(self):
            METRICS.inc("finchat_fleet_drain_failures_total")
"""


def test_r5_flags_fleet_counter_through_labeled_view(tmp_path):
    """The historical PR 6 catch: a fleet-family counter emitted through
    a replica's labeled view splits into per-replica series no dashboard
    sums."""
    res = _lint(
        tmp_path,
        {"finchat_tpu/sched.py": FLEET_LABELED_BUG},
        {"metrics-discipline"},
    )
    assert len(res.findings) == 1
    assert "finchat_fleet_drain_failures_total" in res.findings[0].message


def test_r5_passes_fleet_counter_on_global_registry(tmp_path):
    res = _lint(
        tmp_path,
        {"finchat_tpu/sched.py": FLEET_UNLABELED_FIXED},
        {"metrics-discipline"},
    )
    assert res.findings == []


def test_r5_naming_and_suffix_conventions(tmp_path):
    src = """
        from finchat_tpu.utils.metrics import METRICS

        def emit():
            METRICS.inc("finchat_things")            # counter without _total
            METRICS.inc("bad_name_total")            # missing finchat_ prefix
            METRICS.observe("finchat_lat_ms")        # histogram without _seconds
            METRICS.set_gauge("finchat_depth_total") # gauge with counter suffix
            METRICS.inc("finchat_good_total")        # fine
            METRICS.set_gauge("finchat_depth")       # fine
            METRICS.observe("finchat_step_seconds")  # fine
    """
    res = _lint(tmp_path, {"finchat_tpu/m.py": src}, {"metrics-discipline"})
    assert len(res.findings) == 4


def test_r5_mixed_labeled_unlabeled_family(tmp_path):
    src = """
        from finchat_tpu.utils.metrics import METRICS

        def a():
            METRICS.inc("finchat_x_total", labels={"k": "v"})

        def b():
            METRICS.inc("finchat_x_total")
    """
    res = _lint(tmp_path, {"finchat_tpu/m.py": src}, {"metrics-discipline"})
    assert any("both with and without" in m for m in _messages(res))


# ---------------------------------------------------------------------------
# R5 span discipline (ISSUE 12)
# ---------------------------------------------------------------------------

_MINI_TRACING = """
    SPAN_MARKS = frozenset({"admitted", "first_token", "done"})
    TRACE_EVENTS = frozenset({"dispatch", "ingress"})
    ANOMALY_KINDS = frozenset({"breaker_trip", "shed"})
"""


def test_r5_span_mark_must_be_registered(tmp_path):
    """A typo'd mark name silently vanishes from every timeline — the
    span-discipline check catches it statically against SPAN_MARKS."""
    src = """
        class H:
            def go(self, handle):
                handle.span.mark("admited")      # typo: flagged
                handle.span.mark("admitted")     # registered: fine
                self.span.mark("first_token")    # registered: fine
    """
    res = _lint(
        tmp_path,
        {"finchat_tpu/utils/tracing.py": _MINI_TRACING,
         "finchat_tpu/sched.py": src},
        {"metrics-discipline"},
    )
    assert len(res.findings) == 1
    assert "admited" in res.findings[0].message
    assert "SPAN_MARKS" in res.findings[0].message


def test_r5_tracer_event_and_anomaly_names(tmp_path):
    src = """
        from finchat_tpu.utils.tracing import TRACER

        def go():
            TRACER.event("dispatch", "t1")        # registered event
            TRACER.event("admitted", "t1")        # span marks count too
            TRACER.event("dispach")               # typo: flagged
            TRACER.anomaly("breaker_trip")        # registered anomaly
            TRACER.anomaly("dispatch")            # not an ANOMALY kind: flagged
    """
    res = _lint(
        tmp_path,
        {"finchat_tpu/utils/tracing.py": _MINI_TRACING,
         "finchat_tpu/app.py": src},
        {"metrics-discipline"},
    )
    msgs = _messages(res)
    assert len(msgs) == 2
    assert any("dispach" in m for m in msgs)
    assert any("ANOMALY_KINDS" in m for m in msgs)


def test_r5_trace_forwarding_helper_literals_checked(tmp_path):
    """The agent's ``_trace(state, "name")`` forwarding convention: the
    literal is checked at the helper CALL site (the helper's own
    non-literal pass-through to TRACER.event is exempt by construction)."""
    src = """
        from finchat_tpu.utils.tracing import TRACER

        class Agent:
            def _trace(self, state, name, **args):
                TRACER.event(name, state.trace_id)   # non-literal: exempt

            def decide(self, state):
                self._trace(state, "admitted")       # registered: fine
                self._trace(state, "decide_startt")  # typo: flagged
    """
    res = _lint(
        tmp_path,
        {"finchat_tpu/utils/tracing.py": _MINI_TRACING,
         "finchat_tpu/agent.py": src},
        {"metrics-discipline"},
    )
    assert len(res.findings) == 1
    assert "decide_startt" in res.findings[0].message


def test_r5_span_checks_skip_without_tracing_module(tmp_path):
    src = """
        def go(handle):
            handle.span.mark("anything_goes")
    """
    res = _lint(tmp_path, {"finchat_tpu/x.py": src}, {"metrics-discipline"})
    assert res.findings == []


def test_r2_composes_with_tracing_calls_in_hot_regions(tmp_path):
    """ISSUE 12 satellite: tracing calls inside ``# finchat-lint: hot``
    regions must not smuggle device reads — a device value cast inside a
    TRACER.event args dict is exactly the hidden sync R2 exists for.
    Host-data-only tracing passes."""
    src = """
        import jax.numpy as jnp
        from finchat_tpu.utils.tracing import TRACER

        def dispatch_bad(active):  # finchat-lint: hot
            tokens = jnp.argmax(active)
            TRACER.event("dispatch", args={"tok": int(tokens)})

        def dispatch_ok(slot_list, tally):  # finchat-lint: hot
            TRACER.event("dispatch", args={"rows": slot_list, "n": tally})
    """
    res = _lint(
        tmp_path,
        {"finchat_tpu/hot.py": src},
        {"hot-path-host-sync"},
    )
    assert len(res.findings) == 1
    assert res.findings[0].symbol.endswith("dispatch_bad")


# ---------------------------------------------------------------------------
# suppressions + baseline + CLI
# ---------------------------------------------------------------------------


def test_suppression_requires_justification(tmp_path):
    src = """
        import time

        async def f():
            time.sleep(1)  # finchat-lint: disable=event-loop-blocking
    """
    res = _lint(tmp_path, {"x.py": src}, {"event-loop-blocking"})
    assert res.findings == []  # suppressed...
    assert len(res.suppressed) == 1
    assert any(  # ...but the bare suppression is itself a finding
        f.rule == "suppression-discipline" for f in res.meta_findings
    )


def test_scope_suppression_on_def_line(tmp_path):
    src = """
        import time

        async def f():  # finchat-lint: disable=event-loop-blocking -- fixture: scope form
            time.sleep(1)
            time.sleep(2)
    """
    res = _lint(tmp_path, {"x.py": src}, {"event-loop-blocking"})
    assert res.findings == [] and len(res.suppressed) == 2
    assert res.meta_findings == []


def test_unused_suppressions_reported(tmp_path):
    src = "x = 1  # finchat-lint: disable=event-loop-blocking -- nothing here\n"
    res = _lint(tmp_path, {"x.py": src}, {"event-loop-blocking"})
    assert res.unused_suppressions == [("x.py", 1)]


def test_baseline_gates_and_shrinks(tmp_path):
    f_old = Finding("event-loop-blocking", "a.py", 3, "f", "old message")
    f_new = Finding("event-loop-blocking", "a.py", 9, "g", "new message")
    path = tmp_path / "LINT_BASELINE.json"
    write_baseline(path, [f_old])
    baseline = load_baseline(path)
    assert f_old.fingerprint() in baseline
    assert f_new.fingerprint() not in baseline
    # fingerprints are line-stable: moving the finding keeps it baselined
    moved = Finding("event-loop-blocking", "a.py", 77, "f", "old message")
    assert moved.fingerprint() in baseline


def test_cli_exit_codes(tmp_path, monkeypatch):
    from finchat_tpu.analysis.__main__ import main

    bad = tmp_path / "bad.py"
    bad.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    assert main([str(bad), "--root", str(tmp_path)]) == 1
    # baselining the finding turns the run green
    assert main([str(bad), "--root", str(tmp_path), "--update-baseline"]) == 0
    assert main([str(bad), "--root", str(tmp_path)]) == 0
    # fixing the finding leaves a stale entry (reported, not failing);
    # --update-baseline shrinks the file back to empty
    bad.write_text("async def f():\n    return 1\n")
    assert main([str(bad), "--root", str(tmp_path)]) == 0
    assert main([str(bad), "--root", str(tmp_path), "--update-baseline"]) == 0
    assert load_baseline(tmp_path / "LINT_BASELINE.json") == {}


def test_repo_is_lint_clean():
    """The ISSUE 8 acceptance gate, as a test: zero unsuppressed findings
    over the real tree (the baseline is empty — nothing grandfathered)."""
    root = Path(__file__).resolve().parent.parent
    res = run_analysis(root, [root / "finchat_tpu", root / "tests"])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    assert res.meta_findings == [], "\n".join(
        f.render() for f in res.meta_findings
    )
    assert load_baseline(root / "LINT_BASELINE.json") == {}


# ---------------------------------------------------------------------------
# runtime sanitizers
# ---------------------------------------------------------------------------


def test_stall_sanitizer_catches_blocking_callback():
    from finchat_tpu.analysis.sanitizers import StallSanitizer

    async def blocker():
        time.sleep(0.25)  # finchat-lint: disable=event-loop-blocking -- fixture: the stall the sanitizer must catch

    san = StallSanitizer(threshold_s=0.1)
    with pytest.raises(RuntimeError, match="stall sanitizer"):
        san.run(blocker())


def test_stall_sanitizer_passes_off_loop_work():
    from finchat_tpu.analysis.sanitizers import StallSanitizer

    async def clean():
        await asyncio.to_thread(time.sleep, 0.25)

    san = StallSanitizer(threshold_s=0.1)
    san.run(clean())  # no raise
    assert san.violations() == []


def test_stall_sanitizer_allowlist():
    from finchat_tpu.analysis.sanitizers import StallSanitizer

    async def blocker():
        time.sleep(0.25)  # finchat-lint: disable=event-loop-blocking -- fixture: allowlisted stall

    san = StallSanitizer(threshold_s=0.1, allow=(r"blocker",))
    san.run(blocker())  # stall recorded but allowlisted
    assert san.stalls and san.violations() == []


class _FakeEngineCfg:
    max_seqs = 4


class _FakeEngine:
    engine_cfg = _FakeEngineCfg()


class _FakeSched:
    """The exact attribute surface scheduler_leak_report audits."""

    def __init__(self, allocator):
        self.allocator = allocator
        self.engine = _FakeEngine()
        self._prefixes = []
        self._prefix_jobs = []
        self.decoding = {}
        self.prefilling = []
        self.free_slots = [0, 1, 2, 3]
        self.session_cache = None
        self._running = False


def test_leak_report_clean_and_dirty():
    from finchat_tpu.analysis.sanitizers import scheduler_leak_report
    from finchat_tpu.engine.kv_cache import PageAllocator

    alloc = PageAllocator(8)
    sched = _FakeSched(alloc)
    assert scheduler_leak_report(sched) == []

    # a dead owner's pages (the cancel-delegation bug class)
    alloc.allocate("ghost", 2)
    report = scheduler_leak_report(sched)
    assert any("ghost" in p for p in report)
    alloc.free("ghost", alloc.owned_by("ghost"))

    # a slot that never came back (the _fail_prefix_job class)
    sched.free_slots = [0, 1, 2]
    report = scheduler_leak_report(sched)
    assert any("slot accounting" in p for p in report)


def test_leak_report_counts_live_prefix_entries_and_jobs():
    from finchat_tpu.analysis.sanitizers import scheduler_leak_report
    from finchat_tpu.engine.kv_cache import PageAllocator

    class _Entry:
        def __init__(self, owner, pages):
            self.owner = owner
            self.pages = pages
            self.refs = 0
            self.shared_len = 128

    alloc = PageAllocator(8)
    sched = _FakeSched(alloc)
    pages = alloc.allocate("__prefix_0__", 2)
    sched._prefixes = [_Entry("__prefix_0__", pages)]
    assert scheduler_leak_report(sched) == []  # accounted, not a leak

    # a refcount with no referent IS a leak
    sched._prefixes[0].refs = 1
    assert any("ref leak" in p for p in scheduler_leak_report(sched))


def test_update_baseline_scope_safety(tmp_path):
    """--update-baseline must not silently delete entries it did not
    re-analyze: rule filters are refused, and a narrowed-path run keeps
    entries for files outside the analyzed set."""
    from finchat_tpu.analysis.__main__ import main

    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    b.write_text("import os\n\nasync def g():\n    os.fsync(3)\n")
    assert main([str(tmp_path), "--root", str(tmp_path), "--update-baseline"]) == 0
    full = load_baseline(tmp_path / "LINT_BASELINE.json")
    assert len(full) == 2
    # rule-filtered update refused (exit 2), baseline untouched
    assert main([str(tmp_path), "--root", str(tmp_path), "--rule", "R1",
                 "--update-baseline"]) == 2
    assert load_baseline(tmp_path / "LINT_BASELINE.json") == full
    # narrowed-path update: a.py fixed and re-baselined; b.py's entry kept
    a.write_text("async def f():\n    return 1\n")
    assert main([str(a), "--root", str(tmp_path), "--update-baseline"]) == 0
    kept = load_baseline(tmp_path / "LINT_BASELINE.json")
    assert len(kept) == 1
    assert next(iter(kept.values()))["path"] == "b.py"
    # and the full run is still green (b.py's finding stays baselined)
    assert main([str(tmp_path), "--root", str(tmp_path)]) == 0


def test_stall_sanitizer_run_cancels_pending_tasks():
    """StallSanitizer.run must mirror asyncio.run's teardown: a test that
    leaves a background task running gets it cancelled WITH its cleanup
    executed (a failing test that never stopped its scheduler must not
    strand the loop task or skip its finally blocks)."""
    from finchat_tpu.analysis.sanitizers import StallSanitizer

    cleaned = []

    async def background():
        try:
            await asyncio.sleep(60)
        finally:
            cleaned.append(True)

    async def body():
        asyncio.ensure_future(background())
        await asyncio.sleep(0.01)
        # exits with the background task still pending

    StallSanitizer(threshold_s=5.0).run(body())
    assert cleaned == [True]


def test_r1_plain_dotted_import_resolves_root_binding(tmp_path):
    """`import os.path` binds the name `os` — the import map must not
    alias it to `os.path`, which would resolve `os.fsync` to
    `os.path.fsync` and silently miss a real on-loop fsync."""
    src = """
        import os.path

        async def f(fh):
            os.fsync(fh.fileno())
    """
    res = _lint(tmp_path, {"x.py": src}, {"event-loop-blocking"})
    assert len(res.findings) == 1 and "os.fsync" in res.findings[0].message
