"""Agent-level tool-streaming plane (ISSUE 9): eager launch during the
decision decode, byte-identical parity with the serial path, the
tool.execute fault fallback, and the early response-prefix hold."""

import asyncio
import time
import types

from finchat_tpu.agent.graph import LLMAgent
from finchat_tpu.engine.generator import StubGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.utils import faults
from finchat_tpu.utils.metrics import METRICS

SYSTEM = "You are Penny."
TOOL = "Decide retrieval."


class PacedToolGenerator(StubGenerator):
    """Word-paced decision decode that records when its stream ended —
    the boundary eager launches must beat."""

    def __init__(self, text, chunk_delay=0.01):
        super().__init__(default=text, chunk_delay=chunk_delay)
        self.stream_ended_at = None

    async def stream(self, *args, **kwargs):
        async for piece in super().stream(*args, **kwargs):
            yield piece
        self.stream_ended_at = time.perf_counter()


class TimedRetriever:
    def __init__(self, rows=("COFFEE $4",), delay=0.0):
        self.rows = list(rows)
        self.delay = delay
        self.calls = []
        self.called_at = []

    async def __call__(self, args):
        self.called_at.append(time.perf_counter())
        self.calls.append(dict(args))
        if self.delay:
            await asyncio.sleep(self.delay)
        return list(self.rows)


def make_agent(tool_text, retriever, response="Here is my advice.", **kw):
    return LLMAgent(
        PacedToolGenerator(tool_text), StubGenerator(default=response),
        retriever, SYSTEM, TOOL, today=lambda: "2026-08-03", **kw,
    )


async def test_tool_launches_before_decode_completes():
    tool_gen = PacedToolGenerator(
        'retrieve_transactions({"search_query": "coffee", '
        '"num_transactions": 5, "time_period_days": 30})',
        chunk_delay=0.02,
    )
    retriever = TimedRetriever(delay=0.01)
    agent = LLMAgent(tool_gen, StubGenerator(default="ok"), retriever,
                     SYSTEM, TOOL)
    saved0 = METRICS.snapshot().get("finchat_tool_overlap_saved_seconds_sum", 0.0)
    result = await agent.query("what did I spend on coffee?", "u1")
    assert result["retrieved_transactions_count"] == 1
    # the eager launch beat the end of the decision decode ...
    assert retriever.called_at[0] < tool_gen.stream_ended_at
    # ... and the overlap-saved histogram saw nonzero hidden tool time
    saved = METRICS.snapshot()["finchat_tool_overlap_saved_seconds_sum"] - saved0
    assert saved > 0.0
    # the adopted launch carried the FINAL validated args
    assert retriever.calls[-1]["search_query"] == "coffee"
    assert retriever.calls[-1]["num_transactions"] == 5
    assert retriever.calls[-1]["user_id"] == "u1"  # server-side injection


async def test_streaming_matches_serial_path_byte_identical():
    cases = [
        'retrieve_transactions({"search_query": "groceries", "num_transactions": 2})',
        "No tool call",
        'retrieve_transactions({bad json})',  # named-without-args rescue
        "I cannot help with that",  # off-grammar, no tool named
    ]
    for tool_text in cases:
        outcomes = {}
        for streaming in (False, True):
            retriever = TimedRetriever(rows=["t1", "t2"])
            agent = make_agent(tool_text, retriever, tool_streaming=streaming)
            result = await agent.query("spending?", "u7", "CTX", [])
            outcomes[streaming] = (
                result["response"],
                result["state"].retrieved_transactions,
                # speculation may run interim/subset executions, but the
                # data the answer sees and the injected identity must match
                retriever.calls[-1].get("user_id") if retriever.calls else None,
            )
        assert outcomes[True] == outcomes[False], tool_text


async def test_late_arg_commit_cancels_and_relaunches():
    """Acceptance pin: a late token invalidating an eagerly-launched
    argument (the date window changes WHICH rows score — not a refine
    key) cancels the speculative call; only the relaunch is adopted."""
    c0 = METRICS.get("finchat_tool_speculative_cancels_total")

    class UnblockOnSecond(TimedRetriever):
        async def __call__(self, args):
            self.called_at.append(time.perf_counter())
            self.calls.append(dict(args))
            if len(self.calls) > 1:
                return ["windowed row"]
            await asyncio.sleep(5.0)  # the stale launch can never finish
            return ["stale row"]

    retriever = UnblockOnSecond()
    agent = make_agent(
        'retrieve_transactions({"search_query": "rent", "time_period_days": 7})',
        retriever,
    )
    result = await agent.query("rent?", "u1")
    assert result["state"].retrieved_transactions == ["windowed row"]
    assert [c.get("time_period_days") for c in retriever.calls] == [None, 7]
    assert METRICS.get("finchat_tool_speculative_cancels_total") - c0 >= 1


async def test_late_refine_key_adopts_sliced_superset():
    """A late num_transactions commit refines (slices) the in-flight
    launch's result instead of relaunching — one retriever execution."""
    retriever = TimedRetriever(rows=["a", "b", "c"], delay=0.01)
    agent = make_agent(
        'retrieve_transactions({"search_query": "rent", "num_transactions": 2})',
        retriever,
    )
    result = await agent.query("rent?", "u1")
    assert result["state"].retrieved_transactions == ["a", "b"]
    assert len(retriever.calls) == 1  # launch survived the late commit
    assert "num_transactions" not in retriever.calls[0]  # speculative subset


async def test_tool_execute_fault_falls_back_to_serial_retry():
    """Satellite: an injected tool failure mid-decode (tool.execute site)
    degrades to the serial path — the answer is built from the retried
    serial execution, the fallback is counted, and the speculative error
    carries the structured retryable contract (pinned in
    test_streamparse.py::test_launcher_failure_is_structured_retryable)."""
    f0 = METRICS.get("finchat_tool_fallbacks_total")
    retriever = TimedRetriever(rows=["row A"])
    agent = make_agent(
        'retrieve_transactions({"search_query": "x"})', retriever,
        response="Answer.",
    )
    with faults.armed("tool.execute", faults.one_shot(RuntimeError("index down"))):
        result = await agent.query("spending?", "u1")
    assert result["response"] == "Answer."
    assert result["state"].retrieved_transactions == ["row A"]  # serial retry won
    assert METRICS.get("finchat_tool_fallbacks_total") - f0 >= 1


async def test_tool_execute_persistent_fault_degrades_like_serial():
    retriever = TimedRetriever()

    def always(**ctx):
        raise RuntimeError("index down")

    agent = make_agent('retrieve_transactions({"search_query": "x"})', retriever)
    with faults.armed("tool.execute", always):
        result = await agent.query("spending?", "u1")
    # both the speculative launch and the serial retry failed: the
    # reference degradation contract holds (Error marker, answer made)
    assert result["response"] == "Here is my advice."
    assert result["state"].retrieved_transactions == ["Error: index down"]


class FakePartialGenerator(StubGenerator):
    """Response-role double exposing the hold-park-graft seam, so the
    early-prefix behavior is testable without an engine."""

    def __init__(self):
        super().__init__(default="resp")
        self.begun = []
        self.released = []
        self.stream_partials = []

    async def begin_partial(self, prefix_text, sampling, conversation_id=None,
                            deadline=None):
        self.begun.append((prefix_text, time.perf_counter()))
        return types.SimpleNamespace(hold=len(self.begun))

    def release_partial(self, partial):
        # EngineGenerator contract: a hold the stream claimed is the
        # stream's to manage — release only unclaimed ones
        if not getattr(partial, "_partial_claimed", False):
            self.released.append(partial)

    async def stream(self, prompt, sampling, conversation_id=None,
                     deadline=None, partial=None):
        if partial is not None:
            partial._partial_claimed = True  # the EngineGenerator contract
        self.stream_partials.append(partial)
        async for piece in super().stream(prompt, sampling):
            yield piece

    async def generate(self, prompt, sampling, conversation_id=None,
                       deadline=None, partial=None):
        if partial is not None:
            partial._partial_claimed = True
        self.stream_partials.append(partial)
        return self.default


async def test_prefix_hold_taken_at_name_commit_and_consumed():
    tool_gen = PacedToolGenerator(
        'retrieve_transactions({"search_query": "coffee"})', chunk_delay=0.02,
    )
    resp = FakePartialGenerator()
    retriever = TimedRetriever()
    agent = LLMAgent(tool_gen, resp, retriever, SYSTEM, TOOL)
    result = await agent.query("coffee?", "u1")
    assert result["response"] == "resp"
    assert len(resp.begun) == 1
    # the hold was taken DURING the decision decode (at name-commit) ...
    assert resp.begun[0][1] < tool_gen.stream_ended_at
    # ... and handed to response generation, not leaked
    assert len(resp.stream_partials) == 1 and resp.stream_partials[0].hold == 1
    assert resp.released == []


async def test_prefix_hold_released_when_serial_parse_overrules():
    """Grammatical call whose string value smuggles the no-tool literal:
    the incremental plane commits a name (prefix hold taken, tool
    launched) but the AUTHORITATIVE serial parse refuses the turn — the
    no-tool scan wins in parse_tool_decision's first 80 chars. The plane
    must converge on the serial outcome: no retrieval, launch abandoned,
    hold released."""
    resp = FakePartialGenerator()
    retriever = TimedRetriever()
    agent = LLMAgent(
        PacedToolGenerator('retrieve_transactions({"search_query": "No tool call"})'),
        resp, retriever, SYSTEM, TOOL,
    )
    f0 = METRICS.get("finchat_tool_fallbacks_total")
    result = await agent.query("hello", "u1")
    assert result["retrieved_transactions_count"] == 0
    assert result["response"] == "resp"
    assert METRICS.get("finchat_tool_fallbacks_total") - f0 >= 1
    # the eagerly-taken hold was given back, none left claimed
    assert len(resp.begun) == 1
    assert len(resp.released) == 1
