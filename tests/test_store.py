"""Context rendering + store behavior parity (reference database.py)."""

import pytest

from finchat_tpu.io.store import InMemoryStore, render_context

CONTEXT_DOC = {
    "conversation_id": "conv-1",
    "user_id": "user-9",
    "name": "Alex",
    "income": 8000,
    "savings_goal": 1500,
    "accounts": [
        {
            "account_id": "a1",
            "balances": {"available": 900.0, "current": 1234.5, "limit": None, "iso_currency_code": "USD"},
            "mask": "1234",
            "name": "Checking",
            "official_name": "Plaid Gold Standard Checking",
            "subtype": "checking",
            "type": "depository",
        },
        {"balances": {}},  # exercise normalization defaults
    ],
    "additional_monthly_expenses": [
        {"name": "Gym", "amount": 40, "description": ""},
        {"name": "Rent", "amount": 2000, "description": "downtown apartment"},
    ],
}


def test_render_context_exact_format():
    # byte-for-byte the reference's format (database.py:56-68)
    expected = (
        "My name is Alex.\n"
        "I make 8000 dollars a month.\n"
        "I want to save 1500 a month.\n\n"
        "Here is a list of my current account balances:\n"
        "Plaid Gold Standard Checking : 1234.5 USD\n"
        "Unnamed Account : 0.0 \n"
        "Here is a list of my recurring monthly expenses:\n"
        "Name: Gym | Amount: 40\n"
        "Name: Rent | Amount: 2000 | Description: downtown apartment\n"
    )
    assert render_context(CONTEXT_DOC) == expected


def test_render_context_missing_optional_sections():
    doc = {"name": "B", "income": 1, "savings_goal": 2, "accounts": None, "additional_monthly_expenses": None}
    out = render_context(doc)
    assert "account balances:\nHere is a list" in out


async def test_get_context_returns_user_id():
    store = InMemoryStore()
    store.upsert_context("conv-1", CONTEXT_DOC)
    context, user_id = await store.get_context("conv-1")
    assert user_id == "user-9"
    assert context.startswith("My name is Alex.")


async def test_get_context_missing_raises():
    store = InMemoryStore()
    with pytest.raises(LookupError):
        await store.get_context("nope")


async def test_get_context_missing_user_id_raises():
    store = InMemoryStore()
    store.upsert_context("conv-2", {**CONTEXT_DOC, "user_id": ""})
    with pytest.raises(LookupError):
        await store.get_context("conv-2")


async def test_history_sorted_and_empty_raises():
    store = InMemoryStore()
    with pytest.raises(LookupError):
        await store.get_history("conv-1")  # empty history is a hard error (database.py:78-79)

    store.add_user_message("conv-1", "second", "user-9", timestamp=200)
    store.add_user_message("conv-1", "first", "user-9", timestamp=100)
    await store.save_ai_message("conv-1", "reply", "user-9")
    history = await store.get_history("conv-1")
    assert [m.message for m in history[:2]] == ["first", "second"]
    assert history[0].is_user and history[-1].sender == "AIMessage"
