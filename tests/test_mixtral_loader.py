"""Mixtral (MoE) checkpoint loading + torch logits parity.

Mirrors tests/test_hf_loader.py for the MoE family: a tiny seeded torch
Mixtral is saved in HF format, loaded through ``load_llama_params``
(block_sparse_moe mapping), and the jax forward's logits are checked
against the torch model's — real-weight parity for router + experts, not
just shape checks.
"""

import json

import pytest

torch = pytest.importorskip("torch")
pytest.importorskip("transformers")
pytest.importorskip("safetensors")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from safetensors.numpy import save_file  # noqa: E402

from finchat_tpu.checkpoints.hf_loader import load_llama_params  # noqa: E402
from finchat_tpu.models.llama import LlamaConfig, forward_full  # noqa: E402

HF_CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    intermediate_size=96,
    max_position_embeddings=256,
    rope_theta=10_000.0,
    rms_norm_eps=1e-5,
    num_local_experts=4,
    num_experts_per_tok=2,
)

OUR_CFG = LlamaConfig(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    hidden_dim=96, rope_theta=10_000.0, norm_eps=1e-5, max_seq_len=256,
    dtype=jnp.float32, n_experts=4, top_k_experts=2,
)


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    from transformers import MixtralConfig, MixtralForCausalLM

    path = tmp_path_factory.mktemp("mixtral_ckpt")
    torch.manual_seed(13)
    model = MixtralForCausalLM(
        MixtralConfig(**HF_CFG, attn_implementation="eager")
    )
    model.eval()
    tensors = {
        k: v.detach().to(torch.float32).numpy().copy()
        for k, v in model.state_dict().items()
    }
    save_file(tensors, str(path / "model.safetensors"))
    (path / "config.json").write_text(
        json.dumps({**HF_CFG, "model_type": "mixtral",
                    "architectures": ["MixtralForCausalLM"]})
    )
    return path, model, tensors


def test_loader_layout_matches_hand_stacking(checkpoint):
    path, _, tensors = checkpoint
    params = load_llama_params(str(path), OUR_CFG)
    assert params["layers"]["moe_gate"].shape == (2, 4, 64, 96)
    assert params["layers"]["moe_down"].shape == (2, 4, 96, 64)
    assert params["layers"]["router"].dtype == jnp.float32
    want = tensors["model.layers.1.block_sparse_moe.experts.3.w1.weight"].T
    np.testing.assert_allclose(
        np.asarray(params["layers"]["moe_gate"][1, 3]), want, rtol=1e-6
    )
    want_router = tensors["model.layers.0.block_sparse_moe.gate.weight"].T
    np.testing.assert_allclose(
        np.asarray(params["layers"]["router"][0]), want_router, rtol=1e-6
    )


def test_mixtral_logits_parity_with_transformers(checkpoint):
    path, model, _ = checkpoint
    params = load_llama_params(str(path), OUR_CFG)
    ids = np.array([[5, 99, 23, 42, 7, 68, 11, 3]], np.int64)

    with torch.no_grad():
        ref = model(torch.from_numpy(ids)).logits.numpy()[0]

    positions = jnp.arange(ids.shape[1])[None, :]
    got = np.asarray(
        forward_full(
            params, jnp.asarray(ids, jnp.int32), positions,
            config=OUR_CFG, attn_backend="ref",
        )
    )[0]
    np.testing.assert_allclose(got, ref, atol=2e-3, rtol=2e-3)


def test_moe_config_mismatch_raises(checkpoint):
    path, _, _ = checkpoint
    dense_cfg = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=96, max_seq_len=256, dtype=jnp.float32,  # n_experts=0
    )
    with pytest.raises(ValueError, match="num_local_experts"):
        load_llama_params(str(path), dense_cfg)
