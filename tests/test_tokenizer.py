"""Byte tokenizer, HF tokenizer adapter, UTF-8-safe streaming detokenizer,
chat template."""

import json

import pytest

from finchat_tpu.io.schemas import ChatMessage
from finchat_tpu.models.tokenizer import (
    ByteTokenizer,
    HFTokenizer,
    IncrementalDecoder,
    get_tokenizer,
    render_chat,
)


def test_byte_roundtrip():
    tok = ByteTokenizer()
    text = "Penny saves $1,500/mo — 良い 🎉"
    assert tok.decode(tok.encode(text)) == text


def test_bos_prepend():
    tok = ByteTokenizer()
    ids = tok.encode("a", add_bos=True)
    assert ids[0] == tok.bos_id and ids[1:] == [ord("a")]


def test_incremental_decoder_never_tears_multibyte():
    tok = ByteTokenizer()
    text = "héllo 🎉 良"
    ids = tok.encode(text)
    dec = IncrementalDecoder(tok)
    out = ""
    for t in ids:
        piece = dec.push(t)
        assert "�" not in piece
        out += piece
    out += dec.flush()
    assert out == text


def test_incremental_decoder_ignores_specials():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    assert dec.push(tok.eos_id) == ""
    assert dec.push(ord("x")) == "x"


def test_incremental_decoder_garbage_does_not_stall():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    # 0xFF is never valid UTF-8; a run of them must flush as replacements
    out = "".join(dec.push(0xFF) for _ in range(6))
    assert "�" in out  # emitted, not buffered forever


# --- HFTokenizer over a locally-built tokenizer dir (no network) -----------


@pytest.fixture(scope="module")
def hf_tokenizer_dir(tmp_path_factory):
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    path = tmp_path_factory.mktemp("hf_tok")
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=320,
        special_tokens=["<s>", "</s>", "<pad>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(
        ["hello world", "what did I spend on groceries?",
         "retrieve_transactions", '{"search_query": "recent"}', "🎉 良い"],
        trainer,
    )
    tok.save(str(path / "tokenizer.json"))
    (path / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "bos_token": "<s>", "eos_token": "</s>", "pad_token": "<pad>",
    }))
    return path


def test_hf_tokenizer_roundtrip_and_specials(hf_tokenizer_dir):
    pytest.importorskip("transformers")
    tok = HFTokenizer(str(hf_tokenizer_dir))
    assert tok.vocab_size > 0
    assert tok.bos_id != tok.eos_id
    text = "what did I spend on groceries?"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    with_bos = tok.encode(text, add_bos=True)
    assert with_bos[0] == tok.bos_id and with_bos[1:] == ids


def test_get_tokenizer_dispatch(hf_tokenizer_dir):
    pytest.importorskip("transformers")
    assert isinstance(get_tokenizer(""), ByteTokenizer)
    assert isinstance(get_tokenizer(str(hf_tokenizer_dir)), HFTokenizer)


def test_incremental_decoder_hf_path(hf_tokenizer_dir):
    """The HF branch of IncrementalDecoder: multibyte text split across
    byte-fallback pieces streams without mojibake."""
    pytest.importorskip("transformers")
    tok = HFTokenizer(str(hf_tokenizer_dir))
    text = "hello 🎉 良い world"
    ids = tok.encode(text)
    dec = IncrementalDecoder(tok)
    out = ""
    for t in ids:
        piece = dec.push(t)
        assert "�" not in piece
        out += piece
    out += dec.flush()
    assert out == text


def test_render_chat_structure():
    history = [
        ChatMessage(sender="UserMessage", message="hi"),
        ChatMessage(sender="AIMessage", message="hello!"),
    ]
    prompt = render_chat("SYSTEM RULES", "MY CONTEXT", history, "what now?")
    # system block contains system_prompt then context (llm_agent.py:47-51)
    assert prompt.index("SYSTEM RULES") < prompt.index("MY CONTEXT")
    assert prompt.index("MY CONTEXT") < prompt.index("hi")
    assert prompt.index("hi") < prompt.index("hello!") < prompt.index("what now?")
    assert prompt.rstrip().endswith("<|assistant|>")
