"""Byte tokenizer, UTF-8-safe streaming detokenizer, chat template."""

from finchat_tpu.io.schemas import ChatMessage
from finchat_tpu.models.tokenizer import ByteTokenizer, IncrementalDecoder, render_chat


def test_byte_roundtrip():
    tok = ByteTokenizer()
    text = "Penny saves $1,500/mo — 良い 🎉"
    assert tok.decode(tok.encode(text)) == text


def test_bos_prepend():
    tok = ByteTokenizer()
    ids = tok.encode("a", add_bos=True)
    assert ids[0] == tok.bos_id and ids[1:] == [ord("a")]


def test_incremental_decoder_never_tears_multibyte():
    tok = ByteTokenizer()
    text = "héllo 🎉 良"
    ids = tok.encode(text)
    dec = IncrementalDecoder(tok)
    out = ""
    for t in ids:
        piece = dec.push(t)
        assert "�" not in piece
        out += piece
    out += dec.flush()
    assert out == text


def test_incremental_decoder_ignores_specials():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    assert dec.push(tok.eos_id) == ""
    assert dec.push(ord("x")) == "x"


def test_incremental_decoder_garbage_does_not_stall():
    tok = ByteTokenizer()
    dec = IncrementalDecoder(tok)
    # 0xFF is never valid UTF-8; a run of them must flush as replacements
    out = "".join(dec.push(0xFF) for _ in range(6))
    assert "�" in out  # emitted, not buffered forever


def test_render_chat_structure():
    history = [
        ChatMessage(sender="UserMessage", message="hi"),
        ChatMessage(sender="AIMessage", message="hello!"),
    ]
    prompt = render_chat("SYSTEM RULES", "MY CONTEXT", history, "what now?")
    # system block contains system_prompt then context (llm_agent.py:47-51)
    assert prompt.index("SYSTEM RULES") < prompt.index("MY CONTEXT")
    assert prompt.index("MY CONTEXT") < prompt.index("hi")
    assert prompt.index("hi") < prompt.index("hello!") < prompt.index("what now?")
    assert prompt.rstrip().endswith("<|assistant|>")
