"""Speculative decoding: verify-step exactness + prompt-lookup proposer.

The contract (engine/engine.py verify_step): a greedy slot's emitted
stream is token-for-token IDENTICAL to plain decode_step — speculation
changes how many tokens commit per step, never which tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.engine.spec import propose_ngram_drafts
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.utils.config import EngineConfig

CONFIG = PRESETS["tiny"]
ENGINE_CFG = EngineConfig(max_seqs=4, page_size=8, num_pages=64, max_seq_len=128, prefill_chunk=8)
KD = 3  # draft tokens per verify step in these tests


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _arm_slot(eng, alloc, slot, prompt, budget, seq_id):
    pages = alloc.allocate(seq_id, pages_needed(len(prompt) + budget, eng.page_size))
    eng.set_page_table_row(slot, pages)
    logits = eng.prefill(slot, prompt)
    eng.state, tok = commit_first_token(
        eng.state, jnp.int32(slot), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
    )
    return int(tok)


def _greedy_reference(params, prompt, n_new, attn=None):
    """Plain decode_step greedy tokens (the oracle for exactness)."""
    eng = InferenceEngine(CONFIG, params, ENGINE_CFG, attn_backend=attn)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    out = [_arm_slot(eng, alloc, 0, prompt, n_new, "ref")]
    B = ENGINE_CFG.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    z, o, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    for _ in range(n_new - 1):
        out.append(int(eng.decode(active, z, o, zk)[0]))
    return out


def _spec_greedy(params, prompt, n_new, drafts_for, attn=None):
    """Greedy decode via verify steps; ``drafts_for(tokens_so_far)`` returns
    the next step's draft list (possibly empty)."""
    eng = InferenceEngine(CONFIG, params, ENGINE_CFG, attn_backend=attn)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    out = [_arm_slot(eng, alloc, 0, prompt, n_new, "spec")]
    B = ENGINE_CFG.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    z, o, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    steps = 0
    while len(out) < n_new:
        proposal = list(drafts_for(list(out)))[: min(KD, n_new - len(out) - 1)]
        drafts = np.zeros((B, KD), np.int32)
        n_drafts = np.zeros((B,), np.int32)
        drafts[0, : len(proposal)] = proposal
        n_drafts[0] = len(proposal)
        emitted, n_emitted = eng.decode_spec(
            active, jnp.asarray(drafts), jnp.asarray(n_drafts), z, o, zk
        )
        n = int(n_emitted[0])
        assert 1 <= n <= len(proposal) + 1
        out.extend(int(t) for t in np.asarray(emitted[0, :n]))
        steps += 1
    return out, steps


def test_correct_drafts_all_accepted(params):
    """Drafting the true greedy continuation commits Kd+1 tokens per step."""
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 9
    want = _greedy_reference(params, prompt, n_new)
    got, steps = _spec_greedy(
        params, prompt, n_new,
        # oracle drafts: the actual upcoming greedy tokens
        lambda so_far: want[len(so_far): len(so_far) + KD],
    )
    assert got == want
    # 1 commit token + ceil(8 remaining / (KD+1)) fully-accepted steps
    assert steps == -(-(n_new - 1) // (KD + 1))


def test_wrong_drafts_rejected_exactly(params):
    """Garbage drafts must not corrupt the stream: every step falls back to
    the single model token and the KV left by rejected drafts is ignored
    and overwritten."""
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 7
    want = _greedy_reference(params, prompt, n_new)
    wrong = [(want[i] + 1) % CONFIG.vocab_size for i in range(len(want))]
    got, steps = _spec_greedy(
        params, prompt, n_new,
        lambda so_far: wrong[len(so_far): len(so_far) + KD],
    )
    assert got == want
    assert steps == n_new - 1  # nothing accepted -> one token per step


def test_partial_acceptance(params):
    """A draft list that is right then wrong commits exactly the matching
    prefix plus the correction."""
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 8
    want = _greedy_reference(params, prompt, n_new)

    def half_right(so_far):
        i = len(so_far)
        good = want[i: i + KD]
        if len(good) < 2:
            return good
        return [good[0], (good[1] + 1) % CONFIG.vocab_size, good[0]]

    got, _ = _spec_greedy(params, prompt, n_new, half_right)
    assert got == want


def test_kernel_path_partial_acceptance(params):
    """The TPU production write path — verify_step's per-token IN-PLACE
    kv-append loop (engine.py inplace_append, interpret-mode kernels) —
    must reproduce the same greedy stream as the jnp scatter path the
    other tests run ('ref' backend on CPU): positions, per-token validity,
    and rejected-draft overwrites all go through ops/kv_append.py here."""
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 6
    want = _greedy_reference(params, prompt, n_new, attn="pallas-interpret")
    assert want == _greedy_reference(params, prompt, n_new)  # backends agree

    def half_right(so_far):
        i = len(so_far)
        good = want[i: i + KD]
        if len(good) < 2:
            return good
        return [good[0], (good[1] + 1) % CONFIG.vocab_size, good[0]]

    got, _ = _spec_greedy(params, prompt, n_new, half_right, attn="pallas-interpret")
    assert got == want


def test_no_drafts_matches_plain_decode(params):
    """n_drafts == 0 everywhere reduces verify_step to decode_step."""
    prompt = [7, 7, 3, 250]
    n_new = 6
    want = _greedy_reference(params, prompt, n_new)
    got, steps = _spec_greedy(params, prompt, n_new, lambda so_far: [])
    assert got == want and steps == n_new - 1


def test_mixed_batch_isolation(params):
    """A drafting slot and a draft-free slot in the same verify step each
    produce their own reference stream."""
    eng = InferenceEngine(CONFIG, params, ENGINE_CFG)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    prompt_a, prompt_b = [5, 9, 2, 100, 17, 3], [11, 4, 200]
    n_new = 6
    want_a = _greedy_reference(params, prompt_a, n_new)
    want_b = _greedy_reference(params, prompt_b, n_new)
    out = {0: [_arm_slot(eng, alloc, 0, prompt_a, n_new, "a")],
           2: [_arm_slot(eng, alloc, 2, prompt_b, n_new, "b")]}
    B = ENGINE_CFG.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True).at[2].set(True)
    z, o, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    while len(out[0]) < n_new or len(out[2]) < n_new:
        drafts = np.zeros((B, KD), np.int32)
        n_drafts = np.zeros((B,), np.int32)
        prop = want_a[len(out[0]): len(out[0]) + KD]  # oracle drafts, slot 0 only
        prop = prop[: max(0, n_new - len(out[0]) - 1)]
        drafts[0, : len(prop)] = prop
        n_drafts[0] = len(prop)
        emitted, n_emitted = eng.decode_spec(
            active, jnp.asarray(drafts), jnp.asarray(n_drafts), z, o, zk
        )
        for slot in (0, 2):
            n = int(n_emitted[slot])
            take = min(n, n_new - len(out[slot]))
            out[slot].extend(int(t) for t in np.asarray(emitted[slot, :take]))
    assert out[0] == want_a
    assert out[2] == want_b


def _run_scheduler_stream(params, spec_tokens, prompt_text, n_new, temperature=0.0):
    """Submit one request through the full scheduler and collect its token
    stream (spec_tokens=0 -> pipelined decode path, >0 -> verify steps)."""
    import asyncio
    import dataclasses as dc

    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.tokenizer import ByteTokenizer

    async def run():
        tok = ByteTokenizer()
        cfg = dc.replace(ENGINE_CFG, spec_tokens=spec_tokens)
        eng = InferenceEngine(CONFIG, params, cfg)
        scheduler = ContinuousBatchingScheduler(eng, eos_id=tok.eos_id)
        await scheduler.start()
        try:
            handle = await scheduler.submit(
                "s", tok.encode(prompt_text, add_bos=True),
                SamplingParams(temperature=temperature, max_new_tokens=n_new),
            )
            tokens = []
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=120)
                if event["type"] == "token":
                    tokens.append(event["token_id"])
                elif event["type"] == "done":
                    return tokens
                else:
                    raise AssertionError(event)
        finally:
            await scheduler.stop()

    return asyncio.run(run())


def test_scheduler_spec_stream_matches_plain_greedy(params):
    """End-to-end through the continuous-batching scheduler: the greedy
    token stream with speculative decoding on (prompt-lookup drafts) must
    equal the non-speculative stream exactly."""
    plain = _run_scheduler_stream(params, 0, "abcabcabc", 16)
    spec = _run_scheduler_stream(params, 3, "abcabcabc", 16)
    assert spec == plain
    assert len(plain) == 16


def test_scheduler_spec_sampled_slot_rides_draft_free(params):
    """temperature > 0 slots never draft but must still stream the full
    budget through the spec path."""
    tokens = _run_scheduler_stream(params, 3, "hello", 8, temperature=0.9)
    assert len(tokens) == 8


def test_scheduler_spec_with_constrained_slot(params):
    """Grammar-constrained sequences ride verify steps draft-free: the
    host-side pick lands before the next dispatch (spec mode is depth-1),
    and bystander greedy slots keep speculating. Both must complete."""
    import asyncio
    import dataclasses as dc

    from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.tokenizer import ByteTokenizer

    async def run():
        tok = ByteTokenizer()
        cfg = dc.replace(ENGINE_CFG, spec_tokens=3)
        eng = InferenceEngine(CONFIG, params, cfg)
        scheduler = ContinuousBatchingScheduler(eng, eos_id=tok.eos_id)
        vocab = GrammarVocab.for_tokenizer(tok)
        await scheduler.start()
        try:
            bystander = await scheduler.submit(
                "bystander", tok.encode("abcabc", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=12),
            )
            constrained = await scheduler.submit(
                "tool", tok.encode("decide", add_bos=True),
                SamplingParams(temperature=0.7, max_new_tokens=24),
                constraint=TokenConstraint(vocab),
            )
            counts = {"bystander": 0, "tool": 0}
            for name, handle in (("bystander", bystander), ("tool", constrained)):
                while True:
                    event = await asyncio.wait_for(handle.events.get(), timeout=120)
                    if event["type"] == "token":
                        counts[name] += 1
                    elif event["type"] == "done":
                        break
                    else:
                        raise AssertionError(event)
            return counts
        finally:
            await scheduler.stop()

    counts = asyncio.run(run())
    assert counts["bystander"] == 12
    assert counts["tool"] >= 1  # grammar emitted something before closing


def test_spec_all_miss_demotes_then_reprobes(params):
    """Sustained zero-accept verify steps must demote the scheduler to the
    pipelined depth-2 path (ADVICE r4: depth-1 spec on all-miss traffic
    loses the device/host overlap), and the cooldown must re-arm the spec
    path afterwards rather than demoting one-way."""
    import dataclasses as dc

    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    cfg = dc.replace(ENGINE_CFG, spec_tokens=3)
    eng = InferenceEngine(CONFIG, params, cfg)
    sched = ContinuousBatchingScheduler(eng, eos_id=tok.eos_id)

    for _ in range(sched.SPEC_MISS_DEMOTE - 1):
        sched._spec_note_step(accepted=0)
    assert sched._spec_cooldown == 0  # streak alone must not demote
    sched._spec_note_step(accepted=2)  # any acceptance resets the streak
    assert sched._spec_miss_streak == 0
    for _ in range(sched.SPEC_MISS_DEMOTE):
        sched._spec_note_step(accepted=0)
    assert sched._spec_cooldown == sched.SPEC_RETRY_EVERY
    assert sched._spec_miss_streak == 0  # streak consumed by the demotion


def test_spec_stream_exact_under_demotion(params):
    """A non-repetitive prompt drives all-miss verify steps through the
    demote/re-probe cycle; the stream must still equal plain greedy
    token-for-token (mode switches change cadence, never tokens)."""
    plain = _run_scheduler_stream(params, 0, "q8#zLw", 24)
    spec = _run_scheduler_stream(params, 3, "q8#zLw", 24)
    assert spec == plain
    assert len(plain) == 24


def test_spec_replay_stream_semantics():
    """benchmarks/spec_replay.py replays the scheduler's verify-step
    semantics over a scripted stream; pin them on a hand-checkable case."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from benchmarks.spec_replay import replay_stream

    # prompt establishes "1 2 -> 3 4"; answer repeats it twice
    prompt = [9, 1, 2, 3, 4, 8]
    answer = [1, 2, 3, 4, 1, 2, 3, 4]
    steps, accepted, n = replay_stream(prompt, answer, k=3)
    assert n == 8
    # step1: no suffix match for [9? ...] -> miss, commit [1]; step2:
    # suffix [9,1]/[1]? min_ngram=2: after [.., 8, 1] no match -> commit
    # [2]; then suffix [1,2] matches prompt -> drafts [3,4,?]...
    # acceptance must make this take fewer steps than tokens
    assert steps < n
    assert accepted == n - steps  # commits = steps + accepted
    # a stream with no repetition gets zero acceptance, one token/step
    steps2, accepted2, n2 = replay_stream([5, 6, 7], [10, 11, 12, 13], k=3)
    assert (steps2, accepted2, n2) == (4, 0, 4)


def test_ngram_proposer():
    # repetition: suffix [3, 4] occurred earlier, followed by 5, 6
    assert propose_ngram_drafts([1, 2, 3, 4, 5, 6, 9, 3, 4], 2) == [5, 6]
    # longest n-gram wins over a shorter, more recent match
    hist = [1, 2, 3, 7, 7, 2, 3, 8, 1, 2, 3]
    assert propose_ngram_drafts(hist, 1, ngram=3) == [7]
    # no recurrence -> no drafts
    assert propose_ngram_drafts([1, 2, 3, 4, 5], 4) == []
    # k caps the draft length
    assert propose_ngram_drafts([1, 2, 3, 4, 1, 2], 10) == [3, 4, 1, 2]
    # degenerate inputs
    assert propose_ngram_drafts([], 4) == []
    assert propose_ngram_drafts([1, 2], 0) == []


def test_ngram_index_build_is_bounded():
    """The scheduler builds the index lazily ON THE EVENT LOOP from the
    full sequence history; the constructor must cap how much it indexes
    (a 32k ring-prefilled prompt would otherwise stall every stream)."""
    from finchat_tpu.engine.spec import NgramIndex

    ancient = [1, 2, 3, 9, 9, 9]  # the only recurrence source
    history = ancient + [int(c) for c in range(4, 9)] * 1000 + [1, 2, 3]
    idx = NgramIndex(history, max_history=100)
    assert len(idx._h) == 100  # only the tail was indexed
    assert idx.propose(3) == []  # the ancient [1,2,3] match is outside the cap
    # a cap covering the whole history finds it
    assert NgramIndex(history, max_history=10_000).propose(3) == [9, 9, 9]


def test_ngram_index_incremental_matches_oneshot():
    """Pushing token-by-token must propose exactly what a fresh index over
    the full history proposes (the scheduler keeps a live index; the
    one-shot wrapper is the reference)."""
    import random

    from finchat_tpu.engine.spec import NgramIndex

    rng = random.Random(7)
    history = [rng.randrange(6) for _ in range(400)]  # small alphabet: many repeats
    live = NgramIndex()
    for i, tok in enumerate(history):
        live.push(tok)
        if i % 17 == 0:
            assert live.propose(4) == propose_ngram_drafts(history[: i + 1], 4)
