"""Tool-streaming plane (ISSUE 9): the incremental parser's event/commit
semantics, its split-point invariance against the serial parser, and the
ToolLauncher's speculative launch / cancel / adopt lifecycle."""

import asyncio
import random

import pytest

from finchat_tpu.agent.state import ToolCall
from finchat_tpu.agent.streamparse import (
    ArgComplete,
    CallComplete,
    NoToolComplete,
    ParseAnomaly,
    StreamingToolParser,
    ToolLauncher,
    ToolNameComplete,
    ToolResult,
    ToolStreamError,
)
from finchat_tpu.agent.toolcall import parse_tool_decision

VALID_RETRIEVE = (
    'retrieve_transactions({"search_query": "coffee shops", '
    '"num_transactions": 25, "time_period_days": 30})'
)
VALID_PLOT = (
    'create_financial_plot({"chart_type": "pie", "title": "Spending", '
    '"search_query": "all spending"})'
)


def feed_all(parser, text, pieces=None):
    events = []
    for piece in pieces if pieces is not None else [text]:
        events.extend(parser.feed(piece))
    return events


# --- event semantics ------------------------------------------------------

def test_valid_call_event_stream_and_commit_order():
    parser = StreamingToolParser()
    events = feed_all(parser, VALID_RETRIEVE, list(VALID_RETRIEVE))  # char-by-char
    kinds = [type(e).__name__ for e in events]
    assert kinds == [
        "ToolNameComplete", "ArgComplete", "ArgComplete", "ArgComplete",
        "CallComplete",
    ]
    assert events[0] == ToolNameComplete("retrieve_transactions")
    assert events[1] == ArgComplete("search_query", "coffee shops")
    assert events[2] == ArgComplete("num_transactions", 25)
    assert events[3] == ArgComplete("time_period_days", 30)
    final = parser.finish()
    assert final == events[-1].call
    assert final == parse_tool_decision(VALID_RETRIEVE)


def test_string_arg_commits_only_at_closing_quote():
    parser = StreamingToolParser()
    evs = parser.feed('retrieve_transactions({"search_query": "half a quer')
    assert not any(isinstance(e, ArgComplete) for e in evs)
    assert parser.launchable_call() is None  # arg not launch-safe yet
    evs = parser.feed("y")
    assert not any(isinstance(e, ArgComplete) for e in evs)
    evs = parser.feed('"')  # the commit point
    assert evs == [ArgComplete("search_query", "half a query")]
    call = parser.launchable_call()
    assert call is not None and call.args["search_query"] == "half a query"


def test_int_arg_commits_at_terminator():
    parser = StreamingToolParser()
    parser.feed('retrieve_transactions({"search_query": "x", "num_transactions": 41')
    assert parser.feed("2") == []  # still accumulating digits
    evs = parser.feed("}")  # terminator commits AND closes the object
    assert evs == [ArgComplete("num_transactions", 412)]
    assert isinstance(parser.feed(")")[0], CallComplete)


def test_no_tool_literal_and_anomaly():
    parser = StreamingToolParser()
    assert feed_all(parser, "No tool call") == [NoToolComplete()]
    assert parser.finish() is None

    parser = StreamingToolParser()
    events = feed_all(parser, "Sure! I will retrieve_transactions({})")
    assert len(events) == 1 and isinstance(events[0], ParseAnomaly)
    # the serial parser still decides (regex searches anywhere)
    assert parser.finish() == parse_tool_decision(
        "Sure! I will retrieve_transactions({})"
    )
    assert parser.feed("more") == []  # permanently disengaged


def test_launchable_requires_name_and_required_args():
    parser = StreamingToolParser()
    parser.feed("retrieve_transactions(")
    assert parser.launchable_call() is None  # search_query not committed
    parser.feed('{"num_transactions": 5, ')
    assert parser.launchable_call() is None
    parser.feed('"search_query": "rent"')
    call = parser.launchable_call()
    assert call.name == "retrieve_transactions"
    assert call.args["search_query"] == "rent"
    assert call.args["num_transactions"] == 5  # committed extras ride along


# --- split-point invariance fuzz (satellite) ------------------------------

CORPUS = [
    VALID_RETRIEVE,
    VALID_PLOT,
    'retrieve_transactions({})',
    'retrieve_transactions({"search_query": "café ümläut €99"})',
    'retrieve_transactions({"num_transactions": 10000})',
    'create_financial_plot({"chart_type": "bar", "title": "T"})',
    "No tool call",
    "No tool call.",  # trailing junk: off-grammar, still parses serially
    "no tool call",  # case drift: off-grammar, serial no-tool rule applies
    "",
    "   \n\t  ",
    "I don't know what you mean.",
    "Sure — retrieve_transactions is the tool I'd use",  # named, no parens
    'retrieve_transactions({"search_query": "a}b"})',  # regex/JSON quirk row
    'retrieve_transactions({"search_query": "unterminated',
    'retrieve_transactions({"search_query": "x", "num_transactions":',
    'retrieve_transactions({bad json})',
    'retrieve_transactions  ({"search_query": "x"})',  # ws the regex takes
    'create_financial_plot({"chart_type": "volcano"})',  # off-enum value
    'retrieve_transactions({"num_transactions": 007})',  # leading zeros
    'retrieve_transactions({"search_query": "x"}) trailing words',
    'ééé retrieve_transactions({"search_query": "x"})',
    # grammatical call whose value smuggles the no-tool literal: the
    # serial no-tool scan overrules the incremental CallComplete
    'retrieve_transactions({"search_query": "No tool call"})',
]


def chunkings(text, rng):
    yield [text]
    yield list(text)  # per-char (per-token SSE flush)
    for _ in range(4):  # random decode-burst splits, incl. mid-JSON-string
        if not text:
            yield []
            continue
        cuts = sorted(rng.sample(range(1, len(text) + 1), min(rng.randint(1, 7), len(text))))
        pieces, prev = [], 0
        for cut in cuts:
            pieces.append(text[prev:cut])
            prev = cut
        if prev < len(text):
            pieces.append(text[prev:])
        yield pieces


def test_split_point_invariance_against_serial_parser():
    """For every corpus text and every chunking (whole, per-char, random
    bursts), finish() must equal parse_tool_decision(text) and the event
    stream must be identical — the incremental plane may never let the
    chunk boundaries of decode_loop K-token bursts change the outcome."""
    rng = random.Random(9)
    for text in CORPUS:
        serial = parse_tool_decision(text)
        reference_events = None
        for pieces in chunkings(text, rng):
            parser = StreamingToolParser()
            events = feed_all(parser, text, pieces)
            assert parser.finish() == serial, (text, pieces)
            if reference_events is None:
                reference_events = events
            else:
                assert events == reference_events, (text, pieces)


def test_truncated_prefixes_never_complete_and_stay_serial_identical():
    for cut in range(len(VALID_RETRIEVE)):
        prefix = VALID_RETRIEVE[:cut]
        parser = StreamingToolParser()
        events = feed_all(parser, prefix, list(prefix))
        assert not any(isinstance(e, CallComplete) for e in events)
        assert parser.finish() == parse_tool_decision(prefix), prefix


# --- launcher lifecycle ---------------------------------------------------

class Recorder:
    """Execute seam double: records launches, optionally stalls so a
    later commit can invalidate an in-flight one."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.started: list[dict] = []
        self.finished: list[dict] = []
        self.cancelled: list[dict] = []

    async def __call__(self, call: ToolCall) -> ToolResult:
        self.started.append(call.args)
        try:
            if self.delay:
                await asyncio.sleep(self.delay)
        except asyncio.CancelledError:
            self.cancelled.append(call.args)
            raise
        self.finished.append(call.args)
        return ToolResult([f"rows for {call.args.get('search_query')}"])


def _drive(parser, launcher, text):
    for event in parser.feed(text):
        if isinstance(event, ParseAnomaly):
            launcher.abandon()
        elif isinstance(event, CallComplete):
            launcher.update(event.call)
        elif isinstance(event, ArgComplete):
            launcher.update(parser.launchable_call())


async def test_launcher_eager_launch_and_adoption():
    recorder = Recorder()
    parser = StreamingToolParser()
    launcher = ToolLauncher(recorder)
    _drive(parser, launcher, 'retrieve_transactions({"search_query": "rent"')
    await asyncio.sleep(0)  # let the launched task start
    assert len(recorder.started) == 1  # launched before ")" ever decodes
    _drive(parser, launcher, "})")
    launcher.mark_decode_done()
    final = parser.finish()
    result = await launcher.result_for(final)
    assert result.texts == ["rows for rent"]
    assert len(recorder.started) == 1  # adopted, not re-run


async def test_late_token_invalidates_eager_launch():
    """A later token committing a result-changing argument (the date
    window — NOT a refine key) cancels the in-flight speculative launch
    and relaunches — the acceptance-pinned invalidation path."""
    recorder = Recorder(delay=10.0)  # first launch can never finish in time
    parser = StreamingToolParser()
    launcher = ToolLauncher(recorder, refine=lambda result, call: result)
    _drive(parser, launcher, 'retrieve_transactions({"search_query": "rent", ')
    await asyncio.sleep(0)  # let the speculative task enter its sleep
    assert len(recorder.started) == 1
    _drive(parser, launcher, '"time_period_days": 3')
    await asyncio.sleep(0)
    assert len(recorder.started) == 1  # int not committed yet → no change
    _drive(parser, launcher, "0})")
    await asyncio.sleep(0)  # let the relaunched task start
    assert len(recorder.started) == 2  # relaunched with the refined args
    recorder.delay = 0.0
    launcher.mark_decode_done()
    result = await launcher.result_for(parser.finish())
    assert result.texts == ["rows for rent"]
    await asyncio.sleep(0)  # let the cancelled task unwind
    assert recorder.cancelled == [{"search_query": "rent"}]
    assert recorder.finished == [{"search_query": "rent", "time_period_days": 30}]


async def test_late_refine_key_keeps_launch_and_refines_at_adoption():
    """A late-committed REFINE KEY (num_transactions) must NOT cancel the
    in-flight launch: the adopter slices the speculative superset."""
    recorder = Recorder(delay=0.05)

    async def execute(call):
        recorder.started.append(call.args)
        await asyncio.sleep(0.05)
        recorder.finished.append(call.args)
        return ToolResult(["r1", "r2", "r3", "r4"])

    def refine(result, call):
        n = call.args.get("num_transactions")
        return ToolResult(result.texts[:n]) if n else result

    parser = StreamingToolParser()
    launcher = ToolLauncher(execute, refine=refine)
    _drive(parser, launcher, 'retrieve_transactions({"search_query": "rent", ')
    await asyncio.sleep(0)
    assert len(recorder.started) == 1
    _drive(parser, launcher, '"num_transactions": 2})')
    await asyncio.sleep(0)
    assert len(recorder.started) == 1  # refine key: launch survives
    launcher.mark_decode_done()
    result = await launcher.result_for(parser.finish())
    assert result.texts == ["r1", "r2"]  # superset sliced at adoption
    assert recorder.finished == [{"search_query": "rent"}]  # ran ONCE


async def test_launcher_mismatch_reruns_final_call():
    recorder = Recorder()
    launcher = ToolLauncher(recorder)
    launcher.update(ToolCall("retrieve_transactions", {"search_query": "a"}))
    await asyncio.sleep(0.01)
    final = ToolCall("retrieve_transactions", {"search_query": "b"})
    result = await launcher.result_for(final)
    assert result.texts == ["rows for b"]
    assert recorder.started == [{"search_query": "a"}, {"search_query": "b"}]


async def test_launcher_failure_is_structured_retryable():
    async def boom(call):
        raise RuntimeError("index down")

    launcher = ToolLauncher(boom)
    launcher.update(ToolCall("retrieve_transactions", {"search_query": "x"}))
    with pytest.raises(ToolStreamError) as exc:
        await launcher.result_for(ToolCall("retrieve_transactions", {"search_query": "x"}))
    # parity with the scheduler's structured error contract
    # (generator.GenerationError / io.schemas.error_chunk fields)
    assert exc.value.code == "tool_execute_failed"
    assert exc.value.retryable is True


async def test_abandon_cancels_without_adoption():
    recorder = Recorder(delay=10.0)
    launcher = ToolLauncher(recorder)
    launcher.update(ToolCall("retrieve_transactions", {"search_query": "x"}))
    await asyncio.sleep(0)
    launcher.abandon()
    await asyncio.sleep(0)
    assert recorder.cancelled == [{"search_query": "x"}]
    assert launcher.abandoned


async def test_refine_key_growing_via_duplicate_commit_relaunches():
    """Review regression: the grammar doesn't track used keys, so a
    duplicate-key decode can GROW num_transactions after the launch
    (n=5 → n=20). Refine can only slice down — the launcher must cancel
    and relaunch, never adopt the smaller speculative fetch."""
    recorder = Recorder()
    parser = StreamingToolParser()
    launcher = ToolLauncher(recorder, refine=lambda result, call: result)
    text = ('retrieve_transactions({"num_transactions": 5, '
            '"search_query": "coffee", "num_transactions": 20})')
    assert parse_tool_decision(text).args["num_transactions"] == 20  # last wins
    cut = text.index('"coffee"') + len('"coffee"')  # search_query committed
    _drive(parser, launcher, text[:cut])
    await asyncio.sleep(0)
    assert recorder.started == [{"search_query": "coffee", "num_transactions": 5}]
    _drive(parser, launcher, text[cut:])
    await asyncio.sleep(0)
    # the grown limit invalidated the n=5 launch
    assert len(recorder.started) == 2
    launcher.mark_decode_done()
    result = await launcher.result_for(parser.finish())
    assert result.texts == ["rows for coffee"]
    assert recorder.finished[-1]["num_transactions"] == 20


def test_refinable_direction_contract():
    from finchat_tpu.agent.streamparse import refinable
    base = ToolCall("retrieve_transactions", {"search_query": "x"})
    grown = ToolCall("retrieve_transactions",
                     {"search_query": "x", "num_transactions": 7})
    assert refinable(base, grown)  # absent in base: superset fetch, slice down
    assert refinable(grown, base) is False  # final wants the default 10k: can't grow 7
    tighter = ToolCall("retrieve_transactions",
                       {"search_query": "x", "num_transactions": 3})
    assert refinable(grown, tighter)  # 7 -> 3 slices down
    assert refinable(tighter, grown) is False  # 3 -> 7 would grow


async def test_settle_prefix_propagates_caller_cancellation():
    """Review regression: a client disconnect delivered while the agent
    awaits the prefix settle must CANCEL the turn, not be swallowed."""
    from finchat_tpu.agent.graph import LLMAgent
    from finchat_tpu.engine.generator import StubGenerator

    agent = LLMAgent(StubGenerator(), StubGenerator(), None, "s", "t")

    class NeverDone:
        async def hold(self):
            await asyncio.sleep(30)

    state = type("S", (), {"partial_prefill": None})()
    prefix_task = asyncio.ensure_future(NeverDone().hold())

    async def settle():
        await agent._settle_prefix(state, prefix_task, keep=True)
        return "not cancelled"

    outer = asyncio.ensure_future(settle())
    await asyncio.sleep(0.01)
    outer.cancel()
    with pytest.raises(asyncio.CancelledError):
        await outer
    await asyncio.sleep(0)
    assert prefix_task.cancelled()  # the in-flight hold task was reaped
