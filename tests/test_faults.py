"""Fault injection (utils/faults.py) drives the §5.3 degradation contracts:
per-sequence isolation in the scheduler, transient decode faults, and
retrieval failure degrading to an Error marker with the answer still
generated."""

import asyncio

import jax
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.generator import EngineGenerator, GenerationError
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.disarm_all()


def _make_stack(**cfg_overrides):
    tok = ByteTokenizer()
    config = PRESETS["tiny"]
    from finchat_tpu.utils.config import EngineConfig

    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=64, max_seq_len=128, prefill_chunk=16,
        **cfg_overrides,
    )
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, engine_cfg)
    scheduler = ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)
    return tok, scheduler, EngineGenerator(scheduler, tok)


def test_prefill_fault_isolates_one_sequence():
    """A prefill fault for one victim evicts it with an error event; the
    other sequence completes normally — per-sequence failure isolation."""

    async def run():
        _, scheduler, gen = _make_stack()
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            faults.arm("scheduler.prefill", faults.for_seq("seq-0", RuntimeError("injected")))

            async def collect(prompt):
                try:
                    return ("ok", await gen.generate(prompt, sampling))
                except GenerationError as e:
                    return ("error", str(e))

            # seq-0 is the victim (EngineGenerator numbers sequences)
            results = await asyncio.gather(collect("victim prompt"), collect("healthy prompt"))
        finally:
            await scheduler.stop()
        return results

    results = asyncio.run(run())
    kinds = sorted(kind for kind, _ in results)
    assert kinds == ["error", "ok"], results
    error = next(msg for kind, msg in results if kind == "error")
    assert "injected" in error
    ok_text = next(msg for kind, msg in results if kind == "ok")
    assert isinstance(ok_text, str)


def test_transient_decode_fault_absorbed_by_preempt_replay():
    """ISSUE 5: with the breaker enabled (default), a one-shot decode
    fault no longer fails the in-flight batch — the round's sequences are
    recompute-preempted and replayed, so the stream COMPLETES, identical
    to a fault-free run (greedy), with the preemption counted."""
    from finchat_tpu.utils.metrics import METRICS

    async def run():
        _, scheduler, gen = _make_stack()
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            clean = await gen.generate("first request", sampling)
            p0 = METRICS.get("finchat_preemptions_total")
            faults.arm("scheduler.decode", faults.one_shot(RuntimeError("blip")))
            text = await gen.generate("first request", sampling)
            preempts = METRICS.get("finchat_preemptions_total") - p0
        finally:
            await scheduler.stop()
        return clean, text, preempts

    clean, text, preempts = asyncio.run(run())
    assert text == clean, "preempt/replay changed the greedy stream"
    assert preempts >= 1
    # the fault never tripped the breaker (one blip < threshold)
    from finchat_tpu.utils.metrics import METRICS

    assert METRICS.get("finchat_breaker_state") == 0


def test_transient_decode_fault_legacy_eviction_with_breaker_off():
    """breaker_threshold=0 keeps the pre-ISSUE-5 contract: a one-shot
    decode fault errors the in-flight batch (whole-batch failure is not
    attributable to one sequence) but the NEXT request succeeds — the
    engine recovers without restart."""

    async def run():
        _, scheduler, gen = _make_stack(breaker_threshold=0)
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            faults.arm("scheduler.decode", faults.one_shot(RuntimeError("blip")))
            with pytest.raises(GenerationError, match="blip"):
                await gen.generate("first request", sampling)
            text = await gen.generate("second request", sampling)
        finally:
            await scheduler.stop()
        return text

    assert isinstance(asyncio.run(run()), str)


def test_retrieval_fault_degrades_to_error_marker():
    """Retrieval raising degrades per the reference contract
    (llm_agent.py:129-131): Error marker in context, answer still made.
    Pinned on the serial path (tool_streaming=False) — the streamed path
    RETRIES a failed speculative launch serially before degrading
    (tests/test_tool_streaming.py covers that contract)."""
    from finchat_tpu.agent.graph import LLMAgent
    from finchat_tpu.engine.generator import StubGenerator

    class FaultyRetriever:
        async def __call__(self, args):
            faults.inject("retriever.call", seq_id=None)
            return ["row"]

    faults.arm("retriever.call", faults.one_shot(RuntimeError("vector index down")))
    agent = LLMAgent(
        StubGenerator(default='retrieve_transactions({"search_query": "x"})'),
        StubGenerator(default="Here's what I can say without your data."),
        FaultyRetriever(), "sys", "tool", tool_streaming=False,
    )
    result = asyncio.run(agent.query("what did I spend?", "u1"))
    assert result["response"].startswith("Here's")
    state = result["state"]
    assert state.retrieved_transactions == ["Error: vector index down"]


async def _drain_tokens(handle):
    """Collect a handle's token ids until done; raises on an error event."""
    tokens = []
    while True:
        event = await handle.events.get()
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return tokens
        else:
            raise RuntimeError(event["message"])


def test_mixed_round_fault_site_recovers_both_populations():
    """New armable site ``scheduler.mixed`` (ISSUE 5 satellite): a fault in
    the unified prefill+decode dispatch recovers BOTH the prefilling and
    the decoding rows via preempt/replay — greedy streams byte-identical
    to a fault-free run."""
    import asyncio

    from finchat_tpu.engine.sampler import SamplingParams

    short = list(range(1, 13))
    long = list(range(1, 49))  # 3 chunks at prefill_chunk=16

    async def run(arm_fault: bool):
        _, scheduler, _gen = _make_stack()
        scheduler = ContinuousBatchingScheduler(scheduler.engine, eos_id=-1)
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            a = await scheduler.submit("a", short, sampling)
            ta = asyncio.create_task(_drain_tokens(a))
            while a.generated < 1:  # a is decoding before b admits
                await asyncio.sleep(0.002)
            if arm_fault:
                faults.arm("scheduler.mixed", faults.one_shot(RuntimeError("mixed blip")))
            b = await scheduler.submit("b", long, sampling)
            out_b = await _drain_tokens(b)
            out_a = await ta
        finally:
            await scheduler.stop()
            faults.disarm_all()
        return out_a, out_b

    from finchat_tpu.utils.metrics import METRICS

    clean = asyncio.run(run(False))
    f0 = METRICS.get("finchat_dispatch_failures_total")
    faulted = asyncio.run(run(True))
    assert METRICS.get("finchat_dispatch_failures_total") > f0, (
        "scheduler.mixed site never fired (mixed round did not run?)"
    )
    assert faulted == clean, "mixed-round fault recovery changed greedy streams"


def test_embed_dispatch_fault_isolated_per_request_retry():
    """New armable site ``embed.dispatch``: a failed coalesced embed
    dispatch retries per-request, so every caller still resolves."""
    import asyncio

    import numpy as np

    from finchat_tpu.embed.batcher import EmbedMicrobatcher
    from finchat_tpu.utils.metrics import METRICS

    class FakeEncoder:
        dim = 4

        def embed_batch(self, texts):
            return np.ones((len(texts), self.dim), np.float32)

    async def run():
        batcher = EmbedMicrobatcher(FakeEncoder(), window_ms=5.0, max_batch=8)
        faults.arm("embed.dispatch", faults.one_shot(RuntimeError("encoder down")))
        try:
            rows = await asyncio.gather(
                *[batcher.embed_one(f"text {i}") for i in range(3)]
            )
        finally:
            await batcher.close()
        return rows

    r0 = METRICS.get("finchat_embed_batch_retries_total")
    rows = asyncio.run(run())
    assert len(rows) == 3 and all(r.shape == (4,) for r in rows)
    assert METRICS.get("finchat_embed_batch_retries_total") > r0, (
        "coalesced-dispatch failure did not take the per-request retry path"
    )


def test_session_offload_fault_never_fails_retirement():
    """New armable site ``session.offload``: a failed device→host snapshot
    must not fail the retiring stream — the cache entry is simply not
    stored (the cache is an optimization)."""
    import asyncio

    from finchat_tpu.engine.sampler import SamplingParams

    async def run():
        _, scheduler, _gen = _make_stack()
        scheduler = ContinuousBatchingScheduler(scheduler.engine, eos_id=-1)
        await scheduler.start()
        try:
            faults.arm("session.offload", faults.one_shot(RuntimeError("D2H failed")))
            h = await scheduler.submit(
                "t1", list(range(1, 20)),
                SamplingParams(temperature=0.0, max_new_tokens=8),
                conversation_id="conv-off",
            )
            tokens = await _drain_tokens(h)
            entry = scheduler.session_cache.get("conv-off")
        finally:
            await scheduler.stop()
        return tokens, entry

    tokens, entry = asyncio.run(run())
    assert len(tokens) == 8  # the stream completed normally
    assert entry is None  # nothing cached — and nothing crashed


def test_session_restore_fault_falls_back_to_cold_prefill():
    """New armable site ``session.restore``: a failed host→device restore
    at admission demotes to a cold start — the stream completes and the
    allocator invariants hold (no leaked restore pages)."""
    import asyncio

    from finchat_tpu.engine.sampler import SamplingParams

    async def run():
        _, scheduler, _gen = _make_stack()
        scheduler = ContinuousBatchingScheduler(scheduler.engine, eos_id=-1)
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            h1 = await scheduler.submit(
                "t1", list(range(1, 20)), sampling, conversation_id="conv-res"
            )
            t1 = await _drain_tokens(h1)
            assert scheduler.session_cache.get("conv-res") is not None
            faults.arm("session.restore", faults.one_shot(RuntimeError("H2D failed")))
            prompt2 = list(range(1, 20)) + t1 + list(range(30, 40))
            h2 = await scheduler.submit(
                "t2", prompt2, sampling, conversation_id="conv-res"
            )
            t2 = await _drain_tokens(h2)
            scheduler.allocator.check_invariants()
        finally:
            await scheduler.stop()
        return t2

    assert len(asyncio.run(run())) == 8


def test_kafka_drop_produce_is_silent_for_chunks():
    """Broker-level drop hook: fire-and-forget chunks vanish without error
    (reference QoS split, kafka_client.py:26-36)."""
    from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient
    from finchat_tpu.utils.config import KafkaConfig

    broker = InMemoryBroker()
    broker.faults.drop_produce = lambda topic, value: value.get("drop_me", False)
    client = KafkaClient(KafkaConfig(), broker=broker)
    observer = KafkaClient(KafkaConfig(), broker=broker)
    observer.setup_consumer(topics=["t"])

    client.produce_message("t", "k", {"drop_me": True, "n": 1})
    client.produce_message("t", "k", {"drop_me": False, "n": 2})
    import json

    seen = []
    for _ in range(50):
        msg = observer.poll_message()
        if msg is not None:
            seen.append(json.loads(msg.value().decode())["n"])
    assert seen == [2]
