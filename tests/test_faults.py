"""Fault injection (utils/faults.py) drives the §5.3 degradation contracts:
per-sequence isolation in the scheduler, transient decode faults, and
retrieval failure degrading to an Error marker with the answer still
generated."""

import asyncio

import jax
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.generator import EngineGenerator, GenerationError
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.disarm_all()


def _make_stack():
    tok = ByteTokenizer()
    config = PRESETS["tiny"]
    from finchat_tpu.utils.config import EngineConfig

    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=64, max_seq_len=128, prefill_chunk=16
    )
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, engine_cfg)
    scheduler = ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)
    return tok, scheduler, EngineGenerator(scheduler, tok)


def test_prefill_fault_isolates_one_sequence():
    """A prefill fault for one victim evicts it with an error event; the
    other sequence completes normally — per-sequence failure isolation."""

    async def run():
        _, scheduler, gen = _make_stack()
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            faults.arm("scheduler.prefill", faults.for_seq("seq-0", RuntimeError("injected")))

            async def collect(prompt):
                try:
                    return ("ok", await gen.generate(prompt, sampling))
                except GenerationError as e:
                    return ("error", str(e))

            # seq-0 is the victim (EngineGenerator numbers sequences)
            results = await asyncio.gather(collect("victim prompt"), collect("healthy prompt"))
        finally:
            await scheduler.stop()
        return results

    results = asyncio.run(run())
    kinds = sorted(kind for kind, _ in results)
    assert kinds == ["error", "ok"], results
    error = next(msg for kind, msg in results if kind == "error")
    assert "injected" in error
    ok_text = next(msg for kind, msg in results if kind == "ok")
    assert isinstance(ok_text, str)


def test_transient_decode_fault_fails_inflight_then_recovers():
    """A one-shot decode fault errors the in-flight batch (whole-batch
    failure is not attributable to one sequence) but the NEXT request
    succeeds — the engine recovers without restart."""

    async def run():
        _, scheduler, gen = _make_stack()
        await scheduler.start()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        try:
            faults.arm("scheduler.decode", faults.one_shot(RuntimeError("blip")))
            with pytest.raises(GenerationError, match="blip"):
                await gen.generate("first request", sampling)
            text = await gen.generate("second request", sampling)
        finally:
            await scheduler.stop()
        return text

    assert isinstance(asyncio.run(run()), str)


def test_retrieval_fault_degrades_to_error_marker():
    """Retrieval raising degrades per the reference contract
    (llm_agent.py:129-131): Error marker in context, answer still made."""
    from finchat_tpu.agent.graph import LLMAgent
    from finchat_tpu.engine.generator import StubGenerator

    class FaultyRetriever:
        async def __call__(self, args):
            faults.inject("retriever.call", seq_id=None)
            return ["row"]

    faults.arm("retriever.call", faults.one_shot(RuntimeError("vector index down")))
    agent = LLMAgent(
        StubGenerator(default='retrieve_transactions({"search_query": "x"})'),
        StubGenerator(default="Here's what I can say without your data."),
        FaultyRetriever(), "sys", "tool",
    )
    result = asyncio.run(agent.query("what did I spend?", "u1"))
    assert result["response"].startswith("Here's")
    state = result["state"]
    assert state.retrieved_transactions == ["Error: vector index down"]


def test_kafka_drop_produce_is_silent_for_chunks():
    """Broker-level drop hook: fire-and-forget chunks vanish without error
    (reference QoS split, kafka_client.py:26-36)."""
    from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient
    from finchat_tpu.utils.config import KafkaConfig

    broker = InMemoryBroker()
    broker.faults.drop_produce = lambda topic, value: value.get("drop_me", False)
    client = KafkaClient(KafkaConfig(), broker=broker)
    observer = KafkaClient(KafkaConfig(), broker=broker)
    observer.setup_consumer(topics=["t"])

    client.produce_message("t", "k", {"drop_me": True, "n": 1})
    client.produce_message("t", "k", {"drop_me": False, "n": 2})
    import json

    seen = []
    for _ in range(50):
        msg = observer.poll_message()
        if msg is not None:
            seen.append(json.loads(msg.value().decode())["n"])
    assert seen == [2]
