"""retrieve_transactions security invariants (reference qdrant_tool.py)."""

import jax
import pytest

from finchat_tpu.embed.encoder import EMBED_PRESETS, EmbeddingEncoder, init_bert_params
from finchat_tpu.embed.index import DeviceVectorIndex
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.tools.retrieval import TransactionRetriever

NOW = 1_700_000_000.0


@pytest.fixture(scope="module")
def retriever():
    config = EMBED_PRESETS["bge-tiny"]
    params = init_bert_params(config, jax.random.key(0))
    encoder = EmbeddingEncoder(config, params, ByteTokenizer())
    index = DeviceVectorIndex(dim=config.dim)
    r = TransactionRetriever(encoder, index, now=lambda: NOW)
    r.upsert_transactions(
        "alice",
        ["GROCERY OUTLET $54.12", "RENT PAYMENT $2000", "COFFEE SHOP $4.50"],
        dates=[NOW - 86400 * 40, NOW - 86400 * 5, NOW - 86400 * 1],
    )
    r.upsert_transactions("bob", ["BOB'S SECRET PURCHASE $999"], dates=[NOW - 100])
    return r


async def test_empty_user_id_returns_empty(retriever):
    # qdrant_tool.py:89-91
    assert await retriever({"search_query": "anything"}) == []
    assert await retriever({"user_id": "", "search_query": "anything"}) == []


async def test_user_isolation(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases"})
    assert len(hits) == 3
    assert all("BOB" not in h for h in hits)


async def test_time_period_filter(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases", "time_period_days": 7})
    assert len(hits) == 2  # 40-day-old grocery txn filtered out
    assert not any("GROCERY" in h for h in hits)


async def test_num_transactions_limit(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases", "num_transactions": 1})
    assert len(hits) == 1


async def test_default_limit_is_10000(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases", "num_transactions": None})
    assert len(hits) == 3  # None → default 10000 (qdrant_tool.py:145)


async def test_exception_returns_empty_list(retriever):
    broken = TransactionRetriever(retriever.encoder, None, now=lambda: NOW)  # type: ignore
    assert await broken({"user_id": "alice", "search_query": "x"}) == []


async def test_retrieval_runs_off_the_event_loop(retriever):
    """The embed+query device work must not stall the asyncio loop: a
    concurrent 5 ms heartbeat keeps ticking while a (artificially slow)
    retrieval is in flight (verdict r3 weak #3; mirrors the scheduler's
    responsiveness test)."""
    import asyncio
    import time

    slow = TransactionRetriever(retriever.encoder, retriever.index, now=lambda: NOW)
    orig_embed = slow.encoder.embed_query

    class SlowEncoder:
        def __init__(self, inner):
            self._inner = inner

        def embed_query(self, text):
            time.sleep(0.25)  # simulate a long device sync
            return orig_embed(text)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    slow.encoder = SlowEncoder(slow.encoder)

    beats = 0

    async def heartbeat():
        nonlocal beats
        while True:
            await asyncio.sleep(0.005)
            beats += 1

    hb = asyncio.create_task(heartbeat())
    t0 = time.perf_counter()
    hits = await slow.structured({"user_id": "alice", "search_query": "purchases"})
    elapsed = time.perf_counter() - t0
    hb.cancel()
    assert len(hits) == 3
    assert elapsed >= 0.25
    # a blocked loop would record ~0 beats during the 250 ms sleep; a
    # responsive one fits dozens of 5 ms heartbeats
    assert beats >= 10, f"event loop starved during retrieval ({beats} beats)"
