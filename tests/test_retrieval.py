"""retrieve_transactions security invariants (reference qdrant_tool.py)."""

import jax
import pytest

from finchat_tpu.embed.encoder import EMBED_PRESETS, EmbeddingEncoder, init_bert_params
from finchat_tpu.embed.index import DeviceVectorIndex
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.tools.retrieval import TransactionRetriever

NOW = 1_700_000_000.0


@pytest.fixture(scope="module")
def retriever():
    config = EMBED_PRESETS["bge-tiny"]
    params = init_bert_params(config, jax.random.key(0))
    encoder = EmbeddingEncoder(config, params, ByteTokenizer())
    index = DeviceVectorIndex(dim=config.dim)
    r = TransactionRetriever(encoder, index, now=lambda: NOW)
    r.upsert_transactions(
        "alice",
        ["GROCERY OUTLET $54.12", "RENT PAYMENT $2000", "COFFEE SHOP $4.50"],
        dates=[NOW - 86400 * 40, NOW - 86400 * 5, NOW - 86400 * 1],
    )
    r.upsert_transactions("bob", ["BOB'S SECRET PURCHASE $999"], dates=[NOW - 100])
    return r


async def test_empty_user_id_returns_empty(retriever):
    # qdrant_tool.py:89-91
    assert await retriever({"search_query": "anything"}) == []
    assert await retriever({"user_id": "", "search_query": "anything"}) == []


async def test_user_isolation(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases"})
    assert len(hits) == 3
    assert all("BOB" not in h for h in hits)


async def test_time_period_filter(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases", "time_period_days": 7})
    assert len(hits) == 2  # 40-day-old grocery txn filtered out
    assert not any("GROCERY" in h for h in hits)


async def test_num_transactions_limit(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases", "num_transactions": 1})
    assert len(hits) == 1


async def test_default_limit_is_10000(retriever):
    hits = await retriever({"user_id": "alice", "search_query": "purchases", "num_transactions": None})
    assert len(hits) == 3  # None → default 10000 (qdrant_tool.py:145)


async def test_exception_returns_empty_list(retriever):
    broken = TransactionRetriever(retriever.encoder, None, now=lambda: NOW)  # type: ignore
    assert await broken({"user_id": "alice", "search_query": "x"}) == []
