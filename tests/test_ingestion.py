"""Vector-index durability + ingestion surface (VERDICT r1 task 5).

The reference's transaction data lives in an external durable Qdrant fed by
an out-of-band pipeline (qdrant_tool.py:24-37); here ingestion is
first-class (POST /transactions + the transaction_upsert Kafka topic) and
the on-device index snapshots to ``vector.persist_path`` so retrieval is
not empty-at-boot."""

import asyncio
import json

import numpy as np

from finchat_tpu.embed.index import DeviceVectorIndex, VectorPoint
from finchat_tpu.engine.generator import StubGenerator
from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient, Message
from finchat_tpu.io.store import InMemoryStore
from finchat_tpu.serve.app import build_app
from finchat_tpu.utils.config import TRANSACTION_UPSERT_TOPIC, load_config


def test_index_snapshot_roundtrip(tmp_path):
    base = str(tmp_path / "snap")
    index = DeviceVectorIndex(dim=4)
    index.upsert([
        VectorPoint(
            id=f"p{i}", vector=np.eye(4)[i % 4].astype(np.float32),
            payload={"page_content": f"txn {i}",
                     "metadata": {"user_id": "u", "date": 100.0 + i}},
        )
        for i in range(6)
    ])
    index.save(base)

    restored = DeviceVectorIndex.load(base, dim=4)
    assert len(restored) == 6
    hits = restored.query_points(
        np.asarray([1, 0, 0, 0], np.float32), limit=10, user_id="u"
    )
    assert {h.payload["page_content"] for h in hits} == {f"txn {i}" for i in range(6)}
    # date filter data survived too
    hits = restored.query_points(
        np.asarray([1, 0, 0, 0], np.float32), limit=10, user_id="u", date_gte=104.0
    )
    assert {h.payload["page_content"] for h in hits} == {"txn 4", "txn 5"}


def test_load_missing_snapshot_is_empty(tmp_path):
    index = DeviceVectorIndex.load(str(tmp_path / "absent"), dim=4)
    assert len(index) == 0


def _make_app(tmp_path):
    cfg = load_config(overrides={
        "model.preset": "stub",
        "vector.persist_path": str(tmp_path / "vectors"),
    })
    broker = InMemoryBroker()
    store = InMemoryStore()
    app = build_app(
        cfg, store=store, kafka=KafkaClient(cfg.kafka, broker=broker),
        tool_generator=StubGenerator(default="No tool call"),
        response_generator=StubGenerator(default="ok"),
    )
    return app, broker


ROWS = [
    {"text": "Spent $4.50 at Blue Bottle Coffee", "date": 1000.0, "amount": -4.5},
    {"text": "Rent payment $1800", "date": 2000.0, "amount": -1800.0},
]


def test_boot_ingest_retrieve_persist_roundtrip(tmp_path):
    """boot → ingest → retrieve → reboot: data survives the restart."""

    async def first_boot():
        app, _ = _make_app(tmp_path)
        count = await asyncio.to_thread(app._ingest_rows, "u1", ROWS)
        assert count == 2
        rows = await app.retriever.structured(
            {"user_id": "u1", "search_query": "coffee"}
        )
        assert len(rows) == 2
        assert all(r["user_id"] == "u1" for r in rows)
        # wrong user sees nothing (security invariant holds on ingested data)
        assert await app.retriever({"user_id": "other"}) == []

    asyncio.run(first_boot())

    async def second_boot():
        app, _ = _make_app(tmp_path)  # fresh app, same persist path
        rows = await app.retriever.structured(
            {"user_id": "u1", "search_query": "rent"}
        )
        texts = {r["page_content"] for r in rows}
        assert texts == {ROWS[0]["text"], ROWS[1]["text"]}
        # structured metadata (the plot tool's input) survived the snapshot
        assert {r.get("amount") for r in rows} == {-4.5, -1800.0}

    asyncio.run(second_boot())


def test_kafka_upsert_topic_ingests(tmp_path):
    async def run():
        app, broker = _make_app(tmp_path)
        payload = {"user_id": "u2", "transactions": ROWS}
        msg = Message(TRANSACTION_UPSERT_TOPIC, "u2", json.dumps(payload).encode())
        await app.process_upsert(msg)
        return await app.retriever({"user_id": "u2"})

    texts = asyncio.run(run())
    assert len(texts) == 2


def test_http_upsert_endpoint(tmp_path):
    """POST /transactions through the real handler (request object faked)."""

    class Req:
        def __init__(self, body):
            self._body = body

        def json(self):
            return self._body

    async def run():
        app, _ = _make_app(tmp_path)
        resp = await app.upsert_transactions(Req({"user_id": "u3", "transactions": ROWS}))
        assert json.loads(resp.body.decode())["upserted"] == 2
        bad = await app.upsert_transactions(Req({"user_id": "u3"}))
        assert bad.status == 400
        bad2 = await app.upsert_transactions(
            Req({"user_id": "u3", "transactions": [{"date": 1.0}]})
        )
        assert bad2.status == 400
        return await app.retriever({"user_id": "u3"})

    assert len(asyncio.run(run())) == 2
