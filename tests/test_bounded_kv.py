"""Bounded-KV long-context serving (ISSUE 15; SnapStream-style sink +
window with page-granular eviction — engine/kv_cache.BoundedKVPolicy).

The contracts under test:

- eviction is pure host metadata riding the paged indirection: page
  occupancy stays bounded at sink+window pages for arbitrarily long
  sessions, the allocator invariants hold through eviction waves, and
  nothing leaks;
- streams are BYTE-IDENTICAL to the unbounded path while the context
  still fits the bounded budget (the policy is inert until it evicts);
  past it, the stream keeps decoding at flat cost (the divergence
  envelope — quality, not identity, is the contract there);
- a bounded row preempts by SNAPSHOT: the replay restores the surviving
  pages byte-identically and re-prefills only the residual tail, so a
  preempted long stream equals the unpreempted one token-for-token (the
  ISSUE 15 satellite bugfix — the old path re-prefilled tokens the
  policy would immediately evict);
- the session tier round-trips bounded entries through RAM and disk with
  the gap intact (record header field, CRC'd payload), and a gapped
  entry resumes whole-or-not;
- the free-run capture composes: eviction is staged at capture
  boundaries (like budget stops), so captured streams are byte-identical
  to host-stepped ones WITH eviction active;
- ring/seq-sharded prefill is PROMOTED into the ragged round (no more
  reason="ring" demotions): ring-routed prompts ride packed chunk rows
  whose per-page online-softmax is the ring fold's carry.

fp32 config throughout, for the same reason as tests/test_mixed_step.py:
identity contracts must not hide behind (or be excused by) bf16 near-tie
rounding.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.kv_cache import (
    BoundedKVPolicy,
    PageAllocationError,
    pages_needed,
)
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.utils.config import EngineConfig, load_config
from finchat_tpu.utils.metrics import METRICS

CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
CHUNK = 16
PAGE = 8
SINK, WINDOW = 1, 4  # budget 5 pages = 40 tokens
BUDGET_TOKENS = (SINK + WINDOW) * PAGE


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _sched(params, *, sink=SINK, window=WINDOW, mixed=True, max_seqs=4,
           num_pages=128, eos_id=-1, spec_tokens=0, decode_loop_depth=1,
           freerun_rounds=1, session=False, disk="", max_seq_len=512):
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=PAGE, num_pages=num_pages,
        max_seq_len=max_seq_len, prefill_chunk=CHUNK, mixed_step=mixed,
        session_cache=session,
        session_cache_bytes=(32 << 20) if session else 0,
        session_cache_disk_path=disk,
        spec_tokens=spec_tokens, decode_loop_depth=decode_loop_depth,
        freerun_rounds=freerun_rounds,
        kv_sink_pages=sink, kv_window_pages=window,
    )
    engine = InferenceEngine(CONFIG, params, cfg)
    return ContinuousBatchingScheduler(engine, eos_id=eos_id)


async def _drain(handle, out):
    while True:
        ev = await asyncio.wait_for(handle.events.get(), timeout=120)
        if ev["type"] == "token":
            out.append(ev["token_id"])
        elif ev["type"] == "done":
            return
        else:
            raise AssertionError(ev)


def _greedy(n):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        1, CONFIG.vocab_size, size=n).tolist()


# --- policy units (pure host math) -----------------------------------------


def test_policy_eviction_plan_math():
    bp = BoundedKVPolicy(sink_pages=1, window_pages=4, page_size=8)
    assert bp.enabled and bp.budget_pages == 5 and bp.sink_tokens == 8
    # fits: nothing to evict
    assert bp.plan_eviction(30, 8, 5, 1) == 0
    # 38 written + 8 incoming = 46 tokens -> 6 pages > 5 capacity: evict 1
    assert bp.plan_eviction(38, 8, 5, 1) == 1
    # a whole chunk arriving: evict enough pages for it
    assert bp.plan_eviction(38, 16, 5, 1) == 2
    # pinned head widens the sink but doesn't change the count while
    # enough full post-sink pages exist
    assert bp.plan_eviction(38, 8, 5, 2) == 1
    # infeasible: everything below the partial tail is pinned
    with pytest.raises(PageAllocationError):
        bp.plan_eviction(38, 8, 5, 4)
    # eviction plan is deterministic in the written count alone
    assert all(bp.plan_eviction(w, 1, 5, 1) == (1 if (w + 1) > 40 else 0)
               for w in range(8, 41))


def test_policy_validation():
    # window too small for a prefill chunk between waves
    with pytest.raises(ValueError, match="dispatch burst"):
        BoundedKVPolicy(1, 2, 8).validate(
            prefill_chunk=16, max_pages_per_seq=32)
    # budget exceeding the page-table row width
    with pytest.raises(ValueError, match="max_pages_per_seq"):
        BoundedKVPolicy(4, 8, 8).validate(
            prefill_chunk=16, max_pages_per_seq=8)
    # disabled policy validates vacuously
    BoundedKVPolicy(0, 0, 8).validate(prefill_chunk=512, max_pages_per_seq=4)
    # a valid shape passes
    BoundedKVPolicy(1, 4, 8).validate(prefill_chunk=16, max_pages_per_seq=32)


def test_engine_rejects_infeasible_policy(params):
    with pytest.raises(ValueError, match="dispatch burst"):
        _sched(params, sink=1, window=2)


# --- identity while the context fits ---------------------------------------


def _run_single(params, *, sink, window, prompt, max_new, seed=0, **kw):
    sched = _sched(params, sink=sink, window=window, **kw)
    out: list[int] = []
    peak = {"pages": 0}

    async def go():
        await sched.start()
        try:
            h = await sched.submit("s", prompt, _greedy(max_new))
            task = asyncio.create_task(_drain(h, out))
            while not h.finished:
                peak["pages"] = max(
                    peak["pages"], len(sched.allocator.owned_by("s")))
                await asyncio.sleep(0.001)
            await task
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
        finally:
            await sched.stop()

    asyncio.run(go())
    return out, peak["pages"], sched


def test_bounded_identical_while_context_fits(params):
    """prompt + max_new within sink+window: the policy never evicts and
    the stream is byte-identical to the unbounded engine's."""
    prompt = _prompt(20, seed=1)
    base, _, _ = _run_single(params, sink=0, window=0,
                             prompt=prompt, max_new=12)
    before = METRICS.snapshot().get("finchat_boundedkv_evicted_pages_total", 0)
    bounded, peak, _ = _run_single(params, sink=SINK, window=WINDOW,
                                   prompt=prompt, max_new=12)
    after = METRICS.snapshot().get("finchat_boundedkv_evicted_pages_total", 0)
    assert bounded == base
    assert after == before, "eviction fired inside the window"
    assert peak <= SINK + WINDOW


def test_long_session_bounded_occupancy_and_envelope(params):
    """A session well past the budget: page occupancy stays pinned at
    sink+window while the stream decodes to completion (the divergence
    envelope — past the window the output is a valid greedy decode of the
    bounded attention, not the unbounded oracle's)."""
    prompt = _prompt(40, seed=2)  # 5 pages — at the budget before decode
    max_new = 40  # total 80 tokens = 10 unbounded pages, 2x the budget
    before = METRICS.snapshot().get("finchat_boundedkv_evicted_pages_total", 0)
    out, peak, sched = _run_single(params, sink=SINK, window=WINDOW,
                                   prompt=prompt, max_new=max_new)
    after = METRICS.snapshot().get("finchat_boundedkv_evicted_pages_total", 0)
    assert len(out) == max_new, "bounded stream did not complete"
    assert all(0 <= t < CONFIG.vocab_size for t in out)
    assert peak <= SINK + WINDOW, (peak, "occupancy exceeded the budget")
    # the unbounded requirement would have been 10 pages; eviction made
    # up the difference
    assert after - before >= pages_needed(len(prompt) + max_new, PAGE) - (
        SINK + WINDOW)


def test_bounded_composes_with_loop_tails_and_spec(params):
    """decode_loop fused tails and spec verify rows ride bounded rows:
    the stream completes with occupancy bounded (write bursts covered by
    the eviction reserve) and zero leaks."""
    prompt = (_prompt(4, seed=3) * 5)[:18]  # repetitive: proposals fire
    out, peak, _ = _run_single(
        params, sink=SINK, window=WINDOW, prompt=prompt, max_new=36,
        spec_tokens=2, decode_loop_depth=3,
    )
    assert len(out) == 36
    assert peak <= SINK + WINDOW


# --- preempt/replay (the satellite bugfix) ---------------------------------


def test_bounded_preempt_replay_byte_identity(params):
    """Preempting a bounded stream AFTER eviction started and replaying
    it yields the exact tokens of the unpreempted run: the replay
    restores the surviving sink+window pages from the preemption snapshot
    (byte-identical KV) and re-prefills only the residual tail — it never
    re-prefills (or re-allocates) evicted tokens."""
    prompt = _prompt(24, seed=4)
    max_new = 36

    def run(preempt: bool):
        sched = _sched(params)
        out: list[int] = []
        info = {}

        async def go():
            await sched.start()
            try:
                h = await sched.submit("s", prompt, _greedy(max_new))
                task = asyncio.create_task(_drain(h, out))
                if preempt:
                    # wait until the policy has actually evicted, then
                    # preempt at a CONSUMED boundary — the condition the
                    # page-pressure path guarantees by draining in-flight
                    # before executing its plan (the identity caveat in
                    # _bounded_preempt_snapshot): a preempt inside an
                    # eviction transition has no identity contract
                    for _ in range(100_000):
                        if (h.kv_gap > 0 and h.generated >= 24
                                and h.kv_gap_pos <= len(h.history) - 1):
                            break
                        await asyncio.sleep(0.001)
                    assert h.kv_gap > 0, "eviction never engaged"
                    sched._preempt(h)
                    info["preempted_gap"] = h.kv_gap
                await task
                sched.allocator.check_invariants()
                info["preempted"] = h.preempted
            finally:
                await sched.stop()

        asyncio.run(go())
        return out, info

    snap0 = METRICS.snapshot()
    clean, _ = run(False)
    replayed, info = run(True)
    snap1 = METRICS.snapshot()
    assert info["preempted"] == 1 and info["preempted_gap"] > 0
    assert replayed == clean, "bounded preempt/replay diverged"
    assert snap1.get("finchat_boundedkv_recompute_fallbacks_total", 0) == \
        snap0.get("finchat_boundedkv_recompute_fallbacks_total", 0), (
            "replay fell back to recompute instead of restoring")


def test_bounded_replay_allocates_only_surviving_pages(params):
    """The sizing half of the satellite bugfix: a preempted bounded
    stream re-admits with at most sink+window pages — never the unbounded
    prompt+budget requirement its full history would imply."""
    prompt = _prompt(24, seed=5)
    sched = _sched(params)

    async def go():
        await sched.start()
        try:
            h = await sched.submit("s", prompt, _greedy(36))
            out: list[int] = []
            task = asyncio.create_task(_drain(h, out))
            for _ in range(100_000):
                if h.kv_gap > 0 and h.generated >= 24:
                    break
                await asyncio.sleep(0.001)
            assert h.kv_gap > 0
            sched._preempt(h)
            # the full-history replay would need 8+ pages unbounded; the
            # bounded sizing caps at the budget
            assert sched._admission_pages(h) <= SINK + WINDOW
            while h.slot < 0 and not h.finished:
                await asyncio.sleep(0.001)
            assert len(sched.allocator.owned_by("s")) <= SINK + WINDOW
            await task
        finally:
            await sched.stop()

    asyncio.run(go())


# --- session tier round trip -----------------------------------------------


def _two_turn(params, *, disk="", fresh_for_turn2=False):
    """Turn 1 evicts and retires; turn 2 extends the history and resumes.
    Returns (turn2 tokens, entry gap, metrics window, scheduler)."""
    prompt1 = _prompt(24, seed=6)
    sched = _sched(params, session=True, disk=disk)
    t1: list[int] = []
    t2: list[int] = []

    async def turn1():
        await sched.start()
        try:
            h = await sched.submit("s1", prompt1, _greedy(32),
                                   conversation_id="conv")
            await _drain(h, t1)
            assert h.kv_gap > 0, "turn 1 never evicted"
        finally:
            await sched.stop()

    asyncio.run(turn1())
    entry = sched.session_cache.get("conv")
    assert entry is not None and entry.kv_gap > 0
    assert entry.n_tokens % PAGE == 0
    gap = entry.kv_gap

    sched2 = sched
    if fresh_for_turn2:
        # restart: a NEW scheduler over the same disk directory must
        # restore the record (RAM tier starts empty)
        if sched.session_cache is not None and sched.session_cache.disk:
            sched.session_cache.disk.flush()
        sched2 = _sched(params, session=True, disk=disk)

    prompt2 = prompt1 + t1 + _prompt(6, seed=7)
    snap0 = METRICS.snapshot()

    async def turn2():
        await sched2.start()
        try:
            h = await sched2.submit("s2", prompt2, _greedy(10),
                                    conversation_id="conv")
            await _drain(h, t2)
            sched2.allocator.check_invariants()
        finally:
            await sched2.stop()

    asyncio.run(turn2())
    snap1 = METRICS.snapshot()
    win = {k: snap1.get(k, 0) - snap0.get(k, 0) for k in (
        "finchat_session_cache_hits_total",
        "finchat_session_cache_restored_tokens_total",
        "finchat_durability_disk_restores_total",
    )}
    return t2, gap, win, sched2


def test_session_roundtrip_bounded_ram(params):
    t2, gap, win, sched2 = _two_turn(params)
    assert len(t2) == 10
    assert win["finchat_session_cache_hits_total"] == 1
    assert win["finchat_session_cache_restored_tokens_total"] > 0
    # the resumed row carries the entry's gap forward
    assert gap > 0


def test_session_roundtrip_bounded_disk(params, tmp_path):
    """Restart between turns: the bounded record (kv_gap in the v2
    header, CRC'd payload) restores from disk and the conversation
    resumes with its sink+window intact."""
    t2, gap, win, _ = _two_turn(
        params, disk=str(tmp_path / "skv"), fresh_for_turn2=True)
    assert len(t2) == 10
    assert win["finchat_durability_disk_restores_total"] == 1
    assert win["finchat_session_cache_hits_total"] == 1


def test_bounded_record_serialization_roundtrip():
    """Record-level: kv_gap survives the v2 header round trip, the CRC
    still covers the payload, and a gap-less record reads as gap 0."""
    from finchat_tpu.engine.session_cache import SessionDiskTier

    ids = np.arange(48, dtype=np.int32)
    snap = (np.ones((2, 3, 8, 4), np.float32), np.ones((2, 3, 8, 4), np.float32),
            None, None)
    blob = SessionDiskTier._serialize("k", ids, 8, snap, kv_gap=16)
    out = SessionDiskTier._deserialize(blob)
    assert out["kv_gap"] == 16
    assert np.array_equal(out["token_ids"], ids)
    assert np.array_equal(out["snap"][0], snap[0])
    # corruption still quarantines: flip a payload byte -> CRC mismatch
    bad = bytearray(blob)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        SessionDiskTier._deserialize(bytes(bad))
    # pre-ISSUE-15 records carry no kv_gap field: read as 0
    legacy = SessionDiskTier._serialize("k", ids, 8, snap)
    assert SessionDiskTier._deserialize(legacy)["kv_gap"] == 0


def test_gapped_entry_whole_resume_or_sink_salvage():
    """A bounded entry resumes WHOLE when the prompt extends past its
    span unchanged; a prompt stopping short leaves it intact; divergence
    stales the windowed remainder (it attended to the evicted tokens) and
    salvages at most the pre-gap sink region as a gap-free prefix."""
    from finchat_tpu.engine.session_cache import SessionEntry, SessionKVCache

    def entry(kv_sink=8):
        return SessionEntry(
            conversation_id="c",
            token_ids=np.arange(1, 41, dtype=np.int32),  # 40 tokens
            snap=(np.ones((1, 3, 8, 2), np.float32),
                  np.ones((1, 3, 8, 2), np.float32), None, None),
            kv_gap=16,  # snapshot covers 24 of the 40 tokens
            kv_sink=kv_sink,
        )

    cache = SessionKVCache(1 << 20, page_size=8)
    cache.put(entry(), spill=False)
    # full-prefix prompt that extends past the span: whole resume
    e, matched = cache.match("c", list(range(1, 41)) + [99, 98])
    assert e is not None and matched == 40 and e.kv_gap == 16
    # prompt stopping short: no resume, entry kept INTACT
    e, matched = cache.match("c", list(range(1, 31)))
    assert e is None and matched == 0
    assert cache.get("c") is not None and cache.get("c").kv_gap == 16
    # divergence past the sink: the sink region survives as a gap-free
    # prefix (one 8-token page here) and the windowed remainder is gone
    diverged = list(range(1, 41))
    diverged[20] = 999
    e, matched = cache.match("c", diverged + [99])
    assert e is not None and matched == 8
    assert e.kv_gap == 0 and e.n_tokens == 8
    # a sink-less gapped entry (kv_sink 0) has nothing to salvage
    cache.put(entry(kv_sink=0), spill=False)
    e, matched = cache.match("c", diverged + [99])
    assert e is None and matched == 0
    assert cache.get("c") is None


# --- free-run composition ---------------------------------------------------


def _freerun_workload(params, freerun):
    """Decode streams + a long bounded stream admitted mid-decode, long
    enough that eviction waves fire while captures are (or would be) in
    flight."""
    sched = _sched(params, freerun_rounds=freerun, decode_loop_depth=2,
                   max_seqs=4, num_pages=64)
    rng = np.random.default_rng(11)
    a = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    b = rng.integers(1, CONFIG.vocab_size, size=30).tolist()

    async def go():
        snap0 = METRICS.snapshot()
        await sched.start()
        try:
            ha = await sched.submit("a", a, _greedy(40))
            outs = {"a": [], "b": []}
            tasks = [asyncio.create_task(_drain(ha, outs["a"]))]
            while len(outs["a"]) < 2:
                await asyncio.sleep(0.002)
            hb = await sched.submit("b", b, _greedy(30))
            tasks.append(asyncio.create_task(_drain(hb, outs["b"])))
            await asyncio.gather(*tasks)
            await asyncio.sleep(0.05)
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            snap1 = METRICS.snapshot()
            win = {k: snap1.get(k, 0) - snap0.get(k, 0) for k in (
                "finchat_freerun_dispatches_total",
                "finchat_boundedkv_evicted_pages_total",
            )}
            return outs, win
        finally:
            await sched.stop()

    return asyncio.run(go())


def test_freerun_capture_equality_with_eviction(params):
    """Captured vs host-stepped WITH eviction active: byte-identical
    streams. Eviction is staged at capture boundaries (the boundedkv cap
    reason), so a capture's gap schedule equals the host-stepped one."""
    base, win1 = _freerun_workload(params, 1)
    fr, win4 = _freerun_workload(params, 4)
    assert win1["finchat_boundedkv_evicted_pages_total"] > 0
    assert win4["finchat_boundedkv_evicted_pages_total"] == \
        win1["finchat_boundedkv_evicted_pages_total"]
    assert win4["finchat_freerun_dispatches_total"] >= 1, (
        "captures never engaged")
    assert fr == base


# --- ring promotion ---------------------------------------------------------


def test_ring_promotion_no_demotion_and_identity(params, monkeypatch):
    """Ring-routed prefill rides the ragged round (no reason="ring"
    demotion; _use_mixed is unconditional): with a decode stream live and
    a ring-eligible prompt admitted, the coexist iterations stay fused
    and the streams equal the plain chunked scheduler's byte-for-byte."""

    def run(force_ring: bool):
        sched = _sched(params, sink=0, window=0)
        if force_ring:
            # route the long prompt down the ring predicate without a seq
            # mesh (the test_prefix_cache idiom): the promoted path must
            # treat it as packed chunk rows inside the ragged round —
            # never demote, never call the seq-sharded entry points
            monkeypatch.setattr(
                sched.engine, "_use_ring_prefill", lambda n: n >= 48)

            def boom(*a, **k):
                raise AssertionError(
                    "ring collective entry point reached from the mixed path")

            monkeypatch.setattr(sched.engine, "prefill_ring", boom)
        rng = np.random.default_rng(13)
        short = rng.integers(1, CONFIG.vocab_size, size=8).tolist()
        long_p = rng.integers(1, CONFIG.vocab_size, size=3 * CHUNK + 5).tolist()

        async def go():
            snap0 = METRICS.snapshot()
            await sched.start()
            try:
                hs = await sched.submit("short", short, _greedy(30))
                outs = {"short": [], "long": []}
                tasks = [asyncio.create_task(_drain(hs, outs["short"]))]
                while len(outs["short"]) < 2:
                    await asyncio.sleep(0.002)
                hl = await sched.submit("long", long_p, _greedy(6))
                tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
                await asyncio.gather(*tasks)
                await asyncio.sleep(0.05)
                snap1 = METRICS.snapshot()
                ring_demotions = (
                    snap1.get('finchat_mixed_demotions_total{reason="ring"}', 0)
                    - snap0.get('finchat_mixed_demotions_total{reason="ring"}', 0)
                )
                coexist = {
                    k: snap1.get(k, 0) - snap0.get(k, 0)
                    for k in ("finchat_coexist_dispatches_total",
                              "finchat_coexist_iterations_total",
                              "finchat_coexist_rounds_total")
                }
                return outs, ring_demotions, coexist
            finally:
                await sched.stop()

        return asyncio.run(go())

    plain, _, _ = run(False)
    promoted, ring_demotions, coexist = run(True)
    assert ring_demotions == 0, "ring rows still demote the mixed path"
    assert promoted == plain, "promoted ring rows changed the streams"
    iters = coexist["finchat_coexist_iterations_total"]
    assert iters > 0, "long prompt never coexisted with the decode stream"
    # the acceptance headline: one fused dispatch per coexist round even
    # with the ring-routed row in the mix
    assert coexist["finchat_coexist_dispatches_total"] == \
        coexist["finchat_coexist_rounds_total"]


@pytest.mark.slow
def test_ring_promotion_real_seq_mesh(params):
    """The same promotion on a REAL seq-sharded mesh: when no decode
    coexists the prompt runs the genuine ring collective (split path);
    when a decode stream is live the ragged round takes the chunk rows —
    the greedy continuation matches the unsharded chunked scheduler
    (the test_parallel ring/chunked equality precedent)."""
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=4, seq=2, expert=1, model=1))
    rng = np.random.default_rng(17)
    short = rng.integers(1, CONFIG.vocab_size, size=8).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=50).tolist()

    def run(use_mesh: bool):
        cfg = EngineConfig(
            max_seqs=2, page_size=PAGE, num_pages=64, max_seq_len=256,
            prefill_chunk=CHUNK, mixed_step=True, session_cache=False,
            ring_prefill_min_tokens=32, ring_prefill_chunk=16,
        )
        engine = InferenceEngine(CONFIG, params, cfg,
                                 mesh=mesh if use_mesh else None)
        sched = ContinuousBatchingScheduler(engine, eos_id=-1)

        async def go():
            snap0 = METRICS.snapshot()
            await sched.start()
            try:
                hs = await sched.submit("short", short, _greedy(24))
                outs = {"short": [], "long": []}
                tasks = [asyncio.create_task(_drain(hs, outs["short"]))]
                while len(outs["short"]) < 2:
                    await asyncio.sleep(0.002)
                if use_mesh:
                    assert engine._use_ring_prefill(len(long_p))
                hl = await sched.submit("long", long_p, _greedy(5))
                tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
                await asyncio.gather(*tasks)
                await asyncio.sleep(0.05)
                snap1 = METRICS.snapshot()
                ring_demotions = (
                    snap1.get('finchat_mixed_demotions_total{reason="ring"}', 0)
                    - snap0.get('finchat_mixed_demotions_total{reason="ring"}', 0)
                )
                return outs, ring_demotions
            finally:
                await sched.stop()

        return asyncio.run(go())

    plain, _ = run(False)
    promoted, ring_demotions = run(True)
    assert ring_demotions == 0
    assert promoted == plain


def test_bounded_rows_never_ring_route(params, monkeypatch):
    """Bounded rows must never take the seq-sharded ring path (the ring
    steps write at absolute positions with no kv_gaps awareness, and a
    segment's burst exceeds the wave's chunk reserve): even a ring-
    eligible prompt rides chunked prefill, evicts, and completes within
    the budget — the ring entry points are never reached."""
    sched = _sched(params)  # bounded: SINK + WINDOW

    def boom(*a, **k):
        raise AssertionError("ring entry point reached on a bounded row")

    monkeypatch.setattr(sched.engine, "_use_ring_prefill", lambda n: n >= 48)
    monkeypatch.setattr(sched.engine, "prefill_ring", boom)
    monkeypatch.setattr(sched.engine, "prefill_ring_segment", boom)
    prompt = _prompt(64, seed=21)  # ring-eligible AND past the 40-token budget
    out: list[int] = []

    async def go():
        await sched.start()
        try:
            h = await sched.submit("s", prompt, _greedy(12))
            assert not sched._ring_routed(h)
            await _drain(h, out)
            sched.allocator.check_invariants()
        finally:
            await sched.stop()

    asyncio.run(go())
    assert len(out) == 12


def test_gapped_entry_refused_on_unbounded_engine(params):
    """A gapped session entry arriving on an engine WITHOUT the bounded
    policy (disk restore / fleet import after the knobs were turned off)
    must cold-start — there is no eviction machinery for it to live
    under; pre-fix this crashed retirement with an AttributeError on
    bounded_kv.sink_tokens."""
    from finchat_tpu.engine.session_cache import SessionEntry

    sched = _sched(params, sink=0, window=0, session=True)
    prompt = _prompt(40, seed=22)
    snap_pages = 3
    entry = SessionEntry(
        conversation_id="conv",
        token_ids=np.asarray(prompt[:40], np.int32),
        snap=tuple(
            np.zeros((CONFIG.n_layers, snap_pages, PAGE,
                      CONFIG.n_kv_heads * CONFIG.head_dim), np.float32)
            if i < 2 else None for i in range(4)
        ),
        kv_gap=16,
        kv_sink=8,
    )
    sched.session_cache.put(entry, spill=False)
    out: list[int] = []

    async def go():
        await sched.start()
        try:
            h = await sched.submit("s", prompt + _prompt(6, seed=23),
                                   _greedy(8), conversation_id="conv")
            await _drain(h, out)
            assert h.kv_gap == 0, "gapped resume leaked onto an unbounded engine"
            sched.allocator.check_invariants()
        finally:
            await sched.stop()

    snap0 = METRICS.snapshot()
    asyncio.run(go())
    snap1 = METRICS.snapshot()
    assert len(out) == 8
    # the admission was a cold start, not a gapped resume
    assert snap1.get("finchat_session_cache_hits_total", 0) == \
        snap0.get("finchat_session_cache_hits_total", 0)


# --- config plumbing --------------------------------------------------------


def test_bounded_kv_env_readers(monkeypatch):
    monkeypatch.setenv("FINCHAT_KV_SINK_PAGES", "3")
    monkeypatch.setenv("FINCHAT_KV_WINDOW_PAGES", "17")
    cfg = load_config()
    assert cfg.engine.kv_sink_pages == 3
    assert cfg.engine.kv_window_pages == 17


def test_boundedkv_metrics_preseeded(params):
    reg = METRICS.labeled(replica="probe-bkv")
    cfg = EngineConfig(
        max_seqs=2, page_size=PAGE, num_pages=32, max_seq_len=128,
        prefill_chunk=CHUNK, session_cache=False,
        kv_sink_pages=SINK, kv_window_pages=WINDOW,
    )
    engine = InferenceEngine(CONFIG, params, cfg)
    ContinuousBatchingScheduler(engine, eos_id=-1, metrics=reg,
                                replica_id="probe-bkv")
    snap = METRICS.snapshot()
    assert snap.get('finchat_boundedkv_sink_pages{replica="probe-bkv"}') == SINK
    assert snap.get('finchat_boundedkv_window_pages{replica="probe-bkv"}') == WINDOW
    assert snap.get('finchat_boundedkv_evicted_pages_total{replica="probe-bkv"}') == 0
    assert snap.get('finchat_boundedkv_bounded_sessions_total{replica="probe-bkv"}') == 0
    assert snap.get('finchat_boundedkv_recompute_fallbacks_total{replica="probe-bkv"}') == 0
