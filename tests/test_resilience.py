"""Overload-resilient serving plane (ISSUE 5; ROBUSTNESS.md).

Pins the three-part resilience contract:

- RECOMPUTE PREEMPTION: a preempted sequence keeps prompt + generated
  tokens on its handle, replays through admission, and its greedy stream
  is byte-identical to an unpreempted run — zero duplicate or dropped
  tokens. Page pressure preempts the latest-deadline victim instead of
  stalling the earliest-deadline candidate head-of-line.
- ENGINE CIRCUIT BREAKER: ``breaker_threshold`` consecutive failed decode
  rounds trip a rebuild of the engine's device state (weights retained);
  in-flight streams survive byte-identically. A persistently wedged engine
  gives up after ``breaker_max_rebuilds`` instead of rebuild-looping.
- DEADLINE/SHED ADMISSION: past-deadline pending requests shed with a
  structured retryable error and leak nothing; admission is
  earliest-deadline-first with a starvation guard; ``max_queue_depth``
  rejects new load with a retryable overload error.

Plus the watchdog-timeout bugfix: a timed-out Kafka message releases its
scheduler slot and KV pages BEFORE the timeout chunk is emitted.
"""

import asyncio
import dataclasses
import time

import jax
import jax.numpy as jnp
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler, OverloadedError
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.utils import faults
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.disarm_all()


def _make_scheduler(**cfg_overrides):
    """Tiny fp32 stack (fp32 pins greedy byte-identity across the
    prefill-replay vs decode-step shapes — the same contract the mixed
    step's identity tests use)."""
    config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
    defaults = dict(
        max_seqs=2, page_size=8, num_pages=64, max_seq_len=128,
        prefill_chunk=16, session_cache=False,
    )
    defaults.update(cfg_overrides)
    engine_cfg = EngineConfig(**defaults)
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, engine_cfg)
    return ContinuousBatchingScheduler(engine, eos_id=-1)


async def _drain(handle):
    tokens = []
    while True:
        event = await handle.events.get()
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return tokens, None
        else:
            return tokens, event


def _greedy(max_new: int) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=max_new)


# --- recompute preemption -------------------------------------------------

def test_direct_preempt_replay_byte_identity():
    """_preempt mid-decode, then replay: the stream completes with the
    exact token sequence of an unpreempted greedy run."""
    prompt = list(range(1, 20))

    async def run(preempt_at: int | None):
        scheduler = _make_scheduler()
        await scheduler.start()
        try:
            handle = await scheduler.submit("s", prompt, _greedy(12))
            task = asyncio.create_task(_drain(handle))
            if preempt_at is not None:
                while handle.generated < preempt_at:
                    await asyncio.sleep(0.002)
                scheduler._preempt(handle)
            tokens, err = await task
            scheduler.allocator.check_invariants()
        finally:
            await scheduler.stop()
        return tokens, err, handle.preempted

    clean, err, _ = asyncio.run(run(None))
    assert err is None and len(clean) == 12
    replayed, err, n_preempted = asyncio.run(run(4))
    assert err is None
    assert n_preempted == 1
    assert replayed == clean, "preempt/replay duplicated or dropped tokens"


def test_page_pressure_preempts_latest_deadline_victim():
    """A page-starved earlier-deadline candidate preempts the deadline-less
    hog instead of stalling head-of-line; both streams complete, and the
    hog's replayed greedy stream is byte-identical to an uncontended run."""
    hog_prompt = list(range(1, 24))  # + 24 new → 6 of the 7 usable pages
    urgent_prompt = list(range(40, 56))  # + 8 new → 3 pages: must preempt

    async def run(contended: bool):
        scheduler = _make_scheduler(num_pages=8)
        await scheduler.start()
        try:
            hog = await scheduler.submit("hog", hog_prompt, _greedy(24))
            hog_task = asyncio.create_task(_drain(hog))
            urgent_tokens = None
            if contended:
                while hog.generated < 3:
                    await asyncio.sleep(0.002)
                p0 = METRICS.get("finchat_preemptions_total")
                urgent = await scheduler.submit(
                    "urgent", urgent_prompt, _greedy(8),
                    deadline=time.perf_counter() + 60.0,
                )
                urgent_tokens, uerr = await _drain(urgent)
                assert uerr is None, uerr
                assert METRICS.get("finchat_preemptions_total") > p0, (
                    "page pressure never preempted"
                )
            hog_tokens, herr = await hog_task
            assert herr is None, herr
            scheduler.allocator.check_invariants()
            assert scheduler.allocator.used_count == 0
        finally:
            await scheduler.stop()
        return hog_tokens, urgent_tokens

    clean_hog, _ = asyncio.run(run(False))
    contended_hog, urgent_tokens = asyncio.run(run(True))
    assert len(urgent_tokens) == 8
    assert contended_hog == clean_hog, (
        "preemption under page pressure changed the victim's greedy stream"
    )


# --- engine circuit breaker ----------------------------------------------

def test_breaker_trips_rebuilds_and_streams_survive():
    """breaker_threshold consecutive decode-round faults trip the breaker:
    the engine device state is rebuilt, every in-flight greedy stream
    completes byte-identical to a fault-free run, and the allocator is
    clean afterwards."""
    prompts = [list(range(1, 14)), list(range(20, 38))]

    async def run(fault: bool):
        scheduler = _make_scheduler()
        rebuilt = []
        scheduler.on_rebuild.append(lambda: rebuilt.append(True))
        await scheduler.start()
        try:
            handles = [
                await scheduler.submit(f"s{i}", p, _greedy(10))
                for i, p in enumerate(prompts)
            ]
            tasks = [asyncio.create_task(_drain(h)) for h in handles]
            if fault:
                while any(h.generated < 2 for h in handles):
                    await asyncio.sleep(0.002)
                faults.arm(
                    "scheduler.decode",
                    faults.n_shot(scheduler.breaker_threshold,
                                  RuntimeError("wedged dispatch")),
                )
            results = [await t for t in tasks]
            assert all(err is None for _, err in results), results
            scheduler.allocator.check_invariants()
            assert scheduler.allocator.used_count == 0
            assert len(scheduler.free_slots) == 2
        finally:
            await scheduler.stop()
        return [tokens for tokens, _ in results], bool(rebuilt)

    clean, rebuilt = asyncio.run(run(False))
    assert not rebuilt
    r0 = METRICS.get("finchat_engine_rebuilds_total")
    survived, rebuilt = asyncio.run(run(True))
    assert rebuilt, "on_rebuild callbacks never ran"
    assert METRICS.get("finchat_engine_rebuilds_total") == r0 + 1
    assert METRICS.get("finchat_breaker_state") == 0  # closed by the probe round
    assert survived == clean, "streams did not survive the rebuild byte-identically"
    # recovery latency was observed
    assert METRICS.quantile("finchat_breaker_recovery_seconds", 0.5) > 0


def test_breaker_gives_up_after_max_rebuilds_then_recovers():
    """A PERSISTENT fault must not rebuild-loop forever: after
    breaker_max_rebuilds consecutive trips the in-flight streams fail with
    an error — and once the fault clears, the engine serves again."""

    async def run():
        scheduler = _make_scheduler(breaker_threshold=2, breaker_max_rebuilds=1)
        await scheduler.start()
        try:
            def always_fail(**_ctx):
                raise RuntimeError("dead device")

            faults.arm("scheduler.decode", always_fail)
            handle = await scheduler.submit("doomed", list(range(1, 14)), _greedy(8))
            tokens, err = await asyncio.wait_for(_drain(handle), timeout=60)
            assert err is not None and "dead device" in err["message"]
            faults.disarm_all()
            handle2 = await scheduler.submit("healthy", list(range(1, 14)), _greedy(8))
            tokens2, err2 = await asyncio.wait_for(_drain(handle2), timeout=60)
            assert err2 is None and len(tokens2) == 8
            scheduler.allocator.check_invariants()
        finally:
            await scheduler.stop()

    asyncio.run(run())


# --- deadline shed / EDF admission / backpressure -------------------------

def test_expired_deadline_sheds_with_structured_retryable_error():
    """A pending request past its deadline is shed pre-admission with a
    structured retryable error chunk — and frees nothing it never held."""

    async def run():
        scheduler = _make_scheduler()
        await scheduler.start()
        try:
            s0 = METRICS.get("finchat_sheds_total")
            handle = await scheduler.submit(
                "late", list(range(1, 14)), _greedy(8),
                deadline=time.perf_counter() - 1.0,
            )
            tokens, err = await asyncio.wait_for(_drain(handle), timeout=30)
            assert tokens == []
            assert err is not None
            assert err["code"] == "deadline_exceeded"
            assert err["retryable"] is True
            assert METRICS.get("finchat_sheds_total") == s0 + 1
            assert len(scheduler.free_slots) == 2
            assert scheduler.allocator.used_count == 0
        finally:
            await scheduler.stop()

    asyncio.run(run())


def test_edf_ordering_and_starvation_guard():
    """Admission order is earliest-deadline-first; an entry that has waited
    past edf_starvation_seconds jumps ahead of deadline order."""

    async def run():
        scheduler = _make_scheduler(edf_starvation_seconds=5.0)
        now = time.perf_counter()
        a = await scheduler.submit("a", [1, 2, 3], _greedy(4))  # no deadline
        b = await scheduler.submit("b", [1, 2, 3], _greedy(4), deadline=now + 50)
        c = await scheduler.submit("c", [1, 2, 3], _greedy(4), deadline=now + 5)
        scheduler._prepare_pending()
        assert [h.seq_id for h in scheduler.pending] == ["c", "b", "a"]
        # starve a: it jumps ahead of every deadline
        a.submitted_at = now - 10.0
        scheduler._prepare_pending()
        assert [h.seq_id for h in scheduler.pending] == ["a", "c", "b"]

    asyncio.run(run())


def test_submit_backpressure_above_max_queue_depth():
    async def run():
        scheduler = _make_scheduler(max_queue_depth=1)
        await scheduler.submit("q1", [1, 2, 3], _greedy(4))
        with pytest.raises(OverloadedError) as ei:
            await scheduler.submit("q2", [1, 2, 3], _greedy(4))
        assert ei.value.retryable is True
        assert ei.value.code == "overloaded"

    asyncio.run(run())


# --- watchdog timeout: no slot leak (serve/app.py bugfix) -----------------

def _engine_app(scheduler, tokenizer, watchdog: float):
    from finchat_tpu.engine.generator import EngineGenerator, StubGenerator
    from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient
    from finchat_tpu.io.store import InMemoryStore
    from finchat_tpu.serve.app import build_app
    from finchat_tpu.utils.config import load_config

    cfg = load_config(overrides={"model.preset": "stub"})
    cfg.engine.watchdog_seconds = watchdog
    cfg.engine.max_new_tokens = 96
    cfg.engine.temperature = 0.0
    broker = InMemoryBroker()
    store = InMemoryStore()
    store.upsert_context(
        "c1", {"user_id": "u9", "name": "Alex", "income": 5000, "savings_goal": 800}
    )
    store.add_user_message("c1", "How am I doing?", "u9")

    class NullRetriever:
        async def __call__(self, args):
            return []

    app = build_app(
        cfg, store=store, kafka=KafkaClient(cfg.kafka, broker=broker),
        tool_generator=StubGenerator(default="No tool call"),
        response_generator=EngineGenerator(scheduler, tokenizer),
        retriever=NullRetriever(),
    )
    return app, broker


async def test_watchdog_timeout_releases_slot_before_timeout_chunk():
    """A timed-out Kafka message must cancel its in-flight generation and
    release the scheduler slot + KV pages BEFORE the timeout chunk goes
    out — the engine keeps full capacity after a watchdog fire."""
    import json

    from finchat_tpu.io.kafka import Message
    from finchat_tpu.models.tokenizer import ByteTokenizer
    from finchat_tpu.utils.config import AI_RESPONSE_TOPIC, USER_MESSAGE_TOPIC

    tok = ByteTokenizer()
    scheduler = _make_scheduler()
    app, broker = _engine_app(scheduler, tok, watchdog=0.4)
    await scheduler.start()
    try:
        # ~30 ms per decode dispatch: generation cannot finish 96 tokens
        # inside the 0.4 s watchdog
        faults.arm("scheduler.decode", lambda **_ctx: time.sleep(0.03))  # finchat-lint: disable=event-loop-blocking -- deliberate fault payload: slows decode dispatch so the watchdog fires mid-generation
        payload = {"message": "tell me everything", "conversation_id": "c1",
                   "user_id": "u9"}
        msg = Message(USER_MESSAGE_TOPIC, "c1", json.dumps(payload).encode())
        await app._process_with_watchdog(msg, payload, None)
        # the fix's ordering guarantee: by the time the timeout chunk is
        # emitted (i.e. _process_with_watchdog returned), the slot and
        # every KV page are already back — no drain/grace loop here
        assert scheduler.allocator.used_count == 0, "timed-out message leaked KV pages"
        assert not scheduler.decoding and not scheduler.prefilling
        assert len(scheduler.free_slots) == 2, "timed-out message leaked its slot"
        out = [json.loads(m.value().decode()) for m in broker.drain(AI_RESPONSE_TOPIC)]
        assert out and out[-1]["message"] == "Request timed out. Please try again."
        assert out[-1]["error"] is True
    finally:
        faults.disarm_all()
        await scheduler.stop()


async def test_expired_kafka_message_sheds_with_structured_error_chunk():
    """End-to-end deadline plane: a Kafka message whose producer timestamp
    is far in the past (deadline = timestamp + allowance) is shed by the
    scheduler and the outbound error chunk carries the structured
    code/retryable fields."""
    import json

    from finchat_tpu.io.kafka import Message
    from finchat_tpu.models.tokenizer import ByteTokenizer
    from finchat_tpu.utils.config import AI_RESPONSE_TOPIC, USER_MESSAGE_TOPIC

    tok = ByteTokenizer()
    scheduler = _make_scheduler()
    app, broker = _engine_app(scheduler, tok, watchdog=30.0)
    app.cfg.engine.request_deadline_seconds = 5.0
    await scheduler.start()
    try:
        payload = {"message": "too late", "conversation_id": "c1", "user_id": "u9"}
        msg = Message(
            USER_MESSAGE_TOPIC, "c1", json.dumps(payload).encode(),
            timestamp_ms=int((time.time() - 120.0) * 1000),
        )
        await app._process_with_watchdog(msg, payload, None)
        out = [json.loads(m.value().decode()) for m in broker.drain(AI_RESPONSE_TOPIC)]
        assert out, "expected a shed error chunk"
        err = out[-1]
        assert err["error"] is True and err["last_message"] is True
        assert err["code"] == "deadline_exceeded"
        assert err["retryable"] is True
        assert scheduler.allocator.used_count == 0
    finally:
        await scheduler.stop()
