"""Quantized serving plane end-to-end (ISSUE 14).

The contracts under test:

- int4 weight quantization (models/quant.py Q4Tensor): packed two
  nibbles per byte, per-channel or per-group scales, stacked build
  bitwise-identical to whole-leaf, bounded roundtrip error, forward
  logits inside the quality envelope vs full precision.
- int8 KV as a first-class page dtype on every serving path: the
  free-run capture equals host-stepped rounds, spec-verify acceptance
  stays greedy-exact, and the session tier round-trips the scale planes
  byte-identically (RAM and disk).
- Record-format versioning (SessionDiskTier v2): dtypes stored by NAME
  (v1's ``dtype.str`` made bf16 snapshots unreadable — the latent bug
  this version fixes), v1 records stay readable, cross-mode records are
  refused with a counted quarantine-style fallback instead of serving
  garbage KV.
- The quantized embed encoder ranks like the fp32 one (top-k overlap
  >= 0.99 on a golden corpus).
- Observability: quant mode labels stay inside the declared registries
  and ride every dispatch trace event; the finchat_quant_* family is
  pre-seeded.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token, prefill_step
from finchat_tpu.engine.kv_cache import (
    PageAllocator,
    gather_pages_host,
    pages_needed,
    scatter_pages_device,
)
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.engine.session_cache import SessionDiskTier, snap_kv_mode
from finchat_tpu.models.llama import PRESETS, forward_full, init_params
from finchat_tpu.models.quant import (
    Q4Tensor,
    dequantize,
    init_quantized_llama_params,
    quantize_int4,
    quantize_stacked,
    validate_quant_mode,
)
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import QUANT_MODES, TRACER

# fp32 pins the byte-identity contracts (the PR 4/10 discipline): int8
# page ints and fp32 scale planes round-trip bit-exactly, so restored KV
# must decode exactly like recomputed KV
CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


# --- int4 weight machinery --------------------------------------------------


def test_int4_roundtrip_error_bound():
    w = jax.random.normal(jax.random.key(0), (24, 16), jnp.float32)
    qt = quantize_int4(w)
    deq = np.asarray(dequantize(qt, jnp.float32))
    # symmetric rounding: error per element <= half the column's scale
    bound = np.asarray(qt.scale)[0][None, :] / 2 + 1e-7
    assert np.all(np.abs(deq - np.asarray(w)) <= bound)


def test_int4_exact_on_representable_values():
    # values that are exact multiples of amax/7 round-trip exactly
    scale = 0.37
    ints = np.random.default_rng(0).integers(-7, 8, size=(8, 4))
    ints[0, :] = 7  # pin each column's amax so scale = 7*s/7 = s
    w = jnp.asarray(ints * scale, jnp.float32)
    qt = quantize_int4(w)
    assert np.allclose(np.asarray(dequantize(qt, jnp.float32)),
                       np.asarray(w), atol=1e-6)


def test_int4_group_scales_shapes_and_tighter_error():
    w = jax.random.normal(jax.random.key(1), (32, 8), jnp.float32)
    per_col = quantize_int4(w)
    grouped = quantize_int4(w, group_size=8)
    assert per_col.scale.shape == (1, 8)
    assert grouped.scale.shape == (4, 8)
    assert per_col.shape == grouped.shape == (32, 8)
    err_col = float(jnp.max(jnp.abs(dequantize(per_col, jnp.float32) - w)))
    err_grp = float(jnp.max(jnp.abs(dequantize(grouped, jnp.float32) - w)))
    assert err_grp <= err_col + 1e-7
    with pytest.raises(AssertionError):
        quantize_int4(w, group_size=3)  # odd groups can't pack nibble pairs


def test_int4_stacked_bitwise_matches_whole_leaf():
    w = jax.random.normal(jax.random.key(2), (3, 16, 8), jnp.float32)
    stacked = quantize_stacked(w, mode="int4", group_size=4)
    whole = quantize_int4(w, group_size=4)
    assert isinstance(stacked, Q4Tensor)
    assert np.array_equal(np.asarray(stacked.q), np.asarray(whole.q))
    assert np.array_equal(np.asarray(stacked.scale), np.asarray(whole.scale))


@pytest.mark.parametrize("group", [0, 32])
def test_int4_forward_logits_track_fp32(params, group):
    """The quality envelope: an int4 tree's full-causal logits stay within
    a bounded relative delta of the fp32 tree's (coarser than int8 — 15
    levels per group — but bounded; the bench --quant-sweep gates the same
    figure per mode)."""
    qparams = init_quantized_llama_params(
        CONFIG, jax.random.key(0), mode="int4", group_size=group)
    tokens = jnp.asarray([[5, 9, 2, 100, 17, 3, 44, 8]], jnp.int32)
    pos = jnp.arange(8)[None, :]
    base = np.asarray(forward_full(params, tokens, pos, config=CONFIG))
    got = np.asarray(forward_full(qparams, tokens, pos, config=CONFIG))
    rel = np.max(np.abs(got - base)) / np.max(np.abs(base))
    assert 0 < rel < 0.6
    if group:
        # per-group scales must not be WORSE than per-channel at the
        # smallest group that spans the whole contraction (same scales)
        assert got.shape == base.shape


def test_quant_mode_validation():
    validate_quant_mode("")
    validate_quant_mode("int8")
    validate_quant_mode("int4")
    with pytest.raises(ValueError):
        validate_quant_mode("int2")
    with pytest.raises(ValueError):
        InferenceEngine(CONFIG, init_params(CONFIG, jax.random.key(0)),
                        EngineConfig(max_seqs=2, page_size=8, num_pages=16,
                                     max_seq_len=64, prefill_chunk=8),
                        quant="fp8")


def test_int4_engine_serves_and_labels(params):
    cfg = EngineConfig(max_seqs=2, page_size=8, num_pages=32, max_seq_len=128,
                       prefill_chunk=8, kv_quant="int8")
    eng = InferenceEngine(CONFIG, params, cfg, quant="int4", quant_group=32)
    assert eng.quant_label == "int4+kv8"
    alloc = PageAllocator(cfg.num_pages)
    eng.set_page_table_row(0, alloc.allocate("s", 4))
    logits = eng.prefill(0, [5, 9, 2, 100, 17, 3])
    assert np.isfinite(np.asarray(logits)).all()


# --- int8-KV on the whole hot path -----------------------------------------


def _kv8_engine(params, **over):
    cfg = EngineConfig(max_seqs=4, page_size=8, num_pages=64, max_seq_len=128,
                       prefill_chunk=8, kv_quant="int8", **over)
    return InferenceEngine(CONFIG, params, cfg), cfg


def test_session_offload_restore_byte_identity_ram_and_disk(params, tmp_path):
    """The ISSUE 14 session contract: an int8-KV page snapshot — data ints
    AND per-token-per-head scale planes — survives offload -> disk record
    -> restore byte-identically, so a resumed turn decodes the exact same
    KV the retiring turn wrote."""
    eng, cfg = _kv8_engine(params)
    alloc = PageAllocator(cfg.num_pages)
    pages = alloc.allocate("s", 4)
    eng.set_page_table_row(0, pages)
    eng.prefill(0, list(range(1, 25)))  # 3 pages of real KV
    snap = eng.offload_pages(pages[:3])
    assert snap[2] is not None and snap[3] is not None  # scale planes travel
    assert snap_kv_mode(snap) == "int8"

    # disk roundtrip (record v2): byte-identical including scales
    tier = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False,
                           kv_quant="int8")
    assert tier.spill("conv", np.arange(24, dtype=np.int32), 0, snap)
    payload = tier.load("conv")
    assert payload is not None
    for a, b in zip(payload["snap"], snap):
        assert np.array_equal(a, b)

    # restore into FRESH pages on a second engine: gathered bytes equal
    eng2, _ = _kv8_engine(params)
    fresh = [9, 10, 11]
    s = eng2.state
    k, v, ks, vs = scatter_pages_device(
        s.k_pages, s.v_pages, s.k_scales, s.v_scales, fresh, payload["snap"])
    back = gather_pages_host(k, v, ks, vs, fresh)
    for a, b in zip(back, snap):
        assert np.array_equal(a, b)


def test_scatter_pages_cross_mode_raises(params):
    """The last line behind the counted refusal gates: a cross-mode
    snapshot must raise, never value-cast into plausible garbage KV."""
    eng_bf = InferenceEngine(
        CONFIG, params,
        EngineConfig(max_seqs=2, page_size=8, num_pages=32, max_seq_len=64,
                     prefill_chunk=8),
    )
    eng_q8, _ = _kv8_engine(params)
    alloc = PageAllocator(32)
    pages = alloc.allocate("s", 2)
    eng_q8.set_page_table_row(0, pages)
    eng_q8.prefill(0, list(range(1, 10)))
    snap_q8 = eng_q8.offload_pages(pages)
    s = eng_bf.state
    with pytest.raises(ValueError, match="cross-mode"):
        scatter_pages_device(s.k_pages, s.v_pages, s.k_scales, s.v_scales,
                             [3, 4], snap_q8)


def test_import_session_entry_cross_mode_refused_and_counted(params):
    """A cross-mode export (fleet handoff / disk record from an engine
    serving the other page dtype) is refused at import — counted as a
    dequant fallback — and the conversation resumes cold."""
    cfg = EngineConfig(max_seqs=2, page_size=8, num_pages=32, max_seq_len=64,
                       prefill_chunk=8, session_cache=True,
                       session_cache_bytes=1 << 20)
    sched = ContinuousBatchingScheduler(
        InferenceEngine(CONFIG, params, cfg), eos_id=-1)
    snap_q8 = (np.zeros((2, 1, 8, 16), np.int8), np.zeros((2, 1, 8, 16), np.int8),
               np.ones((2, 1, 8, 8), np.float32), np.ones((2, 1, 8, 8), np.float32))
    payload = {"conversation_id": "x", "token_ids": np.arange(8, dtype=np.int32),
               "prefix_len": 0, "snap": snap_q8}
    before = METRICS.get("finchat_quant_dequant_fallbacks_total")
    assert not sched.import_session_entry(payload)
    assert METRICS.get("finchat_quant_dequant_fallbacks_total") == before + 1
    assert sched.session_cache.get("x") is None


def test_freerun_capture_matches_stepped_rounds_int8kv(params):
    """ISSUE 14 acceptance: the free-running capture composes with
    quantized pages — a 3-round ragged_multi_round over an int8-KV pool
    equals 3 host-stepped ragged_mixed_step rounds exactly (ring tokens,
    emission counts, fused tails, final device state)."""
    from finchat_tpu.engine.engine import ragged_mixed_step, ragged_multi_round

    CHUNK = 16

    def prepare():
        cfg = EngineConfig(max_seqs=4, page_size=8, num_pages=64,
                           max_seq_len=128, prefill_chunk=CHUNK,
                           decode_loop_depth=2, freerun_rounds=3,
                           kv_quant="int8")
        eng = InferenceEngine(CONFIG, params, cfg)
        alloc = PageAllocator(cfg.num_pages)
        p0 = [3, 7, 11, 200, 42]
        eng.set_page_table_row(0, alloc.allocate("s0", pages_needed(len(p0) + 16, 8)))
        logits = eng.prefill(0, p0)
        eng.state, _ = commit_first_token(
            eng.state, jnp.int32(0), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0))
        p1 = list(range(1, CHUNK + 6))
        eng.set_page_table_row(1, alloc.allocate("s1", pages_needed(len(p1) + 16, 8)))
        eng.state, _ = prefill_step(
            eng.params, eng.state,
            jnp.asarray([p1[:CHUNK]], jnp.int32), jnp.asarray([1], jnp.int32),
            jnp.asarray([0], jnp.int32), jnp.asarray([CHUNK], jnp.int32),
            config=eng.config, page_size=8, attn_backend=eng.attn_backend)
        return eng, p1

    B = R = 4
    F, T = 3, 8
    zR, oR = jnp.zeros((R,)), jnp.ones((R,))
    kR = jnp.zeros((R,), jnp.int32)
    zB, oB = jnp.zeros((B,)), jnp.ones((B,))
    kB = jnp.zeros((B,), jnp.int32)

    def stage():
        eng, p1 = prepare()
        tail = p1[CHUNK:]
        tokens = np.zeros((F, T), np.int32)
        tok_row = np.full((F, T), R, np.int32)
        row_slot = np.zeros((R,), np.int32)
        row_slot[0], row_slot[1] = 1, 0
        row_start = np.zeros((F, R), np.int32)
        row_len = np.zeros((F, R), np.int32)
        from_dev = np.zeros((F, R), bool)
        arm = np.zeros((F, R), bool)
        loop_active = np.zeros((F, B), bool)
        tokens[0, : len(tail)] = tail
        tok_row[0, : len(tail)] = 0
        tok_row[0, len(tail)] = 1
        row_start[0, 0], row_len[0, 0], arm[0, 0] = CHUNK, len(tail), True
        row_len[0, 1], from_dev[0, 1], arm[0, 1] = 1, True, True
        loop_active[0, 0] = True
        for r in (1, 2):
            tok_row[r, 0], tok_row[r, 1] = 0, 1
            row_len[r, 0], from_dev[r, 0], arm[r, 0] = 1, True, True
            row_len[r, 1], from_dev[r, 1], arm[r, 1] = 1, True, True
            loop_active[r, 0] = True
        return eng, (tokens, tok_row, row_slot, row_start, row_len,
                     from_dev, arm, loop_active)

    eng_s, (tokens, tok_row, row_slot, row_start, row_len, from_dev, arm,
            loop_active) = stage()
    stepped = []
    for r in range(F):
        eng_s.state, emitted, n_em, _lg, blk = ragged_mixed_step(
            eng_s.params, eng_s.state,
            jnp.asarray(tokens[r]), jnp.asarray(tok_row[r]),
            jnp.asarray(row_slot), jnp.asarray(row_start[r]),
            jnp.asarray(row_len[r]), jnp.asarray(from_dev[r]),
            jnp.asarray(arm[r]), jnp.zeros((R,), jnp.int32),
            zR, oR, kR, jnp.asarray(loop_active[r]), zB, oB, kB,
            jnp.int32(-1),
            config=eng_s.config, page_size=8, attn_backend=eng_s.attn_backend,
            spec_width=0, loop_depth=2)
        stepped.append((np.asarray(emitted[:, 0]).tolist(),
                        np.asarray(n_em).tolist(), np.asarray(blk).tolist()))
    final_s = (np.asarray(eng_s.state.context_lens).tolist(),
               np.asarray(eng_s.state.last_tokens).tolist())

    eng_c, (tokens, tok_row, row_slot, row_start, row_len, from_dev, arm,
            loop_active) = stage()
    eng_c.state, ring_tok, ring_n, ring_blk = ragged_multi_round(
        eng_c.params, eng_c.state,
        jnp.asarray(tokens), jnp.asarray(tok_row), jnp.asarray(row_slot),
        jnp.asarray(row_start), jnp.asarray(row_len), jnp.asarray(from_dev),
        jnp.asarray(arm), zR, oR, kR, jnp.asarray(loop_active),
        zB, oB, kB, jnp.int32(-1),
        config=eng_c.config, page_size=8, attn_backend=eng_c.attn_backend,
        loop_depth=2)
    captured = [(np.asarray(ring_tok[r]).tolist(),
                 np.asarray(ring_n[r]).tolist(),
                 np.asarray(ring_blk[r]).tolist()) for r in range(F)]
    final_c = (np.asarray(eng_c.state.context_lens).tolist(),
               np.asarray(eng_c.state.last_tokens).tolist())
    assert captured == stepped
    assert final_c == final_s


def test_spec_verify_acceptance_parity_int8kv(params):
    """Spec verify under quantized KV keeps the greedy-exactness
    contract: oracle drafts fully accept, garbage drafts fully reject,
    and the emitted stream equals token-by-token decode — on the SAME
    int8-KV engine config, so acceptance is judged against the quantized
    model's own greedy stream."""
    cfg = EngineConfig(max_seqs=4, page_size=8, num_pages=64, max_seq_len=128,
                       prefill_chunk=8, kv_quant="int8")
    KD = 3
    prompt = [5, 9, 2, 100, 17, 3]
    n_new = 9

    def arm(eng, alloc, prompt):
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0))
        return int(tok)

    def plain():
        eng = InferenceEngine(CONFIG, params, cfg)
        out = [arm(eng, PageAllocator(cfg.num_pages), prompt)]
        B = cfg.max_seqs
        active = jnp.zeros((B,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return out

    def spec(drafts_for):
        eng = InferenceEngine(CONFIG, params, cfg)
        out = [arm(eng, PageAllocator(cfg.num_pages), prompt)]
        B = cfg.max_seqs
        active = jnp.zeros((B,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
        steps = 0
        while len(out) < n_new:
            proposal = list(drafts_for(out))[: min(KD, n_new - len(out) - 1)]
            drafts = np.zeros((B, KD), np.int32)
            n_drafts = np.zeros((B,), np.int32)
            drafts[0, : len(proposal)] = proposal
            n_drafts[0] = len(proposal)
            emitted, n_emitted = eng.decode_spec(
                active, jnp.asarray(drafts), jnp.asarray(n_drafts), z, o, zk)
            n = int(n_emitted[0])
            assert 1 <= n <= len(proposal) + 1
            out.extend(int(t) for t in np.asarray(emitted[0, :n]))
            steps += 1
        return out, steps

    want = plain()
    got, steps = spec(lambda so_far: want[len(so_far): len(so_far) + KD])
    assert got == want
    assert steps == -(-(n_new - 1) // (KD + 1))  # full acceptance
    wrong = [(t + 1) % CONFIG.vocab_size for t in want]
    got, steps = spec(lambda so_far: wrong[len(so_far): len(so_far) + KD])
    assert got == want
    assert steps == n_new - 1  # nothing accepted


def test_scheduler_resume_byte_identity_int8kv(params, tmp_path):
    """Scheduler-level: turn 2 resumed from the quantized session tier
    (RAM + disk write-through) is byte-identical to a cold re-prefill on
    a fresh int8-KV engine, and the resume dispatches fewer chunks."""
    def run(session: bool, turn2_prompt=None):
        cfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=64, max_seq_len=256,
            prefill_chunk=16, kv_quant="int8", session_cache=session,
            session_cache_bytes=1 << 20,
            session_cache_disk_path=str(tmp_path / "skv") if session else "",
        )
        sched = ContinuousBatchingScheduler(
            InferenceEngine(CONFIG, params, cfg), eos_id=-1)
        rng = np.random.default_rng(3)
        p1 = rng.integers(1, CONFIG.vocab_size, size=40).tolist()
        out = {}

        async def go():
            await sched.start()
            try:
                async def stream(seq, prompt):
                    h = await sched.submit(
                        seq, prompt,
                        SamplingParams(temperature=0.0, max_new_tokens=12),
                        conversation_id="conv")
                    toks = []
                    while True:
                        ev = await asyncio.wait_for(h.events.get(), timeout=120)
                        if ev["type"] == "token":
                            toks.append(ev["token_id"])
                        elif ev["type"] == "done":
                            return toks
                        else:
                            raise AssertionError(ev)

                t1 = await stream("t1", p1)
                prompt2 = turn2_prompt if turn2_prompt is not None else (
                    p1 + t1 + rng.integers(1, CONFIG.vocab_size, size=10).tolist())
                c0 = METRICS.snapshot().get("finchat_prefill_seconds_count", 0)
                t2 = await stream("t2", prompt2)
                out["chunks"] = METRICS.snapshot().get(
                    "finchat_prefill_seconds_count", 0) - c0
                return prompt2, t2
            finally:
                await sched.stop()

        return asyncio.run(go()) + (out["chunks"],)

    prompt2, warm_t2, warm_chunks = run(True)
    _, cold_t2, cold_chunks = run(False, turn2_prompt=prompt2)
    assert warm_t2 == cold_t2
    assert warm_chunks < cold_chunks


# --- quantized embed encoder ------------------------------------------------


def test_quantized_embed_topk_overlap():
    """The retrieval-quality gate: int8 encoder rankings overlap the fp32
    encoder's top-k >= 0.99 on a golden corpus (per-channel weight
    rounding moves cosine scores ~1e-3 — far below ranking resolution)."""
    from finchat_tpu.embed.encoder import (
        EMBED_PRESETS,
        EmbeddingEncoder,
        init_bert_params,
    )
    from finchat_tpu.models.tokenizer import ByteTokenizer

    cfg = EMBED_PRESETS["bge-tiny"]
    p = init_bert_params(cfg, jax.random.key(0))
    enc = EmbeddingEncoder(cfg, p, ByteTokenizer())
    encq = EmbeddingEncoder(cfg, p, ByteTokenizer(), quant="int8")
    corpus = [
        f"{i}: {kind} {3 * i + 7}.{(13 * i) % 100:02d} at {place}-{i % 7}"
        for i, (kind, place) in enumerate(
            (kind, place)
            for kind in ("coffee", "grocery", "rent", "salary", "transfer")
            for place in ("acme", "downtown", "north", "airport")
        )
    ]
    queries = ["coffee purchases", "rent payment", "salary deposit",
               "airport spending", "grocery run downtown"]
    E, Eq = enc.embed_batch(corpus), encq.embed_batch(corpus)
    overlaps = []
    K, EPS = 10, 2e-3
    for q in queries:
        s = E @ enc.embed_query(q)  # fp32 scores (the reference ranking)
        b = np.argsort(-(Eq @ encq.embed_query(q)))[:K]
        # near-tie tolerant: a quantized pick whose FP32 score sits within
        # the quant envelope of the rank-K boundary is not a real ranking
        # change — random tiny weights cluster scores ~1e-3 apart at the
        # boundary, which no ranking (fp32 included) resolves stably
        kth = np.sort(s)[-K]
        overlaps.append(float(np.mean(s[b] >= kth - EPS)))
    assert float(np.mean(overlaps)) >= 0.99
    with pytest.raises(ValueError):
        EmbeddingEncoder(cfg, p, ByteTokenizer(), quant="int4")


# --- record-format versioning ----------------------------------------------


def test_bf16_snapshot_dtype_roundtrips(tmp_path):
    """The v1 latent bug, fixed: bf16 arrays serialize by dtype NAME and
    deserialize bit-exactly (v1 stored np.dtype.str — '<V2' void — and
    every bf16 record quarantined at restore)."""
    import ml_dtypes

    snap = (np.arange(64, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 2, 16),
            np.ones((2, 2, 16), ml_dtypes.bfloat16), None, None)
    tier = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False)
    assert tier.spill("c", np.arange(8, dtype=np.int32), 0, snap)
    p = tier.load("c")
    assert p is not None and p["snap"][0].dtype == ml_dtypes.bfloat16
    assert np.array_equal(p["snap"][0].view(np.uint16), snap[0].view(np.uint16))


def test_v1_record_still_readable(tmp_path):
    import json

    snap = (np.ones((2, 1, 4), np.float32), np.ones((2, 1, 4), np.float32),
            None, None)
    blob = SessionDiskTier._serialize("c3", np.arange(4, dtype=np.int32), 0, snap)
    hlen = int.from_bytes(blob[5:9], "big")
    hdr = json.loads(blob[9:9 + hlen])
    payload = blob[9 + hlen:]
    hdr.pop("kv")  # v1 had no mode stamp
    for s in hdr["snap"]:
        if s:
            s["dtype"] = np.dtype(s["dtype"]).str  # v1 stored dtype.str
    h2 = json.dumps(hdr).encode()
    v1 = SessionDiskTier.MAGIC + bytes([1]) + len(h2).to_bytes(4, "big") + h2 + payload
    (tmp_path / SessionDiskTier._fname("c3")).write_bytes(v1)
    tier = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False)
    p = tier.load("c3")
    assert p is not None and p["snap"][0].dtype == np.float32
    assert np.array_equal(p["snap"][0], snap[0])


@pytest.mark.parametrize("direction", ["q8_into_bf16", "bf16_into_q8"])
def test_cross_mode_record_refused_and_counted(tmp_path, direction):
    """A valid record written under the other page-pool dtype is set
    aside (*.crossmode — quarantine-style, distinct from corruption),
    counted as a dequant fallback, and the conversation cold-starts; the
    startup sweep applies the same policy."""
    if direction == "q8_into_bf16":
        snap = (np.ones((2, 1, 8, 16), np.int8), np.ones((2, 1, 8, 16), np.int8),
                np.ones((2, 1, 8, 8), np.float32), np.ones((2, 1, 8, 8), np.float32))
        writer_mode, reader_mode = "int8", ""
    else:
        snap = (np.ones((2, 1, 8, 16), np.float32),
                np.ones((2, 1, 8, 16), np.float32), None, None)
        writer_mode, reader_mode = "", "int8"
    writer = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False,
                             kv_quant=writer_mode)
    assert writer.spill("conv", np.arange(8, dtype=np.int32), 0, snap)
    before = METRICS.get("finchat_quant_dequant_fallbacks_total")
    q_before = METRICS.get("finchat_durability_quarantines_total")
    reader = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False,
                             kv_quant=reader_mode)
    assert "conv" not in reader  # sweep set it aside
    assert reader.load("conv") is None
    assert METRICS.get("finchat_quant_dequant_fallbacks_total") == before + 1
    # NOT a quarantine: the record is valid, just for the other mode
    assert METRICS.get("finchat_durability_quarantines_total") == q_before
    assert list(tmp_path.glob("*.crossmode"))


def test_prefix_only_records_are_mode_agnostic(tmp_path):
    """A record with no snapshot (shared-head-only entry) restores under
    either mode — nothing to scatter, nothing to refuse."""
    writer = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False,
                             kv_quant="int8")
    assert writer.spill("conv", np.arange(16, dtype=np.int32), 16, None)
    reader = SessionDiskTier(str(tmp_path), 1 << 20, async_writes=False,
                             kv_quant="")
    p = reader.load("conv")
    assert p is not None and p["snap"] is None and p["prefix_len"] == 16


# --- observability ----------------------------------------------------------


def test_quant_labels_in_registry(params):
    """Every label the engine can emit is declared in tracing.QUANT_MODES
    (the timeline consumers' source of truth)."""
    base = EngineConfig(max_seqs=2, page_size=8, num_pages=16,
                        max_seq_len=64, prefill_chunk=8)
    for quant in ("", "int8", "int4"):
        for kv in ("", "int8"):
            eng = InferenceEngine(
                CONFIG, params, dataclasses.replace(base, kv_quant=kv),
                quant=quant)
            assert eng.quant_label in QUANT_MODES, eng.quant_label


def test_quant_metrics_preseeded_and_dispatch_traced(params):
    """Scheduler construction pre-seeds the finchat_quant_* family (mode
    gauges in bits, zeroed fallback/envelope counters) and every dispatch
    trace event carries the quant label."""
    cfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64,
                       prefill_chunk=8, kv_quant="int8")
    sched = ContinuousBatchingScheduler(
        InferenceEngine(CONFIG, params, cfg, quant="int4"), eos_id=-1)
    snap = METRICS.snapshot()
    assert snap.get("finchat_quant_weight_bits") == 4
    assert snap.get("finchat_quant_kv_bits") == 8
    assert "finchat_quant_dequant_fallbacks_total" in snap
    assert "finchat_quant_envelope_exceeded_total" in snap
    TRACER.configure(enabled=True)
    sched._trace_dispatch("decode", [[0, "tid", "decode"]])
    ev = TRACER.snapshot()[-1]
    assert ev[2] == "dispatch" and ev[5]["quant"] == "int4+kv8"
