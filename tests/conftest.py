"""Test environment: force an 8-device CPU mesh BEFORE jax initializes.

SURVEY §4.3 — ``xla_force_host_platform_device_count=8`` lets TP/DP/SP
sharding, collective correctness, and scheduler tests run anywhere with no
TPU. Must happen before any ``import jax`` in the test process.

Escape hatch: ``FINCHAT_TESTS_TPU=1`` keeps the real backend so the kernel
parity matrix (tests/test_pallas_attention.py) can run ON-CHIP with
``interpret=False`` — the round-3 verdict's missing on-hardware proof.
Single-device suites only; mesh-dependent tests skip themselves.
"""

import os

_ON_TPU = bool(os.environ.get("FINCHAT_TESTS_TPU"))

# The image's sitecustomize imports jax at interpreter boot and pins the
# axon (TPU-tunnel) platform, so env vars set here are too late; the config
# update below still works because no backend is initialized yet.
_flags = os.environ.get("XLA_FLAGS", "")
if not _ON_TPU and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, "tests require the virtual 8-device CPU mesh"
if os.environ.get("FINCHAT_REQUIRE_TPU"):
    # On-chip capture harnesses (benchmarks/pallas_onchip_split.py) set this
    # so a silent CPU fallback (tunnel init failing FAST instead of hanging)
    # can never produce a passing "on-chip" parity record: the kernel tests
    # would run interpret=True on CPU and pass, and the artifact would claim
    # interpret=False hardware coverage it never had. Checked UNCONDITIONALLY
    # (not only under FINCHAT_TESTS_TPU): a harness that sets REQUIRE_TPU
    # but loses the TESTS_TPU flag would otherwise run the suite on the
    # forced-CPU mesh with the guard silently disarmed (ADVICE r5).
    assert jax.default_backend() == "tpu", (
        f"FINCHAT_REQUIRE_TPU=1 but backend is {jax.default_backend()!r}"
    )

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Free compiled executables between test modules.

    The full suite runs ~600 distinct XLA CPU compilations in one
    process; at a deterministic point near the end (observed 4/4 at
    test_warmup, 2026-07-31) the NEXT compilation segfaults inside
    ``backend_compile_and_load`` — an XLA compiler crash on accumulated
    jit-cache state, not host OOM (RSS ~6 GB of 125 GB) and not stack
    (reproduced at ulimit -s 64 MB). Clearing caches per module keeps
    the executable count bounded; cross-module cache reuse is minimal
    anyway since shapes/configs differ per module."""
    yield
    jax.clear_caches()


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (pytest-asyncio isn't in the
    image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None
