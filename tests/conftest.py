"""Test environment: force an 8-device CPU mesh BEFORE jax initializes.

SURVEY §4.3 — ``xla_force_host_platform_device_count=8`` lets TP/DP/SP
sharding, collective correctness, and scheduler tests run anywhere with no
TPU. Must happen before any ``import jax`` in the test process.

Escape hatch: ``FINCHAT_TESTS_TPU=1`` keeps the real backend so the kernel
parity matrix (tests/test_pallas_attention.py) can run ON-CHIP with
``interpret=False`` — the round-3 verdict's missing on-hardware proof.
Single-device suites only; mesh-dependent tests skip themselves.
"""

import os

_ON_TPU = bool(os.environ.get("FINCHAT_TESTS_TPU"))

# The image's sitecustomize imports jax at interpreter boot and pins the
# axon (TPU-tunnel) platform, so env vars set here are too late; the config
# update below still works because no backend is initialized yet.
_flags = os.environ.get("XLA_FLAGS", "")
if not _ON_TPU and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, "tests require the virtual 8-device CPU mesh"
if os.environ.get("FINCHAT_REQUIRE_TPU"):
    # On-chip capture harnesses (benchmarks/pallas_onchip_split.py) set this
    # so a silent CPU fallback (tunnel init failing FAST instead of hanging)
    # can never produce a passing "on-chip" parity record: the kernel tests
    # would run interpret=True on CPU and pass, and the artifact would claim
    # interpret=False hardware coverage it never had. Checked UNCONDITIONALLY
    # (not only under FINCHAT_TESTS_TPU): a harness that sets REQUIRE_TPU
    # but loses the TESTS_TPU flag would otherwise run the suite on the
    # forced-CPU mesh with the guard silently disarmed (ADVICE r5).
    assert jax.default_backend() == "tpu", (
        f"FINCHAT_REQUIRE_TPU=1 but backend is {jax.default_backend()!r}"
    )

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

# --- runtime sanitizers (ISSUE 8; finchat_tpu/analysis/sanitizers.py) ------
# The scheduler/fleet/durability suites run under two sanitizers:
# - STALL: async tests run on an asyncio-debug loop that FAILS the test
#   when any loop callback blocks past FINCHAT_STALL_THRESHOLD_S (default
#   1.0 s) — the dynamic form of finchat-lint R1 (the inline-rebuild /
#   sync-spill stall class). FINCHAT_STALL_SANITIZER=0 disables.
# - LEAK: after every test, each scheduler the test constructed and
#   stopped is audited — allocator pages, engine slots, prefix-head
#   refcounts, session-cache refs, in-flight prefix jobs — the dynamic
#   form of finchat-lint R3 (the _fail_prefix_job leak class). Leftover
#   open journal handles are closed (fd hygiene).
SANITIZED_MODULES = {
    "test_scheduler_pipeline",
    "test_fleet",
    "test_durability",
    "test_resilience",
    "test_session_cache",
    "test_mixed_step",
    "test_freerun",
    "test_faults",
    "test_decode_loop",
    "test_prefix_cache",
    "test_spec_decode",
    "test_bounded_kv",
    "test_pod",
}

_SANITIZERS_ON = os.environ.get("FINCHAT_STALL_SANITIZER", "1") not in ("0", "false")


def _sanitized(module_name: str) -> bool:
    return _SANITIZERS_ON and module_name.rsplit(".", 1)[-1] in SANITIZED_MODULES


@pytest.fixture(autouse=True)
def _finchat_leak_sanitizer(request):
    """Track every scheduler/journal constructed during the test; audit
    the stopped schedulers afterwards (analysis/sanitizers.py)."""
    if not _sanitized(request.module.__name__):
        yield
        return
    from finchat_tpu.analysis import sanitizers
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.io.journal import AnsweredJournal

    sanitizers.clear_tracked()
    with sanitizers.track_constructions(ContinuousBatchingScheduler, "scheduler"):
        with sanitizers.track_constructions(AnsweredJournal, "journal"):
            yield
    problems: list[str] = []
    for sched in sanitizers.tracked_instances("scheduler"):
        task = getattr(sched, "_task", None)
        if getattr(sched, "_running", False) and not (task and task.done()):
            # genuinely still running (module-scoped fixture) — live
            # streams legitimately hold slots/pages. A scheduler whose
            # loop task was CANCELLED at loop teardown (test never called
            # stop()) keeps _running=True but IS quiescent — audit it:
            # the accounting invariants hold continuously, and skipping
            # it would hide exactly the leaks of tests that forgot stop()
            continue
        problems += [
            f"{type(sched).__name__}[{getattr(sched, 'replica_id', '?')}]: {p}"
            for p in sanitizers.scheduler_leak_report(sched)
        ]
    sanitizers.close_journals()
    sanitizers.clear_tracked()
    if problems:
        pytest.fail(
            "leak sanitizer (finchat-lint R3 class):\n  " + "\n  ".join(problems),
            pytrace=False,
        )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Free compiled executables between test modules.

    The full suite runs ~600 distinct XLA CPU compilations in one
    process; at a deterministic point near the end (observed 4/4 at
    test_warmup, 2026-07-31) the NEXT compilation segfaults inside
    ``backend_compile_and_load`` — an XLA compiler crash on accumulated
    jit-cache state, not host OOM (RSS ~6 GB of 125 GB) and not stack
    (reproduced at ulimit -s 64 MB). Clearing caches per module keeps
    the executable count bounded; cross-module cache reuse is minimal
    anyway since shapes/configs differ per module."""
    yield
    jax.clear_caches()


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (pytest-asyncio isn't in the
    image). Sanitized modules run on an instrumented debug loop instead:
    any callback blocking past the threshold fails the test (the ISSUE 8
    stall sanitizer — asyncio debug mode stays on for these suites)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name] for name in pyfuncitem._fixtureinfo.argnames}
        exempt = pyfuncitem.get_closest_marker("no_stall_sanitizer") is not None
        if _sanitized(pyfuncitem.module.__name__) and not exempt:
            from finchat_tpu.analysis.sanitizers import StallSanitizer

            try:
                StallSanitizer.from_env().run(fn(**kwargs))
            except RuntimeError as e:
                if "stall sanitizer" not in str(e):
                    raise
                pytest.fail(str(e), pytrace=False)
        else:
            asyncio.run(fn(**kwargs))
        return True
    return None
