"""Batched retrieval plane (ISSUE 3): embedding microbatcher, batched
multi-query top-k with device-side filters, and retrieval/prefill overlap.

The golden contracts:
- ``query_points_batch`` (device-filter plane) returns byte-identical hit
  lists to ``query_points`` (serial host-mask plane) for every filter
  combination, including the post-hoc security re-check backstop;
- the overlap path (submit_partial → extend_prompt) produces greedy
  token streams identical to a plain submit of the same prompt;
- one bad text in a coalesced embed batch fails only its own request.
"""

import asyncio
import time

import jax
import numpy as np
import pytest

from finchat_tpu.embed.batcher import EmbedMicrobatcher
from finchat_tpu.embed.encoder import EMBED_PRESETS, EmbeddingEncoder, init_bert_params
from finchat_tpu.embed.index import DeviceVectorIndex, QuerySpec, VectorPoint
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.metrics import METRICS

NOW = 1_700_000_000.0


def _point(uid, date, text, vec):
    return VectorPoint(
        id=f"{uid}-{text[:12]}-{date}",
        vector=np.asarray(vec, np.float32),
        payload={"page_content": text, "metadata": {"user_id": uid, "date": date}},
    )


@pytest.fixture(scope="module")
def encoder():
    config = EMBED_PRESETS["bge-tiny"]
    params = init_bert_params(config, jax.random.key(0))
    return EmbeddingEncoder(config, params, ByteTokenizer())


# --- batched multi-query top-k ------------------------------------------

def test_batch_topk_matches_serial_under_all_filters():
    rng = np.random.default_rng(7)
    index = DeviceVectorIndex(dim=8, initial_capacity=4)  # forces growth
    points = [
        _point(f"u{i % 3}", float(i * 1000), f"txn {i}", rng.normal(size=8))
        for i in range(37)
    ]
    index.upsert(points[:10])
    index.upsert(points[10:])  # second upsert exercises the incremental splice
    specs = [
        QuerySpec(rng.normal(size=8), limit=5, user_id="u0"),
        QuerySpec(rng.normal(size=8), limit=3, user_id="u1", date_gte=9_000.0),
        QuerySpec(rng.normal(size=8), limit=50),           # no filters
        QuerySpec(rng.normal(size=8), limit=10, user_id="nobody"),  # unknown user
        QuerySpec(rng.normal(size=8), limit=10, user_id="u2", date_gte=1e12),  # empty window
    ]
    batched = index.query_points_batch(specs)
    for spec, hits in zip(specs, batched):
        serial = index.query_points(
            spec.vector, limit=spec.limit, user_id=spec.user_id, date_gte=spec.date_gte
        )
        assert [p.id for p in serial] == [p.id for p in hits]
    assert batched[3] == [] and batched[4] == []


def test_batch_topk_date_filter_exact_at_modern_epoch():
    """Unix timestamps (~1.7e9) have 128 s float32 spacing — a single-f32
    device date column would mis-filter rows within ~2 min of the cutoff.
    The double-single (hi, lo) compare must match the serial float64 host
    path exactly at second granularity."""
    base = 1_700_000_000.0
    index = DeviceVectorIndex(dim=4, initial_capacity=8)
    index.upsert([
        _point("u", base + 10.0, "just inside", [1, 0, 0, 0]),
        _point("u", base - 10.0, "just outside", [1, 0, 0, 0]),
        _point("u", base, "exactly at cutoff", [1, 0, 0, 0]),
    ])
    spec = QuerySpec(np.asarray([1.0, 0, 0, 0]), limit=8, user_id="u", date_gte=base)
    batched = index.query_points_batch([spec])[0]
    serial = index.query_points(
        spec.vector, limit=spec.limit, user_id=spec.user_id, date_gte=spec.date_gte
    )
    assert [p.id for p in batched] == [p.id for p in serial]
    kept = {p.payload["page_content"] for p in batched}
    assert kept == {"just inside", "exactly at cutoff"}


def test_batch_topk_sees_rows_upserted_after_first_query():
    """The incremental device upload must land new rows without a full
    re-upload being the only correct path."""
    index = DeviceVectorIndex(dim=4, initial_capacity=8)
    index.upsert([_point("u", 1.0, "old row", [0, 1, 0, 0])])
    index.query_points_batch([QuerySpec(np.asarray([1.0, 0, 0, 0]), limit=4)])
    index.upsert([_point("u", 2.0, "new row", [1, 0, 0, 0])])
    hits = index.query_points_batch(
        [QuerySpec(np.asarray([1.0, 0, 0, 0]), limit=4, user_id="u")]
    )[0]
    assert hits and hits[0].payload["page_content"] == "new row"


def test_save_releases_lock_before_file_io(tmp_path, monkeypatch):
    """A snapshot must not stall concurrent queries: the index lock is
    released before compression/IO begins."""
    index = DeviceVectorIndex(dim=4, initial_capacity=8)
    index.upsert([_point("u", 1.0, "row", [1, 0, 0, 0])])
    saw = {}
    orig = np.savez_compressed

    def probe(*args, **kwargs):
        saw["lock_free"] = index._lock.acquire(blocking=False)
        if saw["lock_free"]:
            index._lock.release()
        return orig(*args, **kwargs)

    monkeypatch.setattr(np, "savez_compressed", probe)
    index.save(str(tmp_path / "snap"))
    assert saw["lock_free"] is True
    restored = DeviceVectorIndex.load(str(tmp_path / "snap"), dim=4)
    assert len(restored) == 1


def test_security_post_check_on_both_planes(encoder):
    """A payload whose user_id was tampered with AFTER upsert passes the
    (stale) filter column but must be dropped by the post-hoc re-check —
    on the serial AND the batched retrieval plane."""
    from finchat_tpu.tools.retrieval import TransactionRetriever

    async def run():
        index = DeviceVectorIndex(dim=encoder.dim)
        plain = TransactionRetriever(encoder, index, now=lambda: NOW)
        plain.upsert_transactions("alice", ["ALICE TXN $1", "ALICE TXN $2"], dates=[NOW, NOW])
        # tamper: the interned code column still says alice, payload says eve
        index._points[1].payload["metadata"]["user_id"] = "eve"
        serial_hits = await plain({"user_id": "alice", "search_query": "txn"})

        batcher = EmbedMicrobatcher(encoder, window_ms=0.5, max_batch=8)
        batched = TransactionRetriever(encoder, index, now=lambda: NOW, batcher=batcher)
        batched_hits = await batched({"user_id": "alice", "search_query": "txn"})
        await batcher.close()
        return serial_hits, batched_hits

    serial_hits, batched_hits = asyncio.run(run())
    assert serial_hits == batched_hits
    assert serial_hits == ["ALICE TXN $1"]


def test_batched_retriever_matches_serial(encoder):
    """Full-tool golden: the batched plane returns the same rows in the
    same order as the serial plane for the same query."""
    from finchat_tpu.tools.retrieval import TransactionRetriever

    async def run():
        index = DeviceVectorIndex(dim=encoder.dim)
        serial = TransactionRetriever(encoder, index, now=lambda: NOW)
        serial.upsert_transactions(
            "alice",
            ["GROCERY $54.12", "RENT $2000", "COFFEE $4.50", "GAS $30"],
            dates=[NOW - 86400 * 40, NOW - 86400 * 5, NOW - 86400, NOW - 3600],
        )
        serial.upsert_transactions("bob", ["BOB SECRET $999"], dates=[NOW])
        batcher = EmbedMicrobatcher(encoder, window_ms=0.5, max_batch=8)
        batched = TransactionRetriever(encoder, index, now=lambda: NOW, batcher=batcher)
        args = {"user_id": "alice", "search_query": "purchases", "time_period_days": 7}
        a = await serial.structured(args)
        b = await batched.structured(args)
        await batcher.close()
        return a, b

    a, b = asyncio.run(run())
    assert a == b
    assert len(a) == 3 and not any("BOB" in r["page_content"] for r in a)


# --- embedding microbatcher ---------------------------------------------

async def test_microbatcher_window_flush(encoder):
    """Requests landing inside the wait window ride ONE dispatch."""
    b = EmbedMicrobatcher(encoder, window_ms=30, max_batch=16)
    d0 = METRICS.get("finchat_embed_batch_dispatches_total")
    outs = await asyncio.gather(*[b.embed_one(f"text {i}") for i in range(5)])
    d1 = METRICS.get("finchat_embed_batch_dispatches_total")
    assert d1 - d0 == 1
    assert METRICS.get("finchat_embed_batch_occupancy") == 5
    direct = encoder.embed_batch([f"text {i}" for i in range(5)])
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, direct[i])
    await b.close()


async def test_microbatcher_max_batch_flush(encoder):
    """A full bucket dispatches immediately — the window is a CAP on the
    wait, not a floor."""
    b = EmbedMicrobatcher(encoder, window_ms=10_000, max_batch=4)
    t0 = time.perf_counter()
    await asyncio.gather(*[b.embed_one(f"t{i}") for i in range(4)])
    assert time.perf_counter() - t0 < 5.0  # nowhere near the 10 s window
    await b.close()


async def test_microbatcher_error_isolation(encoder):
    """One request's un-encodable text fails only its own future."""
    class Boom(Exception):
        pass

    class FlakyEncoder:
        dim = encoder.dim

        def embed_batch(self, texts):
            if any(t == "BAD" for t in texts):
                raise Boom("bad text")
            return encoder.embed_batch(texts)

    b = EmbedMicrobatcher(FlakyEncoder(), window_ms=30, max_batch=16)
    results = await asyncio.gather(
        b.embed_one("fine 1"), b.embed_one("BAD"), b.embed_one("fine 2"),
        return_exceptions=True,
    )
    assert isinstance(results[1], Boom)
    assert not isinstance(results[0], Exception)
    assert not isinstance(results[2], Exception)
    np.testing.assert_array_equal(results[0], encoder.embed_batch(["fine 1"])[0])
    await b.close()


async def test_microbatcher_threadsafe_ingest_path(encoder):
    """Worker threads (the ingest path) coalesce through the same loop."""
    b = EmbedMicrobatcher(encoder, window_ms=20, max_batch=16)
    b.bind_loop()
    d0 = METRICS.get("finchat_embed_batch_dispatches_total")
    query, ingest = await asyncio.gather(
        b.embed_one("query text"),
        asyncio.to_thread(b.embed_threadsafe, ["ingest 1", "ingest 2"]),
    )
    d1 = METRICS.get("finchat_embed_batch_dispatches_total")
    assert d1 - d0 == 1  # query + ingest shared one dispatch
    assert query.shape == (encoder.dim,) and ingest.shape == (2, encoder.dim)
    await b.close()


# --- retrieval/prefill overlap (scheduler + agent) ----------------------

def _mini_scheduler(max_new=8):
    from finchat_tpu.engine.engine import InferenceEngine
    from finchat_tpu.engine.kv_cache import pages_needed
    from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
    from finchat_tpu.models.llama import PRESETS, init_params
    from finchat_tpu.utils.config import EngineConfig

    config = PRESETS["mini"]
    page_size = 32
    max_seq_len = 512
    pps = pages_needed(max_seq_len, page_size)
    ecfg = EngineConfig(
        max_seqs=4, page_size=page_size, num_pages=4 * pps + 8,
        max_seq_len=max_seq_len, prefill_chunk=32, session_cache=False,
    )
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, ecfg)
    return ContinuousBatchingScheduler(engine, eos_id=-1), config


async def _collect(handle):
    tokens = []
    while True:
        ev = await handle.events.get()
        if ev["type"] == "token":
            tokens.append(ev["token_id"])
        elif ev["type"] == "done":
            return tokens
        else:
            raise RuntimeError(ev)


async def _wait_parked(handle, timeout=30.0):
    t0 = time.perf_counter()
    while handle.prefill_pos < len(handle.prompt_ids):
        assert time.perf_counter() - t0 < timeout
        await asyncio.sleep(0.02)


def test_partial_extend_golden_equivalence():
    """Greedy tokens from submit_partial→park→extend_prompt must be
    byte-identical to a plain submit of the same full prompt."""
    from finchat_tpu.engine.sampler import SamplingParams

    sched, config = _mini_scheduler()
    rng = np.random.default_rng(0)
    prefix = rng.integers(1, config.vocab_size, size=90).tolist()
    full = prefix + rng.integers(1, config.vocab_size, size=30).tolist()
    samp = SamplingParams(temperature=0.0, max_new_tokens=8)

    async def run():
        await sched.start()
        try:
            plain = await _collect(await sched.submit("plain", full, samp))
            hold = await sched.submit_partial("hold", prefix, samp)
            assert hold is not None
            await _wait_parked(hold)
            assert sched.extend_prompt(hold, full)
            overlapped = await _collect(hold)
            return plain, overlapped
        finally:
            await sched.stop()

    plain, overlapped = asyncio.run(run())
    assert plain == overlapped


def test_partial_extend_mismatch_falls_back_cleanly():
    """A graft that does not extend the held prefix is refused; cancel
    returns every page to the allocator."""
    from finchat_tpu.engine.sampler import SamplingParams

    sched, config = _mini_scheduler()
    rng = np.random.default_rng(1)
    prefix = rng.integers(1, config.vocab_size, size=70).tolist()
    samp = SamplingParams(temperature=0.0, max_new_tokens=4)

    async def run():
        await sched.start()
        try:
            hold = await sched.submit_partial("hold", prefix, samp)
            await _wait_parked(hold)
            divergent = [9] + prefix  # does not start with the prefix
            assert not sched.extend_prompt(hold, divergent)
            assert not sched.extend_prompt(hold, prefix)  # no new tokens
            sched.cancel(hold)
            await asyncio.sleep(0.05)
            assert not sched.prefilling and not sched.decoding
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
        finally:
            await sched.stop()

    asyncio.run(run())


def test_agent_overlap_stream_identical_to_serial():
    """Full-stack golden: the agent's streamed greedy response with
    retrieval_overlap on equals the serial path byte-for-byte, and the
    overlap run actually grafted (not silently fallen back)."""
    from finchat_tpu.agent.graph import LLMAgent
    from finchat_tpu.engine.generator import EngineGenerator, StubGenerator
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.models.tokenizer import get_tokenizer

    async def retriever(args):
        await asyncio.sleep(0.2)  # stand-in for embed+search latency
        return ["COFFEE $4.50 on 2026-07-30", "RENT $2000 on 2026-07-01"]

    async def run(overlap: bool):
        sched, _ = _mini_scheduler()
        await sched.start()
        try:
            gen = EngineGenerator(sched, get_tokenizer())
            agent = LLMAgent(
                StubGenerator(default='retrieve_transactions({"search_query": "spending"})'),
                gen, retriever, "You are Penny.", "Decide retrieval.",
                response_sampling=SamplingParams(temperature=0.0, max_new_tokens=8),
                today=lambda: "2026-08-03",
                retrieval_overlap=overlap,
            )
            text = []
            async for ev in agent.stream_with_status(
                "what did I spend?", "u1", "CTX",
                [], conversation_id=None,
            ):
                if ev["type"] == "response_chunk":
                    text.append(ev["content"])
            return "".join(text)
        finally:
            await sched.stop()

    g0 = METRICS.get("finchat_partial_grafts_total")
    on = asyncio.run(run(True))
    g1 = METRICS.get("finchat_partial_grafts_total")
    off = asyncio.run(run(False))
    g2 = METRICS.get("finchat_partial_grafts_total")
    assert on == off and on  # byte-identical, non-empty
    assert g1 - g0 == 1  # overlap run grafted
    assert g2 - g1 == 0  # serial run did not


async def test_release_partial_frees_abandoned_hold():
    """A hold whose stream never runs (retrieval errored upstream) is
    released by the agent's leak guard, not reaped 30 s later."""
    from finchat_tpu.engine.generator import EngineGenerator
    from finchat_tpu.engine.sampler import SamplingParams
    from finchat_tpu.models.tokenizer import get_tokenizer

    sched, _ = _mini_scheduler()
    await sched.start()
    try:
        gen = EngineGenerator(sched, get_tokenizer())
        samp = SamplingParams(temperature=0.0, max_new_tokens=4)
        hold = await gen.begin_partial("<|system|>\nA long enough prefix text.\n", samp)
        assert hold is not None
        await _wait_parked(hold)
        assert sched.allocator.used_count > 0
        gen.release_partial(hold)
        await asyncio.sleep(0.05)
        assert sched.allocator.used_count == 0
        sched.allocator.check_invariants()
    finally:
        await sched.stop()
