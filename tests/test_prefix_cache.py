"""Shared-prefix KV cache: identical prompt heads (the constant system
prompt) prefill once per process and share pages across requests.

Contracts pinned here:
- golden equality: a prefix-cached request streams the SAME tokens as an
  uncached one (the shared KV is byte-identical to what the request would
  have written itself);
- resource accounting: cache hits allocate fewer pages and skip the shared
  tokens' prefill; eviction never frees shared pages; allocator ownership
  invariants hold through churn;
- matching rules: whole pages only, at least one prompt token left to
  prefill, non-matching prompts unaffected.
"""

import asyncio

import jax
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.generator import EngineGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig

CONFIG = PRESETS["tiny"]
PAGE = 8


def _make_scheduler(max_seqs=4):
    tok = ByteTokenizer()
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=PAGE, num_pages=128, max_seq_len=128,
        prefill_chunk=16,
    )
    params = init_params(CONFIG, jax.random.key(0))
    engine = InferenceEngine(CONFIG, params, cfg)
    return tok, ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)


HEAD = "system: you are a terse financial assistant, answer briefly."  # 58 chars


async def _collect(scheduler, seq_id, prompt_ids, n_new):
    handle = await scheduler.submit(
        seq_id, prompt_ids, SamplingParams(temperature=0.0, max_new_tokens=n_new)
    )
    tokens = []
    while True:
        event = await asyncio.wait_for(handle.events.get(), timeout=120)
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return handle, tokens
        else:
            raise AssertionError(event)


def test_register_shares_whole_pages_only():
    tok, scheduler = _make_scheduler()
    ids = tok.encode(HEAD, add_bos=True)
    shared = scheduler.register_prefix(ids)
    assert shared == (len(ids) // PAGE) * PAGE > 0
    # registration is idempotent and holds its pages under a prefix owner
    used_after = scheduler.allocator.used_count
    assert scheduler.register_prefix(ids) == shared
    assert scheduler.allocator.used_count == used_after
    # too-short prefix registers nothing
    assert scheduler.register_prefix([1, 2, 3]) == 0
    # registration must leave the engine slot-state clean
    import numpy as np

    assert np.asarray(scheduler.engine.state.context_lens).sum() == 0
    assert np.asarray(scheduler.engine.state.page_table).sum() == 0


def test_prefix_hit_streams_identical_tokens_and_saves_pages():
    tok = ByteTokenizer()
    prompt = tok.encode(HEAD + " q: how much did I spend?", add_bos=True)
    n_new = 10

    async def run(register):
        _, scheduler = _make_scheduler()
        shared = scheduler.register_prefix(tok.encode(HEAD, add_bos=True)) if register else 0
        base_used = scheduler.allocator.used_count
        await scheduler.start()
        try:
            handle, tokens = await _collect(scheduler, "s", prompt, n_new)
            return shared, base_used, handle, tokens, scheduler
        finally:
            await scheduler.stop()

    shared, _, h_hit, hit_tokens, sched_hit = asyncio.run(run(True))
    _, _, _, miss_tokens, _ = asyncio.run(run(False))
    assert shared > 0
    assert hit_tokens == miss_tokens  # golden equality
    # the hit skipped the shared tokens' prefill
    assert h_hit.prefill_pos >= shared
    # after the stream finished, only the prefix pages remain allocated
    sched_hit.allocator.check_invariants()
    assert sched_hit.allocator.used_count == shared // PAGE


def test_eviction_never_frees_shared_pages():
    tok, scheduler = _make_scheduler(max_seqs=2)
    ids = tok.encode(HEAD, add_bos=True)
    shared = scheduler.register_prefix(ids)
    prefix_pages = scheduler.allocator.used_count
    prompt = ids + tok.encode(" extra question", add_bos=False)

    async def run():
        await scheduler.start()
        try:
            for i in range(3):  # churn: admit, finish, slot reuse
                _, tokens = await _collect(scheduler, f"s{i}", prompt, 4)
                assert len(tokens) == 4
        finally:
            await scheduler.stop()

    asyncio.run(run())
    scheduler.allocator.check_invariants()
    assert scheduler.allocator.used_count == prefix_pages
    assert shared > 0


def test_non_matching_prompt_unaffected():
    tok, scheduler = _make_scheduler()
    scheduler.register_prefix(tok.encode(HEAD, add_bos=True))
    other = tok.encode("completely different beginning, same engine", add_bos=True)

    async def run():
        await scheduler.start()
        try:
            handle, tokens = await _collect(scheduler, "other", other, 5)
            return handle, tokens
        finally:
            await scheduler.stop()

    handle, tokens = asyncio.run(run())
    assert len(tokens) == 5
    # no shared pages were attached: the full prompt was prefilled
    assert handle.prefill_pos == len(other)


def test_retire_frees_only_after_last_reference_releases():
    """Date-rollover path: retired prefixes stop matching immediately but
    their pages survive until no in-flight page table references them."""
    tok, scheduler = _make_scheduler(max_seqs=2)
    ids = tok.encode(HEAD, add_bos=True)
    shared = scheduler.register_prefix(ids)
    assert shared > 0
    prefix_pages = shared // PAGE
    prompt = ids + tok.encode(" and a question", add_bos=False)

    async def run():
        await scheduler.start()
        try:
            handle = await scheduler.submit(
                "s", prompt, SamplingParams(temperature=0.0, max_new_tokens=24)
            )
            # wait for admission (prefix attached)
            while handle.prefix_entry is None and not handle.finished:
                await asyncio.sleep(0.005)
            entry = handle.prefix_entry
            assert entry is not None and entry.refs == 1
            scheduler.retire_prefixes()
            # still referenced: pages must NOT be freed yet
            assert scheduler.allocator.used_count >= prefix_pages
            assert scheduler._match_prefix(prompt) == (None, 0)  # stops matching
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=120)
                if event["type"] == "done":
                    break
            return entry
        finally:
            await scheduler.stop()

    asyncio.run(run())
    scheduler.allocator.check_invariants()
    assert scheduler._prefixes == []  # reaped after release
    assert scheduler.allocator.used_count == 0  # pages returned


def test_agent_prompt_heads_are_rendered_prompt_prefixes():
    """The byte-for-byte-prefix claim prompt_heads() makes (and the prefix
    cache relies on) must hold against the actual prompt builders."""
    from finchat_tpu.agent.graph import LLMAgent
    from finchat_tpu.agent.state import AgentState
    from finchat_tpu.engine.generator import StubGenerator

    stub = StubGenerator(default="x")
    agent = LLMAgent(stub, stub, None, "SYSTEM RULES", "TOOL RULES")
    state = AgentState(
        user_query="how much did I spend?", user_id="u", user_context="name: Pat",
    )
    tool_head, resp_head = agent.prompt_heads()
    assert agent._tool_prompt_text(state).startswith(tool_head)
    state.retrieved_transactions = ["row1", "row2"]
    assert agent._response_prompt_text(state).startswith(resp_head)


def test_ring_eligible_prompts_skip_prefix_match():
    """Long prompts that would take the seq-sharded ring prefill keep it:
    admission must not attach a prefix (which would force the chunked
    path, trading away the ring's activation-memory safety)."""
    from finchat_tpu.engine.scheduler import SequenceHandle

    tok, scheduler = _make_scheduler()
    ids = tok.encode(HEAD, add_bos=True)
    assert scheduler.register_prefix(ids) > 0
    prompt = ids + [7, 8, 9]

    def admit(ring_eligible):
        scheduler.engine._use_ring_prefill = lambda n: ring_eligible
        handle = SequenceHandle(
            seq_id=f"s{ring_eligible}", prompt_ids=prompt,
            sampling=SamplingParams(temperature=0.0, max_new_tokens=4),
        )
        scheduler.pending.append(handle)
        scheduler._admit()
        assert handle.slot >= 0
        return handle

    ring = admit(True)
    assert ring.prefix_entry is None and ring.prefill_pos == 0
    chunked = admit(False)
    assert chunked.prefix_entry is not None and chunked.prefill_pos > 0


def test_prefix_cache_composes_with_speculative_decoding():
    """Both round-4 serving features on at once: a prefix-cached greedy
    request decoding through verify steps must stream exactly what the
    plain (no prefix, no spec) scheduler streams."""
    import dataclasses as dc

    tok = ByteTokenizer()
    prompt = tok.encode(HEAD + " abcabcabc", add_bos=True)
    n_new = 12

    async def run(spec_tokens, register):
        cfg = EngineConfig(
            max_seqs=4, page_size=PAGE, num_pages=128, max_seq_len=128,
            prefill_chunk=16, spec_tokens=spec_tokens,
        )
        params = init_params(CONFIG, jax.random.key(0))
        scheduler = ContinuousBatchingScheduler(
            InferenceEngine(CONFIG, params, cfg), eos_id=tok.eos_id
        )
        if register:
            assert scheduler.register_prefix(tok.encode(HEAD, add_bos=True)) > 0
        await scheduler.start()
        try:
            handle, tokens = await _collect(scheduler, "s", prompt, n_new)
            if register:
                assert handle.prefill_pos >= PAGE  # the hit engaged
            return tokens
        finally:
            await scheduler.stop()

    plain = asyncio.run(run(0, False))
    both = asyncio.run(run(3, True))
    assert both == plain and len(plain) >= 1  # (this prompt EOSes early)


def test_register_prompt_prefixes_partial_success():
    """One unregistrable head (shorter than a page) must not poison the
    other's registration, and the refresh path retries only the missing
    one without retiring the good one (serve/app.py)."""
    from finchat_tpu.serve.app import register_prompt_prefixes

    tok, scheduler = _make_scheduler()

    class FakeAgent:
        def prompt_heads(self):
            return [HEAD, "hi"]  # "hi" can never fill a page

    registered = register_prompt_prefixes(FakeAgent(), scheduler, tok)
    assert registered == {HEAD}
    pages_used = scheduler.allocator.used_count
    assert pages_used > 0
    # idempotent retry: the good head is NOT re-prefilled into new pages
    assert register_prompt_prefixes(FakeAgent(), scheduler, tok) == {HEAD}
    assert scheduler.allocator.used_count == pages_used


def test_chunked_registration_interleaves_with_decode():
    """register_prefix_async on a RUNNING scheduler (the midnight prefix
    refresh path, VERDICT r4 weak #6) must not stall in-flight streams:
    the head prefills one chunk per round with decode steps between, so a
    concurrent stream keeps receiving tokens DURING the registration, and
    the registered head then matches exactly like a sync registration."""
    tok, scheduler = _make_scheduler()
    # a head long enough for several chunks (chunk=16): 96 tokens → 6 rounds
    long_head = (HEAD + " ") * 2
    long_ids = tok.encode(long_head, add_bos=True)[: 96 + 1]

    async def run():
        await scheduler.start()
        try:
            stream = await scheduler.submit(
                "stream", tok.encode("hello there", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=64),
            )
            # let the stream reach steady-state decode
            seen = []
            while len(seen) < 4:
                event = await asyncio.wait_for(stream.events.get(), timeout=120)
                assert event["type"] == "token", event
                seen.append(event["token_id"])
            before = len(seen)
            reg_task = asyncio.create_task(
                scheduler.register_prefix_async(long_ids)
            )
            # drain stream tokens while the registration is in flight
            while not reg_task.done():
                event = await asyncio.wait_for(stream.events.get(), timeout=120)
                if event["type"] == "token":
                    seen.append(event["token_id"])
                else:
                    break
            shared = await reg_task
            during = len(seen) - before
            return shared, during
        finally:
            await scheduler.stop()

    shared, during = asyncio.run(run())
    assert shared == (len(long_ids) // PAGE) * PAGE > 0
    # ≥6 prefill rounds ran; a decode step interleaves with every round,
    # so the stream must have advanced while the head was registering
    assert during >= 3, f"stream starved during registration ({during} tokens)"
    # the chunked registration's pages hold real KV: a prompt starting
    # with the head must hit and stream the same tokens as an uncached run
    prompt = long_ids + tok.encode(" ok?", add_bos=False)

    async def collect(register_first):
        tok2, sched2 = _make_scheduler()
        if register_first:
            # golden: sync registration on an idle scheduler
            assert sched2.register_prefix(long_ids) > 0
        await sched2.start()
        try:
            _, tokens = await _collect(sched2, "s", prompt, 8)
            return tokens
        finally:
            await sched2.stop()

    async def collect_chunked():
        await scheduler.start()
        try:
            handle, tokens = await _collect(scheduler, "s2", prompt, 8)
            assert handle.prefill_pos >= PAGE  # hit engaged
            return tokens
        finally:
            await scheduler.stop()

    golden = asyncio.run(collect(True))
    assert asyncio.run(collect_chunked()) == golden


def test_app_rollover_refresh_is_chunked_and_nonblocking():
    """VERDICT r4 weak #6 'done' criterion, app level: a date rollover
    (prompt heads change) picked up by the app's periodic checker retires
    the stale head and registers the fresh one through the CHUNKED path —
    and a concurrent stream keeps receiving tokens during the refresh."""
    from types import SimpleNamespace

    from finchat_tpu.serve.app import (
        _maybe_refresh_prefix_cache,
        register_prompt_prefixes,
    )

    tok, scheduler = _make_scheduler()
    old_head = (HEAD + " v1 ") * 2
    new_head = (HEAD + " v2 ") * 2
    heads = [old_head]

    agent = SimpleNamespace(
        prompt_heads=lambda: list(heads),
        tool_generator=SimpleNamespace(tokenizer=tok),
    )
    app = SimpleNamespace(
        _prefix_cache_enabled=True,
        scheduler=scheduler,
        agent=agent,
        _registered_heads=register_prompt_prefixes(agent, scheduler, tok),
    )
    assert app._registered_heads == {old_head}

    async def run():
        await scheduler.start()
        try:
            stream = await scheduler.submit(
                "stream", tok.encode("hello there", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=64),
            )
            seen = 0
            while seen < 4:  # steady-state decode first
                event = await asyncio.wait_for(stream.events.get(), timeout=120)
                assert event["type"] == "token", event
                seen += 1
            heads[:] = [new_head]  # midnight: the rendered head changes
            refresh = asyncio.create_task(_maybe_refresh_prefix_cache(app))
            during = 0
            while not refresh.done():
                event = await asyncio.wait_for(stream.events.get(), timeout=120)
                if event["type"] != "token":
                    break
                during += 1
            await refresh
            return during
        finally:
            await scheduler.stop()

    during = asyncio.run(run())
    assert app._registered_heads == {new_head}
    # the fresh head matches; the stale one no longer does
    assert scheduler._match_prefix(tok.encode(new_head + "x", add_bos=True))[1] > 0
    assert scheduler._match_prefix(tok.encode(old_head + "x", add_bos=True)) == (None, 0)
    assert during >= 2, f"stream starved during rollover refresh ({during} tokens)"


def test_match_leaves_at_least_one_token_to_prefill():
    tok, scheduler = _make_scheduler()
    ids = tok.encode(HEAD, add_bos=True)
    shared = scheduler.register_prefix(ids)
    # a prompt that IS exactly the registered shared head: matching must
    # cap below the prompt length so the last token still prefills
    exact = ids[:shared]
    entry, used = scheduler._match_prefix(exact)
    assert used <= len(exact) - 1
    assert used % PAGE == 0 and entry is not None
