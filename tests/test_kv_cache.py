"""Page allocator invariants (SURVEY §5.2: a KV page never owned by two
sequences; double-free detection) and scatter/gather correctness."""

import jax.numpy as jnp
import pytest

from finchat_tpu.engine.kv_cache import (
    PageAllocationError,
    PageAllocator,
    gather_kv,
    pages_needed,
    scatter_kv_chunk,
)


def test_pages_needed():
    assert pages_needed(1, 8) == 1
    assert pages_needed(8, 8) == 1
    assert pages_needed(9, 8) == 2
    assert pages_needed(0, 8) == 1


def test_allocator_never_hands_out_trash_page():
    alloc = PageAllocator(8)
    pages = alloc.allocate("s", 7)
    assert 0 not in pages
    assert sorted(pages) == list(range(1, 8))


def test_allocator_exhaustion():
    alloc = PageAllocator(4)
    alloc.allocate("a", 3)
    assert not alloc.can_allocate(1)
    with pytest.raises(PageAllocationError):
        alloc.allocate("b", 1)


def test_double_free_raises():
    alloc = PageAllocator(8)
    pages = alloc.allocate("a", 2)
    alloc.free("a", pages)
    with pytest.raises(PageAllocationError):
        alloc.free("a", pages)


def test_foreign_free_raises():
    alloc = PageAllocator(8)
    pages = alloc.allocate("a", 2)
    with pytest.raises(PageAllocationError):
        alloc.free("b", pages)


def test_free_then_realloc_keeps_invariants():
    alloc = PageAllocator(16)
    a = alloc.allocate("a", 5)
    b = alloc.allocate("b", 5)
    alloc.free("a", a)
    c = alloc.allocate("c", 8)
    alloc.check_invariants()
    assert set(c).isdisjoint(b)


def test_scatter_gather_roundtrip():
    L, P, ps, Hkv, hd = 2, 6, 4, 2, 8
    k_pages = jnp.zeros((L, P, ps, Hkv * hd))
    v_pages = jnp.zeros((L, P, ps, Hkv * hd))
    B, C = 1, 6
    k_new = jnp.arange(B * C * Hkv * hd, dtype=jnp.float32).reshape(B, C, Hkv, hd)
    v_new = -k_new
    page_table = jnp.asarray([[2, 4, 0]], jnp.int32)  # logical pages 0,1 -> phys 2,4
    # write 6 tokens starting at absolute position 2 into layer 1: positions
    # 2,3 in page 2, positions 4..7 in page 4
    k_pages, v_pages = scatter_kv_chunk(
        k_pages, v_pages, k_new, v_new, page_table,
        start_pos=jnp.asarray([2]), n_valid=jnp.asarray([6]), page_size=ps,
        layer=jnp.int32(1),
    )
    k_all, v_all = gather_kv(k_pages, v_pages, page_table, ps, jnp.int32(1), Hkv)
    assert k_all.shape == (B, 3 * ps, Hkv, hd)
    # gathered positions 2..7 must equal the chunk in order
    assert jnp.array_equal(k_all[0, 2:8], k_new[0])
    assert jnp.array_equal(v_all[0, 2:8], v_new[0])
    # trash page (phys 0) is untouched territory for this row's logical page 2
    assert jnp.array_equal(k_all[0, 8:], jnp.zeros((ps, Hkv, hd)))
    # the other layer is untouched
    assert float(jnp.abs(k_pages[0]).sum()) == 0.0


def test_scatter_padding_goes_to_trash():
    L, P, ps, Hkv, hd = 1, 4, 4, 1, 2
    k_pages = jnp.zeros((L, P, ps, Hkv * hd))
    v_pages = jnp.zeros((L, P, ps, Hkv * hd))
    k_new = jnp.ones((1, 4, Hkv, hd))
    page_table = jnp.asarray([[1, 2]], jnp.int32)
    k_pages, v_pages = scatter_kv_chunk(
        k_pages, v_pages, k_new, k_new, page_table,
        start_pos=jnp.asarray([0]), n_valid=jnp.asarray([2]), page_size=ps,
        layer=jnp.int32(0),
    )
    # only 2 valid tokens written to page 1; padding went to trash page 0
    assert float(k_pages[0, 1, :2].sum()) == 2 * Hkv * hd
    assert float(k_pages[0, 1, 2:].sum()) == 0.0
    assert float(k_pages[0, 2].sum()) == 0.0


def test_page_hbm_bytes_matches_real_allocation():
    """page_hbm_bytes (the no-alloc sizing helper harnesses use to fit a
    KV pool to an HBM budget) must mirror PagedKVCache.create exactly,
    for both the native-dtype and int8 layouts."""
    from finchat_tpu.engine.kv_cache import PagedKVCache, page_hbm_bytes
    from finchat_tpu.models.llama import PRESETS

    config = PRESETS["mini"]
    for kv_quant in ("", "int8"):
        cache = PagedKVCache.create(config, num_pages=6, page_size=16,
                                    kv_quant=kv_quant)
        per_page = page_hbm_bytes(config, 16, kv_quant)
        expected = per_page * 6
        if not kv_quant:
            # the no-quant layout carries (1,1,1,1) scale placeholders
            expected += cache.k_scales.nbytes + cache.v_scales.nbytes
        assert cache.hbm_bytes() == expected
