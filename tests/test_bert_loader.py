"""BERT checkpoint loader parity (VERDICT r1 task 5).

A tiny HF-format BertModel checkpoint is written by torch/transformers and
loaded through ``load_bert_params``; our ``encode_batch`` must reproduce the
torch model's hidden states under both pooling modes — proving the fused-qkv
transposition, bias handling, token-type folding, and exact-GELU semantics.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from safetensors.numpy import save_file  # noqa: E402

from finchat_tpu.checkpoints.bert_loader import load_bert_params  # noqa: E402
from finchat_tpu.embed.encoder import BertConfig, encode_batch  # noqa: E402

HF_CFG = dict(
    vocab_size=96,
    hidden_size=48,
    num_hidden_layers=2,
    num_attention_heads=4,
    intermediate_size=64,
    max_position_embeddings=64,
    type_vocab_size=2,
    hidden_act="gelu",
    layer_norm_eps=1e-12,
)


def _our_config(pooling: str) -> BertConfig:
    return BertConfig(
        vocab_size=96, dim=48, n_layers=2, n_heads=4, hidden_dim=64,
        max_position=64, norm_eps=1e-12, dtype=jnp.float32, pooling=pooling,
    )


@pytest.fixture(scope="module")
def bert_checkpoint(tmp_path_factory):
    from transformers import BertConfig as HFBertConfig
    from transformers import BertModel

    path = tmp_path_factory.mktemp("bert_ckpt")
    torch.manual_seed(3)
    model = BertModel(HFBertConfig(**HF_CFG, attn_implementation="eager"))
    model.eval()
    tensors = {
        k: v.detach().to(torch.float32).numpy().copy()
        for k, v in model.state_dict().items()
    }
    save_file(tensors, str(path / "model.safetensors"))
    (path / "config.json").write_text(
        json.dumps({**HF_CFG, "model_type": "bert", "architectures": ["BertModel"]})
    )
    return path, model


@pytest.mark.parametrize("pooling", ["cls", "mean"])
def test_pooled_embedding_matches_torch(bert_checkpoint, pooling):
    path, model = bert_checkpoint
    cfg = _our_config(pooling)
    params = load_bert_params(str(path), cfg)

    # ragged batch: row 1 is padded from length 7 to 9
    tokens = np.zeros((2, 9), np.int32)
    tokens[0] = [2, 17, 33, 80, 5, 9, 61, 44, 12]
    tokens[1, :7] = [2, 90, 4, 33, 17, 6, 1]
    lengths = np.asarray([9, 7], np.int32)

    mask = (np.arange(9)[None, :] < lengths[:, None]).astype(np.int64)
    with torch.no_grad():
        hidden = model(
            input_ids=torch.from_numpy(tokens.astype(np.int64)),
            attention_mask=torch.from_numpy(mask),
        ).last_hidden_state.numpy()
    if pooling == "cls":
        ref = hidden[:, 0, :]
    else:
        m = mask[:, :, None].astype(np.float32)
        ref = (hidden * m).sum(axis=1) / m.sum(axis=1)
    ref = ref / np.linalg.norm(ref, axis=-1, keepdims=True)

    ours = np.asarray(
        encode_batch(params, jnp.asarray(tokens), jnp.asarray(lengths), config=cfg)
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_config_mismatch_raises(bert_checkpoint):
    path, _ = bert_checkpoint
    wrong = BertConfig(vocab_size=96, dim=48, n_layers=5, n_heads=4,
                       hidden_dim=64, max_position=64, dtype=jnp.float32)
    with pytest.raises(ValueError, match="num_hidden_layers"):
        load_bert_params(str(path), wrong)
