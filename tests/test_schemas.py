"""Lock the §2.4 wire contract byte-for-byte (reference main.py:86-150)."""

import json

from finchat_tpu.io.schemas import (
    TIMEOUT_TEXT,
    complete_chunk,
    error_chunk,
    response_chunk,
    timeout_chunk,
)

INBOUND = {
    "message": "What did I spend on groceries?",
    "conversation_id": "conv-1",
    "user_id": "user-9",
    "extra_passthrough": 42,
}


def test_response_chunk_shape():
    chunk = response_chunk(INBOUND, "Hello")
    assert chunk == {
        "message": "Hello",
        "conversation_id": "conv-1",
        "user_id": "user-9",
        "extra_passthrough": 42,
        "last_message": False,
        "error": False,
        "sender": "AIMessage",
        "type": "response_chunk",
    }


def test_complete_chunk_keeps_original_user_text():
    chunk = complete_chunk(INBOUND)
    # reference main.py:101-107: no "message" override on the completion marker
    assert chunk["message"] == "What did I spend on groceries?"
    assert chunk["last_message"] is True
    assert chunk["error"] is False
    assert chunk["type"] == "complete"
    assert chunk["sender"] == "AIMessage"


def test_error_chunk_has_no_type_field():
    chunk = error_chunk(INBOUND)
    # reference main.py:114-120: error marker has empty message and NO type key
    assert chunk["message"] == ""
    assert chunk["last_message"] is True
    assert chunk["error"] is True
    assert chunk["sender"] == "AIMessage"
    assert "type" not in chunk


def test_timeout_chunk_text():
    chunk = timeout_chunk(INBOUND)
    assert chunk["message"] == TIMEOUT_TEXT == "Request timed out. Please try again."
    assert chunk["error"] is True
    assert chunk["last_message"] is True
    assert "type" not in chunk


def test_chunks_are_json_serializable():
    for chunk in (response_chunk(INBOUND, "x"), complete_chunk(INBOUND), error_chunk(INBOUND)):
        assert json.loads(json.dumps(chunk)) == chunk
