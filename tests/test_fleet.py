"""Engine fleet (ISSUE 6; serve/fleet.py; ROBUSTNESS.md).

Pins the fleet contract:

- RENDEZVOUS ROUTING: conversation→replica routing hashes the
  conversation's KAFKA PARTITION (io/kafka.py ``partition_for_key`` — the
  broker's own key→partition placement), so routing agrees with partition
  assignment by construction; replica loss moves ONLY the lost replica's
  share (≤ ~1/N of conversations) and rejoin restores exactly the old
  mapping.
- DRAIN HANDOFF: a killed replica's in-flight streams are preempted to
  host, adopted by siblings, and complete BYTE-IDENTICAL to an
  undisturbed run — zero user-visible errors; the victim goes OUT and the
  supervisor respawns it once the device heals.
- SESSION MIGRATION: session-cache entries are portable host bytes —
  drain hands them off with the stream, and the router migrates them
  lazily at route time, so a migrated conversation admission-resumes
  (resumed_len > 0) instead of cold-prefilling. Entries riding a shared
  prompt head re-link against the importer's own live registration, and
  are REFUSED (cold resume, counted) when the importer has no matching
  head.
- ROUTER-LEVEL DEDUPE: the answered-``message_id`` ring is shared
  fleet-wide, so replica death + Kafka redelivery to a sibling cannot
  double-answer (closes the per-replica hole PR 5 documented).
"""

import asyncio
import dataclasses
import threading
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler, _PrefixJob
from finchat_tpu.engine.session_cache import SESSION_KEY_ROLES, session_key
from finchat_tpu.io.kafka import partition_for_key
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.serve.fleet import (
    LIVE,
    OUT,
    DedupeRing,
    EngineFleet,
    EngineReplica,
    rendezvous_hash,
)
from finchat_tpu.utils import faults
from finchat_tpu.utils.config import EngineConfig, FleetConfig
from finchat_tpu.utils.metrics import METRICS, MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.disarm_all()


# --- rendezvous routing (pure; no engines) --------------------------------

def _stub_replica(rid: str) -> EngineReplica:
    """Router-only replica: the scheduler surface EngineFleet wires
    (drain_sink assignment target, on_give_up list, no session cache)."""
    sched = types.SimpleNamespace(on_give_up=[], session_cache=None)
    return EngineReplica(replica_id=rid, scheduler=sched)


def _stub_fleet(n: int, num_partitions: int = 32) -> EngineFleet:
    return EngineFleet(
        [_stub_replica(str(i)) for i in range(n)],
        FleetConfig(replicas=n, respawn=False),
        num_partitions=num_partitions,
    )


def test_rendezvous_loss_moves_only_the_lost_share():
    """Removing a candidate reassigns exactly the keys it owned (each to
    its runner-up); every other key keeps its owner. Rejoin restores the
    original mapping bit-for-bit."""
    cands = [str(i) for i in range(4)]
    keys = [str(p) for p in range(64)]
    before = {k: rendezvous_hash(k, cands) for k in keys}
    survivors = [c for c in cands if c != "2"]
    after = {k: rendezvous_hash(k, survivors) for k in keys}
    for k in keys:
        if before[k] == "2":
            assert after[k] != "2"
        else:
            assert after[k] == before[k]
    # rejoin: exactly the old mapping
    assert {k: rendezvous_hash(k, cands) for k in keys} == before
    # and the lost share is ~1/N — not empty, not the whole keyspace
    moved = sum(1 for k in keys if before[k] == "2")
    assert 0 < moved < len(keys) / 2


def test_fleet_reshuffle_fraction_on_replica_loss():
    """Marking one of N replicas OUT reroutes ONLY the conversations
    whose partition it owned: ≤ ~1/N of conversations move (slack for
    hash imbalance), everyone else keeps their replica."""
    fleet = _stub_fleet(4)
    convs = [f"conv-{i}" for i in range(200)]
    before = {c: fleet.replica_for(c).replica_id for c in convs}
    victim = fleet.replicas[1]
    victim.state = OUT
    after = {c: fleet.replica_for(c).replica_id for c in convs}
    moved = [c for c in convs if after[c] != before[c]]
    assert all(before[c] == victim.replica_id for c in moved)
    assert all(after[c] != victim.replica_id for c in convs)
    assert len(moved) <= len(convs) * 2 / 4  # ~1/N with imbalance slack
    # rejoin: everything routes exactly as before the loss
    victim.state = LIVE
    assert {c: fleet.replica_for(c).replica_id for c in convs} == before


def test_routing_agrees_with_kafka_partition_assignment():
    """The routing unit is the Kafka partition: two conversations the
    broker would place on the same partition route to the same replica,
    and the conversation route equals the partition route — so a
    replica's share is expressible as a partition→replica assignment."""
    fleet = _stub_fleet(4, num_partitions=8)
    by_partition: dict[int, str] = {}
    for i in range(100):
        conv = f"c{i}"
        part = partition_for_key(conv, 8)
        assert part == fleet.partition_for(conv)
        rid = fleet.replica_for(conv).replica_id
        assert rid == fleet.replica_for_partition(part).replica_id
        assert by_partition.setdefault(part, rid) == rid
    # the 8 partitions cover several replicas (sanity: it IS spreading)
    assert len(set(by_partition.values())) > 1


def test_overprovisioned_fleet_warns(caplog):
    """The partition is the routing unit: more replicas than partitions
    means the extras can never be routed traffic — that misconfiguration
    must be loud at construction, not a silent capacity black hole."""
    import logging
    with caplog.at_level(logging.WARNING, logger="finchat_tpu.serve.fleet"):
        _stub_fleet(5, num_partitions=4)
    assert any("NO traffic" in r.getMessage() for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="finchat_tpu.serve.fleet"):
        _stub_fleet(4, num_partitions=4)  # at the bound: fine
    assert not caplog.records


def test_no_live_replica_raises():
    fleet = _stub_fleet(2)
    for rep in fleet.replicas:
        rep.state = OUT
    assert fleet.replica_for("c") is None
    with pytest.raises(RuntimeError):
        fleet.agent_for("c")


# --- router-level dedupe ring ---------------------------------------------

def test_dedupe_ring_shared_and_forget_removes_ring_slot():
    ring = DedupeRing(size=4)
    assert not ring.seen("m1")
    assert ring.seen("m1")  # second delivery (sibling replica) skips
    # a FAILED id is forgotten — set and ring slot — so a retry reprocesses
    assert not ring.seen("m2")
    ring.forget("m2")
    assert not ring.seen("m2")
    # overflow evicts oldest, and forget leaves no stale slot behind that
    # could age out a re-added answered id early
    for i in range(10):
        ring.seen(f"fill-{i}")
    assert not ring.seen("m1")  # aged out by overflow, as sized


# --- real-engine fleet: drain handoff + respawn + migration ----------------

def _make_replica(rid: str, params, config, **cfg_overrides) -> EngineReplica:
    defaults = dict(
        max_seqs=3, page_size=8, num_pages=64, max_seq_len=128,
        prefill_chunk=16, session_cache=True, session_cache_bytes=16 << 20,
        breaker_max_rebuilds=1,
    )
    defaults.update(cfg_overrides)
    engine = InferenceEngine(config, params, EngineConfig(**defaults))
    sched = ContinuousBatchingScheduler(
        engine, eos_id=-1, metrics=METRICS.labeled(replica=rid),
        replica_id=rid,
    )
    return EngineReplica(replica_id=rid, scheduler=sched)


def _make_fleet(n: int, **cfg_overrides) -> EngineFleet:
    config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
    params = init_params(config, jax.random.key(0))
    reps = [_make_replica(str(i), params, config, **cfg_overrides)
            for i in range(n)]
    return EngineFleet(
        reps,
        FleetConfig(replicas=n, respawn_backoff_seconds=0.05,
                    supervisor_interval_seconds=0.05),
        num_partitions=16,
    )


async def _drain(handle):
    tokens = []
    while True:
        event = await handle.events.get()
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return tokens, None
        else:
            return tokens, event


def _greedy(max_new: int) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=max_new)


def test_drain_handoff_byte_identity_and_respawn():
    """Kill one replica of three mid-stream (wedge its decode AND revive
    sites until healed): every in-flight stream — including the victim's —
    completes on a sibling with the exact greedy tokens of an undisturbed
    run, zero errors; the victim goes OUT (gauge drops) and respawns LIVE
    after the heal (gauge recovers)."""
    prompts = {f"conv-{i}": list(range(7 * i + 1, 7 * i + 15))
               for i in range(6)}

    async def run(fault: bool) -> dict:
        fleet = _make_fleet(3)
        await fleet.start()
        out: dict = {"errors": 0}
        try:
            victim = next(rep for rep in fleet.replicas
                          if any(fleet.replica_for(c) is rep for c in prompts))
            handles = {}
            for conv, prompt in prompts.items():
                rep = fleet.replica_for(conv)
                handles[conv] = await rep.scheduler.submit(
                    conv, prompt, _greedy(10), conversation_id=conv)
            tasks = {c: asyncio.create_task(_drain(h))
                     for c, h in handles.items()}
            if fault:
                while any(h.generated < 2 for h in handles.values()
                          if fleet.replica_for(h.conversation_id) is victim):
                    await asyncio.sleep(0.002)
                dead = [True]

                def wedge(**ctx):
                    if dead[0] and ctx.get("replica") == victim.replica_id:
                        raise RuntimeError("drill: dead replica")

                faults.arm("scheduler.decode", wedge)
                faults.arm("engine.rebuild", wedge)
            results = {c: await asyncio.wait_for(t, timeout=120)
                       for c, t in tasks.items()}
            out["tokens"] = {c: toks for c, (toks, _e) in results.items()}
            out["errors"] = sum(1 for _t, e in results.values()
                                if e is not None)
            if fault:
                # poke the wedged replica until its breaker gives up
                # (probe streams drain to siblings and still complete)
                for i in range(6):
                    if victim.state != LIVE:
                        break
                    h = await victim.scheduler.submit(
                        f"probe{i}", list(range(50 + i, 62 + i)), _greedy(3))
                    _t, e = await asyncio.wait_for(
                        asyncio.ensure_future(_drain(h)), timeout=120)
                    out["errors"] += 1 if e is not None else 0
                for _ in range(2000):
                    if victim.state != LIVE:
                        break
                    await asyncio.sleep(0.01)
                out["victim_out"] = victim.state != LIVE
                out["live_during"] = int(
                    METRICS.get("finchat_fleet_replicas_live"))
                dead[0] = False  # heal: the supervisor's revive succeeds
                for _ in range(2000):
                    if victim.state == LIVE:
                        break
                    await asyncio.sleep(0.01)
                out["victim_respawned"] = victim.state == LIVE
                out["live_after"] = int(
                    METRICS.get("finchat_fleet_replicas_live"))
            for rep in fleet.replicas:
                rep.scheduler.allocator.check_invariants()
        finally:
            await fleet.stop()
            faults.disarm_all()
        return out

    clean = asyncio.run(run(False))
    drained0 = METRICS.get("finchat_fleet_drained_streams_total")
    chaos = asyncio.run(run(True))
    assert chaos["errors"] == 0
    assert chaos["tokens"] == clean["tokens"]  # byte-identical on siblings
    assert METRICS.get("finchat_fleet_drained_streams_total") > drained0
    assert chaos["victim_out"] and chaos["live_during"] == 2
    assert chaos["victim_respawned"] and chaos["live_after"] == 3


def test_cancel_of_drained_handle_targets_adopter():
    """A handle drained to a sibling is OWNED by the adopter: cleanup
    paths (the generator's disconnect/watchdog cancel) still hold the
    SOURCE scheduler, and cancelling there must delegate — evicting on
    the source with the adopter's slot index would kill an unrelated
    stream on the source and leak the slot+pages on the adopter."""

    async def run():
        fleet = _make_fleet(2)
        await fleet.start()
        try:
            a, b = fleet.replicas
            # a live stream on A (the one the 'client' will abandon) and
            # an unrelated stream on A that must survive the cancel
            h = await a.scheduler.submit("drained", list(range(1, 14)),
                                         _greedy(40))
            other = await a.scheduler.submit("bystander", list(range(30, 44)),
                                             _greedy(40))
            while h.generated < 2 or other.generated < 2:
                await asyncio.sleep(0.002)
            # breaker-style drain of h: preempt to host, sibling adopts.
            # Mirror _drain_to_sink faithfully: the drain POPS the handle
            # from the source's pending before offering it — leaving it
            # there makes both schedulers race to admit the same handle
            # (caught by the ISSUE 8 leak sanitizer: the loser strands a
            # slot and a phantom prefilling entry on the source)
            a.scheduler._preempt(h, for_rebuild=True)
            a.scheduler.pending.remove(h)
            b.scheduler.adopt(h)
            assert h.owner is b.scheduler
            while h.slot < 0:  # B admits the replay
                await asyncio.sleep(0.002)
            # the client goes away; the generator's finally still holds A
            a.scheduler.cancel(h)
            for _ in range(500):
                if h.finished and h.slot == -1:
                    break
                await asyncio.sleep(0.01)
            assert h.finished
            # the bystander on A kept streaming (its slot was untouched)
            g0 = other.generated
            for _ in range(500):
                if other.generated > g0 or other.finished:
                    break
                await asyncio.sleep(0.01)
            assert other.generated > g0 or other.finished
            await asyncio.wait_for(asyncio.ensure_future(_drain(other)),
                                   timeout=120)
            for rep in fleet.replicas:
                rep.scheduler.allocator.check_invariants()
            # nothing leaked on the adopter: its slot pool is whole again
            assert len(b.scheduler.free_slots) == 3
            assert not b.scheduler.decoding
        finally:
            await fleet.stop()

    asyncio.run(run())


def test_giveup_with_no_sibling_counts_each_drain_failure_once():
    """Last-replica-standing give-up: the sink refuses every offer (no
    live sibling) and the pending-fail loop fails each stream with a
    retryable ``replica_out`` error — finchat_fleet_drain_failures_total
    moves by EXACTLY one per failed stream (the sink's refusal must not
    also count, or an operator alert keyed on the series reads 2x)."""

    async def run() -> dict:
        config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(config, jax.random.key(0))
        reps = [_make_replica(str(i), params, config) for i in range(2)]
        fleet = EngineFleet(reps, FleetConfig(replicas=2, respawn=False),
                            num_partitions=16)
        await fleet.start()
        out: dict = {}
        try:
            a, b = fleet.replicas
            fleet._mark_out(b)  # the sink has nowhere to place a drain
            handles = [await a.scheduler.submit(
                f"lone-{i}", list(range(3 * i + 1, 3 * i + 14)), _greedy(40),
                conversation_id=f"lone-{i}") for i in range(2)]
            while any(h.generated < 2 for h in handles):
                await asyncio.sleep(0.002)
            failures0 = METRICS.get("finchat_fleet_drain_failures_total")
            drained0 = METRICS.get("finchat_fleet_drained_streams_total")
            faults.arm("scheduler.decode",
                       lambda **ctx: (_ for _ in ()).throw(
                           RuntimeError("drill: no sibling")))
            results = [await asyncio.wait_for(
                asyncio.ensure_future(_drain(h)), timeout=120)
                for h in handles]
            out["errors"] = [e for _t, e in results]
            out["failures_delta"] = (
                METRICS.get("finchat_fleet_drain_failures_total") - failures0)
            out["drained_delta"] = (
                METRICS.get("finchat_fleet_drained_streams_total") - drained0)
            # the OUT replica's queue is empty — no phantom backlog on
            # the gauge for its whole OUT period
            out["queue_depth"] = METRICS.get(
                "finchat_queue_depth", labels={"replica": "0"})
        finally:
            await fleet.stop()
            faults.disarm_all()
        return out

    out = asyncio.run(run())
    assert all(e is not None and e["code"] == "replica_out"
               and e["retryable"] for e in out["errors"])
    assert out["failures_delta"] == 2  # once per stream, not once per site
    assert out["drained_delta"] == 0
    assert out["queue_depth"] == 0


def test_adopt_honors_queue_bound_for_never_admitted_handles():
    """A give-up drain offers the victim's whole pending queue to
    siblings. Live streams (preempted/generated) always adopt — they jump
    the queue like local preempt-replays, which never count against the
    bound. NEVER-admitted handles are plain queued load: an adopter at
    ``max_queue_depth`` must refuse them (sink returns False → the
    give-up pending-fail loop sheds them retryable), or the transplant
    lands the sibling past its bound and submit() locks out every new
    client with OverloadedError until the foreign backlog drains."""

    async def run() -> None:
        config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(config, jax.random.key(0))
        a = _make_replica("0", params, config)
        b = _make_replica("1", params, config, max_queue_depth=2)
        fleet = EngineFleet([a, b], FleetConfig(replicas=2, respawn=False),
                            num_partitions=16)
        # schedulers NOT started: submits stay pending (never admitted)
        for i in range(2):
            await b.scheduler.submit(f"b-{i}", list(range(1, 10)),
                                     _greedy(8))
        fresh = await a.scheduler.submit("fresh", list(range(1, 10)),
                                         _greedy(8), conversation_id="cv")
        assert not b.scheduler.adopt(fresh)  # at the bound: refused
        assert fresh.owner is a.scheduler  # untouched — still the source's
        assert len(b.scheduler.pending) == 2
        # the drain sink surfaces the refusal (handle stays with source)
        drained0 = METRICS.get("finchat_fleet_drained_streams_total")
        sink = fleet._make_drain_sink(a)
        assert sink(fresh, None) is False
        assert METRICS.get("finchat_fleet_drained_streams_total") == drained0
        # a LIVE stream adopts even at the bound (queue-jumps like a
        # local preempt-replay) and rebinds its owner
        live = await a.scheduler.submit("live", list(range(1, 10)),
                                        _greedy(8))
        live.preempted = True
        assert b.scheduler.adopt(live)
        assert live.owner is b.scheduler
        assert b.scheduler.pending[0] is live

    asyncio.run(run())


def test_fail_prefix_job_resolves_future_when_reset_slot_raises():
    """``_fail_prefix_job`` runs a device op (reset_slot) that can raise
    on the very dead device that is failing the job. The job is already
    off ``_prefix_jobs`` by then, so nothing later can resolve it — the
    slot must come back and the future must resolve anyway, or the
    register_prefix_async awaiter hangs forever. And the error must NOT
    propagate: two callers (_fail_prefill_round under breaker_threshold=0,
    stop()) are unguarded — an escaping exception there kills the
    scheduler loop and strands every remaining job's awaiter."""

    async def run() -> None:
        config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(config, jax.random.key(0))
        rep = _make_replica("0", params, config)
        sched = rep.scheduler
        pages = sched.allocator.allocate("__prefix_test__", 2)
        slot = sched.free_slots.pop()
        job = _PrefixJob(ids=list(range(16)), shared_len=16,
                         owner="__prefix_test__", pages=pages, slot=slot,
                         future=asyncio.get_running_loop().create_future())
        sched._prefix_jobs.append(job)

        def dead(_slot):
            raise RuntimeError("drill: device gone")

        sched.engine.reset_slot = dead
        sched._fail_prefix_job(job)  # must neither raise nor hang
        assert job.future.done() and job.future.result() == 0
        assert job not in sched._prefix_jobs
        assert slot in sched.free_slots
        sched.allocator.check_invariants()

    asyncio.run(run())


def test_revive_async_threads_rebuild_and_resolves_prefix_futures():
    """``revive_async`` is what the supervisor runs: the device rebuild —
    seconds of KV-pool reallocation at real sizes — must leave the shared
    event loop free for the sibling schedulers (worker thread), while a
    prefix job stranded from before the give-up resolves device-free on
    the loop (no reset_slot against the dead engine)."""

    async def run() -> None:
        config = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
        params = init_params(config, jax.random.key(0))
        rep = _make_replica("0", params, config)
        sched = rep.scheduler
        pages = sched.allocator.allocate("__prefix_test__", 1)
        slot = sched.free_slots.pop()
        job = _PrefixJob(ids=list(range(8)), shared_len=8,
                         owner="__prefix_test__", pages=pages, slot=slot,
                         future=asyncio.get_running_loop().create_future())
        sched._prefix_jobs.append(job)
        sched.gave_up = True
        rebuild_thread: list[int] = []
        real_rebuild = sched.engine.rebuild_device_state

        def spying_rebuild():
            rebuild_thread.append(threading.get_ident())
            real_rebuild()

        sched.engine.rebuild_device_state = spying_rebuild
        assert await sched.revive_async()
        assert rebuild_thread and rebuild_thread[0] != threading.get_ident()
        assert job.future.done() and job.future.result() == 0
        assert not sched._prefix_jobs
        assert not sched.gave_up
        assert len(sched.free_slots) == sched.engine.engine_cfg.max_seqs
        sched.allocator.check_invariants()

    asyncio.run(run())


def test_respawn_rechecks_giveup_before_marking_live():
    """A flaky device can re-wedge DURING the respawn: the on_respawn
    prompt-head re-registration drives real prefill rounds, and a breaker
    give-up fired while state is RESPAWNING is invisible to _mark_out
    (LIVE-guarded). The supervisor must re-check ``gave_up`` after the
    hooks — marking LIVE anyway would route every new conversation to a
    known-wedged engine for a full fail-streak cycle each."""

    async def run() -> dict:
        fleet = _make_fleet(2)
        await fleet.start()
        out: dict = {}
        try:
            a, b = fleet.replicas
            rewedged = {"n": 0}

            def rewedge_once(rep):
                # first attempt: the re-registration "trips to give-up"
                if rep is b and rewedged["n"] == 0:
                    rewedged["n"] += 1
                    rep.scheduler.gave_up = True

            fleet.on_respawn.append(rewedge_once)
            b.scheduler.gave_up = True
            fleet._mark_out(b)
            for _ in range(1000):
                if b.state == LIVE:
                    break
                await asyncio.sleep(0.01)
            out["state"] = b.state
            out["rewedged"] = rewedged["n"]
            out["gave_up"] = b.scheduler.gave_up
        finally:
            await fleet.stop()
        return out

    out = asyncio.run(run())
    assert out["state"] == LIVE  # the retry (no re-wedge) went LIVE
    assert out["rewedged"] == 1  # attempt 1 ran the hooks and was rejected
    assert not out["gave_up"]  # LIVE only with the give-up actually clear


def test_poll_gate_counts_only_live_replicas():
    """The Kafka poll gate sizes in-flight claims by LIVE replicas:
    during an outage a worker polling at full-fleet capacity hoards
    messages the survivors must absorb instead of letting the consumer
    group redistribute them. Floored at one batch so a whole-fleet-out
    window still answers (retryable errors), never black-holes."""
    from finchat_tpu.serve.app import App

    fleet = _stub_fleet(4)
    stub = types.SimpleNamespace(
        cfg=types.SimpleNamespace(engine=types.SimpleNamespace(max_seqs=3)),
        fleet=fleet,
    )
    assert App._max_inflight(stub) == 12
    fleet.replicas[0].state = OUT
    assert App._max_inflight(stub) == 9
    for rep in fleet.replicas:
        rep.state = OUT
    assert App._max_inflight(stub) == 3  # floor: one batch
    stub.fleet = None
    assert App._max_inflight(stub) == 3  # fleetless: one engine, one batch


def test_session_migration_at_route_time():
    """A conversation whose session bytes retired on a replica that then
    went OUT resumes on its rerouted sibling FROM THOSE BYTES: the router
    migrates the entry at route time (counted), the source copy is
    discarded, and admission reports resumed_len > 0 with the greedy
    stream byte-identical to an unmigrated second turn."""

    async def run(kill_home: bool) -> dict:
        fleet = _make_fleet(2)
        await fleet.start()
        try:
            conv = "mig-conv"
            home = fleet.replica_for(conv)
            t1_prompt = list(range(1, 14))
            h1 = await home.scheduler.submit(
                "t1", t1_prompt, _greedy(10), conversation_id=conv)
            t1_tokens, err = await asyncio.wait_for(
                asyncio.ensure_future(_drain(h1)), timeout=120)
            assert err is None
            # retirement offloaded the entry on HOME
            assert home.scheduler.session_cache.get(conv) is not None
            m0 = METRICS.get("finchat_fleet_session_migrations_total")
            if kill_home:
                home.state = OUT
            rep2 = fleet.replica_for(conv)
            if kill_home:
                assert rep2 is not home
                # route-time migration moved the bytes, source discarded
                assert METRICS.get(
                    "finchat_fleet_session_migrations_total") == m0 + 1
                assert home.scheduler.session_cache.get(conv) is None
                assert rep2.scheduler.session_cache.get(conv) is not None
            t2_prompt = t1_prompt + t1_tokens + [7, 8, 9]
            h2 = await rep2.scheduler.submit(
                "t2", t2_prompt, _greedy(8), conversation_id=conv)
            t2_tokens, err = await asyncio.wait_for(
                asyncio.ensure_future(_drain(h2)), timeout=120)
            assert err is None
            return {"t2": t2_tokens, "resumed": h2.resumed_len}
        finally:
            await fleet.stop()

    stay = asyncio.run(run(False))
    moved = asyncio.run(run(True))
    assert moved["t2"] == stay["t2"]  # migration can't change the stream
    assert moved["resumed"] > 0  # admission resumed from migrated bytes
    assert moved["resumed"] == stay["resumed"]  # same profile as staying home


def test_route_time_migration_moves_role_suffixed_keys():
    """The PRODUCTION serving path keys session entries per LLM role
    (``conv#resp`` — agent/graph.py via session_key), while the router is
    asked for the BARE conversation id: route-time migration must find
    and move the suffixed entries too, or lazy migration is inert for
    real traffic (it only ever worked for direct scheduler submissions)."""

    async def run() -> None:
        fleet = _make_fleet(2)
        await fleet.start()
        try:
            conv = "prod-conv"
            key = session_key(conv, "resp")
            home = fleet.replica_for(conv)
            h1 = await home.scheduler.submit(
                "t1", list(range(1, 14)), _greedy(10), conversation_id=key)
            _toks, err = await asyncio.wait_for(
                asyncio.ensure_future(_drain(h1)), timeout=120)
            assert err is None
            assert home.scheduler.session_cache.get(key) is not None
            m0 = METRICS.get("finchat_fleet_session_migrations_total")
            home.state = OUT
            rep2 = fleet.replica_for(conv)  # routed by the BARE id
            assert rep2 is not home
            assert METRICS.get(
                "finchat_fleet_session_migrations_total") == m0 + 1
            assert home.scheduler.session_cache.get(key) is None
            assert rep2.scheduler.session_cache.get(key) is not None
        finally:
            await fleet.stop()

    asyncio.run(run())


def test_drain_sink_routes_by_conversation_not_role_key():
    """A drained handle carries the per-role cache key as its
    conversation_id; the sink must pick the sibling by the BARE
    conversation — the replica the conversation's NEXT TURNS route to —
    or the handed-off session bytes strand on a non-affinity sibling and
    a conversation's #tool/#resp streams can split across replicas."""
    fleet = _stub_fleet(4)
    adopted: list[str] = []
    imported: list[str] = []
    for rep in fleet.replicas:
        rep.scheduler.adopt = (
            lambda h, rid=rep.replica_id: (adopted.append(rid), True)[1])
        rep.scheduler.import_session_entry = (
            lambda p, rid=rep.replica_id: imported.append(rid) or True)
    source = fleet.replicas[0]

    def owner(key):
        return fleet.replica_for_partition(
            fleet.partition_for(key), exclude=source)

    # a conversation whose raw role key would route elsewhere — the
    # regression this pins (routing once hashed handle.conversation_id)
    conv = next(c for c in (f"conv-{i}" for i in range(500))
                if owner(c) is not owner(session_key(c, "resp")))
    expected = owner(conv).replica_id
    sink = source.scheduler.drain_sink
    for role in SESSION_KEY_ROLES:
        handle = types.SimpleNamespace(
            conversation_id=session_key(conv, role), seq_id=f"s-{role}")
        assert sink(handle, {"conversation_id": handle.conversation_id})
    assert adopted == [expected] * 2  # both roles, both on the home sibling
    assert imported == [expected] * 2


def test_session_import_relinks_shared_head_or_refuses():
    """An exported entry whose KV rides a shared prompt head re-links
    against the importer's OWN live registration of that head (ref
    counted); an importer with no matching head refuses the entry
    (counted) instead of serving positionally-wrong KV."""

    async def run():
        fleet = _make_fleet(2)
        await fleet.start()
        try:
            a, b = fleet.replicas
            head = list(range(1, 12))  # page-whole shared part: 8 tokens
            assert a.scheduler.register_prefix(head) >= 8
            payload = {
                "conversation_id": "hc",
                "token_ids": np.asarray(head[:8], np.int32),
                "prefix_len": 8,
                "snap": None,
            }
            refused0 = METRICS.get("finchat_fleet_session_import_refused_total")
            # b has no matching head: refused, counted (unlabeled, like
            # every finchat_fleet_* series), nothing cached
            assert not b.scheduler.import_session_entry(dict(payload))
            assert METRICS.get(
                "finchat_fleet_session_import_refused_total") == refused0 + 1
            assert b.scheduler.session_cache.get("hc") is None
            # a holds the head: the import re-links and takes a reference
            entry_a = a.scheduler._prefixes[0]
            refs0 = entry_a.refs
            assert a.scheduler.import_session_entry(dict(payload))
            got = a.scheduler.session_cache.get("hc")
            assert got is not None and got.prefix_entry is entry_a
            assert entry_a.refs == refs0 + 1
            # dropping the entry releases the reference (on_drop path)
            a.scheduler.session_cache.discard("hc")
            assert entry_a.refs == refs0
        finally:
            await fleet.stop()

    asyncio.run(run())


def test_replica_labeled_metrics_render():
    """Per-replica series share one TYPE line per family and carry the
    replica label — the scrape separates a draining replica from its
    healthy siblings."""
    reg = MetricsRegistry()
    reg.labeled(replica="0").inc("finchat_preemptions_total")
    reg.labeled(replica="1").inc("finchat_preemptions_total", 2)
    reg.labeled(replica="1").set_gauge("finchat_breaker_state", 1)
    assert reg.get("finchat_preemptions_total", {"replica": "0"}) == 1
    assert reg.get("finchat_preemptions_total", {"replica": "1"}) == 2
    text = reg.render_prometheus()
    assert text.count("# TYPE finchat_preemptions_total counter") == 1
    assert 'finchat_preemptions_total{replica="0"} 1' in text
    assert 'finchat_preemptions_total{replica="1"} 2' in text
    assert 'finchat_breaker_state{replica="1"} 1' in text
