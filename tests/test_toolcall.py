"""Tool-decision parsing; the tool_prompt few-shots are the test cases
(SURVEY §7.3 hard part 5)."""

from finchat_tpu.agent.toolcall import parse_tool_decision


def test_no_tool_literal():
    assert parse_tool_decision("No tool call") is None
    assert parse_tool_decision("  no tool call  ") is None
    assert parse_tool_decision("") is None


def test_fewshot_groceries():
    # tool_prompt.txt example 1
    out = parse_tool_decision(
        'Call tool: retrieve_transactions({"search_query": "grocery store purchases", "num_transactions": 20})'
    )
    assert out is not None
    assert out.args["search_query"] == "grocery store purchases"
    assert out.args["num_transactions"] == 20


def test_fewshot_time_period():
    # tool_prompt.txt example 2
    out = parse_tool_decision(
        'retrieve_transactions({"search_query": "all purchases", "time_period_days": 2})'
    )
    assert out is not None
    assert out.args["time_period_days"] == 2
    assert "num_transactions" not in out.args


def test_user_id_from_model_is_dropped():
    out = parse_tool_decision(
        'retrieve_transactions({"search_query": "x", "user_id": "attacker"})'
    )
    assert out is not None
    assert "user_id" not in out.args


def test_num_transactions_clamped():
    out = parse_tool_decision('retrieve_transactions({"num_transactions": 999999})')
    assert out.args["num_transactions"] == 10_000
    out = parse_tool_decision('retrieve_transactions({"num_transactions": -3})')
    assert out.args["num_transactions"] == 1


def test_malformed_json_degrades_to_defaults():
    out = parse_tool_decision("retrieve_transactions({search_query: broken")
    assert out is not None
    assert out.args["search_query"] == "recent transactions"


def test_prose_without_tool_name_is_no_call():
    assert parse_tool_decision("I think we should check the weather.") is None


def test_multiline_json():
    out = parse_tool_decision(
        'retrieve_transactions({\n  "search_query": "rent",\n  "time_period_days": 90\n})'
    )
    assert out.args["search_query"] == "rent"
    assert out.args["time_period_days"] == 90
