"""Free-running device loop (ISSUE 13; engine ragged_multi_round +
scheduler _dispatch_freerun/_consume_ring over ops/freerun.stage_freerun).

The contract under test: a captured multi-round run is pure dispatch
fusion — greedy streams are byte-identical to the host-stepped path
(freerun_rounds=1) under every riding feature (fused loop tails, mid-run
EOS via the on-device stop mask, prompts completing and flipping to decode
rows mid-capture, admissions mid-flight forcing an epoch break), residual
ring tokens replay exactly once across a preemption epoch boundary (no
duplicate or dropped tokens — the PR 5 discipline), the dispatch counters
attribute a capture as N rounds / 1 dispatch so dispatches-per-round drops
below 1, rows needing host decisions (grammar constraints, live spec
proposals) cap the capture to one round, and allocator/slot state audits
leak-free after free-run waves (the conftest sanitizer also audits every
scheduler built here)."""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import (
    InferenceEngine,
    commit_first_token,
    prefill_step,
    ragged_mixed_step,
    ragged_multi_round,
)
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

# fp32 pins the byte-identity contract (the PR 4/10 discipline): a token
# computed inside a captured scan must match the host-stepped round bit
# for bit, so a structural staging bug cannot hide behind bf16 rounding
CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
CHUNK = 16


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _stack(params, freerun=4, max_seqs=4, num_pages=128, eos_id=-1,
           decode_loop_depth=1, spec_tokens=0):
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=num_pages, max_seq_len=128,
        prefill_chunk=CHUNK, mixed_step=True, session_cache=False,
        decode_loop_depth=decode_loop_depth, spec_tokens=spec_tokens,
        freerun_rounds=freerun,
    )
    engine = InferenceEngine(CONFIG, params, cfg)
    return ContinuousBatchingScheduler(engine, eos_id=eos_id)


async def _drain(handle, out):
    while True:
        ev = await asyncio.wait_for(handle.events.get(), timeout=120)
        if ev["type"] == "token":
            out.append(ev["token_id"])
        elif ev["type"] == "done":
            assert handle.events.empty()
            return
        else:
            raise AssertionError(ev)


# --- engine level -----------------------------------------------------------


def test_engine_multi_round_matches_stepped_rounds(params):
    """ragged_multi_round over a staged 3-round queue == 3 host-stepped
    ragged_mixed_step calls over the same descriptors, exactly: the
    completing prefill row's on-device first token, the decode rows'
    tokens, the fused tails, and the final context_lens/last_tokens all
    match an identically prepared engine — the captured round body IS the
    host-stepped one."""

    def prepare():
        cfg = EngineConfig(
            max_seqs=4, page_size=8, num_pages=64, max_seq_len=128,
            prefill_chunk=CHUNK, decode_loop_depth=2, freerun_rounds=3,
        )
        eng = InferenceEngine(CONFIG, params, cfg)
        alloc = PageAllocator(cfg.num_pages)
        # slot 0: decoding (rides a fused tail each round)
        p0 = [3, 7, 11, 200, 42]
        eng.set_page_table_row(0, alloc.allocate("s0", pages_needed(len(p0) + 16, 8)))
        logits = eng.prefill(0, p0)
        eng.state, _ = commit_first_token(
            eng.state, jnp.int32(0), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
        # slot 1: a 2-chunk prompt with only the FIRST chunk prefilled —
        # its tail completes in round 0 of the capture, decodes after
        p1 = list(range(1, CHUNK + 6))
        eng.set_page_table_row(1, alloc.allocate("s1", pages_needed(len(p1) + 16, 8)))
        eng.state, _ = prefill_step(
            eng.params, eng.state,
            jnp.asarray([p1[:CHUNK]], jnp.int32), jnp.asarray([1], jnp.int32),
            jnp.asarray([0], jnp.int32), jnp.asarray([CHUNK], jnp.int32),
            config=eng.config, page_size=8, attn_backend=eng.attn_backend,
        )
        return eng, p1

    B, R, F = 4, 4, 3
    tail = None
    zR = np.zeros((R,), np.float32)
    oR = np.ones((R,), np.float32)
    kR = np.zeros((R,), np.int32)
    zB = jnp.zeros((B,), jnp.float32)
    oB = jnp.ones((B,), jnp.float32)
    kB = jnp.zeros((B,), jnp.int32)

    def stage():
        """The 3-round descriptor queue: round 0 = slot 1's completing
        tail (armed) + slot 0 decode w/ tail; rounds 1-2 = both slots
        decode, slot 0 with tails."""
        eng, p1 = prepare()
        tail = p1[CHUNK:]
        T = 8
        tokens = np.zeros((F, T), np.int32)
        tok_row = np.full((F, T), R, np.int32)
        row_slot = np.zeros((R,), np.int32)
        row_slot[0] = 1  # row 0 = slot 1 (prefill), row 1 = slot 0
        row_slot[1] = 0
        row_start = np.zeros((F, R), np.int32)
        row_len = np.zeros((F, R), np.int32)
        from_dev = np.zeros((F, R), bool)
        arm = np.zeros((F, R), bool)
        loop_active = np.zeros((F, B), bool)
        # round 0
        tokens[0, : len(tail)] = tail
        tok_row[0, : len(tail)] = 0
        tok_row[0, len(tail)] = 1
        row_start[0, 0], row_len[0, 0], arm[0, 0] = CHUNK, len(tail), True
        row_len[0, 1], from_dev[0, 1], arm[0, 1] = 1, True, True
        loop_active[0, 0] = True
        # rounds 1-2: both decode; slot 0 keeps its tail
        for r in (1, 2):
            tok_row[r, 0] = 0
            tok_row[r, 1] = 1
            row_len[r, 0], from_dev[r, 0], arm[r, 0] = 1, True, True
            row_len[r, 1], from_dev[r, 1], arm[r, 1] = 1, True, True
            loop_active[r, 0] = True
        return eng, (tokens, tok_row, row_slot, row_start, row_len,
                     from_dev, arm, loop_active)

    # --- host-stepped: 3 ragged_mixed_step calls ------------------------
    eng_s, staged = stage()
    (tokens, tok_row, row_slot, row_start, row_len, from_dev, arm,
     loop_active) = staged
    stepped = []
    for r in range(F):
        eng_s.state, emitted, n_em, _lg, blk = ragged_mixed_step(
            eng_s.params, eng_s.state,
            jnp.asarray(tokens[r]), jnp.asarray(tok_row[r]),
            jnp.asarray(row_slot), jnp.asarray(row_start[r]),
            jnp.asarray(row_len[r]), jnp.asarray(from_dev[r]),
            jnp.asarray(arm[r]), jnp.zeros((R,), jnp.int32),
            jnp.asarray(zR), jnp.asarray(oR), jnp.asarray(kR),
            jnp.asarray(loop_active[r]), zB, oB, kB, jnp.int32(-1),
            config=eng_s.config, page_size=8, attn_backend=eng_s.attn_backend,
            spec_width=0, loop_depth=2,
        )
        stepped.append((np.asarray(emitted[:, 0]).tolist(),
                        np.asarray(n_em).tolist(),
                        np.asarray(blk).tolist()))
    final_s = (np.asarray(eng_s.state.context_lens).tolist(),
               np.asarray(eng_s.state.last_tokens).tolist())

    # --- captured: ONE ragged_multi_round dispatch ----------------------
    eng_c, staged = stage()
    (tokens, tok_row, row_slot, row_start, row_len, from_dev, arm,
     loop_active) = staged
    eng_c.state, ring_tok, ring_n, ring_blk = ragged_multi_round(
        eng_c.params, eng_c.state,
        jnp.asarray(tokens), jnp.asarray(tok_row), jnp.asarray(row_slot),
        jnp.asarray(row_start), jnp.asarray(row_len), jnp.asarray(from_dev),
        jnp.asarray(arm),
        jnp.asarray(zR), jnp.asarray(oR), jnp.asarray(kR),
        jnp.asarray(loop_active), zB, oB, kB, jnp.int32(-1),
        config=eng_c.config, page_size=8, attn_backend=eng_c.attn_backend,
        loop_depth=2,
    )
    captured = [
        (np.asarray(ring_tok[r]).tolist(), np.asarray(ring_n[r]).tolist(),
         np.asarray(ring_blk[r]).tolist())
        for r in range(F)
    ]
    final_c = (np.asarray(eng_c.state.context_lens).tolist(),
               np.asarray(eng_c.state.last_tokens).tolist())
    assert captured == stepped
    assert final_c == final_s


def test_engine_multi_round_eos_stop_mask(params):
    """The generalized stop mask: a decode row whose slot holds EOS in
    last_tokens rides every captured round inert — n_emitted 0, context
    frozen, KV writes trash-redirected — while the other row advances."""
    cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=64,
        prefill_chunk=8, freerun_rounds=3,
    )
    eng = InferenceEngine(CONFIG, params, cfg)
    alloc = PageAllocator(cfg.num_pages)
    for slot, p in ((0, [3, 7, 11, 200, 42]), (1, [9, 9, 9, 9])):
        eng.set_page_table_row(
            slot, alloc.allocate(f"s{slot}", pages_needed(len(p) + 16, 8)))
        logits = eng.prefill(slot, p)
        eng.state, _ = commit_first_token(
            eng.state, jnp.int32(slot), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
    # pretend slot 1's last commit WAS the eos token
    eos = 77
    eng.set_last_token(1, eos)
    ctx_before = np.asarray(eng.state.context_lens).tolist()
    F, R, B, T = 3, 2, 2, 8
    tokens = np.zeros((F, T), np.int32)
    tok_row = np.full((F, T), R, np.int32)
    tok_row[:, 0] = 0
    tok_row[:, 1] = 1
    ones = np.ones((F, R), np.int32)
    true_ = np.ones((F, R), bool)
    eng.state, ring_tok, ring_n, _blk = ragged_multi_round(
        eng.params, eng.state,
        jnp.asarray(tokens), jnp.asarray(tok_row),
        jnp.asarray([0, 1], jnp.int32), jnp.zeros((F, R), jnp.int32),
        jnp.asarray(ones), jnp.asarray(true_), jnp.asarray(true_),
        jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
        jnp.zeros((R,), jnp.int32),
        jnp.zeros((F, B), bool), jnp.zeros((B,), jnp.float32),
        jnp.ones((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
        jnp.int32(eos),
        config=eng.config, page_size=8, attn_backend=eng.attn_backend,
        loop_depth=1,
    )
    n = np.asarray(ring_n)
    assert n[:, 0].tolist() == [1, 1, 1]  # live row advanced every round
    assert n[:, 1].tolist() == [0, 0, 0]  # dead row inert every round
    ctx = np.asarray(eng.state.context_lens).tolist()
    assert ctx[0] == ctx_before[0] + 3
    assert ctx[1] == ctx_before[1]  # frozen
    assert int(eng.state.last_tokens[1]) == eos  # still the sentinel


# --- scheduler level --------------------------------------------------------


def _run_workload(params, freerun, *, eos_id=-1, decode_loop_depth=2,
                  spec_tokens=0, seed=7, constrained=False):
    """Two decode streams, then a long prompt admitted mid-decode (so its
    chunks coexist with live decodes and the captures carry prefill +
    completion-flip + decode rows). Returns (streams, freerun dispatches,
    coexist dispatches/rounds window)."""
    sched = _stack(params, freerun=freerun, eos_id=eos_id,
                   decode_loop_depth=decode_loop_depth,
                   spec_tokens=spec_tokens)
    rng = np.random.default_rng(seed)
    short_a = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    short_b = rng.integers(1, CONFIG.vocab_size, size=14).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=5 * CHUNK + 2).tolist()

    async def go():
        snap0 = METRICS.snapshot()
        await sched.start()
        try:
            ha = await sched.submit(
                "a", short_a, SamplingParams(temperature=0.0, max_new_tokens=28))
            hb = await sched.submit(
                "b", short_b, SamplingParams(temperature=0.0, max_new_tokens=22))
            outs = {"a": [], "b": [], "long": []}
            tasks = [asyncio.create_task(_drain(ha, outs["a"])),
                     asyncio.create_task(_drain(hb, outs["b"]))]
            if constrained:
                from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

                tok = ByteTokenizer()
                hc = await sched.submit(
                    "tool", tok.encode("decide", add_bos=True),
                    SamplingParams(temperature=0.0, max_new_tokens=20),
                    constraint=TokenConstraint(GrammarVocab.for_tokenizer(tok)),
                )
                outs["tool"] = []
                tasks.append(asyncio.create_task(_drain(hc, outs["tool"])))
            while len(outs["a"]) < 2 or len(outs["b"]) < 2:
                await asyncio.sleep(0.002)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=8))
            tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
            await asyncio.gather(*tasks)
            await asyncio.sleep(0.05)  # post-episode tick: attribution lands
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            assert sorted(sched.free_slots) == list(range(4))
            snap1 = METRICS.snapshot()
            win = {
                k: snap1.get(k, 0) - snap0.get(k, 0)
                for k in ("finchat_freerun_dispatches_total",
                          "finchat_coexist_dispatches_total",
                          "finchat_coexist_rounds_total",
                          "finchat_coexist_iterations_total")
            }
            return outs, win
        finally:
            await sched.stop()

    return asyncio.run(go())


@pytest.mark.parametrize("seed", [7, 11, 23])
def test_freerun_streams_byte_identical(params, seed):
    """3-seed feature fuzz: loop tails + admission mid-flight (the long
    prompt lands while captures are in flight, forcing the epoch-boundary
    re-entry) — every greedy stream byte-identical captured vs
    host-stepped, with captures actually engaging."""
    base, _ = _run_workload(params, 1, seed=seed)
    fr, win = _run_workload(params, 4, seed=seed)
    assert win["finchat_freerun_dispatches_total"] >= 1
    assert fr == base


def test_freerun_mid_run_eos_byte_identical(params):
    """Mid-run EOS: pick a token the base run emits mid-stream, make it
    the eos id, and re-run both modes — the device stop mask must end the
    stream at the same point the host-stepped path does, byte-identically,
    with the remaining streams unaffected."""
    base, _ = _run_workload(params, 1)
    stream = base["a"]
    eos = stream[len(stream) // 2]  # a token emitted mid-stream
    base_eos, _ = _run_workload(params, 1, eos_id=eos)
    fr_eos, win = _run_workload(params, 4, eos_id=eos)
    # the eos stream genuinely ended early, mid-capture
    assert len(base_eos["a"]) < len(base["a"])
    assert win["finchat_freerun_dispatches_total"] >= 1
    assert fr_eos == base_eos


def test_freerun_dispatches_per_round_below_one(params):
    """The acceptance headline: on a loaded engine (prefill + decode
    coexisting) at freerun_rounds=4, the PR 10 scheduler-attributed
    counters must show dispatches per ROUND < 1 — a capture books N
    rounds for its one dispatch."""
    _, win = _run_workload(params, 4)
    assert win["finchat_freerun_dispatches_total"] >= 1
    rounds = win["finchat_coexist_rounds_total"]
    dispatches = win["finchat_coexist_dispatches_total"]
    assert rounds > 0
    assert dispatches / rounds < 1.0, (dispatches, rounds)
    # the host-stepped path books exactly 1 dispatch per round
    _, win1 = _run_workload(params, 1)
    r1, d1 = win1["finchat_coexist_rounds_total"], win1["finchat_coexist_dispatches_total"]
    assert r1 > 0 and d1 / r1 >= 1.0, (d1, r1)


def test_freerun_capped_for_constrained_rows(params):
    """Grammar-constrained rows need a host pick every round: with one in
    the mix the capture must cap to 1 (zero freerun dispatches), streams
    still correct (byte-identical to freerun off)."""
    base, _ = _run_workload(params, 1, constrained=True)
    fr, win = _run_workload(params, 4, constrained=True)
    assert win["finchat_freerun_dispatches_total"] == 0
    assert METRICS.get("finchat_freerun_capped_total",
                       labels={"reason": "constrained"}) >= 1
    assert fr == base


def test_freerun_capped_for_live_spec_proposals(params):
    """A live spec-proposal window (drafts come from delivered host
    tokens) caps the capture; streams stay byte-identical to the
    host-stepped path with the same spec config."""
    base, _ = _run_workload(params, 1, spec_tokens=2, seed=3)
    fr, win = _run_workload(params, 4, spec_tokens=2, seed=3)
    assert fr == base


def test_freerun_epoch_boundary_exactly_once(params):
    """Preempt a decoding stream while a capture is mid-flight: residual
    ring tokens for the stale epoch are discarded, the replay re-prefills
    from the handle's history, and the stream completes byte-identical to
    an unpreempted run — zero duplicate or dropped tokens (the PR 5
    discipline riding the ring), with the epoch break recorded."""
    base, _ = _run_workload(params, 1)

    sched = _stack(params, freerun=4, decode_loop_depth=2)
    rng = np.random.default_rng(7)
    short_a = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    short_b = rng.integers(1, CONFIG.vocab_size, size=14).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=5 * CHUNK + 2).tolist()

    async def go():
        d0 = METRICS.get("finchat_freerun_dispatches_total")
        p0 = METRICS.get("finchat_preemptions_total")
        await sched.start()
        try:
            ha = await sched.submit(
                "a", short_a, SamplingParams(temperature=0.0, max_new_tokens=28))
            hb = await sched.submit(
                "b", short_b, SamplingParams(temperature=0.0, max_new_tokens=22))
            outs = {"a": [], "b": [], "long": []}
            tasks = [asyncio.create_task(_drain(ha, outs["a"])),
                     asyncio.create_task(_drain(hb, outs["b"]))]
            while len(outs["a"]) < 2 or len(outs["b"]) < 2:
                await asyncio.sleep(0.002)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=8))
            tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
            # wait until captures are flying, then preempt stream "a"
            # mid-flight — its undelivered ring tokens go stale
            for _ in range(200_000):
                if METRICS.get("finchat_freerun_dispatches_total") - d0 >= 1:
                    break
                await asyncio.sleep(0.001)
            if not ha.finished:
                sched._preempt(ha)
            await asyncio.gather(*tasks)
            assert METRICS.get("finchat_preemptions_total") - p0 >= 1
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            return outs
        finally:
            await sched.stop()

    outs = asyncio.run(go())
    assert outs == base  # exactly-once: no dup/dropped tokens anywhere


def test_freerun_cancel_mid_capture_spares_completions(params):
    """Regression (review find): cancelling the only decode stream while
    a capture is mid-flight empties `decoding`, so the next iteration
    leaves the mixed path with the ring UNDRAINED — and a prompt that
    completed inside that capture is still in `prefilling` until the
    drain flips it. The split prefill round must not run first: it would
    re-complete the prompt on an empty chunk (a garbage duplicate first
    token off an all-padding logits row) and the later drain's flip would
    raise. The loop now drains a leftover ring before any split-path
    round; the long stream must stay byte-identical to the no-cancel
    host-stepped run at every cancel timing."""
    sched_base = _stack(params, freerun=1, decode_loop_depth=1)
    rng = np.random.default_rng(7)
    short_a = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=3 * CHUNK + 2).tolist()

    def run(sched, cancel_after=None):
        async def go():
            d0 = METRICS.get("finchat_freerun_dispatches_total")
            await sched.start()
            errs: list = []

            async def drain_ok(h, out):
                while True:
                    ev = await asyncio.wait_for(h.events.get(), timeout=120)
                    if ev["type"] == "token":
                        out.append(ev["token_id"])
                    elif ev["type"] == "done":
                        return
                    else:
                        errs.append(ev)
                        return

            try:
                ha = await sched.submit(
                    "a", short_a,
                    SamplingParams(temperature=0.0, max_new_tokens=40))
                outs = {"a": [], "long": []}
                ta = asyncio.create_task(drain_ok(ha, outs["a"]))
                while len(outs["a"]) < 2:
                    await asyncio.sleep(0.002)
                hl = await sched.submit(
                    "long", long_p,
                    SamplingParams(temperature=0.0, max_new_tokens=6))
                tl = asyncio.create_task(drain_ok(hl, outs["long"]))
                if cancel_after is not None:
                    for _ in range(200_000):
                        if (METRICS.get("finchat_freerun_dispatches_total")
                                - d0 >= cancel_after):
                            break
                        await asyncio.sleep(0.0005)
                    sched.cancel(ha)  # mid-flight: the capture goes stale
                await asyncio.gather(ta, tl)
                sched.allocator.check_invariants()
                assert sched.allocator.used_count == 0
                return outs, errs
            finally:
                await sched.stop()

        return asyncio.run(go())

    base, berrs = run(sched_base)
    assert not berrs
    for trigger in (1, 2):  # cancel right after the 1st / 2nd capture
        sched = _stack(params, freerun=4, decode_loop_depth=1)
        outs, errs = run(sched, cancel_after=trigger)
        assert not errs, errs
        assert outs["long"] == base["long"], (trigger, outs["long"])


def test_freerun_divergence_anomaly_detected(params):
    """A ring round emitting where the staged plan never armed a row is a
    free-run divergence: the drain refuses the cell (nothing delivered)
    and records the anomaly."""
    from finchat_tpu.engine.scheduler import _InFlightRing

    sched = _stack(params, freerun=2)
    F, R, B = 2, 4, 4
    armed = np.zeros((F, R), bool)  # nothing staged to emit...
    ring = _InFlightRing(
        tokens=np.full((F, R), 5, np.int32),
        n_emitted=np.ones((F, R), np.int32),  # ...yet everything "emitted"
        blocks=np.full((F, 0, B), -1, np.int32),
        rounds=F, members=[], armed=armed,
        loop_rounds=np.zeros((F, B), bool), completes_at={}, ahead={},
    )
    d0 = METRICS.get("finchat_freerun_divergences_total")
    asyncio.run(sched._consume_ring(ring))
    assert METRICS.get("finchat_freerun_divergences_total") - d0 == 1


def test_freerun_dispatch_traced_with_rows(params):
    """Free-run dispatches land in the trace ring as mode-"freerun" rows
    (the _trace_dispatch format), so shared-dispatch attribution keeps
    working on captured rounds."""
    TRACER.configure(enabled=True)
    TRACER.clear()
    _run_workload(params, 4)
    dispatches = [
        ev for ev in TRACER.snapshot()
        if ev[2] == "dispatch" and ev[5] and ev[5].get("kind") == "freerun"
    ]
    assert dispatches, "no freerun dispatch event recorded"
    rows = dispatches[0][5]["rows"]
    assert rows and all(r[2] == "freerun" for r in rows)


def test_freerun_waves_leak_free(params):
    """Back-to-back admission waves across captures: allocator and slot
    invariants hold after every wave (the conftest sanitizer additionally
    audits the stopped scheduler)."""
    sched = _stack(params, freerun=4, decode_loop_depth=2)
    rng = np.random.default_rng(5)

    async def go():
        await sched.start()
        try:
            for wave in range(3):
                handles = []
                outs = []
                for i in range(3):
                    n = int(rng.integers(6, 2 * CHUNK + 4))
                    p = rng.integers(1, CONFIG.vocab_size, size=n).tolist()
                    h = await sched.submit(
                        f"w{wave}-{i}", p,
                        SamplingParams(temperature=0.0,
                                       max_new_tokens=int(rng.integers(4, 16))))
                    handles.append(h)
                    outs.append([])
                await asyncio.gather(*[
                    _drain(h, o) for h, o in zip(handles, outs)
                ])
                assert all(o for o in outs)
                sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            assert sorted(sched.free_slots) == list(range(4))
        finally:
            await sched.stop()

    asyncio.run(go())


def test_stage_freerun_underfill_and_budget():
    """ops/freerun staging: budgets are consumed deterministically (loop
    rounds take loop_depth, plain rounds 1), exhausted rows stop being
    staged, and a plan whose work runs out mid-capture reports the
    underfill so the scheduler falls back to host-stepped rounds."""
    from finchat_tpu.ops.freerun import RowSpec, stage_freerun

    bucket = lambda n: max(8, n)
    # a decode row with budget 3 at loop_depth 2: round 0 rides a tail
    # (consumes 2), round 1 plain (1), rounds 2-3 unstaged -> underfill
    plan = stage_freerun(
        [RowSpec(slot=0, kind="decode", budget=3, loop_ok=True)],
        rounds=4, chunk=4, loop_depth=2, max_seqs=2, bucket=bucket,
    )
    assert plan.active_rounds == 2
    assert plan.loop_active[:, 0].tolist() == [True, False, False, False]
    assert plan.row_arm[:, 0].tolist() == [True, True, False, False]
    assert plan.ahead == {0: 3}
    # a prefill row completes at round 1 (5 tokens, chunk 4), arms there,
    # then decodes; held rows never arm
    plan = stage_freerun(
        [RowSpec(slot=0, kind="prefill", ids=list(range(1, 6)), budget=8,
                 loop_ok=False),
         RowSpec(slot=1, kind="prefill", ids=list(range(1, 10)), arm=False)],
        rounds=3, chunk=4, loop_depth=1, max_seqs=2, bucket=bucket,
    )
    assert plan.completes_at == {0: 1}
    assert plan.row_arm[:, 0].tolist() == [False, True, True]
    assert plan.row_from_device[:, 0].tolist() == [False, False, True]
    assert not plan.row_arm[:, 1].any()  # held: parks at prefix end
    assert plan.advanced == {0: 5, 1: 9}
    assert plan.active_rounds == 3


def test_freerun_config_env_reader(monkeypatch):
    from finchat_tpu.utils.config import load_config

    monkeypatch.setenv("FINCHAT_FREERUN_ROUNDS", "8")
    assert load_config().engine.freerun_rounds == 8
    monkeypatch.delenv("FINCHAT_FREERUN_ROUNDS")
    assert load_config().engine.freerun_rounds == 1  # host-stepped default


def test_freerun_spec_caps_only_on_live_proposal(params):
    """Eligibility alone must NOT cap a capture (the PR 16 fix): a greedy
    spec-eligible slot whose suffix n-gram never recurred would make the
    spec step fall back to a plain decode round anyway, so the capture
    free-runs. Only a history whose n-gram lookup actually PROPOSES
    drafts caps to 1 (and books the "spec" reason)."""
    from types import SimpleNamespace

    sched = _stack(params, freerun=4, spec_tokens=2)

    def handle(history):
        # seq_id/slot keep the teardown leak audit happy (slot=-1 = none)
        return SimpleNamespace(
            constraint=None, seq_id="spec-probe", slot=-1,
            sampling=SamplingParams(temperature=0.0, max_new_tokens=64),
            generated=4, history=list(history), ngram_index=None,
        )

    spec_caps = lambda: METRICS.get(  # noqa: E731 — tiny probe
        "finchat_freerun_capped_total", labels={"reason": "spec"})

    # eligible slot, non-recurring history: no proposal -> full capture
    before = spec_caps()
    sched.decoding = {0: handle([1, 2, 3, 4, 5, 6, 7, 8])}
    assert sched._freerun_rounds_cap() == 4
    assert spec_caps() == before
    # the probe built the index lazily, exactly as the spec step would
    assert sched.decoding[0].ngram_index is not None

    # recurring suffix n-gram: a proposal WOULD fire -> cap to 1 + metric
    sched.decoding = {0: handle([5, 6, 7, 9, 5, 6, 7])}
    assert sched._freerun_rounds_cap() == 1
    assert spec_caps() == before + 1

    # spec disabled entirely: same recurring history free-runs
    sched.spec_k = 0
    assert sched._freerun_rounds_cap() == 4
    sched.decoding = {}


def test_freerun_spec_eligible_no_proposal_byte_identical(params):
    """With spec on and no grammar rows, captures now ENGAGE whenever no
    n-gram proposal is live — the streams must stay byte-identical to the
    host-stepped path across the engage/cap flips (spec verify is
    greedy-exact, so either path is the same stream)."""
    base, _ = _run_workload(params, 1, spec_tokens=2, seed=11)
    fr, win = _run_workload(params, 4, spec_tokens=2, seed=11)
    assert fr == base
