"""Llama forward: shapes, causality, determinism."""

import jax
import jax.numpy as jnp
import pytest

from finchat_tpu.models.llama import PRESETS, forward_full, init_params


@pytest.fixture(scope="module")
def tiny():
    config = PRESETS["tiny"]
    params = init_params(config, jax.random.key(0))
    return config, params


def test_forward_shapes_and_dtype(tiny):
    config, params = tiny
    B, S = 2, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, config.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    logits = forward_full(params, tokens, positions, config=config)
    assert logits.shape == (B, S, config.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_causality(tiny):
    """Perturbing token t must not change logits at positions < t."""
    config, params = tiny
    S = 12
    tokens = jax.random.randint(jax.random.key(2), (1, S), 0, config.vocab_size)
    positions = jnp.arange(S)[None]
    base = forward_full(params, tokens, positions, config=config)
    perturbed = tokens.at[0, 8].set((tokens[0, 8] + 1) % config.vocab_size)
    out = forward_full(params, perturbed, positions, config=config)
    assert jnp.abs(base[0, :8] - out[0, :8]).max() == 0.0
    assert jnp.abs(base[0, 8:] - out[0, 8:]).max() > 0.0


def test_deterministic(tiny):
    config, params = tiny
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    positions = jnp.arange(4)[None]
    a = forward_full(params, tokens, positions, config=config)
    b = forward_full(params, tokens, positions, config=config)
    assert jnp.array_equal(a, b)


def test_presets_sane():
    for name, c in PRESETS.items():
        assert c.dim % c.n_heads == 0, name
        assert c.n_heads % c.n_kv_heads == 0, name
