"""Pod-scale multi-host fleet (ISSUE 20; serve/pod.py, io/journal.py v2,
ROBUSTNESS.md §7).

What must hold:

- journal ownership aligns with PARTITION ownership: per-partition files,
  legacy single-file migration (one-way), seq-stamped lines so multiple
  files interleave by true append order at replay — a rebalance can never
  age a recently answered id out of the ring early (the ISSUE 20 bugfix);
- the liaison frame codec detects every corruption (CRC + length), the
  transport is asyncio-only, peers carry circuit breakers, and the
  ``pod.heartbeat`` / ``pod.transfer`` fault sites are armable;
- a host death is a group rebalance: survivors adopt EXACTLY the dead
  host's partitions, replay exactly those journals into their dedupe
  rings (zero double answers after a host-level kill -9), and a rejoin
  under the old member id restores the exact prior mapping;
- the session wire format (the disk tier's checksummed v2 records)
  crosses hosts: a record exported under {fp32, int8-KV} × {bounded,
  unbounded} imports on a DIFFERENT host's fresh engine with
  byte-identical greedy resume — and a cross-KV-mode record is refused
  and counted, never garbage-decoded;
- pod off (no ``pod.host_id``) or liaison-less single host is
  bit-identical to the plain fleet.
"""

import asyncio
import dataclasses
import json
import socket
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.engine.session_cache import SessionDiskTier
from finchat_tpu.io.journal import AnsweredJournal, partition_filename
from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient, partition_for_key
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.serve import pod as pod_mod
from finchat_tpu.serve.fleet import DedupeRing, EngineFleet, EngineReplica
from finchat_tpu.serve.pod import (
    PEER_DEAD,
    PEER_LIVE,
    PeerChannel,
    PodCoordinator,
    decode_frame,
    encode_frame,
    parse_peers,
)
from finchat_tpu.utils import faults
from finchat_tpu.utils.config import (
    GROUP_ID,
    USER_MESSAGE_TOPIC,
    EngineConfig,
    FleetConfig,
    KafkaConfig,
    PodConfig,
)
from finchat_tpu.utils.metrics import METRICS

CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
PAGE = 8
CHUNK = 16


@pytest.fixture(autouse=True)
def _clean_pod_state():
    yield
    faults.disarm_all()
    pod_mod._INPROC.clear()


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _make_scheduler(params, replica_id="0", kv_quant="", bounded=False):
    cfg = EngineConfig(
        max_seqs=3, page_size=PAGE, num_pages=96, max_seq_len=256,
        prefill_chunk=CHUNK, session_cache=True, kv_quant=kv_quant,
        kv_sink_pages=1 if bounded else 0,
        kv_window_pages=4 if bounded else 0,
    )
    return ContinuousBatchingScheduler(
        InferenceEngine(CONFIG, params, cfg), eos_id=-1, replica_id=replica_id
    )


async def _collect(scheduler, seq_id, prompt_ids, n_new, conversation_id=None):
    handle = await scheduler.submit(
        seq_id, list(prompt_ids),
        SamplingParams(temperature=0.0, max_new_tokens=n_new),
        conversation_id=conversation_id,
    )
    tokens = []
    while True:
        event = await asyncio.wait_for(handle.events.get(), timeout=120)
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return handle, tokens
        else:
            return handle, event


def _pod_record(sched, key):
    """A conversation's session-cache entry as pod-transfer wire bytes
    (the disk tier's serialized record — exactly what the liaison ships)."""
    payload = sched.export_session(key)
    assert payload is not None
    return SessionDiskTier._serialize(
        key, payload["token_ids"], payload["prefix_len"], payload["snap"],
        payload["kv_gap"], payload["kv_sink"],
    )


def _import_record(sched, raw):
    rec = SessionDiskTier._deserialize(raw)
    rec = sched.session_cache.fit_payload(rec)
    return rec is not None and sched.import_session_entry(rec)


# --- per-partition journal plane -------------------------------------------

def test_journal_per_partition_layout_and_inherited_replay(tmp_path):
    """One file per partition; ``replay(partitions=...)`` replays exactly
    the inherited partitions' ids — the adoption contract."""
    j = AnsweredJournal(str(tmp_path), num_partitions=4)
    j.append("a0", partition=0)
    j.append("b0", partition=2)
    j.append("a1", partition=0)
    j.close()
    assert (tmp_path / partition_filename(0)).exists()
    assert (tmp_path / partition_filename(2)).exists()
    assert not (tmp_path / partition_filename(1)).exists()
    assert AnsweredJournal(str(tmp_path)).partitions_on_disk() == [0, 2]
    # inherited-only replay (compact=False: an adopter never rewrites
    # files it is only just inheriting)
    assert AnsweredJournal(str(tmp_path)).replay(
        partitions=[2], compact=False) == ["b0"]
    assert AnsweredJournal(str(tmp_path)).replay(
        partitions=[0], compact=False) == ["a0", "a1"]
    # full replay interleaves by append order across files
    assert AnsweredJournal(str(tmp_path)).replay() == ["a0", "b0", "a1"]


def test_journal_seq_merge_keeps_global_recency(tmp_path):
    """The ISSUE 20 bugfix pin: replay interleaves MULTIPLE partition
    files by the per-line seq stamp. Naive per-file concatenation (p0
    then p1) would order the stale p1 ids AFTER the newer p0 ids and age
    the still-hot ones out of the ``keep`` window early."""
    j = AnsweredJournal(str(tmp_path), num_partitions=4, keep=3)
    j.append("b0", partition=1)  # oldest
    j.append("b1", partition=1)
    j.append("a0", partition=0)  # newest three
    j.append("a1", partition=0)
    j.append("a2", partition=0)
    j.close()
    # true append order keeps the three newest; the naive p0-then-p1
    # concat would have produced ["a2", "b0", "b1"] — dropping hot ids
    # for stale ones
    assert AnsweredJournal(str(tmp_path), keep=3).replay() == ["a0", "a1", "a2"]


def test_journal_seq_survives_restart_and_adoption_order(tmp_path):
    """Seqs stay monotonic across writer restarts, so a restarted host's
    new appends still sort AFTER everything already on disk — adoption
    replay order is append order even through restarts."""
    j1 = AnsweredJournal(str(tmp_path), num_partitions=2)
    j1.append("old", partition=0)
    j1.close()
    j2 = AnsweredJournal(str(tmp_path), num_partitions=2)
    j2.replay()  # seeds the seq counter past everything on disk
    j2.append("new", partition=1)
    j2.close()
    assert AnsweredJournal(str(tmp_path)).replay() == ["old", "new"]


def test_journal_legacy_migration_one_way(tmp_path, caplog):
    """A pre-ISSUE-20 single ``answered.journal`` splits into
    per-partition files on first startup: each id lands on the partition
    the broker's CRC32 partitioner assigns its JSON form (where its
    redelivery will be consumed), order is preserved, the torn tail is
    dropped, and the legacy file is gone — one-way, logged."""
    mids = ["x1", "x2", "x3", 42]
    legacy = tmp_path / AnsweredJournal.FILENAME
    lines = b""
    for mid in mids:
        body = json.dumps(mid).encode()
        lines += b"v1 %08x " % zlib.crc32(body) + body + b"\n"
    legacy.write_bytes(lines + b"v1 deadbe")  # torn final line (crash)
    import logging
    with caplog.at_level(logging.INFO, logger="finchat_tpu.io.journal"):
        j = AnsweredJournal(str(tmp_path), num_partitions=4)
    assert any("migrated legacy" in r.getMessage() for r in caplog.records)
    assert not legacy.exists()
    for mid in mids:
        part = partition_for_key(json.dumps(mid), 4)
        assert (tmp_path / partition_filename(part)).exists()
    # order preserved across the split (seq-merged replay)
    assert j.replay() == mids
    j.close()
    # idempotent: a second startup has nothing to migrate and replays
    # identically
    assert AnsweredJournal(str(tmp_path), num_partitions=4).replay() == mids


def test_journal_migration_appends_land_in_partition_files(tmp_path):
    """Post-migration appends extend the per-partition files (fsync
    contract unchanged), and replay merges migrated + fresh lines in
    append order."""
    body = json.dumps("m-old").encode()
    (tmp_path / AnsweredJournal.FILENAME).write_bytes(
        b"v1 %08x " % zlib.crc32(body) + body + b"\n"
    )
    j = AnsweredJournal(str(tmp_path), num_partitions=4)
    j.append("m-new", partition=1)
    j.close()
    assert AnsweredJournal(str(tmp_path)).replay() == ["m-old", "m-new"]


def test_journal_fsync_before_return_and_relief_valve(tmp_path, monkeypatch):
    """Re-assert the §5 ordering through the per-partition split: append
    fsyncs the PARTITION file before returning (the commit that follows
    observes a durable id), and ``journal.fsync=false`` skips it."""
    import finchat_tpu.io.journal as journal_mod

    real_fsync = journal_mod.os.fsync
    calls = []

    def spy(fd):
        calls.append(fd)
        real_fsync(fd)

    monkeypatch.setattr(journal_mod.os, "fsync", spy)
    j = AnsweredJournal(str(tmp_path), fsync=True, num_partitions=4)
    assert j.append("m1", partition=3) is True
    assert len(calls) == 1  # durably on disk by the time append returned
    assert AnsweredJournal(str(tmp_path)).replay(
        partitions=[3], compact=False) == ["m1"]
    calls.clear()
    j2 = AnsweredJournal(str(tmp_path), fsync=False, num_partitions=4)
    assert j2.append("m2", partition=3) is True
    assert calls == []  # the relief valve really skips fsync
    j.close()
    j2.close()


def test_journal_torn_line_per_partition(tmp_path):
    """A torn tail in ONE partition file quarantines only that line; the
    file's intact records and every other partition still replay."""
    j = AnsweredJournal(str(tmp_path), num_partitions=4)
    j.append("p0-a", partition=0)
    j.append("p1-a", partition=1)
    j.append("p0-b", partition=0)
    j.close()
    with open(tmp_path / partition_filename(0), "ab") as f:
        f.write(b"v2 dead")  # crash mid-append
    q0 = METRICS.get("finchat_durability_quarantines_total")
    assert AnsweredJournal(str(tmp_path)).replay() == ["p0-a", "p1-a", "p0-b"]
    assert METRICS.get("finchat_durability_quarantines_total") == q0 + 1


# --- liaison frame codec and transport -------------------------------------

def test_frame_codec_roundtrip_and_corruption_detection():
    raw = encode_frame("pull_session", {"key": "c#resp"}, b"payload-bytes")
    op, meta, payload = decode_frame(raw)
    assert (op, meta["key"], payload) == ("pull_session", "c#resp",
                                          b"payload-bytes")
    # bit flip in the payload: CRC catches it
    flipped = bytearray(raw)
    flipped[-1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        decode_frame(bytes(flipped))
    # truncation: length prefix catches it
    with pytest.raises(ValueError, match="truncated"):
        decode_frame(raw[:-3])
    # wrong magic / unknown version never misparse
    with pytest.raises(ValueError, match="magic"):
        decode_frame(b"XPOD" + raw[4:])
    with pytest.raises(ValueError, match="version"):
        decode_frame(raw[:4] + bytes([99]) + raw[5:])


def test_parse_peers_validates_loudly():
    assert parse_peers("b=tcp:127.0.0.1:9710, c=inproc:hostC") == {
        "b": "tcp:127.0.0.1:9710", "c": "inproc:hostC",
    }
    assert parse_peers("") == {}
    with pytest.raises(ValueError):
        parse_peers("no-address-here")
    with pytest.raises(ValueError):
        parse_peers("b=udp:127.0.0.1:1")


def _pod_cfg(host, listen="", peers="", **kw):
    defaults = dict(heartbeat_interval_seconds=60.0,
                    heartbeat_miss_threshold=2,
                    transfer_timeout_seconds=1.0, transfer_retries=1,
                    retry_backoff_seconds=0.0, breaker_threshold=3,
                    breaker_cooldown_seconds=0.05)
    defaults.update(kw)
    return PodConfig(host_id=host, listen=listen, peers=peers, **defaults)


async def test_inproc_liaison_ping_pull_miss_and_kill():
    coord_a = PodCoordinator(_pod_cfg("hostA", listen="inproc:hostA"))
    await coord_a.start()
    coord_b = PodCoordinator(_pod_cfg("hostB", peers="hostA=inproc:hostA"))
    try:
        peer = coord_b.peers["hostA"]
        op, meta, _ = await coord_b.liaison.call(peer.addr, "ping", {})
        assert op == "pong" and meta["host_id"] == "hostA"
        # no fleet on hostA: every pull is an honest miss
        op, _, _ = await coord_b.liaison.call(
            peer.addr, "pull_session", {"key": "nope"})
        assert op == "miss"
        # unknown ops answer an error frame, never crash the server
        op, meta, _ = await coord_b.liaison.call(peer.addr, "bogus", {})
        assert op == "error" and "bogus" in meta["message"]
        # kill -9: drops off the wire, dials fail from then on
        coord_a.kill()
        with pytest.raises(ConnectionError):
            await coord_b.liaison.call(peer.addr, "ping", {})
    finally:
        coord_a.kill()
        await coord_b.stop()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def test_tcp_liaison_roundtrip_and_refused_dial():
    port = _free_port()
    coord_a = PodCoordinator(_pod_cfg("hostA", listen=f"tcp:127.0.0.1:{port}"))
    await coord_a.start()
    coord_b = PodCoordinator(
        _pod_cfg("hostB", peers=f"hostA=tcp:127.0.0.1:{port}"))
    try:
        peer = coord_b.peers["hostA"]
        op, meta, _ = await coord_b.liaison.call(
            peer.addr, "ping", {}, timeout=2.0)
        assert op == "pong" and meta["host_id"] == "hostA"
        coord_a.kill()
        with pytest.raises((ConnectionError, OSError, asyncio.TimeoutError)):
            await coord_b.liaison.call(peer.addr, "ping", {}, timeout=0.5)
    finally:
        coord_a.kill()
        await coord_b.stop()


def test_breaker_opens_at_threshold_and_half_open_probe():
    cfg = _pod_cfg("hostB", breaker_threshold=2,
                   breaker_cooldown_seconds=3600.0)
    peer = PeerChannel("hostA", "inproc:hostA", cfg)
    trips0 = METRICS.get("finchat_pod_breaker_trips_total")
    assert peer.breaker_allows()
    peer.record_failure()
    assert peer.breaker_allows()  # below threshold
    peer.record_failure()
    assert not peer.breaker_allows()  # open
    assert METRICS.get("finchat_pod_breaker_trips_total") == trips0 + 1
    peer.record_failure()  # further failures do not re-count the trip
    assert METRICS.get("finchat_pod_breaker_trips_total") == trips0 + 1
    # cooldown elapsed -> the half-open probe rides through; success closes
    peer._open_until = 0.0
    assert peer.breaker_allows()
    peer.record_success()
    assert peer.breaker_allows()


async def test_heartbeat_fault_site_death_and_rejoin():
    """``pod.heartbeat`` is armable; miss_threshold consecutive failures
    declare the peer dead (counted + anomaly), and a later pong rejoins
    it."""
    coord_a = PodCoordinator(_pod_cfg("hostA", listen="inproc:hostA"))
    await coord_a.start()
    coord_b = PodCoordinator(_pod_cfg("hostB", peers="hostA=inproc:hostA"))
    peer = coord_b.peers["hostA"]
    try:
        hb0 = METRICS.get("finchat_pod_heartbeats_total")
        await coord_b._heartbeat(peer)
        assert METRICS.get("finchat_pod_heartbeats_total") == hb0 + 1
        assert peer.state == PEER_LIVE and peer.misses == 0

        deaths0 = METRICS.get("finchat_pod_peer_deaths_total")
        fails0 = METRICS.get("finchat_pod_heartbeat_failures_total")
        faults.arm("pod.heartbeat", faults.n_shot(2, RuntimeError("cable cut")))
        await coord_b._heartbeat(peer)
        assert peer.state == PEER_LIVE and peer.misses == 1
        await coord_b._heartbeat(peer)  # second miss = threshold
        assert peer.state == PEER_DEAD
        assert METRICS.get("finchat_pod_peer_deaths_total") == deaths0 + 1
        assert METRICS.get("finchat_pod_heartbeat_failures_total") == fails0 + 2
        assert METRICS.get("finchat_pod_hosts_live") == 1.0

        rejoin0 = METRICS.get("finchat_pod_peer_rejoins_total")
        await coord_b._heartbeat(peer)  # fault exhausted: pong again
        assert peer.state == PEER_LIVE
        assert METRICS.get("finchat_pod_peer_rejoins_total") == rejoin0 + 1
        assert METRICS.get("finchat_pod_hosts_live") == 2.0
    finally:
        coord_a.kill()
        await coord_b.stop()


# --- host death: partition adoption + exactly-once dedupe ------------------

async def test_host_death_adoption_replays_inherited_journals_exactly(tmp_path):
    """The tentpole drill at the coordinator level: hostA dies (kill -9
    of its liaison), hostB's detector declares it dead, evicts its group
    member, adopts EXACTLY hostA's partitions, and replays EXACTLY those
    per-partition journals into its dedupe ring — so a redelivered
    answered id is refused on the adopter: zero double answers. A rejoin
    under the old member id restores the exact prior mapping."""
    broker = InMemoryBroker(num_partitions=8)
    ka = KafkaClient(KafkaConfig(num_partitions=8), broker=broker)
    kb = KafkaClient(KafkaConfig(num_partitions=8), broker=broker)
    ka.setup_consumer([USER_MESSAGE_TOPIC])
    kb.setup_consumer([USER_MESSAGE_TOPIC])
    parts_a = {p for _t, p in ka.assignment()}
    parts_b = {p for _t, p in kb.assignment()}
    assert parts_a and parts_b and parts_a.isdisjoint(parts_b)
    assert parts_a | parts_b == set(range(8))

    # hostA answers one message per owned partition (shared journal dir —
    # in a real pod this is the shared disk fabric)
    ja = AnsweredJournal(str(tmp_path), num_partitions=8)
    for p in sorted(parts_a):
        ja.append(f"mid-a{p}", partition=p)
    ja.close()

    coord_a = PodCoordinator(
        _pod_cfg("hostA", listen="inproc:hostA"), kafka=ka)
    await coord_a.start()
    ring_b = DedupeRing(size=64)
    jb = AnsweredJournal(str(tmp_path), num_partitions=8)
    coord_b = PodCoordinator(
        _pod_cfg("hostB", peers="hostA=inproc:hostA"),
        kafka=kb, journal=jb, dedupe=ring_b,
    )
    await coord_b.start()
    peer = coord_b.peers["hostA"]
    try:
        await coord_b._heartbeat(peer)  # learns hostA's member id
        assert peer.member_id == ka.member_id

        adopt0 = METRICS.get("finchat_pod_partition_adoptions_total")
        replayed0 = METRICS.get("finchat_pod_adopted_ids_replayed_total")
        coord_a.kill()  # kill -9: no drain, no goodbye
        await coord_b._heartbeat(peer)
        await coord_b._heartbeat(peer)  # threshold reached
        assert peer.state == PEER_DEAD

        # the rebalance moved ONLY the dead host's share onto hostB
        assert {p for _t, p in kb.assignment()} == parts_a | parts_b
        assert METRICS.get(
            "finchat_pod_partition_adoptions_total") == adopt0 + len(parts_a)
        assert METRICS.get("finchat_pod_adopted_ids_replayed_total") == (
            replayed0 + len(parts_a))
        assert coord_b._pull_partitions >= parts_a
        # every inherited answered id is in the adopter's ring: the
        # redelivery after the uncommitted-offset rewind dedupes — zero
        # double answers across the host kill
        for p in parts_a:
            assert f"mid-a{p}" in ring_b._ids
        # ids hostA never journaled ARE processed (no over-dedupe)
        assert f"mid-never" not in ring_b._ids

        # hostA rejoins under its old member id: exact mapping restored
        ka.setup_consumer([USER_MESSAGE_TOPIC])
        coord_a2 = PodCoordinator(
            _pod_cfg("hostA", listen="inproc:hostA"), kafka=ka)
        await coord_a2.start()
        await coord_b._heartbeat(peer)
        assert peer.state == PEER_LIVE
        assert {p for _t, p in ka.assignment()} == parts_a
        assert {p for _t, p in kb.assignment()} == parts_b
        coord_a2.kill()
    finally:
        coord_a.kill()
        await coord_b.stop()
        jb.close()


# --- cross-host session transfer: wire-format compat matrix ----------------

@pytest.mark.parametrize("kv_quant", ["", "int8"])
@pytest.mark.parametrize("bounded", [False, True])
def test_wire_format_cross_host_compat_matrix(params, kv_quant, bounded):
    """v2 session records exported under {fp32, int8-KV} × {bounded,
    unbounded} import on a DIFFERENT host (fresh engine, different
    replica id) with byte-identical greedy resume vs the uninterrupted
    original."""
    t1 = list(range(1, 29)) if bounded else list(range(1, 14))
    n1 = 20 if bounded else 8  # bounded: long enough to open a KV gap

    async def run():
        sched_a = _make_scheduler(params, "hostA-0", kv_quant, bounded)
        await sched_a.start()
        _, toks1 = await _collect(sched_a, "a-t1", t1, n1,
                                  conversation_id="convM")
        raw = _pod_record(sched_a, "convM")  # exported BEFORE turn 2
        if bounded:
            # the bound must have evicted pages: the record carries a gap
            assert SessionDiskTier._deserialize(raw)["kv_gap"] > 0
        t2 = t1 + toks1 + [7, 8, 9]
        h_ref, toks2_ref = await _collect(sched_a, "a-t2", t2, 8,
                                          conversation_id="convM")
        await sched_a.stop()

        sched_b = _make_scheduler(params, "hostB-0", kv_quant, bounded)
        await sched_b.start()
        assert _import_record(sched_b, raw)
        h_mig, toks2_mig = await _collect(sched_b, "b-t2", t2, 8,
                                          conversation_id="convM")
        await sched_b.stop()
        assert h_mig.resumed_len == h_ref.resumed_len > 0
        assert toks2_mig == toks2_ref  # byte-identical resume
        sched_b.allocator.check_invariants()

    asyncio.run(run())


def test_cross_mode_record_refused_and_counted(params):
    """An fp32-KV record arriving on an int8-KV host is refused and
    counted (never value-cast into garbage KV) — the conversation cold
    starts with the golden output."""
    t1 = list(range(1, 14))

    async def run():
        sched_a = _make_scheduler(params, "hostA-0", kv_quant="")
        await sched_a.start()
        _, toks1 = await _collect(sched_a, "a-t1", t1, 8,
                                  conversation_id="convX")
        raw = _pod_record(sched_a, "convX")
        await sched_a.stop()

        sched_b = _make_scheduler(params, "hostB-0", kv_quant="int8")
        await sched_b.start()
        refuse0 = METRICS.get("finchat_quant_dequant_fallbacks_total")
        assert not _import_record(sched_b, raw)
        assert METRICS.get(
            "finchat_quant_dequant_fallbacks_total") == refuse0 + 1
        assert sched_b.session_cache.get("convX") is None
        # cold start still answers (golden int8 output, no stale KV)
        t2 = t1 + toks1 + [7, 8, 9]
        h, _ = await _collect(sched_b, "b-t2", t2, 8, conversation_id="convX")
        assert h.resumed_len == 0
        await sched_b.stop()

    asyncio.run(run())


# --- cross-host migration through the liaison ------------------------------

def _single_replica_fleet(sched):
    return EngineFleet([EngineReplica(replica_id=sched.replica_id,
                                      scheduler=sched)],
                       FleetConfig(replicas=1), num_partitions=8)


def test_pod_session_pull_end_to_end(params):
    """The full tentpole path: hostB's scheduler submit pulls the
    conversation's newest record from hostA over the liaison (deepest
    RAM entry, serialized v2 record, CRC checked), imports it through
    ``import_session_entry``, and resumes byte-identically; misses,
    corrupt transfers, and armed ``pod.transfer`` faults all degrade to
    counted cold starts — never a user error."""
    t1 = list(range(1, 14))

    async def run():
        sched_a = _make_scheduler(params, "hostA-0")
        await sched_a.start()
        coord_a = PodCoordinator(_pod_cfg("hostA", listen="inproc:hostA"),
                                 fleet=_single_replica_fleet(sched_a))
        await coord_a.start()

        sched_b = _make_scheduler(params, "hostB-0")
        await sched_b.start()
        coord_b = PodCoordinator(
            _pod_cfg("hostB", peers="hostA=inproc:hostA"))
        sched_b.pod = coord_b
        try:
            _, toks1 = await _collect(sched_a, "a-t1", t1, 8,
                                      conversation_id="convP")
            t2 = t1 + toks1 + [7, 8, 9]
            pulls0 = METRICS.get("finchat_pod_session_pulls_total")
            h_mig, toks2_mig = await _collect(sched_b, "b-t2", t2, 8,
                                              conversation_id="convP")
            assert METRICS.get(
                "finchat_pod_session_pulls_total") == pulls0 + 1
            assert h_mig.resumed_len > 0  # resumed warm, not cold
            # reference: the uninterrupted turn 2 on hostA
            _, toks2_ref = await _collect(sched_a, "a-t2", t2, 8,
                                          conversation_id="convP")
            assert toks2_mig == toks2_ref  # migrated stream byte-identical

            # one liaison round per conversation: a second unknown key is
            # a counted miss, and is never re-pulled on the next turn
            miss0 = METRICS.get("finchat_pod_pull_misses_total")
            await _collect(sched_b, "b-u1", t1, 4, conversation_id="convU")
            assert METRICS.get("finchat_pod_pull_misses_total") == miss0 + 1
            await _collect(sched_b, "b-u2", t1 + [9], 4,
                           conversation_id="convU")
            assert METRICS.get("finchat_pod_pull_misses_total") == miss0 + 1

            # corrupt transfer: counted cold start, stream still answers
            async def corrupt_export(key):
                return b"garbage-not-a-record"
            coord_a.export_record = corrupt_export
            cold0 = METRICS.get("finchat_pod_cold_starts_total",
                                {"reason": "transfer_corrupt"})
            h_c, _ = await _collect(sched_b, "b-c1", t1, 4,
                                    conversation_id="convC")
            assert METRICS.get("finchat_pod_cold_starts_total",
                               {"reason": "transfer_corrupt"}) == cold0 + 1
            assert h_c.resumed_len == 0

            # armed pod.transfer fault: retries exhaust, counted cold
            # start, stream still answers
            faults.arm("pod.transfer", faults.n_shot(8, RuntimeError("net")))
            unreach0 = METRICS.get("finchat_pod_cold_starts_total",
                                   {"reason": "peer_unreachable"})
            h_f, _ = await _collect(sched_b, "b-f1", t1, 4,
                                    conversation_id="convF")
            assert METRICS.get("finchat_pod_cold_starts_total",
                               {"reason": "peer_unreachable"}) == unreach0 + 1
            assert h_f.resumed_len == 0
        finally:
            coord_a.kill()
            await coord_b.stop()
            await sched_a.stop()
            await sched_b.stop()
            sched_a.allocator.check_invariants()
            sched_b.allocator.check_invariants()

    asyncio.run(run())


# --- graceful degradation: pod off == plain fleet --------------------------

def test_single_host_no_liaison_is_bit_identical(params):
    """The regression pin: a scheduler with the pod plane off, and one
    with a peer-less coordinator attached, produce byte-identical greedy
    streams — single-host pods cost nothing."""

    async def run():
        sched = _make_scheduler(params, "solo-0")
        await sched.start()
        t1 = list(range(1, 14))
        pulls0 = METRICS.get("finchat_pod_session_pulls_total")
        misses0 = METRICS.get("finchat_pod_pull_misses_total")
        assert sched.pod is None  # default: plane off
        _, toks_off = await _collect(sched, "s-1", t1, 8,
                                     conversation_id="solo1")
        # liaison-less single-host pod: maybe_pull returns before any I/O
        sched.pod = PodCoordinator(_pod_cfg("solo"))
        _, toks_pod = await _collect(sched, "s-2", t1, 8,
                                     conversation_id="solo2")
        await sched.stop()
        assert toks_pod == toks_off
        # and the peer-less pull path never touched the liaison counters
        assert METRICS.get("finchat_pod_session_pulls_total") == pulls0
        assert METRICS.get("finchat_pod_pull_misses_total") == misses0

    asyncio.run(run())


def test_pod_off_in_app_config_builds_no_coordinator(tmp_path):
    """``pod.host_id`` empty (the default) never constructs the pod
    plane: the App is structurally the PR 17 fleet."""
    from finchat_tpu.engine.generator import StubGenerator
    from finchat_tpu.io.store import InMemoryStore
    from finchat_tpu.serve.app import build_app
    from finchat_tpu.utils.config import load_config

    cfg = load_config(overrides={"model.preset": "stub"})
    assert cfg.pod.host_id == ""
    app = build_app(
        cfg, store=InMemoryStore(),
        kafka=KafkaClient(cfg.kafka, broker=InMemoryBroker()),
        tool_generator=StubGenerator(default="No tool call"),
        response_generator=StubGenerator(default="fine"),
    )
    assert app.pod is None
    for sched in app._all_schedulers():
        assert getattr(sched, "pod", None) is None
