"""Fused dequant-matmul kernels (ops/quant_matmul.py) correctness.

Three contracts, mirroring the attention-kernel test discipline:

1. ``quant_matmul_ref`` is BITWISE the historical inline-dequant math —
   literally ``x @ dequantize(w, x.dtype)`` (or the
   ``preferred_element_type`` einsum at the lm_head site). The reference
   is the CPU/tier-1 serving path, so routing every QTensor/Q4Tensor
   matmul site through the dispatcher must not change a single stream
   byte; this file pins the identity at the op level and the whole-model
   level (tests/test_quant.py + bench --quantmatmul-smoke pin streams).
2. Interpret-mode kernel-vs-ref parity across the layout matrix:
   int8/int4 x per-channel/per-group x aligned/ragged shapes. The kernel
   tiles K and accumulates fp32, so parity is allclose (tile-order
   summation), not bitwise — same contract as the flash kernels.
3. The kernel honors parallel/sharding.py's packed-K layout: a K-sharded
   shard_map over the forced 8-device CPU mesh (conftest) feeds each
   device its LOCAL packed shard (nibble pairs never split — byte rows
   shard as units) and the psum of per-shard fused matmuls matches the
   unsharded reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from finchat_tpu.models.quant import (
    dense,
    dequantize,
    quantize,
    quantize_int4,
)
from finchat_tpu.ops.dispatch import quant_matmul, quant_matmul_backend
from finchat_tpu.ops.quant_matmul import (
    quant_matmul_int4,
    quant_matmul_int8,
    quant_matmul_ref,
)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype)


# --- 1. the reference IS the inline-dequant serving math (bitwise) -------

@pytest.mark.parametrize("mode", ["int8", "int4-pc", "int4-pg"])
def test_ref_is_inline_dequant_bitwise(mode):
    x = _rand(0, (4, 64))
    w = _rand(1, (64, 32))
    if mode == "int8":
        qt = quantize(w)
    else:
        qt = quantize_int4(w, group_size=64 if mode == "int4-pc" else 16)
    got = quant_matmul_ref(x, qt)
    want = x @ dequantize(qt, x.dtype)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # the lm_head site: fp32-accumulating einsum, also bitwise
    got32 = quant_matmul_ref(x, qt, preferred_element_type=jnp.float32)
    want32 = jnp.einsum("...k,kn->...n", x, dequantize(qt, x.dtype),
                        preferred_element_type=jnp.float32)
    np.testing.assert_array_equal(np.asarray(got32), np.asarray(want32))


def test_dense_routes_through_dispatcher_ref_bitwise():
    """models/quant.dense — THE matmul entry every decoder/encoder site
    uses — must stay bitwise the historical ``x @ dequantize(w)`` on the
    reference backend (the tier-1 path)."""
    x = _rand(2, (3, 48))
    for qt in (quantize(_rand(3, (48, 24))),
               quantize_int4(_rand(4, (48, 24)), group_size=16)):
        got = dense(x, qt, qm_backend="ref")
        want = x @ dequantize(qt, x.dtype)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_backend_resolution_validates():
    import os

    assert quant_matmul_backend() in ("pallas", "ref", "pallas-interpret")
    os.environ["FINCHAT_QUANT_MATMUL"] = "bogus"
    try:
        with pytest.raises(ValueError):
            quant_matmul_backend()
    finally:
        del os.environ["FINCHAT_QUANT_MATMUL"]


def test_stacked_weight_falls_back_to_ref():
    """MoE expert leaves are stacked [E, K, N]; the dispatcher must route
    them to the reference (no fused kernel for 3-D weights) and count the
    fallback."""
    from finchat_tpu.utils.metrics import METRICS

    x = _rand(5, (2, 16))
    qt = quantize(_rand(6, (3, 16, 8)))  # stacked leaf
    before = METRICS.get("finchat_quantmatmul_fallbacks_total")
    # stacked weight: the dispatcher falls back to the inline-dequant
    # reference (same math the MoE expert einsums run) and counts it
    out = quant_matmul(x, qt, backend="pallas-interpret")
    np.testing.assert_array_equal(
        np.asarray(out),
        np.asarray(x @ dequantize(qt, x.dtype)))
    after = METRICS.get("finchat_quantmatmul_fallbacks_total")
    assert after == before + 1


# --- 2. interpret-mode kernel-vs-ref parity matrix -----------------------

PARITY_CASES = [
    # (name, M, K, N, quant, group)
    ("int8-aligned", 16, 256, 256, "int8", None),
    ("int8-ragged", 7, 130, 96, "int8", None),
    ("int4-per-channel-aligned", 16, 256, 128, "int4", 256),
    ("int4-per-channel-ragged", 5, 96, 80, "int4", 96),
    ("int4-per-group-aligned", 8, 256, 128, "int4", 64),
    ("int4-per-group-ragged", 5, 192, 80, "int4", 32),
]


@pytest.mark.parametrize("name,M,K,N,mode,group",
                         PARITY_CASES, ids=[c[0] for c in PARITY_CASES])
def test_kernel_matches_ref_interpret(name, M, K, N, mode, group):
    x = _rand(10, (M, K))
    w = _rand(11, (K, N))
    if mode == "int8":
        qt = quantize(w)
        out = quant_matmul_int8(x, qt.q, qt.scale, interpret=True)
    else:
        qt = quantize_int4(w, group_size=group)
        out = quant_matmul_int4(x, qt.q, qt.scale, interpret=True)
    ref = quant_matmul_ref(x, qt)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_kernel_bf16_activations_and_leading_dims(mode):
    """bf16 activations (the serving dtype) through the kernel, with a
    leading batch dim (the [B, S, D] encoder/decoder shape)."""
    x = _rand(12, (2, 5, 128), jnp.bfloat16)
    w = _rand(13, (128, 64))
    if mode == "int8":
        qt = quantize(w)
        out = quant_matmul_int8(x, qt.q, qt.scale, interpret=True)
    else:
        qt = quantize_int4(w, group_size=32)
        out = quant_matmul_int4(x, qt.q, qt.scale, interpret=True)
    assert out.shape == (2, 5, 64) and out.dtype == jnp.bfloat16
    ref = quant_matmul_ref(x, qt)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


def test_kernel_out_dtype_fp32_head():
    """The lm_head site: fused kernel accumulates fp32 and can emit fp32
    logits directly (preferred_element_type through the dispatcher)."""
    x = _rand(14, (4, 64), jnp.bfloat16)
    qt = quantize(_rand(15, (64, 32)))
    out = quant_matmul(x, qt, backend="pallas-interpret",
                       preferred_element_type=jnp.float32)
    assert out.dtype == jnp.float32
    ref = quant_matmul_ref(x, qt, preferred_element_type=jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_quantized_forward_fused_tracks_ref():
    """Whole-model check: every QTensor site (attention projections, MLP,
    lm_head) routed through the interpret-mode kernel tracks the
    inline-dequant forward within kernel-parity tolerance."""
    from finchat_tpu.models.llama import LlamaConfig, forward_full, init_params
    from finchat_tpu.models.quant import quantize_llama_params

    config = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                         n_kv_heads=4, hidden_dim=64, max_seq_len=32)
    params = quantize_llama_params(init_params(config, jax.random.key(0)))
    tokens = jax.random.randint(jax.random.key(1), (1, 8), 1, 64)
    positions = jnp.arange(8)[None]
    ref = forward_full(params, tokens, positions, config=config,
                       attn_backend="ref", qm_backend="ref")
    fused = forward_full(params, tokens, positions, config=config,
                         attn_backend="ref", qm_backend="pallas-interpret")
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


# --- 3. packed-K sharding: the kernel honors the local-shard layout ------

def test_tp_sharded_int8_kernel_matches_unsharded():
    """K-sharded int8 matmul over the forced 8-device mesh: each device
    runs the fused kernel on its LOCAL [K/8, N] shard (per-output-column
    scale replicated) and the psum matches the unsharded reference."""
    from jax.experimental.shard_map import shard_map
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    M, K, N = 8, 512, 64
    x = _rand(20, (M, K))
    qt = quantize(_rand(21, (K, N)))

    def local(x_l, q_l, s_l):
        out = quant_matmul_int8(x_l, q_l, s_l, interpret=True)
        return jax.lax.psum(out, "model")

    f = shard_map(local, mesh=mesh,
                  in_specs=(P(None, "model"), P("model", None), P(None)),
                  out_specs=P(None, None), check_rep=False)
    got = f(x, qt.q, qt.scale)
    ref = quant_matmul_ref(x, qt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_tp_sharded_int4_packed_shards_as_bytes():
    """Packed int4 K-sharding (parallel/sharding.py spec): the packed
    [K//2, N] byte rows shard as UNITS (a nibble pair never splits across
    devices) and per-group scales shard with their groups — each device's
    fused kernel sees a self-consistent local shard."""
    from jax.experimental.shard_map import shard_map
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    M, K, N, g = 8, 512, 64, 64  # 8 shards x one group each
    x = _rand(22, (M, K))
    qt = quantize_int4(_rand(23, (K, N)), group_size=g)
    assert qt.q.shape == (K // 2, N) and qt.scale.shape == (K // g, N)

    def local(x_l, q_l, s_l):
        out = quant_matmul_int4(x_l, q_l, s_l, interpret=True)
        return jax.lax.psum(out, "model")

    f = shard_map(local, mesh=mesh,
                  in_specs=(P(None, "model"), P("model", None),
                            P("model", None)),
                  out_specs=P(None, None), check_rep=False)
    got = f(x, qt.q, qt.scale)
    ref = quant_matmul_ref(x, qt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_q4_slice_out_cols_roundtrip():
    """tp_overlap chunks a quantized weight along OUTPUT columns without
    unpacking: slicing then dequantizing == dequantizing then slicing."""
    from finchat_tpu.ops.tp_overlap import _slice_out_cols

    qt = quantize_int4(_rand(24, (64, 32)), group_size=16)
    full = dequantize(qt, jnp.float32)
    for start, size in ((0, 8), (8, 16), (24, 8)):
        part = dequantize(_slice_out_cols(qt, start, size), jnp.float32)
        np.testing.assert_array_equal(np.asarray(part),
                                      np.asarray(full[:, start:start + size]))
    q8 = quantize(_rand(25, (64, 32)))
    full8 = dequantize(q8, jnp.float32)
    part8 = dequantize(_slice_out_cols(q8, 8, 16), jnp.float32)
    np.testing.assert_array_equal(np.asarray(part8),
                                  np.asarray(full8[:, 8:24]))
