"""Sampler: greedy, temperature, top-k, top-p semantics."""

import jax
import jax.numpy as jnp

from finchat_tpu.engine.sampler import sample


def _logits(rows):
    return jnp.asarray(rows, jnp.float32)


def test_greedy_when_temperature_zero():
    logits = _logits([[0.1, 5.0, 0.2, 0.3], [9.0, 0.0, 0.0, 0.0]])
    out = sample(logits, jax.random.key(0), jnp.zeros(2), jnp.ones(2), jnp.zeros(2, jnp.int32))
    assert out.tolist() == [1, 0]


def test_top_k_restricts_support():
    logits = _logits([[10.0, 9.0, -50.0, -50.0]])
    for seed in range(20):
        out = sample(logits, jax.random.key(seed), jnp.ones(1) * 5.0, jnp.ones(1), jnp.asarray([2], jnp.int32))
        assert int(out[0]) in (0, 1)


def test_top_p_restricts_support():
    # token 0 has ~98% mass; top_p=0.5 keeps only it
    logits = _logits([[10.0, 6.0, 5.0, 1.0]])
    for seed in range(20):
        out = sample(logits, jax.random.key(seed), jnp.ones(1), jnp.asarray([0.5]), jnp.zeros(1, jnp.int32))
        assert int(out[0]) == 0


def test_mixed_batch_greedy_and_sampled():
    logits = _logits([[0.0, 8.0, 0.0], [3.0, 3.0, 3.0]])
    out = sample(
        logits, jax.random.key(3),
        jnp.asarray([0.0, 1.0]), jnp.ones(2), jnp.zeros(2, jnp.int32),
    )
    assert int(out[0]) == 1
    assert 0 <= int(out[1]) < 3


def test_full_categorical_fast_path_is_not_truncated():
    """With no truncating slot (top_k=0, top_p=1) sampling is an exact
    full-vocab categorical: tokens OUTSIDE the candidate set must be
    reachable (candidates=2 here, uniform logits over 4 tokens)."""
    logits = _logits([[1.0, 1.0, 1.0, 1.0]])
    seen = set()
    for seed in range(80):
        out = sample(logits, jax.random.key(seed), jnp.ones(1), jnp.ones(1),
                     jnp.zeros(1, jnp.int32), candidates=2)
        seen.add(int(out[0]))
    assert seen == {0, 1, 2, 3}


def test_truncating_slot_forces_candidate_path():
    """One truncating slot in the batch routes the WHOLE batch through the
    candidate-set path: with candidates=2, the uniform slot can then only
    ever draw from its top-2 candidates."""
    logits = _logits([[10.0, 9.0, -50.0, -50.0], [1.0, 1.0, 1.0, 1.0]])
    for seed in range(40):
        out = sample(
            logits, jax.random.key(seed), jnp.ones(2) * 2.0, jnp.ones(2),
            jnp.asarray([1, 0], jnp.int32), candidates=2,
        )
        assert int(out[0]) == 0  # top_k=1 keeps only the argmax
        assert int(out[1]) in (0, 1)  # truncated to the candidate set


def test_sampled_distribution_roughly_matches():
    logits = _logits([[2.0, 1.0, 0.0]])
    counts = [0, 0, 0]
    for seed in range(300):
        out = sample(logits, jax.random.key(seed), jnp.ones(1), jnp.ones(1), jnp.zeros(1, jnp.int32))
        counts[int(out[0])] += 1
    assert counts[0] > counts[1] > counts[2] > 0
