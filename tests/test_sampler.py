"""Sampler: greedy, temperature, top-k, top-p semantics."""

import jax
import jax.numpy as jnp

from finchat_tpu.engine.sampler import sample


def _logits(rows):
    return jnp.asarray(rows, jnp.float32)


def test_greedy_when_temperature_zero():
    logits = _logits([[0.1, 5.0, 0.2, 0.3], [9.0, 0.0, 0.0, 0.0]])
    out = sample(logits, jax.random.key(0), jnp.zeros(2), jnp.ones(2), jnp.zeros(2, jnp.int32))
    assert out.tolist() == [1, 0]


def test_top_k_restricts_support():
    # 20 independent draws in ONE call: the gumbel noise is drawn [B, V]
    # from the key, so replicated rows are iid draws (batching keeps this
    # statistical test off the suite's critical path)
    logits = _logits([[10.0, 9.0, -50.0, -50.0]] * 20)
    out = sample(logits, jax.random.key(0), jnp.ones(20) * 5.0,
                 jnp.ones(20), jnp.full((20,), 2, jnp.int32))
    assert all(int(t) in (0, 1) for t in out)


def test_top_p_restricts_support():
    # token 0 has ~98% mass; top_p=0.5 keeps only it (20 iid rows)
    logits = _logits([[10.0, 6.0, 5.0, 1.0]] * 20)
    out = sample(logits, jax.random.key(0), jnp.ones(20),
                 jnp.full((20,), 0.5), jnp.zeros(20, jnp.int32))
    assert all(int(t) == 0 for t in out)


def test_mixed_batch_greedy_and_sampled():
    logits = _logits([[0.0, 8.0, 0.0], [3.0, 3.0, 3.0]])
    out = sample(
        logits, jax.random.key(3),
        jnp.asarray([0.0, 1.0]), jnp.ones(2), jnp.zeros(2, jnp.int32),
    )
    assert int(out[0]) == 1
    assert 0 <= int(out[1]) < 3


def test_full_categorical_fast_path_is_not_truncated():
    """With no truncating slot (top_k=0, top_p=1) sampling is an exact
    full-vocab categorical: tokens OUTSIDE the candidate set must be
    reachable (candidates=2 here, uniform logits over 4 tokens)."""
    logits = _logits([[1.0, 1.0, 1.0, 1.0]] * 80)  # 80 iid rows, one call
    out = sample(logits, jax.random.key(0), jnp.ones(80), jnp.ones(80),
                 jnp.zeros(80, jnp.int32), candidates=2)
    assert set(out.tolist()) == {0, 1, 2, 3}


def test_truncating_slot_forces_candidate_path():
    """One truncating slot in the batch routes the WHOLE batch through the
    candidate-set path: with candidates=2, the uniform slot can then only
    ever draw from its top-2 candidates."""
    # 40 (truncating, uniform) pairs interleaved as 80 iid rows, one call
    logits = _logits([[10.0, 9.0, -50.0, -50.0], [1.0, 1.0, 1.0, 1.0]] * 40)
    out = sample(
        logits, jax.random.key(0), jnp.ones(80) * 2.0, jnp.ones(80),
        jnp.asarray([1, 0] * 40, jnp.int32), candidates=2,
    )
    toks = out.tolist()
    assert all(t == 0 for t in toks[0::2])  # top_k=1 keeps only the argmax
    assert all(t in (0, 1) for t in toks[1::2])  # truncated to candidates


def test_sampled_distribution_roughly_matches():
    # 3 keys × 100 replicated rows = 300 iid draws in 3 calls (per-row
    # gumbel noise makes replicated rows independent draws)
    counts = [0, 0, 0]
    for seed in range(3):
        logits = _logits([[2.0, 1.0, 0.0]] * 100)
        out = sample(logits, jax.random.key(seed), jnp.ones(100),
                     jnp.ones(100), jnp.zeros(100, jnp.int32))
        for t in out.tolist():
            counts[t] += 1
    assert counts[0] > counts[1] > counts[2] > 0
