"""Ragged paged attention kernel vs its jax.lax reference oracle (ISSUE 10).

Runs the Pallas kernel in interpret mode on the CPU test mesh (the same
matrix runs on-chip under FINCHAT_TESTS_TPU=1 — the kernel joins the
PARITY.md on-chip matrix at both cache dtypes). The reference itself is
pinned against per-row ``mha_reference`` over dense gathered KV, which is
what makes it the fp32 byte-identity anchor for the ragged mixed step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.kv_cache import (
    gather_kv,
    scatter_kv_chunk,
    scatter_kv_chunk_q8,
)
from finchat_tpu.ops.ragged_paged_attention import (
    ragged_flash_attention,
    ragged_flash_attention_q8,
    ragged_paged_attention_ref,
)
from finchat_tpu.ops.refs import mha_reference

INTERPRET = jax.default_backend() != "tpu"
ATOL = RTOL = 2e-5 if INTERPRET else 2e-2

L, PS, Hkv, H, D = 2, 8, 2, 4, 16
LAYER = 1


def _build(rows, *, max_pages, num_pages=64, seed=0, quant=False):
    """Build a paged cache + packed descriptors from ``rows`` =
    [(q_len, pos0, kv_len)] — row r's q tokens sit at absolute positions
    [pos0, pos0+q_len) and its pages hold KV for positions [0, kv_len).
    Returns (q [T,H,D], pages..., page_table, tok_row, tok_pos, kv_len)."""
    rng = np.random.default_rng(seed)
    R = len(rows)
    if quant:
        k_pages = jnp.zeros((L, num_pages, PS, Hkv * D), jnp.int8)
        v_pages = jnp.zeros((L, num_pages, PS, Hkv * D), jnp.int8)
        k_scales = jnp.zeros((L, num_pages, 8, PS), jnp.float32)
        v_scales = jnp.zeros((L, num_pages, 8, PS), jnp.float32)
    else:
        k_pages = jnp.zeros((L, num_pages, PS, Hkv * D), jnp.float32)
        v_pages = jnp.zeros((L, num_pages, PS, Hkv * D), jnp.float32)
        k_scales = v_scales = None
    page_table = np.zeros((R, max_pages), np.int32)
    next_page = 1
    kv_lens = np.asarray([kv for _q, _p, kv in rows], np.int32)
    for r, (_q_len, _pos0, kv_len) in enumerate(rows):
        n_pages = max(1, -(-kv_len // PS))
        page_table[r, :n_pages] = range(next_page, next_page + n_pages)
        next_page += n_pages
        kk = rng.standard_normal((1, max(kv_len, 1), Hkv, D)).astype(np.float32)
        vv = rng.standard_normal((1, max(kv_len, 1), Hkv, D)).astype(np.float32)
        for lay in range(L):
            if quant:
                k_pages, v_pages, k_scales, v_scales = scatter_kv_chunk_q8(
                    k_pages, v_pages, k_scales, v_scales,
                    jnp.asarray(kk), jnp.asarray(vv),
                    jnp.asarray(page_table[r][None]),
                    jnp.asarray([0], jnp.int32),
                    jnp.asarray([kv_len], jnp.int32), PS, jnp.int32(lay), Hkv,
                )
            else:
                k_pages, v_pages = scatter_kv_chunk(
                    k_pages, v_pages, jnp.asarray(kk), jnp.asarray(vv),
                    jnp.asarray(page_table[r][None]),
                    jnp.asarray([0], jnp.int32),
                    jnp.asarray([kv_len], jnp.int32), PS, jnp.int32(lay),
                )
    T = sum(q for q, _p, _k in rows)
    tok_row, tok_pos = [], []
    for r, (q_len, pos0, _kv) in enumerate(rows):
        tok_row += [r] * q_len
        tok_pos += list(range(pos0, pos0 + q_len))
    q = rng.standard_normal((T, H, D)).astype(np.float32)
    return (jnp.asarray(q), k_pages, v_pages, k_scales, v_scales,
            jnp.asarray(page_table), jnp.asarray(tok_row, jnp.int32),
            jnp.asarray(tok_pos, jnp.int32), jnp.asarray(kv_lens))


def _pad(q, tok_row, tok_pos, n_pad, R):
    """Append ``n_pad`` buffer-padding tokens (tok_row == R)."""
    T, _h, _d = q.shape
    qp = jnp.concatenate([q, jnp.ones((n_pad, H, D), q.dtype)])
    rp = jnp.concatenate([tok_row, jnp.full((n_pad,), R, jnp.int32)])
    pp = jnp.concatenate([tok_pos, jnp.zeros((n_pad,), jnp.int32)])
    return qp, rp, pp


CASES = {
    # prefill chunk + decode row + spec block — the serving mix
    "mix": [(13, 0, 13), (1, 9, 10), (3, 5, 8)],
    # all decode rows (q_len 1), distinct contexts
    "decode": [(1, 0, 1), (1, 7, 8), (1, 15, 16), (1, 16, 17)],
    # page-boundary edges: kv_len exactly at page multiples, chunk
    # crossing a page boundary, chunk starting mid-page
    "boundary": [(8, 0, 8), (16, 8, 24), (5, 6, 11), (1, 23, 24)],
    # single long row (one-row dispatch)
    "single": [(29, 3, 32)],
    # unaligned lengths around the block_q=8 sublane tile
    "unaligned": [(7, 0, 7), (9, 2, 11), (8, 8, 16), (2, 1, 3)],
}


@pytest.mark.parametrize("case", sorted(CASES))
def test_ragged_kernel_matches_reference(case):
    rows = CASES[case]
    (q, kp, vp, _ks, _vs, pt, tok_row, tok_pos, kv_len) = _build(
        rows, max_pages=4, seed=hash(case) % 1000)
    ref = ragged_paged_attention_ref(
        q, kp, vp, pt, tok_row, tok_pos, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv)
    out = ragged_flash_attention(
        q, kp, vp, pt, tok_row, tok_pos, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv,
        interpret=INTERPRET)
    np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)


def test_padding_tokens_are_inert():
    """Buffer padding (tok_row == R) must neither disturb real rows nor
    produce non-finite output; the reference yields zeros there."""
    rows = CASES["mix"]
    (q, kp, vp, _ks, _vs, pt, tok_row, tok_pos, kv_len) = _build(
        rows, max_pages=4, seed=3)
    T = q.shape[0]
    qp, rp, pp = _pad(q, tok_row, tok_pos, 7, len(rows))
    base = ragged_flash_attention(
        q, kp, vp, pt, tok_row, tok_pos, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv,
        interpret=INTERPRET)
    padded = ragged_flash_attention(
        qp, kp, vp, pt, rp, pp, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv,
        interpret=INTERPRET)
    np.testing.assert_allclose(padded[:T], base, atol=ATOL, rtol=RTOL)
    assert np.isfinite(np.asarray(padded)).all()
    ref = ragged_paged_attention_ref(
        qp, kp, vp, pt, rp, pp, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv)
    np.testing.assert_allclose(np.asarray(ref)[T:], 0.0, atol=1e-7)


def test_reference_is_per_row_mha_reference():
    """The oracle is pinned to the SPLIT path's math: each packed token
    equals ``mha_reference`` over its row's densely gathered KV at the
    token's absolute position — bitwise (same function, same fp32 ops),
    which is what the scheduler-level byte-identity contract leans on."""
    rows = CASES["mix"]
    (q, kp, vp, _ks, _vs, pt, tok_row, tok_pos, kv_len) = _build(
        rows, max_pages=4, seed=11)
    ref = np.asarray(ragged_paged_attention_ref(
        q, kp, vp, pt, tok_row, tok_pos, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv))
    k_all, v_all = gather_kv(kp, vp, pt, PS, jnp.int32(LAYER), Hkv)
    t = 0
    for r, (q_len, pos0, kv) in enumerate(rows):
        direct = mha_reference(
            q[t:t + q_len, None],
            jnp.broadcast_to(k_all[r][None], (q_len,) + k_all[r].shape),
            jnp.broadcast_to(v_all[r][None], (q_len,) + v_all[r].shape),
            causal=True,
            q_offset=jnp.arange(pos0, pos0 + q_len, dtype=jnp.int32),
            kv_len=jnp.full((q_len,), kv, jnp.int32),
        )[:, 0]
        assert (np.asarray(direct) == ref[t:t + q_len]).all(), (
            f"row {r} diverged from per-row mha_reference")
        t += q_len


def test_int8_kernel_matches_int8_reference():
    """The q8 kernel and the q8 reference share the dequantization math —
    near-bitwise agreement (both dequantize int8 * fp32 scale rows), and
    both sit within quantization error of the fp32 path."""
    rows = CASES["boundary"]
    (q, kp8, vp8, ks, vs, pt, tok_row, tok_pos, kv_len) = _build(
        rows, max_pages=4, seed=5, quant=True)
    ref8 = ragged_paged_attention_ref(
        q, kp8, vp8, pt, tok_row, tok_pos, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv,
        k_scales=ks, v_scales=vs)
    out8 = ragged_flash_attention_q8(
        q, kp8, vp8, ks, vs, pt, tok_row, tok_pos, kv_len,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv,
        interpret=INTERPRET)
    np.testing.assert_allclose(out8, ref8, atol=ATOL, rtol=RTOL)
    # parity with the fp32 path within int8 quantization error
    (qf, kpf, vpf, _ks, _vs, ptf, trf, tpf, kvf) = _build(
        rows, max_pages=4, seed=5, quant=False)
    reff = ragged_paged_attention_ref(
        qf, kpf, vpf, ptf, trf, tpf, kvf,
        jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv)
    np.testing.assert_allclose(out8, reff, atol=0.12, rtol=0.12)


def test_row_count_edges():
    """1-row and many-row dispatches, including rows whose kv_len exceeds
    their own chunk (history below the chunk) and fresh rows (kv == q)."""
    for rows in (
        [(1, 0, 1)],
        [(4, 4, 8)],
        [(1, i, i + 1) for i in range(6)],
    ):
        (q, kp, vp, _ks, _vs, pt, tok_row, tok_pos, kv_len) = _build(
            rows, max_pages=4, seed=len(rows))
        ref = ragged_paged_attention_ref(
            q, kp, vp, pt, tok_row, tok_pos, kv_len,
            jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv)
        out = ragged_flash_attention(
            q, kp, vp, pt, tok_row, tok_pos, kv_len,
            jnp.asarray([LAYER], jnp.int32), page_size=PS, n_kv=Hkv,
            interpret=INTERPRET)
        np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)
