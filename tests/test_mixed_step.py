"""Unified packed ragged step (engine ragged_mixed_step + scheduler ragged
path, ISSUE 10 — rebuilt from PR 4's padded mixed step).

The contract under test: the ragged path is pure dispatch fusion — greedy
streams are byte-identical to the split path (prefill round + decode-side
dispatches), including the combinations the PADDED mixed step used to demote
(a grammar-constrained slot, spec-decode verify rows, decode_loop fused
tails, and a short-tail prefill chunk, all coexisting in one iteration);
decode slots advance in EVERY ragged round while a long prompt prefills
(admission fairness); allocator/page-table invariants hold after ragged
rounds; the demotion counter stays at zero for the erased reasons; and a
whole-round prefill failure spares parked overlap holds (regression)."""

import asyncio
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import (
    InferenceEngine,
    commit_first_token,
    decode_loop_step,
    decode_step,
    prefill_step,
    ragged_mixed_step,
    verify_step,
)
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS

# fp32: a decode row computes at the packed ragged shape in mixed mode vs
# [max_seqs, 1] in split mode, and under bf16 a last-ulp KV difference can
# flip a LATER near-tie argmax (the chunk-width caveat verify_step
# documents). fp32 pins the byte-identity contract so a structural bug
# cannot hide behind — or be excused by — rounding.
CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
CHUNK = 16


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _stack(params, mixed=True, max_seqs=4, num_pages=128, eos_id=-1,
           spec_tokens=0, decode_loop_depth=1):
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=num_pages, max_seq_len=128,
        prefill_chunk=CHUNK, mixed_step=mixed, session_cache=False,
        spec_tokens=spec_tokens, decode_loop_depth=decode_loop_depth,
    )
    engine = InferenceEngine(CONFIG, params, cfg)
    return ContinuousBatchingScheduler(engine, eos_id=eos_id)


async def _drain(handle, out):
    while True:
        ev = await asyncio.wait_for(handle.events.get(), timeout=120)
        if ev["type"] == "token":
            out.append(ev["token_id"])
        elif ev["type"] == "done":
            assert handle.events.empty()
            return
        else:
            raise AssertionError(ev)


# --- engine level -----------------------------------------------------------


def test_engine_ragged_step_matches_split_math(params):
    """One packed ragged dispatch == the split dispatches, exactly: a
    completing prefill row's greedy first token (vs prefill + commit), a
    decode row's token (vs a verify row with no drafts — the split spec
    path's plain-slot math), a spec row's accepted prefix (vs verify_step),
    the fused tail block (vs decode_loop_step), and the resulting
    context_lens / last_tokens all match an identically prepared engine."""

    def prepare():
        cfg = EngineConfig(
            max_seqs=4, page_size=8, num_pages=64, max_seq_len=128,
            prefill_chunk=CHUNK, spec_tokens=2, decode_loop_depth=3,
        )
        eng = InferenceEngine(CONFIG, params, cfg)
        alloc = PageAllocator(cfg.num_pages)
        # slot 0: decoding (will ride the fused tail)
        p0 = [3, 7, 11, 200, 42]
        eng.set_page_table_row(0, alloc.allocate("s0", pages_needed(len(p0) + 16, 8)))
        logits = eng.prefill(0, p0)
        eng.state, _ = commit_first_token(
            eng.state, jnp.int32(0), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
        # slot 1: a 2-chunk prompt with only the FIRST chunk prefilled
        p1 = list(range(1, CHUNK + 6))
        eng.set_page_table_row(1, alloc.allocate("s1", pages_needed(len(p1) + 8, 8)))
        eng.state, _ = prefill_step(
            eng.params, eng.state,
            jnp.asarray([p1[:CHUNK]], jnp.int32), jnp.asarray([1], jnp.int32),
            jnp.asarray([0], jnp.int32), jnp.asarray([CHUNK], jnp.int32),
            config=eng.config, page_size=8, attn_backend=eng.attn_backend,
        )
        # slot 2: decoding, will carry spec drafts
        p2 = [9, 9, 9, 9, 9, 9]
        eng.set_page_table_row(2, alloc.allocate("s2", pages_needed(len(p2) + 16, 8)))
        logits = eng.prefill(2, p2)
        eng.state, _ = commit_first_token(
            eng.state, jnp.int32(2), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
        return eng, p1

    B = 4
    zB = jnp.zeros((B,), jnp.float32)
    oB = jnp.ones((B,), jnp.float32)
    kB = jnp.zeros((B,), jnp.int32)

    # --- split: prefill tail + commit, verify step, loop tail -----------
    eng_s, p1 = prepare()
    tail = p1[CHUNK:]
    drafts = np.zeros((B, 2), np.int32)
    drafts[2] = [9, 9]
    nd = np.zeros((B,), np.int32)
    nd[2] = 2
    eng_s.state, lg = prefill_step(
        eng_s.params, eng_s.state,
        jnp.asarray([tail + [0] * (CHUNK - len(tail))], jnp.int32),
        jnp.asarray([1], jnp.int32), jnp.asarray([CHUNK], jnp.int32),
        jnp.asarray([len(tail)], jnp.int32),
        config=eng_s.config, page_size=8, attn_backend=eng_s.attn_backend,
    )
    eng_s.state, first1 = commit_first_token(
        eng_s.state, jnp.int32(1), lg[0],
        jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
    )
    active = jnp.zeros((B,), bool).at[0].set(True).at[2].set(True)
    eng_s.state, emitted_s, n_em_s, _ = verify_step(
        eng_s.params, eng_s.state, active, jnp.asarray(drafts),
        jnp.asarray(nd), zB, oB, kB,
        config=eng_s.config, page_size=8, attn_backend=eng_s.attn_backend,
    )
    act0 = jnp.zeros((B,), bool).at[0].set(True)
    eng_s.state, blk_s = decode_loop_step(
        eng_s.params, eng_s.state, act0, zB, oB, kB, jnp.int32(-1),
        config=eng_s.config, page_size=8, attn_backend=eng_s.attn_backend,
        loop_depth=2,
    )
    split = dict(
        first1=int(first1), tok0=int(emitted_s[0, 0]),
        em2=np.asarray(emitted_s[2, : int(n_em_s[2])]).tolist(),
        blk0=np.asarray(blk_s[:, 0]).tolist(),
        ctx=np.asarray(eng_s.state.context_lens).tolist(),
        last=np.asarray(eng_s.state.last_tokens).tolist(),
    )

    # --- ragged: all of it in ONE packed dispatch ------------------------
    eng_r, p1 = prepare()
    R, T = 4, 32
    toks, tok_row = [], []
    row_slot = np.zeros((R,), np.int32)
    row_start = np.zeros((R,), np.int32)
    row_len = np.zeros((R,), np.int32)
    from_dev = np.zeros((R,), bool)
    arm = np.zeros((R,), bool)
    ndr = np.zeros((R,), np.int32)
    # row 0: slot 1's completing tail
    row_slot[0], row_start[0], row_len[0], arm[0] = 1, CHUNK, len(tail), True
    toks += tail
    tok_row += [0] * len(tail)
    # row 1: slot 0 plain decode (loop tail slot)
    row_slot[1], row_len[1], from_dev[1], arm[1] = 0, 1, True, True
    toks += [0]
    tok_row += [1]
    # row 2: slot 2 spec verify with drafts [9, 9]
    row_slot[2], row_len[2], from_dev[2], arm[2], ndr[2] = 2, 3, True, True, 2
    toks += [0, 9, 9]
    tok_row += [2] * 3
    toks += [0] * (T - len(toks))
    tok_row += [R] * (T - len(tok_row))
    loop_active = np.zeros((B,), bool)
    loop_active[0] = True
    eng_r.state, emitted, n_em, _logits, blk = ragged_mixed_step(
        eng_r.params, eng_r.state,
        jnp.asarray(toks, jnp.int32), jnp.asarray(tok_row, jnp.int32),
        jnp.asarray(row_slot), jnp.asarray(row_start), jnp.asarray(row_len),
        jnp.asarray(from_dev), jnp.asarray(arm), jnp.asarray(ndr),
        jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
        jnp.zeros((R,), jnp.int32),
        jnp.asarray(loop_active), zB, oB, kB, jnp.int32(-1),
        config=eng_r.config, page_size=8, attn_backend=eng_r.attn_backend,
        spec_width=2, loop_depth=3,
    )
    got = dict(
        first1=int(emitted[0, 0]), tok0=int(emitted[1, 0]),
        em2=np.asarray(emitted[2, : int(n_em[2])]).tolist(),
        blk0=np.asarray(blk[:, 0]).tolist(),
        ctx=np.asarray(eng_r.state.context_lens).tolist(),
        last=np.asarray(eng_r.state.last_tokens).tolist(),
    )
    assert got == split


def test_engine_ragged_step_accepts_matching_drafts(params):
    """Spec acceptance inside the ragged step is verify_step's math: drafts
    equal to the model's own greedy continuation all commit (n_emitted =
    n_drafts + 1), and the resulting state matches token-by-token decode."""
    cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=64,
        prefill_chunk=8, spec_tokens=2,
    )
    eng = InferenceEngine(CONFIG, params, cfg)
    alloc = PageAllocator(cfg.num_pages)
    p = [3, 7, 11, 200, 42]
    eng.set_page_table_row(0, alloc.allocate("s", pages_needed(len(p) + 8, 8)))
    logits = eng.prefill(0, p)
    eng.state, _ = commit_first_token(
        eng.state, jnp.int32(0), logits,
        jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
    )
    B = 2
    zB, oB, kB = (jnp.zeros((B,), jnp.float32), jnp.ones((B,), jnp.float32),
                  jnp.zeros((B,), jnp.int32))
    # ground truth: three greedy decode steps over a COPY of the state
    # (decode_step donates its state argument)
    ref_state = jax.tree_util.tree_map(jnp.copy, eng.state)
    ref_tokens = []
    act = jnp.zeros((B,), bool).at[0].set(True)
    for _ in range(3):
        ref_state, toks, _ = decode_step(
            eng.params, ref_state, act, zB, oB, kB,
            config=eng.config, page_size=8, attn_backend=eng.attn_backend,
        )
        ref_tokens.append(int(toks[0]))
    # ragged spec row drafting exactly those continuations
    R, T = 2, 8
    toks = [0, ref_tokens[0], ref_tokens[1]] + [0] * (T - 3)
    tok_row = [0, 0, 0] + [R] * (T - 3)
    row_slot = np.zeros((R,), np.int32)
    row_len = np.asarray([3, 0], np.int32)
    from_dev = np.asarray([True, False])
    arm = np.asarray([True, False])
    ndr = np.asarray([2, 0], np.int32)
    eng.state, emitted, n_em, _lg, _blk = ragged_mixed_step(
        eng.params, eng.state,
        jnp.asarray(toks, jnp.int32), jnp.asarray(tok_row, jnp.int32),
        jnp.asarray(row_slot), jnp.zeros((R,), jnp.int32),
        jnp.asarray(row_len), jnp.asarray(from_dev), jnp.asarray(arm),
        jnp.asarray(ndr),
        jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
        jnp.zeros((R,), jnp.int32),
        jnp.zeros((B,), bool), zB, oB, kB, jnp.int32(-1),
        config=eng.config, page_size=8, attn_backend=eng.attn_backend,
        spec_width=2, loop_depth=1,
    )
    assert int(n_em[0]) == 3  # both drafts + bonus token committed
    assert np.asarray(emitted[0, :3]).tolist() == ref_tokens
    assert int(eng.state.context_lens[0]) == len(p) + 3
    assert int(eng.state.last_tokens[0]) == ref_tokens[-1]


# --- scheduler level: byte-identity -----------------------------------------


def _run_workload(params, mixed, with_constraint=False):
    """Two decode streams, then a long prompt admitted mid-decode (so its
    chunks coexist with live decodes), plus optionally a grammar-constrained
    stream. Returns (streams dict, mixed dispatch count)."""
    sched = _stack(params, mixed=mixed)
    tok = ByteTokenizer()
    rng = np.random.default_rng(7)
    short_a = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    short_b = rng.integers(1, CONFIG.vocab_size, size=14).tolist()
    # 5 full chunks + a 2-token tail: the final ragged round packs a SHORT
    # row instead of padding to the chunk width — identity covers the
    # ragged tail case the old two-bucket scheme special-cased
    long_p = rng.integers(1, CONFIG.vocab_size, size=5 * CHUNK + 2).tolist()

    async def go():
        d0 = METRICS.get("finchat_mixed_dispatches_total")
        await sched.start()
        try:
            ha = await sched.submit(
                "a", short_a, SamplingParams(temperature=0.0, max_new_tokens=28))
            hb = await sched.submit(
                "b", short_b, SamplingParams(temperature=0.0, max_new_tokens=22))
            outs = {"a": [], "b": [], "long": []}
            tasks = [asyncio.create_task(_drain(ha, outs["a"])),
                     asyncio.create_task(_drain(hb, outs["b"]))]
            if with_constraint:
                from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

                hc = await sched.submit(
                    "tool", tok.encode("decide", add_bos=True),
                    SamplingParams(temperature=0.0, max_new_tokens=20),
                    constraint=TokenConstraint(GrammarVocab.for_tokenizer(tok)),
                )
                outs["tool"] = []
                tasks.append(asyncio.create_task(_drain(hc, outs["tool"])))
            while len(outs["a"]) < 2 or len(outs["b"]) < 2:
                await asyncio.sleep(0.002)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=6))
            tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
            await asyncio.gather(*tasks)
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            assert sorted(sched.free_slots) == list(range(4))
            return outs, METRICS.get("finchat_mixed_dispatches_total") - d0
        finally:
            await sched.stop()

    return asyncio.run(go())


def test_mixed_vs_split_streams_identical(params):
    """Greedy streams — two in-flight decodes, a long prompt admitted
    mid-decode, and the long prompt completing mid-batch — are
    byte-identical ragged vs split, and the ragged run actually fused."""
    split, n_split = _run_workload(params, mixed=False)
    mixed, n_mixed = _run_workload(params, mixed=True)
    assert [len(s) for s in split.values()] == [28, 22, 6]
    assert mixed == split
    assert n_split == 0
    # the long prompt spans 5+ chunks; each coexisted with live decodes
    assert n_mixed >= 5


def _demoted_combo_workload(params, mixed, recorded=None, seed=7):
    """The previously-demoted feature mix in ONE scheduler (satellite
    fuzz): spec decode on, decode_loop on, a grammar-constrained stream, a
    greedy bystander, and a long prompt with a short tail admitted
    mid-decode — under PR 4 any ONE of these demoted every coexist
    iteration to the split path. ``recorded`` (ragged runs) collects, per
    ragged dispatch, which features were carried."""
    sched = _stack(params, mixed=mixed, max_seqs=5, num_pages=256,
                   spec_tokens=2, decode_loop_depth=3)
    if recorded is not None:
        real = sched.engine.ragged_mixed

        def spy(tokens, tok_row, row_slot, row_start, row_len,
                row_from_device, row_arm, row_n_drafts, *rest):
            loop_active = rest[3]
            nd = np.asarray(row_n_drafts)
            fd = np.asarray(row_from_device)
            rl = np.asarray(row_len)
            recorded.append({
                "prefill": bool(((rl > 0) & ~fd).any()),
                "spec": bool((nd > 0).any()),
                "loop": bool(np.asarray(loop_active).any()),
                "constrained": any(
                    h.constraint is not None for h in sched.decoding.values()
                ),
                "short_tail": bool(((rl > 0) & ~fd & (rl < CHUNK)).any()),
            })
            return real(tokens, tok_row, row_slot, row_start, row_len,
                        row_from_device, row_arm, row_n_drafts, *rest)

        sched.engine.ragged_mixed = spy
    tok = ByteTokenizer()
    rng = np.random.default_rng(seed)
    # repetitive prompts: greedy decode on random tiny weights settles into
    # loops, so prompt-lookup proposals (and acceptances) actually fire
    base = rng.integers(1, CONFIG.vocab_size, size=4).tolist()
    spec_prompt = (base * 5)[:18]
    by_prompt = rng.integers(1, CONFIG.vocab_size, size=9).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=5 * CHUNK + 3).tolist()

    async def go():
        from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

        await sched.start()
        try:
            outs = {"spec": [], "by": [], "tool": [], "long": []}
            hs = await sched.submit(
                "spec", spec_prompt,
                SamplingParams(temperature=0.0, max_new_tokens=64))
            hb = await sched.submit(
                "by", by_prompt, SamplingParams(temperature=0.0, max_new_tokens=56))
            hc = await sched.submit(
                "tool", tok.encode("decide", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=40),
                constraint=TokenConstraint(GrammarVocab.for_tokenizer(tok)),
            )
            tasks = [asyncio.create_task(_drain(hs, outs["spec"])),
                     asyncio.create_task(_drain(hb, outs["by"])),
                     asyncio.create_task(_drain(hc, outs["tool"]))]
            # admit the long prompt inside a live PROPOSAL window: the
            # greedy stream has looped (its n-gram index proposes) and
            # the all-miss cooldown is clear, so the coexist iterations
            # actually carry spec verify rows. Timing only — greedy token
            # VALUES are submission-timing independent, so the split run
            # (same gate) stays byte-comparable.
            for _ in range(30_000):
                if hs.finished or (
                    sched._spec_cooldown == 0
                    and hs.ngram_index is not None
                    and hs.ngram_index.propose(2)
                ):
                    break
                await asyncio.sleep(0.001)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=5))
            tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
            await asyncio.gather(*tasks)
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            assert sorted(sched.free_slots) == list(range(5))
            return outs
        finally:
            await sched.stop()

    return asyncio.run(go())


@pytest.mark.parametrize("seed", [7, 23, 41])
def test_previously_demoted_combo_byte_identity(params, seed):
    """The erased-demotion fuzz (ISSUE 10 satellite): spec verify rows,
    decode_loop fused tails, a grammar-constrained stream, and a
    short-tail prefill coexisting in one iteration — greedy/constrained
    streams byte-identical ragged vs split, with the ragged run actually
    carrying the feature mix in fused dispatches."""
    split = _demoted_combo_workload(params, mixed=False, seed=seed)
    recorded: list[dict] = []
    ragged = _demoted_combo_workload(params, mixed=True, recorded=recorded,
                                     seed=seed)
    assert ragged == split
    assert recorded, "no ragged dispatch ran"
    assert any(r["prefill"] and r["constrained"] for r in recorded), (
        "constrained slot never rode a fused dispatch", recorded)
    assert any(r["prefill"] and r["loop"] for r in recorded), (
        "no fused loop tail in any coexist dispatch", recorded)
    assert any(r["prefill"] and r["spec"] for r in recorded), (
        "no spec verify row in any coexist dispatch", recorded)
    assert any(r["short_tail"] for r in recorded), recorded


def test_demotion_counter_erased_reasons_stay_zero(params):
    """finchat_mixed_demotions_total (ISSUE 10 satellite): the reason
    family is pre-seeded, and running the previously-demoting feature mix
    increments NONE of the erased reasons (spec / decode_loop /
    constrained) — the erasure is observable, not assumed."""
    before = {
        r: METRICS.get("finchat_mixed_demotions_total", labels={"reason": r})
        for r in ContinuousBatchingScheduler.MIXED_DEMOTION_REASONS
    }
    _demoted_combo_workload(params, mixed=True)
    snap = METRICS.snapshot()
    for reason in ("spec", "decode_loop", "constrained"):
        key = f'finchat_mixed_demotions_total{{reason="{reason}"}}'
        assert snap.get(key, 0) == before[reason], (reason, snap.get(key))


# --- scheduler level: admission fairness ------------------------------------


def test_admission_fairness_decode_advances_every_ragged_round(params):
    """While a long prompt prefills, every ragged dispatch carries ALL live
    decoding slots as device-read rows — decode streams advance at least
    one token per scheduler iteration instead of stalling behind a
    serialized prefill round."""
    sched = _stack(params, mixed=True)
    calls: list[tuple[int, int, int]] = []  # (#prefill rows, #decode rows, #decoding)
    real = sched.engine.ragged_mixed

    def spy(tokens, tok_row, row_slot, row_start, row_len,
            row_from_device, row_arm, row_n_drafts, *rest):
        rl = np.asarray(row_len)
        fd = np.asarray(row_from_device)
        calls.append((
            int(((rl > 0) & ~fd).sum()), int(fd.sum()), len(sched.decoding),
        ))
        return real(tokens, tok_row, row_slot, row_start, row_len,
                    row_from_device, row_arm, row_n_drafts, *rest)

    sched.engine.ragged_mixed = spy
    rng = np.random.default_rng(3)
    short = rng.integers(1, CONFIG.vocab_size, size=9).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=6 * CHUNK).tolist()

    async def go():
        await sched.start()
        try:
            h1 = await sched.submit(
                "d1", short, SamplingParams(temperature=0.0, max_new_tokens=40))
            h2 = await sched.submit(
                "d2", short[:5], SamplingParams(temperature=0.0, max_new_tokens=36))
            o1, o2 = [], []
            t1 = asyncio.create_task(_drain(h1, o1))
            t2 = asyncio.create_task(_drain(h2, o2))
            while len(o1) < 2 or len(o2) < 2:
                await asyncio.sleep(0.002)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=4))
            ol = []
            tl = asyncio.create_task(_drain(hl, ol))
            await asyncio.gather(t1, t2, tl)
            return o1, o2, ol
        finally:
            await sched.stop()

    o1, o2, ol = asyncio.run(go())
    assert (len(o1), len(o2), len(ol)) == (40, 36, 4)
    assert len(calls) >= 6  # one ragged round per long-prompt chunk, minimum
    for n_prefill, n_decode, n_decoding in calls:
        assert n_prefill >= 1, "a ragged dispatch carried no prefill row"
        assert n_decode == n_decoding, (
            "a decoding slot sat out a ragged dispatch", calls)
        assert n_decode >= 1


# --- scheduler level: invariants under churn --------------------------------


def test_allocator_and_slot_invariants_after_ragged_waves(params):
    """Wave-loaded ragged rounds (pool smaller than offered load, staggered
    budgets, admissions landing while others decode) leave the allocator
    and slot bookkeeping clean."""
    tok = ByteTokenizer()
    sched = _stack(params, mixed=True, max_seqs=3, num_pages=32)

    async def go():
        await sched.start()
        try:
            handles = [
                await sched.submit(
                    f"w{i}", tok.encode(f"wave prompt number {i}", add_bos=True),
                    SamplingParams(temperature=0.0, max_new_tokens=8 + 4 * i),
                )
                for i in range(6)
            ]
            outs = [[] for _ in handles]
            await asyncio.gather(*[
                _drain(h, o) for h, o in zip(handles, outs)
            ])
            return [len(o) for o in outs]
        finally:
            await sched.stop()

    counts = asyncio.run(go())
    assert counts == [8 + 4 * i for i in range(6)], counts
    sched.allocator.check_invariants()
    assert sched.allocator.used_count == 0
    assert sorted(sched.free_slots) == list(range(3))
    assert not sched.prefilling and not sched.decoding
    assert np.asarray(sched.engine.state.context_lens).sum() == 0
    assert np.asarray(sched.engine.state.page_table).sum() == 0


def test_inter_token_histogram_labeled_by_prefill_coexistence(params):
    """The finchat_inter_token_seconds histogram distinguishes tokens
    emitted while prefill work ran (admission) from steady decode — both
    series must be populated by a coexistence workload."""
    y0 = METRICS.quantile("finchat_inter_token_seconds", 0.5,
                          labels={"prefill_concurrent": "yes"})
    before_yes = METRICS.snapshot().get(
        'finchat_inter_token_seconds{prefill_concurrent="yes"}_count', 0)
    before_no = METRICS.snapshot().get(
        'finchat_inter_token_seconds{prefill_concurrent="no"}_count', 0)
    _run_workload(params, mixed=True)
    snap = METRICS.snapshot()
    assert snap['finchat_inter_token_seconds{prefill_concurrent="yes"}_count'] > before_yes
    assert snap['finchat_inter_token_seconds{prefill_concurrent="no"}_count'] > before_no
    assert y0 >= 0.0  # quantile path accepts labels


# --- regression: whole-round failure must spare parked holds ----------------


def test_prefill_round_failure_spares_parked_holds(params, monkeypatch):
    """A whole-round prefill failure touches only the sequences IN the
    dispatch: a parked overlap hold (prefix complete, awaiting
    extend_prompt) was skipped from the round and must survive it
    untouched, then complete normally after its graft. The sequence that
    WAS in the failed round is recompute-preempted and replayed (ISSUE 5
    breaker semantics, default on), so its stream completes too. The
    pre-fix handler evicted everything in self.prefilling, killing
    in-flight retrieval overlaps that never touched the failed dispatch."""
    import finchat_tpu.engine.scheduler as sched_mod

    sched = _stack(params, mixed=False)
    rng = np.random.default_rng(11)
    prefix = rng.integers(1, CONFIG.vocab_size, size=40).tolist()
    full = prefix + rng.integers(1, CONFIG.vocab_size, size=12).tolist()
    samp = SamplingParams(temperature=0.0, max_new_tokens=5)

    real = sched_mod.prefill_step
    state = {"armed": False, "fired": False}

    def flaky(*args, **kwargs):
        if state["armed"]:
            state["armed"] = False
            state["fired"] = True
            raise RuntimeError("injected whole-round failure")
        return real(*args, **kwargs)

    monkeypatch.setattr(sched_mod, "prefill_step", flaky)

    async def go():
        await sched.start()
        try:
            hold = await sched.submit_partial("hold", prefix, samp)
            assert hold is not None
            t0 = time.perf_counter()
            while hold.prefill_pos < len(hold.prompt_ids):
                assert time.perf_counter() - t0 < 60
                await asyncio.sleep(0.01)
            assert hold.held and hold in sched.prefilling

            # now fail the NEXT whole round (the victim's dispatch)
            state["armed"] = True
            victim = await sched.submit("victim", full[:20], samp)
            victim_tokens = []
            await asyncio.wait_for(_drain(victim, victim_tokens), timeout=60)
            assert state["fired"]
            # the victim rode the failed round but was preempted and
            # replayed — its stream completed anyway
            assert len(victim_tokens) == 5 and victim.preempted == 1

            # the parked hold survived the failed round UNTOUCHED (its
            # prefilled prefix KV intact — it was not preempted)
            assert not hold.finished and hold in sched.prefilling and hold.held
            assert hold.preempted == 0
            assert hold.prefill_pos >= len(hold.prompt_ids)

            # ...and still completes after its graft
            assert sched.extend_prompt(hold, full)
            tokens = []
            await _drain(hold, tokens)
            return tokens
        finally:
            await sched.stop()

    tokens = asyncio.run(go())
    assert len(tokens) == 5
    sched.allocator.check_invariants()
    assert sched.allocator.used_count == 0
