"""Unified mixed prefill+decode step (engine mixed_step + scheduler mixed
path, ISSUE 4).

The contract under test: the mixed path is pure dispatch fusion — greedy
streams are byte-identical to the split path (prefill round + decode step),
including a prompt completing mid-batch and a grammar-constrained slot
forcing demotion; decode slots advance a token in EVERY mixed round while a
long prompt prefills (admission fairness); allocator/page-table invariants
hold after mixed rounds; and a whole-round prefill failure no longer evicts
parked overlap holds that were not in the failed dispatch (regression)."""

import asyncio
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import (
    InferenceEngine,
    commit_first_token,
    decode_step,
    mixed_step,
    prefill_step,
)
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS

# fp32: a decode row computes at the ragged [rows, chunk] shape in mixed
# mode vs [max_seqs, 1] in split mode, and under bf16 a last-ulp KV
# difference can flip a LATER near-tie argmax (the chunk-width caveat
# verify_step documents). fp32 pins the byte-identity contract so a
# structural bug cannot hide behind — or be excused by — rounding.
import dataclasses

CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
CHUNK = 16


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _stack(params, mixed=True, max_seqs=4, num_pages=128, eos_id=-1):
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=num_pages, max_seq_len=128,
        prefill_chunk=CHUNK, mixed_step=mixed, session_cache=False,
    )
    engine = InferenceEngine(CONFIG, params, cfg)
    return ContinuousBatchingScheduler(engine, eos_id=eos_id)


async def _drain(handle, out):
    while True:
        ev = await asyncio.wait_for(handle.events.get(), timeout=120)
        if ev["type"] == "token":
            out.append(ev["token_id"])
        elif ev["type"] == "done":
            assert handle.events.empty()
            return
        else:
            raise AssertionError(ev)


# --- engine level -----------------------------------------------------------


def test_engine_mixed_step_matches_split_math(params):
    """One mixed dispatch == one prefill chunk + one decode step + one
    commit, exactly: the decode row's greedy token, the completing prefill
    row's greedy first token, and the resulting context_lens all match the
    split dispatches from an identically prepared engine."""

    def prepare():
        cfg = EngineConfig(
            max_seqs=4, page_size=8, num_pages=64, max_seq_len=128,
            prefill_chunk=CHUNK,
        )
        eng = InferenceEngine(CONFIG, params, cfg)
        alloc = PageAllocator(cfg.num_pages)
        # slot 0: fully prefilled + committed → decoding
        p0 = [3, 7, 11, 200, 42]
        pages0 = alloc.allocate("s0", pages_needed(len(p0) + 8, eng.page_size))
        eng.set_page_table_row(0, pages0)
        logits = eng.prefill(0, p0)
        eng.state, tok0 = commit_first_token(
            eng.state, jnp.int32(0), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
        # slot 1: a 2-chunk prompt with only the FIRST chunk prefilled
        p1 = list(range(1, CHUNK + 6))
        pages1 = alloc.allocate("s1", pages_needed(len(p1) + 8, eng.page_size))
        eng.set_page_table_row(1, pages1)
        c1 = p1[:CHUNK]
        eng.state, _ = prefill_step(
            eng.params, eng.state,
            jnp.asarray([c1], jnp.int32), jnp.asarray([1], jnp.int32),
            jnp.asarray([0], jnp.int32), jnp.asarray([len(c1)], jnp.int32),
            config=eng.config, page_size=eng.page_size,
            attn_backend=eng.attn_backend,
        )
        return eng, p1, int(tok0)

    # --- split: finish slot 1's prefill, commit, then one decode step ----
    eng_s, p1, _ = prepare()
    tail = p1[CHUNK:]
    eng_s.state, logits = prefill_step(
        eng_s.params, eng_s.state,
        jnp.asarray([tail + [0] * (CHUNK - len(tail))], jnp.int32),
        jnp.asarray([1], jnp.int32), jnp.asarray([CHUNK], jnp.int32),
        jnp.asarray([len(tail)], jnp.int32),
        config=eng_s.config, page_size=eng_s.page_size,
        attn_backend=eng_s.attn_backend,
    )
    eng_s.state, first1 = commit_first_token(
        eng_s.state, jnp.int32(1), logits[0],
        jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
    )
    B = eng_s.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    tok_dec = eng_s.decode(
        active, jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    )
    split = (int(tok_dec[0]), int(first1),
             np.asarray(eng_s.state.context_lens)[:2].tolist())

    # --- mixed: both advances in ONE ragged dispatch ---------------------
    eng_m, p1, _ = prepare()
    tokens = np.zeros((2, CHUNK), np.int32)
    tokens[0, : len(tail)] = tail  # row 0: slot 1's completing chunk
    eng_m.state, next_tokens, _ = mixed_step(
        eng_m.params, eng_m.state,
        jnp.asarray(tokens),
        jnp.asarray([1, 0], jnp.int32),          # slots
        jnp.asarray([CHUNK, 0], jnp.int32),      # start (decode row overridden)
        jnp.asarray([len(tail), 1], jnp.int32),  # n_valid
        jnp.asarray([False, True]),              # is_decode
        jnp.asarray([True, True]),               # arm (completion + decode)
        jnp.zeros((2,), jnp.float32), jnp.ones((2,), jnp.float32),
        jnp.zeros((2,), jnp.int32),
        config=eng_m.config, page_size=eng_m.page_size,
        attn_backend=eng_m.attn_backend,
    )
    got = (int(next_tokens[1]), int(next_tokens[0]),
           np.asarray(eng_m.state.context_lens)[:2].tolist())
    assert got == split
    # both slots' next decode inputs are armed identically
    assert (np.asarray(eng_m.state.last_tokens)[:2]
            == np.asarray(eng_s.state.last_tokens)[:2]).all()


# --- scheduler level: byte-identity -----------------------------------------


def _run_workload(params, mixed, with_constraint=False):
    """Two decode streams, then a long prompt admitted mid-decode (so its
    chunks coexist with live decodes), plus optionally a grammar-constrained
    stream. Returns (streams dict, mixed dispatch count)."""
    sched = _stack(params, mixed=mixed)
    tok = ByteTokenizer()
    rng = np.random.default_rng(7)
    short_a = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    short_b = rng.integers(1, CONFIG.vocab_size, size=14).tolist()
    # 5 full chunks + a 2-token tail: the final mixed round fits the SMALL
    # chunk bucket (mixed_chunk_buckets → CHUNK//8 = 2), so identity
    # covers both compiled column widths
    long_p = rng.integers(1, CONFIG.vocab_size, size=5 * CHUNK + 2).tolist()

    async def go():
        d0 = METRICS.get("finchat_mixed_dispatches_total")
        await sched.start()
        try:
            ha = await sched.submit(
                "a", short_a, SamplingParams(temperature=0.0, max_new_tokens=28))
            hb = await sched.submit(
                "b", short_b, SamplingParams(temperature=0.0, max_new_tokens=22))
            outs = {"a": [], "b": [], "long": []}
            tasks = [asyncio.create_task(_drain(ha, outs["a"])),
                     asyncio.create_task(_drain(hb, outs["b"]))]
            if with_constraint:
                from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

                hc = await sched.submit(
                    "tool", tok.encode("decide", add_bos=True),
                    SamplingParams(temperature=0.0, max_new_tokens=20),
                    constraint=TokenConstraint(GrammarVocab.for_tokenizer(tok)),
                )
                outs["tool"] = []
                tasks.append(asyncio.create_task(_drain(hc, outs["tool"])))
            while len(outs["a"]) < 2 or len(outs["b"]) < 2:
                await asyncio.sleep(0.002)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=6))
            tasks.append(asyncio.create_task(_drain(hl, outs["long"])))
            await asyncio.gather(*tasks)
            sched.allocator.check_invariants()
            assert sched.allocator.used_count == 0
            assert sorted(sched.free_slots) == list(range(4))
            return outs, METRICS.get("finchat_mixed_dispatches_total") - d0
        finally:
            await sched.stop()

    return asyncio.run(go())


def test_mixed_vs_split_streams_identical(params):
    """Greedy streams — two in-flight decodes, a long prompt admitted
    mid-decode, and the long prompt completing mid-batch — are
    byte-identical mixed vs split, and the mixed run actually fused."""
    split, n_split = _run_workload(params, mixed=False)
    mixed, n_mixed = _run_workload(params, mixed=True)
    assert [len(s) for s in split.values()] == [28, 22, 6]
    assert mixed == split
    assert n_split == 0
    # the long prompt spans 5 chunks; each coexisted with live decodes
    assert n_mixed >= 5


def _constrained_workload(params, mixed, recorded=None):
    """A bystander decode, a grammar-constrained stream, a long prompt
    admitted while the constrained stream is live (phase 1 — every
    iteration must demote to split), then a second long prompt admitted
    after the constrained stream retires (phase 2 — fusion must resume).
    ``recorded`` (mixed runs) collects, per mixed dispatch, whether any
    constrained handle was live."""
    sched = _stack(params, mixed=mixed)
    if recorded is not None:
        real_mixed = sched.engine.mixed

        def spy(*args, **kwargs):
            live = list(sched.decoding.values()) + list(sched.prefilling)
            recorded.append(any(h.constraint is not None for h in live))
            return real_mixed(*args, **kwargs)

        sched.engine.mixed = spy
    tok = ByteTokenizer()
    rng = np.random.default_rng(7)
    by_prompt = rng.integers(1, CONFIG.vocab_size, size=10).tolist()
    long1 = rng.integers(1, CONFIG.vocab_size, size=3 * CHUNK).tolist()
    long2 = rng.integers(1, CONFIG.vocab_size, size=3 * CHUNK).tolist()

    async def go():
        from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

        await sched.start()
        try:
            outs = {"by": [], "tool": [], "long1": [], "long2": []}
            hb = await sched.submit(
                "by", by_prompt, SamplingParams(temperature=0.0, max_new_tokens=80))
            tasks = [asyncio.create_task(_drain(hb, outs["by"]))]
            hc = await sched.submit(
                "tool", tok.encode("decide", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=12),
                constraint=TokenConstraint(GrammarVocab.for_tokenizer(tok)),
            )
            tool_task = asyncio.create_task(_drain(hc, outs["tool"]))
            tasks.append(tool_task)
            while len(outs["by"]) < 2:
                await asyncio.sleep(0.002)
            hl1 = await sched.submit(
                "long1", long1, SamplingParams(temperature=0.0, max_new_tokens=4))
            tasks.append(asyncio.create_task(_drain(hl1, outs["long1"])))
            await tool_task  # constrained stream retires
            hl2 = await sched.submit(
                "long2", long2, SamplingParams(temperature=0.0, max_new_tokens=4))
            tasks.append(asyncio.create_task(_drain(hl2, outs["long2"])))
            await asyncio.gather(*tasks)
            return outs
        finally:
            await sched.stop()

    return asyncio.run(go())


def test_constrained_slot_forces_demotion_and_identity(params):
    """A grammar-constrained slot demotes every iteration it is in flight
    to the split path (its host-side pick cannot ride a fused dispatch):
    no mixed dispatch ever sees it live, fusion resumes once it retires,
    and the whole workload's greedy streams stay byte-identical mixed vs
    split."""
    split = _constrained_workload(params, mixed=False)
    recorded: list[bool] = []
    mixed = _constrained_workload(params, mixed=True, recorded=recorded)
    assert mixed == split
    assert not any(recorded), "a mixed dispatch ran with a constrained slot live"
    # phase 2 (constrained stream retired, long2 prefilling beside the
    # bystander) must have fused at least long2's chunk count
    assert len(recorded) >= 3, "mixed fusion never resumed after demotion"


# --- scheduler level: admission fairness ------------------------------------


def test_admission_fairness_decode_advances_every_mixed_round(params):
    """While a long prompt prefills, every mixed dispatch carries ALL live
    decoding slots as decode rows — decode streams advance one token per
    scheduler iteration instead of stalling behind a serialized prefill
    round. Each mixed call must contain a prefill row AND exactly the
    decoding population as length-1 rows."""
    sched = _stack(params, mixed=True)
    calls: list[tuple[int, int, int]] = []  # (#prefill rows, #decode rows, #decoding)
    real_mixed = sched.engine.mixed

    def spy(tokens, slots, start_pos, n_valid, is_decode, arm, *rest):
        nv = np.asarray(n_valid)
        dec = np.asarray(is_decode)
        calls.append((
            int(((nv > 0) & ~dec).sum()), int(dec.sum()), len(sched.decoding),
        ))
        return real_mixed(tokens, slots, start_pos, n_valid, is_decode, arm, *rest)

    sched.engine.mixed = spy
    rng = np.random.default_rng(3)
    short = rng.integers(1, CONFIG.vocab_size, size=9).tolist()
    long_p = rng.integers(1, CONFIG.vocab_size, size=6 * CHUNK).tolist()

    async def go():
        await sched.start()
        try:
            h1 = await sched.submit(
                "d1", short, SamplingParams(temperature=0.0, max_new_tokens=40))
            h2 = await sched.submit(
                "d2", short[:5], SamplingParams(temperature=0.0, max_new_tokens=36))
            o1, o2 = [], []
            t1 = asyncio.create_task(_drain(h1, o1))
            t2 = asyncio.create_task(_drain(h2, o2))
            while len(o1) < 2 or len(o2) < 2:
                await asyncio.sleep(0.002)
            hl = await sched.submit(
                "long", long_p, SamplingParams(temperature=0.0, max_new_tokens=4))
            ol = []
            tl = asyncio.create_task(_drain(hl, ol))
            await asyncio.gather(t1, t2, tl)
            return o1, o2, ol
        finally:
            await sched.stop()

    o1, o2, ol = asyncio.run(go())
    assert (len(o1), len(o2), len(ol)) == (40, 36, 4)
    assert len(calls) >= 6  # one mixed round per long-prompt chunk, minimum
    for n_prefill, n_decode, n_decoding in calls:
        assert n_prefill >= 1, "a mixed dispatch carried no prefill row"
        assert n_decode == n_decoding, (
            "a decoding slot sat out a mixed dispatch", calls)
        assert n_decode >= 1


# --- scheduler level: invariants under churn --------------------------------


def test_allocator_and_slot_invariants_after_mixed_waves(params):
    """Wave-loaded mixed rounds (pool smaller than offered load, staggered
    budgets, admissions landing while others decode) leave the allocator
    and slot bookkeeping clean."""
    tok = ByteTokenizer()
    sched = _stack(params, mixed=True, max_seqs=3, num_pages=32)

    async def go():
        await sched.start()
        try:
            handles = [
                await sched.submit(
                    f"w{i}", tok.encode(f"wave prompt number {i}", add_bos=True),
                    SamplingParams(temperature=0.0, max_new_tokens=8 + 4 * i),
                )
                for i in range(6)
            ]
            outs = [[] for _ in handles]
            await asyncio.gather(*[
                _drain(h, o) for h, o in zip(handles, outs)
            ])
            return [len(o) for o in outs]
        finally:
            await sched.stop()

    counts = asyncio.run(go())
    assert counts == [8 + 4 * i for i in range(6)], counts
    sched.allocator.check_invariants()
    assert sched.allocator.used_count == 0
    assert sorted(sched.free_slots) == list(range(3))
    assert not sched.prefilling and not sched.decoding
    assert np.asarray(sched.engine.state.context_lens).sum() == 0
    assert np.asarray(sched.engine.state.page_table).sum() == 0


def test_inter_token_histogram_labeled_by_prefill_coexistence(params):
    """The finchat_inter_token_seconds histogram distinguishes tokens
    emitted while prefill work ran (admission) from steady decode — both
    series must be populated by a coexistence workload."""
    y0 = METRICS.quantile("finchat_inter_token_seconds", 0.5,
                          labels={"prefill_concurrent": "yes"})
    before_yes = METRICS.snapshot().get(
        'finchat_inter_token_seconds{prefill_concurrent="yes"}_count', 0)
    before_no = METRICS.snapshot().get(
        'finchat_inter_token_seconds{prefill_concurrent="no"}_count', 0)
    _run_workload(params, mixed=True)
    snap = METRICS.snapshot()
    assert snap['finchat_inter_token_seconds{prefill_concurrent="yes"}_count'] > before_yes
    assert snap['finchat_inter_token_seconds{prefill_concurrent="no"}_count'] > before_no
    assert y0 >= 0.0  # quantile path accepts labels


# --- regression: whole-round failure must spare parked holds ----------------


def test_prefill_round_failure_spares_parked_holds(params, monkeypatch):
    """A whole-round prefill failure touches only the sequences IN the
    dispatch: a parked overlap hold (prefix complete, awaiting
    extend_prompt) was skipped from the round and must survive it
    untouched, then complete normally after its graft. The sequence that
    WAS in the failed round is recompute-preempted and replayed (ISSUE 5
    breaker semantics, default on), so its stream completes too. The
    pre-fix handler evicted everything in self.prefilling, killing
    in-flight retrieval overlaps that never touched the failed dispatch."""
    import finchat_tpu.engine.scheduler as sched_mod

    sched = _stack(params, mixed=False)
    rng = np.random.default_rng(11)
    prefix = rng.integers(1, CONFIG.vocab_size, size=40).tolist()
    full = prefix + rng.integers(1, CONFIG.vocab_size, size=12).tolist()
    samp = SamplingParams(temperature=0.0, max_new_tokens=5)

    real = sched_mod.prefill_step
    state = {"armed": False, "fired": False}

    def flaky(*args, **kwargs):
        if state["armed"]:
            state["armed"] = False
            state["fired"] = True
            raise RuntimeError("injected whole-round failure")
        return real(*args, **kwargs)

    monkeypatch.setattr(sched_mod, "prefill_step", flaky)

    async def go():
        await sched.start()
        try:
            hold = await sched.submit_partial("hold", prefix, samp)
            assert hold is not None
            t0 = time.perf_counter()
            while hold.prefill_pos < len(hold.prompt_ids):
                assert time.perf_counter() - t0 < 60
                await asyncio.sleep(0.01)
            assert hold.held and hold in sched.prefilling

            # now fail the NEXT whole round (the victim's dispatch)
            state["armed"] = True
            victim = await sched.submit("victim", full[:20], samp)
            victim_tokens = []
            await asyncio.wait_for(_drain(victim, victim_tokens), timeout=60)
            assert state["fired"]
            # the victim rode the failed round but was preempted and
            # replayed — its stream completed anyway
            assert len(victim_tokens) == 5 and victim.preempted == 1

            # the parked hold survived the failed round UNTOUCHED (its
            # prefilled prefix KV intact — it was not preempted)
            assert not hold.finished and hold in sched.prefilling and hold.held
            assert hold.preempted == 0
            assert hold.prefill_pos >= len(hold.prompt_ids)

            # ...and still completes after its graft
            assert sched.extend_prompt(hold, full)
            tokens = []
            await _drain(hold, tokens)
            return tokens
        finally:
            await sched.stop()

    tokens = asyncio.run(go())
    assert len(tokens) == 5
    sched.allocator.check_invariants()
    assert sched.allocator.used_count == 0
