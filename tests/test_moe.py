"""MoE / expert parallelism (SURVEY §2.3 C6).

The MoE block is Mixtral-shaped (top-k routed SwiGLU experts) with expert
weights sharded over the mesh's ``expert`` axis — GSPMD turns the
expert-sum into a psum over EP shards. These tests pin routing semantics,
EP-sharded == unsharded parity, engine serving with an MoE config, and a
learning EPxDPxTP train step.
"""

import jax
import jax.numpy as jnp
import numpy as np

from finchat_tpu.models.llama import (
    PRESETS,
    LlamaConfig,
    forward,
    init_params,
    make_causal_attention,
    moe_mlp,
)
from finchat_tpu.parallel.mesh import MeshSpec, build_mesh
from finchat_tpu.parallel.sharding import llama_param_shardings, shard_params


def _moe_cfg(**kw):
    base = dict(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=64, max_seq_len=32, n_experts=4, top_k_experts=2,
        dtype=jnp.float32,
    )
    base.update(kw)
    return LlamaConfig(**base)


def test_moe_mlp_matches_per_token_reference():
    """moe_mlp == a per-token numpy reference that routes each token to its
    top-k experts, renormalizes the selected logits, and sums the selected
    experts' SwiGLU outputs (Mixtral semantics). Catches regressions in the
    actual implementation, not a re-derivation of it."""
    config = _moe_cfg()
    params = init_params(config, jax.random.key(0))
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"])
    B, S = 2, 8
    h = jax.random.normal(jax.random.key(1), (B, S, config.dim), jnp.float32)

    out = np.asarray(moe_mlp(h, lp, config))

    hn = np.asarray(h, np.float64)
    router = np.asarray(lp["router"], np.float64)
    Wg = np.asarray(lp["moe_gate"], np.float64)
    Wu = np.asarray(lp["moe_up"], np.float64)
    Wd = np.asarray(lp["moe_down"], np.float64)
    ref = np.zeros_like(hn)
    k = config.top_k_experts
    for b in range(B):
        for s in range(S):
            x = hn[b, s]
            logits = x @ router
            top = np.argsort(-logits)[:k]  # exactly k experts
            sel = np.exp(logits[top] - logits[top].max())
            weights = sel / sel.sum()
            for e, w in zip(top, weights):
                gate = x @ Wg[e]
                up = x @ Wu[e]
                act = gate / (1 + np.exp(-gate)) * up  # silu * up
                ref[b, s] += w * (act @ Wd[e])
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_moe_selects_exactly_k_even_on_ties():
    """Tied router logits must not over-select: gates come from top_k
    INDICES, so exactly top_k experts carry weight."""
    config = _moe_cfg()
    params = init_params(config, jax.random.key(0))
    lp = dict(jax.tree_util.tree_map(lambda a: a[0], params["layers"]))
    # zero router -> ALL logits tie at 0 for every token
    lp["router"] = jnp.zeros_like(lp["router"])
    h = jax.random.normal(jax.random.key(2), (1, 4, config.dim), jnp.float32)
    out = moe_mlp(h, lp, config)
    assert bool(jnp.isfinite(out).all())
    # reconstruct gates the way moe_mlp does to assert the exact-k property
    r = jnp.zeros((1, 4, config.n_experts), jnp.float32)
    top_vals, top_idx = jax.lax.top_k(r, config.top_k_experts)
    w = jax.nn.softmax(top_vals, axis=-1)
    gates = jnp.einsum("bske,bsk->bse", jax.nn.one_hot(top_idx, config.n_experts), w)
    np.testing.assert_array_equal(np.asarray((gates > 0).sum(-1)), config.top_k_experts)
    np.testing.assert_allclose(np.asarray(gates.sum(-1)), 1.0, atol=1e-6)


def test_moe_forward_and_engine_serving():
    """moe-tiny preset serves through the full engine path (prefill +
    paged decode), producing valid greedy tokens."""
    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = PRESETS["moe-tiny"]
    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8
    )
    params = init_params(config, jax.random.key(0))
    eng = InferenceEngine(config, params, engine_cfg, attn_backend="ref")
    alloc = PageAllocator(engine_cfg.num_pages)
    prompt = [3, 7, 11, 200, 42, 9]
    pages = alloc.allocate("s", pages_needed(len(prompt) + 4, 8))
    eng.set_page_table_row(0, pages)
    logits = eng.prefill(0, prompt)
    eng.state, tok = commit_first_token(
        eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
    )
    out = [int(tok)]
    active = jnp.zeros((2,), bool).at[0].set(True)
    z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
    for _ in range(3):
        out.append(int(eng.decode(active, z, o, zk)[0]))
    assert all(0 <= t < config.vocab_size for t in out), out


def test_moe_ep_sharded_matches_unsharded():
    """Expert-parallel placement (expert=2 x model=2 mesh) computes the
    same logits as unsharded (fp32)."""
    config = _moe_cfg()
    params = init_params(config, jax.random.key(0))
    B, S = 2, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, 64)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    ref, _ = forward(params, tokens, positions, config=config,
                     attention=make_causal_attention("ref"))

    mesh = build_mesh(MeshSpec(data=2, seq=1, expert=2, model=2))
    sharded = shard_params(params, llama_param_shardings(mesh))
    got, _ = forward(sharded, tokens, positions, config=config,
                     attention=make_causal_attention("ref"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_moe_train_step_learns_ep_dp_tp():
    from finchat_tpu.train.train_step import (
        init_train_state, make_optimizer, make_train_step,
    )

    config = _moe_cfg(dtype=jnp.bfloat16)
    mesh = build_mesh(MeshSpec(data=2, seq=1, expert=2, model=2))
    params = shard_params(init_params(config, jax.random.key(0)), llama_param_shardings(mesh))
    optimizer = make_optimizer(learning_rate=1e-2)
    step = make_train_step(config, optimizer, mesh)
    state = init_train_state(config, params, optimizer)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(jnp.isfinite(jnp.asarray(losses))), losses
    assert losses[-1] < losses[0], losses


def test_moe_segmented_ring_prefill_matches_monolithic():
    """MoE composes with the chunked SP prefill (r5): a routed-experts
    model prefilled in ring segments (prefix fold over the cached
    earlier segments) must match the one-shot ring prefill — EP + SP +
    TP in the serving prefill path at once."""
    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.utils.config import EngineConfig

    config = PRESETS["moe-tiny"]
    params = init_params(config, jax.random.key(0))
    prompt = list(np.random.RandomState(3).randint(1, 250, size=100))
    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=2, model=2))
    n_new = 5

    def run(ring_chunk):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=64, max_seq_len=256,
            prefill_chunk=16, ring_prefill_min_tokens=16,
            ring_prefill_chunk=ring_chunk,
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        if ring_chunk:
            rc = eng.ring_segment_tokens()
            logits = None
            for start in range(0, len(prompt), rc):
                logits = eng.prefill_ring_segment(0, prompt[start : start + rc], start)
        else:
            logits = eng.prefill_ring(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return np.asarray(logits, np.float32), out

    mono_logits, mono_tokens = run(0)
    seg_logits, seg_tokens = run(32)  # 100 tokens -> 4 segments
    np.testing.assert_allclose(seg_logits, mono_logits, atol=2e-2, rtol=2e-2)
    assert seg_tokens == mono_tokens
