"""Agent graph semantics + the internal streaming event protocol
(reference llm_agent.py:57-79, :202-252)."""

from finchat_tpu.agent.graph import LLMAgent
from finchat_tpu.engine.generator import StubGenerator
from finchat_tpu.io.schemas import ChatMessage

SYSTEM = "You are Penny."
TOOL = "Decide retrieval."


def make_agent(tool_response="No tool call", response_text="Here is my advice.",
               retriever=None, **kwargs):
    async def default_retriever(args):
        return [f"txn for {args['user_id']}"]

    return LLMAgent(
        StubGenerator(default=tool_response),
        StubGenerator(default=response_text),
        retriever or default_retriever,
        SYSTEM, TOOL,
        today=lambda: "2026-07-29",
        **kwargs,
    )


async def test_no_retrieval_path():
    agent = make_agent(tool_response="No tool call")
    result = await agent.query("How should I invest?", "u1", "CTX", [])
    assert result["response"] == "Here is my advice."
    assert result["retrieved_transactions_count"] == 0


async def test_retrieval_path_injects_user_id():
    seen = {}

    async def retriever(args):
        seen.update(args)
        return ["t1", "t2"]

    agent = make_agent(
        tool_response='retrieve_transactions({"search_query": "groceries", "user_id": "attacker"})',
        retriever=retriever,
    )
    result = await agent.query("What did I spend?", "real-user", "CTX", [])
    assert seen["user_id"] == "real-user"  # server-side injection wins (llm_agent.py:119-120)
    assert result["retrieved_transactions_count"] == 2


async def test_retrieval_failure_degrades():
    async def failing(args):
        raise RuntimeError("index down")

    agent = make_agent(
        tool_response='retrieve_transactions({"search_query": "x"})', retriever=failing
    )
    result = await agent.query("spending?", "u1")
    # reference llm_agent.py:129-131: error marker recorded, answer still generated
    assert result["response"] == "Here is my advice."
    assert result["state"].retrieved_transactions == ["Error: index down"]


async def test_stream_event_protocol_with_retrieval():
    agent = make_agent(tool_response='retrieve_transactions({"search_query": "q"})')
    events = [e async for e in agent.stream_with_status("spending?", "u1", "CTX", [])]
    types = [e["type"] for e in events]
    # protocol order (llm_agent.py:206-252)
    assert types[0] == "status" and events[0]["message"] == "Starting query processing..."
    assert "retrieval_complete" in types
    rc = events[types.index("retrieval_complete")]
    assert rc["count"] == 1 and rc["message"] == "Retrieved 1 transactions"
    assert types[-1] == "complete"
    assert events[-1]["message"] == "Query processing completed"
    chunks = [e["content"] for e in events if e["type"] == "response_chunk"]
    assert "".join(chunks) == "Here is my advice."


async def test_stream_event_protocol_without_retrieval():
    agent = make_agent(tool_response="No tool call")
    events = [e async for e in agent.stream_with_status("hello", "u1")]
    messages = [e.get("message") for e in events if e["type"] == "status"]
    assert "No transaction data retrieval needed" in messages
    assert all(e["type"] != "retrieval_complete" for e in events)


async def test_prompt_contains_context_history_and_date():
    tool_stub = StubGenerator(default="No tool call")
    response_stub = StubGenerator(default="ok")

    async def retriever(args):
        return []

    agent = LLMAgent(tool_stub, response_stub, retriever, SYSTEM, TOOL, today=lambda: "2026-07-29")
    history = [ChatMessage(sender="UserMessage", message="earlier question")]
    await agent.query("now?", "u1", "MY CONTEXT BLOCK", history)
    assert "The current date is 2026-07-29" in tool_stub.calls[0]
    assert "MY CONTEXT BLOCK" in tool_stub.calls[0]
    assert "earlier question" in response_stub.calls[0]
    assert SYSTEM in response_stub.calls[0]


async def test_retrieved_data_lands_in_response_prompt():
    response_stub = StubGenerator(default="ok")

    async def retriever(args):
        return ["COFFEE $4", "RENT $2000"]

    agent = LLMAgent(
        StubGenerator(default='retrieve_transactions({"search_query": "x"})'),
        response_stub, retriever, SYSTEM, TOOL,
    )
    await agent.query("spending?", "u1", "CTX")
    prompt = response_stub.calls[0]
    assert "Retrieved Transaction Data:" in prompt
    assert "COFFEE $4" in prompt and "RENT $2000" in prompt


def test_plot_tool_call_renders_chart_and_streams_event():
    """create_financial_plot through the agent: server-side structured
    retrieval feeds the chart; the stream emits a plot event; user_id is
    injected server-side."""
    import asyncio

    from finchat_tpu.agent.graph import LLMAgent
    from finchat_tpu.engine.generator import StubGenerator

    class FakeStructuredRetriever:
        def __init__(self):
            self.calls = []

        async def __call__(self, args):
            return [r["page_content"] for r in await self.structured(args)]

        async def structured(self, args):
            self.calls.append(args)
            return [
                {"page_content": "coffee $4", "amount": 4.0, "date": 1.0, "user_id": args["user_id"]},
                {"page_content": "coffee $5", "amount": 5.0, "date": 2.0, "user_id": args["user_id"]},
            ]

    retriever = FakeStructuredRetriever()
    tool_gen = StubGenerator(
        default='create_financial_plot({"chart_type": "bar", "title": "Coffee", "search_query": "coffee"})'
    )
    agent = LLMAgent(tool_gen, StubGenerator(default="Here is your chart."),
                     retriever, "sys", "tool")

    async def run():
        events = []
        async for ev in agent.stream_with_status("chart my coffee", "u1"):
            events.append(ev)
        return events

    events = asyncio.run(run())
    plot_events = [e for e in events if e["type"] == "plot"]
    assert len(plot_events) == 1
    assert plot_events[0]["data_uri"].startswith("data:image/png;base64,")
    assert retriever.calls[0]["user_id"] == "u1"
    assert retriever.calls[0]["chart_type"] == "bar"
    # batch path carries the chart too
    result = asyncio.run(agent.query("chart my coffee", "u1"))
    assert result["plot_data_uri"].startswith("data:image/png;base64,")
