"""Disaggregated prefill/decode serving + cluster-wide warm-state fabric
(ISSUE 17; serve/disagg.py, engine/warm_fabric.py; ROBUSTNESS.md §6).

The contracts under test:

- ROLE-TYPED POOLS: routing hashes over the SERVING pool only (decode +
  mixed) — prefill replicas never own conversations; an empty serving
  pool falls back to all live replicas with the fallback counted.
- CROSS-POOL HANDOFF: a cold turn prefills on the prefill pool and the
  surviving KV arrives on the serving replica through the EXISTING
  drain-handoff wire format before admission — the stream is
  BYTE-IDENTICAL to a mixed-fleet control and admission resumes
  (resumed_len > 0) instead of cold-prefilling. Bounded-KV entries
  travel with ``kv_gap``/``kv_sink`` intact; a cross-quant-mode snapshot
  is refused AND counted; every fallback leaves the plain local-prefill
  path (clean fallback by contract).
- WARM-STATE FABRIC: one shared disk tier + global index — ANY replica
  resumes ANY conversation warm (fabric hit counted on the restoring
  replica), the shared prompt head prefills ONCE per fleet, and
  route-time deeper-entry-wins is an O(1) index lookup whose migration
  drops only the source's RAM copy (the shared record must survive).
- INGRESS PARITY: HTTP /chat, /chat/stream and the Kafka worker all
  route through the ONE fleet entry (``agent_for``) that performs lazy
  route-time migration — no path can silently serve cold.

fp32 tiny config for the identity contracts (same rationale as
tests/test_mixed_step.py: no bf16 near-tie excuse).
"""

import asyncio
import dataclasses
import json
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.engine.warm_fabric import WarmFabric
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.serve.disagg import (
    FALLBACK_REASONS,
    ROLE_DECODE,
    ROLE_MIXED,
    ROLE_PREFILL,
    parse_roles,
)
from finchat_tpu.serve.fleet import LIVE, OUT, EngineFleet, EngineReplica
from finchat_tpu.utils import faults
from finchat_tpu.utils.config import EngineConfig, FleetConfig
from finchat_tpu.utils.metrics import METRICS

CONFIG = dataclasses.replace(PRESETS["tiny"], dtype=jnp.float32)
CHUNK = 16
PAGE = 8


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.disarm_all()


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _greedy(n: int) -> SamplingParams:
    return SamplingParams(temperature=0.0, max_new_tokens=n)


async def _drain(handle):
    tokens = []
    while True:
        ev = await asyncio.wait_for(handle.events.get(), timeout=120)
        if ev["type"] == "token":
            tokens.append(ev["token_id"])
        elif ev["type"] == "done":
            return tokens, None
        else:
            return tokens, ev


def _make_replica(rid, params, *, role=ROLE_MIXED, fabric=None,
                  **cfg_overrides) -> EngineReplica:
    defaults = dict(
        max_seqs=3, page_size=PAGE, num_pages=64, max_seq_len=256,
        prefill_chunk=CHUNK, session_cache=True,
        session_cache_bytes=16 << 20, breaker_max_rebuilds=1,
    )
    defaults.update(cfg_overrides)
    engine = InferenceEngine(CONFIG, params, EngineConfig(**defaults))
    sched = ContinuousBatchingScheduler(
        engine, eos_id=-1, metrics=METRICS.labeled(replica=rid),
        replica_id=rid, fabric=fabric,
    )
    return EngineReplica(replica_id=rid, scheduler=sched, role=role)


def _make_fleet(roles, params, *, fabric=None, **cfg_overrides) -> EngineFleet:
    reps = [_make_replica(str(i), params, role=role, fabric=fabric,
                          **cfg_overrides)
            for i, role in enumerate(roles)]
    return EngineFleet(
        reps,
        FleetConfig(replicas=len(reps), respawn_backoff_seconds=0.05,
                    supervisor_interval_seconds=0.05),
        num_partitions=16,
    )


def _serving(fleet: EngineFleet) -> EngineReplica:
    return next(r for r in fleet.replicas if r.role != ROLE_PREFILL)


def _get(name: str, rid: str, **labels) -> float:
    return METRICS.get(name, {"replica": rid, **labels})


# --- role parsing + routing (pure; no engines) -----------------------------

def test_parse_roles_contract():
    assert parse_roles("", 3) == [ROLE_MIXED] * 3
    assert parse_roles("prefill,decode", 4) == [
        ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED, ROLE_MIXED]
    assert parse_roles(" Prefill , decode , decode , mixed , mixed ", 3) == [
        ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE]
    with pytest.raises(ValueError):
        parse_roles("prefill,bogus", 2)
    # all-prefill would leave nothing to serve: loud demotion to mixed
    assert parse_roles("prefill,prefill", 2) == [ROLE_MIXED] * 2


def _stub_replica(rid: str, role: str) -> EngineReplica:
    sched = types.SimpleNamespace(on_give_up=[], session_cache=None,
                                  metrics=METRICS.labeled(replica=rid))
    return EngineReplica(replica_id=rid, scheduler=sched, role=role)


def _stub_fleet(roles) -> EngineFleet:
    return EngineFleet(
        [_stub_replica(str(i), r) for i, r in enumerate(roles)],
        FleetConfig(replicas=len(roles), respawn=False),
        num_partitions=32,
    )


def test_routing_excludes_prefill_pool_and_seeds_metrics():
    """Conversations route over the serving pool only; the role gauge and
    every fallback-reason series are pre-seeded per replica (R5: the
    quiet state is scrapeable before the first handoff)."""
    fleet = _stub_fleet([ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE, ROLE_MIXED])
    assert fleet.disagg is not None
    # coordinator attached only to SERVING schedulers (no recursion)
    assert getattr(fleet.replicas[0].scheduler, "disagg", None) is None
    for rep in fleet.replicas[1:]:
        assert rep.scheduler.disagg is fleet.disagg
    for conv in (f"conv-{i}" for i in range(200)):
        assert fleet.replica_for(conv).role != ROLE_PREFILL
    assert _get("finchat_disagg_role", "0") == 1
    assert _get("finchat_disagg_role", "1") == 2
    assert _get("finchat_disagg_role", "3") == 0
    text = METRICS.render_prometheus()
    for rid in ("0", "1", "2", "3"):
        for reason in FALLBACK_REASONS:
            assert (f'finchat_disagg_fallbacks_total{{reason="{reason}",'
                    f'replica="{rid}"}}') in text  # seeded, scrapeable


def test_empty_serving_pool_falls_back_to_prefill_and_counts():
    """Every decode replica down: the prefill replica absorbs routed
    traffic (serving beats shedding) and each absorbed message counts a
    ``serving_pool_empty`` fallback on it."""
    fleet = _stub_fleet([ROLE_PREFILL, ROLE_DECODE])
    before = _get("finchat_disagg_fallbacks_total", "0",
                  reason="serving_pool_empty")
    fleet.replicas[1].state = OUT
    rep = fleet.replica_for("conv-x")
    assert rep is fleet.replicas[0] and rep.role == ROLE_PREFILL
    assert _get("finchat_disagg_fallbacks_total", "0",
                reason="serving_pool_empty") == before + 1


def test_empty_prefill_pool_counts_fallback_and_serves(params):
    """The prefill pool going OUT degrades to exactly mixed serving: the
    cold turn prefills locally (counted no_prefill_replica), completes,
    and is byte-identical to never having had a pool."""
    prompt = list(range(1, 41))

    async def run():
        fleet = _make_fleet([ROLE_PREFILL, ROLE_MIXED], params)
        await fleet.start()
        try:
            serving = _serving(fleet)
            fleet.replicas[0].state = OUT
            before = _get("finchat_disagg_fallbacks_total",
                          serving.replica_id, reason="no_prefill_replica")
            h = await serving.scheduler.submit(
                "t1", prompt, _greedy(6), conversation_id="conv-np")
            toks, err = await _drain(h)
            assert err is None
            assert _get("finchat_disagg_fallbacks_total", serving.replica_id,
                        reason="no_prefill_replica") == before + 1
            return toks
        finally:
            await fleet.stop()

    async def control():
        fleet = _make_fleet([ROLE_MIXED, ROLE_MIXED], params)
        await fleet.start()
        try:
            h = await fleet.replicas[0].scheduler.submit(
                "t1", prompt, _greedy(6), conversation_id="conv-np2")
            toks, err = await _drain(h)
            assert err is None
            return toks
        finally:
            await fleet.stop()

    assert asyncio.run(run()) == asyncio.run(control())


# --- cross-pool handoff ----------------------------------------------------

def test_cold_turn_handoff_byte_identity_and_warm_resume(params):
    """THE tentpole identity: a cold turn submitted to the serving
    replica prefills on the PREFILL replica, the KV crosses pools over
    the drain-handoff wire format, admission resumes from it
    (resumed_len > 0), and the stream is byte-identical to a mixed-fleet
    control. The source's copy is discarded after the handoff."""
    prompt = list(range(1, 41))  # residue 39 >= one chunk: handoff engages

    async def run(roles) -> dict:
        fleet = _make_fleet(roles, params)
        await fleet.start()
        try:
            serving = _serving(fleet)
            rid = serving.replica_id
            h0 = _get("finchat_disagg_handoffs_total", rid)
            h = await serving.scheduler.submit(
                "t1", prompt, _greedy(8), conversation_id="conv-h")
            toks, err = await _drain(h)
            assert err is None
            out = {
                "tokens": toks,
                "resumed": h.resumed_len,
                "handoffs": _get("finchat_disagg_handoffs_total", rid) - h0,
            }
            if roles[0] == ROLE_PREFILL:
                # source copy discarded — a stale twin could serve
                # diverged KV if the conversation ever re-handed-off
                src = fleet.replicas[0].scheduler.session_cache
                out["source_clean"] = src.get("conv-h") is None
            for rep in fleet.replicas:
                rep.scheduler.allocator.check_invariants()
            return out
        finally:
            await fleet.stop()

    disagg = asyncio.run(run([ROLE_PREFILL, ROLE_DECODE]))
    mixed = asyncio.run(run([ROLE_MIXED, ROLE_MIXED]))
    assert disagg["tokens"] == mixed["tokens"]  # byte-identical across pools
    assert disagg["handoffs"] == 1 and mixed["handoffs"] == 0
    assert disagg["resumed"] > 0  # admission resumed from the handed KV
    assert disagg["source_clean"]
    # the handoff detour was timed
    assert METRICS.snapshot().get(
        'finchat_disagg_handoff_seconds{replica="1"}_count', 0) >= 1


def test_warm_turn_skips_the_handoff(params):
    """A second turn whose residue is under one prefill chunk must NOT
    detour through the prefill pool — the handoff is for cold work
    only (its KV is already home)."""

    async def run():
        fleet = _make_fleet([ROLE_PREFILL, ROLE_DECODE], params)
        await fleet.start()
        try:
            serving = _serving(fleet)
            rid = serving.replica_id
            prompt = list(range(1, 41))
            h = await serving.scheduler.submit(
                "t1", prompt, _greedy(8), conversation_id="conv-w")
            t1, err = await _drain(h)
            assert err is None
            h1 = _get("finchat_disagg_handoffs_total", rid)
            # turn 2: history + a short tail — residue < CHUNK
            h2 = await serving.scheduler.submit(
                "t2", prompt + t1 + [5, 6, 7], _greedy(4),
                conversation_id="conv-w")
            _t2, err = await _drain(h2)
            assert err is None
            assert h2.resumed_len > 0
            assert _get("finchat_disagg_handoffs_total", rid) == h1
        finally:
            await fleet.stop()

    asyncio.run(run())


def test_bounded_kv_gapped_handoff(params):
    """A prompt past the bounded budget evicts DURING the prefill pass:
    the handed-off entry travels with its ``kv_gap``/``kv_sink`` and the
    serving replica's stream equals the mixed bounded control."""
    bounded = dict(kv_sink_pages=1, kv_window_pages=4, num_pages=128)
    prompt = list(range(1, 57))  # 56 tokens > 40-token bounded budget

    async def run(roles) -> dict:
        fleet = _make_fleet(roles, params, **bounded)
        await fleet.start()
        try:
            serving = _serving(fleet)
            h0 = _get("finchat_disagg_handoffs_total", serving.replica_id)
            h = await serving.scheduler.submit(
                "t1", prompt, _greedy(8), conversation_id="conv-b")
            toks, err = await _drain(h)
            assert err is None
            entry = serving.scheduler.session_cache.get("conv-b")
            assert entry is not None and entry.kv_gap > 0
            assert entry.kv_sink is not None
            for rep in fleet.replicas:
                rep.scheduler.allocator.check_invariants()
            return {
                "tokens": toks,
                "handoffs": _get("finchat_disagg_handoffs_total",
                                 serving.replica_id) - h0,
            }
        finally:
            await fleet.stop()

    disagg = asyncio.run(run([ROLE_PREFILL, ROLE_DECODE]))
    mixed = asyncio.run(run([ROLE_MIXED, ROLE_MIXED]))
    assert disagg["handoffs"] == 1
    assert disagg["tokens"] == mixed["tokens"]


def test_crossmode_handoff_refused_and_counted(params):
    """Prefill pool serving int8 KV, decode pool fp32: the exported
    snapshot is refused at import (value-casting it would be garbage
    KV), BOTH counters fire (the quant dequant-fallback gate and the
    disagg import_refused fallback), and the turn completes on the
    local-prefill path byte-identical to a mixed fp32 control."""
    prompt = list(range(1, 41))

    async def run() -> dict:
        reps = [
            _make_replica("0", params, role=ROLE_PREFILL, kv_quant="int8"),
            _make_replica("1", params, role=ROLE_DECODE),
        ]
        fleet = EngineFleet(
            reps, FleetConfig(replicas=2, respawn=False), num_partitions=16)
        await fleet.start()
        try:
            serving = reps[1]
            q0 = _get("finchat_quant_dequant_fallbacks_total", "1")
            f0 = _get("finchat_disagg_fallbacks_total", "1",
                      reason="import_refused")
            h = await serving.scheduler.submit(
                "t1", prompt, _greedy(6), conversation_id="conv-q")
            toks, err = await _drain(h)
            assert err is None
            assert _get("finchat_quant_dequant_fallbacks_total", "1") == q0 + 1
            assert _get("finchat_disagg_fallbacks_total", "1",
                        reason="import_refused") == f0 + 1
            assert serving.scheduler.session_cache.get("conv-q") is not None
            return {"tokens": toks}
        finally:
            await fleet.stop()

    async def control() -> dict:
        fleet = _make_fleet([ROLE_MIXED, ROLE_MIXED], params)
        await fleet.start()
        try:
            h = await fleet.replicas[1].scheduler.submit(
                "t1", prompt, _greedy(6), conversation_id="conv-q2")
            toks, err = await _drain(h)
            assert err is None
            return {"tokens": toks}
        finally:
            await fleet.stop()

    assert asyncio.run(run())["tokens"] == asyncio.run(control())["tokens"]


def test_prefill_pass_error_falls_back_to_local_prefill(params):
    """A fault inside the prefill pass (the pass's own sequence evicted
    with an error) leaves the serving replica on the plain local-prefill
    path: fallback counted, stream completes byte-identical."""
    prompt = list(range(1, 41))

    def wedge(seq_id="", **_ctx):
        if seq_id.startswith("__disagg_"):
            raise RuntimeError("drill: prefill pool fault")

    async def run(fault: bool) -> dict:
        fleet = _make_fleet([ROLE_PREFILL, ROLE_DECODE], params)
        await fleet.start()
        try:
            if fault:
                faults.arm("scheduler.prefill", wedge)
            serving = _serving(fleet)
            e0 = _get("finchat_disagg_fallbacks_total", serving.replica_id,
                      reason="prefill_error")
            h = await serving.scheduler.submit(
                "t1", prompt, _greedy(6), conversation_id="conv-e")
            toks, err = await _drain(h)
            assert err is None
            de = _get("finchat_disagg_fallbacks_total", serving.replica_id,
                      reason="prefill_error") - e0
            for rep in fleet.replicas:
                rep.scheduler.allocator.check_invariants()
            return {"tokens": toks, "errors": de}
        finally:
            await fleet.stop()
            faults.disarm_all()

    clean = asyncio.run(run(False))
    chaos = asyncio.run(run(True))
    assert chaos["errors"] == 1 and clean["errors"] == 0
    assert chaos["tokens"] == clean["tokens"]


def test_handoff_then_decode_breaker_trip_drains_clean(params):
    """The handed-off KV must survive a decode-pool breaker trip racing
    the turn: the decode replica imports the handoff, wedges on its
    first decode round, trips, and the drain hands the stream (with its
    session bytes) to the OTHER decode replica — the client sees the
    byte-identical stream, zero errors, zero leaks."""
    prompt = list(range(1, 41))

    async def run(fault: bool) -> dict:
        fleet = _make_fleet([ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE], params)
        await fleet.start()
        try:
            victim = _serving(fleet)
            if fault:
                dead = [True]

                def wedge(**ctx):
                    if dead[0] and ctx.get("replica") == victim.replica_id:
                        raise RuntimeError("drill: decode pool trip")

                faults.arm("scheduler.decode", wedge)
                faults.arm("engine.rebuild", wedge)
            h0 = _get("finchat_disagg_handoffs_total", victim.replica_id)
            d0 = METRICS.get("finchat_fleet_drained_streams_total")
            h = await victim.scheduler.submit(
                "t1", prompt, _greedy(8), conversation_id="conv-t")
            toks, err = await _drain(h)
            assert err is None
            out = {
                "tokens": toks,
                "handoffs": _get("finchat_disagg_handoffs_total",
                                 victim.replica_id) - h0,
                "drained": METRICS.get(
                    "finchat_fleet_drained_streams_total") - d0,
            }
            if fault:
                for rep in fleet.replicas:
                    if rep is not victim:
                        rep.scheduler.allocator.check_invariants()
            return out
        finally:
            await fleet.stop()
            faults.disarm_all()

    clean = asyncio.run(run(False))
    chaos = asyncio.run(run(True))
    assert clean["handoffs"] == 1 and chaos["handoffs"] == 1
    assert chaos["tokens"] == clean["tokens"]
    assert chaos["drained"] >= 1  # the trip really raced the turn


# --- warm-state fabric -----------------------------------------------------

def _fabric_sched(rid, params, fabric, **cfg_overrides):
    return _make_replica(rid, params, fabric=fabric,
                         **cfg_overrides).scheduler


def test_fabric_session_restore_on_never_seen_replica(params, tmp_path):
    """A conversation retired on replica A resumes WARM on replica B —
    which never saw it — through the fabric's shared tier: fabric hit
    counted on B, resumed_len > 0, and the stream byte-identical to the
    same turn run where the conversation lived."""
    prompt1 = list(range(1, 41))

    async def turn(sched, seq, prompt, conv):
        await sched.start()
        try:
            h = await sched.submit(seq, prompt, _greedy(8),
                                   conversation_id=conv)
            toks, err = await _drain(h)
            assert err is None
            return toks, h.resumed_len
        finally:
            await sched.stop()

    def scenario(dirname, rids):
        fabric = WarmFabric(str(tmp_path / dirname), 32 << 20)
        try:
            a = _fabric_sched(rids[0], params, fabric)
            t1, _ = asyncio.run(turn(a, "t1", prompt1, "conv-f"))
            fabric.flush()
            b = a if rids[1] == rids[0] else _fabric_sched(rids[1], params,
                                                           fabric)
            if b is not a:
                # B starts genuinely cold in RAM — the record must come
                # off the shared tier
                assert b.session_cache.get("conv-f") is None
            prompt2 = prompt1 + t1 + [9, 10, 11]
            hits0 = _get("finchat_fabric_hits_total", rids[1])
            t2, resumed = asyncio.run(turn(b, "t2", prompt2, "conv-f"))
            return {
                "t2": t2, "resumed": resumed,
                "hits": _get("finchat_fabric_hits_total", rids[1]) - hits0,
            }
        finally:
            fabric.close()

    stay = scenario("fab-stay", ("fa", "fa"))
    moved = scenario("fab-move", ("fb", "fc"))
    assert moved["t2"] == stay["t2"]
    assert moved["resumed"] > 0 and moved["resumed"] == stay["resumed"]
    assert moved["hits"] == 1


def test_fabric_head_prefills_once_per_fleet(params, tmp_path):
    """The shared prompt head is prefilled by the FIRST replica to
    register it; every later replica restores the published snapshot
    with one H2D scatter — its engine.prefill is never called — and
    serves streams byte-identical to the prefilling replica's."""
    fabric = WarmFabric(str(tmp_path / "fab-head"), 32 << 20)
    head = list(range(1, 49))  # 48 tokens: 6 whole pages
    prompt = head + list(range(60, 72))

    async def gen(sched, seq):
        await sched.start()
        try:
            h = await sched.submit(seq, prompt, _greedy(8))
            toks, err = await _drain(h)
            assert err is None
            return toks
        finally:
            await sched.stop()

    try:
        a = _fabric_sched("ha", params, fabric)
        misses0 = _get("finchat_fabric_misses_total", "ha")
        assert a.register_prefix(head) == 48  # cold: local prefill + publish
        assert _get("finchat_fabric_misses_total", "ha") == misses0 + 1
        fabric.flush()

        b = _fabric_sched("hb", params, fabric)
        real_prefill = b.engine.prefill
        calls = []
        b.engine.prefill = lambda *a_, **k: (calls.append(1),
                                             real_prefill(*a_, **k))[1]
        hits0 = _get("finchat_fabric_hits_total", "hb")
        assert b.register_prefix(head) == 48  # fabric hit: no prefill
        assert calls == []
        assert _get("finchat_fabric_hits_total", "hb") == hits0 + 1
        assert METRICS.snapshot().get(
            'finchat_fabric_restore_seconds{replica="hb"}_count', 0) >= 1

        ta = asyncio.run(gen(a, "ga"))
        tb = asyncio.run(gen(b, "gb"))
        assert ta == tb  # the restored head KV is the prefilled head KV
    finally:
        fabric.close()


def test_fabric_crossmode_head_refused(params, tmp_path):
    """A head snapshot published by an int8-KV engine is refused by an
    fp32 replica (counted) — it prefills locally instead of scattering a
    value-cast snapshot."""
    fabric = WarmFabric(str(tmp_path / "fab-x"), 32 << 20, kv_quant="int8")
    head = list(range(1, 25))
    try:
        a = _fabric_sched("xa", params, fabric, kv_quant="int8")
        assert a.register_prefix(head) == 24
        fabric.flush()
        b = _fabric_sched("xb", params, fabric)
        r0 = _get("finchat_fabric_import_refused_total", "xb")
        assert b.register_prefix(head) == 24  # still registers, locally
        assert _get("finchat_fabric_import_refused_total", "xb") == r0 + 1
    finally:
        fabric.close()


def test_fabric_migration_is_index_lookup_and_keeps_shared_record(params,
                                                                  tmp_path):
    """Route-time deeper-entry-wins over the fabric: the router asks the
    GLOBAL index who holds the conversation (O(1), no pairwise scan),
    moves the RAM entry, and — the shared-tier discipline — drops only
    the source's RAM copy, so the record both replicas share survives
    the migration."""
    fabric = WarmFabric(str(tmp_path / "fab-mig"), 32 << 20)

    async def run():
        reps = [EngineReplica(replica_id=rid,
                              scheduler=_fabric_sched(rid, params, fabric),
                              role=ROLE_MIXED)
                for rid in ("0", "1")]
        fleet = EngineFleet(
            reps, FleetConfig(replicas=2, respawn=False), num_partitions=16)
        await fleet.start()
        try:
            conv = "conv-m"
            home = fleet.replica_for(conv)
            other = next(r for r in reps if r is not home)
            prompt = list(range(1, 41))
            h = await home.scheduler.submit(
                "t1", prompt, _greedy(8), conversation_id=conv)
            t1, err = await _drain(h)
            assert err is None
            assert fabric.holder(conv)[0] == home.replica_id
            m0 = METRICS.get("finchat_fleet_session_migrations_total")
            home.state = OUT
            rep2 = fleet.replica_for(conv)
            assert rep2 is other
            assert METRICS.get(
                "finchat_fleet_session_migrations_total") == m0 + 1
            # RAM moved; index follows the bytes
            assert home.scheduler.session_cache.get(conv) is None
            assert rep2.scheduler.session_cache.get(conv) is not None
            assert fabric.holder(conv)[0] == rep2.replica_id
            # THE shared-tier contract: the migration did not delete the
            # record both replicas back onto
            fabric.flush()
            assert conv in fabric.tier
            h2 = await rep2.scheduler.submit(
                "t2", prompt + t1 + [3, 4], _greedy(4), conversation_id=conv)
            _t2, err = await _drain(h2)
            assert err is None
            assert h2.resumed_len > 0
        finally:
            await fleet.stop()

    asyncio.run(run())


# --- ingress parity (HTTP /chat, /chat/stream, Kafka) ----------------------

def test_all_ingress_paths_route_through_fleet_agent_for():
    """HTTP /chat, /chat/stream and the Kafka worker all fetch their
    agent through fleet.agent_for — the ONE entry that performs lazy
    route-time session migration — with the BARE conversation id. A
    path reaching the agent any other way would serve migrated
    conversations cold (the regression this pins)."""
    from finchat_tpu.engine.generator import StubGenerator
    from finchat_tpu.io.kafka import (
        InMemoryBroker, KafkaClient, Message,
    )
    from finchat_tpu.io.store import InMemoryStore
    from finchat_tpu.serve.app import build_app
    from finchat_tpu.serve.http import Request
    from finchat_tpu.utils.config import USER_MESSAGE_TOPIC, load_config

    cfg = load_config(overrides={"model.preset": "stub"})
    store = InMemoryStore()
    store.upsert_context("c1", {"user_id": "u9", "name": "Alex",
                                "income": 5000, "savings_goal": 800})
    store.add_user_message("c1", "How am I doing?", "u9")
    broker = InMemoryBroker()
    app = build_app(
        cfg, store=store, kafka=KafkaClient(cfg.kafka, broker=broker),
        tool_generator=StubGenerator(default="No tool call"),
        response_generator=StubGenerator(default="Hi.", chunk_delay=0.001),
    )

    calls: list[str] = []
    real_agent = app.agent

    class RecordingFleet:
        replicas: list = []

        def agent_for(self, conversation_id):
            calls.append(conversation_id)
            return real_agent

    app.fleet = RecordingFleet()
    payload = {"message": "How am I doing?", "conversation_id": "c1",
               "user_id": "u9"}
    body = json.dumps(payload).encode()

    async def drive():
        resp = await app.chat(Request("POST", "/chat", {}, body))
        assert resp.status == 200
        stream = await app.chat_stream(
            Request("POST", "/chat/stream", {}, body))
        async for _chunk in stream.chunks:
            pass
        await app.process_message(
            Message(USER_MESSAGE_TOPIC, "c1", body))

    asyncio.run(drive())
    # one routed lookup per ingress path, always the bare conversation id
    assert calls == ["c1", "c1", "c1"]
