"""Config tree: env compatibility with reference config.py + overrides."""

import json

from finchat_tpu.utils.config import load_config


def test_defaults():
    cfg = load_config()
    assert cfg.kafka.backend == "memory"
    assert cfg.engine.temperature == 0.5  # parity with llm_agent.py:37,44
    assert cfg.engine.watchdog_seconds == 100.0  # parity with main.py:138
    assert cfg.vector.default_limit == 10_000  # parity with qdrant_tool.py:145


def test_reference_env_names(monkeypatch):
    # The reference's .env drops in unchanged (config.py:8-47)
    monkeypatch.setenv("KAFKA_SERVER", "broker:9092")
    monkeypatch.setenv("KAFKA_USERNAME", "u")
    monkeypatch.setenv("KAFKA_PASSWORD", "p")
    monkeypatch.setenv("MONGODB_URI", "mongodb://x")
    monkeypatch.setenv("QDRANT_URL", "http://q")
    cfg = load_config()
    assert cfg.kafka.bootstrap_servers == "broker:9092"
    assert cfg.store.mongodb_uri == "mongodb://x"
    assert cfg.vector.url == "http://q"
    rendered = cfg.kafka.librdkafka_config()
    assert rendered["security.protocol"] == "SASL_SSL"
    assert rendered["sasl.mechanisms"] == "PLAIN"


def test_plaintext_switch(monkeypatch):
    monkeypatch.delenv("KAFKA_USERNAME", raising=False)
    monkeypatch.delenv("KAFKA_PASSWORD", raising=False)
    cfg = load_config()
    assert cfg.kafka.librdkafka_config()["security.protocol"] == "PLAINTEXT"


def test_unknown_override_key_rejected():
    import pytest

    with pytest.raises(KeyError):
        load_config(overrides={"engine.max_seq": 4})  # typo for max_seqs


def test_file_and_override_precedence(tmp_path):
    cfile = tmp_path / "cfg.json"
    cfile.write_text(json.dumps({"engine.max_seqs": 8, "model": {"preset": "llama3-8b"}}))
    cfg = load_config(str(cfile), overrides={"engine.max_seqs": 16})
    assert cfg.engine.max_seqs == 16  # explicit override wins
    assert cfg.model.preset == "llama3-8b"


def test_engine_env_readers(monkeypatch):
    from finchat_tpu.utils.config import load_config

    monkeypatch.setenv("FINCHAT_WARMUP", "0")
    monkeypatch.setenv("FINCHAT_RING_PREFILL_MIN", "2048")
    monkeypatch.setenv("FINCHAT_DECODE_LOOP_DEPTH", "4")
    cfg = load_config()
    assert cfg.engine.warmup_on_start is False
    assert cfg.engine.ring_prefill_min_tokens == 2048
    assert cfg.engine.decode_loop_depth == 4

    monkeypatch.delenv("FINCHAT_DECODE_LOOP_DEPTH")
    assert load_config().engine.decode_loop_depth == 1  # per-token default

    monkeypatch.setenv("FINCHAT_WARMUP", "1")
    cfg = load_config()
    assert cfg.engine.warmup_on_start is True


def test_mixed_step_and_compilation_cache_env_readers(monkeypatch):
    from finchat_tpu.utils.config import load_config

    cfg = load_config()
    assert cfg.engine.mixed_step is True  # default on for the chunked path
    assert cfg.engine.compilation_cache_dir == ""  # default off

    monkeypatch.setenv("FINCHAT_MIXED_STEP", "0")
    monkeypatch.setenv("FINCHAT_COMPILATION_CACHE_DIR", "/tmp/finchat-xla-cache")
    cfg = load_config()
    assert cfg.engine.mixed_step is False
    assert cfg.engine.compilation_cache_dir == "/tmp/finchat-xla-cache"


def test_tool_streaming_and_hold_ttl_env_readers(monkeypatch):
    from finchat_tpu.utils.config import load_config

    cfg = load_config()
    assert cfg.engine.tool_streaming is True  # default on (ISSUE 9)
    assert cfg.engine.partial_hold_ttl_seconds == 30.0  # legacy HOLD_TTL_S

    monkeypatch.setenv("FINCHAT_TOOL_STREAMING", "0")
    monkeypatch.setenv("FINCHAT_PARTIAL_HOLD_TTL_SECONDS", "2.5")
    cfg = load_config()
    assert cfg.engine.tool_streaming is False
    assert cfg.engine.partial_hold_ttl_seconds == 2.5
