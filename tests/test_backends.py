"""Production-backend contract tests against mocks (verdict r3 weak #6).

The confluent-kafka and pymongo paths are deployment-only (the CI image may
lack the services), but their CONTRACTS — librdkafka config rendering, the
produce/poll/flush call sequences, Mongo read/write shapes and error
mapping — are pinned here with fakes, so ``pragma: no cover`` shrinks to
the import guards.
"""

from __future__ import annotations

import json
import types

import pytest

import finchat_tpu.io.kafka as kafka_mod
import finchat_tpu.io.store as store_mod
from finchat_tpu.io.kafka import KafkaClient
from finchat_tpu.io.store import AI_SENDER, MongoStore
from finchat_tpu.utils.config import GROUP_ID, KafkaConfig, StoreConfig


# --------------------------------------------------------------------------
# librdkafka config rendering (reference config.py:15-23)
# --------------------------------------------------------------------------


def test_librdkafka_config_sasl_switch():
    plain = KafkaConfig(bootstrap_servers="broker:9092")
    assert plain.librdkafka_config() == {
        "bootstrap.servers": "broker:9092",
        "security.protocol": "PLAINTEXT",
    }

    sasl = KafkaConfig(bootstrap_servers="broker:9092", username="u", password="p")
    cfg = sasl.librdkafka_config()
    assert cfg["security.protocol"] == "SASL_SSL"
    assert cfg["sasl.mechanisms"] == "PLAIN"
    assert cfg["sasl.username"] == "u"
    assert cfg["sasl.password"] == "p"


def test_librdkafka_config_requires_both_credentials():
    # username without password (or vice versa) must NOT half-enable SASL
    for kwargs in ({"username": "u"}, {"password": "p"}):
        cfg = KafkaConfig(bootstrap_servers="b", **kwargs).librdkafka_config()
        assert cfg["security.protocol"] == "PLAINTEXT"
        assert "sasl.username" not in cfg


# --------------------------------------------------------------------------
# confluent KafkaClient path (faked confluent_kafka module)
# --------------------------------------------------------------------------


class _FakeKafkaMessage:
    def __init__(self, value: bytes, error=None):
        self._value = value
        self._error = error

    def value(self):
        return self._value

    def error(self):
        return self._error


class _FakeProducer:
    def __init__(self, config):
        self.config = config
        self.produced: list[tuple[str, str, bytes]] = []
        self.polls = 0
        self.flushes = 0

    def produce(self, topic, key=None, value=None):
        self.produced.append((topic, key, value))

    def poll(self, timeout):
        self.polls += 1

    def flush(self):
        self.flushes += 1


class _FakeConsumer:
    def __init__(self, config):
        self.config = config
        self.subscribed: list[str] = []
        self.queue: list[_FakeKafkaMessage] = []
        self.closed = False

    def subscribe(self, topics):
        self.subscribed = list(topics)

    def poll(self, timeout):
        return self.queue.pop(0) if self.queue else None

    def close(self):
        self.closed = True


@pytest.fixture
def confluent_client(monkeypatch):
    fake_module = types.SimpleNamespace(Producer=_FakeProducer, Consumer=_FakeConsumer)
    monkeypatch.setattr(kafka_mod, "confluent_kafka", fake_module)
    monkeypatch.setattr(kafka_mod, "HAVE_CONFLUENT", True)
    cfg = KafkaConfig(bootstrap_servers="broker:9092", username="u", password="p",
                      backend="confluent")
    return KafkaClient(cfg)


def test_confluent_producer_built_with_rendered_config(confluent_client):
    assert confluent_client._broker is None
    assert confluent_client._producer.config["security.protocol"] == "SASL_SSL"


def test_confluent_consumer_setup_contract(confluent_client):
    confluent_client.setup_consumer(["user_message"])
    consumer = confluent_client._consumer
    assert consumer.subscribed == ["user_message"]
    assert consumer.config["group.id"] == GROUP_ID
    assert consumer.config["auto.offset.reset"] == "latest"
    assert consumer.config["session.timeout.ms"] == "45000"  # kafka_client.py:15


def test_confluent_poll_paths(confluent_client):
    # not initialized -> None with an error log, no crash
    assert confluent_client.poll_message() is None

    confluent_client.setup_consumer(["user_message"])
    assert confluent_client.poll_message() is None  # empty queue

    good = _FakeKafkaMessage(b'{"message": "hi"}')
    bad = _FakeKafkaMessage(b"", error="broker down")
    confluent_client._consumer.queue = [bad, good]
    assert confluent_client.poll_message() is None  # errored record dropped
    assert confluent_client.poll_message() is good


def test_confluent_produce_qos_split(confluent_client):
    """Normal chunks fire-and-forget (produce + poll(0)); error messages
    flush — the reference's delivery-guarantee split (kafka_client.py:24-40)."""
    confluent_client.produce_message("ai_response", "conv1", {"message": "tok"})
    prod = confluent_client._producer
    assert prod.polls == 1 and prod.flushes == 0
    topic, key, payload = prod.produced[-1]
    assert (topic, key) == ("ai_response", "conv1")
    assert json.loads(payload) == {"message": "tok"}

    confluent_client.produce_error_message("ai_response", "conv1", {"error": True})
    assert prod.flushes == 1


def test_confluent_close_contract(confluent_client):
    confluent_client.setup_consumer()
    confluent_client.close()
    assert confluent_client._consumer.closed
    assert confluent_client._producer.flushes == 1


# --------------------------------------------------------------------------
# MongoStore path (faked pymongo client)
# --------------------------------------------------------------------------


class _FakeCursor:
    def __init__(self, rows):
        self._rows = rows

    def sort(self, field, direction):
        return sorted(self._rows, key=lambda r: r[field], reverse=direction < 0)


class _FakeCollection:
    def __init__(self):
        self.rows: list[dict] = []

    def find_one(self, query):
        for row in self.rows:
            if all(row.get(k) == v for k, v in query.items()):
                return row
        return None

    def find(self, query):
        return _FakeCursor([r for r in self.rows
                            if all(r.get(k) == v for k, v in query.items())])

    def insert_one(self, doc):
        self.rows.append(dict(doc))


class _FakeAdmin:
    def __init__(self, fail=False):
        self.fail = fail

    def command(self, name):
        if self.fail:
            raise ConnectionError("no mongod")
        return {"ok": 1}


class _FakeMongoClient:
    def __init__(self, uri, tls=None, tlsCAFile=None):
        self.uri = uri
        self.admin = _FakeAdmin()
        self._dbs: dict[str, dict[str, _FakeCollection]] = {}

    def __getitem__(self, name):
        db = self._dbs.setdefault(name, {})

        class _DB:
            def __getitem__(_self, coll):
                return db.setdefault(coll, _FakeCollection())

        return _DB()


@pytest.fixture
def mongo_store(monkeypatch):
    fake_pymongo = types.SimpleNamespace(MongoClient=_FakeMongoClient)
    fake_certifi = types.SimpleNamespace(where=lambda: "/fake/ca.pem")
    monkeypatch.setattr(store_mod, "pymongo", fake_pymongo)
    monkeypatch.setattr(store_mod, "certifi", fake_certifi, raising=False)
    monkeypatch.setattr(store_mod, "HAVE_PYMONGO", True)
    return MongoStore(StoreConfig(mongodb_uri="mongodb://fake", backend="mongo"))


async def test_mongo_check_connection(mongo_store):
    await mongo_store.check_connection()  # ok path
    mongo_store._client.admin.fail = True
    with pytest.raises(RuntimeError, match="MongoDB connection failed"):
        await mongo_store.check_connection()


async def test_mongo_get_context_contract(mongo_store):
    with pytest.raises(LookupError):
        await mongo_store.get_context("conv1")
    mongo_store._contexts.insert_one({
        "conversation_id": "conv1", "user_id": "u1", "name": "Ada",
        "income": 90000, "savings_goal": 10000,
    })
    context, user_id = await mongo_store.get_context("conv1")
    assert user_id == "u1"
    assert "Ada" in context

    # context without user_id is a hard error (reference database.py behavior)
    mongo_store._contexts.insert_one({"conversation_id": "conv2", "name": "X"})
    with pytest.raises(LookupError, match="user_id"):
        await mongo_store.get_context("conv2")


async def test_mongo_history_sorted_and_empty_raises(mongo_store):
    with pytest.raises(LookupError):  # database.py:78-79 raise-on-empty
        await mongo_store.get_history("conv1")
    for ts, text in [(30, "third"), (10, "first"), (20, "second")]:
        mongo_store._messages.insert_one({
            "conversation_id": "conv1", "sender": "UserMessage",
            "user_id": "u1", "message": text, "timestamp": ts,
        })
    history = await mongo_store.get_history("conv1")
    assert [m.message for m in history] == ["first", "second", "third"]


async def test_mongo_save_ai_message(mongo_store):
    await mongo_store.save_ai_message("conv1", "answer text", "u1")
    rows = mongo_store._messages.rows
    assert len(rows) == 1
    assert rows[0]["sender"] == AI_SENDER
    assert rows[0]["message"] == "answer text"
    assert rows[0]["user_id"] == "u1"
    assert isinstance(rows[0]["timestamp"], int)
