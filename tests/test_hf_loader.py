"""Real-checkpoint correctness (SURVEY §4.5, VERDICT r1 task 3).

A tiny HF-format Llama checkpoint (config.json + model.safetensors) is
written by torch/transformers, loaded through ``load_llama_params``, and the
jax stack is checked against the INDEPENDENT torch implementation:

- pytree layout (transpose/stack) equals hand-stacked expectations;
- full-sequence logits match transformers' LlamaForCausalLM in fp32;
- greedy decode through the paged InferenceEngine (chunked prefill + paged
  decode) reproduces torch's greedy continuation exactly — the golden
  token-id test;
- the tied-embedding branch (TinyLlama/Llama-3.2 style, hf_loader.py) and
  the config cross-check both behave.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from safetensors.numpy import save_file  # noqa: E402

from finchat_tpu.checkpoints.hf_loader import load_llama_params  # noqa: E402
from finchat_tpu.models.llama import LlamaConfig, forward_full  # noqa: E402

HF_CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    intermediate_size=96,
    max_position_embeddings=256,
    rope_theta=10_000.0,
    rms_norm_eps=1e-5,
)

OUR_CFG = LlamaConfig(
    vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
    hidden_dim=96, rope_theta=10_000.0, norm_eps=1e-5, max_seq_len=256,
    dtype=jnp.float32,
)


def _write_checkpoint(path, tied: bool):
    """Build a seeded torch Llama and save it in HF checkpoint format."""
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(7 if tied else 11)
    model = LlamaForCausalLM(
        HFLlamaConfig(**HF_CFG, tie_word_embeddings=tied, attn_implementation="eager")
    )
    model.eval()
    tensors = {
        k: v.detach().to(torch.float32).numpy().copy()
        for k, v in model.state_dict().items()
    }
    if tied:
        # tied checkpoints ship without lm_head (hf_loader.py handles it)
        tensors.pop("lm_head.weight", None)
    save_file(tensors, str(path / "model.safetensors"))
    (path / "config.json").write_text(
        json.dumps({**HF_CFG, "model_type": "llama",
                    "architectures": ["LlamaForCausalLM"],
                    "tie_word_embeddings": tied})
    )
    return model, tensors


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    path = tmp_path_factory.mktemp("hf_ckpt")
    model, tensors = _write_checkpoint(path, tied=False)
    return path, model, tensors


def test_loader_layout_matches_hand_stacking(checkpoint):
    path, _, tensors = checkpoint
    params = load_llama_params(str(path), OUR_CFG)

    np.testing.assert_array_equal(
        np.asarray(params["embed"]), tensors["model.embed_tokens.weight"]
    )
    np.testing.assert_array_equal(
        np.asarray(params["lm_head"]), tensors["lm_head.weight"].T
    )
    expect_q = np.stack([
        tensors[f"model.layers.{i}.self_attn.q_proj.weight"].T for i in range(2)
    ])
    np.testing.assert_array_equal(np.asarray(params["layers"]["attn_q"]), expect_q)
    expect_ln = np.stack([
        tensors[f"model.layers.{i}.input_layernorm.weight"] for i in range(2)
    ])
    np.testing.assert_array_equal(np.asarray(params["layers"]["ln_attn"]), expect_ln)


def test_logits_parity_with_transformers(checkpoint):
    path, model, _ = checkpoint
    params = load_llama_params(str(path), OUR_CFG)

    ids = np.array([[1, 5, 9, 42, 99, 17, 3, 64]], np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(ids.astype(np.int64))).logits.numpy()

    positions = np.arange(ids.shape[1], dtype=np.int32)[None, :]
    ours = np.asarray(
        forward_full(params, jnp.asarray(ids), jnp.asarray(positions),
                     config=OUR_CFG, attn_backend="ref")
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_golden_greedy_decode_through_paged_engine(checkpoint):
    """Greedy continuation through chunked prefill + paged decode equals
    torch's greedy loop token-for-token (exact ids, SURVEY §4.5)."""
    import jax

    from finchat_tpu.engine.engine import InferenceEngine
    from finchat_tpu.utils.config import EngineConfig

    path, model, _ = checkpoint
    params = load_llama_params(str(path), OUR_CFG)

    prompt = [1, 5, 9, 42, 99]
    n_new = 12

    # torch golden: greedy argmax loop
    golden = []
    ids = torch.tensor([prompt], dtype=torch.long)
    with torch.no_grad():
        for _ in range(n_new):
            nxt = int(model(ids).logits[0, -1].argmax())
            golden.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)

    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=4
    )
    engine = InferenceEngine(OUR_CFG, params, engine_cfg, attn_backend="ref")
    engine.set_page_table_row(0, list(range(1, 9)))
    logits = engine.prefill(0, prompt)
    first = int(jnp.argmax(logits))
    engine.set_last_token(0, first)
    got = [first]
    active = jnp.asarray([True, False])
    zeros = jnp.zeros((2,), jnp.float32)
    topk = jnp.zeros((2,), jnp.int32)
    for _ in range(n_new - 1):
        toks = engine.decode(active, zeros, jnp.ones((2,), jnp.float32), topk)
        got.append(int(np.asarray(toks)[0]))
    assert got == golden, (got, golden)


def test_tied_embedding_checkpoint(tmp_path):
    model, tensors = _write_checkpoint(tmp_path, tied=True)
    assert "lm_head.weight" not in tensors
    params = load_llama_params(str(tmp_path), OUR_CFG)
    np.testing.assert_array_equal(
        np.asarray(params["lm_head"]), tensors["model.embed_tokens.weight"].T
    )

    ids = np.array([[2, 40, 77]], np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
    positions = np.arange(ids.shape[1], dtype=np.int32)[None, :]
    ours = np.asarray(
        forward_full(params, jnp.asarray(ids), jnp.asarray(positions),
                     config=OUR_CFG, attn_backend="ref")
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_quantized_load_matches_load_then_quantize(checkpoint):
    """Per-tensor int8 loading (what lets 8B checkpoints onto one chip)
    must equal quantizing a full-precision load."""
    import jax
    import numpy as np

    from finchat_tpu.models.quant import QTensor, quantize_llama_params

    path, _, _ = checkpoint
    streamed = load_llama_params(str(path), OUR_CFG, quant="int8")
    full = quantize_llama_params(load_llama_params(str(path), OUR_CFG))
    assert isinstance(streamed["layers"]["attn_q"], QTensor)
    flat_s, tree_s = jax.tree_util.tree_flatten(streamed)
    flat_f, tree_f = jax.tree_util.tree_flatten(full)
    assert tree_s == tree_f
    for a, b in zip(flat_s, flat_f):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_config_mismatch_raises(checkpoint):
    path, _, _ = checkpoint
    wrong = LlamaConfig(
        vocab_size=128, dim=64, n_layers=3, n_heads=4, n_kv_heads=2,
        hidden_dim=96, dtype=jnp.float32,
    )
    with pytest.raises(ValueError, match="num_hidden_layers"):
        load_llama_params(str(path), wrong)
