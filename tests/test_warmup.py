"""Startup warmup: the first-request path must not compile anything new.

Verdict r3 weak #4/#5: the first real request used to pay full prefill +
decode XLA compilation inside the 100 s watchdog, and the first tool
decision compiled the ``return_logits=True`` decode variant mid-stream.
``InferenceEngine.warmup()`` closes both; these tests pin it.
"""

import jax
import jax.numpy as jnp
import numpy as np

from finchat_tpu.engine.engine import (
    InferenceEngine,
    commit_first_token,
    decode_loop_step,
    decode_step,
    prefill_step,
    ragged_mixed_step,
    verify_step,
)
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.utils.config import EngineConfig


def _tiny_engine(max_seqs=2, spec_tokens=0, decode_loop_depth=1):
    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8,
        spec_tokens=spec_tokens, decode_loop_depth=decode_loop_depth,
    )
    params = init_params(config, jax.random.key(0))
    return InferenceEngine(config, params, engine_cfg, attn_backend="ref")


def test_warmup_is_state_neutral():
    eng = _tiny_engine()
    eng.warmup()
    assert np.asarray(eng.state.context_lens).tolist() == [0, 0]
    assert np.asarray(eng.state.page_table).sum() == 0


def test_first_request_path_compiles_nothing_after_warmup():
    eng = _tiny_engine()
    eng.warmup()
    sizes = {
        "prefill": prefill_step._cache_size(),
        "decode": decode_step._cache_size(),
        "commit": commit_first_token._cache_size(),
    }

    # a real first request: admit, prefill (2 chunks), commit, decode with
    # BOTH variants (the return_logits=True one is the tool-decision path)
    alloc = PageAllocator(eng.engine_cfg.num_pages)
    prompt = [3, 7, 11, 200, 42, 9, 13, 55, 21, 8]
    pages = alloc.allocate("s", pages_needed(len(prompt) + 4, eng.page_size))
    eng.set_page_table_row(0, pages)
    logits = eng.prefill(0, prompt)
    eng.state, _ = commit_first_token(
        eng.state, jnp.int32(0), logits,
        jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
    )
    B = eng.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    zeros, ones, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    eng.decode(active, zeros, ones, zk)
    eng.decode(active, zeros, ones, zk, return_logits=True)

    assert prefill_step._cache_size() == sizes["prefill"], "first prefill recompiled"
    assert decode_step._cache_size() == sizes["decode"], "first decode recompiled"
    assert commit_first_token._cache_size() == sizes["commit"], "commit recompiled"


def test_warmup_covers_spec_verify_variants():
    """With spec_tokens > 0 the scheduler's verify path (both return_logits
    variants) must be compiled at startup, not on the first drafted step."""
    eng = _tiny_engine(spec_tokens=2)
    eng.warmup()
    before = verify_step._cache_size()

    B = eng.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    drafts = jnp.zeros((B, 2), jnp.int32)
    n_drafts = jnp.zeros((B,), jnp.int32).at[0].set(2)
    zeros, ones, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    alloc = PageAllocator(eng.engine_cfg.num_pages)
    # 3 prompt tokens + two verify steps that can each commit spec+1 = 3
    pages = alloc.allocate("s", pages_needed(3 + 2 * 3, eng.page_size))
    eng.set_page_table_row(0, pages)
    eng.prefill(0, [3, 7, 11])
    eng.decode_spec(active, drafts, n_drafts, zeros, ones, zk)
    eng.decode_spec(active, drafts, n_drafts, zeros, ones, zk, return_logits=True)

    assert verify_step._cache_size() == before, "first verify step recompiled"


def test_warmup_covers_decode_loop_variant():
    """With decode_loop_depth > 1 the scheduler's fused K-token block
    (decode_loop_step) must be compiled at startup — and the eos_id being a
    runtime scalar (not a jit cache key) means one warmed variant covers
    every eos value the scheduler can pass."""
    eng = _tiny_engine(decode_loop_depth=4)
    eng.warmup()
    before = decode_loop_step._cache_size()

    B = eng.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    zeros, ones, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    alloc = PageAllocator(eng.engine_cfg.num_pages)
    # 3 prompt tokens + one block of 4 appends
    pages = alloc.allocate("s", pages_needed(3 + 4, eng.page_size))
    eng.set_page_table_row(0, pages)
    eng.prefill(0, [3, 7, 11])
    eng.decode_loop(active, zeros, ones, zk, eos_id=-1)
    eng.decode_loop(active, zeros, ones, zk, eos_id=7)  # different eos id

    assert decode_loop_step._cache_size() == before, "first block recompiled"
    # state-neutrality of the warmup block itself is covered by
    # test_warmup_is_state_neutral running depth 1; check the depth>1 path
    eng2 = _tiny_engine(decode_loop_depth=4)
    eng2.warmup()
    assert np.asarray(eng2.state.context_lens).tolist() == [0, 0]
    assert np.asarray(eng2.state.page_table).sum() == 0


def test_warmup_covers_ragged_step_variants():
    """With mixed_step on (the default) every packed-token bucket of the
    scheduler's unified ragged dispatch must be compiled at startup — the
    first admission-during-decode must not compile. One bucket axis
    replaces PR 4's row-bucket x chunk-bucket matrix, and spec/loop/
    constrained rows reuse the same variants (ISSUE 10)."""
    eng = _tiny_engine(spec_tokens=2, decode_loop_depth=3)
    eng.warmup()
    before = ragged_mixed_step._cache_size()
    assert before > 0, "warmup compiled no ragged variants"
    assert eng.compiled_variants > 0

    B = eng.engine_cfg.max_seqs  # == 2: row 0 prefill, row 1 spec decode
    R = B
    zB = jnp.zeros((B,), jnp.float32)
    loop_active = jnp.zeros((B,), bool).at[1].set(True)
    for t in eng.ragged_token_buckets():
        # a serving-shaped round: a 3-token prefill row plus a spec verify
        # row with one draft riding a loop tail slot — every feature mix
        # reuses the SAME compiled variant as the all-padding warmup shape
        toks = [5, 6, 7, 0, 9] + [0] * (t - 5)
        tok_row = [0, 0, 0, 1, 1] + [R] * (t - 5)
        eng.ragged_mixed(
            jnp.asarray(toks, jnp.int32), jnp.asarray(tok_row, jnp.int32),
            jnp.asarray([0, 1], jnp.int32),  # row slots
            jnp.zeros((R,), jnp.int32),  # row_start
            jnp.asarray([3, 2], jnp.int32),  # row_len
            jnp.asarray([False, True]),  # from_device
            jnp.asarray([False, True]),  # arm
            jnp.asarray([0, 1], jnp.int32),  # n_drafts
            jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
            jnp.zeros((R,), jnp.int32),
            loop_active, zB, jnp.ones((B,), jnp.float32),
            jnp.zeros((B,), jnp.int32), -1,
        )
    assert ragged_mixed_step._cache_size() == before, (
        "first ragged dispatch recompiled")
    # state-neutrality with the ragged variants included
    eng2 = _tiny_engine()
    eng2.warmup()
    assert np.asarray(eng2.state.context_lens).tolist() == [0, 0]
    assert np.asarray(eng2.state.page_table).sum() == 0


def test_warmup_covers_freerun_capture_variants():
    """With freerun_rounds > 1 the captured multi-round program
    (ragged_multi_round) is warmed for every packed-token bucket — the
    first free-run capture on the serving path must not compile (one
    extra bucket axis at the fixed rounds depth, ISSUE 13)."""
    from finchat_tpu.engine.engine import ragged_multi_round

    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=64,
        prefill_chunk=8, decode_loop_depth=2, freerun_rounds=3,
    )
    params = init_params(config, jax.random.key(0))
    eng = InferenceEngine(config, params, engine_cfg, attn_backend="ref")
    eng.warmup()
    before = ragged_multi_round._cache_size()
    assert before > 0, "warmup compiled no freerun variants"

    B = R = 2
    F = 3
    zB = jnp.zeros((B,), jnp.float32)
    for t in eng.ragged_token_buckets():
        # a serving-shaped capture: one decode row riding a fused tail
        # every round — reuses the all-padding warmup variant
        tok_row = np.full((F, t), R, np.int32)
        tok_row[:, 0] = 0
        ones = np.ones((F, R), np.int32)
        ones[:, 1] = 0
        live = np.zeros((F, R), bool)
        live[:, 0] = True
        loop = np.zeros((F, B), bool)
        loop[:, 0] = True
        eng.ragged_multi(
            jnp.zeros((F, t), jnp.int32), jnp.asarray(tok_row),
            jnp.asarray([0, 1], jnp.int32), jnp.zeros((F, R), jnp.int32),
            jnp.asarray(ones), jnp.asarray(live), jnp.asarray(live),
            jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
            jnp.zeros((R,), jnp.int32),
            jnp.asarray(loop), zB, jnp.ones((B,), jnp.float32),
            jnp.zeros((B,), jnp.int32), -1,
        )
    assert ragged_multi_round._cache_size() == before, (
        "first freerun capture recompiled")


def test_ragged_bucket_matrix_collapsed():
    """The compiled-variant accounting the warmup gauge reports: the
    ragged bucket list is ONE pow-2 axis whose length never exceeds the
    old row x chunk matrix, and the top bucket covers the worst-case
    packed round (every slot a full chunk)."""
    eng = _tiny_engine()
    buckets = eng.ragged_token_buckets()
    cfg = eng.engine_cfg
    assert buckets == sorted(set(buckets))
    assert buckets[-1] >= cfg.max_seqs * cfg.prefill_chunk
    # old matrix: pow-2 row buckets (log2(max_seqs)+1) x 2 chunk buckets
    import math

    old_matrix = (int(math.log2(1 << (cfg.max_seqs - 1).bit_length())) + 1) * 2
    assert len(buckets) <= max(old_matrix, 1)


def test_warmup_covers_non_power_of_two_max_seqs():
    """The scheduler pads a prefill round to the NEXT power of two, which
    for a non-power-of-two max_seqs exceeds it — warmup must cover that
    largest variant too."""
    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=3, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8
    )
    eng = InferenceEngine(
        config, init_params(config, jax.random.key(0)), engine_cfg, attn_backend="ref"
    )
    before = prefill_step._cache_size()
    eng.warmup()
    compiled = prefill_step._cache_size() - before
    assert compiled == 3  # N = 1, 2, 4 — includes the 4-row padding variant
