"""Pipelined-decode scheduler semantics.

The scheduler dispatches decode step N+1 before consuming step N (depth-2
pipeline) and fetches device results in worker threads. These tests pin the
host-visible contract: exact token counts (no speculative-token leaks),
safe cancel while a step is in flight, allocator invariants after churn,
and the request spans (queue→prefill→first-token→done) the serving path
records — SURVEY §5.1/§7.3."""

import asyncio

import jax
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.generator import EngineGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig


def _make_stack(max_seqs: int = 4):
    tok = ByteTokenizer()
    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=128, max_seq_len=128, prefill_chunk=16
    )
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, engine_cfg)
    scheduler = ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)
    return tok, scheduler, EngineGenerator(scheduler, tok)


def test_exact_token_counts_under_pipelining():
    """Each sequence gets exactly max_new_tokens token events (unless EOS):
    the speculative step dispatched after a sequence finishes must never
    leak an extra token into its stream."""

    async def run():
        tok, scheduler, _ = _make_stack()
        await scheduler.start()
        try:
            budgets = [3, 7, 12]
            handles = []
            for i, n in enumerate(budgets):
                handles.append(await scheduler.submit(
                    f"s{i}", tok.encode(f"prompt {i}", add_bos=True),
                    SamplingParams(temperature=0.8, max_new_tokens=n),
                ))
            counts = []
            for handle in handles:
                n_tokens = 0
                while True:
                    event = await asyncio.wait_for(handle.events.get(), timeout=60)
                    if event["type"] == "token":
                        n_tokens += 1
                    elif event["type"] == "done":
                        # stream must be fully drained at the terminal event
                        assert handle.events.empty()
                        break
                    else:
                        raise AssertionError(event)
                counts.append(n_tokens)
            return budgets, counts
        finally:
            await scheduler.stop()

    budgets, counts = asyncio.run(run())
    for budget, count in zip(budgets, counts):
        assert count <= budget
        # random tiny-model weights over the byte vocab essentially never
        # emit EOS, so the count should be the full budget
        assert count == budget, (budgets, counts)


def test_release_restores_non_truncating_slot_defaults():
    """A freed slot must not keep a dead request's top_p/top_k: the
    sampler's exact full-vocab fast path keys on ALL slots' params
    (sampler.py), so one finished truncating request would otherwise
    silently degrade every later batch to candidate-set truncation."""

    async def run():
        tok, scheduler, _ = _make_stack()
        await scheduler.start()
        try:
            handle = await scheduler.submit(
                "trunc", tok.encode("hello", add_bos=True),
                SamplingParams(temperature=0.9, top_p=0.5, top_k=4, max_new_tokens=3),
            )
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=60)
                if event["type"] == "done":
                    break
            slot_params = (
                float(scheduler._temperature.max()),
                float(scheduler._top_p.min()),
                int(scheduler._top_k.max()),
            )
            return slot_params
        finally:
            await scheduler.stop()

    temperature, top_p, top_k = asyncio.run(run())
    assert temperature == 0.0 and top_p == 1.0 and top_k == 0


def test_cancel_while_step_in_flight_is_safe():
    """Cancelling mid-decode frees the slot/pages while a speculative step
    referencing the old slot is still in flight; the survivor completes and
    allocator invariants hold."""

    async def run():
        tok, scheduler, _ = _make_stack(max_seqs=2)
        await scheduler.start()
        try:
            victim = await scheduler.submit(
                "victim", tok.encode("victim", add_bos=True),
                SamplingParams(temperature=0.5, max_new_tokens=64),
            )
            survivor = await scheduler.submit(
                "survivor", tok.encode("survivor", add_bos=True),
                SamplingParams(temperature=0.5, max_new_tokens=10),
            )
            # wait for the victim's first token so it is decoding, then cancel
            event = await asyncio.wait_for(victim.events.get(), timeout=60)
            assert event["type"] == "token"
            scheduler.cancel(victim)

            survivor_tokens = 0
            while True:
                event = await asyncio.wait_for(survivor.events.get(), timeout=60)
                if event["type"] == "token":
                    survivor_tokens += 1
                elif event["type"] == "done":
                    break
                else:
                    raise AssertionError(event)

            # victim's stream ends with its terminal event and nothing after
            terminal = None
            while not victim.events.empty():
                terminal = victim.events.get_nowait()
            assert terminal is not None and terminal["type"] == "done"

            scheduler.allocator.check_invariants()
            assert sorted(scheduler.free_slots) == [0, 1]
            return survivor_tokens
        finally:
            await scheduler.stop()

    assert asyncio.run(run()) == 10


def test_request_spans_recorded():
    """The serving path records queue→prefill→first-token→done spans
    (SURVEY §5.1) on every sequence."""

    async def run():
        tok, scheduler, gen = _make_stack()
        await scheduler.start()
        try:
            handle = await scheduler.submit(
                "spanned", tok.encode("hello", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=4),
            )
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=60)
                if event["type"] != "token":
                    break
            return handle
        finally:
            await scheduler.stop()

    handle = asyncio.run(run())
    marks = handle.span.marks
    for name in ("admitted", "prefill_done", "first_token", "done"):
        assert name in marks, marks
    assert handle.span.ttft() is not None
    assert marks["admitted"] <= marks["prefill_done"] <= marks["first_token"] <= marks["done"]


def test_event_loop_stays_responsive_during_decode():
    """Device fetches run off the event loop: a concurrent heartbeat task
    must keep ticking while a batch decodes (the round-1 design blocked the
    loop on np.asarray every step)."""

    async def run():
        tok, scheduler, _ = _make_stack()
        await scheduler.start()
        ticks = 0
        stop = asyncio.Event()

        async def heartbeat():
            nonlocal ticks
            while not stop.is_set():
                ticks += 1
                await asyncio.sleep(0.005)

        hb = asyncio.create_task(heartbeat())
        try:
            handle = await scheduler.submit(
                "hb", tok.encode("hello there", add_bos=True),
                SamplingParams(temperature=0.5, max_new_tokens=32),
            )
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=120)
                if event["type"] != "token":
                    break
            return ticks
        finally:
            stop.set()
            hb.cancel()
            await scheduler.stop()

    # 32 decode steps of the tiny model take well over 100 ms on CPU; a
    # responsive loop fits many 5 ms heartbeats in that window.
    assert asyncio.run(run()) >= 10


def test_constrained_sequence_does_not_stall_bystanders():
    """While a grammar-constrained sequence is decoding (tool decision), the
    unconstrained streams keep the depth-2 dispatch cadence: the constrained
    slot sits out the speculative steps (it advances every other step), the
    bystander rides every step. The pre-round-4 behavior collapsed the WHOLE
    batch to depth-1 — observable as the constrained slot being active in
    every dispatched step; here it must be excluded from a meaningful share
    (verdict r3 weak #4 / task 6)."""
    import numpy as np

    from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

    async def run():
        tok, scheduler, _ = _make_stack(max_seqs=2)
        vocab = GrammarVocab.for_tokenizer(tok)

        recorded: list[np.ndarray] = []
        real_decode = scheduler.engine.decode

        def spy_decode(active, *args, **kwargs):
            recorded.append(np.asarray(active).copy())
            return real_decode(active, *args, **kwargs)

        scheduler.engine.decode = spy_decode
        await scheduler.start()
        try:
            bystander = await scheduler.submit(
                "bystander", tok.encode("hello", add_bos=True),
                SamplingParams(temperature=0.7, max_new_tokens=48),
            )
            constrained = await scheduler.submit(
                "tool", tok.encode("decide", add_bos=True),
                SamplingParams(temperature=0.7, max_new_tokens=48),
                constraint=TokenConstraint(vocab),
            )
            by_count = tool_count = 0
            terminal = {id(bystander): False, id(constrained): False}
            while not all(terminal.values()):
                progressed = False
                for handle in (bystander, constrained):
                    if terminal[id(handle)]:
                        continue
                    try:
                        event = handle.events.get_nowait()
                    except asyncio.QueueEmpty:
                        continue
                    progressed = True
                    if event["type"] == "token":
                        if handle is bystander:
                            by_count += 1
                        else:
                            tool_count += 1
                    elif event["type"] in ("done", "error"):
                        terminal[id(handle)] = True
                if not progressed:
                    await asyncio.sleep(0.005)
            return bystander, constrained, by_count, tool_count, recorded
        finally:
            await scheduler.stop()

    bystander, constrained, by_count, tool_count, recorded = asyncio.run(run())
    assert by_count == 48, by_count  # bystander got its full budget
    assert tool_count >= 1  # the grammar emitted something before closing

    # steps with BOTH slots active = joint steps (constrained included);
    # steps with exactly ONE active while two seqs were decoding = the
    # speculative steps where the constrained slot sat out and the
    # bystander kept the depth-2 cadence. Pre-fix behavior: every step
    # with the constrained seq in the batch had BOTH slots active
    # (whole-batch depth-1, never excluded).
    joint_idx = [i for i, m in enumerate(recorded) if m.sum() == 2]
    assert joint_idx, "constrained seq never decoded jointly"
    # only count solo steps WHILE the constrained seq was still in the batch
    # (before its last joint step) — solo steps after it finished are just
    # the bystander draining its budget and prove nothing
    solo_during_overlap = sum(
        1 for m in recorded[: joint_idx[-1]] if m.sum() == 1
    )
    assert solo_during_overlap > 0, "no speculative bystander-only steps recorded"


def test_pool_smaller_than_offered_load_serves_in_waves():
    """A KV pool that cannot hold every submitted sequence at once (the
    --kv-budget-gb regime: at the 8B north-star shape, 64 resident
    4k-token sessions would need ~17 GB against a 16 GB chip) must still
    serve ALL sequences to completion via paged admission — excess
    sequences wait for pages, none are dropped or starved."""

    async def run():
        tok = ByteTokenizer()
        config = PRESETS["tiny"]
        # admission reserves pages_needed(prompt + max_new) per sequence
        # (scheduler._admit): ~14 prompt tokens + 50 budget = 64 -> 8
        # pages/seq @ page 8. 18-page pool (17 allocatable past the trash
        # page) holds just 2 resident sequences; submitting 6 forces three
        # admission waves with multiple sequences waiting at once
        engine_cfg = EngineConfig(
            max_seqs=6, page_size=8, num_pages=18, max_seq_len=64,
            prefill_chunk=16,
        )
        params = init_params(config, jax.random.key(0))
        engine = InferenceEngine(config, params, engine_cfg)
        # eos_id=-1: random tiny-model weights DO occasionally sample the
        # byte EOS at temperature>0 (observed: 1 of 6 streams), and this
        # test is about admission waves, not termination — disable EOS so
        # every stream must run its full budget
        scheduler = ContinuousBatchingScheduler(engine, eos_id=-1)
        await scheduler.start()
        try:
            handles = [
                await scheduler.submit(
                    f"w{i}", tok.encode(f"wave prompt {i}", add_bos=True),
                    SamplingParams(temperature=0.8, max_new_tokens=50),
                )
                for i in range(6)
            ]
            counts = []
            for handle in handles:
                n_tokens = 0
                while True:
                    event = await asyncio.wait_for(handle.events.get(), timeout=120)
                    if event["type"] == "token":
                        n_tokens += 1
                    elif event["type"] == "done":
                        break
                    elif event["type"] == "error":
                        raise AssertionError(event)
                counts.append(n_tokens)
            return counts
        finally:
            await scheduler.stop()

    counts = asyncio.run(run())
    assert counts == [50] * 6, counts
