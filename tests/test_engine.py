"""Golden decode tests (SURVEY §4.5): the paged-cache engine must reproduce
the naive full-context forward pass token-for-token, across page boundaries,
chunked prefill, and interleaved multi-sequence decode."""

import jax
import jax.numpy as jnp
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.models.llama import PRESETS, forward_full, init_params
from finchat_tpu.utils.config import EngineConfig

CONFIG = PRESETS["tiny"]

# ONE engine shape for every test in this module → prefill/decode compile
# once per process (jit cache keys on shapes + static args).
ENGINE_CFG = EngineConfig(max_seqs=4, page_size=8, num_pages=64, max_seq_len=128, prefill_chunk=8)


def make_engine(params):
    return InferenceEngine(CONFIG, params, ENGINE_CFG)


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


ORACLE_PAD = 64  # fixed shape so the oracle compiles once


def oracle_greedy(params, prompt, n_new):
    """Naive full-forward greedy decode (the correctness oracle). Padded to
    one fixed shape; causality (test_model.py) guarantees padding after the
    last real token cannot affect its logits."""
    seq = list(prompt)
    out = []
    positions = jnp.arange(ORACLE_PAD)[None]
    for _ in range(n_new):
        tokens = jnp.asarray(seq + [0] * (ORACLE_PAD - len(seq)), jnp.int32)[None]
        logits = forward_full(params, tokens, positions, config=CONFIG)
        nxt = int(jnp.argmax(logits[0, len(seq) - 1]))
        out.append(nxt)
        seq.append(nxt)
    return out


def engine_greedy(eng, alloc, slot, prompt, n_new, seq_id="s"):
    pages = alloc.allocate(seq_id, pages_needed(len(prompt) + n_new, eng.page_size))
    eng.set_page_table_row(slot, pages)
    logits = eng.prefill(slot, prompt)
    eng.state, tok = commit_first_token(
        eng.state, jnp.int32(slot), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
    )
    out = [int(tok)]
    B = eng.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[slot].set(True)
    zeros, ones, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    for _ in range(n_new - 1):
        nxt = eng.decode(active, zeros, ones, zk)
        out.append(int(nxt[slot]))
    return out


def test_engine_matches_oracle_single_chunk(params):
    eng = make_engine(params)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    prompt = [3, 7, 11, 200, 42]
    assert engine_greedy(eng, alloc, 0, prompt, 8) == oracle_greedy(params, prompt, 8)


def test_engine_matches_oracle_multi_chunk_prefill(params):
    """Prompt longer than prefill_chunk exercises chunked prefill reading
    earlier pages while writing new ones."""
    eng = make_engine(params)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    prompt = list(range(1, 28))  # 27 tokens → 4 chunks of 8, crosses pages
    assert engine_greedy(eng, alloc, 1, prompt, 6) == oracle_greedy(params, prompt, 6)


def test_two_sequences_interleaved(params):
    """Two slots decoding in the same batch must not contaminate each other."""
    eng = make_engine(params)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    prompt_a = [5, 9, 2, 250, 17]
    prompt_b = [100, 101, 102]
    n_new = 8

    pages_a = alloc.allocate("a", pages_needed(len(prompt_a) + n_new, 8))
    pages_b = alloc.allocate("b", pages_needed(len(prompt_b) + n_new, 8))
    eng.set_page_table_row(0, pages_a)
    eng.set_page_table_row(2, pages_b)
    logits_a = eng.prefill(0, prompt_a)
    logits_b = eng.prefill(2, prompt_b)
    eng.state, tok_a = commit_first_token(eng.state, jnp.int32(0), logits_a, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0))
    eng.state, tok_b = commit_first_token(eng.state, jnp.int32(2), logits_b, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0))

    got_a, got_b = [int(tok_a)], [int(tok_b)]
    B = 4
    active = jnp.zeros((B,), bool).at[0].set(True).at[2].set(True)
    zeros, ones, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    for _ in range(n_new - 1):
        nxt = eng.decode(active, zeros, ones, zk)
        got_a.append(int(nxt[0]))
        got_b.append(int(nxt[2]))

    assert got_a == oracle_greedy(params, prompt_a, n_new)
    assert got_b == oracle_greedy(params, prompt_b, n_new)


def test_slot_reuse_after_reset(params):
    """Freeing a slot and admitting a new sequence must fully isolate it
    from the previous occupant (per-sequence failure isolation, SURVEY §5.3)."""
    eng = make_engine(params)
    alloc = PageAllocator(ENGINE_CFG.num_pages)
    first = engine_greedy(eng, alloc, 0, [9, 8, 7, 6], 5, seq_id="one")
    alloc.free("one", alloc.owned_by("one"))
    eng.reset_slot(0)
    alloc.check_invariants()
    second = engine_greedy(eng, alloc, 0, [9, 8, 7, 6], 5, seq_id="two")
    assert first == second
