"""Pallas kernels vs jnp reference oracles (SURVEY §4.2).

Runs in interpret mode on the CPU test mesh. Under ``FINCHAT_TESTS_TPU=1``
(see conftest.py) the same matrix runs ON-CHIP with ``interpret=False`` —
Mosaic-lowered kernels asserted against the jnp oracles on real hardware
(benchmarks/pallas_onchip.py records the pass as PALLAS_ONCHIP_r*.json).
On-chip fp32 tolerances are looser because TPU fp32 dots lower to bf16
multi-pass matmuls in both the kernel and the oracle, but not identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.kv_cache import gather_kv, scatter_kv_chunk
from finchat_tpu.ops.flash_attention import flash_attention
from finchat_tpu.ops.paged_attention import paged_flash_attention
from finchat_tpu.ops.refs import mha_reference

INTERPRET = jax.default_backend() != "tpu"
ATOL = RTOL = 2e-5 if INTERPRET else 2e-2


def _rand_qkv(key, B, Sq, Sk, H, Hkv, D, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, Sq, H, D), dtype)
    k = jax.random.normal(kk, (B, Sk, Hkv, D), dtype)
    v = jax.random.normal(kv, (B, Sk, Hkv, D), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "B,Sq,Sk,H,Hkv,D",
    [
        (1, 128, 128, 4, 4, 64),  # MHA, square
        (2, 64, 256, 8, 2, 64),  # GQA, kv longer than q
        (1, 256, 512, 4, 1, 128),  # MQA
    ],
)
def test_flash_matches_reference_causal(B, Sq, Sk, H, Hkv, D):
    q, k, v = _rand_qkv(jax.random.key(0), B, Sq, Sk, H, Hkv, D)
    out = flash_attention(q, k, v, causal=True, interpret=INTERPRET)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)


def test_flash_q_offset_and_kv_len():
    """Chunked-prefill semantics: q chunk sits at an offset inside a padded
    KV axis whose valid length differs per batch element."""
    B, Sq, Sk, H, Hkv, D = 2, 64, 256, 4, 2, 64
    q, k, v = _rand_qkv(jax.random.key(1), B, Sq, Sk, H, Hkv, D)
    q_offset = jnp.array([32, 100], jnp.int32)
    kv_len = jnp.array([96, 164], jnp.int32)  # q_offset + Sq
    out = flash_attention(q, k, v, q_offset=q_offset, kv_len=kv_len, interpret=INTERPRET)
    ref = mha_reference(q, k, v, causal=True, q_offset=q_offset, kv_len=kv_len)
    np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)


def test_flash_non_causal():
    B, Sq, Sk, H, Hkv, D = 1, 128, 128, 4, 4, 64
    q, k, v = _rand_qkv(jax.random.key(2), B, Sq, Sk, H, Hkv, D)
    out = flash_attention(q, k, v, causal=False, interpret=INTERPRET)
    ref = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)


def test_flash_bf16_tolerance():
    B, Sq, Sk, H, Hkv, D = 1, 128, 128, 8, 4, 64
    q, k, v = _rand_qkv(jax.random.key(3), B, Sq, Sk, H, Hkv, D, jnp.bfloat16)
    out = flash_attention(q, k, v, interpret=INTERPRET)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref.astype(jnp.float32), atol=2e-2, rtol=2e-2
    )


# ---------------------------------------------------------------------------
# paged decode/prefill kernel (token-major cache [L, P, PS, Hkv*D])
# ---------------------------------------------------------------------------


def _build_paged_case(key, B, H, Hkv, D, page_size, max_pages, ctx_lens, C,
                      n_layers=2, layer=1):
    """Scatter per-sequence KV into shuffled physical pages of one layer;
    return the paged arrays, the q chunk, and dense KV for the oracle."""
    num_phys = 1 + B * max_pages  # page 0 = trash
    k_pages = jnp.zeros((n_layers, num_phys, page_size, Hkv * D), jnp.float32)
    v_pages = jnp.zeros_like(k_pages)

    # shuffled physical page assignment, like a real allocator under churn
    perm = np.random.RandomState(0).permutation(num_phys - 1) + 1
    page_table = np.zeros((B, max_pages), np.int32)
    dense_max = max_pages * page_size
    k_dense = np.zeros((B, dense_max, Hkv, D), np.float32)
    v_dense = np.zeros_like(k_dense)

    next_phys = 0
    rng = np.random.RandomState(1)
    for b in range(B):
        n_pages = -(-ctx_lens[b] // page_size) if ctx_lens[b] else 0
        for p in range(n_pages):
            page_table[b, p] = perm[next_phys]
            next_phys += 1
        kb = rng.randn(ctx_lens[b], Hkv, D).astype(np.float32)
        vb = rng.randn(ctx_lens[b], Hkv, D).astype(np.float32)
        k_dense[b, : ctx_lens[b]] = kb
        v_dense[b, : ctx_lens[b]] = vb
        for t in range(ctx_lens[b]):
            phys, off = page_table[b, t // page_size], t % page_size
            k_pages = k_pages.at[layer, phys, off].set(kb[t].reshape(-1))
            v_pages = v_pages.at[layer, phys, off].set(vb[t].reshape(-1))

    q = jax.random.normal(key, (B, C, H, D), jnp.float32)
    return q, k_pages, v_pages, jnp.asarray(page_table), jnp.asarray(k_dense), jnp.asarray(v_dense)


def test_paged_decode_matches_reference():
    """C=1 decode: ragged context lengths, shuffled pages, one inactive slot."""
    B, H, Hkv, D, page_size, max_pages = 4, 8, 2, 64, 16, 8
    ctx_lens = [37, 128, 5, 0]  # slot 3 inactive
    q, k_pages, v_pages, page_table, k_dense, v_dense = _build_paged_case(
        jax.random.key(4), B, H, Hkv, D, page_size, max_pages, ctx_lens, C=1
    )
    kv_len = jnp.asarray(ctx_lens, jnp.int32)
    q_offset = jnp.maximum(kv_len - 1, 0)  # decode: q is the last cached token

    out = paged_flash_attention(
        q, k_pages, v_pages, page_table, q_offset, kv_len, jnp.asarray([1]),
        page_size=page_size, n_kv=Hkv, interpret=INTERPRET,
    )
    ref = mha_reference(q, k_dense, v_dense, causal=True, q_offset=q_offset, kv_len=kv_len)
    # inactive slot must be exactly zero (fully masked)
    np.testing.assert_array_equal(np.asarray(out[3]), 0.0)
    np.testing.assert_allclose(out[:3], ref[:3], atol=ATOL, rtol=RTOL)


def test_paged_prefill_chunk_matches_reference():
    """C>1 chunked prefill at an offset: chunk KV already scattered."""
    B, H, Hkv, D, page_size, max_pages = 2, 4, 4, 64, 16, 8
    C = 32
    ctx_lens = [64, 96]  # total cached INCLUDING the current chunk
    q, k_pages, v_pages, page_table, k_dense, v_dense = _build_paged_case(
        jax.random.key(5), B, H, Hkv, D, page_size, max_pages, ctx_lens, C=C
    )
    kv_len = jnp.asarray(ctx_lens, jnp.int32)
    q_offset = kv_len - C

    out = paged_flash_attention(
        q, k_pages, v_pages, page_table, q_offset, kv_len, jnp.asarray([1]),
        page_size=page_size, n_kv=Hkv, interpret=INTERPRET,
    )
    ref = mha_reference(q, k_dense, v_dense, causal=True, q_offset=q_offset, kv_len=kv_len)
    np.testing.assert_allclose(out, ref, atol=ATOL, rtol=RTOL)


def test_paged_kernel_agrees_with_scatter_gather_path():
    """End-to-end consistency with the engine's jnp path: scatter a chunk via
    scatter_kv_chunk, then paged kernel == gather_kv + mha_reference."""
    B, H, Hkv, D, page_size, max_pages = 2, 4, 2, 64, 16, 4
    L = 3
    num_phys = 1 + B * max_pages
    key = jax.random.key(6)
    kk, kv_, kq = jax.random.split(key, 3)

    k_pages = jnp.zeros((L, num_phys, page_size, Hkv * D), jnp.float32)
    v_pages = jnp.zeros_like(k_pages)
    page_table = jnp.asarray(
        [[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32
    )
    C = 16
    start_pos = jnp.array([0, 24], jnp.int32)
    n_valid = jnp.array([16, 9], jnp.int32)
    layer = jnp.int32(2)

    k_new = jax.random.normal(kk, (B, C, Hkv, D), jnp.float32)
    v_new = jax.random.normal(kv_, (B, C, Hkv, D), jnp.float32)
    k_pages, v_pages = scatter_kv_chunk(
        k_pages, v_pages, k_new, v_new, page_table, start_pos, n_valid,
        page_size, layer,
    )

    q = jax.random.normal(kq, (B, C, H, D), jnp.float32)
    kv_len = start_pos + n_valid

    out = paged_flash_attention(
        q, k_pages, v_pages, page_table, start_pos, kv_len, layer[None],
        page_size=page_size, n_kv=Hkv, interpret=INTERPRET,
    )
    k_dense, v_dense = gather_kv(k_pages, v_pages, page_table, page_size, layer, Hkv)
    ref = mha_reference(q, k_dense, v_dense, causal=True, q_offset=start_pos, kv_len=kv_len)
    # rows beyond n_valid are padding; compare valid rows only
    for b in range(B):
        nv = int(n_valid[b])
        np.testing.assert_allclose(out[b, :nv], ref[b, :nv], atol=ATOL, rtol=RTOL)


def test_kv_append_matches_scatter():
    """The in-place decode append kernel == scatter_kv_chunk for C=1, incl.
    the inactive-slot trash redirect and untouched other layers/pages."""
    from finchat_tpu.ops.kv_append import paged_kv_append

    B, Hkv, D, page_size, max_pages, L = 4, 2, 64, 16, 4, 3
    num_phys = 1 + B * max_pages
    rng = np.random.RandomState(7)
    k_pages = jnp.asarray(rng.randn(L, num_phys, page_size, Hkv * D), jnp.float32)
    v_pages = jnp.asarray(rng.randn(L, num_phys, page_size, Hkv * D), jnp.float32)
    page_table = jnp.asarray(
        [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12], [13, 14, 15, 16]], jnp.int32)
    pos = jnp.asarray([13, 37, 0, 63], jnp.int32)
    n_valid = jnp.asarray([1, 1, 0, 1], jnp.int32)
    layer = jnp.asarray([1], jnp.int32)
    k_new = jnp.asarray(rng.randn(B, 1, Hkv, D), jnp.float32)
    v_new = jnp.asarray(rng.randn(B, 1, Hkv, D), jnp.float32)

    want_k, want_v = scatter_kv_chunk(
        k_pages, v_pages, k_new, v_new, page_table, pos, n_valid,
        page_size, jnp.int32(1),
    )

    kv_new = jnp.concatenate(
        [k_new.reshape(B, 1, -1), v_new.reshape(B, 1, -1)], axis=-1)
    got_k, got_v = paged_kv_append(
        kv_new, k_pages, v_pages, page_table, pos, n_valid, layer,
        page_size=page_size, interpret=INTERPRET,
    )
    # trash page contents may differ (scatter drops padding writes there);
    # compare everything but physical page 0
    np.testing.assert_allclose(np.asarray(got_k)[:, 1:], np.asarray(want_k)[:, 1:], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v)[:, 1:], np.asarray(want_v)[:, 1:], rtol=1e-6)


def test_engine_end_to_end_pallas_backend():
    """The engine's chunked prefill + decode must produce identical greedy
    tokens whether attention runs through the jnp reference path or the
    Pallas kernels (interpret mode on the CPU test mesh)."""
    from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
    from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
    from finchat_tpu.models.llama import PRESETS, init_params
    from finchat_tpu.utils.config import EngineConfig

    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=2, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8
    )
    params = init_params(config, jax.random.key(0))
    prompt = [3, 7, 11, 200, 42, 9, 13, 55, 21, 8]  # 2 chunks
    n_new = 6

    def run(backend):
        eng = InferenceEngine(config, params, engine_cfg, attn_backend=backend)
        alloc = PageAllocator(engine_cfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, eng.page_size))
        eng.set_page_table_row(0, pages)
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits,
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
        out = [int(tok)]
        B = engine_cfg.max_seqs
        active = jnp.zeros((B,), bool).at[0].set(True)
        zeros, ones, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, zeros, ones, zk)[0]))
        return out

    assert run("ref") == run("pallas-interpret")


# --- int8-KV (q8) kernels: the on-chip half of ADVICE r4 finding #4 ------
# test_kv_quant.py pins these kernels in interpret mode with tiny shapes;
# these two nodes use TPU-tileable shapes (row width 128 lanes, page 128
# so each page's fp32 scale block [pad8(Hkv)=8, 128] is exactly one tile)
# and follow this file's INTERPRET switch, so the per-test on-chip runner
# (benchmarks/pallas_onchip_split.py) extends Mosaic coverage to the
# quantizing append and int8 paged attention that kv_quant serving uses.

_Q8_HKV, _Q8_HD, _Q8_PAGE = 2, 64, 128


def _q8_cache(n_pages):
    L = 1
    width = _Q8_HKV * _Q8_HD
    k_pages = jnp.zeros((L, n_pages, _Q8_PAGE, width), jnp.int8)
    v_pages = jnp.zeros_like(k_pages)
    sshape = (L, n_pages, 8, _Q8_PAGE)  # pad8(Hkv=2) = 8 scale rows
    return k_pages, v_pages, jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32)


def test_kv_append_q8_matches_scatter():
    """In-place quantizing append kernel == XLA q8 scatter for the same
    tokens: identical int8 rows and scales (interpret), within one int8
    step / fp32 scale tolerance on-chip where Mosaic and XLA may round
    the quantization division differently."""
    from finchat_tpu.engine.kv_cache import scatter_kv_chunk_q8
    from finchat_tpu.ops.kv_append import paged_kv_append_q8

    B = 2
    k_row = jax.random.normal(jax.random.key(3), (B, 1, _Q8_HKV, _Q8_HD), jnp.bfloat16)
    v_row = jax.random.normal(jax.random.key(4), (B, 1, _Q8_HKV, _Q8_HD), jnp.bfloat16)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    pos = jnp.asarray([3, 140], jnp.int32)  # second lands on page 2 of the row
    n_valid = jnp.asarray([1, 1], jnp.int32)
    layer = jnp.zeros((1,), jnp.int32)

    ka, va, ksa, vsa = paged_kv_append_q8(
        jnp.concatenate([k_row.reshape(B, 1, -1), v_row.reshape(B, 1, -1)], axis=-1),
        *_q8_cache(5), page_table, pos, n_valid, layer,
        page_size=_Q8_PAGE, n_kv=_Q8_HKV, interpret=INTERPRET,
    )
    kb, vb, ksb, vsb = scatter_kv_chunk_q8(
        *_q8_cache(5), k_row, v_row, page_table, pos, n_valid,
        _Q8_PAGE, jnp.int32(0), _Q8_HKV,
    )
    if INTERPRET:
        np.testing.assert_array_equal(np.asarray(ka), np.asarray(kb))
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    else:
        np.testing.assert_allclose(
            np.asarray(ka, np.int32), np.asarray(kb, np.int32), atol=1)
        np.testing.assert_allclose(
            np.asarray(va, np.int32), np.asarray(vb, np.int32), atol=1)
    np.testing.assert_allclose(np.asarray(ksa), np.asarray(ksb), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(vsa), np.asarray(vsb), rtol=1e-5)


def test_paged_attention_q8_matches_dequantized_reference():
    """int8 paged attention == mha_reference over the SAME dequantized
    K/V (both sides see identical semantic values; tolerance is fp
    accumulation order only)."""
    from finchat_tpu.engine.kv_cache import gather_kv_q8, scatter_kv_chunk_q8
    from finchat_tpu.ops.dispatch import paged_attention

    B, C, H, T = 2, 1, 4, 200
    kp, vp, ks, vs = scatter_kv_chunk_q8(
        *_q8_cache(5),
        jax.random.normal(jax.random.key(5), (B, T, _Q8_HKV, _Q8_HD), jnp.float32),
        jax.random.normal(jax.random.key(6), (B, T, _Q8_HKV, _Q8_HD), jnp.float32),
        jnp.asarray([[1, 2], [3, 4]], jnp.int32), jnp.zeros((B,), jnp.int32),
        jnp.full((B,), T, jnp.int32), _Q8_PAGE, jnp.int32(0), _Q8_HKV,
    )
    q = jax.random.normal(jax.random.key(7), (B, C, H, _Q8_HD), jnp.float32)
    q_offset = jnp.full((B,), T - 1, jnp.int32)
    kv_len = jnp.full((B,), T, jnp.int32)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)

    got = paged_attention(
        q, kp, vp, page_table, q_offset, kv_len, jnp.zeros((1,), jnp.int32),
        page_size=_Q8_PAGE, n_kv=_Q8_HKV,
        backend="pallas-interpret" if INTERPRET else "pallas",
        k_scales=ks, v_scales=vs,
    )
    k_deq, v_deq = gather_kv_q8(
        kp, vp, ks, vs, page_table, _Q8_PAGE, jnp.int32(0), _Q8_HKV,
        dtype=jnp.float32,
    )
    want = mha_reference(q, k_deq, v_deq, causal=True, q_offset=q_offset, kv_len=kv_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)
