"""History windowing / prompt-length policy (VERDICT r1 task 7).

The reference stuffs unbounded history into the prompt (llm_agent.py:234-236)
with the external API as backstop. Here the engine has a hard KV budget, so
the agent windows the conversation (oldest turns first, then retrieved rows)
and the generator token-splices as a last resort — an over-long conversation
must still answer, never raise."""

import asyncio

import jax
import pytest

from finchat_tpu.agent.graph import LLMAgent
from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.generator import EngineGenerator, StubGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.io.schemas import ChatMessage
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig


def _engine_stack(max_seq_len: int = 256):
    tok = ByteTokenizer()
    config = PRESETS["tiny"]
    engine_cfg = EngineConfig(
        max_seqs=2, page_size=16, num_pages=64,
        max_seq_len=max_seq_len, prefill_chunk=32,
    )
    params = init_params(config, jax.random.key(0))
    engine = InferenceEngine(config, params, engine_cfg)
    scheduler = ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)
    return scheduler, EngineGenerator(scheduler, tok)


class BudgetedStub(StubGenerator):
    """Stub generator that exposes the byte-count budget protocol, so the
    agent's windowing logic is testable without an engine."""

    def __init__(self, budget: int, **kw):
        super().__init__(**kw)
        self._budget = budget

    def count_tokens(self, text: str) -> int:
        return len(text.encode("utf-8")) + 1

    def prompt_budget(self, sampling: SamplingParams) -> int:
        return self._budget


def _agent(gen, **kw):
    return LLMAgent(gen, gen, lambda args: [], "SYSTEM", "TOOLPROMPT", **kw)


def test_windowing_drops_oldest_turns_first():
    gen = BudgetedStub(budget=700, default="No tool call")
    agent = _agent(gen)
    history = [
        ChatMessage(sender="UserMessage", message=f"OLD-TURN-{i} " + "x" * 80)
        for i in range(10)
    ] + [ChatMessage(sender="AIMessage", message="NEWEST-TURN fits")]
    result = asyncio.run(agent.query("current question", "u1", "ctx", history))
    assert result["response"]
    prompt = gen.calls[-1]
    assert "NEWEST-TURN" in prompt  # newest survives
    assert "OLD-TURN-0" not in prompt  # oldest dropped
    assert "current question" in prompt
    assert "SYSTEM" in prompt


def test_windowing_halves_retrieved_rows():
    gen = BudgetedStub(
        budget=600,
        rules=[(lambda p: "TOOLPROMPT" in p, 'retrieve_transactions({"search_query": "x"})')],
        default="here is your answer",
    )
    rows = [f"row-{i}: spent $[{i}] at merchant {'m' * 40}" for i in range(32)]

    async def retriever(args):
        return rows

    agent = LLMAgent(gen, gen, retriever, "SYSTEM", "TOOLPROMPT")
    result = asyncio.run(agent.query("what did I spend?", "u1"))
    assert result["response"] == "here is your answer"
    # retrieval happened but the block was halved down to fit
    assert 0 < result["retrieved_transactions_count"] < 32


def test_overlong_conversation_still_answers_through_engine():
    """End-to-end: history far beyond max_seq_len answers (no ValueError)."""

    async def run():
        scheduler, gen = _engine_stack(max_seq_len=256)
        await scheduler.start()
        try:
            agent = _agent(
                gen,
                tool_sampling=SamplingParams(temperature=0.0, max_new_tokens=16),
                response_sampling=SamplingParams(temperature=0.0, max_new_tokens=16),
            )
            # ~40 turns x ~60 bytes >> 256-token budget
            history = [
                ChatMessage(
                    sender="UserMessage" if i % 2 == 0 else "AIMessage",
                    message=f"turn {i}: " + "blah " * 10,
                )
                for i in range(40)
            ]
            return await agent.query("so what should I do?", "u1", "context", history)
        finally:
            await scheduler.stop()

    result = asyncio.run(run())
    assert isinstance(result["response"], str)


def test_token_level_backstop_splices():
    """A single over-budget prompt (no history to drop) still streams."""

    async def run():
        scheduler, gen = _engine_stack(max_seq_len=128)
        await scheduler.start()
        try:
            sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
            giant = "y" * 4000  # ~4000 byte-tokens >> 120-token budget
            return await gen.generate(giant, sampling)
        finally:
            await scheduler.stop()

    assert isinstance(asyncio.run(run()), str)
