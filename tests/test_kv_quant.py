"""Int8 paged-KV cache (engine kv_quant): quantization bounds, kernel ≡
scatter parity, attention over the quantized cache ≡ reference over the
SAME dequantized values, and end-to-end engine decode.

The contract: per-token-per-head scales are written once at append time
and never requantized (the page RMW copies existing int8 rows verbatim),
so cached values are bit-stable and the only error is the one-time row
rounding, bounded by amax/254 per element.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
from finchat_tpu.engine.kv_cache import (
    PagedKVCache,
    gather_kv_q8,
    pages_needed,
    quantize_kv_rows,
    scale_rows,
    scatter_kv_chunk_q8,
    PageAllocator,
)
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.ops.refs import mha_reference
from finchat_tpu.utils.config import EngineConfig

CONFIG = PRESETS["tiny"]  # n_kv_heads=2, head_dim=32


def test_quantize_kv_rows_error_bound():
    x = jax.random.normal(jax.random.key(0), (3, 5, 2 * 32), jnp.float32)
    q, s = quantize_kv_rows(x, n_kv=2)
    assert q.dtype == jnp.int8 and s.shape == (3, 5, 2)
    deq = (q.reshape(3, 5, 2, 32).astype(jnp.float32) * s[..., None]).reshape(x.shape)
    err = jnp.abs(deq - x)
    bound = jnp.repeat(s, 32, axis=-1) / 2 + 1e-6  # half a step per element
    assert bool((err <= bound).all())


def test_scale_rows_padding():
    assert scale_rows(2) == 8 and scale_rows(8) == 8 and scale_rows(9) == 16


def _fresh_cache(n_pages=8, page_size=8):
    cache = PagedKVCache.create(CONFIG, n_pages, page_size, kv_quant="int8")
    return cache


def test_scatter_gather_roundtrip():
    """scatter_kv_chunk_q8 → gather_kv_q8 reproduces the written rows to
    quantization tolerance, in the right positions."""
    page_size = 8
    cache = _fresh_cache()
    B, C, Hkv, hd = 2, 6, CONFIG.n_kv_heads, CONFIG.head_dim
    k_new = jax.random.normal(jax.random.key(1), (B, C, Hkv, hd), jnp.float32)
    v_new = jax.random.normal(jax.random.key(2), (B, C, Hkv, hd), jnp.float32)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    start_pos = jnp.asarray([0, 5], jnp.int32)
    n_valid = jnp.asarray([6, 4], jnp.int32)  # slot 1: 2 padding lanes

    kp, vp, ks, vs = scatter_kv_chunk_q8(
        cache.k_pages, cache.v_pages, cache.k_scales, cache.v_scales,
        k_new, v_new, page_table, start_pos, n_valid, page_size,
        jnp.int32(0), Hkv,
    )
    k_all, v_all = gather_kv_q8(
        kp, vp, ks, vs, page_table, page_size, jnp.int32(0), Hkv,
        dtype=jnp.float32,
    )
    for b in range(B):
        for i in range(int(n_valid[b])):
            pos = int(start_pos[b]) + i
            for src, got in ((k_new, k_all), (v_new, v_all)):
                want = np.asarray(src[b, i])
                have = np.asarray(got[b, pos])
                amax = np.abs(want).max(axis=-1, keepdims=True)
                assert np.all(np.abs(have - want) <= amax / 127 + 1e-6), (b, i)


def test_append_kernel_matches_scatter():
    """The in-place quantizing append (interpret mode) must write exactly
    what the XLA scatter writes for the same single token: same int8 rows,
    same scales."""
    from finchat_tpu.ops.kv_append import paged_kv_append_q8

    page_size = 8
    Hkv, hd = CONFIG.n_kv_heads, CONFIG.head_dim
    B = 2
    k_row = jax.random.normal(jax.random.key(3), (B, 1, Hkv, hd), jnp.bfloat16)
    v_row = jax.random.normal(jax.random.key(4), (B, 1, Hkv, hd), jnp.bfloat16)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    pos = jnp.asarray([3, 9], jnp.int32)
    n_valid = jnp.asarray([1, 1], jnp.int32)

    ca = _fresh_cache()
    kv_new = jnp.concatenate(
        [k_row.reshape(B, 1, -1), v_row.reshape(B, 1, -1)], axis=-1
    )
    ka, va, ksa, vsa = paged_kv_append_q8(
        kv_new, ca.k_pages, ca.v_pages, ca.k_scales, ca.v_scales,
        page_table, pos, n_valid, jnp.zeros((1,), jnp.int32),
        page_size=page_size, n_kv=Hkv, interpret=True,
    )

    cb = _fresh_cache()
    kb, vb, ksb, vsb = scatter_kv_chunk_q8(
        cb.k_pages, cb.v_pages, cb.k_scales, cb.v_scales,
        k_row, v_row, page_table, pos, n_valid, page_size, jnp.int32(0), Hkv,
    )
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(kb))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    np.testing.assert_allclose(np.asarray(ksa), np.asarray(ksb), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vsa), np.asarray(vsb), rtol=1e-6)


def test_trash_redirect_append_q8():
    """n_valid == 0 lanes must write page 0 (trash), even at an
    out-of-range position (the verify-step padding case)."""
    from finchat_tpu.ops.kv_append import paged_kv_append_q8

    page_size = 8
    Hkv, hd = CONFIG.n_kv_heads, CONFIG.head_dim
    ca = _fresh_cache()
    kv_new = jnp.ones((1, 1, 2 * Hkv * hd), jnp.bfloat16)
    page_table = jnp.asarray([[1, 2]], jnp.int32)
    ka, va, ksa, vsa = paged_kv_append_q8(
        kv_new, ca.k_pages, ca.v_pages, ca.k_scales, ca.v_scales,
        page_table, jnp.asarray([100], jnp.int32), jnp.asarray([0], jnp.int32),
        jnp.zeros((1,), jnp.int32), page_size=page_size, n_kv=Hkv, interpret=True,
    )
    assert int(jnp.abs(ka[:, 1:].astype(jnp.int32)).sum()) == 0  # real pages untouched
    assert int(jnp.abs(va[:, 1:].astype(jnp.int32)).sum()) == 0


@pytest.mark.parametrize("backend", ["ref", "pallas-interpret"])
def test_paged_attention_q8_matches_dequantized_reference(backend):
    """Attention over the int8 cache must equal mha_reference over the SAME
    dequantized K/V — both kernels and the gather path see identical
    semantic values, so the only tolerance is fp accumulation order."""
    from finchat_tpu.ops.dispatch import paged_attention

    page_size = 8
    Hkv, hd, H = CONFIG.n_kv_heads, CONFIG.head_dim, CONFIG.n_heads
    B, C = 2, 1
    cache = _fresh_cache(n_pages=8)
    T = 14
    k_ctx = jax.random.normal(jax.random.key(5), (B, T, Hkv, hd), jnp.float32)
    v_ctx = jax.random.normal(jax.random.key(6), (B, T, Hkv, hd), jnp.float32)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    kp, vp, ks, vs = scatter_kv_chunk_q8(
        cache.k_pages, cache.v_pages, cache.k_scales, cache.v_scales,
        k_ctx, v_ctx, page_table, jnp.zeros((B,), jnp.int32),
        jnp.full((B,), T, jnp.int32), page_size, jnp.int32(0), Hkv,
    )
    q = jax.random.normal(jax.random.key(7), (B, C, H, hd), jnp.float32)
    q_offset = jnp.full((B,), T - 1, jnp.int32)
    kv_len = jnp.full((B,), T, jnp.int32)

    got = paged_attention(
        q, kp, vp, page_table, q_offset, kv_len, jnp.zeros((1,), jnp.int32),
        page_size=page_size, n_kv=Hkv, backend=backend,
        k_scales=ks, v_scales=vs,
    )
    # the oracle sees the SAME dequantized values
    k_deq, v_deq = gather_kv_q8(
        kp, vp, ks, vs, page_table, page_size, jnp.int32(0), Hkv,
        dtype=jnp.float32,
    )
    want = mha_reference(q, k_deq, v_deq, causal=True, q_offset=q_offset, kv_len=kv_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("attn", ["ref", "pallas-interpret"])
def test_engine_int8_kv_logits_track_bf16(attn):
    """End-to-end teacher-forced comparison: drive the int8-KV engine along
    the bf16 engine's exact greedy token path (chunked prefill, per-step
    appends, a page boundary) and require every step's logits to stay
    within quantization tolerance. Token-exact equality is NOT the
    contract — random tiny-model logits have near-ties (observed top-2 gap
    0.006) that flip under any numerics change — logit tracking is."""
    ecfg = dict(max_seqs=2, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8)
    params = init_params(CONFIG, jax.random.key(0))
    prompt, n_new = [5, 9, 2, 100, 17, 3, 77, 4, 250, 31], 8  # crosses a page

    def make(kv_quant):
        eng = InferenceEngine(
            CONFIG, params, EngineConfig(**ecfg, kv_quant=kv_quant),
            attn_backend=attn,
        )
        assert eng.kv_quant == kv_quant
        if kv_quant:
            assert eng.state.k_pages.dtype == jnp.int8
        alloc = PageAllocator(eng.engine_cfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        prefill_logits = eng.prefill(0, prompt)
        return eng, np.asarray(prefill_logits, np.float32)

    bf16, pre_b = make("")
    int8, pre_q = make("int8")
    np.testing.assert_allclose(pre_q, pre_b, atol=0.15)

    # bf16's greedy path, teacher-forced into BOTH engines
    token = int(np.argmax(pre_b))
    active = jnp.zeros((2,), bool).at[0].set(True)
    z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
    for _ in range(n_new - 1):
        bf16.set_last_token(0, token)
        int8.set_last_token(0, token)
        _, logits_b = bf16.decode(active, z, o, zk, return_logits=True)
        _, logits_q = int8.decode(active, z, o, zk, return_logits=True)
        logits_b, logits_q = np.asarray(logits_b[0]), np.asarray(logits_q[0])
        np.testing.assert_allclose(logits_q, logits_b, atol=0.15)
        token = int(np.argmax(logits_b))


def test_kv_quant_disabled_under_mesh():
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    config = PRESETS["tiny"]
    eng = InferenceEngine(
        config, init_params(config, jax.random.key(0)),
        EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64,
                     prefill_chunk=8, kv_quant="int8"),
        mesh=mesh,
    )
    assert eng.kv_quant == "" and eng.state.k_pages.dtype != jnp.int8
