"""Int8 paged-KV cache (engine kv_quant): quantization bounds, kernel ≡
scatter parity, attention over the quantized cache ≡ reference over the
SAME dequantized values, and end-to-end engine decode.

The contract: per-token-per-head scales are written once at append time
and never requantized (the page RMW copies existing int8 rows verbatim),
so cached values are bit-stable and the only error is the one-time row
rounding, bounded by amax/254 per element.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
from finchat_tpu.engine.kv_cache import (
    PagedKVCache,
    gather_kv_q8,
    pages_needed,
    quantize_kv_rows,
    scale_rows,
    scatter_kv_chunk_q8,
    PageAllocator,
)
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.ops.refs import mha_reference
from finchat_tpu.utils.config import EngineConfig

CONFIG = PRESETS["tiny"]  # n_kv_heads=2, head_dim=32


def test_quantize_kv_rows_error_bound():
    x = jax.random.normal(jax.random.key(0), (3, 5, 2 * 32), jnp.float32)
    q, s = quantize_kv_rows(x, n_kv=2)
    assert q.dtype == jnp.int8 and s.shape == (3, 5, 2)
    deq = (q.reshape(3, 5, 2, 32).astype(jnp.float32) * s[..., None]).reshape(x.shape)
    err = jnp.abs(deq - x)
    bound = jnp.repeat(s, 32, axis=-1) / 2 + 1e-6  # half a step per element
    assert bool((err <= bound).all())


def test_scale_rows_padding():
    assert scale_rows(2) == 8 and scale_rows(8) == 8 and scale_rows(9) == 16


def _fresh_cache(n_pages=8, page_size=8):
    cache = PagedKVCache.create(CONFIG, n_pages, page_size, kv_quant="int8")
    return cache


def test_scatter_gather_roundtrip():
    """scatter_kv_chunk_q8 → gather_kv_q8 reproduces the written rows to
    quantization tolerance, in the right positions."""
    page_size = 8
    cache = _fresh_cache()
    B, C, Hkv, hd = 2, 6, CONFIG.n_kv_heads, CONFIG.head_dim
    k_new = jax.random.normal(jax.random.key(1), (B, C, Hkv, hd), jnp.float32)
    v_new = jax.random.normal(jax.random.key(2), (B, C, Hkv, hd), jnp.float32)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    start_pos = jnp.asarray([0, 5], jnp.int32)
    n_valid = jnp.asarray([6, 4], jnp.int32)  # slot 1: 2 padding lanes

    kp, vp, ks, vs = scatter_kv_chunk_q8(
        cache.k_pages, cache.v_pages, cache.k_scales, cache.v_scales,
        k_new, v_new, page_table, start_pos, n_valid, page_size,
        jnp.int32(0), Hkv,
    )
    k_all, v_all = gather_kv_q8(
        kp, vp, ks, vs, page_table, page_size, jnp.int32(0), Hkv,
        dtype=jnp.float32,
    )
    for b in range(B):
        for i in range(int(n_valid[b])):
            pos = int(start_pos[b]) + i
            for src, got in ((k_new, k_all), (v_new, v_all)):
                want = np.asarray(src[b, i])
                have = np.asarray(got[b, pos])
                amax = np.abs(want).max(axis=-1, keepdims=True)
                assert np.all(np.abs(have - want) <= amax / 127 + 1e-6), (b, i)


def test_append_kernel_matches_scatter():
    """The in-place quantizing append (interpret mode) must write exactly
    what the XLA scatter writes for the same single token: same int8 rows,
    same scales."""
    from finchat_tpu.ops.kv_append import paged_kv_append_q8

    page_size = 8
    Hkv, hd = CONFIG.n_kv_heads, CONFIG.head_dim
    B = 2
    k_row = jax.random.normal(jax.random.key(3), (B, 1, Hkv, hd), jnp.bfloat16)
    v_row = jax.random.normal(jax.random.key(4), (B, 1, Hkv, hd), jnp.bfloat16)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    pos = jnp.asarray([3, 9], jnp.int32)
    n_valid = jnp.asarray([1, 1], jnp.int32)

    ca = _fresh_cache()
    kv_new = jnp.concatenate(
        [k_row.reshape(B, 1, -1), v_row.reshape(B, 1, -1)], axis=-1
    )
    ka, va, ksa, vsa = paged_kv_append_q8(
        kv_new, ca.k_pages, ca.v_pages, ca.k_scales, ca.v_scales,
        page_table, pos, n_valid, jnp.zeros((1,), jnp.int32),
        page_size=page_size, n_kv=Hkv, interpret=True,
    )

    cb = _fresh_cache()
    kb, vb, ksb, vsb = scatter_kv_chunk_q8(
        cb.k_pages, cb.v_pages, cb.k_scales, cb.v_scales,
        k_row, v_row, page_table, pos, n_valid, page_size, jnp.int32(0), Hkv,
    )
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(kb))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    np.testing.assert_allclose(np.asarray(ksa), np.asarray(ksb), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vsa), np.asarray(vsb), rtol=1e-6)


def test_trash_redirect_append_q8():
    """n_valid == 0 lanes must write page 0 (trash), even at an
    out-of-range position (the verify-step padding case)."""
    from finchat_tpu.ops.kv_append import paged_kv_append_q8

    page_size = 8
    Hkv, hd = CONFIG.n_kv_heads, CONFIG.head_dim
    ca = _fresh_cache()
    kv_new = jnp.ones((1, 1, 2 * Hkv * hd), jnp.bfloat16)
    page_table = jnp.asarray([[1, 2]], jnp.int32)
    ka, va, ksa, vsa = paged_kv_append_q8(
        kv_new, ca.k_pages, ca.v_pages, ca.k_scales, ca.v_scales,
        page_table, jnp.asarray([100], jnp.int32), jnp.asarray([0], jnp.int32),
        jnp.zeros((1,), jnp.int32), page_size=page_size, n_kv=Hkv, interpret=True,
    )
    assert int(jnp.abs(ka[:, 1:].astype(jnp.int32)).sum()) == 0  # real pages untouched
    assert int(jnp.abs(va[:, 1:].astype(jnp.int32)).sum()) == 0


@pytest.mark.parametrize("backend", ["ref", "pallas-interpret"])
def test_paged_attention_q8_matches_dequantized_reference(backend):
    """Attention over the int8 cache must equal mha_reference over the SAME
    dequantized K/V — both kernels and the gather path see identical
    semantic values, so the only tolerance is fp accumulation order."""
    from finchat_tpu.ops.dispatch import paged_attention

    page_size = 8
    Hkv, hd, H = CONFIG.n_kv_heads, CONFIG.head_dim, CONFIG.n_heads
    B, C = 2, 1
    cache = _fresh_cache(n_pages=8)
    T = 14
    k_ctx = jax.random.normal(jax.random.key(5), (B, T, Hkv, hd), jnp.float32)
    v_ctx = jax.random.normal(jax.random.key(6), (B, T, Hkv, hd), jnp.float32)
    page_table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    kp, vp, ks, vs = scatter_kv_chunk_q8(
        cache.k_pages, cache.v_pages, cache.k_scales, cache.v_scales,
        k_ctx, v_ctx, page_table, jnp.zeros((B,), jnp.int32),
        jnp.full((B,), T, jnp.int32), page_size, jnp.int32(0), Hkv,
    )
    q = jax.random.normal(jax.random.key(7), (B, C, H, hd), jnp.float32)
    q_offset = jnp.full((B,), T - 1, jnp.int32)
    kv_len = jnp.full((B,), T, jnp.int32)

    got = paged_attention(
        q, kp, vp, page_table, q_offset, kv_len, jnp.zeros((1,), jnp.int32),
        page_size=page_size, n_kv=Hkv, backend=backend,
        k_scales=ks, v_scales=vs,
    )
    # the oracle sees the SAME dequantized values
    k_deq, v_deq = gather_kv_q8(
        kp, vp, ks, vs, page_table, page_size, jnp.int32(0), Hkv,
        dtype=jnp.float32,
    )
    want = mha_reference(q, k_deq, v_deq, causal=True, q_offset=q_offset, kv_len=kv_len)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("attn", ["ref", "pallas-interpret"])
def test_engine_int8_kv_logits_track_bf16(attn):
    """End-to-end teacher-forced comparison: drive the int8-KV engine along
    the bf16 engine's exact greedy token path (chunked prefill, per-step
    appends, a page boundary) and require every step's logits to stay
    within quantization tolerance. Token-exact equality is NOT the
    contract — random tiny-model logits have near-ties (observed top-2 gap
    0.006) that flip under any numerics change — logit tracking is."""
    ecfg = dict(max_seqs=2, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8)
    params = init_params(CONFIG, jax.random.key(0))
    prompt, n_new = [5, 9, 2, 100, 17, 3, 77, 4, 250, 31], 8  # crosses a page

    def make(kv_quant):
        eng = InferenceEngine(
            CONFIG, params, EngineConfig(**ecfg, kv_quant=kv_quant),
            attn_backend=attn,
        )
        assert eng.kv_quant == kv_quant
        if kv_quant:
            assert eng.state.k_pages.dtype == jnp.int8
        alloc = PageAllocator(eng.engine_cfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        prefill_logits = eng.prefill(0, prompt)
        return eng, np.asarray(prefill_logits, np.float32)

    bf16, pre_b = make("")
    int8, pre_q = make("int8")
    np.testing.assert_allclose(pre_q, pre_b, atol=0.15)

    # bf16's greedy path, teacher-forced into BOTH engines
    token = int(np.argmax(pre_b))
    active = jnp.zeros((2,), bool).at[0].set(True)
    z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
    for _ in range(n_new - 1):
        bf16.set_last_token(0, token)
        int8.set_last_token(0, token)
        _, logits_b = bf16.decode(active, z, o, zk, return_logits=True)
        _, logits_q = int8.decode(active, z, o, zk, return_logits=True)
        logits_b, logits_q = np.asarray(logits_b[0]), np.asarray(logits_q[0])
        np.testing.assert_allclose(logits_q, logits_b, atol=0.15)
        token = int(np.argmax(logits_b))


@pytest.mark.skipif(
    not hasattr(jax.sharding, "AxisType"),
    reason="jax 0.4 shard_map reduction order flips the near-tie argmax of "
           "the first committed token (legitimate inside the 0.15 int8 "
           "envelope the allclose accepts), and the flip feeds back into "
           "every later token — the continuation contract is only "
           "meaningful where the first tokens agree (jax >= 0.5)",
)
def test_ring_prefill_int8_kv_matches_chunked():
    """The SP/ring prefill write path quantizes too (the old engine
    disabled kv_quant under any mesh, so this path could never see an
    int8 cache): a long prompt prefilled through the seq-sharded ring
    path with kv_quant=int8 must leave the cache equivalent to chunked
    int8 prefill — same greedy continuation, close last-token logits."""
    from finchat_tpu.models.llama import LlamaConfig
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=128,
    )
    params = init_params(config, jax.random.key(0))
    prompt = list(np.random.RandomState(7).randint(1, 128, size=50))
    n_new = 5

    def run(mesh, ring_min):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=32, max_seq_len=128,
            prefill_chunk=16, ring_prefill_min_tokens=ring_min,
            kv_quant="int8",
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        assert eng.kv_quant == "int8" and eng.state.k_pages.dtype == jnp.int8
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        if ring_min <= len(prompt) and mesh is not None:
            assert eng._use_ring_prefill(len(prompt))
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return np.asarray(logits, np.float32), out

    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))
    ring_logits, ring_tokens = run(mesh, ring_min=16)  # ring path engaged
    mesh_logits, mesh_tokens = run(mesh, ring_min=10_000)  # chunked, same mesh
    # both paths quantize per-token rows at write, so the CACHED values are
    # identical — but the prefill-time attention differs by the one-time
    # rounding: ring attends over the exact bf16 K/V activations, chunked
    # reads back the quantized cache. Tolerance is the quantization
    # envelope (same 0.15 as test_engine_int8_kv_logits_track_bf16).
    np.testing.assert_allclose(ring_logits, mesh_logits, atol=0.15)
    # decode reads the same quantized cache in both runs; the greedy
    # continuation AFTER the first token must agree (the first committed
    # token comes from the differing prefill logits, so compare decode)
    assert ring_tokens[1:] == mesh_tokens[1:] or ring_tokens == mesh_tokens


def test_segmented_ring_prefill_int8_kv_matches_monolithic():
    """The SEGMENTED SP prefill's int8 branch (gather_kv_q8 of the cached
    prefix + quantized segment scatter, engine._ring_segment_attention_fn)
    must reproduce the monolithic int8 ring prefill: identical cached
    values, so identical greedy decode, and logits within the
    quantization envelope (later segments attend to the DEQUANTIZED
    earlier segments, the monolithic pass to exact bf16 activations)."""
    from finchat_tpu.models.llama import LlamaConfig
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=256,
    )
    params = init_params(config, jax.random.key(0))
    prompt = list(np.random.RandomState(13).randint(1, 128, size=100))
    n_new = 5
    mesh = build_mesh(MeshSpec(data=1, seq=2, expert=1, model=4))

    def run(ring_chunk):
        ecfg = EngineConfig(
            max_seqs=2, page_size=8, num_pages=64, max_seq_len=256,
            prefill_chunk=16, ring_prefill_min_tokens=16,
            ring_prefill_chunk=ring_chunk, kv_quant="int8",
        )
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        assert eng.state.k_pages.dtype == jnp.int8
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        if ring_chunk:
            rc = eng.ring_segment_tokens()
            logits = None
            for start in range(0, len(prompt), rc):
                logits = eng.prefill_ring_segment(0, prompt[start : start + rc], start)
        else:
            logits = eng.prefill_ring(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return np.asarray(logits, np.float32), out

    mono_logits, mono_tokens = run(0)
    seg_logits, seg_tokens = run(32)  # 100 tokens -> 4 segments
    np.testing.assert_allclose(seg_logits, mono_logits, atol=0.15)
    assert seg_tokens[1:] == mono_tokens[1:] or seg_tokens == mono_tokens


def test_tp_sharded_int8_kv_matches_unsharded():
    """VERDICT r4 #5: int8 KV must survive a mesh. Greedy decode through
    the TP=8 engine with kv_quant=int8 must emit the same tokens as the
    single-device int8 engine, with the scale arrays actually sharded over
    their head row dim (Hkv=8 → pad8(Hkv)=Hkv, so row blocks == the page
    shards' head blocks)."""
    from jax.sharding import PartitionSpec as P

    from finchat_tpu.engine.engine import commit_first_token
    from finchat_tpu.models.llama import LlamaConfig
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=64,
    )
    params = init_params(config, jax.random.key(0))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64,
                        prefill_chunk=8, kv_quant="int8")
    prompt, n_new = [5, 9, 2, 100, 17, 3], 6

    def run(mesh):
        eng = InferenceEngine(config, params, ecfg, mesh=mesh)
        assert eng.kv_quant == "int8"
        assert eng.state.k_pages.dtype == jnp.int8
        if mesh is not None:
            assert eng.state.k_scales.sharding.spec == P(None, None, "model", None)
            assert eng.state.v_scales.sharding.spec == P(None, None, "model", None)
        alloc = PageAllocator(ecfg.num_pages)
        pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, 8))
        eng.set_page_table_row(0, pages)
        logits = eng.prefill(0, prompt)
        eng.state, tok = commit_first_token(
            eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
        )
        out = [int(tok)]
        active = jnp.zeros((2,), bool).at[0].set(True)
        z, o, zk = jnp.zeros((2,)), jnp.ones((2,)), jnp.zeros((2,), jnp.int32)
        for _ in range(n_new - 1):
            out.append(int(eng.decode(active, z, o, zk)[0]))
        return out

    unsharded = run(None)
    sharded = run(build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8)))
    assert unsharded == sharded
