"""Full message-in → chunks-out pipeline (SURVEY §4.4): in-memory broker +
store + stub generators, asserting the §2.4 outbound chunk schema
byte-for-byte, plus the HTTP surface over a real TCP socket."""

import asyncio
import json

import httpx
import pytest

from finchat_tpu.engine.generator import StubGenerator
from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient, Message
from finchat_tpu.io.store import InMemoryStore
from finchat_tpu.serve.app import build_app
from finchat_tpu.utils.config import (
    AI_RESPONSE_TOPIC,
    USER_MESSAGE_TOPIC,
    load_config,
)

CONTEXT_DOC = {"user_id": "u9", "name": "Alex", "income": 5000, "savings_goal": 800}


def make_app(response_text="Hello there friend", tool_response="No tool call",
             fail_response=False, watchdog=None):
    cfg = load_config(overrides={"model.preset": "stub"})
    if watchdog is not None:
        cfg.engine.watchdog_seconds = watchdog
    broker = InMemoryBroker()
    store = InMemoryStore()
    store.upsert_context("c1", CONTEXT_DOC)
    store.add_user_message("c1", "How am I doing?", "u9")

    response_gen = StubGenerator(default=response_text, fail_with="boom" if fail_response else None,
                                 chunk_delay=0.001)
    app = build_app(
        cfg,
        store=store,
        kafka=KafkaClient(cfg.kafka, broker=broker),
        tool_generator=StubGenerator(default=tool_response),
        response_generator=response_gen,
    )
    return app, broker, store


def inbound(message="How am I doing?", conversation_id="c1", **extra):
    return {"message": message, "conversation_id": conversation_id, "user_id": "u9", **extra}


def kafka_msg(payload):
    return Message(USER_MESSAGE_TOPIC, payload["conversation_id"], json.dumps(payload).encode())


def drain_json(broker):
    return [json.loads(m.value().decode()) for m in broker.drain(AI_RESPONSE_TOPIC)]


async def test_pipeline_chunk_schema_byte_for_byte():
    app, broker, store = make_app(response_text="You are fine.")
    payload = inbound(trace="t-1")
    await app.process_message(kafka_msg(payload))

    out = drain_json(broker)
    assert len(out) >= 2
    # every streamed chunk: reference main.py:86-93
    for chunk in out[:-1]:
        assert chunk["last_message"] is False
        assert chunk["error"] is False
        assert chunk["sender"] == "AIMessage"
        assert chunk["type"] == "response_chunk"
        assert chunk["conversation_id"] == "c1"
        assert chunk["trace"] == "t-1"  # passthrough fields preserved
    # completion marker: main.py:101-108 — message is the ORIGINAL user text
    final = out[-1]
    assert final["last_message"] is True
    assert final["type"] == "complete"
    assert final["message"] == "How am I doing?"
    # reassembled text
    assert "".join(c["message"] for c in out[:-1]) == "You are fine."
    # persisted to store (main.py:126)
    history = await store.get_history("c1")
    assert history[-1].sender == "AIMessage"
    assert history[-1].message == "You are fine."


async def test_pipeline_error_chunk():
    app, broker, _ = make_app(fail_response=True)
    await app.process_message(kafka_msg(inbound()))
    out = drain_json(broker)
    assert len(out) == 1
    err = out[0]
    # error marker: main.py:114-121 — empty message, error=True, NO type key
    assert err["message"] == ""
    assert err["error"] is True
    assert err["last_message"] is True
    assert "type" not in err


async def test_missing_context_drops_message():
    app, broker, _ = make_app()
    await app.process_message(kafka_msg(inbound(conversation_id="unknown")))
    assert drain_json(broker) == []  # dropped silently (main.py:68-70)


async def test_watchdog_timeout_chunk():
    app, broker, _ = make_app(watchdog=0.05)
    app.agent.response_generator.chunk_delay = 10.0  # hang the stream

    async def run_once():
        app._running = True
        task = asyncio.create_task(app.consume_messages())
        await asyncio.sleep(0.3)
        app._running = False
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    app.kafka.setup_consumer()
    producer = KafkaClient(app.cfg.kafka, broker=broker)
    producer.produce_message(USER_MESSAGE_TOPIC, "c1", inbound())
    await run_once()
    out = drain_json(broker)
    assert out, "expected a timeout chunk"
    timeout = out[-1]
    assert timeout["message"] == "Request timed out. Please try again."
    assert timeout["error"] is True and timeout["last_message"] is True


async def test_full_loop_end_to_end():
    """Produce on user_message → live consume loop → chunks on ai_response."""
    app, broker, _ = make_app(response_text="All good.")
    await app.start(serve_http=False)
    try:
        producer = KafkaClient(app.cfg.kafka, broker=broker)
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", inbound())
        for _ in range(200):
            out = drain_json(broker)
            if out and out[-1].get("type") == "complete":
                break
            await asyncio.sleep(0.01)
        else:
            raise AssertionError(f"no completion marker; got {drain_json(broker)}")
    finally:
        await app.stop()


async def test_http_surface():
    app, broker, _ = make_app(response_text="Advice here.")
    app.cfg.serve.port = 0  # ephemeral
    app.server.port = 0
    await app.start(serve_http=True)
    try:
        async with httpx.AsyncClient() as client:
            base = f"http://127.0.0.1:{app.server.port}"
            health = await client.get(f"{base}/health")
            assert health.status_code == 200
            assert health.json() == {"status": "healthy"}

            chat = await client.post(f"{base}/chat", json={
                "conversation_id": "c1", "message": "hi", "user_id": "u9",
            })
            assert chat.status_code == 200
            body = chat.json()
            assert body["response"] == "Advice here."
            assert body["retrieved_transactions_count"] == 0

            bad = await client.post(f"{base}/chat", json={"message": "hi"})
            assert bad.status_code == 400

            missing = await client.get(f"{base}/nope")
            assert missing.status_code == 404

            metrics = await client.get(f"{base}/metrics")
            assert metrics.status_code == 200
            assert "finchat" in metrics.text

            # SSE stream carries the FULL event protocol
            async with client.stream("POST", f"{base}/chat/stream", json={
                "conversation_id": "c1", "message": "hi", "user_id": "u9",
            }) as stream:
                events = []
                async for line in stream.aiter_lines():
                    if line.startswith("data: "):
                        events.append(json.loads(line[6:]))
            types = [e["type"] for e in events]
            assert types[0] == "status"
            assert "response_chunk" in types
            assert types[-1] == "complete"
    finally:
        await app.stop()


async def _watch_until(broker, n_complete: int, ticks: int = 500):
    """Poll the ai_response log, recording each record's first-seen tick
    (drain returns the FULL log in per-partition order, which is not a
    global timeline — the (partition, offset) key + tick gives one)."""
    first_seen: dict[tuple[int, int], tuple[int, dict]] = {}
    for tick in range(ticks):
        for m in broker.drain(AI_RESPONSE_TOPIC):
            key = (m.partition(), m.offset())
            if key not in first_seen:
                first_seen[key] = (tick, json.loads(m.value().decode()))
        events = [e for _, e in first_seen.values()]
        if sum(1 for e in events if e.get("type") == "complete") >= n_complete:
            return first_seen
        await asyncio.sleep(0.01)
    raise AssertionError(
        f"only {sum(1 for _, e in first_seen.values() if e.get('type') == 'complete')}"
        f"/{n_complete} completions: {[e for _, e in first_seen.values()]}"
    )


async def test_kafka_conversations_process_concurrently():
    """BASELINE config 4 (Kafka-driven concurrency): two conversations'
    messages in the queue together must INTERLEAVE — the second
    conversation's chunks appear before the first one's complete marker.
    The reference (and the pre-round-4 consume loop) processed one message
    to completion at a time."""
    app, broker, store = make_app(response_text="word " * 30)
    store.upsert_context("c2", {**CONTEXT_DOC, "user_id": "u9"})
    store.add_user_message("c2", "And me?", "u9")
    await app.start(serve_http=False)
    try:
        producer = KafkaClient(app.cfg.kafka, broker=broker)
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", inbound(conversation_id="c1"))
        producer.produce_message(USER_MESSAGE_TOPIC, "c2", inbound(conversation_id="c2"))
        first_seen = await _watch_until(broker, n_complete=2)

        def first_tick(pred):
            ticks = [t for t, e in first_seen.values() if pred(e)]
            return min(ticks) if ticks else None

        c1_done = first_tick(lambda e: e["conversation_id"] == "c1" and e.get("type") == "complete")
        c2_start = first_tick(lambda e: e["conversation_id"] == "c2")
        c2_done = first_tick(lambda e: e["conversation_id"] == "c2" and e.get("type") == "complete")
        c1_start = first_tick(lambda e: e["conversation_id"] == "c1")
        # overlap in either direction proves concurrency
        assert (c2_start is not None and c2_start < c1_done) or (
            c1_start is not None and c1_start < c2_done
        ), f"conversations were processed serially: {c1_start=} {c1_done=} {c2_start=} {c2_done=}"
    finally:
        await app.stop()


async def test_commit_after_process_and_dedupe_ring():
    """kafka.commit_after_process (at-least-once): offsets commit only
    after the watchdog-wrapped handler completes, and a redelivered
    message_id is answered exactly once (dedupe ring)."""
    from finchat_tpu.utils.config import GROUP_ID
    from finchat_tpu.utils.metrics import METRICS

    app, broker, _ = make_app(response_text="Once only.")
    app.cfg.kafka.commit_after_process = True
    app.kafka._manual_commit = True  # client was built before the override
    app._commit_enabled = True
    await app.start(serve_http=False)
    try:
        d0 = METRICS.get("finchat_kafka_dedupe_skips_total")
        producer = KafkaClient(app.cfg.kafka, broker=broker)
        payload = inbound(message_id="m-1")
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", payload)
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", payload)  # redelivery
        for _ in range(300):
            out = drain_json(broker)
            if sum(1 for e in out if e.get("type") == "complete") >= 1:
                break
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.1)  # let the duplicate poll + commit land
        out = drain_json(broker)
        completes = [e for e in out if e.get("type") == "complete"]
        assert len(completes) == 1, f"duplicate message_id answered twice: {out}"
        assert METRICS.get("finchat_kafka_dedupe_skips_total") == d0 + 1
        # both offsets committed: the group's watermark moved past them
        group = broker._groups[GROUP_ID]
        committed = sum(
            off for (topic, _p), off in group.offsets.items()
            if topic == USER_MESSAGE_TOPIC
        )
        assert committed == 2, group.offsets
    finally:
        await app.stop()


async def test_failed_message_id_is_retryable_not_deduped():
    """Only ANSWERED message_ids stay in the dedupe ring: a message whose
    handling failed (error chunk) leaves the ring, so the producer's retry
    is reprocessed instead of black-holed."""
    from finchat_tpu.utils.metrics import METRICS

    app, broker, _ = make_app(fail_response=True)
    app.cfg.kafka.commit_after_process = True
    app.kafka._manual_commit = True
    app._commit_enabled = True
    await app.start(serve_http=False)
    try:
        producer = KafkaClient(app.cfg.kafka, broker=broker)
        payload = inbound(message_id="m-fail")
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", payload)
        for _ in range(300):
            if drain_json(broker):
                break
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.05)  # let the done-callback run
        assert drain_json(broker)[-1]["error"] is True
        assert "m-fail" not in app._seen_ids, "failed id stuck in the dedupe ring"
        d0 = METRICS.get("finchat_kafka_dedupe_skips_total")
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", payload)  # retry
        for _ in range(300):
            if len(drain_json(broker)) >= 2:
                break
            await asyncio.sleep(0.01)
        assert len(drain_json(broker)) >= 2, "retry of a failed message was skipped"
        assert METRICS.get("finchat_kafka_dedupe_skips_total") == d0
    finally:
        await app.stop()


async def test_same_conversation_messages_stay_ordered():
    """Two messages for the SAME conversation must not interleave: the
    second's chunks start only after the first's complete marker (the
    ordering guarantee the reference gets from partition keying + serial
    processing). Same key → same partition → per-partition drain order IS
    the delivery order."""
    app, broker, _ = make_app(response_text="steady " * 10)
    await app.start(serve_http=False)
    try:
        producer = KafkaClient(app.cfg.kafka, broker=broker)
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", inbound(seq="first"))
        producer.produce_message(USER_MESSAGE_TOPIC, "c1", inbound(seq="second"))
        await _watch_until(broker, n_complete=2)

        events = drain_json(broker)  # one partition (same key): exact order
        completes = [i for i, e in enumerate(events) if e.get("type") == "complete"]
        assert len(completes) == 2, events
        # every event before the first complete belongs to the first message
        assert all(e.get("seq") == "first" for e in events[: completes[0]]), events
        assert all(
            e.get("seq") == "second" for e in events[completes[0] + 1 : completes[1]]
        ), events
    finally:
        await app.stop()
