"""create_financial_plot — implemented (dead code in the reference)."""

import json

import pytest

from finchat_tpu.tools.plot import PlotConfig, create_financial_plot

ROWS = json.dumps([
    {"date": "2026-01", "amount": 120.5, "category": "groceries"},
    {"date": "2026-02", "amount": 80.0, "category": "dining"},
    {"date": "2026-03", "amount": 200.0, "category": "groceries"},
])


@pytest.mark.parametrize("chart", ["line", "bar", "scatter", "histogram"])
def test_chart_types_render(chart):
    uri = create_financial_plot(ROWS, PlotConfig(chart_type=chart))
    assert uri.startswith("data:image/png;base64,")
    assert len(uri) > 500


def test_pie_groups_by_x():
    uri = create_financial_plot(ROWS, PlotConfig(chart_type="pie", x_field="category"))
    assert uri.startswith("data:image/png;base64,")


def test_unknown_chart_type():
    with pytest.raises(ValueError, match="unknown chart_type"):
        create_financial_plot(ROWS, PlotConfig(chart_type="sunburst"))


def test_empty_rows():
    with pytest.raises(ValueError):
        create_financial_plot("[]")


def test_missing_field():
    with pytest.raises(ValueError, match="missing"):
        create_financial_plot(ROWS, PlotConfig(y_field="nope"))
