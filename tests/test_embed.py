"""Embedding encoder + on-device vector index."""

import jax
import numpy as np
import pytest

from finchat_tpu.embed.encoder import EMBED_PRESETS, EmbeddingEncoder, init_bert_params
from finchat_tpu.embed.index import DeviceVectorIndex, VectorPoint
from finchat_tpu.models.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def encoder():
    config = EMBED_PRESETS["bge-tiny"]
    params = init_bert_params(config, jax.random.key(0))
    return EmbeddingEncoder(config, params, ByteTokenizer())


def test_embeddings_normalized(encoder):
    out = encoder.embed_batch(["hello world", "rent payment"])
    assert out.shape == (2, encoder.dim)
    norms = np.linalg.norm(out, axis=-1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)


def test_padding_invariance(encoder):
    """A text's embedding must not depend on which batch/bucket it rode in."""
    solo = encoder.embed_query("coffee shop purchase")
    batched = encoder.embed_batch(["coffee shop purchase", "x" * 100])[0]
    np.testing.assert_allclose(solo, batched, atol=2e-2)


def _point(uid, date, text, vec):
    return VectorPoint(
        id=f"{uid}-{text[:8]}-{date}",
        vector=np.asarray(vec, np.float32),
        payload={"page_content": text, "metadata": {"user_id": uid, "date": date}},
    )


def test_index_user_filter():
    index = DeviceVectorIndex(dim=4, initial_capacity=4)
    index.upsert([
        _point("alice", 100, "alice txn", [1, 0, 0, 0]),
        _point("bob", 100, "bob txn", [1, 0, 0, 0]),
    ])
    hits = index.query_points(np.asarray([1, 0, 0, 0], np.float32), limit=10, user_id="alice")
    assert [h.payload["page_content"] for h in hits] == ["alice txn"]


def test_index_date_filter():
    index = DeviceVectorIndex(dim=4, initial_capacity=4)
    index.upsert([
        _point("u", 100, "old", [1, 0, 0, 0]),
        _point("u", 900, "new", [1, 0, 0, 0]),
    ])
    hits = index.query_points(np.asarray([1, 0, 0, 0], np.float32), limit=10, user_id="u", date_gte=500)
    assert [h.payload["page_content"] for h in hits] == ["new"]


def test_index_ranking_and_limit():
    index = DeviceVectorIndex(dim=4, initial_capacity=8)
    index.upsert([
        _point("u", 0, "exact", [1, 0, 0, 0]),
        _point("u", 0, "close", [0.9, 0.1, 0, 0]),
        _point("u", 0, "far", [0, 0, 1, 0]),
    ])
    hits = index.query_points(np.asarray([1, 0, 0, 0], np.float32), limit=2, user_id="u")
    assert [h.payload["page_content"] for h in hits] == ["exact", "close"]


def test_index_growth_past_capacity():
    index = DeviceVectorIndex(dim=4, initial_capacity=2)
    points = [_point("u", i, f"t{i}", np.eye(4)[i % 4]) for i in range(10)]
    index.upsert(points)
    assert len(index) == 10
    hits = index.query_points(np.asarray([1, 0, 0, 0], np.float32), limit=100, user_id="u")
    assert len(hits) == 10


def test_index_empty():
    index = DeviceVectorIndex(dim=4)
    assert index.query_points(np.zeros(4, np.float32), limit=5, user_id="u") == []
