"""Int8 weight-only quantization (models/quant.py) correctness.

No reference counterpart (the reference has no in-process model); the
contract tested here is the one serving relies on: the rounding error is
bounded per channel, the post-matmul scale is EXACTLY the dequantized
matmul, quantized logits track bf16 logits, and the quantized engine is
deterministic and TP-invariant like the bf16 engine (tests/test_parallel.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.models.llama import LlamaConfig, PRESETS, forward_full, init_params
from finchat_tpu.models.quant import (
    QTensor,
    dense,
    dequantize,
    quantize,
    quantize_llama_params,
)
from finchat_tpu.utils.config import EngineConfig


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.key(0), (64, 96), jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (96,)
    # round-to-nearest: per-element error <= half a quantization step
    err = jnp.abs(dequantize(qt, jnp.float32) - w)
    assert float((err - qt.scale[None, :] / 2).max()) < 1e-6


def test_quantize_zero_column_safe():
    w = jnp.zeros((8, 4), jnp.float32).at[:, 0].set(1.0)
    qt = quantize(w)
    assert np.isfinite(np.asarray(qt.scale)).all()
    np.testing.assert_allclose(np.asarray(dequantize(qt, jnp.float32)), np.asarray(w))


def test_post_matmul_scale_exact():
    """dense(x, qt) must equal x @ dequantize(qt): per-output-column scales
    commute out of the dot."""
    kx, kw = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (4, 32), jnp.float32)
    qt = quantize(jax.random.normal(kw, (32, 16), jnp.float32))
    got = dense(x, qt)
    want = x @ dequantize(qt, jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_stacked_layer_quantize_slices_with_scan():
    """QTensor leaves must slice per-layer under lax.scan like plain stacked
    weights: quantizing the stack == quantizing each layer independently."""
    w = jax.random.normal(jax.random.key(2), (3, 16, 8), jnp.float32)
    stacked = quantize(w)
    for layer in range(3):
        per_layer = quantize(w[layer])
        np.testing.assert_array_equal(np.asarray(stacked.q[layer]), np.asarray(per_layer.q))
        np.testing.assert_allclose(np.asarray(stacked.scale[layer]), np.asarray(per_layer.scale))


@pytest.mark.parametrize("preset", ["tiny", "moe-tiny"])
def test_forward_logits_track_bf16(preset):
    config = PRESETS[preset]
    params = init_params(config, jax.random.key(0))
    qparams = quantize_llama_params(params)
    # norms, embed, and router are untouched; matmul weights are QTensor
    assert isinstance(qparams["layers"]["attn_q"], QTensor)
    assert not isinstance(qparams["layers"]["ln_attn"], QTensor)
    assert not isinstance(qparams["embed"], QTensor)
    if config.n_experts:
        assert not isinstance(qparams["layers"]["router"], QTensor)

    tokens = jax.random.randint(jax.random.key(3), (2, 16), 1, config.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(16)[None], (2, 16))
    ref = forward_full(params, tokens, positions, config=config)
    got = forward_full(qparams, tokens, positions, config=config)
    rel = float(jnp.linalg.norm(got - ref) / jnp.linalg.norm(ref))
    assert rel < 0.08, f"quantized logits diverged: rel err {rel:.3f}"


def test_tied_embeddings_keep_dense_head():
    config = LlamaConfig(tie_embeddings=True)
    qparams = quantize_llama_params(init_params(config, jax.random.key(0)))
    assert "lm_head" not in qparams and not isinstance(qparams["embed"], QTensor)
    tokens = jnp.ones((1, 4), jnp.int32)
    positions = jnp.arange(4)[None]
    logits = forward_full(qparams, tokens, positions, config=config)
    assert logits.shape == (1, 4, config.vocab_size)


def test_engine_rejects_unknown_quant_mode():
    config = PRESETS["tiny"]
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64, prefill_chunk=8)
    with pytest.raises(ValueError, match="unknown quant mode"):
        InferenceEngine(config, init_params(config, jax.random.key(0)), ecfg, quant="fp4")


def _engine_greedy(eng, prompt, n_new):
    alloc = PageAllocator(eng.engine_cfg.num_pages)
    pages = alloc.allocate("s", pages_needed(len(prompt) + n_new, eng.page_size))
    eng.set_page_table_row(0, pages)
    logits = eng.prefill(0, prompt)
    eng.state, tok = commit_first_token(
        eng.state, jnp.int32(0), logits, jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0)
    )
    out = [int(tok)]
    B = eng.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    z, o, zk = jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)
    for _ in range(n_new - 1):
        out.append(int(eng.decode(active, z, o, zk)[0]))
    return out


def test_quantized_engine_matches_quantized_oracle():
    """Paged-engine decode over QTensor params must reproduce the naive
    full-forward greedy decode over the SAME quantized params — the golden
    decode contract (tests/test_engine.py) holds under quantization."""
    config = PRESETS["tiny"]
    params = init_params(config, jax.random.key(0))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=32, max_seq_len=64, prefill_chunk=8)
    eng = InferenceEngine(config, params, ecfg, quant="int8")
    prompt, n_new = [5, 9, 2, 100, 17, 3], 6

    qparams = quantize_llama_params(params)
    seq, want = list(prompt), []
    pad = 32
    positions = jnp.arange(pad)[None]
    for _ in range(n_new):
        tokens = jnp.asarray(seq + [0] * (pad - len(seq)), jnp.int32)[None]
        logits = forward_full(qparams, tokens, positions, config=config)
        nxt = int(jnp.argmax(logits[0, len(seq) - 1]))
        want.append(nxt)
        seq.append(nxt)

    assert _engine_greedy(eng, prompt, n_new) == want


@pytest.mark.parametrize("preset", ["tiny", "moe-tiny"])
def test_streaming_init_matches_init_then_quantize(preset):
    """init_quantized_llama_params (leaf-at-a-time, what lets 8B fit one
    chip) must be numerically identical to quantizing a full init."""
    from finchat_tpu.models.quant import init_quantized_llama_params

    config = PRESETS[preset]
    streamed = init_quantized_llama_params(config, jax.random.key(4))
    full = quantize_llama_params(init_params(config, jax.random.key(4)))

    flat_s, tree_s = jax.tree_util.tree_flatten(streamed)
    flat_f, tree_f = jax.tree_util.tree_flatten(full)
    assert tree_s == tree_f
    for a, b in zip(flat_s, flat_f):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prequantized_params_shard_and_decode():
    """A pre-quantized tree (streaming load path) must shard over TP (the
    QTensor-aware shard_params) and decode identically to engine-side
    quantization of the same weights."""
    from finchat_tpu.models.quant import init_quantized_llama_params
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=64,
    )
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64, prefill_chunk=8)
    prompt, n_new = [5, 9, 2, 100, 17, 3], 6
    mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))

    pre = init_quantized_llama_params(config, jax.random.key(0))
    got = _engine_greedy(
        InferenceEngine(config, pre, ecfg, mesh=mesh, quant="int8"), prompt, n_new)
    want = _engine_greedy(
        InferenceEngine(config, init_params(config, jax.random.key(0)), ecfg,
                        mesh=mesh, quant="int8"), prompt, n_new)
    assert got == want


def test_tp_quantized_engine_matches_unsharded():
    """Quantize-after-shard (engine/engine.py) must not change the tokens:
    TP=8 int8 greedy decode == single-device int8 greedy decode."""
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    config = LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=8, n_kv_heads=8,
        hidden_dim=128, max_seq_len=64,
    )
    params = init_params(config, jax.random.key(0))
    ecfg = EngineConfig(max_seqs=2, page_size=8, num_pages=16, max_seq_len=64, prefill_chunk=8)
    prompt, n_new = [5, 9, 2, 100, 17, 3], 6

    unsharded = _engine_greedy(
        InferenceEngine(config, params, ecfg, quant="int8"), prompt, n_new)
    tp_mesh = build_mesh(MeshSpec(data=1, seq=1, expert=1, model=8))
    sharded = _engine_greedy(
        InferenceEngine(config, params, ecfg, mesh=tp_mesh, quant="int8"), prompt, n_new)
    assert unsharded == sharded


def test_large_leaf_init_skips_fp32_intermediate(monkeypatch):
    """Leaves above FP32_INIT_MAX_ELEMS random-init directly in the model
    dtype (the 8B-on-one-chip HBM fix); patching the threshold to 0
    exercises that branch at test shapes. The branch must produce leaves
    of the same shapes/dtypes and compose with streaming quantization —
    values legitimately differ from the fp32-path init (different
    rounding), which is why the threshold exists instead of switching
    generation dtype globally."""
    import finchat_tpu.models.llama as llama_mod
    from finchat_tpu.models.quant import QTensor, init_quantized_llama_params

    config = PRESETS["mini"]
    baseline = init_params(config, jax.random.key(0))

    monkeypatch.setattr(llama_mod, "FP32_INIT_MAX_ELEMS", 0)
    large_path = init_params(config, jax.random.key(0))
    flat_base, tree_base = jax.tree_util.tree_flatten(baseline)
    flat_large, tree_large = jax.tree_util.tree_flatten(large_path)
    assert tree_base == tree_large
    for a, b in zip(flat_base, flat_large):
        assert a.shape == b.shape and a.dtype == b.dtype
    # generated values stay finite and correctly scaled (fan-in ~ O(1) std)
    q = np.asarray(large_path["layers"]["attn_q"], np.float32)
    assert np.isfinite(q).all() and 0.001 < q.std() < 1.0

    # the streaming quantized init rides the same branch
    streamed = init_quantized_llama_params(config, jax.random.key(0))
    assert isinstance(streamed["layers"]["attn_q"], QTensor)
    assert streamed["layers"]["attn_q"].q.dtype == jnp.int8
