"""Native Orbax checkpoint round-trips (checkpoints/orbax_io.py), including
sharded params on the 8-device CPU mesh and TrainState resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.checkpoints.orbax_io import (
    restore_pytree,
    restore_train_state,
    save_pytree,
    save_train_state,
)
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.parallel.mesh import MeshSpec, build_mesh
from finchat_tpu.parallel.sharding import llama_param_shardings, shard_params


def _trees_equal(a, b) -> bool:
    flat_a, _ = jax.tree.flatten(a)
    flat_b, _ = jax.tree.flatten(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(flat_a, flat_b))


def test_roundtrip_unsharded(tmp_path):
    params = init_params(PRESETS["tiny"], jax.random.key(0))
    save_pytree(tmp_path / "ckpt", params)
    restored = restore_pytree(tmp_path / "ckpt", params)
    assert _trees_equal(params, restored)


def test_roundtrip_sharded_placement_preserved(tmp_path):
    """Params sharded over the model axis restore onto the SAME placement —
    the multi-host boot path (each process reads its own shards)."""
    mesh = build_mesh(MeshSpec(data=2, model=4))
    config = PRESETS["tiny"]  # heads divide 4? tiny: H=4, Hkv=2 -> Hkv*hd=64
    params = init_params(config, jax.random.key(1))
    params = shard_params(params, llama_param_shardings(mesh))

    save_pytree(tmp_path / "ckpt", params)
    restored = restore_pytree(tmp_path / "ckpt", params)
    assert _trees_equal(params, restored)
    # placement preserved, not just values
    orig = params["layers"]["mlp_gate"].sharding
    back = restored["layers"]["mlp_gate"].sharding
    assert back.is_equivalent_to(orig, params["layers"]["mlp_gate"].ndim)


def test_train_state_resume(tmp_path):
    """Step counter + optimizer moments survive a save/restore; training can
    continue from the restored state."""
    from finchat_tpu.train.train_step import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    config = PRESETS["tiny"]
    params = init_params(config, jax.random.key(2))
    optimizer = make_optimizer()
    train_step = make_train_step(config, optimizer, None, use_ring_attention=False)
    state = init_train_state(config, params, optimizer)

    tokens = jax.random.randint(jax.random.key(3), (2, 16), 0, config.vocab_size)
    state, _ = train_step(state, tokens)
    state, loss1 = train_step(state, tokens)

    save_train_state(tmp_path, state)
    restored = restore_train_state(tmp_path, state)
    assert int(restored.step) == int(state.step) == 2
    assert _trees_equal(state.params, restored.params)

    # one more step from each must agree exactly (same math, same state)
    s_a, loss_a = train_step(restored, tokens)
    assert jnp.isfinite(loss_a)
    assert int(s_a.step) == 3
