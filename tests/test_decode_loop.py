"""Fused multi-step decode loop (engine decode_loop_step + scheduler
decode_loop mode).

The contract under test: a K-token block is pure dispatch-amortization —
greedy output is TOKEN-FOR-TOKEN identical to K single steps (including
EOS-mid-block and budget-edge sequences), slots needing per-token host
control are demoted to single-step and rejoin, and warmup covers the new
jit variant so the first block compiles nothing."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token
from finchat_tpu.engine.kv_cache import PageAllocator, pages_needed
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig

CONFIG = PRESETS["tiny"]
K = 4


@pytest.fixture(scope="module")
def params():
    return init_params(CONFIG, jax.random.key(0))


def _engine(params, depth=K, max_seqs=4):
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=64, max_seq_len=128,
        prefill_chunk=8, decode_loop_depth=depth,
    )
    return InferenceEngine(CONFIG, params, cfg)


def _arm_slot(eng, alloc, slot, prompt, n_new, seq_id="s"):
    pages = alloc.allocate(seq_id, pages_needed(len(prompt) + n_new, eng.page_size))
    eng.set_page_table_row(slot, pages)
    logits = eng.prefill(slot, prompt)
    eng.state, tok = commit_first_token(
        eng.state, jnp.int32(slot), logits,
        jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
    )
    return int(tok)


def _greedy_args(B):
    return jnp.zeros((B,)), jnp.ones((B,)), jnp.zeros((B,), jnp.int32)


# --- engine level -----------------------------------------------------------

def test_block_matches_single_steps_greedy(params):
    """One K-block produces exactly the K tokens that K decode_steps would,
    for two slots with different context lengths in the same batch."""
    prompts = {0: [3, 7, 11, 200, 42], 2: [100, 101, 102]}
    n_new = 2 * K + 1

    ref = _engine(params, depth=1)
    ref_alloc = PageAllocator(ref.engine_cfg.num_pages)
    streams = {s: [_arm_slot(ref, ref_alloc, s, p, n_new, seq_id=f"r{s}")]
               for s, p in prompts.items()}
    B = ref.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True).at[2].set(True)
    z, o, zk = _greedy_args(B)
    for _ in range(n_new - 1):
        nxt = ref.decode(active, z, o, zk)
        for s in prompts:
            streams[s].append(int(nxt[s]))

    eng = _engine(params, depth=K)
    alloc = PageAllocator(eng.engine_cfg.num_pages)
    got = {s: [_arm_slot(eng, alloc, s, p, n_new, seq_id=f"g{s}")]
           for s, p in prompts.items()}
    while any(len(v) < n_new for v in got.values()):
        block = np.asarray(eng.decode_loop(active, z, o, zk, eos_id=-1))
        assert block.shape == (K, B)
        for row in block:
            for s in prompts:
                if len(got[s]) < n_new:
                    got[s].append(int(row[s]))
    assert got == streams


def test_block_eos_mid_block_stops_slot(params):
    """A slot sampling eos_id mid-block records the EOS token, then
    free-runs: -1 rows after it, context_lens frozen, while the OTHER slot
    keeps generating through the whole block."""
    prompt = [3, 7, 11, 200, 42]
    other = [9, 8, 7, 6]
    n_new = K + 1

    ref = _engine(params, depth=1)
    stream = [_arm_slot(ref, PageAllocator(ref.engine_cfg.num_pages), 0, prompt, n_new)]
    B = ref.engine_cfg.max_seqs
    active0 = jnp.zeros((B,), bool).at[0].set(True)
    z, o, zk = _greedy_args(B)
    for _ in range(n_new - 1):
        stream.append(int(ref.decode(active0, z, o, zk)[0]))
    eos = stream[2]  # greedy emits this 2 tokens into the block

    eng = _engine(params, depth=K)
    alloc = PageAllocator(eng.engine_cfg.num_pages)
    first0 = _arm_slot(eng, alloc, 0, prompt, n_new, seq_id="a")
    _arm_slot(eng, alloc, 1, other, n_new, seq_id="b")
    assert first0 == stream[0]
    active = jnp.zeros((B,), bool).at[0].set(True).at[1].set(True)
    ctx_before = np.asarray(eng.state.context_lens).copy()
    block = np.asarray(eng.decode_loop(active, z, o, zk, eos_id=eos))
    # slot 0: tokens up to and INCLUDING the EOS, then the -1 sentinel
    assert block[0, 0] == stream[1]
    assert block[1, 0] == stream[2] == eos
    assert block[2, 0] == -1 and block[3, 0] == -1
    # slot 1 generated a real token every iteration
    assert (block[:, 1] >= 0).all()
    ctx = np.asarray(eng.state.context_lens)
    assert ctx[0] == ctx_before[0] + 2  # frozen after EOS
    assert ctx[1] == ctx_before[1] + K


def test_inactive_slots_emit_sentinels_and_stay_frozen(params):
    """Slots inactive at entry produce -1 for every row and gain no
    context — the trash-page free-run contract."""
    eng = _engine(params, depth=K)
    _arm_slot(eng, PageAllocator(eng.engine_cfg.num_pages), 0, [5, 9, 2], K + 1)
    B = eng.engine_cfg.max_seqs
    active = jnp.zeros((B,), bool).at[0].set(True)
    z, o, zk = _greedy_args(B)
    block = np.asarray(eng.decode_loop(active, z, o, zk, eos_id=-1))
    assert (block[:, 1:] == -1).all()
    assert np.asarray(eng.state.context_lens)[1:].tolist() == [0] * (B - 1)


# --- scheduler level --------------------------------------------------------

async def _collect_streams(scheduler, tok, budgets, temperature=0.0):
    handles = []
    for i, n in enumerate(budgets):
        handles.append(await scheduler.submit(
            f"s{i}", tok.encode(f"prompt {i}", add_bos=True),
            SamplingParams(temperature=temperature, max_new_tokens=n),
        ))
    streams = []
    for h in handles:
        toks = []
        while True:
            event = await asyncio.wait_for(h.events.get(), timeout=120)
            if event["type"] == "token":
                toks.append(event["token_id"])
            elif event["type"] == "done":
                assert h.events.empty()
                break
            else:
                raise AssertionError(event)
        streams.append(toks)
    return streams


def _stack(params, depth, eos_id=None, spec_tokens=0, max_seqs=4):
    tok = ByteTokenizer()
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=8, num_pages=128, max_seq_len=128,
        prefill_chunk=16, decode_loop_depth=depth, spec_tokens=spec_tokens,
    )
    engine = InferenceEngine(CONFIG, params, cfg)
    scheduler = ContinuousBatchingScheduler(
        engine, eos_id=tok.eos_id if eos_id is None else eos_id
    )
    return tok, scheduler


def test_scheduler_streams_identical_to_single_step(params):
    """Greedy token streams under decode_loop_depth=K are identical to
    depth 1 — budgets chosen to hit the budget-edge demotion (3 < K never
    rides a block; 7 and 13 end with a sub-K tail of single steps)."""

    async def run(depth):
        tok, scheduler = _stack(params, depth, eos_id=-1)
        await scheduler.start()
        try:
            return await _collect_streams(scheduler, tok, [3, 7, 13])
        finally:
            await scheduler.stop()

    base = asyncio.run(run(1))
    loop = asyncio.run(run(K))
    assert [len(s) for s in base] == [3, 7, 13]
    assert loop == base


def test_scheduler_eos_mid_block_matches_single_step(params):
    """A sequence whose greedy continuation hits EOS mid-block terminates at
    the same token under K-blocks as under single steps, and the slot's
    capacity is reclaimed (free-run tokens never leak into the stream)."""

    async def run(depth, eos_id):
        tok, scheduler = _stack(params, depth, eos_id=eos_id)
        await scheduler.start()
        try:
            streams = await _collect_streams(scheduler, tok, [32])
            assert sorted(scheduler.free_slots) == list(range(4))
            scheduler.allocator.check_invariants()
            return streams
        finally:
            await scheduler.stop()

    # find what greedy emits, then make token at index K+1 (mid-block 2)
    # the EOS id for both runs
    probe = asyncio.run(run(1, -1))[0]
    eos = probe[K + 1]
    base = asyncio.run(run(1, eos))
    loop = asyncio.run(run(K, eos))
    assert loop == base
    # EOS is consumed, not delivered: the stream is the probe prefix
    assert base[0] == probe[: probe.index(eos)]


def test_pipelined_blocks_respect_budget_edge(params):
    """Depth-2 dispatches block N+1 BEFORE consuming block N, so
    eligibility must subtract the K undelivered in-flight tokens: a
    sequence with budget < 2K rides exactly ONE block — a second would
    append up to K KV entries past its page allocation."""

    async def run():
        tok, scheduler = _stack(params, K, eos_id=-1)
        blocks: list[np.ndarray] = []
        real_loop = scheduler.engine.decode_loop

        def spy(active, *a, **kw):
            blocks.append(np.asarray(active).copy())
            return real_loop(active, *a, **kw)

        scheduler.engine.decode_loop = spy
        await scheduler.start()
        try:
            streams = await _collect_streams(scheduler, tok, [K + 2])
            return streams, blocks
        finally:
            await scheduler.stop()

    streams, blocks = asyncio.run(run())
    assert len(streams[0]) == K + 2  # exact budget, no leaked block tokens
    slot_blocks = sum(1 for m in blocks if m.any())
    assert slot_blocks == 1, f"budget-{K + 2} sequence rode {slot_blocks} blocks"


def test_constrained_slot_demoted_to_single_step(params):
    """A grammar-constrained slot must never ride a fused block (its pick
    lands between steps); it advances via the demoted single step while the
    bystander rides blocks, and both streams complete."""
    from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

    async def run():
        tok, scheduler = _stack(params, K, max_seqs=2)
        vocab = GrammarVocab.for_tokenizer(tok)
        block_actives: list[np.ndarray] = []
        real_loop = scheduler.engine.decode_loop

        def spy_loop(active, *args, **kwargs):
            block_actives.append(np.asarray(active).copy())
            return real_loop(active, *args, **kwargs)

        scheduler.engine.decode_loop = spy_loop
        await scheduler.start()
        try:
            bystander = await scheduler.submit(
                "bystander", tok.encode("hello", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=24),
            )
            constrained = await scheduler.submit(
                "tool", tok.encode("decide", add_bos=True),
                SamplingParams(temperature=0.0, max_new_tokens=24),
                constraint=TokenConstraint(vocab),
            )
            by_count = tool_count = 0
            done = {id(bystander): False, id(constrained): False}
            while not all(done.values()):
                progressed = False
                for h in (bystander, constrained):
                    if done[id(h)]:
                        continue
                    try:
                        event = h.events.get_nowait()
                    except asyncio.QueueEmpty:
                        continue
                    progressed = True
                    if event["type"] == "token":
                        if h is bystander:
                            by_count += 1
                        else:
                            tool_count += 1
                    elif event["type"] in ("done", "error"):
                        done[id(h)] = True
                if not progressed:
                    await asyncio.sleep(0.005)
            by_slot, tool_slot = bystander.slot, constrained.slot
            return by_count, tool_count, block_actives
        finally:
            await scheduler.stop()

    by_count, tool_count, block_actives = asyncio.run(run())
    assert by_count == 24  # bystander got its full budget via blocks
    assert tool_count >= 1  # the grammar emitted something
    assert block_actives, "no fused blocks dispatched"
    # exactly one slot (the bystander) ever rides a block
    for active in block_actives:
        assert active.sum() == 1, active


def test_spec_mode_demotes_then_rejoins_blocks(params):
    """With speculative decoding configured, greedy slots run the per-token
    verify cadence first; once the all-miss streak demotes spec
    (SPEC_MISS_DEMOTE), the batch rejoins fused blocks for the cooldown
    window — blocks must appear only after the demotion."""

    async def run():
        tok, scheduler = _stack(params, K, eos_id=-1, spec_tokens=2)
        first_block_cooldown = []
        real_loop = scheduler.engine.decode_loop

        def spy_loop(active, *args, **kwargs):
            first_block_cooldown.append(scheduler._spec_cooldown)
            return real_loop(active, *args, **kwargs)

        scheduler.engine.decode_loop = spy_loop
        await scheduler.start()
        try:
            streams = await _collect_streams(scheduler, tok, [40])
            return streams, first_block_cooldown
        finally:
            await scheduler.stop()

    streams, cooldowns = asyncio.run(run())
    assert len(streams[0]) == 40
    assert cooldowns, "blocks never engaged after spec demotion"
    # every block ran inside a spec-demotion cooldown window
    assert all(c > 0 for c in cooldowns), cooldowns


def test_wasted_tail_metric_counts_free_run(params):
    """EOS mid-block leaves K - delivered device iterations as waste; the
    gauge/counter surface must record them."""
    from finchat_tpu.utils.metrics import METRICS

    async def run(eos_id):
        tok, scheduler = _stack(params, K, eos_id=eos_id)
        await scheduler.start()
        try:
            return await _collect_streams(scheduler, tok, [32])
        finally:
            await scheduler.stop()

    probe = asyncio.run(run(-1))[0]
    eos = probe[K + 1]  # mid-block EOS → a free-run tail
    before = METRICS.get("finchat_decode_loop_wasted_tail_tokens_total")
    asyncio.run(run(eos))
    after = METRICS.get("finchat_decode_loop_wasted_tail_tokens_total")
    assert after > before


def test_demoted_step_pinned_to_membership_snapshot(params):
    """Regression (ISSUE 10 satellite): _dispatch_decode_loop derives BOTH
    of the iteration's dispatches — the fused block AND the demoted-slot
    step — from ONE membership snapshot. The pre-fix code rebuilt the
    demoted step's exclusion set from ``self.decoding`` AFTER the block
    dispatch, so a slot vacated by a mid-iteration fault handler and
    re-populated before the second dispatch was swept into the demoted
    step under a handle that was never in this iteration's membership —
    stepped once there and again by its own next iteration (double-step).
    """
    from finchat_tpu.engine.scheduler import SequenceHandle

    _tok, sched = _stack(params, K, eos_id=-1)
    samp = SamplingParams(temperature=0.0, max_new_tokens=64)
    hA = SequenceHandle(seq_id="A", prompt_ids=[1, 2, 3], sampling=samp, owner=sched)
    hB = SequenceHandle(seq_id="B", prompt_ids=[4, 5], sampling=samp, owner=sched)
    hC = SequenceHandle(seq_id="C", prompt_ids=[6], sampling=samp, owner=sched)
    hA.slot, hB.slot = 0, 1
    hB.generated = 62  # 2 tokens of budget left < K → demoted to single-step
    sched.decoding = {0: hA, 1: hB}
    sched.free_slots.remove(0)
    sched.free_slots.remove(1)

    real_loop = sched.engine.decode_loop

    def hijack(*args, **kwargs):
        blk_tokens = real_loop(*args, **kwargs)
        # simulate a mid-iteration fault handler between the two
        # dispatches: B evicted, its freed slot immediately re-populated
        # by a different handle (the fleet-adoption/readmission shape)
        sched._evict(hB, "error", error="injected mid-iteration fault")
        hC.slot = 1
        sched.free_slots.remove(1)
        sched.decoding[1] = hC
        return blk_tokens

    sched.engine.decode_loop = hijack
    blk = sched._dispatch_decode_loop()

    assert [h.seq_id for _s, h, _e in blk.block_members] == ["A"]
    assert blk.step is not None
    step_ids = [h.seq_id for _s, h, _e in blk.step.members]
    # the demoted step carries the SNAPSHOT member (B — whose eviction the
    # consume-side finished/epoch guard discards), never the slot's new
    # occupant: pre-fix, exclude=set(self.decoding)-demoted put C here
    assert step_ids == ["B"], step_ids
    # consuming delivers nothing to the never-dispatched C and nothing to
    # the evicted B beyond its error event
    asyncio.run(sched._consume_block(blk))
    assert hC.generated == 0 and hC.events.empty()
    assert hA.generated == K
