"""Session KV cache (engine/session_cache.py): cross-turn prefix resume.

Contracts pinned here:
- golden equality: a resumed turn streams byte-identical greedy tokens to a
  cold run of the same prompt (the restored KV is the KV the turn would
  have prefilled itself), and resume skips the matched tokens' prefill;
- divergence truncation: an edited history matches only up to the split
  point and the stored tail is cut — stale KV is never served;
- allocator invariants under offload: offloaded-then-freed pages cannot be
  double-freed, a failed restore returns its allocation cleanly and the
  stream falls back to a cold start, ownership invariants hold through
  churn;
- LRU eviction under the host-RAM byte budget;
- composition with the shared-prefix cache: the constant head's pages are
  referenced (refcounted), never copied, and survive retirement while a
  session entry points at them.
"""

import asyncio

import jax
import numpy as np
import pytest

from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.kv_cache import PageAllocationError
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.models.tokenizer import ByteTokenizer
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.metrics import METRICS

CONFIG = PRESETS["tiny"]
PAGE = 8


def _make_scheduler(max_seqs=4, num_pages=128, session_cache_bytes=64 << 20):
    tok = ByteTokenizer()
    cfg = EngineConfig(
        max_seqs=max_seqs, page_size=PAGE, num_pages=num_pages, max_seq_len=128,
        prefill_chunk=16, session_cache=session_cache_bytes > 0,
        session_cache_bytes=session_cache_bytes,
    )
    params = init_params(CONFIG, jax.random.key(0))
    engine = InferenceEngine(CONFIG, params, cfg)
    return tok, ContinuousBatchingScheduler(engine, eos_id=tok.eos_id)


HEAD = "system: you are a terse financial assistant, answer briefly."


async def _collect(scheduler, seq_id, prompt_ids, n_new, conversation_id=None):
    handle = await scheduler.submit(
        seq_id, prompt_ids, SamplingParams(temperature=0.0, max_new_tokens=n_new),
        conversation_id=conversation_id,
    )
    tokens = []
    while True:
        event = await asyncio.wait_for(handle.events.get(), timeout=120)
        if event["type"] == "token":
            tokens.append(event["token_id"])
        elif event["type"] == "done":
            return handle, tokens
        else:
            raise AssertionError(event)


def _run_turns(scheduler, turns, conversation_id, n_new=8):
    """Run a list of prompts as sequential turns of one conversation;
    returns the per-turn token lists."""

    async def run():
        await scheduler.start()
        try:
            out = []
            for i, prompt in enumerate(turns):
                _, tokens = await _collect(
                    scheduler, f"{conversation_id}-t{i}", prompt, n_new,
                    conversation_id=conversation_id,
                )
                out.append(tokens)
            return out
        finally:
            await scheduler.stop()

    return asyncio.run(run())


def test_turn_resume_is_golden_and_skips_prefill():
    tok = ByteTokenizer()
    p1 = tok.encode(HEAD + " q1: how much did I spend?", add_bos=True)
    n_new = 8

    _, warm = _make_scheduler()
    hits0 = METRICS.get("finchat_session_cache_hits_total")
    restored0 = METRICS.get("finchat_session_cache_restored_tokens_total")

    async def run_warm():
        await warm.start()
        try:
            _, t1 = await _collect(warm, "t1", p1, n_new, conversation_id="c1")
            assert len(warm.session_cache) == 1  # retirement offloaded
            p2 = p1 + t1 + tok.encode(" q2: and last week?", add_bos=False)
            h2, t2 = await _collect(warm, "t2", p2, n_new, conversation_id="c1")
            return t1, p2, t2, h2
        finally:
            await warm.stop()

    t1, p2, t2, h2 = asyncio.run(run_warm())
    assert METRICS.get("finchat_session_cache_hits_total") == hits0 + 1
    matched = METRICS.get("finchat_session_cache_restored_tokens_total") - restored0
    # the whole first turn (prompt + response minus the uncached last token)
    # is page-floored and resumed
    assert matched == ((len(p1) + len(t1) - 1) // PAGE) * PAGE > 0
    warm.allocator.check_invariants()
    assert warm.allocator.used_count == 0  # only host copies remain

    # cold: fresh scheduler, session cache disabled, same turn-2 prompt
    _, cold = _make_scheduler(session_cache_bytes=0)
    assert cold.session_cache is None

    async def run_cold():
        await cold.start()
        try:
            _, t = await _collect(cold, "t2", p2, n_new, conversation_id="c1")
            return t
        finally:
            await cold.stop()

    assert asyncio.run(run_cold()) == t2  # golden equality


def test_divergent_history_truncates_to_matched_prefix():
    tok = ByteTokenizer()
    p1 = tok.encode(HEAD + " q1: list my biggest purchases please", add_bos=True)
    _, scheduler = _make_scheduler()

    trunc0 = METRICS.get("finchat_session_cache_truncations_total")
    restored0 = METRICS.get("finchat_session_cache_restored_tokens_total")

    keep = (len(p1) // 2 // PAGE) * PAGE  # divergence point, page-aligned
    p2 = p1[:keep] + tok.encode("completely different history tail now", add_bos=False)

    t2_warm = _run_turns(scheduler, [p1, p2], "c-div")[1]
    entry = scheduler.session_cache.get("c-div")
    assert METRICS.get("finchat_session_cache_truncations_total") == trunc0 + 1
    # resume restored exactly the shared page-whole prefix, nothing stale
    assert METRICS.get("finchat_session_cache_restored_tokens_total") - restored0 == keep
    # the re-offloaded turn-2 entry covers turn 2's stream, not the old tail
    assert entry is not None and list(entry.token_ids[:keep]) == p2[:keep]

    _, cold = _make_scheduler(session_cache_bytes=0)
    t2_cold = _run_turns(cold, [p2], "c-div")[0]
    assert t2_warm == t2_cold  # truncation served no stale KV


def test_offloaded_then_freed_pages_cannot_be_double_freed():
    tok = ByteTokenizer()
    p1 = tok.encode(HEAD + " q: status?", add_bos=True)
    _, scheduler = _make_scheduler()

    async def run():
        await scheduler.start()
        try:
            h, _ = await _collect(scheduler, "s", p1, 8, conversation_id="c")
            return h
        finally:
            await scheduler.stop()

    handle = asyncio.run(run())
    scheduler.allocator.check_invariants()
    assert len(scheduler.session_cache) == 1
    assert handle.page_list  # pages were recorded at admission...
    with pytest.raises(PageAllocationError):  # ...and freed exactly once
        scheduler.allocator.free(handle.seq_id, handle.page_list)
    # the host snapshot survives reallocation of those device pages
    entry = scheduler.session_cache.get("c")
    snap_k = entry.snap[0].copy()
    reused = scheduler.allocator.allocate("other", len(handle.page_list))
    assert np.array_equal(entry.snap[0], snap_k)
    # return the probe allocation: the leak sanitizer (conftest) audits
    # every stopped scheduler for pages held by dead owners
    scheduler.allocator.free("other", reused)


def test_restore_failure_frees_cleanly_and_falls_back_cold():
    tok = ByteTokenizer()
    p1 = tok.encode(HEAD + " q1: how much did I spend?", add_bos=True)
    n_new = 8

    _, cold = _make_scheduler(session_cache_bytes=0)
    _, scheduler = _make_scheduler()
    boom = {"raised": 0}
    real_restore = scheduler.engine.restore_pages

    def failing_restore(page_ids, host):
        boom["raised"] += 1
        raise RuntimeError("injected restore failure")

    async def run():
        await scheduler.start()
        try:
            _, t1 = await _collect(scheduler, "t1", p1, n_new, conversation_id="c")
            p2 = p1 + t1 + tok.encode(" q2?", add_bos=False)
            scheduler.engine.restore_pages = failing_restore
            try:
                _, t2 = await _collect(scheduler, "t2", p2, n_new, conversation_id="c")
            finally:
                scheduler.engine.restore_pages = real_restore
            return p2, t2
        finally:
            await scheduler.stop()

    p2, t2 = asyncio.run(run())
    assert boom["raised"] == 1  # the resume path was attempted
    scheduler.allocator.check_invariants()
    assert scheduler.allocator.used_count == 0  # nothing leaked
    t2_cold = _run_turns(cold, [p2], "c")[0]
    assert t2 == t2_cold  # the stream survived as a cold start


def test_lru_eviction_under_byte_budget():
    tok = ByteTokenizer()
    _, probe = _make_scheduler()
    p = tok.encode(HEAD + " q1: how much did I spend overall?", add_bos=True)
    _run_turns(probe, [p], "c0")
    one_entry = probe.session_cache.get("c0").nbytes
    assert one_entry > 0

    # budget for two entries; the third insert evicts the LRU conversation
    _, scheduler = _make_scheduler(session_cache_bytes=2 * one_entry)
    ev0 = METRICS.get("finchat_session_cache_evictions_total")
    for i in range(3):
        _run_turns(scheduler, [p], f"c{i}")
    cache = scheduler.session_cache
    assert METRICS.get("finchat_session_cache_evictions_total") == ev0 + 1
    assert cache.get("c0") is None  # least recently used went first
    assert cache.get("c1") is not None and cache.get("c2") is not None
    assert cache.resident_bytes <= cache.budget_bytes
    assert METRICS.get("finchat_session_cache_resident_bytes") == cache.resident_bytes


def test_composes_with_shared_prefix_head():
    tok = ByteTokenizer()
    _, scheduler = _make_scheduler()
    head_ids = tok.encode(HEAD, add_bos=True)
    shared = scheduler.register_prefix(head_ids)
    assert shared > 0
    prefix_pages = scheduler.allocator.used_count

    p1 = head_ids + tok.encode(" q1: what changed?", add_bos=False)
    t1 = _run_turns(scheduler, [p1], "c")[0]
    entry = scheduler.session_cache.get("c")
    # the head rode the shared-prefix entry: referenced, never copied
    assert entry.prefix_len == shared
    assert entry.prefix_entry is scheduler._prefixes[0]
    assert entry.prefix_entry.refs == 1  # held by the session entry
    own_pages = (((len(p1) + len(t1) - 1) // PAGE) * PAGE - shared) // PAGE
    assert entry.snap[0].shape[1] == own_pages  # host copy excludes the head
    assert scheduler.allocator.used_count == prefix_pages  # device: head only

    # a resumed turn references the head pages while the head is LIVE
    p2 = p1 + t1 + tok.encode(" q2: and now?", add_bos=False)
    hits0 = METRICS.get("finchat_session_cache_hits_total")
    t2_warm = _run_turns(scheduler, [p2], "c")[0]
    assert METRICS.get("finchat_session_cache_hits_total") == hits0 + 1
    assert scheduler.allocator.used_count == prefix_pages

    _, cold = _make_scheduler(session_cache_bytes=0)
    assert _run_turns(cold, [p2], "c")[0] == t2_warm  # golden through it all

    # retirement (date rollover) purges entries referencing the retired
    # head — post-rollover prompts diverge inside the head, so keeping the
    # entry would only pin the retired head's device pages indefinitely
    scheduler.retire_prefixes()
    assert len(scheduler.session_cache) == 0
    scheduler.allocator.check_invariants()
    assert scheduler.allocator.used_count == 0  # head pages freed at once
    assert scheduler._prefixes == []


def test_incremental_offload_reuses_prior_snapshot():
    """Turn N's retirement must D2H-copy only the pages written THIS turn;
    pages restored at admission (and never rewritten) reuse the previous
    entry's host bytes — otherwise per-turn offload cost grows linearly
    with history, the exact tax the cache exists to remove."""
    tok = ByteTokenizer()
    _, scheduler = _make_scheduler()
    p1 = tok.encode(HEAD + " q1: spending?", add_bos=True)
    n_new = 8

    async def run():
        await scheduler.start()
        try:
            _, t1 = await _collect(scheduler, "t1", p1, n_new, conversation_id="c")
            off1 = METRICS.get("finchat_session_cache_offloaded_pages_total")
            p2 = p1 + t1 + tok.encode(" q2: more?", add_bos=False)
            _, t2 = await _collect(scheduler, "t2", p2, n_new, conversation_id="c")
            off2 = METRICS.get("finchat_session_cache_offloaded_pages_total")
            return p2, t2, int(off2 - off1)
        finally:
            await scheduler.stop()

    p2, t2, delta = asyncio.run(run())
    matched2 = ((len(p1) + n_new - 1) // PAGE) * PAGE  # resumed at turn 2
    n_tok2 = ((len(p2) + n_new - 1) // PAGE) * PAGE  # turn 2's KV coverage
    assert delta == (n_tok2 - matched2) // PAGE  # only the new pages copied
    assert delta < n_tok2 // PAGE  # strictly less than a full re-copy
    # and the spliced snapshot still resumes byte-identically (turn 3)
    p3 = p2 + t2 + tok.encode(" q3: final?", add_bos=False)
    t3_warm = _run_turns(scheduler, [p3], "c")[0]
    _, cold = _make_scheduler(session_cache_bytes=0)
    assert _run_turns(cold, [p3], "c")[0] == t3_warm


def test_cancel_and_error_do_not_offload():
    tok = ByteTokenizer()
    _, scheduler = _make_scheduler()
    p = tok.encode(HEAD + " q: cancel me", add_bos=True)

    async def run():
        await scheduler.start()
        try:
            handle = await scheduler.submit(
                "s", p, SamplingParams(temperature=0.0, max_new_tokens=48),
                conversation_id="c",
            )
            await asyncio.wait_for(handle.events.get(), timeout=120)  # first token
            scheduler.cancel(handle)
            while True:
                event = await asyncio.wait_for(handle.events.get(), timeout=120)
                if event["type"] == "done":
                    return event
        finally:
            await scheduler.stop()

    event = asyncio.run(run())
    assert event["reason"] == "cancelled"
    assert len(scheduler.session_cache) == 0  # no partial-stream snapshots
    scheduler.allocator.check_invariants()
    assert scheduler.allocator.used_count == 0


def test_top_k_clamp_warning_logged_once_per_value(caplog):
    import logging

    tok = ByteTokenizer()
    _, scheduler = _make_scheduler()
    p = tok.encode("hello", add_bos=True)
    clamped0 = METRICS.get("finchat_top_k_clamped_total")

    async def run():
        with caplog.at_level(logging.WARNING, logger="finchat_tpu.engine.scheduler"):
            for i in range(4):  # same oversized top_k, four requests
                await scheduler.submit(
                    f"s{i}", p,
                    SamplingParams(temperature=0.7, top_k=10_000, max_new_tokens=4),
                )
            await scheduler.submit(  # a DISTINCT clamp value logs again
                "s-other", p,
                SamplingParams(temperature=0.7, top_k=20_000, max_new_tokens=4),
            )

    asyncio.run(run())
    warnings = [r for r in caplog.records if "sampler candidate cap" in r.message]
    assert len(warnings) == 2  # once per distinct top_k, not per request
    # the clamp itself still applied every time
    assert METRICS.get("finchat_top_k_clamped_total") == clamped0 + 5
