# Container image for the TPU worker.
#
# Parity with the reference image (Dockerfile:1-42): slim Python base,
# non-root user, curl healthcheck against /health, env-driven config — but
# the process model differs by design: ONE process per TPU chip/slice (the
# engine owns the device), concurrency via the continuous-batching
# scheduler, replicas scaled at the pod level (SURVEY §2.3). Expected to run
# on a TPU VM image / node pool where libtpu is provided by the host.

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends curl \
    && rm -rf /var/lib/apt/lists/*

# jax[tpu] resolves libtpu on TPU VMs; CPU fallback works out of the box.
# matplotlib: the wired plot tool; orbax: native checkpoints; the serve
# extras (confluent-kafka, pymongo, qdrant-client) are the reference-parity
# external backends.
RUN pip install --no-cache-dir "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir safetensors transformers matplotlib orbax-checkpoint \
       confluent-kafka pymongo qdrant-client

WORKDIR /app
COPY pyproject.toml ./
COPY finchat_tpu ./finchat_tpu
COPY prompts ./prompts

RUN useradd --create-home appuser && chown -R appuser /app
USER appuser

EXPOSE 8000
HEALTHCHECK --interval=30s --timeout=3s --start-period=60s --retries=3 \
    CMD curl -f http://localhost:8000/health || exit 1

CMD ["python", "-m", "finchat_tpu"]
