"""Fault injection registry (SURVEY §5.3).

The reference has zero fault injection; its only resilience machinery is
layered timeouts (main.py:136-159). This registry makes failure paths
first-class testable: production code calls ``inject(site, **ctx)`` at
named sites (a no-op unless a handler is armed), and tests arm handlers
that raise, delay, or drop to drive the degradation contracts:

- per-sequence isolation: an injected prefill/decode fault evicts ONE
  sequence with an error event; the engine keeps serving others;
- Kafka produce loss: fire-and-forget chunks vanish silently (reference
  QoS, kafka_client.py:26-27), error chunks are flushed;
- retrieval failure: the answer is still generated with the Error marker
  (llm_agent.py:129-131).
- tool-streaming plane (ISSUE 9): ``tool.execute`` fires inside every
  tool execution — speculative and serial (``agent/graph.py
  _execute_tool``) — so a test can fail an eagerly-launched tool
  mid-decode and assert the structured-retryable serial fallback;
- durability plane (ISSUE 7): ``disk.spill`` (a failed session-record
  write never fails the retiring stream), ``disk.restore`` (a failed /
  corrupt record read quarantines the file and cold-starts the
  conversation — never a crash, never stale KV), and ``journal.append``
  (a failed answered-id append logs and continues — the cost is one
  possible duplicate answer after a crash, the pre-journal trade).

Sites are plain strings; ``ctx`` carries site-specific identifiers (e.g.
``seq_id``) so a handler can target one victim.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Iterator

Handler = Callable[..., None]

_lock = threading.Lock()
_handlers: dict[str, Handler] = {}


def inject(site: str, **ctx: Any) -> None:
    """Production-side hook: no-op unless a handler is armed for ``site``.
    A handler that raises propagates into the site's own error handling —
    that propagation IS the injected fault."""
    handler = _handlers.get(site)
    if handler is not None:
        handler(**ctx)


def arm(site: str, handler: Handler) -> None:
    with _lock:
        _handlers[site] = handler


def disarm(site: str) -> None:
    with _lock:
        _handlers.pop(site, None)


def disarm_all() -> None:
    with _lock:
        _handlers.clear()


@contextmanager
def armed(site: str, handler: Handler) -> Iterator[None]:
    """Scoped arming for tests."""
    arm(site, handler)
    try:
        yield
    finally:
        disarm(site)


def one_shot(exc: Exception) -> Handler:
    """Handler that raises ``exc`` exactly once, then disarms itself —
    models transient faults (the retry/degrade path must recover)."""
    fired = threading.Event()

    def handler(**_ctx: Any) -> None:
        if not fired.is_set():
            fired.set()
            raise exc

    return handler


def n_shot(n: int, exc: Exception) -> Handler:
    """Handler that raises ``exc`` exactly ``n`` times, then passes —
    models a bounded outage (the circuit breaker's consecutive-failure
    threshold is exactly this shape)."""
    remaining = [n]

    def handler(**_ctx: Any) -> None:
        if remaining[0] > 0:
            remaining[0] -= 1
            raise exc

    return handler


def flaky(rate: float, exc: Exception, seed: int = 0) -> Handler:
    """Handler that raises ``exc`` with probability ``rate`` per call —
    the chaos-sweep fault model (bench.py --chaos-sweep)."""
    import random

    rng = random.Random(seed)

    def handler(**_ctx: Any) -> None:
        if rng.random() < rate:
            raise exc

    return handler


def for_seq(seq_id: str, exc: Exception) -> Handler:
    """Handler that raises only for one victim sequence (ctx['seq_id'])."""

    def handler(**ctx: Any) -> None:
        if ctx.get("seq_id") == seq_id:
            raise exc

    return handler


def for_replica(replica_id: str, inner: Handler) -> Handler:
    """Scope ``inner`` to one fleet replica (ctx['replica'] — each
    replica's scheduler stamps its id on its dispatch sites), so a chaos
    drill can wedge ONE engine while its siblings stay healthy
    (bench.py --fleet-sweep, tests/test_fleet.py)."""

    def handler(**ctx: Any) -> None:
        if ctx.get("replica") == replica_id:
            inner(**ctx)

    return handler
