"""Metrics registry.

The reference has no metrics (SURVEY §5.5); these counters ARE the product's
north-star surface (tok/s/chip, TTFT, queue depth, batch occupancy, KV-page
utilization), exported in Prometheus text format at ``/metrics``.

Decode-loop family (scheduler decode_loop mode, engine decode_loop_step):
``finchat_decode_loop_depth`` (gauge — configured K),
``finchat_decode_loop_blocks_total`` (fused K-token blocks dispatched),
``finchat_decode_loop_wasted_tail_tokens_total`` (device iterations spent
free-running past finished slots — the fixed-shape block's overhead), and
``finchat_decode_loop_demoted_slots`` (gauge — slots currently advancing
via single-step because they need per-token host control).

Session-KV-cache family (engine/session_cache.py, scheduler offload/resume):
``finchat_session_cache_hits_total`` / ``_misses_total`` (admission matches
for conversation-keyed submissions), ``finchat_session_cache_resident_bytes``
and ``finchat_session_cache_entries`` (gauges — host-RAM tier occupancy),
``finchat_session_cache_restored_tokens_total`` (prefill tokens skipped by
resume), ``finchat_session_cache_offloaded_pages_total``,
``finchat_session_cache_evictions_total`` (LRU under the byte budget),
``finchat_session_cache_truncations_total`` (divergent-history cuts), and
the ``finchat_session_offload_seconds`` / ``finchat_session_restore_seconds``
histograms (D2H snapshot / H2D resume latency).

Ragged/mixed-step family (engine ragged_mixed_step, scheduler ragged
path — ISSUE 10): ``finchat_mixed_dispatches_total`` (unified packed
dispatches — one per scheduler iteration on the ragged path),
``finchat_mixed_step_seconds`` (host-side dispatch+fetch time per ragged
round), ``finchat_coexist_iterations_total`` (scheduler iterations where
prefill work and in-flight decodes coexist) and
``finchat_coexist_dispatches_total`` (model dispatches BOOKED to those
iterations by the scheduler's own attribution — together the exact
dispatches-per-coexist-iteration figure bench.py --ragged-sweep reports;
the split path pays >= 2 per such iteration, the ragged path 1),
``finchat_mixed_demotions_total{reason=spec|decode_loop|constrained|ring|
other}`` (coexist iterations demoted to the split path, per reason —
spec/decode_loop/constrained are pre-seeded at zero and stay there since
the ragged rebuild; only ring still fires),
``finchat_warmup_compiled_variants`` (serving-variant count of the last
engine warmup — the collapsed row×chunk×mode matrix), and
``finchat_inter_token_seconds`` — a histogram of per-sequence inter-token
gaps LABELED by ``prefill_concurrent`` ("yes" when the emitting iteration
also ran prefill work, "no" for steady decode), the instrument that makes
the ragged step's admission-stall win visible in Prometheus.

Resilience family (scheduler preemption/breaker/deadline plane, ISSUE 5 —
ROBUSTNESS.md): ``finchat_preemptions_total`` (recompute preemptions —
page-pressure victims plus breaker recovery; each keeps prompt+generated
on the handle and replays through admission), ``finchat_sheds_total``
(pending requests shed past their deadline with a structured retryable
error), ``finchat_overload_rejections_total`` (submits rejected at
``max_queue_depth``), ``finchat_dispatch_failures_total`` (whole-round
dispatch failures feeding the breaker streaks),
``finchat_engine_rebuilds_total`` (breaker trips that tore down and
rebuilt device state), ``finchat_breaker_state`` (gauge: 0 closed, 1 open/
rebuilding, 2 half-open awaiting the probe round), and the recovery-
latency histograms ``finchat_engine_rebuild_seconds`` (teardown→rebuilt)
and ``finchat_breaker_recovery_seconds`` (trip → first successful round).
``finchat_kafka_commits_total`` / ``finchat_kafka_dedupe_skips_total``
instrument the at-least-once option (kafka.commit_after_process).

Fleet family (serve/fleet.py — ISSUE 6): with ``fleet.replicas`` > 1 every
per-engine family above (inter-token, dispatches, breaker_state, session
cache, preemptions, ...) is emitted PER REPLICA via a ``replica`` label —
each replica's scheduler and session cache observe through a
``MetricsRegistry.labeled(replica="N")`` view, so one Prometheus scrape
separates a draining replica's recovery from its siblings' steady state.
Fleet-level series: ``finchat_fleet_replicas_live`` (gauge — LIVE replicas
the router spreads over), ``finchat_fleet_drained_streams_total``
(in-flight streams handed to a sibling by a breaker drain),
``finchat_fleet_drain_failures_total`` (streams the give-up drain could
not place on a sibling — each failed with a retryable ``replica_out``
error; counted once per stream), ``finchat_fleet_session_migrations_total`` /
``finchat_fleet_session_handoffs_total`` (cross-replica session-cache
entry moves: lazy route-time migration / drain-time handoff),
``finchat_fleet_session_import_refused_total`` (imported entry's shared
head had no live twin on the adopter — entry dropped, cold resume),
``finchat_fleet_respawns_total`` (supervisor revivals of a given-up
replica), and ``finchat_fleet_reroutes_total`` (messages routed away
from their affinity replica while it was out).

Durability family (ISSUE 7 — session disk tier, answered-message journal,
graceful drain; per replica like the per-engine families, since the disk
tier observes through its cache's labeled view):
``finchat_durability_spills_total`` / ``finchat_durability_spilled_bytes_
total`` (session records written through to disk) and
``finchat_durability_spill_failures_total``,
``finchat_durability_disk_resident_bytes`` / ``finchat_durability_disk_
entries`` (gauges — record-file tier occupancy),
``finchat_durability_disk_evictions_total`` (disk-tier LRU),
``finchat_durability_disk_restores_total`` + the
``finchat_durability_restore_seconds`` histogram (RAM-miss fall-through
loads), ``finchat_durability_quarantines_total`` (corrupt/truncated
records renamed aside — cold start, never a crash),
``finchat_durability_journal_appends_total`` / ``_journal_replayed_total``
/ ``_journal_append_failures_total`` (answered-id journal), and the
process-level ``finchat_durability_graceful_drains_total`` +
``finchat_durability_shutdown_drain_seconds`` histogram (SIGTERM drain).

Retrieval-plane family (embed/batcher.py microbatcher, embed/index.py
batched search, agent/scheduler overlap):
``finchat_embed_batch_occupancy`` (gauge — texts in the last coalesced
dispatch), ``finchat_embed_queue_depth`` (gauge — texts awaiting a
dispatch), ``finchat_embed_batch_dispatches_total`` /
``finchat_embed_requests_total`` / ``finchat_embed_texts_total``
(dispatches ÷ requests is the coalescing figure of merit; < 1 means the
wait-window is batching cross-request), ``finchat_embed_batch_retries_total``
(coalesced dispatch failed, per-request isolation retries),
``finchat_embed_failures_total``, ``finchat_embed_wait_seconds``
(histogram — queueing delay the window adds), and the per-stage retrieval
latency histograms ``finchat_retrieval_embed_seconds`` /
``finchat_retrieval_search_seconds`` / ``finchat_retrieval_graft_seconds``.
Overlap counters: ``finchat_partial_holds_total`` (static-prefix prefills
started), ``finchat_partial_grafts_total`` (extend_prompt grafted the
full prompt onto a hold), ``finchat_partial_fallbacks_total`` (graft
would have invalidated prefilled KV — serial fallback), and
``finchat_partial_stale_reaps_total`` (abandoned holds reclaimed).

Tracing family (utils/tracing.py — ISSUE 12):
``finchat_span_double_finish_total`` (RequestSpan.finish called again
after the first — idempotent by contract, the counter is the exposure
meter for the preempt-replay / drain-handoff overlap paths) and
``finchat_flight_dumps_total{reason=...}`` (anomaly flight-recorder
dumps written, per anomaly kind). Histograms additionally carry
EXEMPLARS: ``observe(..., trace_id=...)`` keeps the last trace id whose
value landed at/above the p99 bucket, rendered as an OpenMetrics-style
comment after the family and readable via ``exemplar()`` — a latency
spike links straight to ``GET /debug/trace/<trace_id>``.

Tool-streaming family (agent/streamparse.py — ISSUE 9; per engine/replica
via the agent's labeled view like every per-engine family):
``finchat_tool_launches_total`` (speculative + adopted tool executions
dispatched by the launcher), ``finchat_tool_speculative_cancels_total``
(in-flight launches cancelled because a later token committed an
argument that invalidated them, or adoption mismatched),
``finchat_tool_fallbacks_total`` (streaming disengaged for a turn —
parser anomaly, incremental/serial mismatch, or a failed speculative
execution retried on the serial path), and the
``finchat_tool_overlap_saved_seconds`` histogram (per adopted launch,
the slice of tool execution that ran under the remainder of the
decision decode — the latency a serial decide→execute turn pays on top).

Disaggregated-serving family (serve/disagg.py — ISSUE 17; per replica via
the scheduler's labeled view): ``finchat_disagg_role`` (gauge — 0 mixed,
1 prefill, 2 decode: the pool the replica serves in),
``finchat_disagg_handoffs_total`` (cold prompts prefilled on the prefill
pool and imported by a serving replica, counted on the importer),
``finchat_disagg_fallbacks_total{reason=no_prefill_replica|prefill_error|
import_refused|serving_pool_empty}`` (turns that fell back to mixed-style
local prefill, per reason — pre-seeded at zero), and the
``finchat_disagg_handoff_seconds`` histogram (prefill-pool submit →
imported on the serving replica, the full handoff detour).

Warm-fabric family (engine/warm_fabric.py — ISSUE 17; per replica, with
the shared disk tier itself observing its durability family under
``replica="fabric"``): ``finchat_fabric_hits_total`` /
``finchat_fabric_misses_total`` (head-snapshot and session-record lookups
against the cluster-wide fabric, counted on the requesting replica),
``finchat_fabric_import_refused_total`` (fabric hit whose KV snapshot
mode mismatched the engine — cold prefill instead), and the
``finchat_fabric_restore_seconds`` histogram (fabric record → device KV,
covering both shared-head restores and session resumes).

Pod family (serve/pod.py — ISSUE 20; host-level, emitted unlabeled on
the global registry — one host process is one reader):
``finchat_pod_hosts_live`` (gauge — this host plus LIVE peers),
``finchat_pod_heartbeats_total`` / ``finchat_pod_heartbeat_failures_
total`` (liaison pings), ``finchat_pod_peer_deaths_total`` /
``finchat_pod_peer_rejoins_total`` (failure-detector verdicts),
``finchat_pod_partition_adoptions_total`` (partitions inherited across
rebalances) + ``finchat_pod_adopted_ids_replayed_total`` (answered ids
replayed from inherited per-partition journals into the dedupe ring),
``finchat_pod_session_pulls_total`` / ``finchat_pod_pull_misses_total``
(cross-host session transfers; misses are peers that had nothing),
``finchat_pod_breaker_trips_total`` (per-peer liaison circuit breaker),
``finchat_pod_cold_starts_total{reason=breaker_open|peer_unreachable|
transfer_corrupt|import_refused}`` (pod-path failures that fell back to
a cold start — pre-seeded at zero; never a user error), and the
``finchat_pod_transfer_seconds`` histogram (pull request → record
imported).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


def _labeled_key(name: str, labels: dict[str, str] | None) -> str:
    """Internal series key: ``name`` or ``name{k="v",...}`` (labels sorted)
    — one histogram per label combination, Prometheus-style."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _split_key(key: str) -> tuple[str, str]:
    """Inverse of _labeled_key: (base name, label string without braces)."""
    base, _, rest = key.partition("{")
    return base, rest[:-1] if rest else ""


@dataclass
class _Histogram:
    """Fixed-bucket histogram (seconds-scale by default).

    With a ``trace_id`` passed to ``observe``, the histogram keeps an
    EXEMPLAR — the last trace id whose value landed strictly above the
    p99 bucket (the first traced observation seeds it) — so a latency
    spike on a dashboard links straight to that request's exported
    timeline (``/debug/trace/<trace_id>``; ISSUE 12). Bucket-resolution
    "above p99" by design — the exact p99 is not known from bucket
    counts, and the exemplar only has to point at a representative slow
    request."""

    buckets: tuple[float, ...] = (
        0.005, 0.01, 0.025, 0.05, 0.1, 0.2, 0.3, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 100.0,
    )
    counts: list[int] = field(default_factory=list)
    total: float = 0.0
    n: int = 0
    # (trace_id, value, unix_ts) of the last above-p99 observation
    exemplar: tuple[str, float, float] | None = None

    def __post_init__(self) -> None:
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def _bucket_index(self, value: float) -> int:
        for i, edge in enumerate(self.buckets):
            if value <= edge:
                return i
        return len(self.buckets)

    def _q_index(self, q: float) -> int:
        """Index of the bucket containing the q-quantile."""
        target = q * self.n
        seen = 0
        for i in range(len(self.counts)):
            seen += self.counts[i]
            if seen >= target:
                return i
        return len(self.counts) - 1

    def observe(self, value: float, trace_id: str | None = None) -> None:
        self.total += value
        self.n += 1
        idx = self._bucket_index(value)
        self.counts[idx] += 1
        if trace_id is not None:
            # strictly ABOVE the p99 bucket: when 99% of mass sits in one
            # bucket, observations inside it must not churn the exemplar
            # away from the genuine outlier. The first traced observation
            # seeds it so the family always links somewhere.
            if self.exemplar is None or idx > self._q_index(0.99):
                self.exemplar = (trace_id, value, time.time())

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket edges (upper bound of the bucket)."""
        if self.n == 0:
            return 0.0
        target = q * self.n
        seen = 0
        for i, edge in enumerate(self.buckets):
            seen += self.counts[i]
            if seen >= target:
                return edge
        return float("inf")


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms with Prometheus rendering."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, _Histogram] = {}

    def inc(self, name: str, value: float = 1.0,
            labels: dict[str, str] | None = None) -> None:
        with self._lock:
            self._counters[_labeled_key(name, labels)] += value

    def set_gauge(self, name: str, value: float,
                  labels: dict[str, str] | None = None) -> None:
        with self._lock:
            self._gauges[_labeled_key(name, labels)] = value

    def observe(self, name: str, value: float,
                labels: dict[str, str] | None = None,
                trace_id: str | None = None) -> None:
        key = _labeled_key(name, labels)
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = _Histogram()
            self._histograms[key].observe(value, trace_id=trace_id)

    def exemplar(self, name: str,
                 labels: dict[str, str] | None = None) -> tuple[str, float, float] | None:
        """The histogram's last above-p99 ``(trace_id, value, unix_ts)``
        exemplar, or None (ISSUE 12 — a metrics spike links to a
        timeline)."""
        with self._lock:
            hist = self._histograms.get(_labeled_key(name, labels))
            return hist.exemplar if hist else None

    def get(self, name: str, labels: dict[str, str] | None = None) -> float:
        key = _labeled_key(name, labels)
        with self._lock:
            if key in self._counters:
                return self._counters[key]
            return self._gauges.get(key, 0.0)

    def labeled(self, **labels: str) -> "LabeledMetrics":
        """A view of this registry that stamps ``labels`` onto every
        series it touches — how a fleet replica's scheduler and session
        cache emit the same metric families under a ``replica`` label
        without threading label dicts through every call site."""
        return LabeledMetrics(self, labels)

    def quantile(self, name: str, q: float,
                 labels: dict[str, str] | None = None) -> float:
        with self._lock:
            hist = self._histograms.get(_labeled_key(name, labels))
            return hist.quantile(q) if hist else 0.0

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            snap = dict(self._counters)
            snap.update(self._gauges)
            for name, h in self._histograms.items():
                snap[f"{name}_count"] = h.n
                snap[f"{name}_sum"] = h.total
                if h.n:
                    snap[f"{name}_p50"] = h.quantile(0.50)
                    snap[f"{name}_p95"] = h.quantile(0.95)
            return snap

    def render_prometheus(self) -> str:
        lines: list[str] = []
        with self._lock:
            # label variants of one counter/gauge group under a single
            # TYPE line keyed by the BASE name (Prometheus text format
            # wants a metric's series consecutive) — same discipline as
            # the histogram rendering below
            for store, kind in ((self._counters, "counter"), (self._gauges, "gauge")):
                seen: set[str] = set()
                for key in sorted(store, key=_split_key):
                    base, _lbl = _split_key(key)
                    if base not in seen:
                        seen.add(base)
                        lines.append(f"# TYPE {base} {kind}")
                    lines.append(f"{key} {store[key]}")
            # group label variants of one histogram under a single TYPE
            # line (Prometheus text format wants a metric's series
            # consecutive); labeled bucket lines merge the series labels
            # with the le= edge
            seen_types: set[str] = set()
            for key in sorted(self._histograms, key=_split_key):
                base, lbl = _split_key(key)
                h = self._histograms[key]
                if base not in seen_types:
                    seen_types.add(base)
                    lines.append(f"# TYPE {base} histogram")

                def series(extra: str = "") -> str:
                    both = ",".join(x for x in (lbl, extra) if x)
                    return "{" + both + "}" if both else ""

                cumulative = 0
                for i, edge in enumerate(h.buckets):
                    cumulative += h.counts[i]
                    le = 'le="%s"' % edge
                    lines.append(f"{base}_bucket{series(le)} {cumulative}")
                cumulative += h.counts[-1]
                le_inf = 'le="+Inf"'
                lines.append(f"{base}_bucket{series(le_inf)} {cumulative}")
                lines.append(f"{base}_sum{series()} {h.total}")
                lines.append(f"{base}_count{series()} {h.n}")
                if h.exemplar is not None:
                    # OpenMetrics-style exemplar surfaced as a comment so
                    # plain Prometheus 0.0.4 parsers skip it while humans
                    # (and the verify drives) can jump from a spiked
                    # family to `/debug/trace/<trace_id>` (ISSUE 12).
                    # The trace id is CLIENT-CONTROLLED (Kafka message_id
                    # / x-trace-id header) — escape it so an embedded
                    # newline/quote can't terminate the comment and forge
                    # a metric line into the exposition
                    tid, val, ts = h.exemplar
                    safe = (tid.replace("\\", "\\\\").replace('"', '\\"')
                            .replace("\n", "\\n").replace("\r", "\\r"))
                    lines.append(
                        f'# exemplar {key} trace_id="{safe}" value={val} ts={ts}'
                    )
        return "\n".join(lines) + "\n"


class LabeledMetrics:
    """Registry view with a fixed label set merged into every call.

    Drop-in for ``METRICS`` at the call sites the scheduler and session
    cache use (``inc`` / ``set_gauge`` / ``observe`` / ``get`` /
    ``quantile`` and as a ``Timer`` target): a fleet replica constructs
    its scheduler with ``METRICS.labeled(replica="2")`` and every
    existing metric family comes out as ``name{replica="2"}`` series.
    Call-site labels merge OVER the fixed ones (call-site wins on a key
    collision, which never happens for ``replica``)."""

    def __init__(self, registry: MetricsRegistry, labels: dict[str, str]):
        self._registry = registry
        self.labels = {k: str(v) for k, v in labels.items()}

    def _merge(self, labels: dict[str, str] | None) -> dict[str, str]:
        return {**self.labels, **labels} if labels else self.labels

    def inc(self, name: str, value: float = 1.0,
            labels: dict[str, str] | None = None) -> None:
        self._registry.inc(name, value, labels=self._merge(labels))

    def set_gauge(self, name: str, value: float,
                  labels: dict[str, str] | None = None) -> None:
        self._registry.set_gauge(name, value, labels=self._merge(labels))

    def observe(self, name: str, value: float,
                labels: dict[str, str] | None = None,
                trace_id: str | None = None) -> None:
        self._registry.observe(name, value, labels=self._merge(labels),
                               trace_id=trace_id)

    def exemplar(self, name: str,
                 labels: dict[str, str] | None = None) -> tuple[str, float, float] | None:
        return self._registry.exemplar(name, labels=self._merge(labels))

    def get(self, name: str, labels: dict[str, str] | None = None) -> float:
        return self._registry.get(name, labels=self._merge(labels))

    def quantile(self, name: str, q: float,
                 labels: dict[str, str] | None = None) -> float:
        return self._registry.quantile(name, q, labels=self._merge(labels))


# Process-global registry (one worker process = one registry, matching the
# reference's one-logger-per-process pattern).
METRICS = MetricsRegistry()


class Timer:
    """Context manager: ``with Timer(METRICS, "prefill_seconds"): ...``"""

    def __init__(self, registry: MetricsRegistry, name: str) -> None:
        self._registry = registry
        self._name = name
        self.elapsed = 0.0

    def __enter__(self) -> "Timer":
        self.started = time.perf_counter()
        self._start = self.started
        return self

    def __exit__(self, *exc: object) -> None:
        self.elapsed = time.perf_counter() - self._start
        self._registry.observe(self._name, self.elapsed)
