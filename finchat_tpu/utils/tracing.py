"""Request-span tracing.

The reference has none (SURVEY §5.1). Two planes here:

1. Host spans — per-request lifecycle timing (queue → prefill → first token →
   done), recorded into the metrics registry and debug logs.
2. Device traces — ``jax.profiler`` capture (TensorBoard/Perfetto dumps) and
   ``jax.named_scope`` annotations around kernel regions, toggled at runtime.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Iterator

import jax

from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS, MetricsRegistry

logger = get_logger(__name__)


@dataclass
class RequestSpan:
    """Lifecycle timestamps for one request through the serving stack."""

    request_id: str
    created_at: float = field(default_factory=time.perf_counter)
    marks: dict[str, float] = field(default_factory=dict)

    def mark(self, name: str) -> None:
        self.marks[name] = time.perf_counter() - self.created_at

    def ttft(self) -> float | None:
        """Time to first token, if the request got that far."""
        return self.marks.get("first_token")

    def finish(self, registry: MetricsRegistry = METRICS) -> None:
        # TTFT is observed at first-token time by the scheduler (so the
        # histogram is live mid-request); here only the total is recorded.
        self.mark("done")
        registry.observe("finchat_request_seconds", self.marks["done"])
        logger.debug(
            "span %s: %s",
            self.request_id,
            " ".join(f"{k}={v * 1e3:.1f}ms" for k, v in sorted(self.marks.items(), key=lambda kv: kv[1])),
        )


@contextlib.contextmanager
def named_scope(name: str) -> Iterator[None]:
    """jax.named_scope wrapper that is a no-op outside a trace."""
    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (view in TensorBoard / Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", log_dir)
