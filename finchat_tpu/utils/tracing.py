"""Request tracing + anomaly flight recorder (ISSUE 12).

The reference has none (SURVEY §5.1). Three planes here:

1. **Host spans** — :class:`RequestSpan`, per-request lifecycle marks
   (queue → admit → prefill → first token → done) recorded into the
   metrics registry, debug logs, AND — when the request carries a
   ``trace_id`` — the process trace ring below.
2. **Structured trace events** — :class:`Tracer`, a bounded per-process
   ring buffer of ``(ts, trace_id, name, dur, track, args)`` tuples.
   A ``trace_id`` is minted at ingress (Kafka ``message_id`` / HTTP
   ``x-trace-id`` header) and threaded app → agent → tool launcher →
   generator → scheduler; dispatch events additionally record which
   ``(slot, trace_id, mode)`` rows rode each ragged dispatch, so
   per-request device time is attributable even when many requests share
   one dispatch. Events stamp exclusively from host data the code already
   holds — appending to the ring is a deque append, ZERO host syncs are
   added on the hot path (finchat-lint R2 polices the seam).
   ``GET /debug/trace/<trace_id>`` exports one request's correlated
   timeline as Chrome trace-event JSON (opens in Perfetto).
3. **Flight recorder** — on anomaly (breaker trip, watchdog fire, shed,
   replica give-up, record quarantine, SIGTERM drain) the anomaly is
   recorded as its own event and the whole ring is dumped to a
   checksummed file under ``tracing.flight_dir`` — a black box for
   exactly the failure drills ROBUSTNESS.md scripts. Dumps are written
   off-loop (a worker thread) and rate-limited per anomaly kind so an
   anomaly storm cannot grind serving; ``flush_dumps`` joins the writers
   (the graceful drain calls it through ``asyncio.to_thread``).

Plus the original device plane: ``jax.profiler`` capture and
``jax.named_scope`` annotations, unchanged.

Every ``mark()``/event name MUST come from the registries below —
finchat-lint R5's span-discipline check enforces it statically, because a
typo'd mark name otherwise just silently vanishes from every timeline.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator

import jax

from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS, MetricsRegistry

logger = get_logger(__name__)

# ---------------------------------------------------------------------------
# the span/event name registries (finchat-lint R5 span-discipline source
# of truth: every ``span.mark(...)`` / ``TRACER.event(...)`` /
# ``TRACER.anomaly(...)`` literal must appear here)
# ---------------------------------------------------------------------------

#: RequestSpan lifecycle marks (scheduler + agent planes).
SPAN_MARKS = frozenset({
    # scheduler lifecycle (engine/scheduler.py)
    "admitted", "prefill_done", "first_token", "done",
    # agent/tool plane (agent/graph.py, agent/streamparse.py — ISSUE 9
    # overlap made visible per request)
    "decide_start", "name_commit", "tool_launch", "tool_adopted",
    "response_prefill_hold",
})

#: Structured events that are not per-request span marks.
TRACE_EVENTS = frozenset({
    "ingress",          # request entered the serving plane (Kafka/HTTP)
    "dispatch",         # one model dispatch; args.rows = [[slot, tid, mode]]
    "preempt",          # recompute preemption (page pressure / breaker)
    "adopt",            # fleet sibling adopted a drained handle
    "drain_handoff",    # breaker drain handed a stream to a sibling
    "session_migrate",  # session-cache bytes moved between replicas
    "request",          # whole-request complete span (emitted at finish)
    # a membership epoch invalidated an in-flight free-run capture: the
    # drain discarded stale residual ring tokens (replayed exactly once
    # via preempt/replay) — the capture/replay boundary on the timeline
    "freerun_epoch_break",
    # bounded-KV eviction wave (ISSUE 15): args carry the evicted page
    # count and the affected slots — page occupancy drops are attributable
    # on the timeline without any per-token cost
    "boundedkv_evict",
    # disaggregated serving (ISSUE 17): a prefill-pool replica ran a cold
    # prompt and handed its KV to the serving replica before admission —
    # args carry source/target replicas and the token count
    "disagg_handoff",
    # warm-state fabric hit (ISSUE 17): a shared-head or session restore
    # served from the cluster-wide fabric instead of a local prefill —
    # args.kind distinguishes "head" from "session"
    "fabric_hit",
    # pod plane (ISSUE 20): a survivor adopted a dead host's partitions —
    # args carry the dead host, the inherited partitions, and how many
    # journaled ids replayed into the dedupe ring
    "pod_adopt",
    # pod plane (ISSUE 20): a conversation's session bytes were pulled
    # from a liaison peer and imported warm — args carry peer and bytes
    "pod_session_pull",
})

#: Anomaly kinds — each records an event AND triggers a flight dump.
ANOMALY_KINDS = frozenset({
    "breaker_trip", "watchdog_timeout", "shed", "replica_give_up",
    "record_quarantine", "sigterm_drain",
    # free-run ring replay mismatch: a captured round emitted where the
    # staged descriptor plan never armed a row (ISSUE 13) — the drain
    # refuses the unarmed cells and dumps the black box
    "freerun_divergence",
    # pod plane (ISSUE 20): a liaison peer missed enough heartbeats to be
    # declared dead — the host failure domain tripped; partition adoption
    # follows
    "pod_host_lost",
})

TRACE_EVENT_NAMES = SPAN_MARKS | TRACE_EVENTS | ANOMALY_KINDS

#: Per-row modes a ``dispatch`` event's ``args.rows`` may carry (the third
#: element of each ``[slot, trace_id, mode]`` row) — declared so timeline
#: consumers and tests have one source of truth.
DISPATCH_ROW_MODES = frozenset({
    "prefill", "prefix", "decode", "decode_loop", "spec", "constrained",
    "ring", "freerun",
})

#: Serving quant-mode labels a ``dispatch`` event's ``args.quant`` may
#: carry (ISSUE 14): the engine's weight mode ("bf16" = unquantized
#: native dtype, "int8", "int4") with "+kv8" appended when the KV page
#: pool is int8 — ``InferenceEngine.quant_label`` must stay inside this
#: set (pinned by tests/test_quant_serving.py), so traced timelines can
#: always distinguish bf16 from quantized dispatches.
QUANT_MODES = frozenset({
    "bf16", "int8", "int4", "bf16+kv8", "int8+kv8", "int4+kv8",
})

_FLIGHT_MAGIC = "FINCHAT-FLIGHT v1"
# per-kind dump rate limit: an anomaly storm (e.g. a shed wave) records
# every EVENT but writes at most one black box per kind per window
_DUMP_MIN_INTERVAL_S = 5.0


def _chrome_event(ev: tuple) -> dict:
    """One ring tuple → one Chrome trace-event object (Perfetto-loadable:
    ``X`` complete events for spans with a duration, ``i`` instants
    otherwise; timestamps in µs on the perf_counter clock)."""
    ts, trace_id, name, dur, track, args = ev
    out: dict = {
        "name": name,
        "cat": "finchat",
        "ph": "X" if dur is not None else "i",
        "ts": round(ts * 1e6, 1),
        "pid": 0,
        "tid": str(track),
        "args": dict(args) if args else {},
    }
    if trace_id is not None:
        out["args"]["trace_id"] = trace_id
    if dur is not None:
        out["dur"] = round(dur * 1e6, 1)
    else:
        out["s"] = "t"  # instant scope: thread
    return out


def _event_carries(ev: tuple, trace_id: str) -> bool:
    """Does this ring tuple belong on ``trace_id``'s timeline? Either it
    is stamped with the id, or it is a dispatch event whose row list
    carries the id (many requests share one ragged dispatch — the PR 10
    coexist attribution made the rows host-known)."""
    if ev[1] == trace_id:
        return True
    args = ev[5]
    if args:
        rows = args.get("rows")
        if rows:
            return any(r[1] == trace_id for r in rows)
    return False


class Tracer:
    """Process-global bounded trace ring + flight recorder.

    Appends are a single ``deque.append`` of a pre-built tuple — safe from
    the event loop and worker threads alike (CPython deque appends are
    atomic), no locks on the hot path. Everything heavier (export, dumps)
    snapshots the ring first.
    """

    def __init__(self, ring_events: int = 65536):
        self.enabled = True
        self.flight_dir = ""
        self._ring: deque = deque(maxlen=max(16, ring_events))
        self._lock = threading.Lock()  # config + dump bookkeeping only
        self._dump_seq = 0
        self._last_dump: dict[str, float] = {}
        self._dump_threads: list[threading.Thread] = []

    # --- configuration ---------------------------------------------------
    def configure(self, enabled: bool | None = None,
                  ring_events: int | None = None,
                  flight_dir: str | None = None) -> None:
        """Apply the ``tracing.*`` knobs (utils/config.py TracingConfig).
        Resizing the ring keeps the most recent events."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if flight_dir is not None:
                self.flight_dir = flight_dir
            if ring_events is not None and ring_events != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(16, ring_events))

    def clear(self) -> None:
        self._ring.clear()

    # --- event recording -------------------------------------------------
    def event(self, name: str, trace_id: str | None = None, *,
              ts: float | None = None, dur: float | None = None,
              track: str = "main", args: dict | None = None) -> None:
        """Append one event to the ring. ``ts``/``dur`` are perf_counter
        seconds; ``dur`` set → a complete ("X") span, else an instant.
        ``name`` must come from the tracing registries (finchat-lint R5).
        No-op when tracing is disabled — callers on hot paths should
        additionally guard row-building with ``TRACER.enabled``."""
        if not self.enabled:
            return
        self._ring.append((
            ts if ts is not None else time.perf_counter(),
            trace_id, name, dur, track, args,
        ))

    @contextlib.contextmanager
    def span(self, name: str, trace_id: str | None = None, *,
             track: str = "main", args: dict | None = None) -> Iterator[None]:
        """Record a complete ("X") event spanning the with-block."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.event(name, trace_id, ts=t0,
                       dur=time.perf_counter() - t0, track=track, args=args)

    def anomaly(self, kind: str, trace_id: str | None = None,
                args: dict | None = None) -> None:
        """Record an anomaly event and dump the ring alongside it (the
        flight recorder). No-op with tracing disabled (an empty/stale ring
        is not a black box); the dump is rate-limited per kind and written
        off-loop."""
        if not self.enabled:
            return
        self.event(kind, trace_id, track="anomaly", args=args)
        self.flight_dump(kind, trace_id=trace_id, args=args)

    # --- export ----------------------------------------------------------
    def snapshot(self) -> list[tuple]:
        return list(self._ring)

    def export(self, trace_id: str) -> dict:
        """One request's correlated timeline as Chrome trace-event JSON
        (``{"traceEvents": [...]}`` — open in Perfetto / chrome://tracing):
        every event stamped with ``trace_id`` plus every dispatch whose
        row list carried it."""
        events = [
            _chrome_event(ev) for ev in self.snapshot()
            if _event_carries(ev, trace_id)
        ]
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace_id},
        }

    # --- flight recorder -------------------------------------------------
    def flight_dump(self, reason: str, trace_id: str | None = None,
                    args: dict | None = None) -> str | None:
        """Dump the ring to a checksummed file under ``flight_dir``
        (pre-reserved filename returned immediately; the serialize+write
        runs in a worker thread so an anomaly on the scheduler loop never
        blocks serving — finchat-lint R1's seam). Returns the dump path,
        or None when the recorder is disabled or rate-limited."""
        with self._lock:
            if not self.flight_dir:
                return None
            now = time.monotonic()
            last = self._last_dump.get(reason)
            if last is not None and now - last < _DUMP_MIN_INTERVAL_S:
                return None
            self._last_dump[reason] = now
            self._dump_seq += 1
            seq = self._dump_seq
        events = self.snapshot()  # snapshot NOW; the writer thread races nothing
        path = os.path.join(
            self.flight_dir, f"flight-{seq:04d}-{reason}.json"
        )
        t = threading.Thread(
            target=self._write_dump, args=(path, reason, trace_id, args, events),
            daemon=True, name=f"flight-dump-{seq}",
        )
        with self._lock:
            self._dump_threads = [x for x in self._dump_threads if x.is_alive()]
            self._dump_threads.append(t)
        t.start()
        return path

    def _write_dump(self, path: str, reason: str, trace_id: str | None,
                    args: dict | None, events: list[tuple]) -> None:
        try:
            payload = json.dumps({
                "reason": reason,
                "trace_id": trace_id,
                "anomaly_args": args,
                "wall_time": time.time(),
                "trace": {
                    "traceEvents": [_chrome_event(ev) for ev in events],
                    "displayTimeUnit": "ms",
                },
            }, default=str).encode()
            header = f"{_FLIGHT_MAGIC} crc32={zlib.crc32(payload):08x} bytes={len(payload)}\n"
            Path(path).parent.mkdir(parents=True, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(header.encode())
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            METRICS.inc("finchat_flight_dumps_total", labels={"reason": reason})
            logger.warning("flight recorder: %d events dumped to %s (%s)",
                           len(events), path, reason)
        except Exception as e:  # the black box is best-effort by contract
            logger.error("flight recorder: dump to %s failed: %s", path, e)

    def flush_dumps(self, timeout: float = 10.0) -> None:
        """Join in-flight dump writers (call via ``asyncio.to_thread`` from
        async code — the graceful drain does, so the black box lands on
        disk before the process exits)."""
        with self._lock:
            threads = list(self._dump_threads)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        with self._lock:
            self._dump_threads = [x for x in self._dump_threads if x.is_alive()]


def load_flight_dump(path: str) -> dict:
    """Parse + verify a flight-recorder file. Raises ``ValueError`` on a
    bad magic, truncation, or checksum mismatch — the black box must be
    trustworthy or loudly not."""
    raw = Path(path).read_bytes()
    nl = raw.find(b"\n")
    if nl < 0:
        raise ValueError(f"{path}: truncated flight dump (no header)")
    header = raw[:nl].decode("latin-1")
    if not header.startswith(_FLIGHT_MAGIC):
        raise ValueError(f"{path}: bad flight-dump magic {header[:32]!r}")
    fields = dict(
        kv.split("=", 1) for kv in header[len(_FLIGHT_MAGIC):].split() if "=" in kv
    )
    payload = raw[nl + 1:]
    if len(payload) != int(fields.get("bytes", -1)):
        raise ValueError(f"{path}: truncated flight dump "
                         f"({len(payload)} != {fields.get('bytes')} bytes)")
    if zlib.crc32(payload) != int(fields.get("crc32", "-1"), 16):
        raise ValueError(f"{path}: flight dump checksum mismatch")
    return json.loads(payload.decode())


# Process-global tracer (one worker process = one ring, matching METRICS).
TRACER = Tracer()


@dataclass
class RequestSpan:
    """Lifecycle timestamps for one request through the serving stack.

    ``mark()`` names must come from :data:`SPAN_MARKS` (finchat-lint R5).
    With a ``trace_id``, every mark also lands in the process trace ring,
    and ``finish()`` additionally emits the whole-request "request" span.
    ``finish()`` is IDEMPOTENT — it is invoked from many scheduler sites
    (shed, evict, drain, give-up, rebuild-failure) whose flows can
    overlap on the preempt-replay and drain-handoff paths; the first call
    wins, later calls are counted in ``finchat_span_double_finish_total``
    and change nothing.
    """

    request_id: str
    trace_id: str | None = None
    created_at: float = field(default_factory=time.perf_counter)
    marks: dict[str, float] = field(default_factory=dict)
    finished: bool = False

    def mark(self, name: str) -> None:
        now = time.perf_counter()
        self.marks[name] = now - self.created_at
        if self.trace_id is not None and TRACER.enabled:
            TRACER.event(name, self.trace_id, ts=now, track="request")

    def ttft(self) -> float | None:
        """Time to first token, if the request got that far."""
        return self.marks.get("first_token")

    def finish(self, registry: MetricsRegistry = METRICS) -> None:
        if self.finished:
            # second finish (preempt-replay / drain-handoff overlap):
            # first call won — count it, change nothing
            registry.inc("finchat_span_double_finish_total")
            return
        self.finished = True
        # TTFT is observed at first-token time by the scheduler (so the
        # histogram is live mid-request); here only the total is recorded.
        self.mark("done")
        registry.observe("finchat_request_seconds", self.marks["done"],
                         trace_id=self.trace_id)
        if self.trace_id is not None and TRACER.enabled:
            TRACER.event("request", self.trace_id, ts=self.created_at,
                         dur=self.marks["done"], track="request",
                         args={"request_id": self.request_id})
        logger.debug(
            "span %s: %s",
            self.request_id,
            " ".join(f"{k}={v * 1e3:.1f}ms" for k, v in sorted(self.marks.items(), key=lambda kv: kv[1])),
        )


@contextlib.contextmanager
def named_scope(name: str) -> Iterator[None]:
    """jax.named_scope wrapper that is a no-op outside a trace."""
    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace (view in TensorBoard / Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", log_dir)
