"""Logger factory.

Keeps the reference's operational contract (``config.py:49-80``): LOG_LEVEL
env var with a whitelist, one-time root configuration, the exact
``[%(levelname)s] %(asctime)s |%(name)s| %(message)s`` line format, and noise
suppression for chatty third-party libraries.
"""

from __future__ import annotations

import logging
import os

_LINE_FORMAT = "[%(levelname)s] %(asctime)s |%(name)s| %(message)s"
_ALLOWED_LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR")

# Libraries whose INFO logs drown ours; parity with reference config.py:69-78,
# extended with the jax ecosystem.
_NOISY_LOGGERS = (
    "pymongo",
    "pymongo.topology",
    "confluent_kafka",
    "uvicorn",
    "uvicorn.access",
    "jax._src.xla_bridge",
    "jax._src.dispatch",
    "asyncio",
)


def get_logger(name: str) -> logging.Logger:
    """Return a configured logger for a module (usually ``__name__``).

    Root configuration happens once, on first call, honoring ``LOG_LEVEL``.
    """
    level = os.getenv("LOG_LEVEL", "INFO").upper()
    if level not in _ALLOWED_LEVELS:
        level = "INFO"

    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=getattr(logging, level), format=_LINE_FORMAT)
        for noisy in _NOISY_LOGGERS:
            logging.getLogger(noisy).setLevel(logging.WARNING)

    return logging.getLogger(name)
