"""Typed configuration tree.

Drop-in env compatibility with the reference's ``config.py:8-47`` — every
env-var name the reference reads keeps working here — plus the sections the
reference has no counterpart for (model, mesh, engine, scheduler), which are
new TPU-framework surface.

Hardcoded constants preserved from the reference:
  topics ``user_message`` / ``ai_response`` (config.py:26-27), consumer group
  ``message_consumer`` (config.py:28), Mongo collections ``contexts`` /
  ``messages`` (config.py:32-33), vector collection ``transactions``
  (config.py:47).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any

# ---------------------------------------------------------------------------
# Constants that are part of the product contract (not configurable in the
# reference either).
# ---------------------------------------------------------------------------
USER_MESSAGE_TOPIC = "user_message"
AI_RESPONSE_TOPIC = "ai_response"
# NEW topic (no reference counterpart): transaction rows for vector-index
# ingestion — the reference's upsert pipeline lives outside its repo.
TRANSACTION_UPSERT_TOPIC = "transaction_upsert"
GROUP_ID = "message_consumer"
CONTEXT_COLLECTION_NAME = "contexts"
MESSAGE_COLLECTION_NAME = "messages"
TRANSACTION_COLLECTION_NAME = "transactions"


def _env(name: str, default: str = "") -> str:
    return os.getenv(name, default)


def _env_bool(name: str, default: bool) -> bool:
    raw = os.getenv(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")


def _env_int(name: str, default: int) -> int:
    raw = os.getenv(name)
    if raw is None or raw == "":
        return default
    return int(raw)


def _env_float(name: str, default: float) -> float:
    raw = os.getenv(name)
    if raw is None or raw == "":
        return default
    return float(raw)


@dataclass
class KafkaConfig:
    """Transport settings; mirrors reference ``config.py:8-28``."""

    bootstrap_servers: str = ""
    username: str = ""
    password: str = ""
    session_timeout_ms: int = 45_000
    client_id: str = "python-client-1"
    auto_offset_reset: str = "latest"
    # "memory" = in-process broker (tests/dev); "confluent" = librdkafka.
    backend: str = "memory"
    # partitions per topic. The process-wide memory broker is created with
    # this count by the FIRST KafkaClient (an explicitly shared broker
    # wins; a count mismatch warns and the broker's count is used for
    # routing); on the confluent backend it must MATCH how the real topics
    # were created — the fleet router hashes conversation keys mod this
    # count (io/kafka.py partition_for_key), so a mismatch silently breaks
    # the routing ≡ partition-assignment alignment (serve/fleet.py). Also
    # FINCHAT_KAFKA_NUM_PARTITIONS.
    num_partitions: int = 4
    # at-least-once delivery (default off = reference at-most-once parity):
    # disable poll-time auto-commit and commit offsets only AFTER the
    # watchdog-wrapped handler completes, so a worker crash mid-message
    # redelivers it to the group instead of silently losing it. The app
    # pairs this with an in-memory per-message_id dedupe ring so
    # SAME-PROCESS redelivery (rebalance, producer retry) doesn't
    # double-answer; pair with journal.path (JournalConfig) to close the
    # crash-redelivery window too — the answered-id journal replays into
    # the ring at restart (serve/app.py; ROBUSTNESS.md §5).
    commit_after_process: bool = False
    # memory-broker committed offsets persist to this directory (defaults
    # to journal.path when that is set), so a restart drill that stands up
    # a fresh broker rewinds to the committed watermark exactly like a
    # real consumer group; "" with no journal = in-memory only. The
    # confluent backend ignores this (the real broker is durable).
    # Also FINCHAT_KAFKA_OFFSETS_DIR.
    offsets_dir: str = ""

    def librdkafka_config(self) -> dict[str, str]:
        """Render the confluent-kafka config dict, including the SASL_SSL ↔
        PLAINTEXT switch the reference performs (config.py:15-23)."""
        cfg: dict[str, str] = {"bootstrap.servers": self.bootstrap_servers}
        if self.username and self.password:
            cfg.update(
                {
                    "security.protocol": "SASL_SSL",
                    "sasl.mechanisms": "PLAIN",
                    "sasl.username": self.username,
                    "sasl.password": self.password,
                }
            )
        else:
            cfg["security.protocol"] = "PLAINTEXT"
        return cfg


@dataclass
class StoreConfig:
    """Conversation store; mirrors reference Mongo usage (``database.py``)."""

    mongodb_uri: str = ""
    database_name: str = "conversations"
    # "memory" = in-process store; "mongo" = pymongo (requires the wheel).
    backend: str = "memory"


@dataclass
class VectorConfig:
    """Vector index over user transactions.

    The reference delegates to a remote Qdrant (``tools/qdrant_tool.py``);
    here the DEFAULT backend is the in-tree on-device index (brute-force
    exact cosine on the MXU) with a local durable snapshot
    (``persist_path``). Setting ``QDRANT_URL`` (the reference's env name,
    .env drop-in compatible) selects the external Qdrant backend instead
    (tools/qdrant_retriever.py) for deployments with an existing
    populated cluster; embeddings stay on-device either way.
    """

    url: str = ""
    api_key: str = ""
    collection: str = TRANSACTION_COLLECTION_NAME  # finchat-lint: disable=knob-consistency -- product-contract constant (reference config.py:47 keys the Qdrant collection); config-file override only, by design
    default_limit: int = 10_000  # finchat-lint: disable=knob-consistency -- reference-parity constant (qdrant_tool.py:145); config-file override only, by design
    persist_path: str = ""  # snapshot directory; empty = in-memory only

    def snapshot_base(self) -> str:
        """Snapshot file base: ``<persist_path>/<collection>`` — the
        collection name keys the on-disk layout the way it keys the
        reference's Qdrant collection (config.py:47)."""
        if not self.persist_path:
            return ""
        import pathlib

        return str(pathlib.Path(self.persist_path) / self.collection)


@dataclass
class ModelConfig:
    """Which decoder to serve and how to load it (no reference counterpart)."""

    preset: str = "tiny"  # see models/llama.py PRESETS
    checkpoint_path: str = ""  # HF safetensors dir; empty = random init
    tokenizer_path: str = ""  # HF tokenizer dir; empty = byte tokenizer
    dtype: str = "bfloat16"
    seed: int = 0
    # weight-only quantized serving (models/quant.py): "" (full precision)
    # | "int8" (per-output-channel scales) | "int4" (two nibbles per byte,
    # per-channel or per-group scales) — halves / quarters weight HBM
    # traffic on the decode hot path. Also FINCHAT_QUANT.
    quant: str = ""
    # int4 scale group size along the contraction axis (rows of K per
    # scale); 0 = one scale per output channel. Smaller groups tighten the
    # quant-error envelope at ~fp32/group_size extra scale bytes. Ignored
    # for int8. Also FINCHAT_QUANT_GROUP.
    quant_group: int = 0


@dataclass
class MeshConfig:
    """Device mesh axes (no reference counterpart — reference has no devices).

    Axis names follow the scaling-book convention: ``data`` (DP/batch),
    ``pipe`` (PP stages), ``model`` (TP), ``seq`` (SP/ring attention),
    ``expert`` (EP). A size of -1 means "absorb all remaining devices".
    """

    data: int = 1
    pipe: int = 1
    model: int = -1
    seq: int = 1
    expert: int = 1


@dataclass
class EngineConfig:
    """Inference engine + continuous-batching scheduler settings."""

    max_seqs: int = 64  # concurrent sequences (BASELINE north star)
    page_size: int = 128  # tokens per KV page
    num_pages: int = 512  # total pages in the paged KV cache
    max_seq_len: int = 8192
    prefill_chunk: int = 512  # chunked prefill granularity
    max_new_tokens: int = 1024
    temperature: float = 0.5  # parity with reference llm_agent.py:37,44
    top_p: float = 1.0
    top_k: int = 0
    watchdog_seconds: float = 100.0  # reference main.py:138
    stream_flush_tokens: int = 1  # tokens per outbound chunk
    # compile every serving step variant at startup so the first request
    # never pays XLA compilation inside the watchdog window
    warmup_on_start: bool = True
    # prompts at least this long prefill seq-sharded via ring attention when
    # the mesh has a seq axis > 1 (SURVEY §5.7c); shorter ones use batched
    # chunked prefill
    ring_prefill_min_tokens: int = 4096
    # speculative decoding: draft tokens per verify step, proposed by
    # prompt-lookup (engine/spec.py); 0 = off. Greedy-exact — RAG answers
    # quote retrieved rows, so drafts hit often on the product workload.
    spec_tokens: int = 0
    # shared-prefix KV cache: prefill each LLM role's constant system head
    # once per process and share its pages across requests (scheduler
    # register_prefix) — the dominant TTFT lever for the RAG workload,
    # whose every prompt repeats the same 1-4.5k-token system prefix
    prefix_cache: bool = True
    # session KV cache (engine/session_cache.py): host-RAM tier keyed by
    # conversation_id — a retiring sequence's KV pages snapshot device→host
    # and the conversation's next turn resumes from the longest matching
    # page-whole prefix instead of re-prefilling the whole history, so
    # turn-N TTFT stops growing with history length. Composes with the
    # shared-prefix cache (cached heads referenced, never copied).
    session_cache: bool = True
    # host-RAM byte budget for session KV snapshots (LRU-evicted beyond
    # it); 0 disables the tier even when session_cache is true
    session_cache_bytes: int = 256 << 20
    # session disk spill tier (ISSUE 7; ROBUSTNESS.md §5): directory for
    # checksummed session-KV record files. Entries WRITE THROUGH at put
    # (atomic write-rename), RAM misses fall back to disk at admission,
    # and a restarted process sweeps the directory and resumes
    # conversations warm — a process kill costs at most the mid-stream
    # turn. "" = host-RAM only. Also FINCHAT_SESSION_CACHE_DISK.
    session_cache_disk_path: str = ""
    # byte budget for the disk tier's own LRU (records evicted beyond it);
    # also FINCHAT_SESSION_CACHE_DISK_BYTES
    session_cache_disk_bytes: int = 4 << 30
    # int8 paged-KV cache (kv_cache.py): halves decode-side KV HBM traffic
    # and cache footprint via per-token-per-head scales; "" = model dtype.
    # Composes with a mesh: scales shard over their head row dim when
    # Hkv % 8 == 0, replicate (cheaply) otherwise (parallel/sharding.py).
    kv_quant: str = ""
    # sequence-parallel mode for the seq-sharded long-prompt serving
    # prefill (SURVEY §5.7c/d): "ring" (K/V blocks rotate the ICI ring;
    # works for any head count, S beyond one chip's HBM) or "ulysses"
    # (two all-to-alls + full-sequence attention per head group; fewer
    # collectives when heads divide the seq axis — falls back to ring
    # when they don't)
    sp_mode: str = "ring"
    # fused multi-step decode (engine decode_loop_step): tokens generated
    # per device dispatch. 1 = today's per-token decode_step. K > 1 runs K
    # decode iterations inside one jitted fori_loop — on-device sampling,
    # in-place KV appends, per-slot EOS mask — cutting host↔device
    # round-trips and Python dispatch overhead ~K× at the cost of up to K
    # steps of inter-token burstiness (the SSE path re-paces emits).
    # Grammar-constrained, spec-decode, and within-K-of-budget slots are
    # demoted to single-step by the scheduler. Bench at 4/8.
    decode_loop_depth: int = 1
    # retrieval/prefill overlap (agent/graph.py + scheduler submit_partial):
    # prefill the response prompt's static prefix (system + context +
    # history) WHILE the retrieval tool's embed+search run, grafting the
    # retrieved block when it arrives; falls back to the serial path
    # whenever the graft would invalidate already-prefilled KV
    retrieval_overlap: bool = True
    # parked-hold TTL for the overlap path's hold-park-graft seam: how
    # long a submit_partial hold may wait for its extend_prompt before
    # the scheduler reclaims its slot and pages (the owner died).
    # Retrieval is ms-scale and the tool-streaming plane takes holds at
    # most one decision decode early, so the default has huge margin.
    partial_hold_ttl_seconds: float = 30.0
    # tool-streaming plane (agent/streamparse.py — ISSUE 9): parse the
    # tool-decision decode incrementally and launch retrieval/plot
    # execution the moment the tool name and each required argument
    # commit, overlapping tool latency with the remainder of decode and
    # with the response-prefix prefill (taken at name-commit). Falls
    # back byte-identically to decode-then-parse on any parser anomaly.
    tool_streaming: bool = True
    # unified mixed prefill+decode step (engine mixed_step): one ragged
    # [rows, chunk] device dispatch per scheduler iteration advances every
    # prefilling row one chunk AND every decoding row one token (decode
    # rows are length-1 rows of the same batch), instead of a serialized
    # prefill round plus a decode step — the admission-stall a long prompt
    # adds to every in-flight stream's inter-token latency shrinks to the
    # fused step's own time. Default on for the chunked path; the split
    # path remains the golden-identical fallback and takes over whenever
    # spec decode, decode_loop blocks, grammar-constrained picks, or
    # ring/seq-sharded prefill are active.
    mixed_step: bool = True
    # free-running device loop (ISSUE 13; engine ragged_multi_round): up
    # to this many CONSECUTIVE ragged rounds are captured into ONE device
    # dispatch — the staged-descriptor queue pre-admits each round's
    # prefill chunks, completed prompts flip to on-device-sampled decode
    # rows mid-run, the decode_loop EOS/budget stop mask generalizes to
    # every row, and per-round tokens land in an output ring the host
    # drains asynchronously while the device is mid-flight on the NEXT
    # capture. Host control returns only at membership epochs (admission,
    # eviction, preemption, breaker — the PR 5 epoch discipline), and
    # grammar-constrained or live spec-proposal rows cap the capture to 1
    # round (today's behavior). 1 = off (one host round-trip per round).
    # Streams stay byte-identical to the round-stepped path (fp32
    # contract; bench --freerun-sweep gates it). Requires mixed_step.
    freerun_rounds: int = 1
    # TP collective-compute overlap (ops/tp_overlap.py): the manual-TP
    # stage path chunks each row-parallel output projection so every
    # chunk's partial-sum all-reduce overlaps the next chunk's matmul —
    # byte-identical per element to the serial psum schedule at every
    # dtype (the chunk split never touches an output element's K
    # reduction or its single n-way collective). Default off: on CPU
    # there is nothing to overlap and the serial collective is the
    # reference schedule the parity tests pin against.
    tp_overlap: bool = False
    # output-column chunks per row-parallel matmul when tp_overlap is on
    # (indivisible output dims fall back to serial with a warning)
    tp_overlap_chunks: int = 4
    # persistent XLA compilation cache directory
    # (jax_compilation_cache_dir): warmup's compiles land on disk and a
    # restarted process reloads them instead of re-paying full XLA
    # compilation; "" = off (JAX default behavior)
    compilation_cache_dir: str = ""
    # --- resilience plane (engine/scheduler; see ROBUSTNESS.md) ---------
    # engine circuit breaker: this many CONSECUTIVE failed dispatch rounds
    # (whole-round prefill/decode/mixed/spec failures — not per-sequence
    # faults) trips the breaker: every live sequence is recompute-preempted
    # to host, the engine's device state (KV pool, page table, slots) is
    # torn down and rebuilt with weights retained, and a half-open probe
    # round re-admits via the recompute path. Below the threshold, a failed
    # round preempts its sequences and replays them — a transient blip
    # costs a re-prefill, not the stream. 0 = breaker off (legacy behavior:
    # a whole-round failure evicts its in-flight sequences with an error).
    breaker_threshold: int = 3
    # consecutive rebuilds WITHOUT an intervening successful round before
    # the breaker gives up and fails the in-flight streams (a persistently
    # wedged engine must not rebuild-loop forever)
    breaker_max_rebuilds: int = 2
    # recompute preemption under page pressure: when the earliest-deadline
    # pending request stalls on KV pages, preempt the latest-deadline
    # decoding victim(s) whose deadline is STRICTLY later (prompt +
    # generated tokens are kept on the handle; re-admission re-prefills and
    # resumes with zero duplicate or dropped tokens). Deadline order makes
    # the policy livelock-free. False = legacy head-of-line wait.
    preemption: bool = True
    # per-request deadline seconds (Kafka message timestamp + this, or HTTP
    # arrival + this): pending requests past their deadline are shed
    # pre-admission with a structured retryable error chunk, and admission
    # orders earliest-deadline-first. 0 = no deadlines (legacy FIFO).
    request_deadline_seconds: float = 0.0
    # EDF starvation guard: a pending request that has waited this long is
    # admitted ahead of deadline order (FIFO among the starved), so a
    # stream of tight-deadline arrivals cannot starve a deadline-less or
    # far-deadline request forever
    edf_starvation_seconds: float = 10.0
    # admission queue bound: submit() rejects with a retryable overload
    # error once this many requests are pending (backpressure instead of
    # an unbounded queue). 0 = unbounded (legacy). Preempted sequences
    # re-enter pending regardless — they are live streams, not new load.
    max_queue_depth: int = 0
    # chunked ring prefill: segment size (tokens) for the seq-sharded
    # prefill. > 0 splits a ring-eligible prompt into segments that
    # interleave with decode steps in the scheduler loop (each segment
    # SP-attends to itself — ring or Ulysses per sp_mode — and folds the
    # cached earlier segments: ops/ring_attention.py
    # ring_attention_with_prefix / ops/ulysses.py
    # ulysses_attention_with_prefix), so one long prompt no longer stalls
    # every in-flight stream for its whole prefill. 0 = monolithic
    # one-shot SP prefill. Rounded up to a seq-axis multiple.
    ring_prefill_chunk: int = 4096
    # --- bounded-KV long-context serving (SnapStream-style; ISSUE 15) ---
    # attention-sink + sliding-window KV with page-granular eviction
    # (engine/kv_cache.py BoundedKVPolicy): a live session keeps the first
    # ``kv_sink_pages`` pages PINNED (the attention sink — system head +
    # earliest context) plus a window of the ``kv_window_pages`` most
    # recent pages; older post-sink pages are evicted back to the page
    # pool as the context grows, so a 100k-token session decodes at flat
    # per-token cost and bounded page occupancy. Evicted pages simply
    # leave the row's page list (the ragged kernel's per-row page
    # indirection makes eviction free); positions/rotary stay ABSOLUTE
    # while the KV gather walks the surviving pages. Both 0 = unbounded
    # (legacy exact attention; requests longer than the page pool are
    # rejected at submit).
    kv_sink_pages: int = 0
    # sliding-window pages for bounded-KV serving; must cover at least
    # prefill_chunk + 2 pages so a prefill chunk always fits between
    # eviction waves (validated at engine construction). 0 = unbounded.
    kv_window_pages: int = 0


@dataclass
class EmbedConfig:
    """TPU embedding encoder (replaces OpenAI embeddings API).

    ``checkpoint_path``: HF BertModel safetensors dir (e.g. bge-base-en-v1.5)
    loaded via checkpoints/bert_loader.py; empty = random weights (dev only).
    ``tokenizer_path``: matching HF tokenizer dir; empty = byte tokenizer.
    ``batch_size``: rows per device call during batch embedding/ingest.
    """

    preset: str = "bge-tiny"  # see embed/encoder.py EMBED_PRESETS
    checkpoint_path: str = ""
    tokenizer_path: str = ""
    batch_size: int = 64
    # cross-request embedding microbatcher (embed/batcher.py): concurrent
    # query embeds + ingest upserts coalesce into one bucket-padded
    # encode_batch dispatch. batch_window_ms = how long the first arrival
    # waits for company (0 = dispatch immediately, coalescing only what is
    # already queued); batch_max = texts per coalesced dispatch.
    batch_window_ms: float = 3.0
    batch_max: int = 32
    # int8 weight-only quantized encoder (embed/encoder.py
    # quantize_bert_params — ISSUE 14): the retrieval plane rides the same
    # QTensor machinery as the decoder; "" = full precision. Gated on
    # quantized-vs-fp32 top-k overlap >= 0.99. Also FINCHAT_EMBED_QUANT.
    quant: str = ""


@dataclass
class FleetConfig:
    """Engine replica fleet (serve/fleet.py — ISSUE 6; ROBUSTNESS.md).

    ``replicas`` > 1 stands up N engine replicas under one serving plane —
    each with its own scheduler, KV page pool, and session cache — behind a
    router that rendezvous-hashes the conversation's Kafka partition
    (io/kafka.py partition_for_key, the SAME hash the broker uses for
    key→partition placement) to a live replica, so a conversation's
    session-cache entries and prefix heads stay local and routing agrees
    with partition assignment by construction.
    """

    replicas: int = 1
    # breaker trips DRAIN the replica's live conversations to siblings
    # (preempt-to-host + session-cache handoff; streams continue
    # byte-identical on the adopter) instead of riding out the rebuild on
    # the tripped replica; a give-up replica is marked OUT, its routing
    # share reassigned, and the supervisor respawns it. False = every
    # replica recovers alone, exactly the PR 5 single-engine behavior.
    drain_on_trip: bool = True
    # supervisor: respawn (rebuild device state, re-register prompt heads)
    # a given-up replica in the background while the rest of the fleet
    # absorbs its load; False leaves it OUT until process restart
    respawn: bool = True
    respawn_backoff_seconds: float = 0.5
    supervisor_interval_seconds: float = 0.2
    # disaggregated serving (serve/disagg.py — ISSUE 17): comma-separated
    # per-replica roles, e.g. "prefill,decode,decode" — ``prefill``
    # replicas never own conversations (the router hashes over the
    # decode+mixed serving pool only); a serving replica routes each cold
    # turn's prompt prefill to the prefill pool and adopts the KV over the
    # drain-handoff wire format. "" = every replica ``mixed`` (the PR 6
    # behavior); a short list pads with ``mixed``. Also FINCHAT_FLEET_ROLES,
    # CLI --fleet-roles.
    roles: str = ""


@dataclass
class FabricConfig:
    """Cluster-wide warm-state fabric (engine/warm_fabric.py — ISSUE 17).

    With ``enabled`` and a ``path``, every replica's session cache shares
    ONE disk tier (instead of per-replica subdirectories) and a global
    RAM index, so any replica resumes any conversation warm and the
    shared prompt heads' prefill is paid once per fleet — later replicas
    and respawns restore the head KV from the fabric with one H2D
    scatter. The tier's byte budget reuses
    ``engine.session_cache_disk_bytes``.
    """

    enabled: bool = False  # FINCHAT_FABRIC
    path: str = ""  # fabric directory; also FINCHAT_FABRIC_PATH, CLI --fabric-path


@dataclass
class JournalConfig:
    """Answered-message journal (io/journal.py — ISSUE 7; ROBUSTNESS.md §5).

    With ``path`` set, every ANSWERED ``message_id`` is appended to a
    checksummed journal and fsynced BEFORE its Kafka offset commits, and a
    restarted process replays the journal into the fleet-wide dedupe ring —
    closing the crash-redelivery double-answer window the in-memory ring
    alone leaves open. Failed/shed/timed-out ids are never journaled, so
    producer retries are reprocessed.
    """

    path: str = ""  # journal directory; "" = journal off. FINCHAT_JOURNAL_PATH
    # fsync each append before returning (the ordering guarantee relies on
    # it; turn off only for drills where torn tails are acceptable)
    fsync: bool = True  # FINCHAT_JOURNAL_FSYNC


@dataclass
class PodConfig:
    """Multi-host pod plane (serve/pod.py — ISSUE 20; ROBUSTNESS.md §7).

    With ``host_id`` set, this process is one HOST of a pod: its fleet is
    one failure domain, its Kafka consumer-group member owns a partition
    share (routing ≡ assignment), and a ``PodCoordinator`` runs a liaison
    channel to the peers — heartbeat for failure detection, session-byte
    transfer for cross-host warm resume. On a peer's death the survivors
    adopt its partitions (broker rebalance), replay exactly the inherited
    per-partition journals into the dedupe ring, and resume the dead
    host's conversations via the warm fabric or a liaison pull. Empty
    ``host_id`` = the plane entirely off: single-host behavior is
    bit-identical to the plain fleet.
    """

    host_id: str = ""  # this host's name in the pod; "" = pod plane off
    # peer table: "hostB=tcp:127.0.0.1:9710,hostC=inproc:hostC" — transport
    # is tcp:<host>:<port> or inproc:<name> (in-process registry, the
    # simulated-pod/test transport). "" = no liaison: heartbeat/transfer
    # off, fabric-or-cold resume only.
    peers: str = ""
    # this host's liaison listen address (same tcp:/inproc: syntax); "" =
    # serve nothing (peers can still be dialed)
    listen: str = ""
    heartbeat_interval_seconds: float = 0.5
    # consecutive missed heartbeats before a peer is declared dead and its
    # partitions adopted
    heartbeat_miss_threshold: int = 3
    transfer_timeout_seconds: float = 5.0
    # per-op retries on top of the first attempt (transfer only; a missed
    # heartbeat is itself the signal and never retries inline)
    transfer_retries: int = 2
    retry_backoff_seconds: float = 0.05
    # per-peer circuit breaker: consecutive liaison failures before the
    # peer's channel opens (calls fail fast), and how long until a
    # half-open probe is allowed through
    breaker_threshold: int = 3
    breaker_cooldown_seconds: float = 2.0


@dataclass
class ShutdownConfig:
    """Graceful SIGTERM drain (serve/app.py drain_and_stop — ISSUE 7)."""

    # how long in-flight streams may keep running after SIGTERM before the
    # stragglers are preempted to host (session bytes spilled, stream
    # failed with a retryable ``shutting_down`` error); also
    # FINCHAT_SHUTDOWN_DEADLINE_SECONDS
    deadline_seconds: float = 20.0


@dataclass
class TracingConfig:
    """End-to-end request tracing + anomaly flight recorder
    (utils/tracing.py — ISSUE 12; OBSERVABILITY.md)."""

    # record structured trace events (span marks, dispatch rows, fleet
    # moves) into the bounded per-process ring and serve
    # GET /debug/trace/<trace_id>; events stamp from host data only, so
    # the decode hot path pays < 2% with this on (bench --trace-overhead
    # gates it). Also FINCHAT_TRACING.
    enabled: bool = True
    # ring capacity in events — bounds tracing memory (~100 bytes/event);
    # the flight recorder dumps exactly this window on anomaly. Also
    # FINCHAT_TRACING_RING_EVENTS.
    ring_events: int = 65536
    # flight-recorder directory: on anomaly (breaker trip, watchdog fire,
    # shed, replica give-up, record quarantine, SIGTERM drain) the ring is
    # dumped to a checksummed file here, alongside the anomaly's own
    # event. "" = flight recorder off (events still ring-buffer). Also
    # FINCHAT_TRACING_FLIGHT_DIR, CLI --flight-dir.
    flight_dir: str = ""


@dataclass
class ServeConfig:
    host: str = "0.0.0.0"
    port: int = 8000


@dataclass
class AppConfig:
    kafka: KafkaConfig = field(default_factory=KafkaConfig)
    store: StoreConfig = field(default_factory=StoreConfig)
    vector: VectorConfig = field(default_factory=VectorConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    embed: EmbedConfig = field(default_factory=EmbedConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    fabric: FabricConfig = field(default_factory=FabricConfig)
    journal: JournalConfig = field(default_factory=JournalConfig)
    pod: PodConfig = field(default_factory=PodConfig)
    shutdown: ShutdownConfig = field(default_factory=ShutdownConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _apply_overrides(cfg: Any, overrides: dict[str, Any]) -> None:
    """Apply a {"section.key": value} or nested-dict override mapping."""
    for key, value in overrides.items():
        node = cfg
        parts = key.split(".")
        for part in parts[:-1]:
            if not hasattr(node, part):
                raise KeyError(f"unknown config key: {key!r}")
            node = getattr(node, part)
        leaf = parts[-1]
        if not hasattr(node, leaf):
            raise KeyError(f"unknown config key: {key!r}")
        if isinstance(value, dict) and dataclasses.is_dataclass(getattr(node, leaf)):
            _apply_overrides(getattr(node, leaf), {k: v for k, v in value.items()})
        else:
            setattr(node, leaf, value)


def load_config(
    config_file: str | None = None, overrides: dict[str, Any] | None = None
) -> AppConfig:
    """Build the config tree: defaults ← env vars ← JSON file ← overrides.

    Env names match the reference (``config.py:8-47``) so a reference
    deployment's ``.env`` drops in unchanged.
    """
    cfg = AppConfig()

    # --- env (reference-compatible names) ---
    cfg.kafka.bootstrap_servers = _env("KAFKA_SERVER")
    cfg.kafka.username = _env("KAFKA_USERNAME")
    cfg.kafka.password = _env("KAFKA_PASSWORD")
    cfg.store.mongodb_uri = _env("MONGODB_URI")
    cfg.vector.url = _env("QDRANT_URL")
    cfg.vector.api_key = _env("QDRANT_API_KEY")

    # --- env (new framework surface; every knob here is listed in the
    # README "Configuration reference" — finchat-lint R4 enforces the
    # three-way knob/env/README agreement) ---
    cfg.kafka.session_timeout_ms = _env_int(
        "FINCHAT_KAFKA_SESSION_TIMEOUT_MS", cfg.kafka.session_timeout_ms
    )
    cfg.kafka.client_id = _env("FINCHAT_KAFKA_CLIENT_ID", cfg.kafka.client_id)
    cfg.kafka.auto_offset_reset = _env(
        "FINCHAT_KAFKA_AUTO_OFFSET_RESET", cfg.kafka.auto_offset_reset
    )
    cfg.store.database_name = _env("FINCHAT_STORE_DB", cfg.store.database_name)
    cfg.model.dtype = _env("FINCHAT_DTYPE", cfg.model.dtype)
    cfg.model.seed = _env_int("FINCHAT_SEED", cfg.model.seed)
    cfg.mesh.data = _env_int("FINCHAT_MESH_DATA", cfg.mesh.data)
    cfg.mesh.pipe = _env_int("FINCHAT_MESH_PIPE", cfg.mesh.pipe)
    cfg.mesh.model = _env_int("FINCHAT_MESH_MODEL", cfg.mesh.model)
    cfg.mesh.seq = _env_int("FINCHAT_MESH_SEQ", cfg.mesh.seq)
    cfg.mesh.expert = _env_int("FINCHAT_MESH_EXPERT", cfg.mesh.expert)
    cfg.engine.page_size = _env_int("FINCHAT_PAGE_SIZE", cfg.engine.page_size)
    cfg.engine.num_pages = _env_int("FINCHAT_NUM_PAGES", cfg.engine.num_pages)
    cfg.engine.max_seq_len = _env_int("FINCHAT_MAX_SEQ_LEN", cfg.engine.max_seq_len)
    cfg.engine.prefill_chunk = _env_int(
        "FINCHAT_PREFILL_CHUNK", cfg.engine.prefill_chunk
    )
    cfg.engine.max_new_tokens = _env_int(
        "FINCHAT_MAX_NEW_TOKENS", cfg.engine.max_new_tokens
    )
    cfg.engine.temperature = _env_float("FINCHAT_TEMPERATURE", cfg.engine.temperature)
    cfg.engine.top_p = _env_float("FINCHAT_TOP_P", cfg.engine.top_p)
    cfg.engine.top_k = _env_int("FINCHAT_TOP_K", cfg.engine.top_k)
    cfg.engine.watchdog_seconds = _env_float(
        "FINCHAT_WATCHDOG_SECONDS", cfg.engine.watchdog_seconds
    )
    cfg.engine.stream_flush_tokens = _env_int(
        "FINCHAT_STREAM_FLUSH_TOKENS", cfg.engine.stream_flush_tokens
    )
    cfg.engine.edf_starvation_seconds = _env_float(
        "FINCHAT_EDF_STARVATION_SECONDS", cfg.engine.edf_starvation_seconds
    )
    cfg.embed.preset = _env("FINCHAT_EMBED_PRESET", cfg.embed.preset)
    cfg.embed.batch_size = _env_int("FINCHAT_EMBED_BATCH_SIZE", cfg.embed.batch_size)
    cfg.fleet.respawn_backoff_seconds = _env_float(
        "FINCHAT_FLEET_RESPAWN_BACKOFF_SECONDS", cfg.fleet.respawn_backoff_seconds
    )
    cfg.fleet.supervisor_interval_seconds = _env_float(
        "FINCHAT_FLEET_SUPERVISOR_INTERVAL_SECONDS",
        cfg.fleet.supervisor_interval_seconds,
    )
    cfg.serve.host = _env("FINCHAT_HOST", cfg.serve.host)
    cfg.kafka.backend = _env("FINCHAT_KAFKA_BACKEND", cfg.kafka.backend)
    cfg.kafka.commit_after_process = _env_bool(
        "FINCHAT_KAFKA_COMMIT_AFTER_PROCESS", cfg.kafka.commit_after_process
    )
    cfg.kafka.num_partitions = _env_int(
        "FINCHAT_KAFKA_NUM_PARTITIONS", cfg.kafka.num_partitions
    )
    cfg.store.backend = _env("FINCHAT_STORE_BACKEND", cfg.store.backend)
    cfg.vector.persist_path = _env("FINCHAT_VECTOR_PERSIST", cfg.vector.persist_path)
    cfg.model.preset = _env("FINCHAT_MODEL_PRESET", cfg.model.preset)
    cfg.model.checkpoint_path = _env("FINCHAT_CHECKPOINT", cfg.model.checkpoint_path)
    cfg.model.tokenizer_path = _env("FINCHAT_TOKENIZER", cfg.model.tokenizer_path)
    cfg.model.quant = _env("FINCHAT_QUANT", cfg.model.quant)
    cfg.model.quant_group = _env_int("FINCHAT_QUANT_GROUP", cfg.model.quant_group)
    cfg.embed.quant = _env("FINCHAT_EMBED_QUANT", cfg.embed.quant)
    cfg.embed.checkpoint_path = _env("FINCHAT_EMBED_CHECKPOINT", cfg.embed.checkpoint_path)
    cfg.embed.tokenizer_path = _env("FINCHAT_EMBED_TOKENIZER", cfg.embed.tokenizer_path)
    cfg.embed.batch_window_ms = _env_float(
        "FINCHAT_EMBED_BATCH_WINDOW_MS", cfg.embed.batch_window_ms
    )
    cfg.embed.batch_max = _env_int("FINCHAT_EMBED_BATCH_MAX", cfg.embed.batch_max)
    cfg.engine.max_seqs = _env_int("FINCHAT_MAX_SEQS", cfg.engine.max_seqs)
    cfg.engine.warmup_on_start = _env_bool("FINCHAT_WARMUP", cfg.engine.warmup_on_start)
    cfg.engine.ring_prefill_min_tokens = _env_int(
        "FINCHAT_RING_PREFILL_MIN", cfg.engine.ring_prefill_min_tokens
    )
    cfg.engine.spec_tokens = _env_int("FINCHAT_SPEC_TOKENS", cfg.engine.spec_tokens)
    cfg.engine.decode_loop_depth = _env_int(
        "FINCHAT_DECODE_LOOP_DEPTH", cfg.engine.decode_loop_depth
    )
    cfg.engine.ring_prefill_chunk = _env_int(
        "FINCHAT_RING_PREFILL_CHUNK", cfg.engine.ring_prefill_chunk
    )
    cfg.engine.kv_sink_pages = _env_int(
        "FINCHAT_KV_SINK_PAGES", cfg.engine.kv_sink_pages
    )
    cfg.engine.kv_window_pages = _env_int(
        "FINCHAT_KV_WINDOW_PAGES", cfg.engine.kv_window_pages
    )
    cfg.engine.sp_mode = _env("FINCHAT_SP_MODE", cfg.engine.sp_mode)
    cfg.engine.kv_quant = _env("FINCHAT_KV_QUANT", cfg.engine.kv_quant)
    cfg.engine.prefix_cache = _env_bool("FINCHAT_PREFIX_CACHE", cfg.engine.prefix_cache)
    cfg.engine.session_cache = _env_bool("FINCHAT_SESSION_CACHE", cfg.engine.session_cache)
    cfg.engine.session_cache_bytes = _env_int(
        "FINCHAT_SESSION_CACHE_BYTES", cfg.engine.session_cache_bytes
    )
    cfg.engine.session_cache_disk_path = _env(
        "FINCHAT_SESSION_CACHE_DISK", cfg.engine.session_cache_disk_path
    )
    cfg.engine.session_cache_disk_bytes = _env_int(
        "FINCHAT_SESSION_CACHE_DISK_BYTES", cfg.engine.session_cache_disk_bytes
    )
    cfg.journal.path = _env("FINCHAT_JOURNAL_PATH", cfg.journal.path)
    cfg.journal.fsync = _env_bool("FINCHAT_JOURNAL_FSYNC", cfg.journal.fsync)
    cfg.pod.host_id = _env("FINCHAT_POD_HOST_ID", cfg.pod.host_id)
    cfg.pod.peers = _env("FINCHAT_POD_PEERS", cfg.pod.peers)
    cfg.pod.listen = _env("FINCHAT_POD_LISTEN", cfg.pod.listen)
    cfg.pod.heartbeat_interval_seconds = _env_float(
        "FINCHAT_POD_HEARTBEAT_INTERVAL_SECONDS",
        cfg.pod.heartbeat_interval_seconds,
    )
    cfg.pod.heartbeat_miss_threshold = _env_int(
        "FINCHAT_POD_HEARTBEAT_MISS_THRESHOLD",
        cfg.pod.heartbeat_miss_threshold,
    )
    cfg.pod.transfer_timeout_seconds = _env_float(
        "FINCHAT_POD_TRANSFER_TIMEOUT_SECONDS",
        cfg.pod.transfer_timeout_seconds,
    )
    cfg.pod.transfer_retries = _env_int(
        "FINCHAT_POD_TRANSFER_RETRIES", cfg.pod.transfer_retries
    )
    cfg.pod.retry_backoff_seconds = _env_float(
        "FINCHAT_POD_RETRY_BACKOFF_SECONDS", cfg.pod.retry_backoff_seconds
    )
    cfg.pod.breaker_threshold = _env_int(
        "FINCHAT_POD_BREAKER_THRESHOLD", cfg.pod.breaker_threshold
    )
    cfg.pod.breaker_cooldown_seconds = _env_float(
        "FINCHAT_POD_BREAKER_COOLDOWN_SECONDS",
        cfg.pod.breaker_cooldown_seconds,
    )
    cfg.shutdown.deadline_seconds = _env_float(
        "FINCHAT_SHUTDOWN_DEADLINE_SECONDS", cfg.shutdown.deadline_seconds
    )
    cfg.kafka.offsets_dir = _env("FINCHAT_KAFKA_OFFSETS_DIR", cfg.kafka.offsets_dir)
    cfg.tracing.enabled = _env_bool("FINCHAT_TRACING", cfg.tracing.enabled)
    cfg.tracing.ring_events = _env_int(
        "FINCHAT_TRACING_RING_EVENTS", cfg.tracing.ring_events
    )
    cfg.tracing.flight_dir = _env(
        "FINCHAT_TRACING_FLIGHT_DIR", cfg.tracing.flight_dir
    )
    cfg.engine.retrieval_overlap = _env_bool(
        "FINCHAT_RETRIEVAL_OVERLAP", cfg.engine.retrieval_overlap
    )
    cfg.engine.partial_hold_ttl_seconds = _env_float(
        "FINCHAT_PARTIAL_HOLD_TTL_SECONDS", cfg.engine.partial_hold_ttl_seconds
    )
    cfg.engine.tool_streaming = _env_bool(
        "FINCHAT_TOOL_STREAMING", cfg.engine.tool_streaming
    )
    cfg.engine.mixed_step = _env_bool("FINCHAT_MIXED_STEP", cfg.engine.mixed_step)
    cfg.engine.freerun_rounds = _env_int(
        "FINCHAT_FREERUN_ROUNDS", cfg.engine.freerun_rounds
    )
    cfg.engine.tp_overlap = _env_bool("FINCHAT_TP_OVERLAP", cfg.engine.tp_overlap)
    cfg.engine.tp_overlap_chunks = _env_int(
        "FINCHAT_TP_OVERLAP_CHUNKS", cfg.engine.tp_overlap_chunks
    )
    cfg.engine.compilation_cache_dir = _env(
        "FINCHAT_COMPILATION_CACHE_DIR", cfg.engine.compilation_cache_dir
    )
    cfg.engine.breaker_threshold = _env_int(
        "FINCHAT_BREAKER_THRESHOLD", cfg.engine.breaker_threshold
    )
    cfg.engine.breaker_max_rebuilds = _env_int(
        "FINCHAT_BREAKER_MAX_REBUILDS", cfg.engine.breaker_max_rebuilds
    )
    cfg.engine.preemption = _env_bool("FINCHAT_PREEMPTION", cfg.engine.preemption)
    cfg.engine.request_deadline_seconds = _env_float(
        "FINCHAT_REQUEST_DEADLINE_SECONDS", cfg.engine.request_deadline_seconds
    )
    cfg.engine.max_queue_depth = _env_int(
        "FINCHAT_MAX_QUEUE_DEPTH", cfg.engine.max_queue_depth
    )
    cfg.fleet.replicas = _env_int("FINCHAT_FLEET_REPLICAS", cfg.fleet.replicas)
    cfg.fleet.drain_on_trip = _env_bool(
        "FINCHAT_FLEET_DRAIN_ON_TRIP", cfg.fleet.drain_on_trip
    )
    cfg.fleet.respawn = _env_bool("FINCHAT_FLEET_RESPAWN", cfg.fleet.respawn)
    cfg.fleet.roles = _env("FINCHAT_FLEET_ROLES", cfg.fleet.roles)
    cfg.fabric.enabled = _env_bool("FINCHAT_FABRIC", cfg.fabric.enabled)
    cfg.fabric.path = _env("FINCHAT_FABRIC_PATH", cfg.fabric.path)
    cfg.serve.port = _env_int("FINCHAT_PORT", cfg.serve.port)

    # --- optional JSON config file ---
    if config_file:
        with open(config_file) as f:  # finchat-lint: disable=event-loop-blocking -- process-start config read, before any loop exists
            _apply_overrides(cfg, json.load(f))

    # --- explicit overrides win ---
    if overrides:
        _apply_overrides(cfg, overrides)

    # memory-broker committed offsets default into the journal dir (one
    # durability directory; ISSUE 7 satellite) — after overrides, so a
    # CLI/file journal path carries the default along
    if not cfg.kafka.offsets_dir and cfg.journal.path:
        cfg.kafka.offsets_dir = cfg.journal.path

    return cfg
