from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.config import AppConfig, load_config

__all__ = ["get_logger", "AppConfig", "load_config"]
