"""Process entrypoint: ``python -m finchat_tpu``.

The reference's process layer is gunicorn spawning N uvicorn workers
(gunicorn.conf.py:5-20, Dockerfile:42). A TPU worker is NOT replicable that
way — the chip is a singleton per process — so the equivalent here is one
process owning the engine, with concurrency supplied by the continuous-
batching scheduler instead of worker replication (SURVEY §2.3 DP note).
Scale-out has two layers: ``--fleet-replicas N`` stands up N engine
replicas INSIDE this process under one conversation-affinity router with
breaker drain-to-sibling and supervised respawn (serve/fleet.py —
ROBUSTNESS.md), and multi-host serving runs one such process per
chip/slice, each its own Kafka consumer-group member (the same
partition-spreading the reference relies on, kafka_client.py:17; the
router hashes the SAME partition ids, so affinity survives both layers).

Env compatibility: every reference env var keeps working (utils/config.py);
``FINCHAT_*`` adds the new surface. ``--watchdog`` mirrors the reference's
100 s per-message timeout (main.py:138).
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from finchat_tpu.utils.config import load_config
from finchat_tpu.utils.logging import get_logger

logger = get_logger("finchat_tpu")


def main() -> None:
    p = argparse.ArgumentParser(prog="finchat_tpu", description=__doc__)
    p.add_argument("--config", default=None, help="JSON config file (see utils/config.py)")
    p.add_argument("--preset", default=None, help="model preset override")
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--no-http", action="store_true", help="Kafka worker loop only")
    p.add_argument("--decode-loop-depth", type=int, default=None,
                   help="tokens per fused decode dispatch (engine "
                        "decode_loop_step); 1 = per-token decode, bench at "
                        "4/8 — also FINCHAT_DECODE_LOOP_DEPTH")
    p.add_argument("--session-cache-bytes", type=int, default=None,
                   help="host-RAM byte budget for the session KV cache "
                        "(engine/session_cache.py); 0 disables cross-turn "
                        "KV resume — also FINCHAT_SESSION_CACHE_BYTES")
    p.add_argument("--request-deadline-seconds", type=float, default=None,
                   help="per-request deadline (Kafka producer timestamp + "
                        "this): past-deadline pending requests shed with a "
                        "retryable error and admission goes earliest-"
                        "deadline-first (ROBUSTNESS.md); 0 = off — also "
                        "FINCHAT_REQUEST_DEADLINE_SECONDS")
    p.add_argument("--fleet-replicas", type=int, default=None,
                   help="engine replicas under this worker's serving plane "
                        "(serve/fleet.py): conversation-affinity routing, "
                        "breaker drains to siblings, supervised respawn; "
                        "1 = single engine — also FINCHAT_FLEET_REPLICAS")
    p.add_argument("--fleet-roles", default=None,
                   help="comma-separated per-replica roles, e.g. "
                        "'prefill,decode,decode' (serve/disagg.py): prefill "
                        "replicas run cold prompts and hand the KV to the "
                        "decode/mixed serving pool over the drain-handoff "
                        "path; empty = all mixed — also FINCHAT_FLEET_ROLES")
    p.add_argument("--fabric-path", default=None,
                   help="cluster-wide warm-state fabric directory (engine/"
                        "warm_fabric.py): one shared session disk tier + "
                        "global index, so any replica resumes any "
                        "conversation warm and shared prompt heads prefill "
                        "once per fleet; implies fabric.enabled — also "
                        "FINCHAT_FABRIC_PATH")
    p.add_argument("--journal-dir", default=None,
                   help="durability directory (io/journal.py; ISSUE 7): "
                        "answered message ids journal here (fsync before "
                        "the Kafka commit) and replay into the dedupe ring "
                        "at restart; the memory broker's committed offsets "
                        "persist here too — also FINCHAT_JOURNAL_PATH")
    p.add_argument("--session-disk", default=None,
                   help="session-KV disk spill tier directory (engine/"
                        "session_cache.py SessionDiskTier): entries write "
                        "through to checksummed record files so a restarted "
                        "process resumes conversations warm — also "
                        "FINCHAT_SESSION_CACHE_DISK")
    p.add_argument("--flight-dir", default=None,
                   help="anomaly flight-recorder directory (utils/"
                        "tracing.py — OBSERVABILITY.md): on breaker trip/"
                        "watchdog fire/shed/give-up/quarantine/SIGTERM the "
                        "trace ring dumps to a checksummed file here — "
                        "also FINCHAT_TRACING_FLIGHT_DIR")
    p.add_argument("--no-tracing", action="store_true",
                   help="disable request tracing + the flight recorder "
                        "(tracing.enabled; also FINCHAT_TRACING=0)")
    p.add_argument("--shutdown-deadline-seconds", type=float, default=None,
                   help="graceful SIGTERM drain window: in-flight streams "
                        "may finish for this long before stragglers are "
                        "preempted to host with a retryable error — also "
                        "FINCHAT_SHUTDOWN_DEADLINE_SECONDS")
    args = p.parse_args()

    overrides: dict = {}
    if args.preset:
        overrides["model.preset"] = args.preset
    if args.port:
        overrides["serve.port"] = args.port
    if args.decode_loop_depth is not None:
        overrides["engine.decode_loop_depth"] = args.decode_loop_depth
    if args.session_cache_bytes is not None:
        overrides["engine.session_cache_bytes"] = args.session_cache_bytes
    if args.request_deadline_seconds is not None:
        overrides["engine.request_deadline_seconds"] = args.request_deadline_seconds
    if args.fleet_replicas is not None:
        overrides["fleet.replicas"] = args.fleet_replicas
    if args.fleet_roles is not None:
        overrides["fleet.roles"] = args.fleet_roles
    if args.fabric_path is not None:
        overrides["fabric.path"] = args.fabric_path
        overrides["fabric.enabled"] = True
    if args.journal_dir is not None:
        overrides["journal.path"] = args.journal_dir
    if args.session_disk is not None:
        overrides["engine.session_cache_disk_path"] = args.session_disk
    if args.shutdown_deadline_seconds is not None:
        overrides["shutdown.deadline_seconds"] = args.shutdown_deadline_seconds
    if args.flight_dir is not None:
        overrides["tracing.flight_dir"] = args.flight_dir
    if args.no_tracing:
        overrides["tracing.enabled"] = False
    cfg = load_config(args.config, overrides)

    from finchat_tpu.serve.app import build_app

    app = build_app(cfg)

    async def run() -> None:
        loop = asyncio.get_running_loop()
        import os

        if os.getenv("FINCHAT_DEV"):
            # SURVEY §5.2: the reference blocks its event loop (sync pymongo
            # in async defs, blocking consumer.poll); dev mode makes any such
            # regression here loudly visible instead of silently copied
            loop.set_debug(True)
            loop.slow_callback_duration = 0.1
            logger.info("dev diagnostics on: asyncio debug + slow-callback detection")
        stop = asyncio.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await app.start(serve_http=not args.no_http)
        logger.info(
            "worker up: preset=%s http=%s port=%d",
            cfg.model.preset, not args.no_http, cfg.serve.port,
        )
        await stop.wait()
        # graceful drain (ISSUE 7): stop admission, finish in-flight
        # streams within shutdown.deadline_seconds, preempt stragglers to
        # host with a retryable error, spill session bytes to the disk
        # tier, journal + commit, exit with zero slot/page leaks — the
        # restarted process resumes conversations warm
        logger.info("shutting down (graceful drain, deadline %.0fs)",
                    cfg.shutdown.deadline_seconds)
        await app.drain_and_stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
