"""Tool-streaming plane: incremental tool-call parsing and eager tool
execution DURING the decision decode (ISSUE 9; ROADMAP item 3).

PR 3 overlapped retrieval with the *response prefix prefill*, but the agent
still fully decoded the tool-call turn (grammar-constrained, up to 96
tokens) before ``parse_tool_decision`` fired a single tool — every tool
turn paid decode + tool serially. Following Conveyor (PAPERS.md: "Efficient
Tool-aware LLM Serving with Tool Partial Execution"), this module parses
the partially decoded output as chunks arrive and launches the tool the
moment enough of the call has *committed*:

- :class:`StreamingToolParser` — an event-emitting character state machine
  run in lockstep with the SAME grammar DFA the constrained sampler uses
  (``agent/constrained.py`` ``build_tool_grammar``), so "is this stream
  still a well-formed tool call" is answered by the exact automaton that
  constrained the decode. Events: ``ToolNameComplete`` (the ``(`` after
  the name), ``ArgComplete(key, value)`` (the arg's *closing delimiter*
  decoded — the commit point: a string's closing quote, an int's
  terminator), ``CallComplete`` (the closing ``)``), ``NoToolComplete``,
  and ``ParseAnomaly`` (the stream left the grammar — streaming disengages
  and the serial parser decides).
- :class:`ToolLauncher` — speculative execution manager. It launches the
  tool as soon as the name and every *launch-required* argument have
  committed, relaunches (cancelling the stale task — a counted
  speculative cancel) when a later token commits an argument that
  invalidates the in-flight launch, and adopts the task at
  ``result_for`` when it matches the authoritative final call.

AUTHORITY CONTRACT: the streaming plane is latency-only. The final
decision is ALWAYS ``parse_tool_decision`` over the accumulated text
(:meth:`StreamingToolParser.finish`), byte-identical to the serial
decode-then-parse path by construction regardless of how the text was
chunked into decode bursts (the split-point invariance fuzz test pins
this). Off-grammar output — impossible under the constrained sampler,
routine from a stub — merely forfeits the eager launch.

Metrics (``finchat_tool_*`` family; emitted through the launcher's
``metrics`` view so fleet replicas label them per replica like every
per-engine family): ``finchat_tool_launches_total``,
``finchat_tool_speculative_cancels_total``,
``finchat_tool_fallbacks_total`` (streaming disengaged — anomaly,
mismatch, or a failed speculative execution retried serially), and the
``finchat_tool_overlap_saved_seconds`` histogram (tool time hidden under
the remainder of decode per adopted launch).

Fault site: ``tool.execute`` (utils/faults.py) fires inside every tool
execution — speculative and serial — so tests can drive the
fail-speculative → retry-serial degradation.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

from finchat_tpu.agent.constrained import DEAD, CharDFA, build_tool_grammar
from finchat_tpu.agent.state import ToolCall
from finchat_tpu.agent.toolcall import (
    NO_TOOL_LITERAL,
    PLOT_TOOL_NAME,
    TOOL_NAME,
    VALIDATORS,
    parse_tool_decision,
)
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

# Arguments that must have COMMITTED before a speculative launch is
# worthwhile: the search query is the embed input — launching before it
# closes would embed a default query the final call almost never uses,
# while the remaining args (limits, date window, chart cosmetics) have
# stable validated defaults that a later commit merely refines (a counted
# relaunch). Tools absent here never launch before CallComplete.
LAUNCH_KEYS: dict[str, tuple[str, ...]] = {
    TOOL_NAME: ("search_query",),
    PLOT_TOOL_NAME: ("search_query",),
}

# Keys whose LATE commit does not invalidate an in-flight speculative
# launch: the adopter refines the speculative superset host-side instead
# of relaunching (Conveyor's partial-execution move, adapted to the
# retrieval schema). ``num_transactions`` is a pure top-k cut — the index
# returns score-ordered rows, so speculative-top-default[:n] equals a
# limit-n query on any retriever with a deterministic score order (the
# in-tree device index; an approximate-ANN backend could drift on score
# ties, which is the documented speculation trade there). Keys that
# change WHICH rows score (``search_query``, ``time_period_days``'s
# device-side date filter, plot cosmetics baked into the render) are
# absent: their late commit cancels and relaunches.
REFINE_KEYS: dict[str, tuple[str, ...]] = {
    TOOL_NAME: ("num_transactions",),
    PLOT_TOOL_NAME: (),
}


def refinable(base: ToolCall, final: ToolCall) -> bool:
    """May ``final`` be served by refining ``base``'s (possibly in-flight)
    speculative result? Same tool, and every differing key is a declared
    refine key that TIGHTENS: the adopter can slice a speculative
    superset down, never grow it — so the key must be absent from
    ``base`` (the launch fetched with the generous default) or its base
    value must already cover the final one. Duplicate-key decodes (the
    grammar doesn't track used keys) make the grow direction reachable."""
    if base.name != final.name:
        return False
    allowed = REFINE_KEYS.get(final.name, ())
    for key in set(base.args) | set(final.args):
        b, f = base.args.get(key), final.args.get(key)
        if b == f:
            continue
        if key not in allowed:
            return False
        if key in base.args and not (
            isinstance(b, int) and isinstance(f, int) and b >= f
        ):
            return False
    return True

_WS = " \t\n"


# --- parse events ---------------------------------------------------------

@dataclass(frozen=True)
class ToolNameComplete:
    name: str


@dataclass(frozen=True)
class ArgComplete:
    key: str
    value: Any  # decoded raw value (str or int), pre-validation


@dataclass(frozen=True)
class CallComplete:
    call: ToolCall  # validated


@dataclass(frozen=True)
class NoToolComplete:
    pass


@dataclass(frozen=True)
class ParseAnomaly:
    reason: str


@dataclass
class ToolResult:
    """What one tool execution produced — returned (not written to agent
    state) so the speculative plane can discard an unadopted run."""

    texts: list[str]
    plot_data_uri: str | None = None


class ToolStreamError(RuntimeError):
    """Speculative tool execution failed. ``code``/``retryable`` mirror
    the scheduler's structured error contract (generator.GenerationError,
    io/schemas error_chunk) so the serving layer can emit a structured
    retryable chunk if the serial retry also fails; the agent's first
    recourse is always the serial-path retry."""

    def __init__(self, message: str, *, code: str | None = None,
                 retryable: bool = False):
        super().__init__(message)
        self.code = code
        self.retryable = retryable


# --- incremental parser ---------------------------------------------------

_GRAMMAR_DFA: CharDFA | None = None


def _tool_dfa() -> CharDFA:
    """Process-wide grammar DFA shared with the constrained sampler's
    machinery (a duplicate build under a racing first call is harmless —
    the char-level automaton is cheap, unlike GrammarVocab's vocab scan)."""
    global _GRAMMAR_DFA
    if _GRAMMAR_DFA is None:
        _GRAMMAR_DFA = build_tool_grammar()
    return _GRAMMAR_DFA


class StreamingToolParser:
    """Incremental tool-decision parser over decode chunks.

    ``feed(chunk)`` processes character-by-character (so the event stream
    is invariant to HOW the text was chunked — decode bursts, per-token
    SSE flushes, mid-JSON-string splits) and returns the events the chunk
    completed. Two automata run in lockstep per char:

    - the shared grammar DFA (``build_tool_grammar``) answers membership:
      the first off-grammar char raises ``ParseAnomaly`` and permanently
      disengages the semantic scanner (the serial parser still decides at
      ``finish``);
    - a semantic scanner — trusting the DFA for structure — tracks which
      production the char advances (name, key, string/int value) and
      emits commit-point events.
    """

    def __init__(self) -> None:
        self._dfa = _tool_dfa()
        self._dfa_state = self._dfa.start
        self._chunks: list[str] = []
        self.anomaly: str | None = None
        self.completed_call: ToolCall | None = None
        self.no_tool = False
        # semantic scanner state
        self._mode = "lead"
        self._buf: list[str] = []
        self._key = ""
        self._name: str | None = None
        self._raw_args: dict[str, Any] = {}

    @property
    def text(self) -> str:
        return "".join(self._chunks)

    # -- public API --------------------------------------------------------

    def feed(self, chunk: str) -> list[Any]:
        self._chunks.append(chunk)
        if self.anomaly is not None:
            return []
        events: list[Any] = []
        for ch in chunk:
            nxt = self._dfa.step(self._dfa_state, ch)
            if nxt == DEAD:
                self.anomaly = "stream left the tool-call grammar"
                events.append(ParseAnomaly(self.anomaly))
                break
            self._dfa_state = nxt
            produced = self._scan(ch)
            if produced:
                events.extend(produced)
        return events

    def launchable_call(self) -> ToolCall | None:
        """The call the launcher may speculatively run RIGHT NOW: name
        committed and every launch-required argument committed (closing
        delimiter decoded). Args are the validated view of the committed
        subset — a later commit may invalidate (the launcher's problem)."""
        if self.anomaly is not None or self._name is None:
            return None
        required = LAUNCH_KEYS.get(self._name)
        if required is None or any(k not in self._raw_args for k in required):
            return None
        return ToolCall(name=self._name, args=VALIDATORS[self._name](dict(self._raw_args)))

    def finish(self) -> ToolCall | None:
        """Authoritative final decision: ALWAYS the serial parser over the
        accumulated text — byte-identical to the decode-then-parse path by
        construction. A disagreement with the incremental ``CallComplete``
        (reachable only through a scanner bug) is logged, flagged as an
        anomaly (so callers count the fallback and drop the speculative
        result), and the serial result wins."""
        final = parse_tool_decision(self.text)
        if self.anomaly is None and self.completed_call is not None and (
            final is None or final != self.completed_call
        ):
            logger.warning(
                "incremental parse disagrees with serial parse (%r vs %r); serial wins",
                self.completed_call, final,
            )
            self.anomaly = "incremental/serial parse mismatch"
        return final

    # -- semantic scanner --------------------------------------------------
    # Only grammatical chars reach here (the DFA stepped first), so each
    # mode needs to recognize exactly the transitions the grammar allows
    # from it; anything unrecognized is structural whitespace.

    def _scan(self, ch: str) -> list[Any]:
        mode = self._mode
        if mode == "lead":
            if not self._buf and ch in _WS:
                return []  # bounded leading whitespace
            self._buf.append(ch)
            if ch == "(":
                self._name = "".join(self._buf[:-1])
                self._buf = []
                self._mode = "pre_obj"
                return [ToolNameComplete(self._name)]
            if "".join(self._buf) == NO_TOOL_LITERAL:
                self.no_tool = True
                self._buf = []
                self._mode = "done"
                return [NoToolComplete()]
            return []
        if mode == "pre_obj":
            if ch == "{":
                self._mode = "obj"
            return []
        if mode in ("obj", "pre_key"):
            if ch == '"':
                self._buf = []
                self._mode = "key"
            elif ch == "}":  # empty object or (grammar forbids it) post-comma
                self._mode = "post_obj"
            return []
        if mode == "key":
            if ch == '"':
                self._key = "".join(self._buf)
                self._buf = []
                self._mode = "post_key"
            else:
                self._buf.append(ch)
            return []
        if mode == "post_key":
            if ch == ":":
                self._mode = "pre_val"
            return []
        if mode == "pre_val":
            if ch == '"':
                self._buf = []
                self._mode = "str_val"
            elif ch.isdigit():
                self._buf = [ch]
                self._mode = "int_val"
            return []
        if mode == "str_val":
            if ch == '"':  # commit point: the closing quote
                value = "".join(self._buf)
                self._buf = []
                self._mode = "post_val"
                return self._commit_arg(value)
            self._buf.append(ch)
            return []
        if mode == "int_val":
            if ch.isdigit():
                self._buf.append(ch)
                return []
            # commit point: an int has no closing char — its terminator
            # ("," / "}" / whitespace) commits it AND advances the object
            value = int("".join(self._buf))
            self._buf = []
            if ch == ",":
                self._mode = "pre_key"
            elif ch == "}":
                self._mode = "post_obj"
            else:
                self._mode = "post_val"
            return self._commit_arg(value)
        if mode == "post_val":
            if ch == ",":
                self._mode = "pre_key"
            elif ch == "}":
                self._mode = "post_obj"
            return []
        if mode == "post_obj":
            if ch == ")":
                self._mode = "done"
                return self._complete_call()
            return []
        return []  # "done": the DFA rejects any further char (→ anomaly)

    def _commit_arg(self, value: Any) -> list[Any]:
        self._raw_args[self._key] = value  # duplicate keys: last one wins, like json.loads
        return [ArgComplete(self._key, value)]

    def _complete_call(self) -> list[Any]:
        assert self._name is not None  # "(" was seen to get here
        self.completed_call = ToolCall(
            name=self._name, args=VALIDATORS[self._name](dict(self._raw_args))
        )
        return [CallComplete(self.completed_call)]


# --- speculative launcher -------------------------------------------------

def _swallow(task: asyncio.Task) -> None:
    # a cancelled/failed speculative launch nobody adopted must not log
    # "Task exception was never retrieved"
    if not task.cancelled():
        task.exception()


class ToolLauncher:
    """Speculative tool-execution manager for one decision decode.

    ``execute`` is an async callable ``ToolCall -> ToolResult`` (the agent
    binds server-side user_id injection into it — the launcher never sees
    an identity the model could have influenced beyond validated args).

    Lifecycle: ``update(call)`` per commit event (launch / keep / cancel+
    relaunch), ``mark_decode_done()`` when the decode stream ends,
    ``result_for(final_call)`` to adopt or re-run, ``abandon()`` when
    nothing will be adopted (anomaly, no-tool turn, upstream error).
    """

    def __init__(
        self,
        execute: Callable[[ToolCall], Awaitable[ToolResult]],
        *,
        refine: Callable[[ToolResult, ToolCall], ToolResult] | None = None,
        metrics=None,
        trace_id: str | None = None,
    ):
        self._execute = execute
        # host-side refinement for late-committed REFINE_KEYS (e.g. the
        # top-k slice); None = exact-match adoption only
        self._refine = refine
        self.metrics = metrics if metrics is not None else METRICS
        # end-to-end trace id (utils/tracing.py — ISSUE 12): launches and
        # adoptions land on the request's timeline, so the Conveyor-style
        # overlap is visible per request, not just as a histogram
        self.trace_id = trace_id
        self._task: asyncio.Task | None = None
        self._task_call: ToolCall | None = None
        self._task_started = 0.0
        self._decode_done_at: float | None = None
        self.abandoned = False

    def update(self, call: ToolCall | None) -> None:
        """Reconcile the in-flight launch with the call the committed
        stream implies right now. A call the in-flight launch can still
        serve (identical, or differing only in refine keys) keeps it; a
        genuinely invalidated launch is cancelled (the counted
        speculative cancel — a later token invalidated an eagerly-
        launched argument) and relaunched."""
        if self.abandoned or call is None:
            return
        if self._task is not None:
            if self._task_call == call or (
                self._refine is not None and refinable(self._task_call, call)
            ):
                return
            self._drop_task(cancelled_speculation=True)
        self._launch(call)

    def mark_decode_done(self) -> None:
        """The decision decode finished — the boundary the overlap-saved
        histogram measures against (serial would only START the tool now)."""
        self._decode_done_at = time.perf_counter()

    def abandon(self) -> None:
        """Cancel any in-flight launch; no adoption will happen."""
        self.abandoned = True
        self._drop_task(cancelled_speculation=True)

    async def result_for(self, call: ToolCall) -> ToolResult:
        """Adopt the in-flight launch when it can serve the authoritative
        final ``call`` — identical args, or differing only in refine keys
        (the result is then refined host-side); otherwise cancel it and
        run ``call`` through the same execute seam. Failures raise
        :class:`ToolStreamError` (structured, retryable) for the caller's
        serial fallback."""
        adoptable = (
            not self.abandoned
            and self._task is not None
            and (self._task_call == call
                 or (self._refine is not None
                     and refinable(self._task_call, call)))
        )
        if not adoptable:
            self._drop_task(cancelled_speculation=True)
            self.abandoned = False
            self._launch(call)
        task = self._task
        task_call = self._task_call
        started = self._task_started
        assert task is not None and task_call is not None
        self._task, self._task_call = None, None  # ownership transfers here
        try:
            result, ended = await task
        except asyncio.CancelledError:
            if task.cancelled():  # the task's own cancellation, not ours
                raise ToolStreamError(
                    "speculative tool launch was cancelled",
                    code="tool_execute_cancelled", retryable=True,
                ) from None
            task.cancel()  # we are being cancelled: don't orphan the tool
            raise
        except Exception as e:
            raise ToolStreamError(
                f"tool execution failed: {e}",
                code="tool_execute_failed", retryable=True,
            ) from e
        if self._decode_done_at is not None:
            # the slice of the adopted run that hid under decode — the
            # latency a serial decide→execute turn would have paid on top
            saved = max(0.0, min(ended, self._decode_done_at) - started)
            self.metrics.observe("finchat_tool_overlap_saved_seconds", saved,
                                 trace_id=self.trace_id)
        if self.trace_id is not None and TRACER.enabled:
            # the adopted execution as a complete span (started→ended) —
            # in Perfetto it visibly overlaps the decision decode
            TRACER.event("tool_adopted", self.trace_id, ts=started,
                         dur=max(0.0, ended - started), track="agent",
                         args={"tool": call.name})
        if task_call != call:
            assert self._refine is not None  # adoptable implies it
            result = self._refine(result, call)
        return result

    # -- internals ---------------------------------------------------------

    def _launch(self, call: ToolCall) -> None:
        self._task_call = call
        self._task_started = time.perf_counter()
        self._task = asyncio.ensure_future(self._timed(call))
        self._task.add_done_callback(_swallow)
        self.metrics.inc("finchat_tool_launches_total")
        if self.trace_id is not None and TRACER.enabled:
            TRACER.event("tool_launch", self.trace_id, track="agent",
                         args={"tool": call.name})

    async def _timed(self, call: ToolCall) -> tuple[ToolResult, float]:
        # completion is stamped INSIDE the task: adoption may happen long
        # after the tool finished, and the overlap-saved histogram must
        # measure the tool run, not the adoption latency
        result = await self._execute(call)
        return result, time.perf_counter()

    def _drop_task(self, cancelled_speculation: bool) -> None:
        task = self._task
        self._task, self._task_call = None, None
        if task is None:
            return
        if not task.done():
            task.cancel()
        if cancelled_speculation:
            self.metrics.inc("finchat_tool_speculative_cancels_total")
