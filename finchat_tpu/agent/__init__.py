from finchat_tpu.agent.state import AgentState, ToolCall
from finchat_tpu.agent.graph import LLMAgent, StateGraph, END
from finchat_tpu.agent.toolcall import parse_tool_decision
from finchat_tpu.agent.streamparse import StreamingToolParser, ToolLauncher

__all__ = [
    "AgentState", "ToolCall", "LLMAgent", "StateGraph", "END",
    "parse_tool_decision", "StreamingToolParser", "ToolLauncher",
]
