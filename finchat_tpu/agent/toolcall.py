"""Tool-decision output parsing.

The reference gets structured tool calls from Gemini's function-calling API
(``llm_agent.py:98-101``). Here the decision LLM runs on-TPU and emits text,
so the call format is parsed — strictly — from the model output, honoring
the prompt contract (``tool_prompt.txt``):

- the literal ``No tool call`` (tool_prompt.txt:12 parity) → no retrieval;
- ``retrieve_transactions({...json...})`` → a validated ToolCall;
- ``create_financial_plot({...json...})`` → a validated ToolCall (the
  reference ships this tool as dead code, tools/plot_tool.py — here it is
  wired; SURVEY §7.2.7).

Validation mirrors the reference's RetrievalIntent schema
(``tools/qdrant_tool.py:39-68``): ``num_transactions`` bounded 1..10000,
``time_period_days`` a positive int, ``search_query`` a string defaulting to
"recent transactions"; plot args add ``chart_type`` (whitelisted) and
``title``. ``user_id`` is NEVER taken from the model — the executor
overwrites it server-side (llm_agent.py:119-120 invariant).
"""

from __future__ import annotations

import json
import re

from finchat_tpu.agent.state import ToolCall
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

from finchat_tpu.tools.plot import CHART_TYPES  # single source of the enum

TOOL_NAME = "retrieve_transactions"
PLOT_TOOL_NAME = "create_financial_plot"
NO_TOOL_LITERAL = "No tool call"

_CALL_RE = re.compile(
    r"(retrieve_transactions|create_financial_plot)\s*\(\s*(\{.*?\})\s*\)", re.DOTALL
)


def _validate_retrieval_args(raw: dict) -> dict:
    args: dict = {}
    sq = raw.get("search_query")
    args["search_query"] = sq if isinstance(sq, str) and sq.strip() else "recent transactions"

    n = raw.get("num_transactions")
    if isinstance(n, bool):
        n = None
    if isinstance(n, (int, float)):
        args["num_transactions"] = max(1, min(10_000, int(n)))

    days = raw.get("time_period_days")
    if isinstance(days, bool):
        days = None
    if isinstance(days, (int, float)) and int(days) > 0:
        args["time_period_days"] = int(days)

    # user_id from the model is dropped on the floor by construction
    return args


def _validate_plot_args(raw: dict) -> dict:
    args = _validate_retrieval_args(raw)
    chart = raw.get("chart_type")
    args["chart_type"] = chart if chart in CHART_TYPES else "bar"
    title = raw.get("title")
    args["title"] = title if isinstance(title, str) and title.strip() else "Financial Plot"
    return args


# public: the streaming parser (agent/streamparse.py) validates per-arg
# commits through the SAME validators, so eager launches and the serial
# parse can never disagree on defaulting/clamping rules
VALIDATORS = {
    TOOL_NAME: _validate_retrieval_args,
    PLOT_TOOL_NAME: _validate_plot_args,
}


def parse_tool_decision(text: str) -> ToolCall | None:
    """Parse the tool-decision model output into a ToolCall, or None."""
    stripped = text.strip()
    if not stripped or NO_TOOL_LITERAL.lower() in stripped.lower()[:80]:
        return None

    match = _CALL_RE.search(stripped)
    if match is None:
        for name in (TOOL_NAME, PLOT_TOOL_NAME):
            if name in stripped:
                # named a tool but args are malformed → call with defaults
                logger.warning("tool call named without parsable args: %r", stripped[:120])
                return ToolCall(name=name, args=VALIDATORS[name]({}))
        return None

    name = match.group(1)
    validator = VALIDATORS[name]
    try:
        raw = json.loads(match.group(2))
    except json.JSONDecodeError:
        logger.warning("unparsable tool-call JSON: %r", match.group(2)[:120])
        return ToolCall(name=name, args=validator({}))

    if not isinstance(raw, dict):
        return ToolCall(name=name, args=validator({}))
    return ToolCall(name=name, args=validator(raw))
