"""Tool-decision output parsing.

The reference gets structured tool calls from Gemini's function-calling API
(``llm_agent.py:98-101``). Here the decision LLM runs on-TPU and emits text,
so the call format is parsed — strictly — from the model output, honoring
the prompt contract (``tool_prompt.txt``):

- the literal ``No tool call`` (tool_prompt.txt:12 parity) → no retrieval;
- ``retrieve_transactions({...json...})`` → a validated ToolCall.

Validation mirrors the reference's RetrievalIntent schema
(``tools/qdrant_tool.py:39-68``): ``num_transactions`` bounded 1..10000,
``time_period_days`` a positive int, ``search_query`` a string defaulting to
"recent transactions". ``user_id`` is NEVER taken from the model — the
executor overwrites it server-side (llm_agent.py:119-120 invariant).
"""

from __future__ import annotations

import json
import re

from finchat_tpu.agent.state import ToolCall
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

TOOL_NAME = "retrieve_transactions"
NO_TOOL_LITERAL = "No tool call"

_CALL_RE = re.compile(r"retrieve_transactions\s*\(\s*(\{.*?\})\s*\)", re.DOTALL)


def _validate_args(raw: dict) -> dict:
    args: dict = {}
    sq = raw.get("search_query")
    args["search_query"] = sq if isinstance(sq, str) and sq.strip() else "recent transactions"

    n = raw.get("num_transactions")
    if isinstance(n, bool):
        n = None
    if isinstance(n, (int, float)):
        args["num_transactions"] = max(1, min(10_000, int(n)))

    days = raw.get("time_period_days")
    if isinstance(days, bool):
        days = None
    if isinstance(days, (int, float)) and int(days) > 0:
        args["time_period_days"] = int(days)

    # user_id from the model is dropped on the floor by construction
    return args


def parse_tool_decision(text: str) -> ToolCall | None:
    """Parse the tool-decision model output into a ToolCall, or None."""
    stripped = text.strip()
    if not stripped or NO_TOOL_LITERAL.lower() in stripped.lower()[:80]:
        return None

    match = _CALL_RE.search(stripped)
    if match is None:
        if TOOL_NAME in stripped:
            # named the tool but args are malformed → retrieve with defaults
            logger.warning("tool call named without parsable args: %r", stripped[:120])
            return ToolCall(name=TOOL_NAME, args=_validate_args({}))
        return None

    try:
        raw = json.loads(match.group(1))
    except json.JSONDecodeError:
        logger.warning("unparsable tool-call JSON: %r", match.group(1)[:120])
        return ToolCall(name=TOOL_NAME, args=_validate_args({}))

    if not isinstance(raw, dict):
        return ToolCall(name=TOOL_NAME, args=_validate_args({}))
    return ToolCall(name=TOOL_NAME, args=_validate_args(raw))
