"""Typed agent state.

Native replacement for the reference's ``AgentState`` TypedDict + langchain
ToolCall (``llm_agent.py:21-28``): same fields, same deque semantics for
pending tool calls (only the first is honored per turn, llm_agent.py:100).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any

from finchat_tpu.io.schemas import ChatMessage


@dataclass
class ToolCall:
    name: str
    args: dict[str, Any]


@dataclass
class AgentState:
    user_query: str
    user_id: str
    # session KV cache key (engine/session_cache.py): turns of the same
    # conversation resume each other's prefilled KV; None = no reuse
    conversation_id: str | None = None
    user_context: str = ""
    chat_history: list[ChatMessage] = field(default_factory=list)
    tool_calls: deque[ToolCall] = field(default_factory=deque)
    retrieved_transactions: list[str] = field(default_factory=list)
    plot_data_uri: str | None = None  # create_financial_plot output
    final_response: str | None = None
    # retrieval/prefill overlap: the engine's in-flight partial prefill of
    # the response prompt's static prefix (generator.begin_partial handle),
    # taken while retrieval runs and grafted at generation time
    partial_prefill: Any = None
    # tool-streaming plane (agent/streamparse.py): the ToolLauncher whose
    # speculative execution started during the decision decode, adopted
    # (or cancelled) by retrieve_data; None outside a streamed tool turn
    tool_stream: Any = None
    # per-request completion deadline (monotonic time.perf_counter; None =
    # none), threaded serve/app → agent → generator → scheduler for the
    # shed/EDF admission plane (ROBUSTNESS.md)
    deadline: float | None = None
    # end-to-end trace id (utils/tracing.py — ISSUE 12): minted at ingress
    # (Kafka message_id / HTTP x-trace-id), threaded through every
    # generator call and tool launch so the request's agent decide, tool
    # overlap, prefill, and dispatch events correlate on one timeline;
    # None = untraced
    trace_id: str | None = None
