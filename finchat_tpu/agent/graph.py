"""The 3-node agent graph and its streaming bypass.

Native, typed replacement for the reference's LangGraph agent
(``llm_agent.py:57-79``): decide_retrieval → (conditional) → retrieve_data →
generate_response → END. Two execution paths, both preserved (SURVEY §2.5):

- ``query()`` walks the compiled graph (reference llm_agent.py:175-200) —
  batch, non-streaming.
- ``stream_with_status()`` bypasses the graph and calls the node functions
  directly so it can interleave status events and stream the final
  generation (reference llm_agent.py:202-252). Event shapes and messages
  are kept verbatim — they are wire contract (SURVEY §2.4).

The two LLM roles of the reference (tool-decision vs response,
llm_agent.py:34-45) become two TextGenerators — typically the same TPU
engine with different prompts and sampling.

The tool-streaming plane (ISSUE 9; agent/streamparse.py) makes node 1 a
streaming consumer of the decision decode: tools launch at argument
commit points and the response prefix hold is taken at name-commit, so
multi-tool turns cost ~max(decode, tool) instead of decode + tool.
"""

from __future__ import annotations

import asyncio
from collections import deque
from dataclasses import replace as dc_replace
from datetime import date
from typing import Any, AsyncGenerator, Awaitable, Callable

from finchat_tpu.agent.state import AgentState, ToolCall
from finchat_tpu.agent.streamparse import (
    ArgComplete,
    CallComplete,
    ParseAnomaly,
    StreamingToolParser,
    ToolLauncher,
    ToolNameComplete,
    ToolResult,
)
from finchat_tpu.agent.toolcall import parse_tool_decision
from finchat_tpu.engine.generator import TextGenerator
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.session_cache import session_key
from finchat_tpu.io.schemas import ChatMessage
from finchat_tpu.models.tokenizer import render_chat
from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

END = "__end__"

# async retriever: validated tool args (with server-injected user_id) -> texts
Retriever = Callable[[dict[str, Any]], Awaitable[list[str]]]


class StateGraph:
    """Minimal typed state machine: named nodes, static edges, conditional
    routing — the semantics the reference gets from langgraph's StateGraph
    (llm_agent.py:59-79) in ~50 lines."""

    def __init__(self) -> None:
        self._nodes: dict[str, Callable[[AgentState], Awaitable[AgentState]]] = {}
        self._edges: dict[str, str] = {}
        self._conditional: dict[str, tuple[Callable[[AgentState], str], dict[str, str]]] = {}
        self._entry: str | None = None

    def add_node(self, name: str, fn: Callable[[AgentState], Awaitable[AgentState]]) -> None:
        self._nodes[name] = fn

    def set_entry_point(self, name: str) -> None:
        self._entry = name

    def add_edge(self, src: str, dst: str) -> None:
        self._edges[src] = dst

    def add_conditional_edges(
        self, src: str, router: Callable[[AgentState], str], mapping: dict[str, str]
    ) -> None:
        self._conditional[src] = (router, mapping)

    async def ainvoke(self, state: AgentState) -> AgentState:
        assert self._entry is not None, "entry point not set"
        node = self._entry
        while node != END:
            state = await self._nodes[node](state)
            if node in self._conditional:
                router, mapping = self._conditional[node]
                node = mapping[router(state)]
            else:
                node = self._edges[node]
        return state


class LLMAgent:
    def __init__(
        self,
        tool_generator: TextGenerator,
        response_generator: TextGenerator,
        retriever: Retriever,
        system_prompt: str,
        tool_prompt: str,
        *,
        tool_sampling: SamplingParams | None = None,
        response_sampling: SamplingParams | None = None,
        today: Callable[[], str] = lambda: date.today().isoformat(),
        retrieval_overlap: bool = True,
        tool_streaming: bool = True,
        metrics=None,
    ):
        self.tool_generator = tool_generator
        self.response_generator = response_generator
        self.retriever = retriever
        self.system_prompt = system_prompt
        self.tool_prompt = tool_prompt
        # temperature 0.5 both roles (reference llm_agent.py:37,44); the
        # decision head is short and greedy-leaning would also be defensible,
        # but parity wins. The decision output is grammar-constrained
        # (agent/constrained.py) — the on-TPU replacement for Gemini's
        # function-calling reliability.
        self.tool_sampling = tool_sampling or SamplingParams(
            temperature=0.5, max_new_tokens=96, grammar="tool_call"
        )
        self.response_sampling = response_sampling or SamplingParams(temperature=0.5)
        self.today = today
        # retrieval/prefill overlap (ISSUE 3): prefill the response
        # prompt's static prefix (system + context + history) WHILE the
        # retrieval tool runs, grafting the retrieved block in when it
        # arrives. Needs a generator exposing the partial-prefill seam
        # (EngineGenerator); anything else silently uses the serial path.
        self.retrieval_overlap = retrieval_overlap
        # tool-streaming plane (ISSUE 9; agent/streamparse.py): consume the
        # decision decode as a chunk stream, launch the tool at its commit
        # points, and take the response-prefix hold at name-commit — a
        # whole decode earlier than the retrieve-node overlap alone. Falls
        # back to decode-then-parse semantics on any parser anomaly.
        self.tool_streaming = tool_streaming
        # metrics view for the finchat_tool_* family: a fleet replica's
        # agent emits through its engine's labeled scheduler view (the
        # same replica label every per-engine family rides); explicit
        # ``metrics`` wins, stub generators fall back to the global
        # registry
        self.metrics = metrics if metrics is not None else getattr(
            getattr(tool_generator, "scheduler", None), "metrics", None
        ) or METRICS
        self.graph = self._build_graph()
        logger.info("Agent initialized with state graph")

    def _build_graph(self) -> StateGraph:
        graph = StateGraph()
        graph.add_node("decide_retrieval", self._decide_retrieval_node)
        graph.add_node("retrieve_data", self._retrieve_data_node)
        graph.add_node("generate_response", self._generate_response_node)
        graph.set_entry_point("decide_retrieval")
        graph.add_conditional_edges(
            "decide_retrieval",
            self._should_retrieve,
            {"retrieve": "retrieve_data", "respond": "generate_response"},
        )
        graph.add_edge("retrieve_data", "generate_response")
        graph.add_edge("generate_response", END)
        return graph

    # --- prompt assembly -------------------------------------------------
    def _tool_system(self) -> str:
        return f"The current date is {self.today()}.\n{self.tool_prompt}"

    def _response_system(self) -> str:
        return f"The current date is {self.today()}.\n\n{self.system_prompt}"

    def prompt_heads(self) -> list[str]:
        """The constant leading strings of every rendered prompt, one per
        LLM role: ``render_chat_head`` over the SAME system builders the
        prompt assembly below uses, so they are byte-for-byte prefixes of
        the rendered prompts by construction (asserted in
        tests/test_prefix_cache.py). The serving layer registers these
        with the scheduler's shared-prefix KV cache and re-registers when
        they change (the embedded date rolls over at midnight)."""
        from finchat_tpu.models.tokenizer import render_chat_head

        return [
            render_chat_head(self._tool_system()),
            render_chat_head(self._response_system()),
        ]

    def _tool_prompt_text(self, state: AgentState) -> str:
        def build(s: AgentState) -> str:
            return render_chat(
                self._tool_system(), s.user_context, s.chat_history, s.user_query
            )

        return self._fit_prompt(build, state, self.tool_generator, self.tool_sampling)

    def _response_prompt_text(self, state: AgentState) -> str:
        def build(s: AgentState) -> str:
            # the retrieved block rides the FINAL user turn, not the system
            # context: everything upstream of it (system + context +
            # history) is then static before retrieval returns, which is
            # what lets the overlap plane prefill it concurrently with the
            # embed+search (``_response_prefix_text`` is its byte prefix)
            user_input = s.user_query
            if s.retrieved_transactions:
                user_input = (
                    "Retrieved Transaction Data:\n"
                    + "\n".join(s.retrieved_transactions)
                    + f"\n\n{s.user_query}"
                )
            return render_chat(
                self._response_system(), f"{s.user_context}\n", s.chat_history, user_input
            )

        return self._fit_prompt(build, state, self.response_generator, self.response_sampling)

    def _response_prefix_text(self, state: AgentState) -> str:
        """The static prefix of ``_response_prompt_text``: known before the
        retrieval tool returns, byte-prefix by construction (same system /
        context / history feed ``render_chat_prefix``, which ``render_chat``
        builds from). If ``_fit_prompt`` later windows history away, the
        prefix stops matching and the overlap plane falls back serially."""
        from finchat_tpu.models.tokenizer import render_chat_prefix

        return render_chat_prefix(
            self._response_system(), f"{state.user_context}\n", state.chat_history
        )

    def _fit_prompt(
        self,
        build: Callable[[AgentState], str],
        state: AgentState,
        generator: TextGenerator,
        sampling: SamplingParams,
    ) -> str:
        """Window the conversation so the rendered prompt fits the engine's
        token budget (history windowing, VERDICT r1 task 7).

        The reference stuffs unbounded history + up to 10k retrieved rows
        into the prompt (llm_agent.py:234-236, qdrant_tool.py:145) and relies
        on the external API to cope; the in-tree engine has a hard KV budget,
        so degrade explicitly: drop oldest history turns first, then halve
        the retrieved-transaction block. ``state`` is mutated so the later
        response prompt sees the same (already-windowed) conversation.
        Generators without budgets (e.g. StubGenerator) skip windowing.
        """
        budget_fn = getattr(generator, "prompt_budget", None)
        count_fn = getattr(generator, "count_tokens", None)
        text = build(state)
        if budget_fn is None or count_fn is None:
            return text
        budget = budget_fn(sampling)
        if count_fn(text) <= budget:
            return text
        # binary-search the max suffix of history that fits (O(log turns)
        # full rebuilds instead of one per dropped turn)
        history = list(state.chat_history)
        dropped_turns = 0
        if history:
            lo, hi = 0, len(history)  # turns KEPT from the end; lo always fits-or-is-floor
            while lo < hi:
                mid = (lo + hi + 1) // 2
                state.chat_history = history[len(history) - mid:]
                if count_fn(build(state)) <= budget:
                    lo = mid
                else:
                    hi = mid - 1
            state.chat_history = history[len(history) - lo:] if lo else []
            dropped_turns = len(history) - lo
            text = build(state)
        dropped_rows = 0
        while state.retrieved_transactions and count_fn(text) > budget:
            keep = len(state.retrieved_transactions) // 2
            dropped_rows += len(state.retrieved_transactions) - keep
            state.retrieved_transactions = state.retrieved_transactions[:keep]
            text = build(state)
        if dropped_turns or dropped_rows:
            logger.warning(
                "windowed prompt to fit %d-token budget: dropped %d history "
                "turns, %d retrieved rows", budget, dropped_turns, dropped_rows,
            )
        # anything still over budget (huge system prompt / user query) is
        # handled by the generator's token-level head+tail splice
        return text

    @staticmethod
    def _session_key(state: AgentState, role: str) -> str | None:
        """Session-KV-cache key for one LLM role. The two roles render
        DIFFERENT prompts for the same conversation, so they must not share
        a key — a shared one would cross-truncate on every turn (the
        matcher sees the other role's prompt as a divergent history)."""
        if not state.conversation_id:
            return None
        return session_key(state.conversation_id, role)

    @staticmethod
    def _trace(state: AgentState, name: str, **args) -> None:
        """Agent-plane trace event (ISSUE 12): the PR 9 overlap win made
        visible per request — decide_start, name_commit, tool_launch,
        tool_adopted, response_prefill_hold all land on the request's
        timeline. No-op for untraced requests, so tracing can never
        change the streamed output (the on/off byte-identity test pins
        it)."""
        if state.trace_id is not None and TRACER.enabled:
            TRACER.event(name, state.trace_id, track="agent",
                         args=args or None)

    def _gen_kwargs(self, state: AgentState, role: str) -> dict[str, Any]:
        """Per-role generator kwargs: session key, deadline, and — only
        when the request is traced — the trace id, so generator doubles
        in tests that predate the kwarg keep working untraced."""
        kwargs: dict[str, Any] = {
            "conversation_id": self._session_key(state, role),
            "deadline": state.deadline,
        }
        if state.trace_id is not None:
            kwargs["trace_id"] = state.trace_id
        return kwargs

    # --- nodes -----------------------------------------------------------
    async def _decide_retrieval_node(self, state: AgentState) -> AgentState:
        """Node 1: decide whether transaction retrieval is needed.

        With ``tool_streaming`` on, the decision decode is consumed as a
        chunk stream (ISSUE 9): the incremental parser emits commit-point
        events as the tool name and each argument finish decoding, the
        ToolLauncher speculatively executes the call while the remaining
        tokens still decode, and the response prompt's static prefix
        starts prefilling at name-commit via the hold-park-graft seam —
        a whole decision decode earlier than the retrieve-node overlap
        alone. The authoritative decision is ALWAYS the serial parser
        over the full text (streamparse.finish), so the streamed and
        serial paths agree byte-for-byte on WHAT to do; streaming only
        moves WHEN the tool and the prefix prefill start.
        """
        logger.info("Deciding if transaction retrieval is needed")
        self._trace(state, "decide_start")
        if not self.tool_streaming:
            decision_text = await self.tool_generator.generate(
                self._tool_prompt_text(state), self.tool_sampling,
                **self._gen_kwargs(state, "tool"),
            )
            tool_call = parse_tool_decision(decision_text)
            if tool_call is not None:
                state.tool_calls.append(tool_call)
                logger.info("LLM requested retrieval with args: %s", tool_call.args)
            else:
                logger.info("LLM decided no retrieval needed")
            return state

        parser = StreamingToolParser()
        launcher = ToolLauncher(
            lambda call: self._execute_streamed(state, call),
            refine=self._refine_tool_result, metrics=self.metrics,
            trace_id=state.trace_id,
        )
        prefix_task: Any = None
        try:
            async for chunk in self.tool_generator.stream(
                self._tool_prompt_text(state), self.tool_sampling,
                **self._gen_kwargs(state, "tool"),
            ):
                for event in parser.feed(chunk):
                    if isinstance(event, ParseAnomaly):
                        # off-grammar output: the eager plane disengages;
                        # the serial parse below still decides (counted
                        # once per turn after finish, which can also flag
                        # an incremental/serial mismatch)
                        launcher.abandon()
                    elif isinstance(event, ToolNameComplete):
                        self._trace(state, "name_commit", tool=event.name)
                        if prefix_task is None and self._overlap_ready(state):
                            prefix_task = asyncio.create_task(self._begin_prefix(state))
                    elif isinstance(event, CallComplete):
                        launcher.update(event.call)
                    elif isinstance(event, ArgComplete):
                        launcher.update(parser.launchable_call())
        except BaseException:
            # stream failure / cancellation: no adoption will happen, and
            # an early prefix hold must not pin its slot and pages
            launcher.abandon()
            await self._settle_prefix(state, prefix_task, keep=False)
            raise
        launcher.mark_decode_done()
        tool_call = parser.finish()
        if parser.anomaly is not None:
            launcher.abandon()  # no-op unless finish() flagged a mismatch
            self.metrics.inc("finchat_tool_fallbacks_total")
        if tool_call is not None:
            state.tool_calls.append(tool_call)
            state.tool_stream = launcher
            if prefix_task is None and self._overlap_ready(state):
                # anomaly paths can reach a call without a name-commit
                # event (parse_tool_decision's named-without-args rescue);
                # take the hold now so retrieve_data still overlaps
                prefix_task = asyncio.create_task(self._begin_prefix(state))
            await self._settle_prefix(state, prefix_task, keep=True)
            logger.info("LLM requested retrieval with args: %s", tool_call.args)
        else:
            launcher.abandon()
            await self._settle_prefix(state, prefix_task, keep=False)
            logger.info("LLM decided no retrieval needed")
        return state

    def _overlap_ready(self, state: AgentState) -> bool:
        return (
            self.retrieval_overlap
            and state.partial_prefill is None
            and hasattr(self.response_generator, "begin_partial")
        )

    async def _begin_prefix(self, state: AgentState):
        try:
            handle = await self.response_generator.begin_partial(
                self._response_prefix_text(state), self.response_sampling,
                **self._gen_kwargs(state, "resp"),
            )
        except Exception as e:  # overlap is an optimization, never fatal
            logger.warning("partial prefill unavailable, serial path: %s", e)
            return None
        if handle is not None:
            self._trace(state, "response_prefill_hold")
        return handle

    async def _settle_prefix(self, state: AgentState, prefix_task, *, keep: bool) -> None:
        """Resolve an early static-prefix prefill task into
        ``state.partial_prefill`` (keep=True), or release the hold it may
        have taken (keep=False — no-tool turn, upstream error): a hold
        nobody will graft must give back its slot and pages."""
        if prefix_task is None:
            return
        if not keep:
            prefix_task.cancel()
        try:
            handle = await prefix_task
        except asyncio.CancelledError:
            # keep=True never cancels the task itself, so a CancelledError
            # here is the CALLER being cancelled (asyncio cancels the
            # awaited task on the way) — propagate, don't swallow the
            # turn's cancellation; a hold whose submit still lands is the
            # scheduler TTL reap's to reclaim. keep=False cancelled the
            # task deliberately: that CancelledError is ours to swallow.
            if keep:
                raise
            return
        except Exception:
            return  # _begin_prefix already logged; overlap is optional
        state.partial_prefill = handle
        if not keep:
            self._release_partial(state)

    async def _retrieve_data_node(self, state: AgentState) -> AgentState:
        """Node 2: execute the tool. Only the first queued call is honored
        (llm_agent.py:100,116); failure degrades to an Error marker and the
        answer is still generated (llm_agent.py:129-131).

        ``create_financial_plot`` (SURVEY §7.2.7 — wired here, dead code in
        the reference) runs a server-side retrieval for its data (the model
        never supplies rows), charts rows that have the y-field, and still
        populates ``retrieved_transactions`` so the response model can
        discuss the same data the chart shows.
        """
        logger.info("Retrieving transaction data")
        if not state.tool_calls:
            return state
        tool_call = state.tool_calls.popleft()
        tool_args = dict(tool_call.args)
        tool_args["user_id"] = state.user_id  # server-side injection, never model-chosen
        launcher, state.tool_stream = state.tool_stream, None
        if launcher is not None:
            # tool-streaming plane: the call is (typically) already in
            # flight since its arguments committed mid-decode, and the
            # prefix hold was taken at name-commit — adopt the result.
            # Any failure degrades to the serial path below (the
            # launcher's error is structured and retryable by contract).
            try:
                self._apply_tool_result(
                    state, await launcher.result_for(tool_call)
                )
                return state
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning(
                    "streamed tool execution failed (code=%s); serial retry: %s",
                    getattr(e, "code", None), e,
                )
                self.metrics.inc("finchat_tool_fallbacks_total")
        if (
            self.retrieval_overlap
            and state.partial_prefill is None
            and hasattr(self.response_generator, "begin_partial")
        ):
            # overlap: the tool (embed + search + graft assembly) runs as a
            # task while the response prompt's static prefix submits for
            # prefill — by the time retrieval returns, the scheduler has
            # the system+context+history KV in flight or done, and only
            # the retrieved block + user turn remain to prefill
            retrieval = asyncio.create_task(self._run_tool(state, tool_call, tool_args))
            try:
                state.partial_prefill = await self._begin_prefix(state)
                await retrieval
            except BaseException:
                # cancellation (client disconnect, watchdog) must not orphan
                # the in-flight tool task
                retrieval.cancel()
                try:
                    await retrieval
                except (asyncio.CancelledError, Exception):
                    pass
                raise
        else:
            await self._run_tool(state, tool_call, tool_args)
        return state

    async def _run_tool(self, state: AgentState, tool_call: ToolCall,
                        tool_args: dict[str, Any]) -> None:
        try:
            self._apply_tool_result(
                state, await self._execute_tool(state, tool_call, tool_args)
            )
        except Exception as e:
            logger.error("Error running tool: %s", e)
            state.retrieved_transactions = [f"Error: {e}"]

    async def _execute_streamed(self, state: AgentState, call: ToolCall) -> ToolResult:
        """The ToolLauncher's execute seam: same server-side user_id
        injection as the serial path — the launcher only ever sees
        validated model args, never an identity it could influence."""
        args = dict(call.args)
        args["user_id"] = state.user_id  # server-side injection, never model-chosen
        return await self._execute_tool(state, call, args)

    async def _execute_tool(self, state: AgentState, tool_call: ToolCall,
                            tool_args: dict[str, Any]) -> ToolResult:
        """One tool execution → ToolResult. Deliberately mutation-free:
        the speculative plane runs this inside a cancellable task, and
        only an ADOPTED result may touch agent state (``_apply_tool_result``).
        ``tool.execute`` is the fault site (utils/faults.py) for both the
        streamed and serial planes."""
        inject("tool.execute", tool=tool_call.name, user_id=state.user_id)
        if tool_call.name == "create_financial_plot" and hasattr(self.retriever, "structured"):
            rows = await self.retriever.structured(tool_args)
            texts = [r["page_content"] for r in rows]
            chartable = [r for r in rows if "amount" in r]
            plot_data_uri = None
            if chartable:
                import json as _json

                from finchat_tpu.tools.plot import PlotConfig, create_financial_plot

                # synchronous by design: the render is cheap (Agg, ≤10k
                # rows) and matplotlib off the main thread has segfaulted
                # the worker (see tools/plot.py)
                plot_data_uri = create_financial_plot(
                    _json.dumps(chartable),
                    # chart_type/title are guaranteed by _validate_plot_args
                    PlotConfig(chart_type=tool_args["chart_type"], title=tool_args["title"]),
                )
            else:
                logger.warning("plot requested but no rows carry an 'amount' field")
            return ToolResult(texts, plot_data_uri)
        return ToolResult(await self.retriever(tool_args))

    @staticmethod
    def _refine_tool_result(result: ToolResult, call: ToolCall) -> ToolResult:
        """Host-side refinement for late-committed REFINE_KEYS
        (streamparse): ``num_transactions`` is a pure top-k cut, and the
        retriever returns score-ordered rows, so slicing the speculative
        superset equals a limit-n query (exact on the in-tree index;
        an approximate-ANN backend could drift on score ties — the
        documented speculation trade)."""
        n = call.args.get("num_transactions")
        if isinstance(n, int) and len(result.texts) > n:
            return ToolResult(result.texts[:n], result.plot_data_uri)
        return result

    def _apply_tool_result(self, state: AgentState, result: ToolResult) -> None:
        state.retrieved_transactions = result.texts
        if result.plot_data_uri is not None:
            state.plot_data_uri = result.plot_data_uri
        logger.info("Retrieved %d transactions", len(state.retrieved_transactions))

    def _response_kwargs(self, state: AgentState) -> dict[str, Any]:
        """Generation kwargs for the response role. ``partial`` is only
        passed when the overlap path actually took a hold — so generators
        without the seam (StubGenerator, test doubles) never see it."""
        kwargs: dict[str, Any] = {"conversation_id": self._session_key(state, "resp")}
        if state.deadline is not None:
            kwargs["deadline"] = state.deadline
        if state.partial_prefill is not None:
            kwargs["partial"] = state.partial_prefill
        if state.trace_id is not None:
            kwargs["trace_id"] = state.trace_id
        return kwargs

    def _release_partial(self, state: AgentState) -> None:
        """Leak guard: a hold the generator never claimed (generation
        failed upstream, stream abandoned) must give back its slot and KV
        pages; a claimed one is the stream's to manage."""
        if state.partial_prefill is not None and hasattr(
            self.response_generator, "release_partial"
        ):
            self.response_generator.release_partial(state.partial_prefill)
        state.partial_prefill = None

    def _cancel_tool_stream(self, state: AgentState) -> None:
        """Leak guard: a speculative launch nobody adopted (error or
        abandonment upstream of retrieve_data) must not keep running."""
        if state.tool_stream is not None:
            state.tool_stream.abandon()
            state.tool_stream = None

    async def _generate_response_node(self, state: AgentState) -> AgentState:
        """Node 3: generate the final response (non-streaming graph path)."""
        logger.info("Generating final response")
        state.final_response = await self.response_generator.generate(
            self._response_prompt_text(state), self.response_sampling,
            **self._response_kwargs(state),
        )
        logger.info("Final response generated")
        return state

    def _should_retrieve(self, state: AgentState) -> str:
        if state.tool_calls:
            logger.info("Routing to retrieve_data")
            return "retrieve"
        logger.info("Routing to generate_response")
        return "respond"

    # --- public API ------------------------------------------------------
    async def query(
        self,
        user_query: str,
        user_id: str,
        user_context: str = "",
        chat_history: list[ChatMessage] | None = None,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> dict[str, Any]:
        """Batch path through the compiled graph (reference llm_agent.py:175)."""
        logger.info("Processing query for user %s: %s", user_id, user_query)
        state = AgentState(
            user_query=user_query,
            user_id=user_id,
            conversation_id=conversation_id,
            user_context=user_context,
            chat_history=list(chat_history or []),
            tool_calls=deque(),
            deadline=deadline,
            trace_id=trace_id,
        )
        try:
            final_state = await self.graph.ainvoke(state)
        finally:
            self._cancel_tool_stream(state)
            self._release_partial(state)
        return {
            "response": final_state.final_response,
            "retrieved_transactions_count": len(final_state.retrieved_transactions),
            "plot_data_uri": final_state.plot_data_uri,
            "state": final_state,
        }

    async def stream_with_status(
        self,
        user_query: str,
        user_id: str,
        user_context: str = "",
        chat_history: list[ChatMessage] | None = None,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> AsyncGenerator[dict[str, Any], None]:
        """Streaming path with status events (reference llm_agent.py:202-252);
        event shapes/messages kept verbatim."""
        logger.info("Processing query with status streaming for user %s: %s", user_id, user_query)
        yield {"type": "status", "message": "Starting query processing..."}

        state = AgentState(
            user_query=user_query,
            user_id=user_id,
            conversation_id=conversation_id,
            user_context=user_context,
            chat_history=list(chat_history or []),
            tool_calls=deque(),
            deadline=deadline,
            trace_id=trace_id,
        )

        try:
            yield {"type": "status", "message": "Analyzing query to determine if transaction data is needed..."}
            state = await self._decide_retrieval_node(state)

            if self._should_retrieve(state) == "retrieve":
                yield {"type": "status", "message": "Retrieving relevant transaction data..."}
                state = await self._retrieve_data_node(state)
                yield {
                    "type": "retrieval_complete",
                    "count": len(state.retrieved_transactions),
                    "message": f"Retrieved {len(state.retrieved_transactions)} transactions",
                }
                if state.plot_data_uri:
                    yield {"type": "plot", "data_uri": state.plot_data_uri}
            else:
                yield {"type": "status", "message": "No transaction data retrieval needed"}

            yield {"type": "status", "message": "Generating response..."}

            async for chunk in self.response_generator.stream(
                self._response_prompt_text(state), self.response_sampling,
                **self._response_kwargs(state),
            ):
                if chunk:
                    yield {"type": "response_chunk", "content": chunk}
        finally:
            # a hold the stream never claimed (consumer abandoned the
            # generator, an upstream error) must not pin its slot/pages,
            # and an unadopted speculative tool launch must not keep running
            self._cancel_tool_stream(state)
            self._release_partial(state)

        yield {"type": "complete", "message": "Query processing completed"}
        logger.info("Status streaming completed")
