"""Grammar-constrained decoding for the tool-decision step.

SURVEY §7.2 step 8 / §7.3 hard part #5: the reference relies on Gemini's
function-calling API for structured tool calls (``llm_agent.py:98-101``);
on-TPU the decision model emits free text, so reliability comes from
constraining generation itself. The output grammar (``tool_prompt.txt``
contract) is compiled to a character-level DFA:

    output := "No tool call"
            | "retrieve_transactions(" json_args ")"
    json_args := "{" (pair ("," pair)*)? "}"
    pair := '"'key'"' ":" value          key ∈ {search_query,
            num_transactions, time_period_days}; string or positive-int
            values per the RetrievalIntent schema (qdrant_tool.py:39-68)

At each step the DFA state induces a vocab bitmask (which token strings keep
the output inside the grammar); masks are cached per DFA state, so steady
states (inside a string value, inside an integer) cost one vocab scan total.
The scheduler samples host-side from the masked logits and overrides the
engine's device-sampled token for that slot — one [vocab] fp32 row crosses
to host per constrained step, only while a constrained sequence is active.

``user_id`` is deliberately NOT in the grammar: the model cannot even spell
an argument the executor would have to distrust (llm_agent.py:119-120
server-side injection invariant).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEAD = -1

_WS = " \n\t"


class CharDFA:
    """Explicit-state character DFA with char classes and EOS-accepting
    states. States are ints; DEAD (-1) is the reject sink."""

    def __init__(self) -> None:
        self.edges: list[dict[str, int]] = []
        self.classes: list[list[tuple[Callable[[str], bool], int]]] = []
        self.eos_ok: list[bool] = []
        self.start = self.new_state()

    def new_state(self, eos_ok: bool = False) -> int:
        self.edges.append({})
        self.classes.append([])
        self.eos_ok.append(eos_ok)
        return len(self.edges) - 1

    def edge(self, src: int, chars: str, dst: int) -> None:
        for ch in chars:
            self.edges[src][ch] = dst

    def edge_class(self, src: int, pred: Callable[[str], bool], dst: int) -> None:
        self.classes[src].append((pred, dst))

    def literal(self, src: int, text: str, dst: int | None = None, eos_ok: bool = False) -> int:
        """Chain states spelling ``text`` from ``src``; returns the end state."""
        cur = src
        for i, ch in enumerate(text):
            last = i == len(text) - 1
            nxt = (dst if dst is not None and last else None)
            if nxt is None:
                nxt = self.edges[cur].get(ch)
                if nxt is None:
                    nxt = self.new_state(eos_ok=eos_ok and last)
            self.edge(cur, ch, nxt)
            cur = nxt
        return cur

    def step(self, state: int, ch: str) -> int:
        if state == DEAD:
            return DEAD
        nxt = self.edges[state].get(ch)
        if nxt is not None:
            return nxt
        for pred, dst in self.classes[state]:
            if pred(ch):
                return dst
        return DEAD

    def step_string(self, state: int, text: str) -> int:
        for ch in text:
            state = self.step(state, ch)
            if state == DEAD:
                return DEAD
        return state


def _string_char(ch: str) -> bool:
    # JSON string body without escapes: printable, no quote/backslash.
    # '}' and ')' are also excluded so every grammatical output stays inside
    # what toolcall.py's non-greedy extraction regex can parse (grammar ⊆
    # parser invariant — tested by test_every_accepted_output_parses).
    return ch not in '"\\})' and (ch >= " ") and ch != "\x7f"


# single source of truth for tool names / literals / chart enum: the parser
# module — grammar and validator must not drift apart (grammar ⊆ parser)
from finchat_tpu.agent.toolcall import (  # noqa: E402
    CHART_TYPES,
    NO_TOOL_LITERAL,
    PLOT_TOOL_NAME,
    TOOL_NAME,
)

# key -> value kind; kind is "string", "int", or a tuple of enum literals
_RETRIEVAL_KEYS: dict[str, Any] = {
    "search_query": "string",
    "num_transactions": "int",
    "time_period_days": "int",
}
_PLOT_KEYS: dict[str, Any] = {
    "chart_type": CHART_TYPES,
    "title": "string",
    **_RETRIEVAL_KEYS,  # plot data comes from a server-side retrieval
}
TOOL_GRAMMARS: dict[str, dict[str, Any]] = {
    TOOL_NAME: _RETRIEVAL_KEYS,
    PLOT_TOOL_NAME: _PLOT_KEYS,
}


def _bound_whitespace(d: CharDFA, max_ws: int = 2) -> None:
    """Unroll every whitespace self-loop into a ≤max_ws chain.

    Unbounded ws loops let a weak/adversarial model spend its whole token
    budget emitting tabs while staying "in grammar"; bounding them makes
    whitespace progress-neutral at most ``max_ws`` chars per position."""
    for s in range(len(d.edges)):
        if not any(d.edges[s].get(ch) == s for ch in _WS):
            continue
        base_edges = {ch: t for ch, t in d.edges[s].items() if not (ch in _WS and t == s)}
        base_classes = list(d.classes[s])
        prev = s
        for _ in range(max_ws):
            nxt = d.new_state(eos_ok=d.eos_ok[s])
            d.edges[nxt] = dict(base_edges)
            d.classes[nxt] = list(base_classes)
            for ch in _WS:
                d.edges[prev][ch] = nxt
            prev = nxt
        for ch in _WS:
            d.edges[prev].pop(ch, None)


def _add_tool_call(d: CharDFA, name: str, keys: dict[str, Any]) -> None:
    """Add one ``name({...})`` alternative with its own key/value machine."""
    pre_obj = d.literal(d.start, name + "(")
    d.edge(pre_obj, _WS, pre_obj)
    key_or_close = d.new_state()
    d.edge(pre_obj, "{", key_or_close)
    d.edge(key_or_close, _WS, key_or_close)

    obj_done = d.new_state()
    d.edge(obj_done, _WS, obj_done)
    done_call = d.new_state(eos_ok=True)
    d.edge(obj_done, ")", done_call)
    d.edge(key_or_close, "}", obj_done)

    after_val = d.new_state()
    d.edge(after_val, _WS, after_val)
    pre_key = d.new_state()
    d.edge(after_val, ",", pre_key)
    d.edge(after_val, "}", obj_done)
    d.edge(pre_key, _WS, pre_key)

    key_start = d.new_state()
    d.edge(key_or_close, '"', key_start)
    d.edge(pre_key, '"', key_start)

    for key, kind in keys.items():
        key_end = d.literal(key_start, key)
        pre_colon = d.new_state()
        d.edge(key_end, '"', pre_colon)
        d.edge(pre_colon, _WS, pre_colon)
        pre_val = d.new_state()
        d.edge(pre_colon, ":", pre_val)
        d.edge(pre_val, _WS, pre_val)
        if kind == "string":
            in_str = d.new_state()
            d.edge(pre_val, '"', in_str)
            d.edge_class(in_str, _string_char, in_str)
            d.edge(in_str, '"', after_val)
        elif isinstance(kind, tuple):  # enum of string literals
            for value in kind:
                d.literal(pre_val, f'"{value}"', dst=after_val)
        else:  # positive int, JSON-valid (no leading zeros: 0 | [1-9][0-9]*)
            in_int = d.new_state()
            int_zero = d.new_state()
            d.edge(pre_val, "0", int_zero)
            d.edge(pre_val, "123456789", in_int)
            d.edge(in_int, "0123456789", in_int)
            # ints have no closing char: terminator edges double as after_val
            for int_state in (in_int, int_zero):
                d.edge(int_state, ",", pre_key)
                d.edge(int_state, "}", obj_done)
                d.edge(int_state, _WS, after_val)


def build_tool_grammar() -> CharDFA:
    """DFA for the tool-decision output contract (module docstring)."""
    d = CharDFA()
    d.edge(d.start, _WS, d.start)  # tolerate leading whitespace

    # alternative 1: the no-tool literal (tool_prompt.txt:12), then EOS
    d.literal(d.start, NO_TOOL_LITERAL, eos_ok=True)

    # one alternative per tool: retrieve_transactions({...}) and
    # create_financial_plot({...}) (SURVEY §7.2.7: the plot tool is wired)
    for name, keys in TOOL_GRAMMARS.items():
        _add_tool_call(d, name, keys)
    _bound_whitespace(d)
    return d


def _distance_to_accept(dfa: CharDFA) -> list[int]:
    """Min chars from each state to an EOS-accepting state (Bellman fixed
    point over explicit + class edges; unreachable = a large sentinel)."""
    INF = 1 << 30
    n = len(dfa.edges)
    dist = [0 if dfa.eos_ok[s] else INF for s in range(n)]
    changed = True
    while changed:
        changed = False
        for s in range(n):
            best = 0 if dfa.eos_ok[s] else INF
            for t in dfa.edges[s].values():
                if dist[t] + 1 < best:
                    best = dist[t] + 1
            for _, t in dfa.classes[s]:
                if dist[t] + 1 < best:
                    best = dist[t] + 1
            if best < dist[s]:
                dist[s] = best
                changed = True
    return dist


def token_texts(tokenizer) -> list[str]:
    """Exact per-token emitted text for every vocab id.

    ``decode([i])`` is NOT it for SentencePiece-style tokenizers: single-
    token decode strips the leading-space marker ('▁foo' → 'foo'), so a
    DFA fed those strings diverges from the real stream ('Notoolcall' vs
    'No tool call'). When the tokenizer exposes ``convert_ids_to_tokens``,
    map pieces directly: '▁' → space, '<0xNN>' byte-fallback → that byte;
    otherwise (byte-level vocabs, tiktoken-style BPE where decode is exact)
    fall back to decode([i]).
    """
    inner = getattr(tokenizer, "_tok", None)
    convert = getattr(inner, "convert_ids_to_tokens", None)
    if convert is None:
        return [tokenizer.decode([i]) for i in range(tokenizer.vocab_size)]

    pieces = convert(list(range(tokenizer.vocab_size)))
    special_ids = set(getattr(inner, "all_special_ids", []) or [])
    texts: list[str] = []
    for i, piece in enumerate(pieces):
        if piece is None or i in special_ids:
            texts.append("")
        elif len(piece) == 6 and piece.startswith("<0x") and piece.endswith(">"):
            try:
                texts.append(bytes([int(piece[3:5], 16)]).decode("utf-8", errors="replace"))
            except ValueError:
                texts.append("")
        elif "▁" in piece:  # SentencePiece space marker
            texts.append(piece.replace("▁", " "))
        elif "Ġ" in piece or "Ċ" in piece:  # GPT-2 byte-level markers
            texts.append(tokenizer.decode([i]))
        else:
            texts.append(tokenizer.decode([i]))
    return texts


_DEAD_ROW_CHAR_REP = "é"  # representative non-ASCII printable char


class GrammarVocab:
    """A grammar bound to a tokenizer's vocab: per-DFA-state token masks.

    The DFA is compiled to a dense byte-level transition table so one
    state's vocab mask is a handful of numpy gathers (max-token-len steps
    over [vocab] arrays), never a Python scan — cheap enough to run on the
    scheduler loop. Bytes ≥ 0x80 (any non-ASCII UTF-8 byte) transition like
    a representative printable non-ASCII char: legal inside string values,
    DEAD elsewhere — exactly the grammar's intent, since every structural
    char is ASCII. Masks are cached per state and shared by every request
    using this (grammar, tokenizer) pair.
    """

    def __init__(self, dfa: CharDFA, token_strs: Sequence[str], eos_id: int):
        self.dfa = dfa
        self.token_strs = list(token_strs)
        self.eos_id = eos_id
        self._mask_cache: dict[int, tuple[np.ndarray, bool, np.ndarray]] = {}
        # token -> end-state transition cache, keyed by (state, token_id)
        self._step_cache: dict[tuple[int, int], int] = {}
        self.distance = _distance_to_accept(dfa)
        # distance indexed by end-state row (DEAD row = unreachable sentinel)
        self._distance_np = np.asarray(self.distance + [1 << 30], np.int64)

        # dense transitions: row per state + absorbing DEAD row (last)
        n = len(dfa.edges)
        self._dead_row = n
        table = np.full((n + 1, 256), self._dead_row, np.int32)
        for s in range(n):
            for b in range(128):
                nxt = dfa.step(s, chr(b))
                table[s, b] = self._dead_row if nxt == DEAD else nxt
            nxt = dfa.step(s, _DEAD_ROW_CHAR_REP)
            table[s, 128:] = self._dead_row if nxt == DEAD else nxt
        self._table = table

        # token byte matrix [V, Lmax] + lengths; empty tokens never allowed
        encoded = [t.encode("utf-8") for t in self.token_strs]
        self._tok_lens = np.asarray([len(e) for e in encoded], np.int32)
        lmax = max(1, int(self._tok_lens.max()))
        mat = np.zeros((len(encoded), lmax), np.uint8)
        for i, e in enumerate(encoded):
            mat[i, : len(e)] = np.frombuffer(e, np.uint8)
        self._tok_bytes = mat

    @classmethod
    def for_tokenizer(cls, tokenizer) -> "GrammarVocab":
        return cls(build_tool_grammar(), token_texts(tokenizer), tokenizer.eos_id)

    def mask(self, state: int) -> tuple[np.ndarray, bool, np.ndarray]:
        """(allowed[vocab] bool, eos_allowed, end_state[vocab]) for a state.

        ``end_state[t]`` is the DFA row after emitting token t (the DEAD row
        when t is not allowed) — pick() uses it with ``distance`` to keep
        generation inside the remaining token budget.
        """
        cached = self._mask_cache.get(state)
        if cached is not None:
            return cached
        V, L = self._tok_bytes.shape
        states = np.full((V,), self._dead_row if state == DEAD else state, np.int32)
        for j in range(L):
            live = j < self._tok_lens
            states = np.where(live, self._table[states, self._tok_bytes[:, j]], states)
        allowed = (states != self._dead_row) & (self._tok_lens > 0)
        eos_ok = state != DEAD and self.dfa.eos_ok[state]
        self._mask_cache[state] = (allowed, eos_ok, states)
        return allowed, eos_ok, states

    def advance(self, state: int, token_id: int) -> int:
        key = (state, token_id)
        nxt = self._step_cache.get(key)
        if nxt is None:
            nxt = self.dfa.step_string(state, self.token_strs[token_id])
            self._step_cache[key] = nxt
        return nxt


class TokenConstraint:
    """Per-request DFA cursor over a shared GrammarVocab."""

    def __init__(self, vocab: GrammarVocab):
        self.vocab = vocab
        self.state = vocab.dfa.start

    def pick(
        self,
        logits: np.ndarray,
        temperature: float,
        rng: np.random.Generator,
        remaining: int | None = None,
        top_p: float = 1.0,
        top_k: int = 0,
    ) -> int:
        """Sample one token from the grammar-masked logits and advance.

        ``remaining`` (tokens left in the budget, this one included) arms the
        feasibility invariant: a token is only allowed if its successor state
        can still reach an accepting state within the budget left AFTER it
        (chars-to-accept ≤ tokens-left - 1, since every token emits ≥1 char).
        Maintained every step, this guarantees the grammar closes in time —
        a one-shot "closing mode" is not enough, because distance-to-accept
        can jump above the budget in a single step (e.g. opening a long key).

        Returns ``eos_id`` when the grammar is complete (or unsatisfiable —
        which degrades to the no-tool path downstream, never a crash).
        """
        allowed, eos_ok, ends = self.vocab.mask(self.state)
        if remaining is not None:
            feasible = allowed & (self.vocab._distance_np[ends] <= remaining - 2)
            if feasible.any() or eos_ok:
                allowed = feasible
            else:
                logger.warning(
                    "no budget-feasible token at state %d (remaining=%d); forcing EOS",
                    self.state, remaining,
                )
                return self.vocab.eos_id
        if eos_ok:
            allowed = allowed.copy()
            allowed[self.vocab.eos_id] = True
        if not allowed.any():
            if eos_ok:
                return self.vocab.eos_id
            logger.warning("constraint unsatisfiable at state %d; forcing EOS", self.state)
            return self.vocab.eos_id

        masked = np.where(allowed, logits.astype(np.float64), -np.inf)
        if temperature <= 0.0:
            token = int(masked.argmax())
        else:
            # same top-k/top-p semantics as the in-jit sampler
            # (engine/sampler.py), applied to the grammar-masked logits
            z = masked / temperature
            if top_k and top_k > 0:
                kth = np.partition(z, -top_k)[-top_k]
                z = np.where(z < kth, -np.inf, z)
            if top_p < 1.0:
                order = np.argsort(-z)
                zs = z[order]
                probs = np.exp(zs - zs.max())
                probs /= probs.sum()
                cum = np.cumsum(probs)
                keep_sorted = (cum - probs) < top_p
                keep_sorted[0] = True
                drop = order[~keep_sorted]
                z[drop] = -np.inf
            z -= z.max()
            p = np.exp(z)
            p /= p.sum()
            token = int(rng.choice(len(p), p=p))
        if token != self.vocab.eos_id:
            self.state = self.vocab.advance(self.state, token)
        return token
