from finchat_tpu.engine.kv_cache import PageAllocator, PagedKVCache
from finchat_tpu.engine.sampler import SamplingParams, sample
from finchat_tpu.engine.engine import InferenceEngine

__all__ = [
    "PageAllocator",
    "PagedKVCache",
    "SamplingParams",
    "sample",
    "InferenceEngine",
]
