"""Text-generation abstraction consumed by the agent layer.

``TextGenerator`` is the seam where the reference called the Gemini API
(``llm_agent.py:88`` invoke, ``llm_agent.py:243`` astream): the agent only
sees "prompt in → text chunks out". Implementations:

- ``EngineGenerator`` — the TPU continuous-batching engine.
- ``StubGenerator`` — canned responses for tests and the no-TPU dev loop
  (plays the role of SURVEY §4.4's fake backend).
"""

from __future__ import annotations

import asyncio
import itertools
from typing import AsyncIterator, Callable, Protocol

from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.models.tokenizer import IncrementalDecoder, Tokenizer
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class GenerationError(RuntimeError):
    """Generation failed. ``code``/``retryable`` carry the scheduler's
    structured error fields when present (deadline shed, overload) so the
    serving layer can emit a retryable error chunk instead of an opaque
    one."""

    def __init__(self, message: str, *, code: str | None = None,
                 retryable: bool = False):
        super().__init__(message)
        self.code = code
        self.retryable = retryable


class TextGenerator(Protocol):
    # ``conversation_id`` keys the engine's cross-turn session KV cache
    # (engine/session_cache.py); None = no cross-turn reuse. ``deadline``
    # (monotonic time.perf_counter) feeds the scheduler's shed/EDF
    # admission; None = no deadline. ``trace_id`` threads the ingress-
    # minted end-to-end trace id into the scheduler's span/dispatch
    # events (utils/tracing.py — ISSUE 12); None = untraced. Non-engine
    # implementations may ignore all three.
    async def stream(
        self, prompt: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> AsyncIterator[str]: ...

    async def generate(
        self, prompt: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> str: ...


class EngineGenerator:
    def __init__(self, scheduler: ContinuousBatchingScheduler, tokenizer: Tokenizer):
        self.scheduler = scheduler
        self.tokenizer = tokenizer
        self._ids = itertools.count()
        self._grammar_vocabs: dict[str, object] = {}  # grammar name -> GrammarVocab

    # --- prompt budgeting (SURVEY §5.7; VERDICT r1 task 7) ---------------
    # The agent uses these to window history BEFORE submit, so over-long
    # conversations degrade gracefully instead of erroring at the scheduler
    # (the reference stuffs unbounded history, llm_agent.py:234-236, and
    # leans on the external API as backstop; here the budget is explicit).
    def count_tokens(self, text: str) -> int:
        return len(self.tokenizer.encode(text, add_bos=True))

    def prompt_budget(self, sampling: SamplingParams) -> int:
        """Max prompt tokens a sequence may carry and still have room for
        ``max_new_tokens`` in its KV allocation."""
        eng = self.scheduler.engine
        max_len = eng.max_pages_per_seq * eng.page_size
        return max(1, max_len - sampling.max_new_tokens)

    async def _make_constraint(self, grammar: str):
        from finchat_tpu.agent.constrained import GrammarVocab, TokenConstraint

        if grammar != "tool_call":
            raise ValueError(f"unknown grammar {grammar!r}")
        # single-flight: cache the build TASK, not the result, so concurrent
        # first requests share one O(vocab) build (token decode + dense DFA
        # table), run off the event loop so in-flight decodes aren't stalled
        task = self._grammar_vocabs.get(grammar)
        if task is None:
            task = asyncio.ensure_future(
                asyncio.to_thread(GrammarVocab.for_tokenizer, self.tokenizer)
            )
            self._grammar_vocabs[grammar] = task
        try:
            vocab = await task
        except Exception:
            # evict the failed build so the next request retries instead of
            # re-raising a stale error forever
            if self._grammar_vocabs.get(grammar) is task:
                del self._grammar_vocabs[grammar]
            raise
        return TokenConstraint(vocab)

    # --- retrieval/prefill overlap (ISSUE 3) -----------------------------
    async def begin_partial(
        self, prefix_text: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ):
        """Start prefilling a prompt's static prefix while its tail (the
        retrieval graft) is still being computed. Returns an opaque handle
        to pass to ``stream(..., partial=...)``, or None when the prefix
        can't ride the overlap path (over budget, ring-eligible, grammar
        use). The final encoded token is dropped — a subword tokenizer can
        merge across the graft boundary, so the last prefix token is the
        only one whose identity depends on what follows (the same boundary
        rule as the shared-prefix head registration, serve/app.py)."""
        if sampling.grammar:
            return None  # constrained decodes need per-token host control
        prefix_ids = self.tokenizer.encode(prefix_text, add_bos=True)[:-1]
        if not prefix_ids or len(prefix_ids) > self.prompt_budget(sampling):
            return None
        return await self.scheduler.submit_partial(
            f"seq-{next(self._ids)}", prefix_ids, sampling,
            conversation_id=conversation_id, deadline=deadline,
            trace_id=trace_id,
        )

    def release_partial(self, partial) -> None:
        """Drop an unconsumed partial hold (retrieval errored before
        generation, or the caller bailed): frees its slot and pages. A
        hold that was already claimed by ``stream`` is left alone."""
        if partial is not None and not getattr(partial, "_partial_claimed", False):
            self.scheduler.cancel(partial)

    async def stream(
        self, prompt: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        partial=None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> AsyncIterator[str]:
        prompt_ids = self.tokenizer.encode(prompt, add_bos=True)
        budget = self.prompt_budget(sampling)
        if len(prompt_ids) > budget:
            # token-level backstop beneath the agent's structural windowing:
            # keep the head (system rules) and the tail (latest turns + open
            # assistant tag) and drop the middle, so a too-long prompt still
            # answers instead of raising at submit
            head = budget // 4
            tail = budget - head
            logger.warning(
                "prompt of %d tokens exceeds budget %d; splicing head %d + tail %d",
                len(prompt_ids), budget, head, tail,
            )
            prompt_ids = prompt_ids[:head] + prompt_ids[-tail:]
        handle = None
        if partial is not None:
            from finchat_tpu.utils.metrics import METRICS, Timer

            # claim BEFORE the extend attempt: whatever happens next, the
            # hold is this stream's to consume or cancel
            partial._partial_claimed = True
            with Timer(METRICS, "finchat_retrieval_graft_seconds"):
                grafted = self.scheduler.extend_prompt(partial, prompt_ids)
            if grafted:
                handle = partial
            else:
                # graft point invalidated (windowing changed the prefix,
                # budget splice, pages unavailable): clean serial fallback
                self.scheduler.cancel(partial)
        if handle is None:
            seq_id = f"seq-{next(self._ids)}"
            constraint = await self._make_constraint(sampling.grammar) if sampling.grammar else None
            handle = await self.scheduler.submit(
                seq_id, prompt_ids, sampling, constraint=constraint,
                conversation_id=conversation_id, deadline=deadline,
                trace_id=trace_id,
            )
        decoder = IncrementalDecoder(self.tokenizer)
        try:
            while True:
                event = await handle.events.get()
                if event["type"] == "token":
                    text = decoder.push(event["token_id"])
                    if text:
                        yield text
                elif event["type"] == "done":
                    tail = decoder.flush()
                    if tail:
                        yield tail
                    return
                else:  # error — carry the scheduler's structured fields
                    # (deadline shed / overload) so the serving layer can
                    # emit a retryable error chunk
                    raise GenerationError(
                        event["message"],
                        code=event.get("code"),
                        retryable=bool(event.get("retryable", False)),
                    )
        finally:
            if not handle.finished:
                self.scheduler.cancel(handle)

    async def generate(
        self, prompt: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        partial=None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> str:
        return "".join([
            piece async for piece in self.stream(
                prompt, sampling, conversation_id=conversation_id,
                partial=partial, deadline=deadline, trace_id=trace_id,
            )
        ])


class StubGenerator:
    """Deterministic canned generator.

    ``rules`` maps a predicate over the prompt to a response; first match
    wins, else ``default``. Streams word-by-word with an optional delay to
    exercise real async interleaving in tests.
    """

    def __init__(
        self,
        default: str = "This is a canned response.",
        rules: list[tuple[Callable[[str], bool], str]] | None = None,
        chunk_delay: float = 0.0,
        fail_with: str | None = None,
    ):
        self.default = default
        self.rules = rules or []
        self.chunk_delay = chunk_delay
        self.fail_with = fail_with
        self.calls: list[str] = []  # prompts seen, for test assertions

    def _respond(self, prompt: str) -> str:
        for predicate, response in self.rules:
            if predicate(prompt):
                return response
        return self.default

    async def stream(
        self, prompt: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> AsyncIterator[str]:
        self.calls.append(prompt)
        if self.fail_with is not None:
            raise GenerationError(self.fail_with)
        response = self._respond(prompt)
        pieces = response.split(" ")
        for i, piece in enumerate(pieces):
            if self.chunk_delay:
                await asyncio.sleep(self.chunk_delay)
            yield piece + (" " if i < len(pieces) - 1 else "")

    async def generate(
        self, prompt: str, sampling: SamplingParams,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> str:
        return "".join([piece async for piece in self.stream(prompt, sampling)])
