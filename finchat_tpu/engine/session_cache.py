"""Session KV cache: a byte-budgeted host-RAM tier for cross-turn prefix resume.

The reference is a multi-turn chatbot whose every Kafka message re-fetches the
whole conversation history and re-prefills it from token zero
(serve/app.py process_message), so turn-N TTFT grows linearly with history
even though the engine computed that exact KV last turn. The shared-prefix
entries (scheduler ``_PrefixEntry``) only cover the constant system-prompt
head shared by ALL conversations; this module adds the per-conversation tier
below it — the hierarchical KV management that serving stacks built on paged
attention standardize on (Ragged Paged Attention, arXiv:2604.15464; long-
sequence state streaming, SnapStream, arXiv:2511.03092):

- OFFLOAD: when a sequence retires normally (eos/length), the scheduler
  snapshots its KV pages device→host (``InferenceEngine.offload_pages``)
  BEFORE the pages are freed, keyed by ``conversation_id``.
- RESUME: when the conversation's next turn arrives, admission matches the
  new prompt against the stored token stream — longest common token prefix,
  floored to page granularity — allocates fresh device pages, copies the
  matched pages host→device (``InferenceEngine.restore_pages``), and starts
  prefill at the matched offset.
- DIVERGENCE TRUNCATION: a turn whose history was edited (or re-rendered
  differently) matches only up to the divergence point; the entry is
  truncated there so stale KV can never be served.
- COMPOSITION with the shared-prefix cache: an entry whose sequence rode a
  refcounted ``_PrefixEntry`` head records those device pages BY REFERENCE
  (holding a ref so retirement cannot free them) and snapshots only the
  sequence's OWN pages — the constant head is never copied to host and
  never duplicated on restore.
- LRU under a byte budget: host bytes are the sum of the entries' own-page
  snapshots; inserting past ``budget_bytes`` evicts least-recently-used
  conversations first.
- DISK TIER (ISSUE 7; ROBUSTNESS.md §5): with ``engine.session_cache_disk_
  path`` set, every stored entry is also written through to a checksummed,
  versioned record file (atomic write-rename), the disk tier keeps its own
  byte-budgeted LRU over those records, and a RAM miss at admission falls
  through to disk (scheduler ``_restore_session_from_disk``). Because the
  records are write-through — not written only at eviction — a full
  process kill loses at most the turn that was mid-stream: the restarted
  process sweeps the directory, rebuilds the index, and the next turn of
  any retired conversation resumes warm. A corrupt or truncated record is
  QUARANTINED (renamed aside, counted) and the conversation cold-starts;
  stale or diverged records are harmless because every restore re-enters
  ``match``'s token comparison and divergence truncation.

Ownership contract (the allocator invariants of SURVEY §5.2 are untouched):
the cache NEVER owns device pages. Snapshots are host copies taken while the
retiring sequence still owns its pages; restores write into pages freshly
allocated to (and owned by) the admitted sequence. The only device pages an
entry points at are the shared-prefix head's, which stay owned by their
``__prefix_*__`` owner and are protected by the entry's reference count.

Everything here runs on the scheduler's host path (admission / retirement),
never inside a jitted step — the D2H/H2D copies are per-turn costs, not
per-token ones.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

import numpy as np

from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

# Cache-key convention, shared across layers: the agent keys each LLM
# role's entry separately (the two roles render DIFFERENT prompts for one
# conversation, so a shared key would cross-truncate every turn), and the
# fleet router must map any such key back to the conversation it belongs
# to — routing and migration are per-CONVERSATION, entries are per-ROLE.
SESSION_KEY_ROLES = ("tool", "resp")


def session_key(conversation_id: str, role: str) -> str:
    """The session-cache key for one LLM role of a conversation."""
    return f"{conversation_id}#{role}"


def conversation_of(key: str) -> str:
    """Inverse of :func:`session_key` for routing: the conversation a
    cache key (or a handle's ``conversation_id``) belongs to. Keys without
    a recognised role suffix — direct scheduler submissions, benches —
    are their own conversation."""
    base, sep, role = key.rpartition("#")
    return base if sep and role in SESSION_KEY_ROLES else key


# Snapshot layout throughout this module: a (k, v, k_scales | None,
# v_scales | None) tuple of host arrays, each [L, n_pages, ...] — the
# gather_pages_host / scatter_pages_device contract (engine/kv_cache.py).
# Under ``kv_quant="int8"`` the data planes are int8 and the scale planes
# are REAL fp32 arrays — both travel through every snapshot path (RAM LRU,
# disk records, fleet export) byte-identically; scales are covered by the
# record CRC like everything else in the payload.


def snap_kv_mode(snap: tuple | None) -> str:
    """The KV quant mode a snapshot was taken under: "int8" when it
    carries scale planes, "" (native dtype) otherwise. ``None`` snapshots
    (prefix-only entries) are mode-agnostic — restorable under either."""
    if snap is None or len(snap) < 3 or snap[2] is None:
        return ""
    return "int8"


def _dtype_name(dt) -> str:
    """Serializable dtype identity. ``np.dtype.str`` is NOT it: ml_dtypes
    dtypes (bfloat16) stringify as ``<V2`` (raw void), which round-trips
    to a void dtype — a bf16 snapshot written that way can never restore
    (latent since ISSUE 7; record version 2 fixes it). ``.name`` gives
    'bfloat16'/'float32'/'int8', resolvable by :func:`resolve_dtype`."""
    return np.dtype(dt).name


def resolve_dtype(name: str) -> np.dtype:
    """Inverse of :func:`_dtype_name`, also accepting v1 records' dtype
    strings ('<f4' etc.). Unknown names raise — the caller quarantines."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _snap_nbytes(snap: tuple | None) -> int:
    if snap is None:
        return 0
    return sum(int(a.nbytes) for a in snap if a is not None)


def concat_snaps(head: tuple | None, n_head_pages: int, tail: tuple | None) -> tuple | None:
    """The first ``n_head_pages`` pages of ``head`` followed by all of
    ``tail`` — the incremental-offload splice: a retiring turn reuses the
    previous entry's host bytes for pages it restored (and never rewrote)
    and only the pages written this turn arrive as a fresh D2H ``tail``.
    Always copies, so the result never aliases the (soon-dropped) head."""
    if n_head_pages == 0 or head is None:
        return tail
    sliced = tuple(a[:, :n_head_pages] if a is not None else None for a in head)
    if tail is None:
        return tuple(
            np.ascontiguousarray(a) if a is not None else None for a in sliced
        )
    return tuple(
        np.concatenate([a, b], axis=1) if a is not None else None
        for a, b in zip(sliced, tail)
    )


def _slice_snap(snap: tuple | None, n_pages: int) -> tuple | None:
    """First ``n_pages`` pages of a snapshot, compacted so truncation
    actually releases host RAM (a view would pin the full buffer)."""
    if snap is None or n_pages == 0:
        return None
    return tuple(
        np.ascontiguousarray(a[:, :n_pages]) if a is not None else None
        for a in snap
    )


class SessionDiskTier:
    """Byte-budgeted LRU of session-KV record files under one directory —
    the durability plane below the host-RAM tier (ISSUE 7).

    Record format (version 2; version 1 records remain readable):

        b"FSKV" | u8 version | u32 header_len | header JSON | payload

    The header carries the cache key, ``prefix_len``, the array specs
    (dtype/shape per array; the shared-prefix head's DEVICE pages are
    never stored — the record is the ``export_entry`` payload shape, so
    a restore re-links against the restoring scheduler's own live head),
    the payload byte length, and a CRC32 of the payload. Version 2
    (ISSUE 14) additionally stamps the snapshot's KV quant mode (``kv``:
    "int8" when scale planes travel, "" for native dtype — the scale
    planes ride the payload and its CRC like every other array) and
    stores dtypes BY NAME: v1 used ``np.dtype.str``, under which
    ml_dtypes bfloat16 serializes as raw void (``<V2``) and can never
    deserialize — v1 bf16 records were unreadable (quarantine → cold
    start); v2 round-trips every serving dtype. Writes go to a ``.tmp``
    sibling, fsync, then ``os.replace`` — a record is either whole or
    absent, never torn. Any read-side anomaly (bad magic, version,
    truncation, CRC mismatch, or an injected ``disk.restore`` fault)
    QUARANTINES the file (renamed ``*.quarantine``) and returns None:
    never a crash, never stale KV — the conversation cold-starts.

    Cross-MODE records (ISSUE 14): a tier constructed with ``kv_quant``
    refuses records whose snapshot was taken under the OTHER page-pool
    dtype — a bf16 snapshot scattered into an int8 pool (or vice versa)
    would serve garbage KV. Refusal is quarantine-STYLE: the record is
    set aside as ``*.crossmode`` (it is valid, just for a different
    serving mode — distinct from corruption), counted on
    ``finchat_quant_dequant_fallbacks_total``, and the conversation
    cold-starts. The startup sweep applies the same check, so a process
    restarted under a flipped ``engine.kv_quant`` sets every stale-mode
    record aside once, up front.

    Startup sweeps the directory: ``.tmp`` orphans from a mid-write crash
    are deleted, records whose header or size don't parse are quarantined,
    and the survivors rebuild the key index (LRU-ordered by mtime), so a
    restarted process resumes conversations warm.

    Writes are WRITE-BEHIND by default (``async_writes``): a record's
    serialize + write + fsync is seconds-class I/O at real model sizes,
    and the spill call sites sit inside the scheduler's event loop — the
    same stall class PR 6 moved off-loop with ``revive_async`` — so
    ``spill``/``discard`` enqueue onto ONE worker thread (FIFO, so a
    discard can never be overtaken by an older write of the same key) and
    return immediately. Snapshot arrays are safe to hand across: they are
    never mutated in place (truncation REPLACES them — the
    ``export_entry`` contract). ``load`` and ``flush`` drain the queue
    first, and the graceful drain's ``spill_all`` flushes, so the
    SIGTERM path stays fully durable; a hard kill can additionally lose
    whatever was still queued — milliseconds of records, inside the
    existing "at most the mid-stream turn" window.
    """

    MAGIC = b"FSKV"
    VERSION = 2
    READABLE_VERSIONS = (1, 2)
    SUFFIX = ".skv"

    def __init__(self, path: str, budget_bytes: int, metrics=None,
                 async_writes: bool = True, kv_quant: str = ""):
        assert budget_bytes > 0
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.budget_bytes = budget_bytes
        # the page-pool dtype this tier serves ("" = native): records whose
        # snapshot was taken under the other mode are refused at load/sweep
        self.kv_quant = kv_quant
        self.metrics = metrics if metrics is not None else METRICS
        # key -> (filename, nbytes), LRU order (oldest first); guarded by
        # _lock — the writer thread updates it as records land
        self._index: OrderedDict[str, tuple[str, int]] = OrderedDict()
        self._resident = 0
        # key -> queued-write count: the index only reflects LANDED
        # records, so membership checks must also see in-flight writes
        # (a just-spilled, RAM-evicted entry would otherwise read as
        # absent and cold-start), and load() need only pay the queue
        # barrier when ITS key is actually pending
        self._pending: dict[str, int] = {}
        self._lock = threading.Lock()
        self._writer = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="skv-spill")
            if async_writes else None
        )
        self._sweep()

    # --- introspection ---------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._index or key in self._pending

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident

    def _publish_gauges(self) -> None:
        self.metrics.set_gauge("finchat_durability_disk_resident_bytes", self._resident)
        self.metrics.set_gauge("finchat_durability_disk_entries", len(self._index))

    @staticmethod
    def _fname(key: str) -> str:
        # the key is user-derived (conversation id + role suffix): hash it
        # so it can never escape the directory or exceed filename limits
        return hashlib.sha1(key.encode()).hexdigest() + SessionDiskTier.SUFFIX

    # --- record (de)serialization ---------------------------------------
    @staticmethod
    def _serialize(key: str, token_ids: np.ndarray, prefix_len: int,
                   snap: tuple | None, kv_gap: int = 0,
                   kv_sink: int = 0) -> bytes:
        token_ids = np.ascontiguousarray(token_ids, np.int32)
        chunks = [token_ids.tobytes()]
        specs: list[dict | None] | None = None
        if snap is not None:
            specs = []
            for a in snap:
                if a is None:
                    specs.append(None)
                    continue
                a = np.ascontiguousarray(a)
                specs.append({"dtype": _dtype_name(a.dtype), "shape": list(a.shape)})
                chunks.append(a.tobytes())
        payload = b"".join(chunks)
        header = json.dumps({
            "key": key,
            "prefix_len": int(prefix_len),
            "n_tokens": int(token_ids.shape[0]),
            "snap": specs,
            "kv": snap_kv_mode(snap),
            # bounded-KV entries (ISSUE 15): evicted-token gap between the
            # pinned sink and the surviving window, and the absolute sink
            # end it inserts at. Additive v2 fields — records without
            # them (pre-ISSUE-15, and all v1) read as 0
            "kv_gap": int(kv_gap),
            "kv_sink": int(kv_sink),
            "payload_len": len(payload),
            "crc": zlib.crc32(payload),
        }).encode()
        return (SessionDiskTier.MAGIC + bytes([SessionDiskTier.VERSION])
                + len(header).to_bytes(4, "big") + header + payload)

    @staticmethod
    def _read_header(raw: bytes) -> tuple[dict, int]:
        """(header, payload offset); raises ValueError on any anomaly."""
        if raw[:4] != SessionDiskTier.MAGIC:
            raise ValueError("bad magic")
        if raw[4] not in SessionDiskTier.READABLE_VERSIONS:
            raise ValueError(f"unknown record version {raw[4]}")
        hlen = int.from_bytes(raw[5:9], "big")
        header = json.loads(raw[9 : 9 + hlen].decode())
        off = 9 + hlen
        if len(raw) - off != header["payload_len"]:
            raise ValueError("truncated record")
        return header, off

    @staticmethod
    def _header_kv_mode(header: dict) -> str:
        """A record's KV quant mode: the v2 ``kv`` stamp, or (v1 records)
        derived from whether scale-plane specs are present."""
        if "kv" in header:
            return header["kv"]
        specs = header.get("snap")
        if specs and len(specs) > 2 and specs[2] is not None:
            return "int8"
        return ""

    @staticmethod
    def _deserialize(raw: bytes) -> dict:
        header, off = SessionDiskTier._read_header(raw)
        payload = raw[off:]
        if zlib.crc32(payload) != header["crc"]:
            raise ValueError("payload checksum mismatch")
        n = header["n_tokens"]
        token_ids = np.frombuffer(payload, np.int32, count=n)
        pos = n * 4
        snap = None
        if header["snap"] is not None:
            arrs = []
            for spec in header["snap"]:
                if spec is None:
                    arrs.append(None)
                    continue
                dt = resolve_dtype(spec["dtype"])
                count = int(np.prod(spec["shape"])) if spec["shape"] else 1
                arrs.append(
                    np.frombuffer(payload, dt, count=count, offset=pos)
                    .reshape(spec["shape"])
                )
                pos += count * dt.itemsize
            snap = tuple(arrs)
        return {
            "conversation_id": header["key"],
            "token_ids": token_ids,
            "prefix_len": int(header["prefix_len"]),
            "snap": snap,
            "kv_gap": int(header.get("kv_gap", 0)),
            "kv_sink": int(header.get("kv_sink", 0)),
        }

    # --- write path ------------------------------------------------------
    def spill(self, key: str, token_ids: np.ndarray, prefix_len: int,
              snap: tuple | None, kv_gap: int = 0, kv_sink: int = 0) -> bool:
        """Record one entry (atomic write-rename), then LRU-evict records
        past the byte budget. Write-behind: the serialize + fsync runs on
        the writer thread and this returns immediately (True = accepted);
        a failed write (disk full, injected ``disk.spill`` fault) logs and
        counts on ``finchat_durability_spill_failures_total`` — the
        serving path never fails, and never waits, on durability I/O."""
        if self._writer is not None:
            with self._lock:
                self._pending[key] = self._pending.get(key, 0) + 1
            self._writer.submit(self._write_record, key, token_ids,
                                prefix_len, snap, kv_gap, kv_sink)
            return True
        return self._write_record(key, token_ids, prefix_len, snap, kv_gap,
                                  kv_sink)

    def _unpend(self, key: str) -> None:
        """One queued write for ``key`` finished (landed or failed)."""
        if self._writer is None:
            return
        with self._lock:
            n = self._pending.get(key, 0) - 1
            if n <= 0:
                self._pending.pop(key, None)
            else:
                self._pending[key] = n

    def _write_record(self, key: str, token_ids: np.ndarray, prefix_len: int,
                      snap: tuple | None, kv_gap: int = 0,
                      kv_sink: int = 0) -> bool:
        """Writer-thread body (inline when ``async_writes`` is off)."""
        fname = self._fname(key)
        final = self.path / fname
        tmp = self.path / (fname + ".tmp")
        try:
            inject("disk.spill", key=key)
            blob = self._serialize(key, token_ids, prefix_len, snap, kv_gap,
                                   kv_sink)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        except Exception as e:
            logger.error("session disk tier: spill of %s failed: %s", key, e)
            self.metrics.inc("finchat_durability_spill_failures_total")
            tmp.unlink(missing_ok=True)
            self._unpend(key)
            return False
        victims: list[tuple[str, str, int]] = []
        with self._lock:
            old = self._index.pop(key, None)
            if old is not None:
                self._resident -= old[1]
            self._index[key] = (fname, len(blob))
            self._resident += len(blob)
            n = self._pending.get(key, 0) - 1
            if n <= 0:
                self._pending.pop(key, None)
            else:
                self._pending[key] = n
            while self._resident > self.budget_bytes and len(self._index) > 1:
                victim_key, (victim_fname, victim_bytes) = next(iter(self._index.items()))
                del self._index[victim_key]
                self._resident -= victim_bytes
                victims.append((victim_key, victim_fname, victim_bytes))
        self.metrics.inc("finchat_durability_spills_total")
        self.metrics.inc("finchat_durability_spilled_bytes_total", len(blob))
        for victim_key, victim_fname, victim_bytes in victims:
            (self.path / victim_fname).unlink(missing_ok=True)
            self.metrics.inc("finchat_durability_disk_evictions_total")
            logger.debug("session disk tier: evicted %s (LRU, %d bytes)",
                         victim_key, victim_bytes)
        self._publish_gauges()
        return True

    def discard(self, key: str) -> None:
        """Drop a key's record. Rides the writer queue (FIFO), so it can
        never be overtaken by an older queued write of the same key — and
        ``load`` flushes first, so a discarded record is unreachable the
        moment any reader could look for it."""
        if self._writer is not None:
            # pending too: a load between enqueue and unlink must barrier
            # and observe the pop, not read the doomed record
            with self._lock:
                self._pending[key] = self._pending.get(key, 0) + 1
            self._writer.submit(self._discard_now, key)
        else:
            self._discard_now(key)

    def _discard_now(self, key: str) -> None:
        with self._lock:
            entry = self._index.pop(key, None)
            if entry is not None:
                self._resident -= entry[1]
        self._unpend(key)
        if entry is not None:
            (self.path / entry[0]).unlink(missing_ok=True)
            self._publish_gauges()

    def flush(self) -> None:
        """Wait for every queued write/discard to land (graceful drain;
        read-side ops that must observe prior writes). FIFO barrier: the
        single worker makes one no-op submission a full drain."""
        if self._writer is not None:
            self._writer.submit(lambda: None).result()  # finchat-lint: disable=event-loop-blocking -- FIFO barrier by contract: reached only from the SIGTERM drain (must exit fully durable) and the per-key pending-write restore gate (ROBUSTNESS §5)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.shutdown(wait=True)
            self._writer = None

    # --- read path -------------------------------------------------------
    def load(self, key: str) -> dict | None:
        """Read, verify, and decode one record: an ``export_entry``-shaped
        payload, or None (absent / quarantined). A hit refreshes LRU
        recency; the record stays on disk (the RAM copy may be evicted or
        lost again before the next spill overwrites it)."""
        with self._lock:
            pending = key in self._pending
        if pending:
            # barrier only when THIS key has a queued write: a full-queue
            # flush on every RAM-miss admission would stall the scheduler
            # loop behind every unrelated spill in flight
            self.flush()
        with self._lock:
            entry = self._index.get(key)
        if entry is None:
            return None
        try:
            inject("disk.restore", key=key)
            raw = (self.path / entry[0]).read_bytes()
            header, _off = self._read_header(raw)
            if header.get("snap") and self._header_kv_mode(header) != self.kv_quant:
                # valid record, WRONG page-pool dtype: scattering it into
                # this engine's pool would serve garbage KV — set it aside
                # (quarantine-style, distinct suffix) and cold-start
                self._refuse_crossmode(key, self._header_kv_mode(header))
                return None
            payload = self._deserialize(raw)
            if payload["conversation_id"] != key:
                raise ValueError("record key mismatch")
        except Exception as e:
            logger.error(
                "session disk tier: record for %s unreadable (%s); "
                "quarantining — conversation cold-starts", key, e,
            )
            self._quarantine(key)
            return None
        with self._lock:
            if key in self._index:
                self._index.move_to_end(key)
        return payload

    def _refuse_crossmode(self, key: str, record_mode: str,
                          fname: str | None = None) -> None:
        """Set aside a valid record written under the OTHER KV quant mode
        (``*.crossmode``; counted as a dequant fallback — the engine falls
        back to recomputing the prefix instead of serving stored KV).
        Distinct from :meth:`_quarantine`: the record is not corrupt, and
        the counter separates mode flips from data damage."""
        with self._lock:
            entry = self._index.pop(key, None)
            if entry is not None:
                fname, nbytes = entry
                self._resident -= nbytes
        if fname is not None:
            src = self.path / fname
            try:
                os.replace(src, self.path / (fname + ".crossmode"))
            except OSError:
                src.unlink(missing_ok=True)
        logger.warning(
            "session disk tier: record for %s was written under "
            "kv_quant=%r, this engine serves kv_quant=%r; set aside — "
            "conversation cold-starts", key, record_mode, self.kv_quant,
        )
        self.metrics.inc("finchat_quant_dequant_fallbacks_total")
        self._publish_gauges()

    def _quarantine(self, key: str, fname: str | None = None) -> None:
        with self._lock:
            entry = self._index.pop(key, None)
            if entry is not None:
                fname, nbytes = entry
                self._resident -= nbytes
        if fname is not None:
            src = self.path / fname
            try:
                os.replace(src, self.path / (fname + ".quarantine"))
            except OSError:
                src.unlink(missing_ok=True)
        self.metrics.inc("finchat_durability_quarantines_total")
        # flight recorder (ISSUE 12): a corrupt record is silent data loss
        # from the client's point of view (cold resume) — the black box
        # records which key, when, and what the serving plane was doing
        TRACER.anomaly("record_quarantine",
                       args={"key": key, "file": fname})
        self._publish_gauges()

    # --- startup ---------------------------------------------------------
    def _sweep(self) -> None:
        """Rebuild the index from the directory: delete ``.tmp`` orphans
        (a crash mid-write), quarantine records whose header or size don't
        parse (full CRC verification is deferred to load — the sweep stays
        O(header) per record), index the rest LRU-ordered by mtime."""
        found: list[tuple[float, str, str, int]] = []  # (mtime, key, fname, nbytes)
        for p in self.path.iterdir():
            name = p.name
            if name.endswith(".tmp"):
                p.unlink(missing_ok=True)  # orphaned partial write
                continue
            if not name.endswith(self.SUFFIX):
                continue  # quarantined or foreign file
            try:
                with open(p, "rb") as f:  # finchat-lint: disable=event-loop-blocking -- constructor-time directory sweep: runs once at process start, before the scheduler loop exists
                    head = f.read(9)
                    if (head[:4] != self.MAGIC
                            or head[4] not in self.READABLE_VERSIONS):
                        raise ValueError("bad magic/version")
                    hlen = int.from_bytes(head[5:9], "big")
                    header = json.loads(f.read(hlen).decode())
                size = p.stat().st_size
                if size != 9 + hlen + header["payload_len"]:
                    raise ValueError("size mismatch")
                if header.get("snap") and self._header_kv_mode(header) != self.kv_quant:
                    # a restart under a flipped engine.kv_quant: set every
                    # stale-mode record aside once, up front (same check
                    # load() applies; sweeping keeps the index honest)
                    self._refuse_crossmode(header["key"],
                                           self._header_kv_mode(header),
                                           fname=name)
                    continue
                found.append((p.stat().st_mtime, header["key"], name, size))
            except Exception as e:
                logger.error("session disk tier: sweeping out bad record %s "
                             "(%s)", name, e)
                try:
                    os.replace(p, self.path / (name + ".quarantine"))
                except OSError:
                    p.unlink(missing_ok=True)
                self.metrics.inc("finchat_durability_quarantines_total")
        for _mtime, key, fname, nbytes in sorted(found):
            self._index[key] = (fname, nbytes)
            self._resident += nbytes
        if self._index:
            logger.info("session disk tier: %d resumable records (%d bytes) "
                        "at %s", len(self._index), self._resident, self.path)
        self._publish_gauges()


@dataclass
class SessionEntry:
    """One retired conversation's resumable KV.

    ``token_ids`` holds the ``n_tokens`` tokens whose KV the entry covers —
    always a whole-page multiple, split as ``[0, prefix_len)`` living in the
    referenced shared-prefix pages and ``[prefix_len, n_tokens)`` in the
    host snapshot. ``prefix_entry`` (a scheduler ``_PrefixEntry`` or None)
    carries one reference held for the entry's lifetime; the cache's
    ``on_drop`` callback is where the scheduler releases it.

    ``kv_gap`` (bounded-KV serving, ISSUE 15): tokens the eviction policy
    dropped between the pinned sink (``kv_sink`` absolute tokens) and the
    surviving window when the sequence retired. The snapshot then covers
    only the SURVIVING pages — ``n_tokens - kv_gap - prefix_len`` tokens —
    while ``token_ids`` still spans the full absolute range (the evicted
    tokens' ids must match the next turn's prompt for the surviving KV to
    be valid). A gapped entry resumes whole (sink+window intact) when the
    prompt extends past its span unchanged; on divergence the windowed
    remainder is unusable (it attended to the now-mismatched history) and
    ``match`` salvages at most the pre-gap sink region as an ordinary
    gap-free prefix.
    """

    conversation_id: str
    token_ids: np.ndarray  # int32 [n_tokens]
    prefix_entry: Any | None = None
    prefix_pages: list[int] = field(default_factory=list)  # device page ids, referenced
    prefix_len: int = 0  # tokens covered by prefix_pages (page multiple)
    snap: tuple | None = None  # host page arrays covering [prefix_len, n_tokens)
    kv_gap: int = 0  # bounded-KV evicted tokens (page multiple; 0 = exact)
    # absolute position the gap inserts at (the sink end; page multiple):
    # tokens below it attended only EARLIER sink tokens, so they remain a
    # valid ordinary prefix even when the windowed remainder is stale —
    # the divergence salvage in match() leans on this. 0 when kv_gap is 0.
    kv_sink: int = 0

    @property
    def n_tokens(self) -> int:
        return len(self.token_ids)

    @property
    def nbytes(self) -> int:
        return _snap_nbytes(self.snap)

    def own_pages_for(self, matched: int, page_size: int) -> int:
        """How many snapshot pages a ``matched``-token resume restores
        (the evicted gap has no pages)."""
        return max(0, matched - self.prefix_len - self.kv_gap) // page_size


class SessionKVCache:
    """Host-RAM LRU of ``SessionEntry`` keyed by conversation id.

    Single-task by design (the scheduler loop is the only caller), so no
    locking; the byte budget counts host snapshot bytes only — referenced
    shared-prefix pages live in device HBM under their own owner and are
    already accounted there.
    """

    def __init__(self, budget_bytes: int, page_size: int,
                 on_drop: Callable[[SessionEntry], None] | None = None,
                 metrics=None, disk: SessionDiskTier | None = None,
                 fabric=None, fabric_replica: str | None = None):
        assert budget_bytes > 0 and page_size > 0
        self.budget_bytes = budget_bytes
        self.page_size = page_size
        self._on_drop = on_drop
        # a fleet replica passes METRICS.labeled(replica=...) so its cache
        # series separate from its siblings'; default is the global registry
        self.metrics = metrics if metrics is not None else METRICS
        # durability plane (ISSUE 7): entries write THROUGH to the disk
        # tier at put — not only at eviction — so a process kill loses at
        # most the mid-stream turn, and a RAM miss falls through to disk
        # via the scheduler (_restore_session_from_disk, which re-links
        # shared heads); None = host-RAM only (pre-ISSUE-7 behavior)
        self.disk = disk
        # warm-state fabric (ISSUE 17): when set, ``disk`` IS the fleet's
        # shared tier and this cache keeps the fabric's global RAM index
        # current — put notes this replica as the key's holder, drops
        # forget it (holder-guarded) — so the router's deeper-entry-wins
        # migration is an index lookup instead of a pairwise scan
        self.fabric = fabric
        self.fabric_replica = fabric_replica
        self._entries: OrderedDict[str, SessionEntry] = OrderedDict()
        self._resident_bytes = 0
        self._publish_gauges()

    # --- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        return self._resident_bytes

    def get(self, conversation_id: str) -> SessionEntry | None:
        return self._entries.get(conversation_id)

    def _publish_gauges(self) -> None:
        self.metrics.set_gauge("finchat_session_cache_resident_bytes", self._resident_bytes)
        self.metrics.set_gauge("finchat_session_cache_entries", len(self._entries))

    # --- write path ------------------------------------------------------
    def put(self, entry: SessionEntry, *, spill: bool = True) -> bool:
        """Insert (replacing any previous entry for the conversation),
        then LRU-evict others until the byte budget holds. Returns False —
        and drops nothing from RAM — when the entry alone exceeds the
        budget. With a disk tier, the entry writes through to its record
        file either way: an over-budget entry is still resumable from disk
        (``fit_payload`` trims it back under the RAM budget at restore —
        the millions-of-idle-conversations case, ROADMAP item 4), and a
        stored one survives a process kill. ``spill=False`` is the
        disk-RESTORE path: the bytes just came off that record, so
        rewriting them would double every restore's I/O for nothing."""
        if spill:
            self._spill(entry)
        if entry.nbytes > self.budget_bytes:
            logger.warning(
                "session cache: entry for %s (%d bytes) exceeds budget %d; not stored",
                entry.conversation_id, entry.nbytes, self.budget_bytes,
            )
            return False
        old = self._entries.pop(entry.conversation_id, None)
        if old is not None:
            self._drop(old)
        self._entries[entry.conversation_id] = entry
        self._resident_bytes += entry.nbytes
        while self._resident_bytes > self.budget_bytes:
            victim_id, victim = next(iter(self._entries.items()))
            del self._entries[victim_id]
            self._drop(victim)
            self.metrics.inc("finchat_session_cache_evictions_total")
            logger.debug("session cache: evicted %s (LRU, %d bytes)",
                         victim_id, victim.nbytes)
        if self.fabric is not None and entry.conversation_id in self._entries:
            # the insert may itself have been LRU-evicted above
            self.fabric.note(entry.conversation_id, self.fabric_replica,
                             entry.n_tokens)
        self._publish_gauges()
        return True

    def discard(self, conversation_id: str) -> None:
        """Drop a conversation's entry from BOTH tiers — used when the
        bytes move elsewhere (fleet migration / drain handoff): a disk
        twin left behind could later restore on a replica the conversation
        no longer routes to."""
        if self.disk is not None:
            self.disk.discard(conversation_id)
        entry = self._entries.pop(conversation_id, None)
        if entry is not None:
            self._drop(entry)
            self._publish_gauges()

    def drop_local(self, conversation_id: str) -> None:
        """Drop the RAM copy ONLY — the fabric-migration counterpart of
        ``discard``: the bytes just moved to another replica whose put
        wrote through to the SHARED tier, so deleting the disk record
        here would erase the record the target just refreshed (the two
        ride the same single writer queue)."""
        entry = self._entries.pop(conversation_id, None)
        if entry is not None:
            self._drop(entry)
            self._publish_gauges()

    def clear(self) -> None:
        for entry in list(self._entries.values()):
            self._drop(entry)
        self._entries.clear()
        self._publish_gauges()

    def discard_if(self, pred: Callable[[SessionEntry], bool]) -> int:
        """Drop every entry matching ``pred``; returns how many. Used by
        prefix retirement: an entry referencing a retired head pins that
        head's DEVICE pages (the whole point of the refcount), but after a
        rollover the head can never match again — idle conversations would
        otherwise pin retired-head HBM indefinitely."""
        victims = [e for e in self._entries.values() if pred(e)]
        for entry in victims:
            del self._entries[entry.conversation_id]
            self._drop(entry)
        if victims:
            self._publish_gauges()
        return len(victims)

    def _drop(self, entry: SessionEntry) -> None:
        self._resident_bytes -= entry.nbytes
        entry.snap = None
        if self._on_drop is not None:
            self._on_drop(entry)
        if self.fabric is not None:
            # holder-guarded: a migration target that already noted its
            # fresher copy keeps its claim when the source drops here
            self.fabric.forget(entry.conversation_id, self.fabric_replica)

    # --- disk tier (ISSUE 7) ---------------------------------------------
    def _spill(self, entry: SessionEntry) -> bool:
        """Write one entry's record through to the disk tier (no-op
        without one). The record is the ``export_entry`` payload shape —
        ``prefix_len`` travels, the head's device pages never do — so a
        restore re-links against the restoring scheduler's own live
        head."""
        if self.disk is None or entry.n_tokens == 0:
            return False
        return self.disk.spill(
            entry.conversation_id, entry.token_ids, entry.prefix_len,
            entry.snap, entry.kv_gap, entry.kv_sink,
        )

    def spill_all(self) -> int:
        """Re-spill every resident entry (graceful-shutdown drain): puts
        already wrote through, so this is a retry pass for any spill that
        failed transiently plus a freshness pass for entries truncated
        since. Flushes the write-behind queue — the SIGTERM path exits
        fully durable. Returns how many records were written."""
        n = sum(1 for e in self._entries.values() if self._spill(e))
        if self.disk is not None:
            self.disk.flush()
        return n

    def fit_payload(self, payload: dict) -> dict | None:
        """Trim a disk/exported payload to the largest page-whole prefix
        whose host bytes fit the RAM budget, so an over-budget record is
        still (partially) resumable instead of being refused by ``put``
        on every turn — per-turn full-record churn that never warms
        anything. Snapshot pages are uniform-size, so the byte budget maps
        directly to a page count. Returns the payload untouched when it
        fits, a trimmed copy when a prefix does, or None when nothing
        does (no shared head, not one page under budget) — the caller
        should drop the record rather than retry forever."""
        snap = payload["snap"]
        nbytes = _snap_nbytes(snap)
        if nbytes <= self.budget_bytes:
            return payload
        if payload.get("kv_gap"):
            # a bounded entry is whole-or-not (see SessionEntry): trimming
            # would cut the window the gap semantics depend on. Bounded
            # snapshots are at most sink+window pages, so one exceeding
            # the RAM budget is a configuration problem, not a hot path.
            return None
        prefix_len = int(payload["prefix_len"])
        own_pages = (len(payload["token_ids"]) - prefix_len) // self.page_size
        keep = int(own_pages * self.budget_bytes // nbytes)
        if keep <= 0 and prefix_len <= 0:
            return None
        trimmed = dict(payload)
        trimmed["token_ids"] = np.asarray(payload["token_ids"], np.int32)[
            : prefix_len + keep * self.page_size
        ]
        trimmed["snap"] = _slice_snap(snap, keep)
        logger.warning(
            "session cache: disk record for %s (%d bytes) exceeds RAM "
            "budget %d; trimmed to %d of %d own pages for a partial warm "
            "resume", payload["conversation_id"], nbytes, self.budget_bytes,
            keep, own_pages,
        )
        return trimmed

    # --- cross-replica migration (serve/fleet.py; ISSUE 6) ---------------
    def export_entry(self, conversation_id: str) -> dict | None:
        """Portable, device-independent image of one conversation's entry
        for cross-replica handoff: token ids + the host snapshot arrays.
        The referenced shared-prefix DEVICE pages are NOT exportable — the
        payload carries only ``prefix_len`` (the head's tokens are
        ``token_ids[:prefix_len]``) so the importer can re-link against
        its OWN live registration of the same head
        (scheduler ``import_session_entry``). Snapshot arrays are shared
        by reference, never mutated in place (truncation replaces them),
        so export is O(1) — no host memcpy of the KV bytes. The entry
        stays resident here; the caller discards it once adopted."""
        entry = self._entries.get(conversation_id)
        if entry is None or entry.n_tokens == 0:
            return None
        return {
            "conversation_id": conversation_id,
            "token_ids": np.array(entry.token_ids, copy=True),
            "prefix_len": int(entry.prefix_len),
            "snap": entry.snap,
            "kv_gap": int(entry.kv_gap),
            "kv_sink": int(entry.kv_sink),
        }

    def import_entry(self, payload: dict, *, prefix_entry: Any | None = None,
                     prefix_pages: list[int] | None = None,
                     spill: bool = True) -> bool:
        """Adopt an exported entry. ``prefix_entry``/``prefix_pages`` is
        the importer's OWN live twin of the exported shared head —
        resolved, validated, and refcounted by the scheduler — covering
        exactly ``payload['prefix_len']`` tokens; both empty only when
        the payload has no head. Returns ``put``'s verdict (the caller
        un-references the head on False, mirroring ``_maybe_offload``)."""
        prefix_len = int(payload["prefix_len"])
        assert (prefix_len == 0) == (prefix_entry is None)
        entry = SessionEntry(
            conversation_id=payload["conversation_id"],
            token_ids=np.asarray(payload["token_ids"], np.int32),
            prefix_entry=prefix_entry,
            prefix_pages=list(prefix_pages or []),
            prefix_len=prefix_len,
            snap=payload["snap"],
            kv_gap=int(payload.get("kv_gap", 0)),
            kv_sink=int(payload.get("kv_sink", 0)),
        )
        return self.put(entry, spill=spill)

    # --- read path -------------------------------------------------------
    def match(self, conversation_id: str, prompt_ids: list[int]) -> tuple[SessionEntry | None, int]:
        """Longest resumable prefix of ``prompt_ids`` held for this
        conversation: the common token prefix with the entry, floored to
        whole pages, capped so at least one prompt token remains to prefill
        (the admission commit needs real last-token logits — same rule as
        the shared-prefix matcher). A hit refreshes LRU recency.

        Divergence is handled HERE, eagerly: if the new turn's tokens split
        from the stored stream before its end, the entry is truncated to
        the common prefix — the tail belongs to a history this conversation
        no longer has, so it could only ever serve stale KV."""
        entry = self._entries.get(conversation_id)
        if entry is None or not prompt_ids:
            return None, 0
        page = self.page_size
        prompt = np.asarray(prompt_ids, np.int32)
        n = min(entry.n_tokens, len(prompt))
        neq = np.nonzero(entry.token_ids[:n] != prompt[:n])[0]
        common = int(neq[0]) if neq.size else n
        if entry.kv_gap:
            # bounded entries (ISSUE 15) resume WHOLE when the prompt
            # extends past their span unchanged (sink+window intact)...
            if not neq.size:
                if common >= entry.n_tokens and len(prompt) - 1 >= entry.n_tokens:
                    self._entries.move_to_end(conversation_id)
                    return entry, entry.n_tokens
                # a prompt that merely STOPS SHORT (no divergence) can't
                # use the entry but hasn't staled it — keep it intact for
                # the turn that extends past the span
                return None, 0
            # ...and on DIVERGENCE salvage only the pre-gap sink region:
            # the windowed remainder attended to the evicted tokens, so a
            # mismatch anywhere below it stales it beyond repair — but
            # sink tokens attended only earlier sink tokens, so they
            # truncate into a perfectly ordinary gap-free prefix entry
            # (the RAG workload diverges every turn where the previous
            # turn's retrieved block sat; without the salvage a bounded
            # conversation would never resume warm).
            salvage = (min(common, entry.kv_sink) // page) * page
            entry.kv_gap = 0
            entry.kv_sink = 0
            self._truncate(entry, min(salvage, entry.n_tokens))
            if entry.n_tokens == 0:
                return None, 0
            # the salvaged entry continues through the ordinary gap-free
            # matching below; the original common may overshoot it
            common = min(common, entry.n_tokens)
        if common < entry.n_tokens:
            self._truncate(entry, (common // page) * page)
            if entry.n_tokens == 0:
                return None, 0
        cap = ((len(prompt) - 1) // page) * page
        matched = min((common // page) * page, cap)
        if matched <= 0:
            return None, 0
        self._entries.move_to_end(conversation_id)
        return entry, matched

    def _truncate(self, entry: SessionEntry, n_tokens: int) -> None:
        """Cut an entry down to a page-aligned token count (divergence).
        An entry truncated to nothing is dropped entirely."""
        assert n_tokens % self.page_size == 0 and n_tokens <= entry.n_tokens
        self.metrics.inc("finchat_session_cache_truncations_total")
        before = entry.nbytes
        entry.token_ids = entry.token_ids[:n_tokens]
        if n_tokens <= entry.prefix_len:
            # the divergence falls inside the shared head: keep only the
            # matched whole head pages (still referenced, still read-only)
            entry.prefix_len = n_tokens
            entry.prefix_pages = entry.prefix_pages[: n_tokens // self.page_size]
            entry.snap = None
        else:
            entry.snap = _slice_snap(
                entry.snap, (n_tokens - entry.prefix_len) // self.page_size
            )
        self._resident_bytes += entry.nbytes - before
        if entry.n_tokens == 0:
            del self._entries[entry.conversation_id]
            self._drop(entry)
        self._publish_gauges()
